"""Rank heartbeat channel (round 6): writer atomicity/bounds/throttle,
reader staleness + terminal evidence, HeartbeatMonitor silence detection,
and the phase-aware watchdog (per-phase deadlines, the single rc-117
path, heartbeat-stamped stalls).

Everything here is plain-python and sub-second — the engine-in-child
halves live in test_supervisor.py's slow matrix (scripts/chaos.sh).
"""

import io
import json
import os
import threading
import time

import pytest

from deepspeed_tpu.launcher.supervisor import HeartbeatMonitor
from deepspeed_tpu.runtime import heartbeat as hb
from deepspeed_tpu.runtime import watchdog as wdg
from deepspeed_tpu.runtime.watchdog import STALL_EXIT_CODE, StallWatchdog
from deepspeed_tpu.testing import chaos


def _writer(tmp_path, rank=0, host="w0", **kw):
    kw.setdefault("refresh_interval", 0)     # tests control time
    return hb.HeartbeatWriter(str(tmp_path), rank, host=host, **kw)


# ------------------------------------------------------------------ writer

def test_writer_record_schema_and_atomicity(tmp_path):
    w = _writer(tmp_path, rank=3, host="worker-3")
    assert w.write(hb.PHASE_INIT, 0, force=True)
    records = hb.read_heartbeats(str(tmp_path))
    rec = records[3]
    assert rec["rank"] == 3 and rec["host"] == "worker-3"
    assert rec["phase"] == hb.PHASE_INIT and rec["step"] == 0
    assert rec["pid"] == os.getpid() and rec["ts"] > 0
    # atomic publish: no torn tmp debris next to the rank file
    assert os.listdir(str(tmp_path)) == ["rank3.hb"]


def test_writer_bounds_record_count(tmp_path):
    w = _writer(tmp_path, keep_records=5, min_interval=0.0)
    for i in range(20):
        w.write(hb.PHASE_STEP, i, force=True)
    lines = open(w.path).read().splitlines()
    assert len(lines) == 5
    assert json.loads(lines[-1])["step"] == 19     # newest last


def test_writer_throttles_same_phase_but_not_transitions(tmp_path):
    t = [1000.0]
    w = _writer(tmp_path, min_interval=10.0, clock=lambda: t[0])
    assert w.write(hb.PHASE_STEP, 1)
    t[0] += 1.0
    assert not w.write(hb.PHASE_STEP, 2)           # same phase, too soon
    assert w.write(hb.PHASE_SAVE, 2)               # transition writes
    assert w.write(hb.PHASE_STEP, 2, force=True)   # force writes


def test_hb_write_failpoint_silences_rank_without_crashing(tmp_path):
    """Acceptance: heartbeat loss is harmless to the worker and looks
    exactly like silence to the reader."""
    w = _writer(tmp_path)
    assert w.write(hb.PHASE_STEP, 5, force=True)
    chaos.arm("hb.write", "raise", times=100)
    assert not w.write(hb.PHASE_STEP, 6, force=True)    # swallowed
    assert chaos.fired("hb.write")
    rec = hb.read_heartbeats(str(tmp_path))[0]
    assert rec["step"] == 5                             # last good record


def test_refresher_restamps_without_appending(tmp_path):
    w = hb.HeartbeatWriter(str(tmp_path), 0, host="w0",
                           refresh_interval=0.05)
    w.write(hb.PHASE_COMPILE, 0, force=True)
    ts0 = hb.read_heartbeats(str(tmp_path))[0]["ts"]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        rec = hb.read_heartbeats(str(tmp_path))[0]
        if rec["ts"] > ts0:
            break
        time.sleep(0.02)
    w.close()
    assert rec["ts"] > ts0                       # liveness re-attested
    assert rec["phase"] == hb.PHASE_COMPILE
    assert len(open(w.path).read().splitlines()) == 1   # re-stamp, not append


def test_write_lock_timeout_never_blocks_exit_paths(tmp_path):
    """A refresher wedged in dead-storage I/O holds the writer lock
    forever (open/fsync on a hard NFS mount blocks, it does not raise);
    a terminal stamp from an exit path must time out and drop the
    record, never block the exit behind diagnostics."""
    w = _writer(tmp_path)
    w.write(hb.PHASE_STEP, 1, force=True)
    w._lock.acquire()                    # the wedged holder
    try:
        t0 = time.monotonic()
        assert not w.write(hb.PHASE_STALLED, 1, force=True,
                           lock_timeout=0.1)
        assert not w.stamp_terminal(hb.PHASE_EXIT, lock_timeout=0.1)
        assert time.monotonic() - t0 < 5
        assert w._stop.is_set()          # terminal intent still recorded
    finally:
        w._lock.release()
    # the last good record stands — silence carries the verdict now
    assert hb.read_heartbeats(str(tmp_path))[0]["phase"] == hb.PHASE_STEP


def test_steady_state_rewrites_skip_fsync(tmp_path, monkeypatch):
    """Only phase transitions and terminal stamps pay the fsync: the
    steady-state STEP re-writes hit the shared filesystem every second
    from the training hot path, and fsync there is charged to step
    time. An unsynced re-stamp lost to a host crash reads as silence —
    what a dead host should read as."""
    real_fsync = os.fsync
    calls = []
    monkeypatch.setattr(
        os, "fsync", lambda fd: (calls.append(fd), real_fsync(fd))[1])
    w = _writer(tmp_path, min_interval=0.0)
    w.write(hb.PHASE_STEP, 1)                    # transition: durable
    assert len(calls) == 1
    w.write(hb.PHASE_STEP, 2)                    # steady state: cheap
    w.write(hb.PHASE_STEP, 3, force=True)
    assert len(calls) == 1
    w.write(hb.PHASE_SAVE, 3)                    # transition: durable
    assert len(calls) == 2
    w.write(hb.PHASE_STALLED, 3, force=True)     # terminal: durable
    assert len(calls) == 3


def test_terminal_phase_stops_refresher(tmp_path):
    w = hb.HeartbeatWriter(str(tmp_path), 0, refresh_interval=0.05)
    w.write(hb.PHASE_STEP, 3, force=True)
    w.write(hb.PHASE_EXIT, 3, force=True)
    ts0 = hb.read_heartbeats(str(tmp_path))[0]["ts"]
    time.sleep(0.25)
    assert hb.read_heartbeats(str(tmp_path))[0]["ts"] == ts0


# ------------------------------------------------------------------ readers

def test_stale_ranks_ignores_terminal_records(tmp_path):
    t = [1000.0]
    live = _writer(tmp_path, rank=0, clock=lambda: t[0])
    done = _writer(tmp_path, rank=1, clock=lambda: t[0])
    live.write(hb.PHASE_STEP, 10, force=True)
    done.write(hb.PHASE_PREEMPTED, 10, force=True)
    stale = hb.stale_ranks(str(tmp_path), timeout=5.0, now=1100.0)
    assert [r["rank"] for r in stale] == [0]     # terminal != silent


def test_terminal_records_reads_last_word(tmp_path):
    w = _writer(tmp_path, rank=2, host="w2")
    w.write(hb.PHASE_STEP, 9, force=True)
    w.write(hb.PHASE_STALLED, 9, force=True)
    term = hb.terminal_records(str(tmp_path))
    assert term[2]["phase"] == hb.PHASE_STALLED
    assert term[2]["host"] == "w2"


def test_monitor_flags_silent_and_missing_ranks(tmp_path):
    t = [1000.0]
    w = _writer(tmp_path, rank=0, host="w0", clock=lambda: t[0])
    w.write(hb.PHASE_STEP, 4, force=True)
    mon = HeartbeatMonitor(str(tmp_path), timeout=5.0,
                           expected_ranks=[0, 1], clock=lambda: t[0])
    assert mon.silent_ranks() == []              # everyone fresh enough
    t[0] += 10.0                                 # both exceed the timeout
    silent = mon.silent_ranks()
    assert [r["rank"] for r in silent] == [0, 1]
    assert silent[0]["host"] == "w0"
    assert silent[1].get("missing") is True      # rank 1 never wrote


def test_read_heartbeats_survives_garbage_files(tmp_path):
    (tmp_path / "rank0.hb").write_text("not json\n")
    (tmp_path / "rank1.hb").write_text("")
    w = _writer(tmp_path, rank=2)
    w.write(hb.PHASE_STEP, 1, force=True)
    assert list(hb.read_heartbeats(str(tmp_path))) == [2]


def test_clear_channel_scopes_dir_to_one_run(tmp_path):
    """clear_channel removes every rank record (and stranded tmp) but
    nothing else, and survives a directory that doesn't exist."""
    w = _writer(tmp_path, rank=0)
    w.write(hb.PHASE_STALLED, 7, force=True)
    (tmp_path / "rank1.hb.tmp").write_text("torn")
    (tmp_path / "notes.txt").write_text("keep me")
    hb.clear_channel(str(tmp_path))
    assert hb.read_heartbeats(str(tmp_path)) == {}
    assert hb.terminal_records(str(tmp_path)) == {}
    assert not (tmp_path / "rank1.hb.tmp").exists()
    assert (tmp_path / "notes.txt").read_text() == "keep me"
    hb.clear_channel(str(tmp_path / "missing"))  # no raise


def test_add_flag_is_sticky_and_immediately_durable(tmp_path):
    """An integrity flag (round 7: the SDC audit's blacklist evidence)
    publishes immediately — the abort follows right behind the stamp —
    and rides EVERY later record, so a consumer reading the newest record
    at any time sees it."""
    t = [1000.0]
    w = _writer(tmp_path, rank=2, host="w2", min_interval=30.0,
                clock=lambda: t[0])
    w.write(hb.PHASE_STEP, 40, force=True)
    assert w.add_flag("SDC", step=40)              # forced past the throttle
    rec = hb.read_heartbeats(str(tmp_path))[2]
    assert rec["flags"] == ["SDC"] and rec["step"] == 40
    w.add_flag("SDC")                              # idempotent: no dup
    w.write(hb.PHASE_STEP, 41, force=True)
    rec = hb.read_heartbeats(str(tmp_path))[2]
    assert rec["flags"] == ["SDC"] and rec["step"] == 41


def test_flagged_ranks_reads_only_marked_records(tmp_path):
    w0 = _writer(tmp_path, rank=0, host="w0")
    w0.write(hb.PHASE_STEP, 10, force=True)
    w1 = _writer(tmp_path, rank=1, host="w1")
    w1.write(hb.PHASE_STEP, 10, force=True)
    w1.add_flag("SDC")
    flagged = hb.flagged_ranks(str(tmp_path))
    assert list(flagged) == [1]
    assert flagged[1]["host"] == "w1" and "SDC" in flagged[1]["flags"]
    assert hb.flagged_ranks(str(tmp_path / "missing")) == {}


def test_flagged_ranks_flag_filter_separates_sdc_from_integrity(tmp_path):
    """Blacklist consumers filter to SDC — the generic INTEGRITY mark
    (every rank of an rc-118 abort carries it, for dstpu health) must
    never become host evidence."""
    w0 = _writer(tmp_path, rank=0, host="w0")
    w0.write(hb.PHASE_STEP, 10, force=True)
    w0.add_flag("INTEGRITY")
    w1 = _writer(tmp_path, rank=1, host="w1")
    w1.write(hb.PHASE_STEP, 10, force=True)
    w1.add_flag("SDC")
    w1.add_flag("INTEGRITY")
    assert sorted(hb.flagged_ranks(str(tmp_path))) == [0, 1]
    assert list(hb.flagged_ranks(str(tmp_path), flag="SDC")) == [1]


def test_writer_host_prefers_hostfile_vocabulary_env(tmp_path,
                                                     monkeypatch):
    """launch.py exports the operator's hostfile name for this rank;
    records must carry IT (the blacklist compares against hostfile
    members), not gethostname()'s FQDN/alias."""
    monkeypatch.setenv(hb.HEARTBEAT_HOST_ENV, "worker-3")
    w = _writer(tmp_path, rank=3, host=None)
    w.write(hb.PHASE_STEP, 1, force=True)
    assert hb.read_heartbeats(str(tmp_path))[3]["host"] == "worker-3"


# -------------------------------------------------- phase-aware watchdog

def test_watchdog_compile_deadline_fires_with_phase_in_message():
    rcs, buf = [], io.StringIO()
    wd = StallWatchdog(stall_timeout=0.0, poll_interval=0.02,
                       exit_fn=rcs.append, stream=buf,
                       phase_timeouts={hb.PHASE_COMPILE: 0.15}).start()
    try:
        wd.enter_phase(hb.PHASE_COMPILE)
        deadline = time.monotonic() + 10
        while not rcs and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert rcs == [STALL_EXIT_CODE]
    assert "COMPILE" in buf.getvalue()
    assert "compile_timeout" in buf.getvalue()


def test_watchdog_unbounded_phase_never_fires():
    rcs = []
    wd = StallWatchdog(stall_timeout=0.1, poll_interval=0.02,
                       exit_fn=rcs.append, stream=io.StringIO(),
                       phase_timeouts={hb.PHASE_COMPILE: 0.0}).start()
    try:
        wd.enter_phase(hb.PHASE_COMPILE)     # compile_timeout=0: unbounded
        time.sleep(0.4)
    finally:
        wd.stop()
    assert rcs == []


def test_watchdog_phase_transition_resets_clock():
    """Time spent in COMPILE must not be charged to the STEP deadline."""
    rcs = []
    wd = StallWatchdog(stall_timeout=0.3, poll_interval=0.02,
                       exit_fn=rcs.append, stream=io.StringIO(),
                       phase_timeouts={hb.PHASE_COMPILE: 10.0}).start()
    try:
        wd.enter_phase(hb.PHASE_COMPILE)
        time.sleep(0.25)                      # would be most of 0.3s
        wd.enter_phase(hb.PHASE_STEP)
        time.sleep(0.2)                       # < stall_timeout from entry
        assert rcs == []
        wd.beat()
    finally:
        wd.stop()
    assert rcs == []


def test_watchdog_phase_scope_restores_previous_phase():
    wd = StallWatchdog(stall_timeout=5.0, poll_interval=0.05,
                       exit_fn=lambda rc: None, stream=io.StringIO())
    wd.enter_phase(hb.PHASE_STEP)
    with wd.phase_scope(hb.PHASE_SAVE):
        assert wd.phase == hb.PHASE_SAVE
    assert wd.phase == hb.PHASE_STEP


def test_watchdog_save_deadline_bounds_wedged_save():
    rcs, buf = [], io.StringIO()
    wd = StallWatchdog(stall_timeout=0.0, poll_interval=0.02,
                       exit_fn=rcs.append, stream=buf,
                       phase_timeouts={hb.PHASE_SAVE: 0.15}).start()
    try:
        with wd.phase_scope(hb.PHASE_SAVE):
            deadline = time.monotonic() + 10
            while not rcs and time.monotonic() < deadline:
                time.sleep(0.02)
    finally:
        wd.stop()
    assert rcs == [STALL_EXIT_CODE]
    assert "SAVE" in buf.getvalue()


def test_watchdog_requires_some_positive_deadline():
    with pytest.raises(ValueError):
        StallWatchdog(stall_timeout=0.0,
                      phase_timeouts={hb.PHASE_COMPILE: 0.0})


def test_watchdog_fire_stamps_stalled_heartbeat(tmp_path):
    w = _writer(tmp_path, rank=0, host="w0")
    rcs = []
    wd = StallWatchdog(stall_timeout=0.1, poll_interval=0.02,
                       exit_fn=rcs.append, stream=io.StringIO(),
                       heartbeat=w).start()
    try:
        wd.enter_phase(hb.PHASE_STEP, step=7)
        deadline = time.monotonic() + 10
        while not rcs and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert rcs == [STALL_EXIT_CODE]
    rec = hb.terminal_records(str(tmp_path))[0]
    assert rec["phase"] == hb.PHASE_STALLED and rec["step"] == 7


def test_watchdog_fire_exits_even_when_heartbeat_lock_is_wedged(
        tmp_path, monkeypatch):
    """The rc-117 exit is the one guarantee the watchdog makes: it must
    hold even when the STALLED stamp can't be written because the writer
    lock is held by a thread wedged in dead-storage I/O."""
    monkeypatch.setattr(wdg, "_STAMP_LOCK_TIMEOUT", 0.1)
    w = _writer(tmp_path)
    w.write(hb.PHASE_COMPILE, 0, force=True)
    rcs = []
    w._lock.acquire()                    # the wedge
    try:
        t0 = time.monotonic()
        assert wdg._fire(io.StringIO(), "wedged stamp", rcs.append,
                         heartbeat=w, step=0)
        assert time.monotonic() - t0 < 3
    finally:
        w._lock.release()
    assert rcs == [STALL_EXIT_CODE]
    # the stamp was dropped; the prior record stands and silence (or the
    # scheduler rc) carries the verdict
    assert hb.read_heartbeats(str(tmp_path))[0]["phase"] == hb.PHASE_COMPILE


def test_single_rc117_path_suppresses_concurrent_double_fire():
    """Satellite fix: two deadlines expiring together (init deadline vs
    armed watchdog) must produce exactly ONE dump-and-exit."""
    fired = []
    gate = threading.Event()

    def slow_exit(rc):
        fired.append(rc)
        gate.wait(2.0)       # hold the guarded section open

    wds = [StallWatchdog(stall_timeout=0.05, poll_interval=0.01,
                         exit_fn=slow_exit, stream=io.StringIO()).start()
           for _ in range(2)]
    try:
        deadline = time.monotonic() + 10
        while not fired and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.3)      # the second deadline expires inside the hold
        gate.set()
        time.sleep(0.1)
    finally:
        gate.set()
        for wd in wds:
            wd.stop()
    assert fired == [STALL_EXIT_CODE]


def test_init_deadline_rides_the_watchdog_machinery():
    """init_deadline is a one-phase watchdog now — same loop, same
    guarded fire path, custom label preserved."""
    rcs, buf = [], io.StringIO()
    with wdg.init_deadline(0.1, what="rendezvous-probe",
                           exit_fn=rcs.append, stream=buf):
        time.sleep(0.4)
    assert rcs == [STALL_EXIT_CODE]
    assert "rendezvous-probe" in buf.getvalue()


def test_fire_guard_starvation_yields_instead_of_wedging(monkeypatch):
    """Regression (TPU019 sweep): the rc-117 once-guard is now bounded —
    if it cannot be taken, this fire yields (another deadline is
    mid-exit, or the interpreter is dying) rather than wedging the one
    path whose job is converting hangs into exits."""
    monkeypatch.setattr(wdg, "_STAMP_LOCK_TIMEOUT", 0.05)
    rcs = []
    wdg._fire_lock.acquire()             # the guard's holder is wedged
    try:
        t0 = time.monotonic()
        assert wdg._fire(io.StringIO(), "starved guard", rcs.append) \
            is False
        assert time.monotonic() - t0 < 2
    finally:
        wdg._fire_lock.release()
    assert rcs == []                     # yielded without side effects
    # guard released: the next deadline fires normally
    assert wdg._fire(io.StringIO(), "after release", rcs.append)
    assert rcs == [STALL_EXIT_CODE]
