"""Routing tests: each attention regime must reach the Pallas flash path.

The round-5 verdict's top gap was real-model regimes (padding masks, alibi,
softcap, sliding windows) silently reroutes to the O(S²) jnp path. These
tests pin the dispatch: a spy on the flash kernel entry asserts the kernel
is invoked (CPU-interpreted Pallas — the same kernel runs compiled on TPU),
and parity against the reference impl pins the numerics. Plus the engine
wiring of the previously parsed-but-dead ``sparse_attention`` and
``sequence_parallel.mode`` config sections.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _precise_matmuls():
    with jax.default_matmul_precision("highest"):
        yield


import deepspeed_tpu.ops.pallas.flash_attention as flash_mod
from deepspeed_tpu.models import build_model
from deepspeed_tpu.models.transformer import alibi_slopes
from deepspeed_tpu.ops.attention import (alibi_bias_from_slopes, attention,
                                         mha_reference)


@pytest.fixture
def flash_spy(monkeypatch):
    """Spy on the flash kernel entry; forces interpret mode so the REAL
    Pallas kernel runs (interpreted) on CPU. calls[] records the kwargs of
    every flash_attention invocation; kernel_calls[] records invocations
    that reached the pallas_call path (not the internal dense fallback)."""
    calls = []
    kernel_calls = []
    real_fa = flash_mod.flash_attention
    real_flash = flash_mod._flash

    def spy_fa(q, k, v, **kw):
        kw["interpret"] = True
        calls.append(kw)
        return real_fa(q, k, v, **kw)

    def spy_flash(*args):
        kernel_calls.append(args)
        return real_flash(*args)

    monkeypatch.setattr(flash_mod, "flash_attention", spy_fa)
    monkeypatch.setattr(flash_mod, "_flash", spy_flash)
    spy_fa.calls = calls
    spy_fa.kernel_calls = kernel_calls
    return spy_fa


def qkv(rng, shape):
    return tuple(jnp.asarray(rng.standard_normal(shape), jnp.float32)
                 for _ in range(3))


# ---------------------------------------------------------------------------
# attention() entry-point routing
# ---------------------------------------------------------------------------

def test_padding_mask_routes_to_kernel(flash_spy):
    rng = np.random.default_rng(0)
    q, k, v = qkv(rng, (2, 2, 128, 32))
    mask = jnp.asarray(np.arange(128)[None, :] < 70)[None, None]
    mask = jnp.broadcast_to(mask, (2, 1, 1, 128))
    out = attention(q, k, v, causal=False, mask=mask, impl="flash")
    assert len(flash_spy.kernel_calls) == 1, "mask did not reach the kernel"
    ref = mha_reference(q, k, v, causal=False, mask=mask)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_alibi_slopes_route_to_kernel(flash_spy):
    rng = np.random.default_rng(1)
    q, k, v = qkv(rng, (1, 4, 128, 32))
    sl = alibi_slopes(4)
    out = attention(q, k, v, causal=True, alibi_slopes=sl, impl="flash")
    assert len(flash_spy.kernel_calls) == 1, "alibi did not reach the kernel"
    ref = mha_reference(q, k, v, causal=True,
                        bias=alibi_bias_from_slopes(sl, 128, 128))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_sliding_window_routes_to_kernel(flash_spy):
    rng = np.random.default_rng(2)
    q, k, v = qkv(rng, (1, 2, 128, 32))
    out = attention(q, k, v, causal=True, window=48, impl="flash")
    assert len(flash_spy.kernel_calls) == 1, "window did not reach the kernel"
    qp, kp = np.arange(128)[:, None], np.arange(128)[None, :]
    ref = mha_reference(q, k, v, causal=True,
                        mask=jnp.asarray(qp - kp < 48)[None, None])
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_softcap_routes_to_kernel(flash_spy):
    rng = np.random.default_rng(3)
    q, k, v = qkv(rng, (1, 2, 128, 32))
    out = attention(q, k, v, causal=True, softcap=30.0, impl="flash")
    assert len(flash_spy.kernel_calls) == 1, "softcap did not reach the kernel"
    ref = mha_reference(q, k, v, causal=True, softcap=30.0)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_dropout_stays_on_reference(flash_spy):
    """Attention dropout is the documented fallback: no kernel call."""
    rng = np.random.default_rng(4)
    q, k, v = qkv(rng, (1, 2, 128, 32))
    attention(q, k, v, causal=True, dropout_rate=0.1,
              dropout_rng=jax.random.PRNGKey(0), impl="flash")
    assert not flash_spy.kernel_calls


# ---------------------------------------------------------------------------
# model-level routing: the HF-zoo regimes ride the kernel through Block
# ---------------------------------------------------------------------------

def _forward(model, params, batch):
    return model.apply({"params": params}, batch)


def _parity_vs_reference(cfg_kw, batch, flash_spy, seed=0):
    """Build the same arch twice (flash vs reference impl), share params,
    assert the flash forward used the kernel and matches the reference."""
    m_flash, _ = build_model("gpt2-tiny", attention_impl="flash",
                             dtype=jnp.float32, **cfg_kw)
    m_ref, _ = build_model("gpt2-tiny", attention_impl="reference",
                           dtype=jnp.float32, **cfg_kw)
    params = m_ref.init(jax.random.PRNGKey(seed), batch)["params"]
    out_ref = _forward(m_ref, params, batch)
    n_before = len(flash_spy.kernel_calls)
    out_flash = _forward(m_flash, params, batch)
    assert len(flash_spy.kernel_calls) > n_before, \
        "model forward did not dispatch to the Pallas kernel"
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_ref),
                               rtol=5e-4, atol=5e-4)
    return flash_spy.calls[-1]


# tier-2 (round-19 budget sweep, ~5s): the cheaper tier-1 cousins are
# test_padding_mask_routes_to_kernel (the routing verdict itself) and
# test_softcap_gemma2_rides_kernel /
# test_uniform_window_mistral_rides_kernel_under_scan (same
# model-level ride, other features); scripts/tier2.sh runs this
@pytest.mark.slow
def test_masked_bert_rides_kernel(flash_spy):
    """BERT with real padding — the verdict's headline example."""
    rng = np.random.default_rng(10)
    ids = rng.integers(0, 512, size=(2, 64))
    lens = np.array([40, 64])
    batch = {"input_ids": jnp.asarray(ids),
             "attention_mask": jnp.asarray(
                 np.arange(64)[None, :] < lens[:, None])}
    kw = _parity_vs_reference(
        dict(causal=False, vocab_size=512, max_seq_len=64, hidden_size=64,
             num_layers=2, num_heads=2), batch, flash_spy)
    assert kw["mask"] is not None


# tier-2 (round-19 budget sweep, ~7s): the cheaper tier-1 cousins are
# test_alibi_slopes_route_to_kernel (the routing verdict) and
# test_hf_policies.test_bloom_decode_parity (alibi model math);
# scripts/tier2.sh runs this model-level ride
@pytest.mark.slow
def test_alibi_bloom_rides_kernel(flash_spy):
    """BLOOM-style alibi positions ride as slopes (no [B,H,S,S] bias)."""
    rng = np.random.default_rng(11)
    batch = {"input_ids": jnp.asarray(rng.integers(0, 512, size=(2, 64)))}
    kw = _parity_vs_reference(
        dict(vocab_size=512, max_seq_len=64, hidden_size=64, num_layers=2,
             num_heads=2, pos_embed="alibi", embed_ln=True), batch, flash_spy)
    assert kw["alibi_slopes"] is not None


def test_softcap_gemma2_rides_kernel(flash_spy):
    """Gemma-2-class attn softcap runs in-kernel."""
    rng = np.random.default_rng(12)
    batch = {"input_ids": jnp.asarray(rng.integers(0, 512, size=(1, 64)))}
    kw = _parity_vs_reference(
        dict(vocab_size=512, max_seq_len=64, hidden_size=64, num_layers=2,
             num_heads=2, attn_softcap=50.0, final_logit_softcap=30.0),
        batch, flash_spy)
    assert kw["softcap"] == 50.0


def test_uniform_window_mistral_rides_kernel_under_scan(flash_spy):
    """Mistral-class UNIFORM layer windows stay a static int through the
    scanned-layers path, so attention() gets a kernel-routable window."""
    rng = np.random.default_rng(13)
    batch = {"input_ids": jnp.asarray(rng.integers(0, 512, size=(1, 64)))}
    kw = _parity_vs_reference(
        dict(vocab_size=512, max_seq_len=64, hidden_size=64, num_layers=2,
             num_heads=2, layer_windows=(32, 32), scan_layers=True),
        batch, flash_spy)
    assert kw["window"] == 32


@pytest.mark.slow
def test_masked_bert_trains_through_kernel(flash_spy):
    """fwd+bwd: grads of a masked encoder step flow through the kernel's
    custom VJP and match the reference-impl grads."""
    from deepspeed_tpu.models.transformer import masked_lm_loss
    rng = np.random.default_rng(14)
    ids = rng.integers(0, 256, size=(2, 32))
    batch = {"input_ids": jnp.asarray(ids),
             "attention_mask": jnp.asarray(
                 np.arange(32)[None, :] < np.array([20, 32])[:, None]),
             "labels": jnp.asarray(ids)}
    kw = dict(causal=False, vocab_size=256, max_seq_len=32, hidden_size=32,
              num_layers=2, num_heads=2)
    m_flash, _ = build_model("gpt2-tiny", attention_impl="flash",
                             dtype=jnp.float32, **kw)
    m_ref, _ = build_model("gpt2-tiny", attention_impl="reference",
                           dtype=jnp.float32, **kw)
    params = m_ref.init(jax.random.PRNGKey(0), batch)["params"]

    def loss(model, p):
        return masked_lm_loss(model.apply({"params": p}, batch), batch)

    g_ref = jax.grad(functools.partial(loss, m_ref))(params)
    n_before = len(flash_spy.kernel_calls)
    g_flash = jax.grad(functools.partial(loss, m_flash))(params)
    assert len(flash_spy.kernel_calls) > n_before
    for (path_f, leaf_f), (_, leaf_r) in zip(
            jax.tree_util.tree_leaves_with_path(g_flash),
            jax.tree_util.tree_leaves_with_path(g_ref)):
        np.testing.assert_allclose(np.asarray(leaf_f), np.asarray(leaf_r),
                                   rtol=1e-3, atol=1e-3,
                                   err_msg=str(path_f))


@pytest.mark.slow
def test_prefill_rides_flash_kernel(flash_spy):
    """Generation prefill (empty cache) runs the flash kernel and matches
    the jnp cache path token-for-token."""
    from deepspeed_tpu.models.generation import forward_with_cache, init_cache
    rng = np.random.default_rng(15)
    model, cfg = build_model("gpt2-tiny", vocab_size=256, max_seq_len=64,
                             hidden_size=64, num_layers=2, num_heads=2,
                             dtype=jnp.float32, attn_softcap=30.0)
    ids = jnp.asarray(rng.integers(0, 256, size=(2, 16)))
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    cache = init_cache(cfg, 2, 64, dtype=jnp.float32)
    logits_jnp, _ = forward_with_cache(cfg, params, ids, cache)
    assert not flash_spy.kernel_calls
    cache = init_cache(cfg, 2, 64, dtype=jnp.float32)
    logits_flash, cache2 = forward_with_cache(cfg, params, ids, cache,
                                              prefill_flash="interpret")
    assert flash_spy.kernel_calls, "prefill did not use the flash kernel"
    np.testing.assert_allclose(np.asarray(logits_flash),
                               np.asarray(logits_jnp), rtol=2e-4, atol=2e-4)
    # the cache written during the flash prefill must decode identically
    tok = jnp.argmax(logits_flash[:, -1:], axis=-1)
    l1, _ = forward_with_cache(cfg, params, tok, cache2)
    cache3 = init_cache(cfg, 2, 64, dtype=jnp.float32)
    _, cache_jnp = forward_with_cache(cfg, params, ids, cache3)
    l2, _ = forward_with_cache(cfg, params, tok, cache_jnp)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# engine wiring: sparse_attention + sequence_parallel.mode config sections
# ---------------------------------------------------------------------------

from deepspeed_tpu.config import load_config
from deepspeed_tpu.runtime.engine import wire_attention_config


def _tiny_model(**kw):
    model, _ = build_model("gpt2-tiny", vocab_size=128, max_seq_len=32,
                           hidden_size=32, num_layers=2, num_heads=2,
                           dtype=jnp.float32, **kw)
    return model


def test_sparse_attention_config_wires_attention_impl():
    model = _tiny_model()
    cfg = load_config({"sparse_attention": {"mode": "fixed", "block": 16,
                                            "num_local_blocks": 2}})
    wired = wire_attention_config(model, cfg)
    assert wired.cfg.attention_impl == "sparse"
    items = dict(wired.cfg.sparse_attention)
    assert items["mode"] == "fixed" and items["num_local_blocks"] == 2
    # config is hashable (jit-static requirement)
    hash(wired.cfg)


def test_sparse_attention_unknown_mode_raises():
    with pytest.raises(ValueError, match="unknown sparse attention mode"):
        wire_attention_config(
            _tiny_model(), load_config({"sparse_attention":
                                        {"mode": "banded"}}))


def test_sparse_attention_requires_in_tree_model():
    with pytest.raises(ValueError, match="in-tree"):
        wire_attention_config(
            object(), load_config({"sparse_attention": {"mode": "fixed"}}))


def test_sparse_attention_conflicting_impl_raises():
    with pytest.raises(ValueError, match="conflicts"):
        wire_attention_config(
            _tiny_model(attention_impl="flash"),
            load_config({"sparse_attention": {"mode": "fixed"}}))


def test_sequence_parallel_mode_selects_impl():
    cfg = load_config({"sequence_parallel": {"sp_size": 2,
                                             "mode": "ulysses"}})
    wired = wire_attention_config(_tiny_model(), cfg)
    assert wired.cfg.attention_impl == "ulysses"
    # hand-set matching impl is left alone
    wired = wire_attention_config(_tiny_model(attention_impl="ulysses"), cfg)
    assert wired.cfg.attention_impl == "ulysses"


def test_sequence_parallel_unknown_mode_raises():
    with pytest.raises(ValueError, match="sequence_parallel.mode"):
        wire_attention_config(
            _tiny_model(), load_config({"sequence_parallel":
                                        {"sp_size": 2, "mode": "zigzag"}}))


def test_sequence_parallel_conflicting_impl_raises():
    with pytest.raises(ValueError, match="conflicts"):
        wire_attention_config(
            _tiny_model(attention_impl="ring"),
            load_config({"sequence_parallel": {"sp_size": 2,
                                               "mode": "ulysses"}}))


# tier-2 (round 10 budget): fattest passing legs demoted per the standing
# guardrail — tier-1 crept past ~80% of the 870s budget once the comm-plan
# legs landed and the jax_compat shard_map wrapper recovered the 1-bit
# family on 0.4.x hosts; cheaper cousins still gate tier-1
@pytest.mark.slow
def test_sparse_model_forward_matches_layout_mask():
    """attention_impl='sparse' (as the engine wires it): 'dense' mode must
    equal the plain reference exactly, and a genuinely-masking fixed layout
    must change the logits (the section is consumed, not decorative)."""
    from deepspeed_tpu.ops.sparse_attention import (FixedSparsityConfig,
                                                    layout_to_dense_mask)
    rng = np.random.default_rng(20)
    batch = {"input_ids": jnp.asarray(rng.integers(0, 128, size=(2, 32)))}
    sa_items = (("block", 4), ("mode", "fixed"), ("num_local_blocks", 2),
                ("num_global_blocks", 1), ("attention", "unidirectional"))
    m_sparse = _tiny_model(attention_impl="sparse", sparse_attention=sa_items)
    m_ref = _tiny_model(attention_impl="reference")
    params = m_ref.init(jax.random.PRNGKey(1), batch)["params"]
    out_sparse = m_sparse.apply({"params": params}, batch)
    out_ref = m_ref.apply({"params": params}, batch)
    # the layout must mask real causal pairs, or the comparison is vacuous
    sp = FixedSparsityConfig(num_heads=2, block=4, num_local_blocks=2,
                             num_global_blocks=1, attention="unidirectional")
    lmask = np.asarray(layout_to_dense_mask(sp.make_layout(32), 4))
    causal = np.tril(np.ones((32, 32), bool))
    assert (lmask[0] & causal).sum() < causal.sum(), "layout masks nothing"
    assert not np.allclose(np.asarray(out_sparse), np.asarray(out_ref),
                           atol=1e-3)
    # dense mode == plain reference bit-for-bit
    m_dense = _tiny_model(attention_impl="sparse",
                          sparse_attention=(("mode", "dense"), ("block", 16)))
    out_dense = m_dense.apply({"params": params}, batch)
    np.testing.assert_allclose(np.asarray(out_dense), np.asarray(out_ref),
                               rtol=1e-5, atol=1e-5)


def test_sparse_model_unknown_mode_raises_at_forward():
    rng = np.random.default_rng(21)
    batch = {"input_ids": jnp.asarray(rng.integers(0, 128, size=(1, 32)))}
    model = _tiny_model(attention_impl="sparse",
                        sparse_attention=(("mode", "banded"),))
    with pytest.raises(ValueError, match="unknown sparse attention mode"):
        model.init(jax.random.PRNGKey(0), batch)


@pytest.mark.slow
def test_engine_initializes_with_sparse_attention():
    """End-to-end: ds.initialize consumes the sparse_attention section —
    the knob is no longer parsed-but-dead."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer import causal_lm_loss
    rng = np.random.default_rng(22)
    model = _tiny_model()
    mk = lambda: {"input_ids": rng.integers(0, 128, size=(8, 32))}
    engine, *_ = ds.initialize(
        model=model,
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "sparse_attention": {"mode": "fixed", "block": 16,
                                     "num_local_blocks": 2}},
        loss_fn=causal_lm_loss, example_batch=mk())
    assert engine.module.cfg.attention_impl == "sparse"
    assert float(engine.train_batch(mk())["loss"]) > 0.0


# ---------------------------------------------------------------------------
# pipelined engine: final_logit_softcap is applied (not silently dropped)
# ---------------------------------------------------------------------------

# tier-2 (round-19 budget sweep, ~5s): the cheaper tier-1 cousins are
# test_softcap_routes_to_kernel and test_softcap_gemma2_rides_kernel
# (the softcap feature itself); scripts/tier2.sh runs this
# pipelined-head plumbing pin
@pytest.mark.slow
def test_pipelined_head_applies_final_logit_softcap():
    from deepspeed_tpu.models.pipeline import PipelinedTransformer
    from deepspeed_tpu.models.transformer import (Transformer,
                                                  TransformerConfig)
    cfg = TransformerConfig(vocab_size=128, max_seq_len=32, hidden_size=32,
                            num_layers=2, num_heads=2, dtype=jnp.float32,
                            final_logit_softcap=5.0, scan_layers=True)
    rng = np.random.default_rng(30)
    batch = {"input_ids": jnp.asarray(rng.integers(0, 128, size=(2, 32)))}
    ref_model = Transformer(cfg)
    params = ref_model.init(jax.random.PRNGKey(0), batch)["params"]
    ref_logits = ref_model.apply({"params": params}, batch)
    assert float(jnp.max(jnp.abs(ref_logits))) <= 5.0
    pipe = PipelinedTransformer(cfg, pp=1, n_micro=1)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]).reshape(1,),
                             ("pipe",))
    pipe_logits = pipe.apply({"params": params}, batch, mesh=mesh)
    assert float(jnp.max(jnp.abs(pipe_logits))) <= 5.0
    np.testing.assert_allclose(np.asarray(pipe_logits),
                               np.asarray(ref_logits), rtol=1e-4, atol=1e-4)
