"""Pallas flash attention vs jnp reference (interpreter mode on CPU).

Mirrors the reference's kernel-parity strategy (tests/unit/ops/cuda/
test_cuda_forward.py / test_cuda_backward.py: fused kernel vs in-tree
baseline within tolerances).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

@pytest.fixture(autouse=True)
def _precise_matmuls():
    """Kernel-parity tolerances assume fp32 math; on real TPUs jnp matmuls
    default to bf16 internally, so pin the precision for these tests."""
    with jax.default_matmul_precision("highest"):
        yield


from deepspeed_tpu.ops.attention import mha_reference
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention


def make_qkv(rng, shape, dtype=jnp.float32):
    q, k, v = (jnp.asarray(rng.standard_normal(shape), dtype) for _ in range(3))
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("shape", [(1, 2, 256, 64), (2, 2, 128, 32)])
def test_forward_parity(causal, shape):
    rng = np.random.default_rng(0)
    q, k, v = make_qkv(rng, shape)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_backward_parity(causal):
    rng = np.random.default_rng(1)
    q, k, v = make_qkv(rng, (1, 2, 128, 32))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=64,
                                       block_k=64, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_cross_length_causal_offset():
    """Sk > S (decode-style): last q row must attend ALL keys (offset mask)."""
    rng = np.random.default_rng(7)
    q, _, _ = make_qkv(rng, (1, 2, 64, 32))
    _, k, v = make_qkv(rng, (1, 2, 192, 32))
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                          interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_fallback_on_odd_shapes():
    rng = np.random.default_rng(2)
    q, k, v = make_qkv(rng, (1, 1, 100, 24))  # not block-divisible
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_bf16_forward_close():
    rng = np.random.default_rng(3)
    q, k, v = make_qkv(rng, (1, 2, 128, 64), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), rtol=5e-2, atol=5e-2)
