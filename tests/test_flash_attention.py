"""Pallas flash attention vs jnp reference (interpreter mode on CPU).

Mirrors the reference's kernel-parity strategy (tests/unit/ops/cuda/
test_cuda_forward.py / test_cuda_backward.py: fused kernel vs in-tree
baseline within tolerances).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

@pytest.fixture(autouse=True)
def _precise_matmuls():
    """Kernel-parity tolerances assume fp32 math; on real TPUs jnp matmuls
    default to bf16 internally, so pin the precision for these tests."""
    with jax.default_matmul_precision("highest"):
        yield


from deepspeed_tpu.ops.attention import mha_reference
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention


def make_qkv(rng, shape, dtype=jnp.float32):
    q, k, v = (jnp.asarray(rng.standard_normal(shape), dtype) for _ in range(3))
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("shape", [(1, 2, 256, 64), (2, 2, 128, 32)])
def test_forward_parity(causal, shape):
    rng = np.random.default_rng(0)
    q, k, v = make_qkv(rng, shape)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_backward_parity(causal):
    rng = np.random.default_rng(1)
    q, k, v = make_qkv(rng, (1, 2, 128, 32))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=64,
                                       block_k=64, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_cross_length_causal_offset():
    """Sk > S (decode-style): last q row must attend ALL keys (offset mask)."""
    rng = np.random.default_rng(7)
    q, _, _ = make_qkv(rng, (1, 2, 64, 32))
    _, k, v = make_qkv(rng, (1, 2, 192, 32))
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                          interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_fallback_on_odd_shapes():
    rng = np.random.default_rng(2)
    q, k, v = make_qkv(rng, (1, 1, 100, 24))  # not block-divisible
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_bf16_forward_close():
    rng = np.random.default_rng(3)
    q, k, v = make_qkv(rng, (1, 2, 128, 64), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), rtol=5e-2, atol=5e-2)


# ---------------------------------------------------------------------------
# in-kernel masks / alibi / sliding window / softcap (fwd + bwd parity)
# ---------------------------------------------------------------------------

from deepspeed_tpu.ops.attention import alibi_bias_from_slopes
from deepspeed_tpu.models.transformer import alibi_slopes


def padding_mask(rng, B, S, min_len):
    """Ragged [B, 1, 1, S] key-padding mask with random per-sample lengths."""
    lens = rng.integers(min_len, S + 1, size=(B,))
    return jnp.asarray(np.arange(S)[None, :] < lens[:, None])[:, None, None, :]


def assert_grad_parity(loss_flash, loss_ref, q, k, v, rtol=5e-4, atol=5e-4):
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_padding_mask_parity(causal, seed):
    """Ragged key-padding masks across several random patterns, fwd + bwd."""
    rng = np.random.default_rng(seed)
    q, k, v = make_qkv(rng, (3, 2, 128, 32))
    mask = padding_mask(rng, 3, 128, min_len=16 + seed * 7)
    fa = functools.partial(flash_attention, causal=causal, mask=mask,
                           block_q=64, block_k=64, interpret=True)
    ref = functools.partial(mha_reference, causal=causal, mask=mask)
    np.testing.assert_allclose(fa(q, k, v), ref(q, k, v),
                               rtol=2e-4, atol=2e-4)
    assert_grad_parity(lambda *a: jnp.sum(fa(*a) ** 2),
                       lambda *a: jnp.sum(ref(*a) ** 2), q, k, v)


def test_full_qk_mask_parity():
    """Arbitrary [B, 1, S, S] boolean mask (per-block tiles in-kernel)."""
    rng = np.random.default_rng(4)
    q, k, v = make_qkv(rng, (2, 2, 128, 32))
    m = jnp.asarray(rng.random((2, 1, 128, 128)) > 0.3)
    m = m | jnp.eye(128, dtype=bool)[None, None]     # >=1 active key per row
    fa = functools.partial(flash_attention, causal=False, mask=m,
                           block_q=64, block_k=64, interpret=True)
    ref = functools.partial(mha_reference, causal=False, mask=m)
    np.testing.assert_allclose(fa(q, k, v), ref(q, k, v),
                               rtol=2e-4, atol=2e-4)
    assert_grad_parity(lambda *a: jnp.sum(fa(*a) ** 2),
                       lambda *a: jnp.sum(ref(*a) ** 2), q, k, v)


def test_per_head_mask_parity():
    rng = np.random.default_rng(5)
    q, k, v = make_qkv(rng, (1, 4, 64, 32))
    m = jnp.asarray(rng.random((1, 4, 64, 64)) > 0.4)
    m = m | jnp.eye(64, dtype=bool)[None, None]
    out = flash_attention(q, k, v, causal=True, mask=m, block_q=32,
                          block_k=32, interpret=True)
    ref = mha_reference(q, k, v, causal=True, mask=m)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_fully_masked_rows_zero():
    """Rows with zero active keys: kernel returns 0 output and 0 grads (the
    jnp reference degenerates to uniform weights there — documented
    divergence; real padding layouts never produce such rows)."""
    rng = np.random.default_rng(6)
    q, k, v = make_qkv(rng, (2, 2, 64, 32))
    m = np.ones((2, 1, 1, 64), bool)
    m[1] = False                                    # sample 1: all keys dead
    m = jnp.asarray(m)
    fa = functools.partial(flash_attention, causal=False, mask=m,
                           block_q=32, block_k=32, interpret=True)
    out = fa(q, k, v)
    assert np.allclose(np.asarray(out)[1], 0.0)
    g = jax.grad(lambda *a: jnp.sum(fa(*a) ** 2), argnums=(0, 1, 2))(q, k, v)
    for t in g:
        assert np.allclose(np.asarray(t)[1], 0.0)


def test_fully_masked_rows_zero_qk_mask_path():
    """Same zero-rows contract on the per-tile (qk) mask path: a
    bidirectional padding mask valid[q] & valid[k] leaves padded QUERY rows
    with zero active keys inside otherwise-live tiles. The kernel's fwd
    must produce zeros there (not the degenerate uniform weights) so the
    bwd — which zeroes the same entries — is the true gradient of the fwd;
    valid rows still match the reference exactly."""
    rng = np.random.default_rng(16)
    S, n_valid = 64, 50
    q, k, v = make_qkv(rng, (1, 1, S, 32))
    valid = np.arange(S) < n_valid
    m = jnp.asarray(valid[:, None] & valid[None, :])[None, None]
    fa = functools.partial(flash_attention, causal=False, mask=m,
                           block_q=32, block_k=32, interpret=True)
    out = fa(q, k, v)
    ref = mha_reference(q, k, v, causal=False, mask=m)
    assert np.allclose(np.asarray(out)[:, :, n_valid:], 0.0)
    np.testing.assert_allclose(np.asarray(out)[:, :, :n_valid],
                               np.asarray(ref)[:, :, :n_valid],
                               rtol=2e-4, atol=2e-4)
    # kernel loss over ALL rows == loss over valid rows (dead rows are 0);
    # the reference oracle must exclude its dead-row uniform outputs
    g = jax.grad(lambda *a: jnp.sum(fa(*a) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(mha_reference(
        *a, causal=False, mask=m)[:, :, :n_valid] ** 2),
        argnums=(0, 1, 2))(q, k, v)
    # dead query rows contribute nothing anywhere
    assert np.allclose(np.asarray(g[0])[:, :, n_valid:], 0.0)
    for a, b, name in zip(g, gr, "qkv"):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [True, False])
def test_alibi_parity(causal):
    """Per-head-slope alibi bias rebuilt from block indices in-kernel."""
    rng = np.random.default_rng(7)
    H = 4
    q, k, v = make_qkv(rng, (2, H, 128, 32))
    sl = alibi_slopes(H)
    fa = functools.partial(flash_attention, causal=causal, alibi_slopes=sl,
                           block_q=64, block_k=64, interpret=True)
    ref = functools.partial(mha_reference, causal=causal,
                            bias=alibi_bias_from_slopes(sl, 128, 128))
    np.testing.assert_allclose(fa(q, k, v), ref(q, k, v),
                               rtol=2e-4, atol=2e-4)
    assert_grad_parity(lambda *a: jnp.sum(fa(*a) ** 2),
                       lambda *a: jnp.sum(ref(*a) ** 2), q, k, v)


@pytest.mark.parametrize("window", [16, 48, 200])
def test_sliding_window_parity(window):
    """Causal sliding window: block-level skip + exact per-token boundary."""
    rng = np.random.default_rng(8)
    q, k, v = make_qkv(rng, (1, 2, 128, 32))
    q_pos = np.arange(128)[:, None]
    k_pos = np.arange(128)[None, :]
    wmask = jnp.asarray(q_pos - k_pos < window)[None, None]
    fa = functools.partial(flash_attention, causal=True, window=window,
                           block_q=32, block_k=32, interpret=True)
    ref = functools.partial(mha_reference, causal=True, mask=wmask)
    np.testing.assert_allclose(fa(q, k, v), ref(q, k, v),
                               rtol=2e-4, atol=2e-4)
    assert_grad_parity(lambda *a: jnp.sum(fa(*a) ** 2),
                       lambda *a: jnp.sum(ref(*a) ** 2), q, k, v)


@pytest.mark.parametrize("cap", [5.0, 30.0])
def test_softcap_parity(cap):
    """Gemma-2 tanh softcap pre-softmax; bwd threads the tanh derivative."""
    rng = np.random.default_rng(9)
    q, k, v = make_qkv(rng, (2, 2, 128, 32))
    fa = functools.partial(flash_attention, causal=True, softcap=cap,
                           block_q=64, block_k=64, interpret=True)
    ref = functools.partial(mha_reference, causal=True, softcap=cap)
    np.testing.assert_allclose(fa(q, k, v), ref(q, k, v),
                               rtol=2e-4, atol=2e-4)
    assert_grad_parity(lambda *a: jnp.sum(fa(*a) ** 2),
                       lambda *a: jnp.sum(ref(*a) ** 2), q, k, v)


def test_combined_mask_alibi_softcap_window():
    """All in-kernel features composed at once (BLOOM+Gemma2+Mistral union)."""
    rng = np.random.default_rng(10)
    H, S, W = 4, 128, 96
    q, k, v = make_qkv(rng, (2, H, S, 32))
    mask = padding_mask(rng, 2, S, min_len=32)
    sl = alibi_slopes(H)
    q_pos = np.arange(S)[:, None]
    k_pos = np.arange(S)[None, :]
    wmask = jnp.asarray(q_pos - k_pos < W)[None, None]
    fa = functools.partial(flash_attention, causal=True, mask=mask,
                           alibi_slopes=sl, window=W, softcap=20.0,
                           block_q=32, block_k=32, interpret=True)
    ref = functools.partial(mha_reference, causal=True, mask=mask & wmask,
                            bias=alibi_bias_from_slopes(sl, S, S),
                            softcap=20.0)
    np.testing.assert_allclose(fa(q, k, v), ref(q, k, v),
                               rtol=2e-4, atol=2e-4)
    assert_grad_parity(lambda *a: jnp.sum(fa(*a) ** 2),
                       lambda *a: jnp.sum(ref(*a) ** 2), q, k, v)


def test_masked_cross_length_offset():
    """Sk > S (decode prefill shape) with a key mask + alibi: the offset
    convention (last q row sees all keys) must hold for every feature."""
    rng = np.random.default_rng(11)
    H = 2
    q, _, _ = make_qkv(rng, (1, H, 64, 32))
    _, k, v = make_qkv(rng, (1, H, 192, 32))
    mask = padding_mask(rng, 1, 192, min_len=100)
    sl = alibi_slopes(H)
    out = flash_attention(q, k, v, causal=True, mask=mask, alibi_slopes=sl,
                          block_q=32, block_k=32, interpret=True)
    ref = mha_reference(q, k, v, causal=True, mask=mask,
                        bias=alibi_bias_from_slopes(sl, 64, 192))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("shape", [(1, 2, 100, 24), (2, 1, 144, 32)])
def test_nondivisible_shapes_with_features(shape):
    """Non-divisible block shapes: seq 100 can't tile (reference fallback),
    seq 144 snaps to 48-blocks and stays on the kernel — identical numerics
    either way, with mask+alibi+softcap active."""
    rng = np.random.default_rng(12)
    B, H, S, D = shape
    q, k, v = make_qkv(rng, shape)
    mask = padding_mask(rng, B, S, min_len=S // 2)
    sl = alibi_slopes(H)
    out = flash_attention(q, k, v, causal=True, mask=mask, alibi_slopes=sl,
                          softcap=15.0, interpret=True)
    ref = mha_reference(q, k, v, causal=True, mask=mask,
                        bias=alibi_bias_from_slopes(sl, S, S), softcap=15.0)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
    assert_grad_parity(
        lambda *a: jnp.sum(flash_attention(
            *a, causal=True, mask=mask, alibi_slopes=sl, softcap=15.0,
            interpret=True) ** 2),
        lambda *a: jnp.sum(mha_reference(
            *a, causal=True, mask=mask,
            bias=alibi_bias_from_slopes(sl, S, S), softcap=15.0) ** 2),
        q, k, v)


def test_bf16_masked_softcap_close():
    rng = np.random.default_rng(13)
    q, k, v = make_qkv(rng, (2, 2, 128, 64), jnp.bfloat16)
    mask = padding_mask(rng, 2, 128, min_len=48)
    out = flash_attention(q, k, v, causal=False, mask=mask, softcap=8.0,
                          block_q=64, block_k=64, interpret=True)
    ref = mha_reference(q, k, v, causal=False, mask=mask, softcap=8.0)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)
