"""Process-per-replica fleet suite (round 18, serving/procfleet.py).

Proves the fleet-across-a-pod contract: each replica engine in a
supervised OS PROCESS (serving/replica_worker.py) — weights via
checkpoint load, request/token streams over the transfer fabric's TCP
star, SERVE heartbeats with gauges in the shared channel — and every
request FINISHES token-identical to an uninjected single-process twin
or FAILS within the retry budget, across process death (SIGKILL),
heartbeat silence (SIGSTOP), and the six ``net.*`` link failpoints.

Budget note: every ProcessFleet spawns real worker processes that each
compile the tiny model (seconds apiece), so tier-1 keeps ONE
single-replica fleet (``test_process_fleet_smoke``) plus the cheap
wire/dispatch tests; the fat legs — SIGKILL recovery, the
crash-at-every-failpoint ``net.*`` matrix, SIGSTOP silence — ride
``slow`` with the smoke as their named tier-1 cousin.
"""

import json
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.generation import generate
from deepspeed_tpu.models.transformer import build_model
from deepspeed_tpu.runtime import heartbeat as hb
from deepspeed_tpu.serving import ProcessFleet, ServingFleet, make_fleet
from deepspeed_tpu.serving.replica_worker import cfg_from_dict, cfg_to_dict


@pytest.fixture(scope="module")
def tiny():
    model, cfg = build_model(
        "gpt2-tiny", hidden_size=32, num_layers=2, num_heads=2,
        vocab_size=64, max_seq_len=256, attention_impl="reference",
        dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.zeros((1, 8), jnp.int32)})["params"]
    return cfg, params


def _scfg(replicas=1, **fleet):
    f = {"replicas": replicas, "placement": "process",
         "heartbeat_timeout": 30.0, "poll_interval": 0.1,
         "retry_budget": 2}
    f.update(fleet)
    # prefix_cache off: the pool-balance assertions read the workers'
    # final pool_used gauge, and cached prefix blocks legitimately
    # outlive their requests
    return {"pool_blocks": 32, "block_size": 8, "max_batch": 2,
            "max_blocks_per_seq": 8, "prefix_cache": False, "fleet": f}


def _oracle(cfg, params, prompt, n):
    """The uninjected twin: single-process greedy decode, f32."""
    out = np.asarray(generate(cfg, params, jnp.asarray([prompt]), n))
    return [int(x) for x in out[0][len(prompt):]]


def _fleet(tiny, scfg, tmp_path, **kw):
    cfg, params = tiny
    fl = ProcessFleet(cfg, params, serving=scfg,
                      log_dir=str(tmp_path), **kw)
    fl.start()
    fl.warmup(timeout=240.0)
    return fl


def _check_exact(fl, cfg, params, prompts, reqs, n, retry_budget=2):
    """Every request token-identical to the twin, or FAILED within the
    retry budget — the round-18 acceptance bar."""
    bad = []
    for p, r in zip(prompts, reqs):
        if r.state == "FINISHED" and r.output_tokens == _oracle(
                cfg, params, p, n):
            continue
        if r.state == "FAILED" and r.retries <= retry_budget:
            continue
        bad.append((r.rid, r.state, r.retries, r.output_tokens))
    assert not bad, f"non-token-exact conclusions: {bad}"


# --------------------------------------------------------------------------
# cheap: wire helpers + placement dispatch (no processes spawned)


def test_cfg_wire_roundtrip(tiny):
    cfg, _ = tiny
    d = json.loads(json.dumps(cfg_to_dict(cfg)))     # through real JSON
    cfg2 = cfg_from_dict(d)
    assert cfg_to_dict(cfg2) == cfg_to_dict(cfg)
    assert np.dtype(cfg2.dtype) == np.dtype(cfg.dtype)
    assert cfg2.hidden_size == cfg.hidden_size
    assert cfg2.num_layers == cfg.num_layers


def test_placement_dispatch(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="placement"):
        make_fleet(cfg, params, serving={"fleet": {"placement": "bogus"}})
    # the thread fleet refuses process placement (point users at make_fleet)
    with pytest.raises(ValueError, match="process"):
        ServingFleet(cfg, params,
                     serving={"fleet": {"placement": "process"}})
    # process placement refuses disagg roles (one in-process pool)
    with pytest.raises(ValueError, match="disagg"):
        ProcessFleet(cfg, params, serving={
            "fleet": {"placement": "process", "prefill_replicas": 1,
                      "decode_replicas": 1}})


# --------------------------------------------------------------------------
# tier-1 cousin: one replica process, token-exact, gauges in the channel


def test_process_fleet_smoke(tiny, tmp_path):
    cfg, params = tiny
    fl = _fleet(tiny, _scfg(replicas=1), tmp_path)
    try:
        assert fl.live_replicas() == [0]
        pids = fl.pids()
        assert pids[0] is not None and pids[0] != os.getpid()
        prompts = [[1, 2, 3, 4], [5, 6, 7]]
        reqs = [fl.submit(p, max_new_tokens=8) for p in prompts]
        assert fl.drain(timeout=120.0)
        _check_exact(fl, cfg, params, prompts, reqs, 8)
        assert all(r.state == "FINISHED" for r in reqs)
        assert fl.stats["deaths"] == 0
        assert fl.stats["completed"] == 2
        # SERVE heartbeats with per-process gauges in the shared channel
        # (what `dstpu health <dir>` renders per replica)
        recs = hb.read_heartbeats(fl.heartbeat_dir)
        assert 0 in recs and recs[0]["phase"] == hb.PHASE_SERVE
        gauges = recs[0].get("gauges", {})
        assert gauges.get("pid") == pids[0]
        assert gauges.get("replica") == 0
        assert gauges.get("pool_used") == 0        # drained: pool balanced
    finally:
        fl.close()
    # close() reaps: no zombie worker left behind
    assert all(p.proc.poll() is not None
               for p in fl._replicas if p.proc is not None)


# --------------------------------------------------------------------------
# fat legs (slow; tier-1 cousin: test_process_fleet_smoke)


@pytest.mark.slow
def test_sigkill_midstream_recovery(tiny, tmp_path):
    """SIGKILL a replica PROCESS mid-generation: death verdicted from
    process exit, in-flight requeued token-exactly (the on_token ledger
    never double-fires), warmed restart, pool gauges balanced."""
    cfg, params = tiny
    fl = _fleet(tiny, _scfg(replicas=2), tmp_path)
    seen = {}
    try:
        prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
        reqs = [fl.submit(p, max_new_tokens=24,
                          on_token=lambda r, t: seen.setdefault(
                              r.rid, []).append(t))
                for p in prompts]
        deadline = time.monotonic() + 120.0
        while fl.stats["tokens_emitted"] < 8:      # let tokens flow first
            assert time.monotonic() < deadline, "no tokens before kill"
            time.sleep(0.01)
        os.kill(fl.pids()[0], signal.SIGKILL)
        assert fl.drain(timeout=240.0)
        _check_exact(fl, cfg, params, prompts, reqs, 24)
        for r in reqs:                             # exactly-once emission
            assert r.state == "FINISHED"
            assert seen.get(r.rid) == r.output_tokens
        assert len(fl.deaths) >= 1
        d = fl.deaths[0]
        assert d["replica"] == 0
        assert d["reason"].startswith("process exit")
        assert d["action"] == "restart" and d["restarted_ts"] is not None
        assert fl.stats["requeues"] >= 1
        # the restarted replica may still be loading (it had nothing left
        # to serve) — wait for every live replica's SERVE gauges, then
        # assert the pool balanced; a fixed sleep races the warm restart
        gauge_deadline = time.monotonic() + 120.0
        while True:
            recs = hb.read_heartbeats(fl.heartbeat_dir)
            live = fl.live_replicas()
            if all(recs.get(i, {}).get("gauges", {}).get("pool_used")
                   is not None for i in live):
                break
            assert time.monotonic() < gauge_deadline, \
                f"no SERVE gauges from replicas {live}: {recs}"
            time.sleep(0.1)
        for idx in live:
            assert recs[idx]["gauges"]["pool_used"] == 0, \
                f"replica {idx} leaked KV blocks across the kill"
    finally:
        fl.close()


_MATRIX = {
    "net.connect": "net.connect:raise:times=2",
    "net.send": "net.send:raise:skip=3",
    "net.recv": "net.recv:raise:skip=2",
    "net.corrupt": "net.corrupt:flag:skip=4:times=1",
    "net.partition": "net.partition:raise:skip=3:times=2",
    "net.slow": "net.slow:sleep:ms=50:times=0:p=30",
}


@pytest.mark.slow
@pytest.mark.parametrize("spec", list(_MATRIX.values()),
                         ids=list(_MATRIX))
def test_net_fault_matrix(tiny, tmp_path, spec):
    """Crash-at-every-failpoint: each ``net.*`` spec is armed in the
    FIRST spawn of every worker (env_first — one-shot specs must not
    re-arm in restarts) and the fleet still concludes every request
    token-identical to the uninjected twin or FAILED within budget.
    net.send/net.recv surface unretried (worker death -> requeue);
    net.partition/net.connect heal through the redial ladder;
    net.corrupt is peer-fatal at the receiving end; net.slow only
    stretches the wall clock."""
    cfg, params = tiny
    fl = _fleet(tiny, _scfg(replicas=2), tmp_path,
                env_first={"DSTPU_CHAOS": spec})
    try:
        prompts = [[i + 1, i + 2, i + 3] for i in range(4)]
        reqs = [fl.submit(p, max_new_tokens=16) for p in prompts]
        assert fl.drain(timeout=240.0), \
            f"{spec}: outstanding requests never concluded"
        _check_exact(fl, cfg, params, prompts, reqs, 16)
    finally:
        fl.close()


@pytest.mark.slow
def test_heartbeat_silence_sigstop(tiny, tmp_path):
    """A SIGSTOPped worker freezes its heartbeat refresher — the ONLY
    legitimate silence verdict (a wedged worker THREAD keeps refreshing;
    link loss is a redial, not a death). The supervisor must verdict
    'heartbeat silence', requeue, and finish token-exactly elsewhere."""
    cfg, params = tiny
    fl = _fleet(tiny, _scfg(replicas=2, heartbeat_timeout=4.0), tmp_path)
    victim = None
    try:
        prompts = [[i + 1, i + 2, i + 3] for i in range(4)]
        reqs = [fl.submit(p, max_new_tokens=24) for p in prompts]
        deadline = time.monotonic() + 120.0
        while fl.stats["tokens_emitted"] < 8:
            assert time.monotonic() < deadline, "no tokens before stop"
            time.sleep(0.01)
        victim = fl.pids()[0]
        os.kill(victim, signal.SIGSTOP)            # frozen, not dead
        assert fl.drain(timeout=240.0)
        _check_exact(fl, cfg, params, prompts, reqs, 24)
        assert any(d["reason"] == "heartbeat silence" for d in fl.deaths), \
            f"no silence verdict in {[d['reason'] for d in fl.deaths]}"
    finally:
        if victim is not None:
            try:
                os.kill(victim, signal.SIGCONT)    # let the SIGKILL land
            except ProcessLookupError:
                pass
        fl.close()
