"""Sequence parallelism: ring attention + Ulysses vs dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from util import require_devices


@pytest.fixture(autouse=True)
def _multidevice():
    """This module's features are inherently multi-device (virtual CPU mesh
    in the default suite); skip on platforms with fewer devices."""
    require_devices(4)

from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model, causal_lm_loss
from deepspeed_tpu.ops.attention import mha_reference
from deepspeed_tpu.parallel.mesh import MeshManager, set_global_mesh
from deepspeed_tpu.parallel.ring_attention import (ring_attention,
                                                  ulysses_attention)


@pytest.fixture(scope="module")
def seq_mesh():
    mm = MeshManager(sp_size=4)   # seq=4, data=2
    set_global_mesh(mm)
    return mm


def _qkv(rng, shape, dtype=jnp.float32):
    return tuple(jnp.asarray(rng.standard_normal(shape), dtype)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(seq_mesh, causal):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng, (2, 4, 64, 16))
    sh = NamedSharding(seq_mesh.mesh, P(None, None, "seq"))
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh=seq_mesh.mesh, causal=causal))(qs, ks, vs)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(seq_mesh, causal):
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, (2, 4, 64, 16))
    sh = NamedSharding(seq_mesh.mesh, P(None, None, "seq"))
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))
    out = jax.jit(lambda q, k, v: ulysses_attention(
        q, k, v, mesh=seq_mesh.mesh, causal=causal))(qs, ks, vs)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match(seq_mesh):
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, (1, 2, 32, 16))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=seq_mesh.mesh,
                                      causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5, err_msg=f"d{n}")


def test_transformer_with_ring_attention_trains(seq_mesh):
    """Flagship model with impl='ring' on a seq-sharded mesh descends."""
    model, cfg = build_model("gpt2-tiny", hidden_size=64, num_layers=2,
                             num_heads=4, vocab_size=256, max_seq_len=64,
                             attention_impl="ring", dtype=jnp.float32)
    config = {
        "train_batch_size": 4,
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
        "zero_optimization": {"stage": 1},
        "sequence_parallel": {"sp_size": 4},
    }
    rng = np.random.default_rng(3)
    mk = lambda: {"input_ids": rng.integers(0, 256, size=(4, 32))}
    engine, *_ = ds.initialize(model=model, config=config,
                               loss_fn=causal_lm_loss, example_batch=mk())
    losses = [float(engine.train_batch(mk())["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0], losses
