"""Pallas decode-attention kernel: parity vs the jnp full-cache oracle.

Mirrors the reference's inference kernel tests (tests/unit/ops/transformer/
inference) — softmax_context against the preallocated KV workspace — in
interpreter mode on CPU; the same kernel runs compiled on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas.decode_attention import decode_attention


def _oracle(q, kc, vc, cur, window=0, slopes=None, softcap=0.0):
    B, nh, T, hd = q.shape
    max_len = kc.shape[2]
    q_abs = np.arange(cur - T, cur)
    k_pos = np.arange(max_len)
    mask = k_pos[None, :] <= q_abs[:, None]
    if window > 0:
        mask = mask & (q_abs[:, None] - k_pos[None, :] < window)
    s = np.einsum("bhqd,bhkd->bhqk", np.asarray(q, np.float32),
                  np.asarray(kc, np.float32)) / np.sqrt(hd)
    if softcap:
        s = np.tanh(s / softcap) * softcap
    if slopes is not None:
        dist = (k_pos[None, :] - q_abs[:, None]).astype(np.float32)
        s = s + slopes[None, :, None, None] * dist[None, None]
    s = np.where(mask[None, None], s, -1e30)
    p = np.asarray(jax.nn.softmax(jnp.asarray(s), axis=-1))
    return np.einsum("bhqk,bhkd->bhqd", p, np.asarray(vc, np.float32))


def _data(B=2, nh=4, T=1, hd=64, max_len=512, cur=200, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, nh, T, hd)).astype(dtype)
    kc = np.zeros((B, nh, max_len, hd), dtype)
    vc = np.zeros((B, nh, max_len, hd), dtype)
    kc[:, :, :cur] = rng.standard_normal((B, nh, cur, hd))
    vc[:, :, :cur] = rng.standard_normal((B, nh, cur, hd))
    return q, kc, vc


@pytest.mark.parametrize("T,cur", [(1, 200), (1, 512), (4, 300), (8, 512)])
def test_decode_parity(T, cur):
    q, kc, vc = _data(T=T, cur=cur)
    out = decode_attention(jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
                           jnp.asarray(cur, jnp.int32), interpret=True)
    np.testing.assert_allclose(np.asarray(out), _oracle(q, kc, vc, cur),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [32, 64, 500])
def test_decode_sliding_window(window):
    q, kc, vc = _data(cur=400)
    out = decode_attention(jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
                           jnp.asarray(400, jnp.int32),
                           window=jnp.asarray(window, jnp.int32),
                           interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               _oracle(q, kc, vc, 400, window=window),
                               rtol=2e-5, atol=2e-5)


def test_decode_stacked_layer_cache():
    """layer_idx form: the kernel indexes blocks out of the [L, ...] cache
    (the scan-carried layout) without a materialized slice."""
    L, cur = 3, 256
    q, kc, vc = _data(cur=cur)
    kcl = np.stack([kc * (l + 1) for l in range(L)])
    vcl = np.stack([vc * 0.5 * (l + 1) for l in range(L)])
    for li in range(L):
        out = decode_attention(jnp.asarray(q), jnp.asarray(kcl),
                               jnp.asarray(vcl), jnp.asarray(cur, jnp.int32),
                               layer_idx=jnp.asarray(li, jnp.int32),
                               interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), _oracle(q, kcl[li], vcl[li], cur),
            rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("T,cur", [(1, 200), (4, 300)])
def test_decode_alibi(T, cur):
    """BLOOM/MPT regime: per-head ALiBi slopes applied in-kernel."""
    q, kc, vc = _data(T=T, cur=cur)
    from deepspeed_tpu.models.transformer import alibi_slopes
    sl = np.asarray(alibi_slopes(4), np.float32)
    out = decode_attention(jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
                           jnp.asarray(cur, jnp.int32),
                           alibi_slopes=jnp.asarray(sl), interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               _oracle(q, kc, vc, cur, slopes=sl),
                               rtol=2e-5, atol=2e-5)


def test_decode_softcap():
    """Gemma-2 regime: tanh logit softcap in-kernel, pre-mask."""
    q, kc, vc = _data(cur=300)
    out = decode_attention(jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
                           jnp.asarray(300, jnp.int32), softcap=20.0,
                           interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               _oracle(q, kc, vc, 300, softcap=20.0),
                               rtol=2e-5, atol=2e-5)


def test_decode_alibi_softcap_window_compose():
    q, kc, vc = _data(cur=400)
    from deepspeed_tpu.models.transformer import alibi_slopes
    sl = np.asarray(alibi_slopes(4), np.float32)
    out = decode_attention(jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
                           jnp.asarray(400, jnp.int32),
                           window=jnp.asarray(64, jnp.int32),
                           alibi_slopes=jnp.asarray(sl), softcap=15.0,
                           interpret=True)
    np.testing.assert_allclose(
        np.asarray(out),
        _oracle(q, kc, vc, 400, window=64, slopes=sl, softcap=15.0),
        rtol=2e-5, atol=2e-5)


def test_decode_bf16():
    q, kc, vc = _data(cur=300)
    import ml_dtypes
    to_bf = lambda a: jnp.asarray(a).astype(jnp.bfloat16)
    out = decode_attention(to_bf(q), to_bf(kc), to_bf(vc),
                           jnp.asarray(300, jnp.int32), interpret=True)
    ref = _oracle(q, kc, vc, 300)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               rtol=5e-2, atol=5e-2)


def test_decode_fallback_guards():
    q, kc, vc = _data()
    with pytest.raises(ValueError, match="small T"):
        decode_attention(jnp.zeros((1, 2, 128, 64)), jnp.asarray(kc),
                         jnp.asarray(vc), jnp.asarray(10), interpret=True)
    with pytest.raises(ValueError, match="tiling"):
        decode_attention(jnp.zeros((1, 2, 1, 64)),
                         jnp.zeros((1, 2, 100, 64)), jnp.zeros((1, 2, 100, 64)),
                         jnp.asarray(10), interpret=True)


@pytest.mark.slow
def test_generation_uses_jnp_path_on_cpu_and_matches():
    """On the CPU backend the decode path takes the jnp route; this pins the
    restructured carry-cache scan (in-place KV update) to the same numerics
    as a fresh full forward."""
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.models.generation import (ensure_scan_layout,
                                                 forward_with_cache, init_cache)
    model, cfg = build_model("gpt2-tiny", hidden_size=32, num_layers=2,
                             num_heads=2, vocab_size=64, max_seq_len=64,
                             attention_impl="reference")
    ids = np.random.default_rng(0).integers(0, 64, size=(2, 10)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    full_logits = model.apply({"params": params}, {"input_ids": ids})
    sparams = ensure_scan_layout(params, cfg.num_layers)
    cache = init_cache(cfg, 2, 16)
    logits, cache = forward_with_cache(cfg, sparams, jnp.asarray(ids), cache)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full_logits),
                               rtol=2e-2, atol=2e-2)
    assert int(cache["pos"]) == 10
