"""HF architecture import policies: logits parity vs torch for GPT-Neo,
GPT-J, OPT, BLOOM, BERT (the GPT-2 policy test lives in test_inference.py).

Mirrors the reference's replace_policy.py per-arch coverage
(module_inject/replace_policy.py:18-32) with tiny randomly-initialized HF
models as oracles.
"""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from deepspeed_tpu.models.hf import load_hf
from deepspeed_tpu.models.transformer import Transformer


def _ours_from(hf_model, ids, batch_extra=None):
    params, cfg = load_hf(hf_model)
    model = Transformer(cfg.__class__(**{**cfg.__dict__,
                                         "dtype": jnp.float32,
                                         "attention_impl": "reference"}))
    batch = {"input_ids": jnp.asarray(ids)}
    if batch_extra:
        batch.update(batch_extra)
    return np.asarray(model.apply({"params": params}, batch))


@pytest.mark.slow
def test_hf_gpt_neo_parity():
    """Alternating global/local attention + unscaled attn + unbiased qkv."""
    hf_cfg = transformers.GPTNeoConfig(
        vocab_size=96, max_position_embeddings=32, hidden_size=32,
        num_layers=4, num_heads=4, intermediate_size=64,
        attention_types=[[["global", "local"], 2]], window_size=8)
    hf = transformers.GPTNeoForCausalLM(hf_cfg).eval()
    ids = np.random.default_rng(0).integers(0, 96, (2, 24))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    ours = _ours_from(hf, ids)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


# tier-2 (round 8 budget): the fattest per-arch HF parity leg; the other
# arch parities (falcon/mixtral/qwen3/...) keep gating tier-1
@pytest.mark.slow
def test_hf_gptj_parity():
    """Rotary positions + parallel residual + untied biased lm head."""
    hf_cfg = transformers.GPTJConfig(
        vocab_size=96, n_positions=32, n_embd=32, n_layer=2, n_head=4,
        rotary_dim=4)
    hf = transformers.GPTJForCausalLM(hf_cfg).eval()
    ids = np.random.default_rng(1).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    ours = _ours_from(hf, ids)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


# tier-2 (round 10 budget): fattest passing legs demoted per the standing
# guardrail — tier-1 crept past ~80% of the 870s budget once the comm-plan
# legs landed and the jax_compat shard_map wrapper recovered the 1-bit
# family on 0.4.x hosts; cheaper cousins still gate tier-1
@pytest.mark.slow
def test_hf_opt_parity():
    """ReLU MLP + learned positions at +2 offset."""
    hf_cfg = transformers.OPTConfig(
        vocab_size=96, max_position_embeddings=32, hidden_size=32,
        num_hidden_layers=2, num_attention_heads=4, ffn_dim=64,
        word_embed_proj_dim=32, do_layer_norm_before=True)
    hf = transformers.OPTForCausalLM(hf_cfg).eval()
    ids = np.random.default_rng(2).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    ours = _ours_from(hf, ids)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_hf_bloom_parity():
    """ALiBi attention + embedding LayerNorm + head-major fused qkv.

    slow (round-14 budget sweep, 11s): the cheaper tier-1 cousins are
    the other arch parities in this file (gpt2/llama/...) and the ALiBi
    kernel parity in test_flash_attention.py / routing in
    test_attention_routing.py."""
    hf_cfg = transformers.BloomConfig(
        vocab_size=96, hidden_size=32, n_layer=2, n_head=4)
    hf = transformers.BloomForCausalLM(hf_cfg).eval()
    ids = np.random.default_rng(3).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    ours = _ours_from(hf, ids)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


# tier-2 (round-19 budget sweep, ~7s): the cheaper tier-1 cousins are
# test_hf_roberta_parity + test_hf_distilbert_parity (same encoder
# loader family) and test_attention_routing's
# test_masked_bert_trains_through_kernel; scripts/tier2.sh runs this
# MLM-head leg
@pytest.mark.slow
def test_hf_bert_parity():
    """Post-LN encoder + token types + MLM transform head."""
    hf_cfg = transformers.BertConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, type_vocab_size=2)
    hf = transformers.BertForMaskedLM(hf_cfg).eval()
    rng = np.random.default_rng(4)
    ids = rng.integers(0, 96, (2, 16))
    tt = rng.integers(0, 2, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids), token_type_ids=torch.tensor(tt)).logits.numpy()
    ours = _ours_from(hf, ids, {"token_type_ids": jnp.asarray(tt)})
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


def test_unknown_arch_raises():
    with pytest.raises(NotImplementedError, match="policy"):
        load_hf(object(), arch="T5ForConditionalGeneration")


# -- KV-cache decode parity for the policy architectures ----------------------

import dataclasses

import jax
from deepspeed_tpu.models.generation import forward_with_cache, init_cache


def _decode_vs_full(hf_model, ids, rtol=2e-3):
    """Last-token logits from the cached decode path must match the full
    forward (which is itself HF-parity-tested above)."""
    from deepspeed_tpu.models.hf import load_hf
    params, cfg = load_hf(hf_model)
    cfg = dataclasses.replace(cfg, dtype=jnp.float32,
                              attention_impl="reference")
    model = Transformer(cfg)
    full = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    cache = init_cache(cfg, ids.shape[0], ids.shape[1])
    # feed the prompt in two chunks to exercise pos-offset handling
    half = ids.shape[1] // 2
    _, cache = forward_with_cache(cfg, params, jnp.asarray(ids[:, :half]),
                                  cache)
    logits, _ = forward_with_cache(cfg, params, jnp.asarray(ids[:, half:]),
                                   cache)
    np.testing.assert_allclose(np.asarray(logits[:, -1]), full[:, -1],
                               rtol=rtol, atol=rtol)


def test_gptj_decode_parity():
    hf_cfg = transformers.GPTJConfig(vocab_size=96, n_positions=32, n_embd=32,
                                     n_layer=2, n_head=4, rotary_dim=4)
    hf = transformers.GPTJForCausalLM(hf_cfg).eval()
    _decode_vs_full(hf, np.random.default_rng(5).integers(0, 96, (2, 16)))


def test_gpt_neo_decode_parity():
    hf_cfg = transformers.GPTNeoConfig(
        vocab_size=96, max_position_embeddings=32, hidden_size=32,
        num_layers=4, num_heads=4, intermediate_size=64,
        attention_types=[[["global", "local"], 2]], window_size=8)
    hf = transformers.GPTNeoForCausalLM(hf_cfg).eval()
    _decode_vs_full(hf, np.random.default_rng(6).integers(0, 96, (2, 16)))


def test_opt_decode_parity():
    hf_cfg = transformers.OPTConfig(
        vocab_size=96, max_position_embeddings=32, hidden_size=32,
        num_hidden_layers=2, num_attention_heads=4, ffn_dim=64,
        word_embed_proj_dim=32, do_layer_norm_before=True)
    hf = transformers.OPTForCausalLM(hf_cfg).eval()
    _decode_vs_full(hf, np.random.default_rng(7).integers(0, 96, (2, 16)))


def test_bloom_decode_parity():
    hf_cfg = transformers.BloomConfig(vocab_size=96, hidden_size=32,
                                      n_layer=2, n_head=4)
    hf = transformers.BloomForCausalLM(hf_cfg).eval()
    _decode_vs_full(hf, np.random.default_rng(8).integers(0, 96, (2, 16)))


@pytest.mark.slow
def test_moe_decode_parity():
    """MoE models decode (round-1 gap: generation.py raised); with a no-drop
    capacity factor the cached decode matches the full forward."""
    from deepspeed_tpu.models import build_model
    model, cfg = build_model("gpt2-tiny", moe_experts=4,
                             moe_capacity_factor=4.0, dtype=jnp.float32,
                             attention_impl="reference")
    ids = np.random.default_rng(9).integers(0, cfg.vocab_size, (2, 16))
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.asarray(ids)})["params"]
    logits_full, _aux = model.apply({"params": params},
                                    {"input_ids": jnp.asarray(ids)})
    cache = init_cache(cfg, 2, 16)
    _, cache = forward_with_cache(cfg, params, jnp.asarray(ids[:, :8]), cache)
    logits, _ = forward_with_cache(cfg, params, jnp.asarray(ids[:, 8:]), cache)
    np.testing.assert_allclose(np.asarray(logits[:, -1]),
                               np.asarray(logits_full[:, -1]),
                               rtol=2e-3, atol=2e-3)


def test_hf_roberta_parity():
    """RoBERTa: BERT encoder + position offset + lm_head transform."""
    hf_cfg = transformers.RobertaConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=34, type_vocab_size=1, pad_token_id=1)
    hf = transformers.RobertaForMaskedLM(hf_cfg).eval()
    ids = np.random.default_rng(10).integers(2, 96, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    ours = _ours_from(hf, ids)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


def test_hf_distilbert_parity():
    hf_cfg = transformers.DistilBertConfig(
        vocab_size=96, dim=32, n_layers=2, n_heads=4, hidden_dim=64,
        max_position_embeddings=32)
    hf = transformers.DistilBertForMaskedLM(hf_cfg).eval()
    ids = np.random.default_rng(11).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    ours = _ours_from(hf, ids)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


def test_hf_gpt_neox_parity():
    """GPT-NeoX/Pythia: dual-LN parallel residual + rotate_half rotary over
    rotary_pct of head_dim + per-head-interleaved fused qkv."""
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, rotary_pct=0.25,
        use_parallel_residual=True)
    hf = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    ids = np.random.default_rng(12).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    ours = _ours_from(hf, ids)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


def test_hf_gpt_neox_sequential_parity():
    """use_parallel_residual=False NeoX variants reduce to the standard
    sequential pre-LN block."""
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, rotary_pct=1.0,
        use_parallel_residual=False)
    hf = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    ids = np.random.default_rng(13).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    ours = _ours_from(hf, ids)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


def test_hf_gpt_neox_decode_parity():
    # rotary_pct=0.5 of head_dim 8 gives rotary_dim 4: at rd=2 the rotate_half
    # and interleaved layouts coincide and the test would be vacuous. Likewise
    # perturb the LayerNorms away from fresh-init identity so the dual-LN
    # parallel residual (ln1 != ln2) is actually observable in decode.
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, rotary_pct=0.5)
    hf = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for name, p in hf.named_parameters():
            if "layernorm" in name:
                p.add_(torch.randn_like(p) * 0.2)
    ids = np.random.default_rng(14).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(_ours_from(hf, ids), ref, rtol=2e-3, atol=2e-3)
    _decode_vs_full(hf, ids)


def test_hf_clip_text_parity():
    """CLIP text encoder: causal pre-LN + quick_gelu; output = final hidden
    states (no LM head)."""
    hf_cfg = transformers.CLIPTextConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32)
    hf = transformers.CLIPTextModel(hf_cfg).eval()
    ids = np.random.default_rng(15).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).last_hidden_state.numpy()
    ours = _ours_from(hf, ids)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


def test_megatron_gpt_load():
    """Megatron-LM GPT checkpoint layout: the v2 per-head-interleaved fused
    qkv de-interleaves to exactly the column-chunked v0 layout."""
    from deepspeed_tpu.models.hf import load_megatron_gpt
    rng = np.random.default_rng(16)
    L, H, nh, V, S = 2, 32, 4, 96, 32
    hd = H // nh

    def mk(shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.05

    qw = [mk((H, H)) for _ in range(L)]      # rows = output (q) dim
    kw = [mk((H, H)) for _ in range(L)]
    vw = [mk((H, H)) for _ in range(L)]
    qb = [mk((H,)) for _ in range(L)]
    kb = [mk((H,)) for _ in range(L)]
    vb = [mk((H,)) for _ in range(L)]

    def interleave_w(i):
        # [nh, 3, hd, H] row layout of megatron v2 fused qkv
        per = np.stack([qw[i].reshape(nh, hd, H), kw[i].reshape(nh, hd, H),
                        vw[i].reshape(nh, hd, H)], axis=1)
        return per.reshape(3 * H, H)

    def interleave_b(i):
        per = np.stack([qb[i].reshape(nh, hd), kb[i].reshape(nh, hd),
                        vb[i].reshape(nh, hd)], axis=1)
        return per.reshape(3 * H)

    sd = {"language_model.embedding.word_embeddings.weight": mk((V, H)),
          "language_model.embedding.position_embeddings.weight": mk((S, H)),
          "language_model.encoder.final_layernorm.weight": mk((H,)),
          "language_model.encoder.final_layernorm.bias": mk((H,))}
    for i in range(L):
        p = f"language_model.encoder.layers.{i}."
        sd[p + "input_layernorm.weight"] = mk((H,))
        sd[p + "input_layernorm.bias"] = mk((H,))
        sd[p + "attention.query_key_value.weight"] = interleave_w(i)
        sd[p + "attention.query_key_value.bias"] = interleave_b(i)
        sd[p + "attention.dense.weight"] = mk((H, H))
        sd[p + "attention.dense.bias"] = mk((H,))
        sd[p + "post_attention_layernorm.weight"] = mk((H,))
        sd[p + "post_attention_layernorm.bias"] = mk((H,))
        sd[p + "mlp.dense_h_to_4h.weight"] = mk((2 * H, H))
        sd[p + "mlp.dense_h_to_4h.bias"] = mk((2 * H,))
        sd[p + "mlp.dense_4h_to_h.weight"] = mk((H, 2 * H))
        sd[p + "mlp.dense_4h_to_h.bias"] = mk((H,))

    meta = {"num_layers": L, "hidden_size": H, "num_heads": nh,
            "vocab_size": V, "max_seq_len": S, "mlp_ratio": 2}
    params, cfg = load_megatron_gpt(sd, meta, version=2)
    # oracle: the de-interleaved kernel must equal the hand-concatenated one
    expect = np.concatenate([qw[0].T, kw[0].T, vw[0].T], axis=1)
    np.testing.assert_allclose(
        np.asarray(params["blocks"]["attn_qkv"]["kernel"][0]), expect,
        rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(params["blocks"]["attn_qkv"]["bias"][0]),
        np.concatenate([qb[0], kb[0], vb[0]]), rtol=1e-6, atol=1e-6)
    # and the loaded model must run
    model = Transformer(cfg.__class__(**{**cfg.__dict__,
                                         "dtype": jnp.float32,
                                         "attention_impl": "reference"}))
    ids = rng.integers(0, V, (2, 16))
    out = model.apply({"params": params}, {"input_ids": jnp.asarray(ids)})
    assert np.asarray(out).shape == (2, 16, V)


def test_replace_and_revert_transformer_layer_api():
    """Reference export names (deepspeed/__init__.py:24-35): replace maps an
    HF model functionally onto the TPU-native Transformer (logits parity);
    revert returns the untouched original."""
    import deepspeed_tpu as ds

    hf_cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=32, n_layer=2, n_head=4)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    module, params, cfg = ds.replace_transformer_layer(
        hf, dtype=jnp.float32)
    assert module.cfg.dtype == jnp.float32      # dtype override applied
    ids = np.random.default_rng(2).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    import dataclasses
    from deepspeed_tpu.models.transformer import Transformer
    # parity through the RETURNED module's cfg (only the attention impl is
    # swapped — the Pallas kernel needs a TPU)
    module = Transformer(dataclasses.replace(
        module.cfg, attention_impl="reference"))
    ours = np.asarray(module.apply({"params": params},
                                   {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)
    assert ds.revert_transformer_layer(hf) is hf


def test_deepspeed_transformer_layer_module():
    """DeepSpeedTransformerLayer: one block over [B, S, H] hidden states
    (the reference's fused-layer export, ops/transformer/transformer.py:459)."""
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(hidden_size=32, num_heads=4, num_layers=1,
                            dtype=jnp.float32, attention_impl="reference")
    layer = ds.DeepSpeedTransformerLayer(cfg)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 8, 32)),
                    jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x)
    y = layer.apply(params, x)
    assert y.shape == x.shape and np.isfinite(np.asarray(y)).all()
    assert ds.DeepSpeedTransformerConfig is TransformerConfig
    assert "dtype" in ds.default_inference_config()


def test_replace_transformer_layer_raw_state_dict():
    """The shim threads an explicit HF config through to the policy (the
    raw-state-dict path load_hf's live-model dispatch can't carry)."""
    import deepspeed_tpu as ds

    hf_cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=32, n_layer=2, n_head=4)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    sd = hf.state_dict()
    module, params, cfg = ds.replace_transformer_layer(
        sd, config=hf_cfg, arch="gpt2")
    assert cfg.num_layers == 2 and cfg.hidden_size == 32
    with pytest.raises(NotImplementedError, match="no import policy"):
        ds.replace_transformer_layer(sd, config=hf_cfg, arch="not-an-arch")


def test_deepspeed_transformer_layer_mask_contract():
    """The shim validates the mask: boolean/int True=attend (HF [B,S]
    accepted and expanded); the reference's ADDITIVE float mask is rejected
    loudly (silently passing it would attend the inverted positions)."""
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(hidden_size=32, num_heads=4, num_layers=1,
                            dtype=jnp.float32, causal=False,
                            attention_impl="reference")
    layer = ds.DeepSpeedTransformerLayer(cfg)
    x = jnp.asarray(np.random.default_rng(4).standard_normal((2, 8, 32)),
                    jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x)
    mask = np.ones((2, 8), np.int32)
    mask[:, -3:] = 0
    y_masked = layer.apply(params, x, jnp.asarray(mask))
    assert np.isfinite(np.asarray(y_masked)).all()
    # masking the tail must change the visible positions' outputs
    y_full = layer.apply(params, x)
    assert not np.allclose(np.asarray(y_masked)[:, :5],
                           np.asarray(y_full)[:, :5])
    with pytest.raises(ValueError, match="additive"):
        layer.apply(params, x, (1.0 - mask) * -10000.0)
    with pytest.raises(ValueError, match="MoE"):
        moe_layer = ds.DeepSpeedTransformerLayer(
            TransformerConfig(hidden_size=32, num_heads=4, num_layers=1,
                              moe_experts=4, dtype=jnp.float32,
                              attention_impl="reference"))
        moe_layer.init(jax.random.PRNGKey(0), x)


def _llama_tiny(**over):
    kw = dict(vocab_size=96, hidden_size=32, intermediate_size=56,
              num_hidden_layers=2, num_attention_heads=4,
              num_key_value_heads=2, max_position_embeddings=64)
    kw.update(over)
    # seeded weights: the token-exact greedy checks are knife-edge argmaxes
    # over near-random logits — unseeded torch init made them flaky
    torch.manual_seed(7)
    return transformers.LlamaForCausalLM(transformers.LlamaConfig(**kw)).eval()


def test_hf_llama_parity():
    """Llama family (EXCEEDS the reference's replace_policy list — v0.8.1
    pre-dates Llama): RMSNorm, SwiGLU, grouped-query attention, rotate_half
    rotary with config rope_theta."""
    import dataclasses
    hf = _llama_tiny(rope_theta=500000.0)
    ids = np.random.default_rng(0).integers(0, 96, (2, 24))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.norm == "rmsnorm" and cfg.gated_mlp and cfg.num_kv_heads == 2
    assert cfg.rope_theta == 500000.0
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)


def test_hf_mistral_parity():
    """Mistral: the Llama block family + a uniform sliding window on every
    layer (window smaller than the test seq so it actually binds)."""
    import dataclasses
    hf = transformers.MistralForCausalLM(transformers.MistralConfig(
        vocab_size=96, hidden_size=32, intermediate_size=56,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=8)).eval()
    ids = np.random.default_rng(1).integers(0, 96, (2, 24))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.layer_windows == (8, 8, 8)
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)


def test_hf_llama_greedy_generate_matches():
    """KV-cache decode (RMSNorm + GQA + SwiGLU through the scan loop) is
    token-exact vs HF greedy generate."""
    import dataclasses
    from deepspeed_tpu.models.generation import generate
    hf = _llama_tiny()
    params, cfg = load_hf(hf)
    cfg = dataclasses.replace(cfg, dtype=jnp.float32,
                              attention_impl="reference")
    ids = np.random.default_rng(2).integers(0, 96, (2, 10))
    with torch.no_grad():
        ref = hf.generate(torch.tensor(ids), max_new_tokens=8,
                          do_sample=False).numpy()
    ours = np.asarray(generate(cfg, params, jnp.asarray(ids), 8))
    np.testing.assert_array_equal(ours, ref)


# tier-2 (round-19 budget sweep, ~4s): the cheaper tier-1 cousins are
# test_hf_llama_parity + test_hf_mistral_parity (real GQA ratios vs
# HF); scripts/tier2.sh runs this degenerate-ratio pin
@pytest.mark.slow
def test_gqa_matches_mha_when_kv_heads_equal():
    """num_kv_heads == num_heads must be numerically identical to the MHA
    path (the GQA split/repeat degenerates away)."""
    from deepspeed_tpu.models import build_model
    kw = dict(hidden_size=64, num_layers=2, num_heads=4, vocab_size=128,
              max_seq_len=32, dtype=jnp.float32, attention_impl="reference")
    m1, _ = build_model("gpt2-tiny", **kw)
    m2, _ = build_model("gpt2-tiny", num_kv_heads=4, **kw)
    import jax
    batch = {"input_ids": jnp.zeros((2, 16), jnp.int32)}
    p = m1.init(jax.random.PRNGKey(0), batch)["params"]
    np.testing.assert_array_equal(
        np.asarray(m1.apply({"params": p}, batch)),
        np.asarray(m2.apply({"params": p}, batch)))


def test_hf_llama_attention_bias_parity():
    """Qwen-style attention_bias=True: biased q/k/v/o projections map and
    match HF; genuinely unsupported RoPE geometry (yarn) is still REJECTED
    at load instead of decoding garbage."""
    import dataclasses
    hf = _llama_tiny(attention_bias=True)
    ids = np.random.default_rng(3).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert "bias" in params["blocks"]["attn_qkv"]
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)

    with pytest.raises(NotImplementedError, match="yarn"):
        load_hf(_llama_tiny(num_hidden_layers=1,
                            rope_scaling={"rope_type": "yarn",
                                          "factor": 2.0}))


def test_hf_llama3_rope_scaling_parity():
    """Llama-3.1-style rope_scaling (per-frequency remap): logits parity
    and token-exact greedy decode vs HF. The original window (16) is far
    below max (64) so all three frequency bands (high kept, low divided,
    medium smoothed) are exercised. Round 4 refused these checkpoints;
    the table now mirrors HF modeling_rope_utils._compute_llama3_parameters."""
    import dataclasses
    from deepspeed_tpu.models.generation import generate
    hf = _llama_tiny(rope_scaling={
        "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
        "high_freq_factor": 4.0, "original_max_position_embeddings": 16})
    ids = np.random.default_rng(6).integers(0, 96, (2, 24))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.rope_scaling_type == "llama3"
    assert cfg.rope_original_max_position == 16
    # the static table itself matches HF's llama3 remap
    from transformers.modeling_rope_utils import _compute_llama3_parameters
    ref_inv, _ = _compute_llama3_parameters(hf.config, device="cpu")
    np.testing.assert_allclose(cfg.rope_inv_freq(), ref_inv.numpy(),
                               rtol=1e-6)
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)
    # token-exact greedy through the KV-cache decode path
    pids = np.random.default_rng(7).integers(0, 96, (2, 10))
    with torch.no_grad():
        gref = hf.generate(torch.tensor(pids), max_new_tokens=8,
                           do_sample=False).numpy()
    gcfg = dataclasses.replace(cfg, dtype=jnp.float32,
                               attention_impl="reference")
    np.testing.assert_array_equal(
        np.asarray(generate(gcfg, params, jnp.asarray(pids), 8)), gref)


def test_hf_llama_linear_and_dynamic_rope_parity():
    """Linear position-interpolation scaling: logits parity vs HF. Dynamic
    NTK: the static table equals HF's _compute_dynamic_ntk_parameters at
    every target length (beyond the original window the base stretches;
    within it the table is the default one — checked both ways)."""
    import dataclasses
    hf = _llama_tiny(rope_scaling={"rope_type": "linear", "factor": 2.0})
    ids = np.random.default_rng(8).integers(0, 96, (2, 24))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.rope_scaling_type == "linear"
    assert cfg.rope_scaling_factor == 2.0
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)

    from transformers.modeling_rope_utils import \
        _compute_dynamic_ntk_parameters
    from deepspeed_tpu.models.transformer import TransformerConfig
    hcfg = transformers.LlamaConfig(
        hidden_size=32, num_attention_heads=4, max_position_embeddings=32,
        rope_theta=10000.0,
        rope_scaling={"rope_type": "dynamic", "factor": 2.0})
    for S in (16, 32, 64, 128):
        ref_inv, _ = _compute_dynamic_ntk_parameters(hcfg, seq_len=S)
        mine = TransformerConfig(
            hidden_size=32, num_heads=4, max_seq_len=32, pos_embed="rotary",
            rope_scaling_type="dynamic", rope_scaling_factor=2.0,
            rope_original_max_position=32).rope_inv_freq(S)
        np.testing.assert_allclose(mine, ref_inv.numpy(), rtol=1e-6)

    # HF's dynamic path IGNORES the dict's original_max_position_embeddings
    # (explicit TODO in modeling_rope_utils) and stretches relative to
    # config.max_position_embeddings — the loader must mirror that, not
    # trust the dict key
    _, cfg_d = load_hf(_llama_tiny(
        num_hidden_layers=1, max_position_embeddings=64,
        rope_scaling={"rope_type": "dynamic", "factor": 2.0,
                      "original_max_position_embeddings": 16}))
    assert cfg_d.rope_original_max_position == 64
    # a scaled config without the mandatory "factor" must fail loudly,
    # not load as an unscaled table
    with pytest.raises(KeyError, match="factor"):
        sd = _llama_tiny(num_hidden_layers=1).state_dict()
        bad = transformers.LlamaConfig(
            vocab_size=96, hidden_size=32, intermediate_size=56,
            num_hidden_layers=1, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64)
        bad.rope_scaling = {"rope_type": "linear"}
        load_hf(sd, arch="llama", config=bad)

    # end-to-end dynamic at S=64 BEYOND the original 32-window: HF's
    # forward recomputes the stretched base from max(position)+1, and the
    # block passes the trace-time S so the tables agree — this is the
    # branch a static-table loader would silently get wrong
    hf3 = _llama_tiny(max_position_embeddings=32,
                      rope_scaling={"rope_type": "dynamic", "factor": 2.0})
    ids3 = np.random.default_rng(15).integers(0, 96, (2, 64))
    with torch.no_grad():
        ref3 = hf3(torch.tensor(ids3)).logits.numpy()
    params3, cfg3 = load_hf(hf3)
    assert cfg3.rope_scaling_type == "dynamic"
    model3 = Transformer(dataclasses.replace(cfg3, dtype=jnp.float32,
                                             attention_impl="reference"))
    ours3 = np.asarray(model3.apply({"params": params3},
                                    {"input_ids": jnp.asarray(ids3)}))
    np.testing.assert_allclose(ours3, ref3, rtol=4e-3, atol=4e-3)


def test_hf_llama_decoupled_head_dim_parity():
    """Mistral-Nemo-style decoupled head_dim (16 vs hidden/heads = 8):
    qkv projects to (nh + 2*kv) * 16 and attn_proj maps 64 -> 32. Logits
    parity and token-exact greedy decode vs HF."""
    import dataclasses
    from deepspeed_tpu.models.generation import generate
    hf = _llama_tiny(head_dim=16)
    ids = np.random.default_rng(9).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.head_dim == 16 and cfg.head_dim_override == 16
    # [L, H, (nh + 2*kv) * hd] = [2, 32, (4 + 4) * 16]
    assert params["blocks"]["attn_qkv"]["kernel"].shape == (2, 32, 128)
    assert params["blocks"]["attn_proj"]["kernel"].shape == (2, 64, 32)
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)
    pids = np.random.default_rng(10).integers(0, 96, (2, 10))
    with torch.no_grad():
        gref = hf.generate(torch.tensor(pids), max_new_tokens=8,
                           do_sample=False).numpy()
    gcfg = dataclasses.replace(cfg, dtype=jnp.float32,
                               attention_impl="reference")
    np.testing.assert_array_equal(
        np.asarray(generate(gcfg, params, jnp.asarray(pids), 8)), gref)


def test_hf_qwen3_parity_qk_norm_and_head_dim():
    """Qwen3 (policy 15): per-head q/k RMSNorm before rotary + decoupled
    head_dim (16 vs hidden/heads = 8) + layer_types sliding windows.
    Logits parity and token-exact greedy decode vs HF. q/k norm scales are
    forced away from 1.0 first (ones-init would pass even if dropped)."""
    import dataclasses
    from deepspeed_tpu.models.generation import generate
    torch.manual_seed(12)
    hf = transformers.Qwen3ForCausalLM(transformers.Qwen3Config(
        vocab_size=96, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, head_dim=16,
        tie_word_embeddings=False)).eval()
    with torch.no_grad():
        for layer in hf.model.layers:
            layer.self_attn.q_norm.weight.normal_(mean=1.0, std=0.2)
            layer.self_attn.k_norm.weight.normal_(mean=1.0, std=0.2)
    ids = np.random.default_rng(12).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.qk_norm and cfg.head_dim == 16
    assert params["blocks"]["q_norm"]["scale"].shape == (2, 16)
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)
    # token-exact greedy through the KV-cache decode path
    pids = np.random.default_rng(13).integers(0, 96, (2, 10))
    with torch.no_grad():
        gref = hf.generate(torch.tensor(pids), max_new_tokens=8,
                           do_sample=False).numpy()
    gcfg = dataclasses.replace(cfg, dtype=jnp.float32,
                               attention_impl="reference")
    np.testing.assert_array_equal(
        np.asarray(generate(gcfg, params, jnp.asarray(pids), 8)), gref)
    # layer_types -> per-layer windows (sliding engages only where typed)
    torch.manual_seed(13)
    hfw = transformers.Qwen3ForCausalLM(transformers.Qwen3Config(
        vocab_size=96, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, head_dim=16, use_sliding_window=True,
        sliding_window=8, max_window_layers=1,
        layer_types=["full_attention", "sliding_attention"])).eval()
    idsw = np.random.default_rng(14).integers(0, 96, (2, 24))
    with torch.no_grad():
        refw = hfw(torch.tensor(idsw)).logits.numpy()
    paramsw, cfgw = load_hf(hfw)
    assert cfgw.layer_windows == (0, 8)
    modelw = Transformer(dataclasses.replace(cfgw, dtype=jnp.float32,
                                             attention_impl="reference"))
    oursw = np.asarray(modelw.apply({"params": paramsw},
                                    {"input_ids": jnp.asarray(idsw)}))
    np.testing.assert_allclose(oursw, refw, rtol=4e-3, atol=4e-3)


def test_hf_mixtral_parity_and_greedy():
    """Mixtral (policy 16): Mistral attention + SwiGLU EXPERTS behind a
    top-2 router (HF block_sparse_moe gate/w1/w3/w2 -> moe.experts
    gate/fc/proj). Logits parity and token-exact greedy decode vs HF —
    the capacity factor E/k makes the GShard queues drop-free, so the
    routing matches HF's capacity-less top-2 exactly at eval."""
    import dataclasses
    from deepspeed_tpu.models.generation import generate
    torch.manual_seed(21)
    hf = transformers.MixtralForCausalLM(transformers.MixtralConfig(
        vocab_size=96, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64)).eval()
    ids = np.random.default_rng(21).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.moe_experts == 4 and cfg.moe_k == 2 and cfg.gated_mlp
    assert cfg.moe_capacity_factor == 2.0          # E/k -> drop-free
    # [L, E, H, I] expert-stacked SwiGLU kernels
    assert params["blocks"]["moe"]["experts"]["gate"]["kernel"].shape == \
        (2, 4, 32, 56)
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours, aux = model.apply({"params": params},
                            {"input_ids": jnp.asarray(ids)})
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=4e-3, atol=4e-3)
    assert np.isfinite(float(aux))
    # token-exact greedy through the KV-cache decode path (_moe_mlp)
    pids = np.random.default_rng(22).integers(0, 96, (2, 10))
    with torch.no_grad():
        gref = hf.generate(torch.tensor(pids), max_new_tokens=8,
                           do_sample=False).numpy()
    gcfg = dataclasses.replace(cfg, dtype=jnp.float32,
                               attention_impl="reference")
    np.testing.assert_array_equal(
        np.asarray(generate(gcfg, params, jnp.asarray(pids), 8)), gref)


def test_hf_gemma_parity_and_greedy():
    """Gemma (policy 17): (1+w) RMSNorm scales folded at load, sqrt(H)
    embedding scaling in the compute dtype, tanh-GELU gated MLP, decoupled
    head_dim, tied embeddings. Norm scales are forced away from 0 first
    (fresh HF zero-inits w, making 1+w == 1 — a loader that dropped the
    +1 fold would still pass random-init parity). Logits parity and
    token-exact greedy decode vs HF."""
    import dataclasses
    from deepspeed_tpu.models.generation import generate
    torch.manual_seed(31)
    hf = transformers.GemmaForCausalLM(transformers.GemmaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64)).eval()
    with torch.no_grad():
        for layer in hf.model.layers:
            layer.input_layernorm.weight.normal_(std=0.3)
            layer.post_attention_layernorm.weight.normal_(std=0.3)
        hf.model.norm.weight.normal_(std=0.3)
    ids = np.random.default_rng(31).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.embed_scale == float(32) ** 0.5
    assert cfg.activation == "gelu" and cfg.head_dim == 16
    assert cfg.tie_embeddings
    # the +1 fold really happened (HF stores w ~ N(0, 0.3); ours = 1 + w)
    assert abs(float(np.mean(params["ln_f"]["scale"])) - 1.0) < 0.5
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)
    pids = np.random.default_rng(32).integers(0, 96, (2, 10))
    with torch.no_grad():
        gref = hf.generate(torch.tensor(pids), max_new_tokens=8,
                           do_sample=False).numpy()
    gcfg = dataclasses.replace(cfg, dtype=jnp.float32,
                               attention_impl="reference")
    np.testing.assert_array_equal(
        np.asarray(generate(gcfg, params, jnp.asarray(pids), 8)), gref)


def test_hf_phi_parity_and_greedy():
    """Phi (policy 18): parallel residual with a single shared LayerNorm,
    partial rotate_half rotary (0.5 * head_dim), biased projections and
    biased untied lm_head. Logits parity and token-exact greedy decode vs
    HF; qk_layernorm configs are refused loudly."""
    import dataclasses
    from deepspeed_tpu.models.generation import generate
    torch.manual_seed(41)
    hf = transformers.PhiForCausalLM(transformers.PhiConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64)).eval()
    ids = np.random.default_rng(41).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.parallel_residual and not cfg.parallel_residual_dual_ln
    assert cfg.rotary_dim == 4 and not cfg.rotary_interleaved
    assert cfg.lm_head_bias and not cfg.tie_embeddings
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)
    pids = np.random.default_rng(42).integers(0, 96, (2, 10))
    with torch.no_grad():
        gref = hf.generate(torch.tensor(pids), max_new_tokens=8,
                           do_sample=False).numpy()
    gcfg = dataclasses.replace(cfg, dtype=jnp.float32,
                               attention_impl="reference")
    np.testing.assert_array_equal(
        np.asarray(generate(gcfg, params, jnp.asarray(pids), 8)), gref)
    with pytest.raises(NotImplementedError, match="qk_layernorm"):
        torch.manual_seed(42)
        load_hf(transformers.PhiForCausalLM(transformers.PhiConfig(
            vocab_size=96, hidden_size=32, intermediate_size=64,
            num_hidden_layers=1, num_attention_heads=4,
            qk_layernorm=True)))


def test_hf_gpt_bigcode_mqa_parity_and_greedy():
    """GPT-BigCode / StarCoder (policy 19): multi-query attention — the
    fused c_attn [H + 2*head_dim, H] maps onto our GQA qkv kernel at
    num_kv_heads=1. Logits parity and token-exact greedy decode vs HF;
    the MHA (multi_query=False) layout is refused loudly."""
    import dataclasses
    from deepspeed_tpu.models.generation import generate
    torch.manual_seed(51)
    hf = transformers.GPTBigCodeForCausalLM(transformers.GPTBigCodeConfig(
        vocab_size=96, n_embd=32, n_head=4, n_layer=2, n_positions=64,
        n_inner=64)).eval()
    ids = np.random.default_rng(51).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.num_kv_heads == 1 and cfg.head_dim == 8
    # [L, H, (nh + 2) * hd] = [2, 32, 48]
    assert params["blocks"]["attn_qkv"]["kernel"].shape == (2, 32, 48)
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)
    pids = np.random.default_rng(52).integers(0, 96, (2, 10))
    with torch.no_grad():
        gref = hf.generate(torch.tensor(pids), max_new_tokens=8,
                           do_sample=False).numpy()
    gcfg = dataclasses.replace(cfg, dtype=jnp.float32,
                               attention_impl="reference")
    np.testing.assert_array_equal(
        np.asarray(generate(gcfg, params, jnp.asarray(pids), 8)), gref)
    with pytest.raises(NotImplementedError, match="multi_query"):
        torch.manual_seed(52)
        load_hf(transformers.GPTBigCodeForCausalLM(
            transformers.GPTBigCodeConfig(
                vocab_size=96, n_embd=32, n_head=4, n_layer=1,
                n_positions=64, multi_query=False)))


# tier-2 (round-19 budget sweep, ~7s): the cheaper tier-1 cousins are
# test_hf_gpt_neox_parity (parallel residual), test_hf_llama_parity
# (GQA de-interleave) and test_hf_gpt_bigcode_mqa_parity_and_greedy
# (fused qkv + token-exact greedy); scripts/tier2.sh runs this
# two-variant falcon leg
@pytest.mark.slow
def test_hf_falcon_parity_and_greedy():
    """Falcon (policy 20), both supported variants. 7B-style: shared-LN
    parallel residual + MQA. 40B-style: dual-LN parallel residual + GQA
    with the per-kv-group interleaved fused qkv de-interleaved at load.
    Logits parity and token-exact greedy decode vs HF each; legacy
    alibi/sequential falcon-rw configs are refused loudly."""
    import dataclasses
    from deepspeed_tpu.models.generation import generate

    def check(hfcfg, seed, kv_expect):
        torch.manual_seed(seed)
        hf = transformers.FalconForCausalLM(hfcfg).eval()
        ids = np.random.default_rng(seed).integers(0, 96, (2, 20))
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.numpy()
        params, cfg = load_hf(hf)
        assert cfg.kv_heads == kv_expect and cfg.parallel_residual
        model = Transformer(dataclasses.replace(
            cfg, dtype=jnp.float32, attention_impl="reference"))
        ours = np.asarray(model.apply({"params": params},
                                      {"input_ids": jnp.asarray(ids)}))
        np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)
        pids = np.random.default_rng(seed + 1).integers(0, 96, (2, 10))
        with torch.no_grad():
            gref = hf.generate(torch.tensor(pids), max_new_tokens=8,
                               do_sample=False).numpy()
        gcfg = dataclasses.replace(cfg, dtype=jnp.float32,
                                   attention_impl="reference")
        np.testing.assert_array_equal(
            np.asarray(generate(gcfg, params, jnp.asarray(pids), 8)), gref)
        return cfg

    cfg7 = check(transformers.FalconConfig(
        vocab_size=96, hidden_size=32, num_attention_heads=4,
        num_hidden_layers=2, new_decoder_architecture=False,
        multi_query=True, parallel_attn=True, bias=False), 61, 1)
    assert not cfg7.parallel_residual_dual_ln
    cfg40 = check(transformers.FalconConfig(
        vocab_size=96, hidden_size=32, num_attention_heads=4,
        num_hidden_layers=2, new_decoder_architecture=True,
        num_kv_heads=2), 63, 2)
    assert cfg40.parallel_residual_dual_ln
    # Falcon2-11B style: new_decoder_architecture with ONE shared LN
    # (num_ln_in_parallel_attn=1) — detected from the state dict
    cfg11 = check(transformers.FalconConfig(
        vocab_size=96, hidden_size=32, num_attention_heads=4,
        num_hidden_layers=2, new_decoder_architecture=True,
        num_kv_heads=2, num_ln_in_parallel_attn=1,
        parallel_attn=True), 67, 2)
    assert not cfg11.parallel_residual_dual_ln

    with pytest.raises(NotImplementedError, match="alibi"):
        torch.manual_seed(65)
        load_hf(transformers.FalconForCausalLM(transformers.FalconConfig(
            vocab_size=96, hidden_size=32, num_attention_heads=4,
            num_hidden_layers=1, new_decoder_architecture=False,
            multi_query=False, parallel_attn=False, alibi=True)))
    with pytest.raises(NotImplementedError, match="bias"):
        torch.manual_seed(66)
        load_hf(transformers.FalconForCausalLM(transformers.FalconConfig(
            vocab_size=96, hidden_size=32, num_attention_heads=4,
            num_hidden_layers=1, new_decoder_architecture=False,
            multi_query=True, parallel_attn=True, bias=True)))


def test_hf_gemma2_parity_and_greedy():
    """Gemma-2 (policy 21): sandwich norms (post-attn/post-MLP branch norms
    + pre-MLP norm in the ln2 slot), tanh softcapping on attention scores
    and final logits, query_pre_attn_scalar scaling, alternating
    sliding/full layers. The attention cap is small (5.0) so its tanh
    saturation bites hard; the final cap keeps Gemma-2's real 30.0 — still
    a >1% logit shift if dropped, without compressing argmax margins to
    the ulp level that flips greedy tokens spuriously.
    Logits parity and token-exact greedy decode vs HF."""
    import dataclasses
    from deepspeed_tpu.models.generation import generate
    torch.manual_seed(71)
    hf = transformers.Gemma2ForCausalLM(transformers.Gemma2Config(
        vocab_size=96, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, query_pre_attn_scalar=32,
        attn_logit_softcapping=5.0, final_logit_softcapping=30.0,
        sliding_window=8,
        layer_types=["sliding_attention", "full_attention"])).eval()
    with torch.no_grad():
        for layer in hf.model.layers:
            for norm in (layer.input_layernorm,
                         layer.post_attention_layernorm,
                         layer.pre_feedforward_layernorm,
                         layer.post_feedforward_layernorm):
                norm.weight.normal_(std=0.3)
        hf.model.norm.weight.normal_(std=0.3)
    ids = np.random.default_rng(71).integers(0, 96, (2, 24))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.post_block_norms and cfg.attn_softcap == 5.0
    assert cfg.final_logit_softcap == 30.0
    assert cfg.attn_scale == float(32) ** -0.5
    assert cfg.layer_windows == (8, 0)
    assert "post_attn_norm" in params["blocks"]
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)
    # Token-exact greedy needs a window the generation never overflows:
    # once HF's rolling HybridCache drops positions, HF generate DIVERGES
    # FROM HF's OWN full forward (verified: at context 12 > window 8 the
    # full forward's top-1 is not what HF generate emits), while our
    # decode stays consistent with the forward both parity-match above.
    torch.manual_seed(72)
    hfg = transformers.Gemma2ForCausalLM(transformers.Gemma2Config(
        vocab_size=96, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, query_pre_attn_scalar=32,
        attn_logit_softcapping=5.0, final_logit_softcapping=30.0,
        sliding_window=32,
        layer_types=["sliding_attention", "full_attention"])).eval()
    with torch.no_grad():
        for layer in hfg.model.layers:
            layer.input_layernorm.weight.normal_(std=0.3)
            layer.post_feedforward_layernorm.weight.normal_(std=0.3)
    gparams, gcfg = load_hf(hfg)
    pids = np.random.default_rng(72).integers(0, 96, (2, 10))
    with torch.no_grad():
        gref = hfg.generate(torch.tensor(pids), max_new_tokens=8,
                            do_sample=False).numpy()
    gcfg = dataclasses.replace(gcfg, dtype=jnp.float32,
                               attention_impl="reference")
    np.testing.assert_array_equal(
        np.asarray(generate(gcfg, gparams, jnp.asarray(pids), 8)), gref)


def test_hf_llama_mlp_bias_parity():
    """mlp_bias=True: biased gate/up/down projections map and match HF.
    Biases forced NONZERO first (fresh HF zero-inits them — a loader that
    dropped them would still pass random-init parity)."""
    import dataclasses
    hf = _llama_tiny(mlp_bias=True)
    torch.manual_seed(1)
    with torch.no_grad():
        for layer in hf.model.layers:
            for proj in (layer.mlp.gate_proj, layer.mlp.up_proj,
                         layer.mlp.down_proj):
                proj.bias.normal_(std=0.2)
    ids = np.random.default_rng(11).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.mlp_bias is True
    for name in ("mlp_gate", "mlp_fc", "mlp_proj"):
        assert "bias" in params["blocks"][name], name
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)


def test_hf_gptneox_nonstandard_rotary_base_parity():
    """NeoX checkpoints with rotary_emb_base != 10000 load with the right
    angles now that apply_rotary takes theta (the old guard refused them)."""
    import dataclasses
    hf = transformers.GPTNeoXForCausalLM(transformers.GPTNeoXConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, rotary_emb_base=50000,
        rotary_pct=0.5)).eval()
    ids = np.random.default_rng(4).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert cfg.rope_theta == 50000.0
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)


def test_llama_untied_without_head_rejected_and_gated_moe_params():
    """Fail-loud guard: a bare decoder state dict (no lm_head.weight,
    untied) must not fabricate a tied head. gated_mlp + MoE (the Mixtral
    family, supported since round 5) must count the 3-matmul experts in
    the FLOPs model."""
    hf = _llama_tiny(num_hidden_layers=1)
    sd = {k: v for k, v in hf.state_dict().items() if k != "lm_head.weight"}
    with pytest.raises(KeyError, match="lm_head.weight"):
        load_hf(sd, arch="llama", config=hf.config)

    from deepspeed_tpu.models.transformer import get_config
    gated = get_config("gpt2-tiny", gated_mlp=True, moe_experts=4)
    plain = get_config("gpt2-tiny", gated_mlp=False, moe_experts=4)
    per_layer_mlp = 4 * gated.mlp_dim * gated.hidden_size
    assert gated.num_params() - plain.num_params() == \
        gated.num_layers * per_layer_mlp


def test_hf_qwen2_parity_nonzero_biases():
    """Qwen2 (policy 14): Llama family with q/k/v biases but NO o bias —
    mapping is presence-driven from the state dict. Biases are forced
    NONZERO first: a fresh HF model zero-inits them, so a loader that
    dropped them would still pass random-init parity (the trap this test
    exists to close)."""
    import dataclasses
    hf = transformers.Qwen2ForCausalLM(transformers.Qwen2Config(
        vocab_size=96, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=True)).eval()
    torch.manual_seed(0)            # unseeded normal_ made this flaky
    with torch.no_grad():
        for layer in hf.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(std=0.2)
    ids = np.random.default_rng(5).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, cfg = load_hf(hf)
    assert "bias" in params["blocks"]["attn_qkv"]
    assert "bias" not in params["blocks"]["attn_proj"]
    assert cfg.tie_embeddings
    model = Transformer(dataclasses.replace(cfg, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)


def test_hf_qwen2_sliding_window_gating():
    """Qwen2's window only engages when use_sliding_window=True, and the
    first max_window_layers stay on full attention."""
    mk = lambda **kw: transformers.Qwen2ForCausalLM(transformers.Qwen2Config(
        vocab_size=96, hidden_size=32, intermediate_size=56,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, **kw)).eval()
    _, cfg_off = load_hf(mk(sliding_window=8, use_sliding_window=False))
    assert cfg_off.layer_windows is None
    hf = mk(sliding_window=8, use_sliding_window=True, max_window_layers=1)
    _, cfg_on = load_hf(hf)
    assert cfg_on.layer_windows == (0, 8, 8)
    # and parity holds with the window binding (seq 20 > window 8)
    import dataclasses
    ids = np.random.default_rng(6).integers(0, 96, (2, 20))
    with torch.no_grad():
        ref = hf(torch.tensor(ids)).logits.numpy()
    params, _ = load_hf(hf)
    model = Transformer(dataclasses.replace(cfg_on, dtype=jnp.float32,
                                            attention_impl="reference"))
    ours = np.asarray(model.apply({"params": params},
                                  {"input_ids": jnp.asarray(ids)}))
    np.testing.assert_allclose(ours, ref, rtol=4e-3, atol=4e-3)
