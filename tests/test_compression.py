"""Compression training tests: config parsing, QAT schedule gating, pruning
masks, layer reduction, int8 export, and end-to-end engine QAT training.

Mirrors the reference's tests/unit/test_compression.py coverage of
init_compression + LinearLayer_Compress behaviors.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.compression import (apply_compression,
                                       apply_layer_reduction, export_int8,
                                       init_compression,
                                       parse_compression_config)

from util import SimpleModel, random_batch


def _wq_config(bits=8, offset=0, modules=(".*kernel.*",), period=0,
               start_bits=None):
    return {"weight_quantization": {
        "shared_parameters": {"enabled": True, "schedule_offset": offset,
                              "quantization_type": "symmetric"},
        "different_groups": {"wq1": {
            "params": {"start_bits": start_bits or bits, "target_bits": bits,
                       "quantization_period": period},
            "modules": list(modules)}}}}


def test_parse_config_groups():
    spec = parse_compression_config({
        **_wq_config(8),
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {"sp1": {"params": {"dense_ratio": 0.5},
                                         "modules": ["Dense_0"]}}},
    })
    assert spec.enabled
    kinds = sorted(g.kind for g in spec.groups)
    assert kinds == ["sparse_pruning", "weight_quantization"]
    sp = [g for g in spec.groups if g.kind == "sparse_pruning"][0]
    assert sp.dense_ratio == 0.5 and sp.schedule_offset == 5


def test_schedule_offset_gates_quantization():
    spec = init_compression({"compression_training": _wq_config(4, offset=10)})
    w = {"layer": {"kernel": jnp.asarray(
        np.random.RandomState(0).randn(16, 16), jnp.float32)}}
    before = apply_compression(w, spec, jnp.asarray(5))
    after = apply_compression(w, spec, jnp.asarray(10))
    np.testing.assert_array_equal(np.asarray(before["layer"]["kernel"]),
                                  np.asarray(w["layer"]["kernel"]))
    assert not np.allclose(np.asarray(after["layer"]["kernel"]),
                           np.asarray(w["layer"]["kernel"]))
    # 4-bit: at most 15 distinct levels per group
    assert len(np.unique(np.asarray(after["layer"]["kernel"]))) <= 15


def test_bit_schedule_halves_to_target():
    """start 16 -> target 4 halving every 10 steps (reference bit schedule)."""
    spec = init_compression({"compression_training": _wq_config(
        4, offset=0, period=10, start_bits=16)})
    w = {"k": {"kernel": jnp.asarray(
        np.random.RandomState(1).randn(64, 8), jnp.float32)}}

    def levels(step):
        out = apply_compression(w, spec, jnp.asarray(step, jnp.float32))
        return len(np.unique(np.asarray(out["k"]["kernel"])))

    assert levels(0) > levels(10) > levels(20)      # 16b -> 8b -> 4b
    assert levels(20) <= 15 and levels(100) <= 15   # floor at 4 bits


def test_ste_gradients_flow():
    spec = init_compression({"compression_training": _wq_config(8)})
    w = {"m": {"kernel": jnp.ones((8, 8))}}

    def loss(params):
        c = apply_compression(params, spec, jnp.asarray(1))
        return jnp.sum(c["m"]["kernel"] ** 2)

    g = jax.grad(loss)(w)
    assert np.all(np.isfinite(np.asarray(g["m"]["kernel"])))
    assert np.abs(np.asarray(g["m"]["kernel"])).sum() > 0


def test_sparse_and_row_pruning_masks():
    cfgd = {
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {"sp": {"params": {"dense_ratio": 0.25},
                                        "modules": ["sparse/kernel"]}}},
        "row_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 0},
            "different_groups": {"rp": {"params": {"dense_ratio": 0.5},
                                        "modules": ["rows/kernel"]}}},
    }
    spec = init_compression({"compression_training": cfgd})
    rng = np.random.RandomState(2)
    params = {"sparse": {"kernel": jnp.asarray(rng.randn(32, 32), jnp.float32)},
              "rows": {"kernel": jnp.asarray(rng.randn(16, 8), jnp.float32)}}
    out = apply_compression(params, spec, jnp.asarray(1))
    sp = np.asarray(out["sparse"]["kernel"])
    assert abs((sp == 0).mean() - 0.75) < 0.02
    rp = np.asarray(out["rows"]["kernel"])
    zero_rows = (np.abs(rp).sum(axis=1) == 0).sum()
    assert zero_rows == 8


def test_head_pruning_zeroes_head_blocks():
    cfgd = {"head_pruning": {
        "shared_parameters": {"enabled": True, "schedule_offset": 0},
        "different_groups": {"hp": {
            "params": {"dense_ratio": 0.5, "num_heads": 4},
            "modules": ["attn_proj/kernel"]}}}}
    spec = init_compression({"compression_training": cfgd})
    w = {"attn_proj": {"kernel": jnp.asarray(
        np.random.RandomState(3).randn(16, 8), jnp.float32)}}
    out = np.asarray(apply_compression(w, spec, jnp.asarray(1))
                     ["attn_proj"]["kernel"])
    per = 4  # 16 rows / 4 heads
    head_zero = [np.abs(out[h * per:(h + 1) * per]).sum() == 0
                 for h in range(4)]
    assert sum(head_zero) == 2


@pytest.mark.slow
def test_layer_reduction_student_init():
    from deepspeed_tpu.models import build_model
    model, cfg = build_model("gpt2-tiny", num_layers=4, dtype=jnp.float32,
                             attention_impl="reference")
    ids = np.random.default_rng(4).integers(0, cfg.vocab_size, (2, 8))
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.asarray(ids)})["params"]
    student = apply_layer_reduction(params, [0, 3])
    assert student["blocks"]["attn_qkv"]["kernel"].shape[0] == 2
    s_model, _ = build_model("gpt2-tiny", num_layers=2, dtype=jnp.float32,
                             attention_impl="reference")
    logits = s_model.apply({"params": student},
                           {"input_ids": jnp.asarray(ids)})
    assert np.all(np.isfinite(np.asarray(logits)))


def test_int8_export_roundtrip():
    spec = init_compression({"compression_training": _wq_config(8)})
    w = {"m": {"kernel": jnp.asarray(
        np.random.RandomState(5).randn(32, 32), jnp.float32)}}
    exported = export_int8(w, spec)
    assert exported["m/kernel.int8"].dtype == np.int8
    deq = exported["m/kernel.int8"].astype(np.float32) * \
        exported["m/kernel.scale"]
    err = np.abs(deq - np.asarray(w["m"]["kernel"])).max()
    assert err < 0.05


def test_engine_qat_training_tracks_fp():
    """End to end: QAT through the engine config; loss decreases and stays
    near the fp run (reference 'Done' criterion)."""
    base = {"train_batch_size": 16,
            "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
            "seed": 3}
    qat = dict(base, compression_training=_wq_config(
        8, offset=3, modules=["kernel"]))
    e_fp, *_ = ds.initialize(model=SimpleModel(), config=base,
                             example_batch=random_batch(16))
    e_q, *_ = ds.initialize(model=SimpleModel(), config=qat,
                            example_batch=random_batch(16))
    assert e_q.compression_spec is not None
    fp, q = [], []
    for i in range(15):
        b = random_batch(16, seed=i)
        fp.append(float(e_fp.train_batch(b)["loss"]))
        q.append(float(e_q.train_batch(b)["loss"]))
    assert q[-1] < q[0]
    assert abs(np.mean(q[-3:]) - np.mean(fp[-3:])) < 0.25, (fp[-3:], q[-3:])
