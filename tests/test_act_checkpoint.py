"""Activation checkpointing for arbitrary user models.

Mirrors the reference's tests/unit/runtime/activation_checkpointing/
test_activation_checkpointing.py (checkpoint() == non-checkpointed outputs
and grads) — plus the engine-level path: enabling the config section for a
plain user flax module changes the compiled program (recompute appears) and
keeps training math identical.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime import act_checkpoint
from tests.util import SimpleModel, random_batch, batch_stream


@pytest.fixture(autouse=True)
def _reset_state():
    yield
    act_checkpoint.reset()


# ---------------------------------------------------------------- module API

def _segment(w, x):
    return jnp.tanh(x @ w) * jnp.cos(x @ w)


def test_checkpoint_matches_plain_grads():
    """deepspeed.checkpointing.checkpoint(fn, *args) == fn(*args), grads too
    (reference: test_activation_checkpointing.py _test_activation_checkpoint)."""
    w = np.random.RandomState(0).randn(16, 16).astype(np.float32)
    x = np.random.RandomState(1).randn(8, 16).astype(np.float32)

    def loss_plain(w):
        return jnp.sum(_segment(w, x))

    def loss_ckpt(w):
        return jnp.sum(deepspeed_tpu.checkpointing.checkpoint(
            lambda w_: _segment(w_, x), w))

    np.testing.assert_allclose(loss_plain(w), loss_ckpt(w), rtol=1e-4)
    np.testing.assert_allclose(jax.grad(loss_plain)(w), jax.grad(loss_ckpt)(w),
                               rtol=1e-4, atol=1e-5)


def test_configure_reset_cycle():
    assert not act_checkpoint.is_configured()
    deepspeed_tpu.checkpointing.configure(
        deepspeed_config={"train_batch_size": 8,
                          "activation_checkpointing": {
                              "partition_activations": True,
                              "number_checkpoints": 4}})
    assert act_checkpoint.is_configured()
    act_checkpoint.reset()
    assert not act_checkpoint.is_configured()


def test_policy_names():
    assert act_checkpoint.make_remat_policy("none") is \
        jax.checkpoint_policies.everything_saveable
    assert act_checkpoint.make_remat_policy("full") is \
        jax.checkpoint_policies.nothing_saveable
    with pytest.raises(ValueError):
        act_checkpoint.make_remat_policy("bogus")


def test_remat_shrinks_saved_residuals():
    """The bytes a vjp closure must hold between forward and backward drop
    under checkpointing: plain saves every intermediate, 'dots' saves only
    matmul outputs, 'full' saves only what the inputs already provide."""

    def stack(params, x):
        for w in params:
            x = jnp.tanh(x @ w)
        return jnp.sum(x * x)

    ps = [np.random.RandomState(i).randn(32, 32).astype(np.float32) * 0.1
          for i in range(6)]
    x = np.random.RandomState(99).randn(16, 32).astype(np.float32)

    def residual_bytes(fn):
        _, vjp = jax.vjp(fn, ps, x)
        return sum(int(np.prod(v.shape)) * v.dtype.itemsize
                   for v in jax.tree.leaves(vjp)
                   if hasattr(v, "shape") and hasattr(v, "dtype"))

    plain = residual_bytes(stack)
    dots = residual_bytes(act_checkpoint.remat(stack, policy_name="dots"))
    full = residual_bytes(act_checkpoint.remat(stack, policy_name="full"))
    assert dots < plain, (dots, plain)
    assert full < dots, (full, dots)

    # and the math is unchanged
    g0 = jax.grad(stack)(ps, x)
    g1 = jax.grad(act_checkpoint.remat(stack, policy_name="dots"))(ps, x)
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------- engine path

def _make_engine(act_section=None, seed_model=None):
    cfg = {
        "train_batch_size": 32,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "bf16": {"enabled": True},
    }
    if act_section:
        cfg["activation_checkpointing"] = act_section
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=seed_model or SimpleModel(), config=cfg,
        example_batch=random_batch(4))
    return engine


def test_engine_section_drives_remat_for_user_model():
    """A plain user flax module + the activation_checkpointing config section:
    the section is behavior (the apply_fn is remat-wrapped), and training math
    matches the non-checkpointed engine step for step."""
    base = _make_engine()
    ckpt = _make_engine(act_section={"partition_activations": True})

    stream_a = batch_stream(32)
    stream_b = batch_stream(32)
    for _ in range(5):
        la = base.train_batch(next(stream_a))["loss"]
        lb = ckpt.train_batch(next(stream_b))["loss"]
        np.testing.assert_allclose(float(la), float(lb), rtol=5e-3)
    assert act_checkpoint.is_configured()


def test_engine_cpu_checkpointing_falls_back_on_cpu_backend():
    """cpu_checkpointing maps to the host-offload policy on TPU; on the CPU
    test backend it falls back to selective recompute — and still trains."""
    engine = _make_engine(act_section={"partition_activations": True,
                                       "cpu_checkpointing": True})
    losses = []
    stream = batch_stream(32)
    for _ in range(30):
        losses.append(float(engine.train_batch(next(stream))["loss"]))
    assert losses[-1] < losses[0] * 0.85
