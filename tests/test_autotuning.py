"""Autotuning tests: tuner enumeration, experiment ranking with failures,
in-process engine runner on the CPU mesh, and the script-mode metric hook.

Mirrors the reference's tests/unit/autotuning coverage of tuning-space
generation + the scheduler's result handling.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deepspeed_tpu.autotuning import (Autotuner, GridSearchTuner, RandomTuner,
                                      engine_runner)
from deepspeed_tpu.autotuning.autotuner import default_tuning_space

from util import SimpleModel, random_batch


def test_grid_tuner_enumerates_product():
    space = {"a": [1, 2], "b.c": [10, 20, 30]}
    combos = list(GridSearchTuner(space))
    assert len(combos) == 6
    assert {"a": 1, "b.c": 30} in combos


def test_random_tuner_caps_trials():
    space = {"a": list(range(10)), "b": list(range(10))}
    assert len(list(RandomTuner(space, num_trials=7))) == 7


def test_autotuner_ranks_and_records_failures(tmp_path):
    calls = []

    def runner(cfg):
        mb = cfg["train_micro_batch_size_per_gpu"]
        calls.append(mb)
        if mb == 4:
            raise MemoryError("simulated OOM")
        return {"throughput": float(mb * 100)}

    base = {"train_batch_size": 64, "train_micro_batch_size_per_gpu": 1}
    tuner = Autotuner(base, runner,
                      tuning_space={"train_micro_batch_size_per_gpu": [1, 2, 4]},
                      results_dir=str(tmp_path))
    exps = tuner.tune()
    assert [e.name for e in exps][0].endswith("2")       # mb=2 wins
    failed = [e for e in exps if e.error]
    assert len(failed) == 1 and "OOM" in failed[0].error
    results = json.load(open(tmp_path / "autotuning_results.json"))
    assert len(results) == 3
    best = json.load(open(tmp_path / "best_config.json"))
    assert best["train_micro_batch_size_per_gpu"] == 2


# tier-2 (round 10 budget): fattest passing legs demoted per the standing
# guardrail — tier-1 crept past ~80% of the 870s budget once the comm-plan
# legs landed and the jax_compat shard_map wrapper recovered the 1-bit
# family on 0.4.x hosts; cheaper cousins still gate tier-1
@pytest.mark.slow
def test_engine_runner_on_cpu_mesh(tmp_path):
    """End-to-end: grid over micro-batch x ZeRO stage with real engines;
    every experiment must produce a throughput."""
    base = {"train_batch_size": 16,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    space = {"train_micro_batch_size_per_gpu": [1, 2],
             "zero_optimization.stage": [0, 1]}
    runner = engine_runner(lambda: SimpleModel(),
                           lambda i: random_batch(16, seed=i), steps=3,
                           warmup=1)
    tuner = Autotuner(base, runner, tuning_space=space,
                      results_dir=str(tmp_path))
    exps = tuner.tune()
    assert len(exps) == 4
    assert all(e.metrics is not None for e in exps), \
        [(e.name, e.error) for e in exps]
    assert exps[0].score >= exps[-1].score


def test_script_mode_metric_hook(tmp_path):
    """The engine must write its metric file and exit at end_profile_step
    when launched under the autotuner (reference: autotuning exit path)."""
    script = tmp_path / "train.py"
    script.write_text("""
import json, os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
sys.path.insert(0, {tests!r})
import deepspeed_tpu as ds
from util import SimpleModel, random_batch
cfg = json.load(open(sys.argv[sys.argv.index("--deepspeed_config") + 1]))
engine, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                           example_batch=random_batch(8))
for i in range(100):
    engine.train_batch(random_batch(8, seed=i))
raise SystemExit("engine did not exit at end_profile_step")
""".format(repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
           tests=os.path.dirname(os.path.abspath(__file__))))
    cfg_path = tmp_path / "base.json"
    cfg_path.write_text(json.dumps({
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "autotuning": {"enabled": True, "end_profile_step": 4},
    }))
    metric_path = tmp_path / "metrics.json"
    env = dict(os.environ, DS_AUTOTUNING_METRIC_FILE=str(metric_path))
    proc = subprocess.run(
        [sys.executable, str(script), "--deepspeed_config", str(cfg_path)],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    metrics = json.load(open(metric_path))
    assert metrics["throughput"] > 0
    assert metrics["steps"] == 4


def test_model_based_tuner_finds_optimum():
    """The ridge-surrogate tuner (reference: tuner/model_based_tuner.py)
    finds the best config on a synthetic throughput surface while trying
    fewer configs than the full grid."""
    from deepspeed_tpu.autotuning import Autotuner, ModelBasedTuner

    space = {
        "train_micro_batch_size_per_gpu": [1, 2, 4, 8, 16, 32],
        "zero_optimization.stage": [0, 1, 2, 3],
        "activation_checkpointing": [False, True],
    }
    # throughput rises with micro batch, dips at stage 3, remat costs 10%
    def runner(cfg):
        mb = cfg["train_micro_batch_size_per_gpu"]
        stage = cfg["zero_optimization"]["stage"]
        remat = cfg.get("activation_checkpointing", {}).get(
            "partition_activations", False)
        thr = mb * (0.8 if stage == 3 else 1.0) * (0.9 if remat else 1.0)
        return {"throughput": thr}

    tuner = Autotuner({"train_batch_size": 64}, runner, tuning_space=space,
                      tuner_type="model", num_trials=14)
    exps = tuner.tune()
    assert len(exps) == 14 < 6 * 4 * 2            # fewer than the grid
    best = tuner.best()
    assert best.config["train_micro_batch_size_per_gpu"] == 32
    assert best.config["zero_optimization"]["stage"] != 3
    # the model guided later trials toward large micro batches: the best
    # config must have been found despite sampling < 30% of the grid
    assert best.score == 32.0


# -- parallel scheduler (round-3 Missing #5) ----------------------------------


def test_parallel_scheduler_runs_concurrently_with_reservations():
    """Experiments overlap in time (up to n_slots in flight) and no slot is
    ever double-booked — the reference scheduler.py reservation semantics."""
    import threading
    import time

    from deepspeed_tpu.autotuning.autotuner import Experiment
    from deepspeed_tpu.autotuning.scheduler import ParallelScheduler

    active = {"n": 0, "max": 0, "by_slot": set()}
    lock = threading.Lock()

    def runner(config, slot, deadline):
        with lock:
            key = slot["devices"]
            assert key not in active["by_slot"], "slot double-booked"
            active["by_slot"].add(key)
            active["n"] += 1
            active["max"] = max(active["max"], active["n"])
        time.sleep(0.2)
        with lock:
            active["by_slot"].discard(key)
            active["n"] -= 1
        return {"throughput": float(config["x"])}

    sched = ParallelScheduler(runner,
                              [{"devices": "0"}, {"devices": "1"}])
    exps = [Experiment(name=f"e{i}", config={"x": i}) for i in range(6)]
    t0 = time.perf_counter()
    sched.run_wave(exps)
    wall = time.perf_counter() - t0
    assert all(e.metrics is not None for e in exps)
    assert active["max"] == 2, active           # really concurrent
    assert wall < 6 * 0.2                       # faster than sequential
    assert {e.slot["devices"] for e in exps} == {"0", "1"}


def test_parallel_scheduler_kills_losing_configs():
    """Once a config completes, a still-running experiment past
    kill_factor x the best wall time sees its deadline expire (losing
    configs give their slot back instead of running out the clock)."""
    import time

    from deepspeed_tpu.autotuning.autotuner import Experiment
    from deepspeed_tpu.autotuning.scheduler import ParallelScheduler

    def runner(config, slot, deadline):
        if config["kind"] == "fast":
            time.sleep(0.1)
            return {"throughput": 100.0}
        # losing config: poll the deadline like a real runner would
        for _ in range(200):
            time.sleep(0.05)
            rem = deadline()
            if rem is not None and rem <= 0:
                raise RuntimeError("killed: losing config")
        return {"throughput": 1.0}

    sched = ParallelScheduler(runner, [{"devices": "0"}, {"devices": "1"}],
                              kill_factor=2.0, min_kill_time=0.3)
    exps = [Experiment(name="fast", config={"kind": "fast"}),
            Experiment(name="slow", config={"kind": "slow"})]
    t0 = time.perf_counter()
    sched.run_wave(exps)
    wall = time.perf_counter() - t0
    assert exps[0].metrics == {"throughput": 100.0}
    assert exps[1].error is not None and "killed" in exps[1].error
    assert wall < 3.0, wall                     # the slow one did NOT run out


def test_autotuner_parallel_mode_matches_sequential_ranking(tmp_path):
    """Autotuner with resource_slots produces the same best config as the
    sequential path, with experiments actually distributed over slots."""
    import time

    from deepspeed_tpu.autotuning.autotuner import Autotuner

    space = {"train_micro_batch_size_per_gpu": [1, 2, 4, 8]}
    base = {"train_batch_size": 64}

    def runner(config, slot=None, deadline=None):
        time.sleep(0.05)
        return {"throughput": float(config["train_micro_batch_size_per_gpu"])}

    at = Autotuner(base, runner, tuning_space=space,
                   resource_slots=[{"devices": "0"}, {"devices": "1"}],
                   results_dir=str(tmp_path))
    exps = at.tune()
    assert at.best().config["train_micro_batch_size_per_gpu"] == 8
    assert len(exps) == 4
    assert {e.slot["devices"] for e in exps} == {"0", "1"}
    assert (tmp_path / "best_config.json").exists()
