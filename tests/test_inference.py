"""Inference: KV-cache decode parity, generation, HF GPT-2 import parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _precise_matmuls():
    """Parity tolerances assume fp32 math; on real TPUs jnp matmuls default
    to bf16 internally, so pin the precision for these tests."""
    import jax as _jax
    with _jax.default_matmul_precision("highest"):
        yield


from util import require_devices

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model
from deepspeed_tpu.models.generation import (forward_with_cache, generate,
                                             init_cache)


def _model_and_params(seed=0, **kw):
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("vocab_size", 128)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("attention_impl", "reference")
    model, cfg = build_model("gpt2-tiny", **kw)
    batch = {"input_ids": np.zeros((1, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(seed), batch)["params"]
    return model, cfg, params


def test_cache_forward_matches_full_forward():
    """Prefill-through-cache logits == plain forward logits."""
    model, cfg, params = _model_and_params()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 16)))
    full = model.apply({"params": params}, {"input_ids": ids})
    cache = init_cache(cfg, 2, 32, jnp.float32)
    cached, cache = forward_with_cache(cfg, params, ids, cache)
    np.testing.assert_allclose(np.asarray(cached), np.asarray(full),
                               rtol=2e-4, atol=2e-4)
    assert int(cache["pos"]) == 16


# tier-2 (round 8 budget): test_cache_forward_matches_full_forward gates
# the same cache numerics in tier-1; the serving integration test pins the
# decode loop token-exactly
@pytest.mark.slow
def test_incremental_decode_matches_full():
    """Token-by-token decode == full forward on the whole sequence."""
    model, cfg, params = _model_and_params(seed=1)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, 128, (1, 12)))
    full = model.apply({"params": params}, {"input_ids": ids})

    cache = init_cache(cfg, 1, 16, jnp.float32)
    logits, cache = forward_with_cache(cfg, params, ids[:, :4], cache)
    outs = [logits]
    for t in range(4, 12):
        logits, cache = forward_with_cache(cfg, params, ids[:, t:t + 1], cache)
        outs.append(logits)
    stitched = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(stitched), np.asarray(full),
                               rtol=3e-4, atol=3e-4)


def test_generate_greedy_deterministic():
    model, cfg, params = _model_and_params(seed=2)
    prompt = jnp.asarray([[5, 17, 3]])
    out1 = generate(cfg, params, prompt, 10)
    out2 = generate(cfg, params, prompt, 10)
    assert out1.shape == (1, 13)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    np.testing.assert_array_equal(np.asarray(out1[:, :3]), np.asarray(prompt))


@pytest.mark.slow
def test_generate_greedy_matches_naive_loop():
    """Cached greedy decode == argmax over repeated full forwards."""
    model, cfg, params = _model_and_params(seed=3)
    prompt = jnp.asarray([[7, 2, 9, 4]])
    out = generate(cfg, params, prompt, 6)
    ids = prompt
    for _ in range(6):
        logits = model.apply({"params": params}, {"input_ids": ids})
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ids))


def test_generate_sampling_reproducible():
    model, cfg, params = _model_and_params(seed=4)
    prompt = jnp.asarray([[1, 2]])
    r = jax.random.PRNGKey(42)
    a = generate(cfg, params, prompt, 8, 0.8, r, 16)
    b = generate(cfg, params, prompt, 8, 0.8, r, 16)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_inference_engine_end_to_end():
    model, cfg, params = _model_and_params(seed=5)
    eng = ds.init_inference(model=model,
                            config={"dtype": "float32"},
                            model_parameters=params)
    prompt = np.asarray([[3, 1, 4]])
    out = eng.generate(prompt, max_new_tokens=5)
    assert out.shape == (1, 8)
    logits = eng({"input_ids": jnp.asarray(prompt)})
    assert logits.shape == (1, 3, cfg.vocab_size)


# tier-2 (round 8 budget): the fattest HF-parity leg; per-component torch
# mirrors + test_hf_policies config parity keep gating tier-1
@pytest.mark.slow
def test_hf_gpt2_import_parity():
    """HF GPT2LMHeadModel -> our params: logits match torch within tolerance."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    hf_cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=32, n_layer=2, n_head=4)
    hf_model = transformers.GPT2LMHeadModel(hf_cfg).eval()

    from deepspeed_tpu.models.hf import load_hf
    from deepspeed_tpu.models.transformer import Transformer
    params, cfg = load_hf(hf_model)
    model = Transformer(cfg.__class__(**{**cfg.__dict__,
                                         "dtype": jnp.float32,
                                         "attention_impl": "reference"}))
    ids = np.random.default_rng(6).integers(0, 96, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.numpy()
    ours = model.apply({"params": params}, {"input_ids": jnp.asarray(ids)})
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-3, atol=2e-3)


def test_tp2_generate_with_resharded_checkpoint(tmp_path):
    require_devices(2)
    """TP-degree resharding at load (reference: state_dict_factory.py:214):
    a checkpoint written topology-free loads into a tp=2 engine and greedy
    generation matches the tp=1 engine token for token."""
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.runtime.checkpointing import save_tree

    model, cfg = build_model("gpt2-tiny", dtype=jnp.float32,
                             attention_impl="reference")
    ids = np.random.default_rng(11).integers(0, cfg.vocab_size, (2, 8))
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.asarray(ids)})["params"]
    path = str(tmp_path / "model_states.npz")
    save_tree(params, path)

    def make(tp):
        return InferenceEngine(
            model=model, model_parameters=params,
            config={"dtype": "float32",
                    "tensor_parallel": {"tp_size": tp}},
            sharding_rules=cfg.tp_rules())

    e1 = make(1).load_checkpoint(path)
    e2 = make(2).load_checkpoint(path)
    # tp=2 weights really are sharded over the model axis
    qkv = e2.params["blocks"]["attn_qkv"]["kernel"]
    assert not qkv.sharding.is_fully_replicated
    t1 = np.asarray(e1.generate(ids, max_new_tokens=8))
    t2 = np.asarray(e2.generate(ids, max_new_tokens=8))
    np.testing.assert_array_equal(t1, t2)


def test_spatial_attention_inference():
    """Spatial (image-model) attention blocks run through the framework's
    attention path + InferenceEngine (reference: diffusers spatial
    injection). Numerics vs a plain softmax attention over the token grid."""
    from deepspeed_tpu.inference.spatial import (SpatialSelfAttention,
                                                 spatial_attention)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 32)), jnp.float32)

    out = spatial_attention(x, num_heads=4, impl="reference")
    # oracle: dense softmax over the 64-token grid
    t = np.asarray(x).reshape(2, 64, 4, 8).transpose(0, 2, 1, 3)
    s = t @ t.transpose(0, 1, 3, 2) / np.sqrt(8)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = (p @ t).transpose(0, 2, 1, 3).reshape(2, 8, 8, 32)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    # the block hosts in InferenceEngine like any module
    from deepspeed_tpu.inference import InferenceEngine
    block = SpatialSelfAttention(num_heads=4, num_groups=8,
                                 attention_impl="reference")
    params = block.init(jax.random.PRNGKey(0), x)["params"]
    eng = InferenceEngine(model=block, model_parameters=params,
                          config={"dtype": "float32"})
    y = eng.forward(x)
    assert np.asarray(y).shape == (2, 8, 8, 32)
    assert np.all(np.isfinite(np.asarray(y)))


# ----------------------------------------------------------------- int8

def test_int8_quantization_error_bound():
    """Dequantized int8 weights reconstruct within scale/2 elementwise (the
    symmetric per-output-channel bound)."""
    from deepspeed_tpu.inference.engine import quantize_weights_int8
    rng = np.random.default_rng(0)
    params = {"attn": {"kernel": rng.standard_normal((32, 16)).astype(np.float32),
                       "bias": np.zeros(16, np.float32)},
              "gate": {"kernel": rng.standard_normal((32, 4)).astype(np.float32)},
              "ln": {"scale": np.ones(32, np.float32)}}
    q = quantize_weights_int8(params)
    assert q["attn"]["kernel"].dtype == jnp.int8
    deq = np.asarray(q["attn"]["kernel"], np.float32) * np.asarray(q["attn"]["kernel_scale"])
    bound = np.asarray(q["attn"]["kernel_scale"]) / 2 + 1e-7
    assert (np.abs(deq - params["attn"]["kernel"]) <= bound).all()
    # the router and non-kernel leaves are untouched
    assert q["gate"]["kernel"].dtype == np.float32
    assert "kernel_scale" not in q["gate"]
    assert q["ln"]["scale"].dtype == np.float32


# tier-2 (round-17 budget sweep, ~10s): the cheaper tier-1 cousins are
# test_serving.test_int8_weight_only_decode_parity and
# test_serving.test_int8_kv_pool_parity_jnp_and_kernel (the round-17
# blockwise int8 tier, token-exact end to end); tier2.sh runs this leg
@pytest.mark.slow
def test_int8_engine_logits_close_and_generates():
    """dtype:int8 builds a weight-only-quantized engine whose logits track
    the bf16 engine within int8 noise and whose generate() runs end to end
    (round-2 Weak #7: int8 used to silently mean bf16)."""
    model, cfg, params = _model_and_params()
    ids = np.random.default_rng(1).integers(0, 128, (2, 16)).astype(np.int32)

    e_bf = ds.init_inference(model=model, model_parameters=params,
                             config={"dtype": "bf16"})
    e_q = ds.init_inference(model=model, model_parameters=params,
                            config={"dtype": "int8"})
    assert e_q.quantized
    l_bf = np.asarray(e_bf.forward({"input_ids": jnp.asarray(ids)}), np.float32)
    l_q = np.asarray(e_q.forward({"input_ids": jnp.asarray(ids)}), np.float32)
    # int8 weight noise perturbs logits but must keep them close; top-1
    # predictions should overwhelmingly agree
    agree = (l_bf.argmax(-1) == l_q.argmax(-1)).mean()
    assert agree > 0.9, agree
    assert np.abs(l_q - l_bf).mean() < 0.15 * (np.abs(l_bf).mean() + 1.0)

    out = e_q.generate(jnp.asarray(ids), max_new_tokens=4)
    assert out.shape == (2, 20)

    # checkpoint load re-quantizes from full precision
    import tempfile, os
    from deepspeed_tpu.runtime import checkpointing as ckpt_lib
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.npz")
        ckpt_lib.save_tree(params, path)
        e_q.load_checkpoint(path)
        l_q2 = np.asarray(e_q.forward({"input_ids": jnp.asarray(ids)}), np.float32)
        np.testing.assert_allclose(l_q2, l_q, rtol=1e-4, atol=1e-4)


def test_int8_engine_rejects_arbitrary_module():
    import flax.linen as nn

    class Plain(nn.Module):
        @nn.compact
        def __call__(self, batch):
            return nn.Dense(4)(batch["x"])

    with pytest.raises(ValueError, match="int8"):
        ds.init_inference(model=Plain(),
                          model_parameters=Plain().init(
                              jax.random.PRNGKey(0),
                              {"x": np.zeros((1, 8), np.float32)})["params"],
                          config={"dtype": "int8"})


# -- serving depth: top-p, repetition penalty, ragged prefill (round-3 #9) ----


def test_top_p_matches_hf_warper():
    """apply_top_p == transformers' TopPLogitsWarper on the same logits."""
    import torch
    from transformers.generation.logits_process import TopPLogitsWarper
    from deepspeed_tpu.models.generation import apply_top_p
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(4, 64)).astype(np.float32) * 3
    for p in (0.3, 0.7, 0.95):
        ours = np.asarray(apply_top_p(jnp.asarray(logits), p))
        hf = TopPLogitsWarper(top_p=p, filter_value=-1e30)(
            None, torch.tensor(logits)).numpy()
        kept_o = ours > -1e29
        kept_h = hf > -1e29
        np.testing.assert_array_equal(kept_o, kept_h)
        np.testing.assert_allclose(np.where(kept_o, ours, 0),
                                   np.where(kept_h, hf, 0), rtol=1e-6)


def test_repetition_penalty_matches_hf_processor():
    """apply_repetition_penalty == HF RepetitionPenaltyLogitsProcessor."""
    import torch
    from transformers.generation.logits_process import (
        RepetitionPenaltyLogitsProcessor)
    from deepspeed_tpu.models.generation import apply_repetition_penalty
    rng = np.random.default_rng(1)
    V = 64
    logits = rng.normal(size=(2, V)).astype(np.float32) * 2
    prompt = rng.integers(0, V, size=(2, 10))
    seen = np.zeros((2, V), bool)
    for b in range(2):
        seen[b, prompt[b]] = True
    ours = np.asarray(apply_repetition_penalty(
        jnp.asarray(logits), jnp.asarray(seen), 1.3))
    hf = RepetitionPenaltyLogitsProcessor(penalty=1.3)(
        torch.tensor(prompt), torch.tensor(logits)).numpy()
    np.testing.assert_allclose(ours, hf, rtol=1e-6)


# tier-2 (round 8 budget): test_generate_sampling_reproducible is the
# cheaper tier-1 cousin; the top-p/penalty unit math keeps its HF-parity
# pins above (test_top_p_matches_hf_warper / repetition_penalty)
@pytest.mark.slow
def test_generate_with_top_p_and_penalty_reproducible():
    model, cfg, params = _model_and_params(seed=3)
    prompt = jnp.asarray(np.random.default_rng(2).integers(0, 128, (2, 8)))
    r = jax.random.PRNGKey(5)
    a = generate(cfg, params, prompt, 8, 0.9, r, 40, 0.9, 1.2)
    b = generate(cfg, params, prompt, 8, 0.9, r, 40, 0.9, 1.2)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # penalty visibly discourages repeats vs no penalty under greedy
    g_plain = generate(cfg, params, prompt, 12)
    g_pen = generate(cfg, params, prompt, 12, 0.0, None, None, None, 4.0)
    assert not np.array_equal(np.asarray(g_plain), np.asarray(g_pen))


@pytest.mark.slow
def test_ragged_batched_prefill_matches_per_sample():
    """LEFT-padded ragged batch: each sample's greedy continuation equals
    its own unpadded single-sample generation (positions and masks are
    pad-corrected per sample)."""
    for pos_embed in ("learned", "rotary"):
        model, cfg, params = _model_and_params(seed=4, pos_embed=pos_embed)
        rng = np.random.default_rng(3)
        lens = [5, 8, 3, 8]
        T = max(lens)
        prompts = [rng.integers(1, 128, size=(L,)) for L in lens]
        ids = np.zeros((len(lens), T), np.int64)
        mask = np.zeros((len(lens), T), np.int64)
        for i, p in enumerate(prompts):
            ids[i, T - len(p):] = p          # left-padded
            mask[i, T - len(p):] = 1
        out = generate(cfg, params, jnp.asarray(ids), 6,
                       attention_mask=jnp.asarray(mask))
        new = np.asarray(out)[:, T:]
        for i, p in enumerate(prompts):
            solo = generate(cfg, params, jnp.asarray(p)[None], 6)
            np.testing.assert_array_equal(
                new[i], np.asarray(solo)[0, len(p):],
                err_msg=f"sample {i} (len {len(p)}, {pos_embed})")


def test_ragged_generate_matches_hf():
    """End-to-end parity with HF's left-padded batched greedy generate with
    repetition penalty, on a real (randomly initialized) HF architecture
    loaded through the policy mapper."""
    import torch
    import transformers
    from deepspeed_tpu.models.hf import load_hf_gpt2

    torch.manual_seed(0)
    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    params, cfg = load_hf_gpt2(hf)
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": jnp.float32})
    params = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params)

    rng = np.random.default_rng(4)
    lens = [4, 7, 7, 3]
    T = max(lens)
    prompts = [rng.integers(1, 128, size=(L,)) for L in lens]
    ids = np.zeros((len(lens), T), np.int64)
    mask = np.zeros((len(lens), T), np.int64)
    for i, p in enumerate(prompts):
        ids[i, T - len(p):] = p
        mask[i, T - len(p):] = 1

    with torch.no_grad():
        hf_out = hf.generate(
            torch.tensor(ids), attention_mask=torch.tensor(mask),
            max_new_tokens=6, do_sample=False, repetition_penalty=1.3,
            pad_token_id=0)
    ours = generate(cfg, params, jnp.asarray(ids), 6,
                    repetition_penalty=1.3,
                    attention_mask=jnp.asarray(mask))
    np.testing.assert_array_equal(np.asarray(ours)[:, T:],
                                  hf_out.numpy()[:, T:])


# -- diffusers-grade spatial path (round-3 Missing #4) ------------------------


def test_resnet_block_matches_torch_mirror():
    """ResnetBlock == a torch mirror of diffusers' ResnetBlock2D ops
    (GroupNorm/SiLU/Conv3x3 + time-emb injection + shortcut)."""
    import torch
    import torch.nn as tnn
    from deepspeed_tpu.inference.spatial import (ResnetBlock,
                                                 load_torch_conv,
                                                 load_torch_linear)

    torch.manual_seed(0)
    Cin, Cout, G = 8, 16, 4

    class TorchRes(tnn.Module):
        def __init__(self):
            super().__init__()
            self.norm1 = tnn.GroupNorm(G, Cin)
            self.conv1 = tnn.Conv2d(Cin, Cout, 3, padding=1)
            self.time_emb_proj = tnn.Linear(12, Cout)
            self.norm2 = tnn.GroupNorm(G, Cout)
            self.conv2 = tnn.Conv2d(Cout, Cout, 3, padding=1)
            self.shortcut = tnn.Conv2d(Cin, Cout, 1)

        def forward(self, x, temb):
            h = self.conv1(tnn.functional.silu(self.norm1(x)))
            h = h + self.time_emb_proj(
                tnn.functional.silu(temb))[:, :, None, None]
            h = self.conv2(tnn.functional.silu(self.norm2(h)))
            return self.shortcut(x) + h

    tm = TorchRes().eval()
    x = torch.randn(2, Cin, 8, 8)
    temb = torch.randn(2, 12)
    with torch.no_grad():
        ref = tm(x, temb).permute(0, 2, 3, 1).numpy()

    params = {
        "norm1": {"scale": jnp.asarray(tm.norm1.weight.detach().numpy()),
                  "bias": jnp.asarray(tm.norm1.bias.detach().numpy())},
        "conv1": load_torch_conv(tm.conv1.weight.detach(),
                                 tm.conv1.bias.detach()),
        "time_emb_proj": load_torch_linear(
            tm.time_emb_proj.weight.detach(),
            tm.time_emb_proj.bias.detach()),
        "norm2": {"scale": jnp.asarray(tm.norm2.weight.detach().numpy()),
                  "bias": jnp.asarray(tm.norm2.bias.detach().numpy())},
        "conv2": load_torch_conv(tm.conv2.weight.detach(),
                                 tm.conv2.bias.detach()),
        "conv_shortcut": load_torch_conv(tm.shortcut.weight.detach(),
                                         tm.shortcut.bias.detach()),
    }
    blk = ResnetBlock(Cout, num_groups=G)
    ours = blk.apply({"params": params},
                     jnp.asarray(x.permute(0, 2, 3, 1).numpy()),
                     jnp.asarray(temb.numpy()))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-5)


def test_transformer_block_matches_torch_mirror():
    """TransformerBlock (self-attn + cross-attn + geglu FF) == a torch
    mirror of diffusers' BasicTransformerBlock."""
    import torch
    import torch.nn as tnn
    from deepspeed_tpu.inference.spatial import (TransformerBlock,
                                                 load_torch_linear)

    torch.manual_seed(1)
    C, H, Tq, Tc, Cc = 16, 2, 12, 5, 16

    class TorchAttn(tnn.Module):
        def __init__(self, kdim):
            super().__init__()
            self.to_q = tnn.Linear(C, C, bias=False)
            self.to_k = tnn.Linear(kdim, C, bias=False)
            self.to_v = tnn.Linear(kdim, C, bias=False)
            self.to_out = tnn.Linear(C, C)

        def forward(self, x, ctx=None):
            ctx = x if ctx is None else ctx
            B, T, _ = x.shape
            hd = C // H
            sh = lambda t: t.reshape(B, -1, H, hd).transpose(1, 2)
            q, k, v = sh(self.to_q(x)), sh(self.to_k(ctx)), sh(self.to_v(ctx))
            o = tnn.functional.scaled_dot_product_attention(q, k, v)
            return self.to_out(o.transpose(1, 2).reshape(B, T, C))

    class TorchBlock(tnn.Module):
        def __init__(self):
            super().__init__()
            self.norm1, self.norm2, self.norm3 = (tnn.LayerNorm(C)
                                                  for _ in range(3))
            self.attn1 = TorchAttn(C)
            self.attn2 = TorchAttn(Cc)
            self.geglu = tnn.Linear(C, 8 * C)
            self.ff_out = tnn.Linear(4 * C, C)

        def forward(self, x, ctx):
            x = x + self.attn1(self.norm1(x))
            x = x + self.attn2(self.norm2(x), ctx)
            h = self.geglu(self.norm3(x))
            a, g = h.chunk(2, dim=-1)
            return x + self.ff_out(a * tnn.functional.gelu(g))

    tm = TorchBlock().eval()
    x = torch.randn(2, Tq, C)
    ctx = torch.randn(2, Tc, Cc)
    with torch.no_grad():
        ref = tm(x, ctx).numpy()

    def attn_params(ta):
        return {"to_q": load_torch_linear(ta.to_q.weight.detach()),
                "to_k": load_torch_linear(ta.to_k.weight.detach()),
                "to_v": load_torch_linear(ta.to_v.weight.detach()),
                "to_out": load_torch_linear(ta.to_out.weight.detach(),
                                            ta.to_out.bias.detach())}

    ln = lambda m: {"scale": jnp.asarray(m.weight.detach().numpy()),
                    "bias": jnp.asarray(m.bias.detach().numpy())}
    params = {
        "norm1": ln(tm.norm1), "norm2": ln(tm.norm2), "norm3": ln(tm.norm3),
        "attn1": attn_params(tm.attn1), "attn2": attn_params(tm.attn2),
        "ff_geglu": {"proj": load_torch_linear(tm.geglu.weight.detach(),
                                               tm.geglu.bias.detach())},
        "ff_out": load_torch_linear(tm.ff_out.weight.detach(),
                                    tm.ff_out.bias.detach()),
    }
    blk = TransformerBlock(H, attention_impl="reference")
    ours = blk.apply({"params": params}, jnp.asarray(x.numpy()),
                     jnp.asarray(ctx.numpy()))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_unet_serves_through_inference_engine():
    """The assembled conditional UNet hosts in InferenceEngine like any
    module (the reference's generic_injection capability slot) and is
    jit-stable end to end."""
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.inference.spatial import UNet2DCondition
    unet = UNet2DCondition(block_channels=(16, 32), num_heads=2,
                           out_channels=4, attention_impl="reference")
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 16, 16, 4)), jnp.float32)
    t = jnp.asarray([1.0, 17.0])
    ctx = jnp.asarray(np.random.default_rng(1).normal(
        size=(2, 6, 16)), jnp.float32)
    params = unet.init(jax.random.PRNGKey(0), x, t, ctx)["params"]
    eng = InferenceEngine(model=unet, model_parameters=params,
                          config={"dtype": "float32"})
    y1 = eng.forward(x, t, ctx)
    y2 = eng.forward(x, t, ctx)
    assert np.asarray(y1).shape == (2, 16, 16, 4)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    assert np.all(np.isfinite(np.asarray(y1)))


def test_timestep_embedding_matches_torch_mirror():
    import torch
    from deepspeed_tpu.inference.spatial import timestep_embedding
    t = np.asarray([0.0, 1.0, 999.0], np.float32)
    dim = 32
    half = dim // 2
    freqs = torch.exp(-torch.log(torch.tensor(10000.0)) *
                      torch.arange(half) / half)
    ang = torch.tensor(t)[:, None] * freqs[None]
    ref = torch.cat([torch.cos(ang), torch.sin(ang)], dim=-1).numpy()
    ours = np.asarray(timestep_embedding(jnp.asarray(t), dim))
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


# tier-2 (round 8 budget; round-17 re-homed the gating cousins to
# test_serving.test_int8_kv_pool_parity_jnp_and_kernel +
# test_int8_weight_only_decode_parity, which keep the int8 tier tier-1)
@pytest.mark.slow
def test_int8_kv_cache_parity_and_capacity():
    """kv_cache_dtype='int8': greedy generations match the bf16-cache path
    (int8 KV error is far below greedy decision margins on a trained-free
    random model), prefill logits stay close, and the cache's k/v HBM bytes
    halve (+small scale overhead) — 2x context/batch capacity."""
    model, cfg, params = _model_and_params(seed=6)
    ids = jnp.asarray(np.random.default_rng(7).integers(0, 128, (2, 12)))

    # prefill logits tolerance through the quantized cache
    cache16 = init_cache(cfg, 2, 32, jnp.float32)
    cache8 = init_cache(cfg, 2, 32, jnp.int8)
    l16, _ = forward_with_cache(cfg, params, ids, cache16)
    l8, c8 = forward_with_cache(cfg, params, ids, cache8)
    assert c8["k"].dtype == jnp.int8
    np.testing.assert_allclose(np.asarray(l8), np.asarray(l16),
                               rtol=0.1, atol=0.05)

    # greedy decode parity end to end
    g16 = generate(cfg, params, ids, 8)
    g8 = generate(cfg, params, ids, 8, kv_cache_dtype="int8")
    np.testing.assert_array_equal(np.asarray(g16), np.asarray(g8))

    # capacity: int8 k/v bytes = half the f32... compare against the
    # compute-dtype cache the same config would build
    bytes16 = cache16["k"].nbytes + cache16["v"].nbytes
    bytes8 = (cache8["k"].nbytes + cache8["v"].nbytes
              + cache8["k_scale"].nbytes + cache8["v_scale"].nbytes)
    assert bytes8 < 0.32 * bytes16, (bytes8, bytes16)   # f32 ref: ~0.28x


def test_generate_rejects_right_padded_mask():
    """The left-pad guard lives in models.generation.generate itself (the
    shared entry point), not only in the InferenceEngine wrapper — a direct
    caller with an HF-default right-padded mask must fail loudly, not
    silently decode garbage."""
    model, cfg, params = _model_and_params(seed=5)
    rng = np.random.default_rng(6)
    ids = np.zeros((2, 8), np.int64)
    mask = np.zeros((2, 8), np.int64)
    ids[0], mask[0] = rng.integers(1, 128, size=8), 1
    ids[1, :5] = rng.integers(1, 128, size=5)
    mask[1, :5] = 1                              # right-padded (HF default)
    with pytest.raises(ValueError, match="LEFT-padded"):
        generate(cfg, params, jnp.asarray(ids), 4,
                 attention_mask=jnp.asarray(mask))
    # bool masks must hit the same guard (np.diff on bool is XOR — a raw
    # diff check would wave a bool right-padded mask through)
    with pytest.raises(ValueError, match="LEFT-padded"):
        generate(cfg, params, jnp.asarray(ids), 4,
                 attention_mask=jnp.asarray(mask.astype(bool)))
    # an all-ones mask is accepted and equals the maskless call
    ids2 = rng.integers(1, 128, size=(2, 8))
    a = generate(cfg, params, jnp.asarray(ids2), 4,
                 attention_mask=jnp.ones((2, 8), np.int64))
    b = generate(cfg, params, jnp.asarray(ids2), 4)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# tier-2 (round 8 budget): test_tp2_generate_with_resharded_checkpoint
# keeps TP2 generate gating tier-1
@pytest.mark.slow
def test_llama_tp2_generate_matches_tp1():
    """GQA + SwiGLU + RMSNorm under tensor parallelism: a Llama-family
    model's greedy generation on a tp=2 mesh matches tp=1 token for token
    (the GQA qkv concat reshards correctly under the model-axis rules)."""
    require_devices(2)
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models import build_model

    model, cfg = build_model(
        "gpt2-tiny", hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, norm="rmsnorm", gated_mlp=True, activation="silu",
        pos_embed="rotary", rotary_interleaved=False, use_bias=False,
        tie_embeddings=False, mlp_dim_override=96, vocab_size=128,
        max_seq_len=64, dtype=jnp.float32, attention_impl="reference")
    ids = np.random.default_rng(12).integers(0, 128, (2, 8))
    params = model.init(jax.random.PRNGKey(1),
                        {"input_ids": jnp.asarray(ids)})["params"]

    def make(tp):
        return InferenceEngine(
            model=model, model_parameters=params,
            config={"dtype": "float32",
                    "tensor_parallel": {"tp_size": tp}},
            sharding_rules=cfg.tp_rules())

    t1 = np.asarray(make(1).generate(ids, max_new_tokens=8))
    t2 = np.asarray(make(2).generate(ids, max_new_tokens=8))
    np.testing.assert_array_equal(t1, t2)
