"""Round-7 satellite: the 8-chip dryrun wall clock is a tracked metric.

``__graft_entry__._dryrun_multichip_impl`` stamps a ``wall=N.Ns`` suffix
on every leg's ok: line plus one machine-readable summary line; the
driver's MULTICHIP report captures the tail, and the next round's run
compares per-leg timings against the newest usable report — so an
r04-style timeout shows up as a named per-leg regression instead of a
mystery. These tests pin the parse/compare/baseline-discovery halves
(plain python, no jax)."""

import json

import __graft_entry__ as entry


def test_parse_leg_timings_prefers_summary_line():
    text = (
        "dryrun_multichip ok: n=8 mesh(dp=2,sp=2,tp=2) loss=9.1 wall=41.3s\n"
        "dryrun_multichip moe ok: mesh(ep=2,dp=4) loss=8.8 wall=95.0s\n"
        'dryrun_multichip timings: {"spmd": 41.3, "moe": 96.2}\n')
    got = entry.parse_leg_timings(text)
    assert got["spmd"] == 41.3
    assert got["moe"] == 96.2            # summary wins over the suffix


def test_parse_leg_timings_per_leg_fallback_on_truncated_run():
    # the r04 shape: the outer timeout fired BEFORE the summary line —
    # exactly the run where per-leg timing matters most
    text = (
        "dryrun_multichip ok: n=8 mesh(dp=2,sp=2,tp=2) loss=9.1 wall=40.0s\n"
        "dryrun_multichip pipeline ok: mesh(pp=2,dp=2,tp=2) loss=9.0 "
        "wall=120.5s\n")
    got = entry.parse_leg_timings(text)
    assert got == {"spmd": 40.0, "pipeline": 120.5}
    assert entry.parse_leg_timings("no timings here") == {}


def test_check_timing_regression_flags_slow_and_missing_legs():
    baseline = {"spmd": 40.0, "pipeline": 100.0, "moe": 60.0}
    current = {"spmd": 41.0, "pipeline": 250.0}
    problems = entry.check_timing_regression(current, baseline, factor=2.0)
    text = "\n".join(problems)
    assert "pipeline" in text and "2.5x" in text
    assert "moe" in text and "missing" in text
    assert "spmd" not in text            # within budget
    assert entry.check_timing_regression(baseline, baseline) == []


def test_check_timing_regression_tolerates_host_speed_noise():
    # CI hosts vary ~30% run to run: 1.9x is inside the 2x default budget
    baseline = {"spmd": 40.0}
    assert entry.check_timing_regression({"spmd": 76.0}, baseline) == []
    assert entry.check_timing_regression({"spmd": 81.0}, baseline)


def test_timings_carry_device_count_and_baseline_filters_on_it(tmp_path):
    # an n=8 round's baseline must not judge an n=1 run: odd n skips most
    # legs legitimately, and cross-n wall clocks aren't comparable
    (tmp_path / "MULTICHIP_r06.json").write_text(json.dumps(
        {"ok": True, "tail": 'dryrun_multichip timings: '
                             '{"spmd": 40.0, "moe": 90.0, "n": 8}\n'}))
    got = entry.parse_leg_timings(
        'dryrun_multichip timings: {"spmd": 40.0, "n": 8}\n')
    assert got == {"spmd": 40.0, "n": 8.0}
    name, t = entry.latest_multichip_timings(str(tmp_path), n_devices=8)
    assert name == "MULTICHIP_r06.json" and t == {"spmd": 40.0, "moe": 90.0}
    assert entry.latest_multichip_timings(str(tmp_path), n_devices=1) == \
        (None, {})


def test_parse_leg_timings_ignores_unknown_legs():
    # DRYRUN_LEGS is the key universe: stray wall= noise in a captured
    # tail can never invent a leg for the regression check to miss later
    text = ('dryrun_multichip bogus ok: loss=1 wall=5.0s\n'
            'dryrun_multichip moe ok: loss=1 wall=60.0s\n')
    assert entry.parse_leg_timings(text) == {"moe": 60.0}


def test_latest_multichip_timings_skips_failed_and_untimed(tmp_path):
    (tmp_path / "MULTICHIP_r02.json").write_text(json.dumps(
        {"ok": True, "tail": "dryrun_multichip ok: loss=1 wall=33.0s\n"}))
    (tmp_path / "MULTICHIP_r03.json").write_text(json.dumps(
        {"ok": True, "tail": "no timing suffixes in this round"}))
    (tmp_path / "MULTICHIP_r04.json").write_text(json.dumps(
        {"ok": False, "tail": "dryrun_multichip ok: loss=1 wall=99.0s\n"}))
    (tmp_path / "MULTICHIP_r05.json").write_text("{ torn json")
    name, timings = entry.latest_multichip_timings(str(tmp_path))
    assert name == "MULTICHIP_r02.json"  # newest USABLE report
    assert timings == {"spmd": 33.0}
    assert entry.latest_multichip_timings(str(tmp_path / "none")) == (None,
                                                                      {})
