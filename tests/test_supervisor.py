"""Run supervision: fail-fast teardown, connect retries, stall watchdog,
and the agent-over-supervisor exit-code contract (round 4).

The fast tests drive RunSupervisor/StallWatchdog over plain python
workers — no engine, sub-second. The ``slow``-marked subprocess tests
spawn a REAL engine in a child and prove the in-worker halves end to end
(run.hang -> stack dump + stall rc; run.preempt -> emergency save + rc
114); ``scripts/chaos.sh`` runs them standalone.

Exit-code contract under test (docs/RESILIENCE.md): 0 = clean, 114 =
preempted-and-checkpointed (agent resumes, uncounted), 117 = stalled
(agent restarts, counted), anything else = crash (counted).
"""

import io
import os
import subprocess
import sys
import textwrap
import time

import pytest

from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent,
                                                    PREEMPTION_EXIT_CODE)
from deepspeed_tpu.launcher.supervisor import (RankSpec, RunSupervisor,
                                               SSH_CONNECT_RC,
                                               STARTED_SENTINEL)
from deepspeed_tpu.runtime.watchdog import (STALL_EXIT_CODE, StallWatchdog,
                                            init_deadline)
from deepspeed_tpu.testing import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PY = sys.executable


def _spec(code, host="h", remote=False):
    return RankSpec(host, [PY, "-c", code], remote=remote)


# -------------------------------------------------------- fail-fast teardown

@pytest.mark.slow
def test_kill_one_rank_tears_down_world_within_grace():
    """Acceptance (a): one rank dies -> every other rank is torn down
    within the grace deadline, not after its natural exit."""
    t0 = time.monotonic()
    sup = RunSupervisor([
        _spec("import time; time.sleep(0.2); raise SystemExit(3)", "h0"),
        _spec("import time; time.sleep(120)", "h1"),
        _spec("import time; time.sleep(120)", "h2"),
    ], grace_secs=2.0)
    rc = sup.run()
    elapsed = time.monotonic() - t0
    assert rc == 3
    # 0.2s crash + SIGTERM (sleepers die instantly) << the 120s naps
    assert elapsed < 30, elapsed
    assert sup.status[0].signaled is False          # the voluntary crash
    assert sup.status[1].signaled and sup.status[2].signaled


@pytest.mark.slow
def test_sigkill_escalation_after_grace_deadline():
    """A rank that ignores SIGTERM is SIGKILLed once the grace expires."""
    stubborn = ("import signal, time\n"
                "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
                "print('armored', flush=True)\n"
                "time.sleep(120)\n")
    t0 = time.monotonic()
    sup = RunSupervisor([
        _spec("import time; time.sleep(0.3); raise SystemExit(1)", "h0"),
        _spec(stubborn, "h1"),
    ], grace_secs=0.5)
    rc = sup.run()
    assert rc == 1
    assert time.monotonic() - t0 < 30
    assert sup.status[1].signaled


def test_all_ranks_clean_is_zero():
    sup = RunSupervisor([_spec("pass", f"h{i}") for i in range(3)])
    assert sup.run() == 0
    assert all(st.rc == 0 and not st.signaled for st in sup.status)


# ------------------------------------------------ preemption-aware aggregate

@pytest.mark.slow
def test_preemption_rc_survives_teardown_aggregation():
    """Acceptance (c), launcher half: one rank exits 114, the rest are
    torn down -> overall 114, not -15/"crash"."""
    sup = RunSupervisor([
        _spec(f"raise SystemExit({PREEMPTION_EXIT_CODE})", "h0"),
        _spec("import time; time.sleep(120)", "h1"),
    ], grace_secs=1.0)
    assert sup.run() == PREEMPTION_EXIT_CODE


def test_crash_beats_preemption_in_aggregate():
    """A genuine crash observed alongside a preemption is a crash — the
    rc that matters is the one that costs the restart budget."""
    sup = RunSupervisor([
        _spec(f"import time; time.sleep(0.05); "
              f"raise SystemExit({PREEMPTION_EXIT_CODE})", "h0"),
        _spec("raise SystemExit(7)", "h1"),
    ], grace_secs=1.0)
    assert sup.run() == 7


def test_stall_rc_propagates_as_failure():
    sup = RunSupervisor([
        _spec(f"raise SystemExit({STALL_EXIT_CODE})", "h0"),
        _spec("import time; time.sleep(120)", "h1"),
    ], grace_secs=1.0)
    assert sup.run() == STALL_EXIT_CODE


# ------------------------------------------------------ connect-phase retry

def test_connect_failure_retries_with_backoff_then_succeeds():
    chaos.arm("launch.ssh", "raise", times=2)
    buf = io.StringIO()
    sup = RunSupervisor(
        [_spec(f"print('{STARTED_SENTINEL}'); print('payload ran')",
               "h0", remote=True)],
        connect_backoff=0.01, stream=buf)
    assert sup.run() == 0
    assert sup.status[0].attempts == 3
    assert sup.status[0].started
    assert "payload ran" in buf.getvalue()
    assert STARTED_SENTINEL not in buf.getvalue()    # sentinel swallowed


def test_connect_retries_are_bounded():
    chaos.arm("launch.ssh", "raise", times=100)
    sup = RunSupervisor([_spec("pass", "h0", remote=True)],
                        connect_retries=2, connect_backoff=0.01)
    assert sup.run() == SSH_CONNECT_RC
    assert sup.status[0].attempts == 3               # 1 try + 2 retries


def test_rank_that_started_user_code_is_never_retried():
    """rc 255 AFTER the sentinel is user-code death over a live
    connection — re-dispatching would double-run the job."""
    sup = RunSupervisor(
        [_spec(f"print('{STARTED_SENTINEL}', flush=True); "
               f"raise SystemExit({SSH_CONNECT_RC})", "h0", remote=True)],
        connect_backoff=0.01)
    assert sup.run() == SSH_CONNECT_RC
    assert sup.status[0].attempts == 1


def test_local_rank_receives_spec_env():
    """Loopback ranks have no ssh command line to carry exports —
    RankSpec.env must reach the child (e.g. .deepspeed_env entries not in
    the launcher's own environ)."""
    sup = RunSupervisor([RankSpec(
        "localhost",
        [PY, "-c", "import os, sys; "
         "sys.exit(0 if os.environ.get('DSTPU_VERIFY_ENV') == 'yes' else 5)"],
        env={"DSTPU_VERIFY_ENV": "yes"})])
    assert sup.run() == 0


# ----------------------------------------------- per-host log persistence

def test_log_dir_persists_each_ranks_output(tmp_path):
    """--log-dir writes <host>.rank<k>.log per rank — local ranks switch
    to captured pipes so their output lands in the file AND the live
    prefixed stream."""
    buf = io.StringIO()
    log_dir = str(tmp_path / "logs")
    sup = RunSupervisor([
        _spec("print('alpha out'); import sys; print('alpha err', "
              "file=sys.stderr)", "h0"),
        _spec("print('beta out')", "h1"),
    ], stream=buf, log_dir=log_dir)
    assert sup.run() == 0
    log0 = (tmp_path / "logs" / "h0.rank0.log").read_text()
    log1 = (tmp_path / "logs" / "h1.rank1.log").read_text()
    assert "[h0] alpha out" in log0
    assert "[h0] alpha err" in log0          # stderr merged
    assert "[h1] beta out" in log1
    assert "beta" not in log0                # no cross-rank bleed
    # live prefixing still happens alongside the files
    assert "[h0] alpha out" in buf.getvalue()
    assert "[h1] beta out" in buf.getvalue()


def test_log_dir_remote_rank_swallows_sentinel_but_logs_payload(tmp_path):
    buf = io.StringIO()
    log_dir = str(tmp_path / "logs")
    sup = RunSupervisor(
        [_spec(f"print('{STARTED_SENTINEL}'); print('payload ran')",
               "w7", remote=True)],
        stream=buf, log_dir=log_dir)
    assert sup.run() == 0
    assert sup.status[0].started
    log = (tmp_path / "logs" / "w7.rank0.log").read_text()
    assert "[w7] payload ran" in log
    assert STARTED_SENTINEL not in log       # sentinel is supervisor meta
    assert STARTED_SENTINEL not in buf.getvalue()


def test_log_dir_appends_across_connect_retries(tmp_path):
    """A retried dispatch must not truncate what the failed attempt
    logged (mode 'w' first attempt, 'a' afterwards)."""
    chaos.arm("launch.ssh", "raise", times=1)
    log_dir = str(tmp_path / "logs")
    sup = RunSupervisor(
        [_spec(f"print('{STARTED_SENTINEL}'); print('attempt output')",
               "h0", remote=True)],
        connect_backoff=0.01, stream=io.StringIO(), log_dir=log_dir)
    assert sup.run() == 0
    assert sup.status[0].attempts == 2
    log = (tmp_path / "logs" / "h0.rank0.log").read_text()
    assert "attempt output" in log


def test_no_log_dir_keeps_local_ranks_unpiped(tmp_path):
    """Without log_dir, local ranks inherit the launcher's stdio (no
    capture thread) — the pre-existing behavior."""
    sup = RunSupervisor([_spec("print('inherit')", "h0")],
                        stream=io.StringIO())
    assert sup.run() == 0
    assert sup.rank_log_path(0) is None


def test_watchdog_restarts_after_stop():
    """start() after stop() must arm a REAL monitor thread (a stale stop
    flag would leave the engine believing it is protected)."""
    rcs = []
    wd = StallWatchdog(stall_timeout=0.1, poll_interval=0.02,
                       exit_fn=rcs.append, stream=io.StringIO())
    wd.start()
    wd.stop()
    assert rcs == []
    wd.start()
    deadline = time.monotonic() + 10
    while not rcs and time.monotonic() < deadline:
        time.sleep(0.02)
    wd.stop()
    assert rcs == [STALL_EXIT_CODE]


# ------------------------------------------- heartbeat-channel liveness (r6)

@pytest.mark.slow
def test_heartbeat_silence_tears_down_world_as_stall(tmp_path):
    """A rank that attested liveness and then went silent (host dead,
    process blackholed) triggers the same fail-fast teardown as an exit
    — and the run reports rc 117 so the agent counts it."""
    from deepspeed_tpu.runtime import heartbeat as hb
    hb_dir = str(tmp_path / "hb")
    t = [1000.0]
    w = hb.HeartbeatWriter(hb_dir, 1, host="h1", refresh_interval=0,
                           clock=lambda: t[0])
    w.write(hb.PHASE_STEP, 3, force=True)        # rank 1's last word
    live = hb.HeartbeatWriter(hb_dir, 0, host="h0", refresh_interval=0.05)
    live.write(hb.PHASE_STEP, 3, force=True)     # rank 0 keeps attesting
    t0 = time.monotonic()
    sup = RunSupervisor([
        _spec("import time; time.sleep(120)", "h0"),
        _spec("import time; time.sleep(120)", "h1"),
    ], grace_secs=0.5, heartbeat_dir=hb_dir, heartbeat_timeout=0.5,
        heartbeat_poll=0.05)
    rc = sup.run()
    live.close()
    assert rc == STALL_EXIT_CODE
    assert time.monotonic() - t0 < 30
    assert "h1" in sup.failed_hosts()
    assert "h0" not in sup.failed_hosts()
    # attribution is a snapshot taken when silence was DETECTED: once the
    # teardown froze h0's record, its growing age must not retroactively
    # implicate the innocent survivor (the agent would quarantine the
    # whole world, not the dead host)
    time.sleep(0.6)                               # > heartbeat_timeout
    assert "h0" not in sup.failed_hosts()


@pytest.mark.slow
def test_heartbeat_fresh_ranks_do_not_trigger_teardown(tmp_path):
    from deepspeed_tpu.runtime import heartbeat as hb
    hb_dir = str(tmp_path / "hb")
    w = hb.HeartbeatWriter(hb_dir, 0, host="h0", refresh_interval=0.05)
    w.write(hb.PHASE_COMPILE, 0, force=True)
    sup = RunSupervisor([_spec("import time; time.sleep(0.5)", "h0")],
                        heartbeat_dir=hb_dir, heartbeat_timeout=5.0,
                        heartbeat_poll=0.05)
    assert sup.run() == 0
    w.close()


def test_blackholed_host_fails_dispatch_and_is_attributed(tmp_path):
    """host.blackhole (keyed chaos): every dispatch to ONE host fails;
    the other rank keeps its dispatch, the world tears down, and
    failed_hosts() names exactly the blackholed host."""
    chaos.arm("host.blackhole", "raise", times=100, match="h1")
    sup = RunSupervisor([
        _spec("import time; time.sleep(120)", "h0"),
        _spec(f"print('{STARTED_SENTINEL}')", "h1", remote=True),
    ], grace_secs=0.5, connect_retries=1, connect_backoff=0.01,
        stream=io.StringIO())
    rc = sup.run()
    assert rc == SSH_CONNECT_RC
    assert sup.status[1].attempts == 2 and not sup.status[1].started
    assert sup.failed_hosts() == ["h1"]


@pytest.mark.slow
def test_sdc_flag_attributes_host_while_rc118_strikes_nobody(tmp_path):
    """Round 7: an integrity abort exits EVERY rank rc 118 (the audit is
    collective), so the rc must strike no host — only the SDC-flagged
    rank's record carries the attribution."""
    from deepspeed_tpu.runtime import heartbeat as hb
    hb_dir = str(tmp_path / "hb")
    sup = RunSupervisor([
        _spec("import time; time.sleep(0.8); raise SystemExit(118)", "h0"),
        _spec("import time; time.sleep(0.8); raise SystemExit(118)", "h1"),
    ], grace_secs=0.5, heartbeat_dir=hb_dir, stream=io.StringIO()).start()
    w0 = hb.HeartbeatWriter(hb_dir, 0, host="h0", refresh_interval=0)
    w0.write(hb.PHASE_STEP, 10, force=True)
    w0.add_flag("INTEGRITY")             # every aborting rank carries this
    w1 = hb.HeartbeatWriter(hb_dir, 1, host="h1", refresh_interval=0)
    w1.write(hb.PHASE_STEP, 10, force=True)
    w1.add_flag("SDC")
    w1.add_flag("INTEGRITY")
    rc = sup.wait(timeout=60)
    assert rc == 118                     # counted failure for the agent
    assert sup.failed_hosts() == ["h1"]  # ...but only the SDC-flagged host


# --------------------------------------------------- Popen facade + the agent

def test_popen_facade_poll_wait_terminate():
    sup = RunSupervisor([_spec("import time; time.sleep(120)", "h0")],
                        grace_secs=0.5).start()
    assert sup.poll() is None
    with pytest.raises(subprocess.TimeoutExpired):
        sup.wait(timeout=0.2)
    sup.terminate()
    rc = sup.wait(timeout=30)
    assert rc != 0                       # torn down, not clean
    assert sup.poll() == rc == sup.returncode


@pytest.mark.slow
def test_agent_resumes_preempted_supervisor_without_counting(tmp_path):
    """Acceptance (c), agent half: worker 114 -> supervisor 114 -> agent
    resumes with max_restarts=0 still intact."""
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n")
    attempts = tmp_path / "n"

    launches = []

    def launch(members):
        launches.append(1)
        code = (f"import os\np={str(attempts)!r}\n"
                "n = int(open(p).read()) if os.path.exists(p) else 0\n"
                "open(p, 'w').write(str(n + 1))\n"
                f"raise SystemExit({PREEMPTION_EXIT_CODE} if n == 0 else 0)\n")
        specs = [RankSpec("localhost", [PY, "-c", code])]
        if len(launches) == 1:
            # first world: a second rank that must be torn down when
            # rank 0 is preempted (the clean relaunch runs solo)
            specs.append(_spec("import time; time.sleep(120)", "h1"))
        return RunSupervisor(specs, grace_secs=1.0).start()

    agent = DSElasticAgent(launch, str(hostfile), max_restarts=0,
                           check_interval=0.05)
    assert agent.run() == 0
    assert agent.preemptions == 1
    assert agent.restarts == 0
    assert attempts.read_text() == "2"


@pytest.mark.slow
def test_agent_counts_stall_against_max_restarts(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n")
    attempts = tmp_path / "n"

    def launch(members):
        code = (f"import os\np={str(attempts)!r}\n"
                "n = int(open(p).read()) if os.path.exists(p) else 0\n"
                "open(p, 'w').write(str(n + 1))\n"
                f"raise SystemExit({STALL_EXIT_CODE} if n == 0 else 0)\n")
        return RunSupervisor([RankSpec("localhost", [PY, "-c", code])],
                             grace_secs=1.0).start()

    agent = DSElasticAgent(launch, str(hostfile), max_restarts=1,
                           check_interval=0.05)
    assert agent.run() == 0
    assert agent.stalls == 1
    assert agent.restarts == 1
    assert agent.preemptions == 0


# ------------------------------------------------------------ stall watchdog

def test_watchdog_fires_on_stall_with_stack_dump():
    rcs = []
    buf = io.StringIO()
    wd = StallWatchdog(stall_timeout=0.15, poll_interval=0.02,
                       exit_fn=rcs.append, stream=buf).start()
    try:
        deadline = time.monotonic() + 10
        while not rcs and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert rcs == [STALL_EXIT_CODE]
    assert wd.fired
    out = buf.getvalue()
    assert "no step progress" in out
    # faulthandler dumped at least this thread's stack
    assert "test_supervisor" in out or "Thread" in out


@pytest.mark.slow
def test_watchdog_beats_and_suspension_prevent_firing():
    rcs = []
    wd = StallWatchdog(stall_timeout=0.2, poll_interval=0.02,
                       exit_fn=rcs.append, stream=io.StringIO()).start()
    try:
        for _ in range(5):                       # heartbeats hold it off
            time.sleep(0.1)
            wd.beat()
        with wd.suspended():                     # a "slow save"
            time.sleep(0.5)
        time.sleep(0.1)                          # resume re-arms from now
    finally:
        wd.stop()
    assert rcs == []
    assert not wd.fired


@pytest.mark.slow
def test_init_deadline_noop_when_disabled_and_fires_when_hung():
    with init_deadline(0):                       # disabled: pure pass-through
        pass
    rcs = []
    buf = io.StringIO()
    with init_deadline(0.1, what="test-init", exit_fn=rcs.append,
                       stream=buf):
        time.sleep(0.4)
    assert rcs == [STALL_EXIT_CODE]
    assert "test-init" in buf.getvalue()
    rcs2 = []
    with init_deadline(5.0, exit_fn=rcs2.append, stream=io.StringIO()):
        pass                                     # fast body: timer cancelled
    time.sleep(0.05)
    assert rcs2 == []


def test_exit_code_contract_is_distinct():
    assert len({0, PREEMPTION_EXIT_CODE, STALL_EXIT_CODE,
                chaos.KILL_EXIT_CODE}) == 4
    assert STALL_EXIT_CODE < 126                 # below shell signal space


# ----------------------------------------------------------- dstpu --elastic

@pytest.mark.slow
def test_dstpu_elastic_cli_preemption_resume(tmp_path):
    """bin/dstpu --elastic end to end: worker exits 114 on the first
    attempt; with --max-restarts 0 only the preemption exemption lets the
    relaunch happen; second attempt exits clean. Slow-marked (a ~6s CLI
    subprocess roundtrip; scripts/chaos.sh runs it) to keep tier-1 wall
    clock inside its budget."""
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n")
    attempts = tmp_path / "n"
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        p = {str(attempts)!r}
        n = int(open(p).read()) if os.path.exists(p) else 0
        open(p, 'w').write(str(n + 1))
        sys.exit({PREEMPTION_EXIT_CODE} if n == 0 else 0)
    """))
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [PY, os.path.join(REPO, "bin", "dstpu"),
         "--hostfile", str(hostfile), "--launcher", "local",
         "--elastic", "--max-restarts", "0", "--min-nodes", "1",
         "--check-interval", "0.05", "--grace-secs", "2",
         str(script)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert attempts.read_text() == "2"


@pytest.mark.slow
def test_dstpu_elastic_cli_crash_exhausts_budget(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n")
    script = tmp_path / "train.py"
    script.write_text("import sys; sys.exit(9)\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [PY, os.path.join(REPO, "bin", "dstpu"),
         "--hostfile", str(hostfile), "--launcher", "local",
         "--elastic", "--max-restarts", "1", "--check-interval", "0.05",
         str(script)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 9, (proc.returncode, proc.stderr[-2000:])


# ----------------------------- engine-in-child chaos proofs (scripts/chaos.sh)

def _run_child(code, tmp_path, env_extra=None, timeout=300):
    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join([REPO, os.path.join(REPO, "tests")]),
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    env.pop("DSTPU_CHAOS", None)
    env.update(env_extra or {})
    script = tmp_path / "child.py"
    script.write_text(textwrap.dedent(code))
    return subprocess.Popen([PY, str(script)], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True), timeout


CHILD_TRAIN = """
import os
import jax
jax.config.update("jax_platforms", "cpu")
import deepspeed_tpu as ds
from util import SimpleModel, random_batch

cfg = {"train_batch_size": 8,
       "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
       "watchdog": {"stall_timeout": 1.5, "poll_interval": 0.1}}
e, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                      example_batch=random_batch(8))
if os.environ.get("INSTALL_PREEMPT"):
    e.install_preemption_handler(os.environ["CKDIR"], grace_secs=60)
for i in range(50):
    e.train_batch(random_batch(8, seed=i))
raise SystemExit(99)                      # chaos must fire before step 50
"""


CHILD_COMPILE_HANG = """
import os
import jax
jax.config.update("jax_platforms", "cpu")
import deepspeed_tpu as ds
from util import SimpleModel, random_batch

cfg = {"train_batch_size": 8,
       "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
       "watchdog": {"compile_timeout": 1.5, "stall_timeout": 60,
                    "poll_interval": 0.1}}
e, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                      example_batch=random_batch(8))
e.train_batch(random_batch(8))            # run.compile_hang wedges here
raise SystemExit(99)                      # must never be reached
"""


@pytest.mark.slow
def test_compile_hang_exits_stall_rc_within_compile_timeout(tmp_path):
    """Acceptance: a rank wedged BEFORE its first completed step (the
    round-4 blind spot) dies with rc 117 + a stack dump naming the
    COMPILE phase, within compile_timeout + grace — and stamps a STALLED
    terminal heartbeat for the launcher side."""
    from deepspeed_tpu.runtime import heartbeat as hb
    hb_dir = str(tmp_path / "hb")
    proc, timeout = _run_child(
        CHILD_COMPILE_HANG, tmp_path,
        env_extra={"DSTPU_CHAOS": "run.compile_hang:hang",
                   "DSTPU_HEARTBEAT_DIR": hb_dir})
    t0 = time.monotonic()
    out, err = proc.communicate(timeout=timeout)
    elapsed = time.monotonic() - t0
    assert proc.returncode == STALL_EXIT_CODE, (proc.returncode, err[-2000:])
    assert "COMPILE" in err and "compile_timeout" in err
    assert "dumping all thread stacks" in err
    assert elapsed < 120, elapsed          # bounded, not a tier-1 hang
    rec = hb.terminal_records(hb_dir).get(0)
    assert rec is not None and rec["phase"] == hb.PHASE_STALLED


@pytest.mark.slow
def test_wedged_engine_dumps_stacks_and_exits_stall_rc(tmp_path):
    """Acceptance (b): a wedged rank (run.hang on the 3rd step) produces
    an all-threads stack dump and the distinct stall rc."""
    proc, timeout = _run_child(
        CHILD_TRAIN, tmp_path,
        env_extra={"DSTPU_CHAOS": "run.hang:hang:skip=2"})
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == STALL_EXIT_CODE, (proc.returncode, err[-2000:])
    assert "no step progress" in err
    assert "dumping all thread stacks" in err
    # the wedged thread is visible in the dump
    assert "Current thread" in err or "Thread" in err


@pytest.mark.slow
def test_run_preempt_failpoint_emergency_save_rc114(tmp_path):
    """run.preempt (SIGTERM self) at a step boundary: the preemption
    handler checkpoints inside the grace window, the watchdog stays
    suspended through the save, and the process exits 114."""
    d = str(tmp_path / "ck")
    proc, timeout = _run_child(
        CHILD_TRAIN, tmp_path,
        env_extra={"DSTPU_CHAOS": "run.preempt:sigterm:skip=2",
                   "INSTALL_PREEMPT": "1", "CKDIR": d})
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == PREEMPTION_EXIT_CODE, (proc.returncode,
                                                     err[-2000:])
    from deepspeed_tpu.runtime import checkpointing as ck
    latest = ck.get_latest_tag(d)
    assert latest is not None
    assert ck.verify_tag(os.path.join(d, latest)) is None


@pytest.mark.slow
def test_run_kill_failpoint_exits_kill_rc(tmp_path):
    proc, timeout = _run_child(
        CHILD_TRAIN, tmp_path,
        env_extra={"DSTPU_CHAOS": "run.kill:kill:skip=1"})
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == chaos.KILL_EXIT_CODE, (proc.returncode,
                                                     err[-2000:])
