"""1-bit optimizer tests: explicit-collective mode wire-byte accounting,
warmup parity with exact Adam, convergence through the freeze transition,
and the real OneBitLamb (vs the round-1 silent lamb alias).

Mirrors the reference's tests/unit/test_onebit.py (TestOneBitAdamBasic /
TestOneBitLambBasic) plus a wire-byte audit the reference can't do (we parse
the compiled HLO's collective ops).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from util import require_devices


@pytest.fixture(autouse=True)
def _multidevice():
    """This module's features are inherently multi-device (virtual CPU mesh
    in the default suite); skip on platforms with fewer devices."""
    require_devices(8)


import deepspeed_tpu as ds
from deepspeed_tpu.runtime.onebit import hlo_collective_bytes

from util import SimpleModel, random_batch


def _onebit_config(opt_type="OneBitAdam", freeze_step=4, lr=1e-2):
    return {
        "train_batch_size": 16,
        "optimizer": {"type": opt_type,
                      "params": {"lr": lr, "freeze_step": freeze_step,
                                 "weight_decay": 0.01}},
        "seed": 7,
    }


def _make(opt_type="OneBitAdam", freeze_step=4, **kw):
    engine, *_ = ds.initialize(model=SimpleModel(), example_batch=random_batch(16),
                               config=_onebit_config(opt_type, freeze_step, **kw))
    return engine


def test_onebit_engine_explicit_mode_active():
    engine = _make()
    assert engine.onebit is not None
    assert engine.onebit.n == 8


def test_onebit_adam_warmup_matches_exact_adam():
    """During warmup the explicit-collective path is exact (uncompressed)
    Adam without bias correction — losses must track a same-hyper reference
    run step for step."""
    e1 = _make("OneBitAdam", freeze_step=1000)
    cfg = _onebit_config("Adam")
    cfg["optimizer"]["params"].pop("freeze_step")
    cfg["optimizer"]["params"]["bias_correction"] = False
    # 1-bit Adam's weight decay is decoupled (reference onebit/adam.py adds
    # wd*p to the update); match it
    cfg["optimizer"]["params"]["adamw_mode"] = True
    e2, *_ = ds.initialize(model=SimpleModel(), example_batch=random_batch(16),
                           config=cfg)
    for i in range(4):
        b = random_batch(16, seed=i)
        l1 = float(e1.train_batch(b)["loss"])
        l2 = float(e2.train_batch(b)["loss"])
        assert abs(l1 - l2) < 3e-3, (i, l1, l2)


@pytest.mark.slow
def test_onebit_adam_trains_through_freeze():
    """Warmup long enough for v to stabilize (the algorithm's intended regime
    — reference docs put freeze at 15-25% of total steps), then the
    compressed stage must keep training without blowup."""
    engine = _make("OneBitAdam", freeze_step=12, lr=2e-3)
    losses = [float(engine.train_batch(random_batch(16, seed=i))["loss"])
              for i in range(36)]
    assert np.mean(losses[-5:]) < losses[0]
    assert all(np.isfinite(losses)), losses
    # the compressed stage must actually run
    assert engine.onebit._step_frozen is not None
    assert engine.onebit._step_warm is not None


@pytest.mark.slow
def test_onebit_lamb_trains_through_freeze():
    engine = _make("OneBitLamb", freeze_step=12, lr=1e-2)
    losses = [float(engine.train_batch(random_batch(16, seed=i))["loss"])
              for i in range(36)]
    assert np.mean(losses[-5:]) < losses[0]
    assert all(np.isfinite(losses)), losses
    assert engine.onebit._step_frozen is not None


def test_onebit_wire_bytes_compressed():
    """The compression-stage step must move far fewer collective bytes than
    the warmup step (which allreduces f32 grads): the 1-bit exchange carries
    packed sign bits + scales. Audited from the optimized HLO."""
    engine = _make("OneBitAdam", freeze_step=5)
    micros = jax.tree.map(
        lambda x: jnp.asarray(x)[None], random_batch(16))
    rng = jax.random.PRNGKey(0)
    params = engine.state.params
    state = engine.state.opt_state["onebit"]
    runner = engine.onebit

    def bytes_for(frozen):
        from deepspeed_tpu.runtime.loss_scaler import LossScaleState
        fn = runner._build(frozen)
        lowered = fn.lower(params, state, micros, rng,
                           jnp.asarray(1e-2, jnp.float32),
                           LossScaleState.identity())
        return hlo_collective_bytes(lowered.compile().as_text())

    warm = bytes_for(False)
    frozen = bytes_for(True)
    assert warm > 0 and frozen > 0
    # sign-bit traffic alone is 1/32 of f32; scales/loss/norm overhead means
    # the end-to-end step must still be >=6x cheaper on the wire
    assert frozen * 6 <= warm, (warm, frozen)


def test_onebit_rejects_zero_stage():
    cfg = _onebit_config()
    cfg["zero_optimization"] = {"stage": 2}
    with pytest.raises(ValueError, match="ZeRO"):
        ds.initialize(model=SimpleModel(), example_batch=random_batch(16),
                      config=cfg)


def test_onebit_lamb_numeric_dp1():
    """The functional onebit_lamb (dp=1 numeric form) must run both stages
    and differ from plain lamb after freeze (the round-1 alias bug)."""
    from deepspeed_tpu.ops.optimizers import build_optimizer, lamb
    ob = build_optimizer("OneBitLamb", {"lr": 1e-2, "freeze_step": 3})
    pl = lamb(lr=1e-2)
    assert ob.name == "onebitlamb"
    params = {"w": jnp.asarray(np.random.RandomState(0).randn(64), jnp.float32)}
    s_ob, s_pl = ob.init(params), pl.init(params)
    p_ob = p_pl = params
    diverged = False
    for step in range(8):
        g = {"w": jnp.asarray(np.random.RandomState(step + 1).randn(64),
                              jnp.float32)}
        p_ob, s_ob = ob.update(g, s_ob, p_ob, jnp.asarray(step, jnp.int32))
        p_pl, s_pl = pl.update(g, s_pl, p_pl, jnp.asarray(step, jnp.int32))
        if step >= 3 and not np.allclose(np.asarray(p_ob["w"]),
                                         np.asarray(p_pl["w"]), atol=1e-6):
            diverged = True
    assert diverged, "onebit_lamb behaved identically to plain lamb"
    assert np.all(np.isfinite(np.asarray(p_ob["w"])))


def test_quantized_gather_fwd_bwd_parity():
    """ZeRO++-style qwZ: the int8 quantized weight gather reconstructs the
    full tensor within int8 tolerance; its custom-vjp backward is the exact
    zero-communication shard slice (STE through the quantization)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from deepspeed_tpu.runtime.comm.compressed import make_quantized_gather

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("data",))
    rng = np.random.default_rng(0)
    w = rng.standard_normal((8, 16)).astype(np.float32)
    w_sh = jax.device_put(jnp.asarray(w), NamedSharding(mesh, P("data", None)))

    qg = make_quantized_gather(mesh, "data", dim=0)
    # forward: int8-accurate reconstruction (per-shard scale, 127 levels)
    full = jax.jit(qg)(w_sh)
    assert full.shape == w.shape
    per_shard_tol = np.abs(w).reshape(4, 2, 16).max(axis=(1, 2)) / 127.0
    err = np.abs(np.asarray(full) - w).reshape(4, -1).max(axis=1)
    assert (err <= per_shard_tol * 1.01).all()

    # backward: STE through the quantization — d/dw sum(full * c) is exactly
    # each shard's slice of c (the cotangent is already globally reduced at
    # this seam; gradient-side quantization lives in quantized_allreduce)
    c = rng.standard_normal((8, 16)).astype(np.float32)
    g = jax.jit(jax.grad(lambda x: jnp.sum(qg(x) * jnp.asarray(c))))(w_sh)
    np.testing.assert_allclose(np.asarray(g), c, rtol=0, atol=1e-6)

    # wire audit: the gather in the compiled forward moves int8, not f32
    txt = jax.jit(qg).lower(w_sh).compile().as_text()
    assert "all-gather" in txt and "s8" in txt


def test_hierarchical_quantized_allreduce():
    """Two-level scheme: exact psum over the intra (ICI) axis, int8
    error-feedback exchange over the inter (DCN) axis — result converges to
    the plain mean as error feedback accumulates."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from deepspeed_tpu.runtime.comm.compressed import (
        hierarchical_quantized_allreduce)

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("inter", "intra"))
    rng = np.random.default_rng(0)
    vals = rng.standard_normal((8, 64)).astype(np.float32)
    x = jax.device_put(jnp.asarray(vals),
                       NamedSharding(mesh, P(("inter", "intra"))))
    err = jax.device_put(jnp.zeros((2, 64), jnp.float32),
                         NamedSharding(mesh, P("inter")))
    want = vals.mean(axis=0)
    out, err = hierarchical_quantized_allreduce(
        x, err, mesh=mesh, intra_axis="intra", inter_axis="inter")
    # single shot: int8-accurate
    np.testing.assert_allclose(np.asarray(out), want, atol=np.abs(
        want).max() / 127 * 3 + 1e-6)
    # repeated same-input rounds: worker error feedback compensates the
    # chunk-exchange quantization; what remains is the (feedback-free)
    # server-side re-quant, bounded by one int8 step of the served mean
    for _ in range(4):
        out, err = hierarchical_quantized_allreduce(
            x, err, mesh=mesh, intra_axis="intra", inter_axis="inter")
    server_step = np.abs(want).max() / 127.0
    np.testing.assert_allclose(np.asarray(out), want,
                               atol=2 * server_step + 1e-6)


@pytest.mark.slow
def test_onebit_fp16_loss_scaling_composes():
    """onebit + fp16 dynamic loss scaling (the reference default envelope:
    onebit/adam.py:11 runs under FP16_Optimizer): trains through the freeze
    transition, and an overflow batch skips the step and halves the scale."""
    cfg = _onebit_config("OneBitAdam", freeze_step=12, lr=2e-3)
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 8, "hysteresis": 1}
    engine, *_ = ds.initialize(model=SimpleModel(),
                               example_batch=random_batch(16), config=cfg)
    assert engine.onebit is not None and engine.onebit.loss_scaler.enabled
    losses = [float(engine.train_batch(random_batch(16, seed=i))["loss"])
              for i in range(20)]
    assert engine.onebit._step_frozen is not None   # compressed stage ran
    assert np.mean(losses[-4:]) < losses[0]

    # overflow: huge inputs blow up the fp16 backward
    scale_before = float(jax.device_get(engine.state.scale.scale))
    p_before = jax.tree.map(np.asarray, jax.device_get(engine.state.params))
    bad = random_batch(16, seed=99)
    bad["x"] = (bad["x"] * 1e30).astype(np.float32)
    m = engine.train_batch(bad)
    assert bool(m["overflow"]) is True
    assert m["loss_scale"] <= scale_before / 2
    p_after = jax.tree.map(np.asarray, jax.device_get(engine.state.params))
    for a, b in zip(jax.tree.leaves(p_before), jax.tree.leaves(p_after)):
        np.testing.assert_array_equal(a, b)   # step skipped: params untouched

    # recovery: training continues after the skip
    m2 = engine.train_batch(random_batch(16, seed=100))
    assert np.isfinite(float(m2["loss"])) and not bool(m2["overflow"])


# tier-2 (round-19 budget sweep, ~6s): the cheaper tier-1 cousins are
# test_onebit_adam_warmup_matches_exact_adam (optimizer math) and
# test_onebit_lamb_numeric_dp1 (sharded-state numerics);
# scripts/tier2.sh runs this ZeRO-1 composition leg
@pytest.mark.slow
def test_onebit_zero1_composes():
    """onebit + ZeRO-1: optimizer state leaves whose dim0 divides the DP
    world are sharded across it (memory /8 on the big leaves), and the math
    is unchanged — losses track the stage-0 run step for step."""
    from jax.sharding import PartitionSpec as P

    cfg0 = _onebit_config("OneBitAdam", freeze_step=5)
    cfg1 = _onebit_config("OneBitAdam", freeze_step=5)
    cfg1["zero_optimization"] = {"stage": 1}
    e0, *_ = ds.initialize(model=SimpleModel(),
                           example_batch=random_batch(16), config=cfg0)
    e1, *_ = ds.initialize(model=SimpleModel(),
                           example_batch=random_batch(16), config=cfg1)
    assert e1.onebit is not None and e1.onebit.zero_stage == 1

    # m/v leaves with divisible dim0 carry the DP axis in their sharding
    mv = e1.state.opt_state["onebit"]["m"]
    sharded = [l for l in jax.tree.leaves(mv)
               if l.ndim >= 1 and l.shape[0] % 8 == 0]
    assert sharded, "model has no dividable leaves to shard"
    for l in sharded:
        assert l.sharding.spec == P("data"), l.sharding
    # ...and the replicated-fallback leaves stay replicated
    for l in jax.tree.leaves(mv):
        if l.ndim >= 1 and l.shape[0] % 8 != 0:
            assert l.sharding.spec == P()

    for i in range(12):
        b = random_batch(16, seed=i)
        l0 = float(e0.train_batch(b)["loss"])
        l1 = float(e1.train_batch(b)["loss"])
        assert abs(l0 - l1) < 5e-4, (i, l0, l1)

    # after frozen steps the v-side leaves KEEP their ZeRO-1 sharding (m is
    # replicated post-freeze by design: the error-feedback exchange needs
    # the full momentum per rank)
    assert e1.onebit._step_frozen is not None
    v_after = e1.state.opt_state["onebit"]["v"]
    for l in jax.tree.leaves(v_after):
        if l.ndim >= 1 and l.shape[0] % 8 == 0:
            assert l.sharding.spec == P("data"), l.sharding


# -- 0/1 Adam (the real algorithm, not the round-3 onebit alias) --------------


class _SmoothModel:
    """tanh MLP factory: every parameter sees a nonzero gradient each step —
    the healthy regime for sign-compression (elements with exactly-zero grad
    AND zero variance would receive +-scale momentum over eps, a property
    the reference algorithm shares)."""

    def __new__(cls):
        import flax.linen as nn

        class M(nn.Module):
            hidden: int = 32
            nclass: int = 8

            @nn.compact
            def __call__(self, batch, train=False):
                x, y = batch["x"], batch["y"]
                h = nn.tanh(nn.Dense(self.hidden)(x))
                h = nn.tanh(nn.Dense(self.hidden)(h))
                logits = nn.Dense(self.nclass)(h)
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(jnp.sum(
                    jax.nn.one_hot(y, self.nclass) * logp, -1))

        return M()


def _zeroone_config(**params):
    p = {"lr": 2e-3, "var_freeze_step": 12, "var_update_scaler": 4,
         "local_step_scaler": 4, "local_step_clipper": 4,
         "weight_decay": 0.01}
    p.update(params)
    return {"train_batch_size": 16,
            "optimizer": {"type": "ZeroOneAdam", "params": p},
            "seed": 7}


def test_zeroone_alias_removed():
    """'ZeroOneAdam' must resolve to the real 0/1 Adam algorithm, not an
    alias of onebit_adam (round-3 Missing #2)."""
    from deepspeed_tpu.ops.optimizers import build_optimizer
    zo = build_optimizer("ZeroOneAdam", {"lr": 1e-2})
    assert zo.name == "zerooneadam"
    ob = build_optimizer("OneBitAdam", {"lr": 1e-2})
    params = {"w": jnp.ones((8,), jnp.float32)}
    # 0/1 Adam state carries the interval machinery 1-bit Adam doesn't have
    st = zo.init(params)
    assert "var_interval" in st and "local_interval" in st and "u" in st
    assert "var_interval" not in ob.init(params)


def test_zeroone_interval_doubling():
    """The variance-update interval doubles after every var_update_scaler
    v-updates, v is untouched between v-steps and frozen after
    var_freeze_step; the local-step interval doubles every local_step_scaler
    steps up to local_step_clipper (reference zoadam.py:283-303)."""
    from deepspeed_tpu.ops.optimizers import zero_one_adam
    opt = zero_one_adam(lr=1e-2, var_freeze_step=6, var_update_scaler=2,
                        local_step_scaler=3, local_step_clipper=4)
    params = {"w": jnp.ones((4,), jnp.float32)}
    st = opt.init(params)
    rng = np.random.RandomState(0)
    p = params
    v_hist, iv_hist, li_hist, u_zero = [], [], [], []
    for t in range(16):
        g = {"w": jnp.asarray(rng.randn(4), jnp.float32)}
        p, st = opt.update(g, st, p, jnp.asarray(t, jnp.int32))
        v_hist.append(np.asarray(st["v"]["w"]).copy())
        iv_hist.append(int(st["var_interval"]))
        li_hist.append(int(st["local_interval"]))
        u_zero.append(float(jnp.abs(st["u"]["w"]).sum()) == 0.0)
    # kappa=2: steps 1,2 at interval 1 -> doubles; v-steps 4, 6 -> doubles
    assert iv_hist == [1, 2, 2, 2, 2, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4]
    # v changes exactly at steps 1,2,4,6 (indices 0,1,3,5), frozen afterwards
    changed = [True] + [not np.array_equal(v_hist[i], v_hist[i - 1])
                        for i in range(1, 16)]
    assert changed == [True, True, False, True, False, True] + [False] * 10
    # local phase from step 7: interval 1 for 3 steps, then 2, then 4 (clip)
    assert li_hist[5] == 1 and li_hist[8] == 2 and li_hist[11] == 4
    assert li_hist[15] == 4  # clipper caps further doubling
    # u resets exactly at boundaries (step % interval == 0)
    assert u_zero[9] and not u_zero[10] and u_zero[11]  # li=2: steps 10,11,12


def test_zeroone_differs_from_onebit():
    """0/1 Adam and 1-bit Adam are different algorithms: same grads, same
    shared hyperparameters, different trajectories."""
    from deepspeed_tpu.ops.optimizers import onebit_adam, zero_one_adam
    zo = zero_one_adam(lr=1e-2, var_freeze_step=6, var_update_scaler=2,
                       local_step_scaler=3, local_step_clipper=4)
    ob = onebit_adam(lr=1e-2, freeze_step=6)
    params = {"w": jnp.asarray(np.random.RandomState(0).randn(64),
                               jnp.float32)}
    s_zo, s_ob = zo.init(params), ob.init(params)
    p_zo = p_ob = params
    for t in range(12):
        g = {"w": jnp.asarray(np.random.RandomState(100 + t).randn(64),
                              jnp.float32)}
        p_zo, s_zo = zo.update(g, s_zo, p_zo, jnp.asarray(t, jnp.int32))
        p_ob, s_ob = ob.update(g, s_ob, p_ob, jnp.asarray(t, jnp.int32))
    diff = float(jnp.abs(p_zo["w"] - p_ob["w"]).max())
    assert diff > 1e-4, "0/1 Adam produced 1-bit Adam's trajectory"
    assert np.all(np.isfinite(np.asarray(p_zo["w"])))


def test_zeroone_engine_program_schedule():
    """The engine must dispatch the right compiled program per step: exact
    v-steps and compressed steps interleaved per the doubling interval in
    the variance phase, local/boundary steps after the freeze."""
    engine, *_ = ds.initialize(model=_SmoothModel(),
                               example_batch=random_batch(16),
                               config=_zeroone_config(
                                   var_freeze_step=8, var_update_scaler=2,
                                   local_step_scaler=4, local_step_clipper=4))
    from deepspeed_tpu.runtime.zeroone import ZeroOneRunner
    assert isinstance(engine.onebit, ZeroOneRunner)
    keys = [engine.onebit.program_key(t) for t in range(14)]
    assert keys == ["vstep", "vstep", "cstep", "vstep", "cstep", "vstep",
                    "cstep", "vstep",
                    "boundary", "boundary", "boundary", "boundary",
                    "local", "boundary"]


# tier-2 (round 10 budget): fattest passing legs demoted per the standing
# guardrail — tier-1 crept past ~80% of the 870s budget once the comm-plan
# legs landed and the jax_compat shard_map wrapper recovered the 1-bit
# family on 0.4.x hosts; cheaper cousins still gate tier-1
@pytest.mark.slow
def test_zeroone_trains_and_local_steps_are_collective_free():
    """End-to-end: 0/1 Adam trains through all four program kinds, and the
    HLO of the local-step program contains ZERO cross-replica collective
    bytes — the algorithm's whole point (1-bit sync with local steps)."""
    engine, *_ = ds.initialize(model=_SmoothModel(),
                               example_batch=random_batch(16),
                               config=_zeroone_config())
    losses = [float(engine.train_batch(random_batch(16, seed=i))["loss"])
              for i in range(40)]
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-6:]) < losses[0]

    micros = jax.tree.map(lambda x: jnp.asarray(x)[None], random_batch(16))
    rng = jax.random.PRNGKey(0)
    params = engine.state.params
    st = engine.state.opt_state["onebit"]
    audit = {k: engine.onebit.collective_bytes(params, st, micros, rng, k)
             for k in ("vstep", "cstep", "local", "boundary")}
    assert audit["local"] == 0, audit
    # compressed steps move far fewer bytes than the exact v-step
    assert audit["cstep"] * 3 <= audit["vstep"], audit
    assert audit["boundary"] * 3 <= audit["vstep"], audit


def test_overflow_does_not_consume_schedule_steps():
    """An fp16 overflow reverts the optimizer state in-jit; the runner's
    program schedule (freeze / v-update / local-step intervals) must not
    advance past the skipped step — the reference's onebit/zoadam counters
    only move on executed torch steps."""
    cfg = _onebit_config("OneBitAdam", freeze_step=12, lr=2e-3)
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 8, "hysteresis": 1}
    engine, *_ = ds.initialize(model=SimpleModel(),
                               example_batch=random_batch(16), config=cfg)
    seen = []
    orig = engine.onebit.step

    def spy(params, state, micros, rng, lr, step, **kw):
        seen.append(step)
        return orig(params, state, micros, rng, lr, step, **kw)

    engine.onebit.step = spy
    m0 = engine.train_batch(random_batch(16, seed=0))
    assert not bool(m0["overflow"])
    bad = random_batch(16, seed=99)
    bad["x"] = (bad["x"] * 1e30).astype(np.float32)
    m1 = engine.train_batch(bad)
    assert bool(m1["overflow"])
    m2 = engine.train_batch(random_batch(16, seed=1))
    assert not bool(m2["overflow"])
    # good step consumed slot 0; the overflow attempted slot 1 and was
    # skipped; the next good step must RETRY slot 1, not move to slot 2
    assert seen == [0, 1, 1], seen


# tier-2 (round-19 budget sweep, ~8s): the cheaper tier-1 cousins are
# test_zeroone_interval_doubling + test_zeroone_differs_from_onebit
# (phase machinery) and test_zeroone_engine_program_schedule (program
# selection); scripts/tier2.sh runs this measured-bytes envelope leg
@pytest.mark.slow
def test_zeroone_local_phase_state_memory_model():
    """Post-freeze per-device state bytes must match the documented envelope
    (docs/BENCHMARKS.md 1-bit table): m_local / u / w_err are one
    full-model copy per DEVICE (stacked [n, ...] dim-0-sharded), v is
    replicated by design (every local step reads it whole), m / s_err stay
    ZeRO-1 sharded — ~17 B/param/device total. Round 5's measurement caught
    the boundary program silently REPLICATING the reset drift u
    (32 B/param/device) because its fresh zeros carried no sharding pin."""
    engine, *_ = ds.initialize(model=_SmoothModel(),
                               example_batch=random_batch(16),
                               config=_zeroone_config(
                                   var_freeze_step=2, var_update_scaler=2,
                                   local_step_scaler=2, local_step_clipper=4))
    for i in range(8):          # vstep/cstep, then boundary + local steps
        engine.train_batch(random_batch(16, seed=i))
    st = engine.state.opt_state["onebit"]
    n_params = sum(x.size for x in jax.tree.leaves(engine.state.params))
    # the same shard-byte accounting the envelope table was measured with
    import pathlib, sys as _sys
    _sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                            / "scripts"))
    from onebit_envelope import per_device_bytes

    for key, expect in [("u", 4.0), ("m_local", 4.0), ("w_err", 4.0)]:
        got = per_device_bytes(st[key]) / n_params
        assert got <= expect * 1.5, \
            f"{key}: {got:.1f} B/param/device (stacked sharding lost?)"
    total = per_device_bytes({k: v for k, v in st.items() if k != "lrs"})
    assert total / n_params <= 17.0 * 1.3, total / n_params
