"""Continuous-batching serving loop: token-exactness, fixed-shape compile
discipline, block-pool admission control, prefix-cache COW, FIFO fairness,
chaos failpoints, SERVE heartbeat supervision.

The oracle everywhere is sequential ``models.generation.generate()`` —
greedy serving output must be TOKEN-EXACT with one-at-a-time generation
(same layer math through serving/model_runner.py), across staggered
arrivals, mixed lengths, admissions and evictions.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import build_model
from deepspeed_tpu.models.generation import generate
from deepspeed_tpu.serving.engine import ServingEngine
from deepspeed_tpu.serving.kv_cache import (BlockPool, BlockPoolExhausted,
                                            PrefixCache)
from deepspeed_tpu.serving.scheduler import QUEUED
from deepspeed_tpu.testing import chaos


@pytest.fixture(scope="module")
def tiny():
    # f32: the token-exactness contract compares greedy argmaxes between
    # two mathematically-identical-but-differently-fused programs; bf16's
    # 8-bit mantissa makes 1-ulp near-ties on a random tiny model likely
    model, cfg = build_model(
        "gpt2-tiny", hidden_size=32, num_layers=2, num_heads=2,
        vocab_size=64, max_seq_len=256, attention_impl="reference",
        dtype=jnp.float32)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, params


def _oracle_tokens(cfg, params, prompt, n):
    out = generate(cfg, params, jnp.asarray([list(prompt)]), n)
    return [int(x) for x in np.asarray(out)[0][len(prompt):]]


SERVE_CFG = {"block_size": 16, "pool_blocks": 64, "max_batch": 4,
             "max_blocks_per_seq": 8}


# ---------------------------------------------------------------------------
# the acceptance-criteria integration leg
# ---------------------------------------------------------------------------

# tier-2 (round-19 budget sweep, ~6s): the cheaper tier-1 cousins are
# test_fifo_fairness_under_full_pool + test_admission_eviction_protects
# _heads_own_prefix (admission/eviction ledger) and the fleet suites'
# token-exact e2e legs (test_fleet.py, test_autoscale.py);
# scripts/tier2.sh runs this 9-request staggered matrix
@pytest.mark.slow
def test_serving_integration_staggered_token_exact(tiny):
    """>= 8 concurrent requests, staggered arrivals, mixed lengths, greedy:
    token-exact vs sequential generate(), with EXACTLY ONE decode-step
    compile across all admissions/evictions (fixed-shape discipline)."""
    cfg, params = tiny
    eng = ServingEngine(cfg, params, serving=SERVE_CFG)
    rng = np.random.default_rng(7)
    # 9 requests (> max_batch lanes), 4 distinct prompt lengths and 2
    # distinct generation lengths: mixed-length coverage while the
    # sequential-generate oracle compiles only 4 (T, max_new) programs
    # (tier-1 budget — each distinct pair is one _generate trace)
    lens = [5, 11, 17, 23, 5, 17, 11, 23, 11]
    prompts = [list(rng.integers(1, 64, size=n)) for n in lens]
    new = [6, 6, 8, 8, 6, 8, 6, 8, 6]     # per-length, so 4 oracle pairs
    finished = []
    # staggered: 3 up front, 3 after a couple of loop iterations, 3 after
    # the first completions — admissions ride a live, partially-full loop
    reqs = [eng.submit(prompts[i], new[i],
                       on_finish=lambda r: finished.append(r.rid))
            for i in range(3)]
    eng.step(); eng.step()
    reqs += [eng.submit(prompts[i], new[i]) for i in range(3, 6)]
    while eng.stats["completed"] == 0:
        eng.step()
    reqs += [eng.submit(prompts[i], new[i]) for i in range(6, 9)]
    eng.run_until_idle()

    assert eng.stats["completed"] == 9
    for p, n, r in zip(prompts, new, reqs):
        assert r.output_tokens == _oracle_tokens(cfg, params, p, n), \
            f"request {r.rid} diverged from sequential generate()"
    # the fixed-shape decode step compiled exactly once
    cache_size = getattr(eng._decode_fn, "_cache_size", None)
    if cache_size is None:
        pytest.skip("jax build has no PjitFunction._cache_size")
    assert cache_size() == 1
    assert finished                      # completion callbacks fired


def test_serving_pool_released_after_drain(tiny):
    cfg, params = tiny
    eng = ServingEngine(cfg, params,
                        serving=dict(SERVE_CFG, prefix_cache=False))
    rng = np.random.default_rng(3)
    eng.generate_batch([list(rng.integers(1, 64, size=12))] * 3,
                       max_new_tokens=5)
    assert eng.pool.used_count == 0      # every block returned


# ---------------------------------------------------------------------------
# block pool + prefix cache units
# ---------------------------------------------------------------------------

def test_block_pool_alloc_release_refcounts():
    pool = BlockPool(num_blocks=8, block_size=16)
    assert pool.free_count == 7          # block 0 reserved
    a = pool.alloc(3)
    assert 0 not in a and pool.free_count == 4
    shared = pool.fork(a[:2])
    assert pool.refcount(a[0]) == 2
    pool.release(a)                      # first holder gone
    assert pool.free_count == 5          # a[2] back; a[0], a[1] still held
    assert pool.refcount(a[0]) == 1
    pool.release(shared)
    assert pool.free_count == 7
    with pytest.raises(BlockPoolExhausted):
        pool.alloc(8)
    with pytest.raises(ValueError):
        pool.fork([0])                   # null block is never shareable


def test_prefix_cache_match_insert_evict():
    pool = BlockPool(num_blocks=16, block_size=4)
    cache = PrefixCache(pool)
    toks = list(range(10))               # 2 full blocks + 2 tokens
    blocks = pool.alloc(3)
    cache.insert(toks, blocks)
    assert len(cache) == 2               # k=1 and k=2 prefixes
    n, forked = cache.match(toks)
    assert n == 8 and forked == blocks[:2]
    # owner + one ref per covering cache entry (k=1, k=2) + the fork:
    # per-entry refs keep partial eviction safe (dropping the k=2 entry
    # must not free the block the k=1 entry still serves)
    assert pool.refcount(blocks[0]) == 4
    pool.release(forked)
    # an 8-token prompt (exactly 2 blocks) must leave >= 1 token to
    # prefill: only the 1-block prefix may be reused
    n8, forked8 = cache.match(toks[:8])
    assert n8 == 4
    pool.release(forked8)
    # eviction under pressure releases LRU entries (owner refs remain)
    pool.release(blocks)
    cache.evict(pool.num_blocks)
    assert pool.used_count == 0


def test_prefix_cache_hash_collision_guard():
    pool = BlockPool(num_blocks=8, block_size=4)
    cache = PrefixCache(pool)
    blocks = pool.alloc(1)
    cache.insert([1, 2, 3, 4], blocks)
    n, forked = cache.match([9, 9, 9, 9, 5])
    assert n == 0 and forked == []


def test_serving_prefix_cow_blocks_are_shared_readonly(tiny):
    """Forked prefix blocks are refcounted and READ-ONLY: the consumer
    writes only above its fork point, the donor's block contents are
    bit-identical after the consumer runs, and freeing the donor does not
    corrupt the consumer (token-exactness holds throughout)."""
    cfg, params = tiny
    eng = ServingEngine(cfg, params, serving=SERVE_CFG)
    rng = np.random.default_rng(11)
    sys_prompt = list(rng.integers(1, 64, size=32))      # 2 full blocks
    p1 = sys_prompt + list(rng.integers(1, 64, size=5))
    p2 = sys_prompt + list(rng.integers(1, 64, size=9))

    r1 = eng.submit(p1, 4)
    eng.run_until_idle()
    assert r1.output_tokens == _oracle_tokens(cfg, params, p1, 4)
    # the shared blocks live on in the prefix cache after r1 drained
    n, forked = eng.prefix_cache.match(p2)
    assert n == 32
    shared = list(forked)
    eng.pool.release(forked)             # undo the probe's fork
    snapshot = np.asarray(
        eng.pools["k"][:, :, shared[0] * 16:(shared[0] + 1) * 16])

    r2 = eng.submit(p2, 4)
    eng.run_until_idle()
    assert r2.prefix_hit_tokens == 32    # reused, not recomputed
    assert r2.output_tokens == _oracle_tokens(cfg, params, p2, 4)
    after = np.asarray(
        eng.pools["k"][:, :, shared[0] * 16:(shared[0] + 1) * 16])
    np.testing.assert_array_equal(snapshot, after)   # copy-on-write honored


# ---------------------------------------------------------------------------
# admission control / FIFO / chaos
# ---------------------------------------------------------------------------

def test_pool_exhaustion_queues_not_crashes(tiny):
    """More lifetime blocks than the pool holds: the overflow requests
    WAIT (admission control) and complete as earlier ones free blocks."""
    cfg, params = tiny
    eng = ServingEngine(cfg, params,
                        serving={"block_size": 16, "pool_blocks": 5,
                                 "max_batch": 4, "max_blocks_per_seq": 4,
                                 "prefix_cache": False})
    rng = np.random.default_rng(5)
    prompts = [list(rng.integers(1, 64, size=20)) for _ in range(4)]
    reqs = [eng.submit(p, 6) for p in prompts]          # 2 blocks each, 4 free
    eng.step()
    assert eng.active == 2 and eng.scheduler.pending == 2   # budget-limited
    eng.run_until_idle()
    assert eng.stats["completed"] == 4
    for p, r in zip(prompts, reqs):
        assert r.output_tokens == _oracle_tokens(cfg, params, p, 6)


def test_fifo_fairness_under_full_pool(tiny):
    """Strict FIFO: a big head request that does not fit blocks the small
    ones behind it — small traffic cannot starve a large request."""
    cfg, params = tiny
    eng = ServingEngine(cfg, params,
                        serving={"block_size": 16, "pool_blocks": 7,
                                 "max_batch": 4, "max_blocks_per_seq": 6,
                                 "prefix_cache": False})
    rng = np.random.default_rng(6)
    running = eng.submit(list(rng.integers(1, 64, size=40)), 6)   # 3 blocks
    eng.step()
    assert eng.active == 1
    big = eng.submit(list(rng.integers(1, 64, size=60)), 6)       # 4 blocks
    small = eng.submit(list(rng.integers(1, 64, size=8)), 4)      # 1 block
    eng.step()
    # 3 free blocks: big does not fit; small WOULD fit but must wait
    assert big.state == QUEUED and small.state == QUEUED
    eng.run_until_idle()
    assert running.done and big.done and small.done
    assert big.first_token_ts <= small.first_token_ts    # FIFO admission


def test_prefill_failure_marks_failed_and_releases_blocks(tiny):
    """A deterministic forward failure mid-prefill must not leak blocks:
    the request is FAILED (callback fires, stats count it), the pool is
    whole, and the loop keeps serving."""
    cfg, params = tiny
    eng = ServingEngine(cfg, params,
                        serving=dict(SERVE_CFG, prefix_cache=False))
    boom = eng._prefill_fn
    eng._prefill_fn = lambda *a, **kw: (_ for _ in ()).throw(
        RuntimeError("injected prefill failure"))
    seen = []
    req = eng.submit([1, 2, 3, 4], 4, on_finish=lambda r: seen.append(r))
    with pytest.raises(RuntimeError, match="injected"):
        eng.step()
    assert req.state == "FAILED" and "injected" in req.error
    assert seen and eng.stats["failed"] == 1
    assert eng.pool.used_count == 0          # nothing leaked
    eng._prefill_fn = boom
    ok = eng.submit([1, 2, 3, 4], 3)
    eng.run_until_idle()
    assert ok.done and ok.state == "FINISHED"


def test_admission_eviction_protects_heads_own_prefix(tiny):
    """Make-room eviction nets the head's prefix hit out of the budget and
    never evicts the entry the head is about to reuse."""
    cfg, params = tiny
    eng = ServingEngine(cfg, params,
                        serving={"block_size": 16, "pool_blocks": 6,
                                 "max_batch": 2, "max_blocks_per_seq": 5})
    rng = np.random.default_rng(17)
    shared = list(rng.integers(1, 64, size=32))          # 2 full blocks
    r1 = eng.submit(shared + [5, 6], 2)                  # 3 blocks lifetime
    eng.run_until_idle()
    # cache holds the 2 shared blocks; 3 blocks free. The follower needs
    # 3 total, nets to 1 with the hit — admissible WITHOUT eviction even
    # though the gross budget (3) equals free (3): the hit survives
    r2 = eng.submit(shared + [7, 8, 9], 2)
    eng.run_until_idle()
    assert r1.done and r2.done
    assert r2.prefix_hit_tokens == 32        # the entry was not evicted


def test_chaos_serve_oom_keeps_request_queued(tiny):
    cfg, params = tiny
    eng = ServingEngine(cfg, params, serving=SERVE_CFG)
    rng = np.random.default_rng(8)
    prompt = list(rng.integers(1, 64, size=10))
    chaos.arm("serve.oom", "raise", times=2)
    req = eng.submit(prompt, 4)
    eng.step()
    assert req.state == QUEUED and not req.done     # deferred, not failed
    assert chaos.fired("serve.oom")
    eng.step(); eng.step()                          # failpoint exhausted
    eng.run_until_idle()
    assert req.done and req.output_tokens == \
        _oracle_tokens(cfg, params, prompt, 4)


def test_chaos_serve_enqueue_surfaces_to_caller(tiny):
    cfg, params = tiny
    eng = ServingEngine(cfg, params, serving=SERVE_CFG)
    chaos.arm("serve.enqueue", "raise")
    with pytest.raises(chaos.ChaosError):
        eng.submit([1, 2, 3], 4)
    # the loop itself is unharmed
    eng.submit([1, 2, 3], 2)
    eng.run_until_idle()
    assert eng.stats["completed"] == 1


def test_scheduler_rejects_overlong_and_full_queue(tiny):
    cfg, params = tiny
    eng = ServingEngine(cfg, params,
                        serving=dict(SERVE_CFG, max_queue=1))
    with pytest.raises(ValueError, match="max_model_len"):
        eng.submit(list(range(1, 60)) * 3, 128)     # 177 + 128 > 128
    eng.submit([1, 2, 3], 2)
    with pytest.raises(RuntimeError, match="queue full"):
        eng.submit([4, 5, 6], 2)


def test_submit_rejects_request_bigger_than_whole_pool(tiny):
    """A lifetime budget beyond the pool could NEVER be admitted — under
    strict FIFO it would wedge the queue forever while the loop keeps
    heartbeating. submit() must reject it synchronously."""
    cfg, params = tiny
    eng = ServingEngine(cfg, params,
                        serving={"block_size": 16, "pool_blocks": 3,
                                 "max_batch": 2, "max_blocks_per_seq": 8,
                                 "prefix_cache": False})
    with pytest.raises(ValueError, match="pool has 2"):
        eng.submit(list(range(1, 40)), 16)          # needs 4 > 2 blocks
    # a fitting request still serves
    r = eng.submit([1, 2, 3], 2)
    eng.run_until_idle()
    assert r.done


# ---------------------------------------------------------------------------
# supervision + sampling + entry points
# ---------------------------------------------------------------------------

def test_serving_stamps_serve_heartbeat(tmp_path, tiny):
    import json
    from deepspeed_tpu.runtime.heartbeat import (PHASE_EXIT, PHASE_SERVE,
                                                 HeartbeatWriter,
                                                 heartbeat_path,
                                                 read_heartbeats)
    cfg, params = tiny
    hb = HeartbeatWriter(str(tmp_path), rank=0, min_interval=0.0,
                         refresh_interval=0.0)
    eng = ServingEngine(cfg, params, serving=SERVE_CFG, heartbeat=hb)
    eng.submit([1, 2, 3, 4], 3)
    eng.run_until_idle()
    eng.close()
    with open(heartbeat_path(str(tmp_path), 0), encoding="utf-8") as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    phases = [r["phase"] for r in recs]
    assert PHASE_SERVE in phases         # the loop was supervised
    assert read_heartbeats(str(tmp_path))[0]["phase"] == PHASE_EXIT
    # SERVE records carry queue/active/lanes load gauges (round 11)
    serve = [r for r in recs if r["phase"] == PHASE_SERVE]
    assert all(set(r["gauges"]) == {"queue", "active", "lanes"}
               for r in serve)
    assert any(r["gauges"]["active"] > 0 for r in serve)


def test_serving_context_manager_stamps_exit_and_health_reads_gauges(
        tmp_path, tiny, capsys):
    """Loop exit through the context manager stamps the EXIT terminal
    heartbeat, and `dstpu health` surfaces the SERVE gauges — a finished
    serving loop must read as a conclusion, never as silence."""
    from deepspeed_tpu.launcher.runner import health_main
    from deepspeed_tpu.runtime.heartbeat import (PHASE_EXIT,
                                                 HeartbeatWriter,
                                                 read_heartbeats)
    cfg, params = tiny
    hb = HeartbeatWriter(str(tmp_path), rank=0, min_interval=0.0,
                         refresh_interval=0.0)
    with ServingEngine(cfg, params, serving=SERVE_CFG, heartbeat=hb) as eng:
        eng.submit([5, 6, 7], 3)
        eng.run_until_idle()
        # still serving inside the block: latest record is SERVE w/ gauges
        rec = read_heartbeats(str(tmp_path))[0]
        assert rec["phase"] == "SERVE" and "gauges" in rec
        assert health_main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "GAUGES" in out and "lanes=4" in out
    assert read_heartbeats(str(tmp_path))[0]["phase"] == PHASE_EXIT
    assert health_main([str(tmp_path)]) == 0
    assert "clean exit" in capsys.readouterr().out


def test_scheduler_deadline_sheds_queued_with_timeout(tiny):
    """Engine-level satellite: a queued request past its deadline is shed
    with TIMEOUT at the next admission pass instead of waiting forever
    behind a too-big head (the strict-FIFO unbounded-wait edge); admitted
    requests are never shed."""
    import time as _time
    cfg, params = tiny
    eng = ServingEngine(cfg, params,
                        serving={"block_size": 16, "pool_blocks": 4,
                                 "max_batch": 1, "max_blocks_per_seq": 3,
                                 "prefix_cache": False})
    rng = np.random.default_rng(23)
    shed = []
    # head takes the lane and nearly the pool; the deadlined follower
    # can never be admitted behind it and must be shed, not starved
    head = eng.submit(list(rng.integers(1, 64, size=30)), 16,
                      deadline_s=30.0)          # admitted -> never shed
    late = eng.submit(list(rng.integers(1, 64, size=30)), 16,
                      deadline_s=0.01, on_finish=lambda r: shed.append(r))
    eng.step()
    assert head.state in ("PREFILL", "RUNNING")
    _time.sleep(0.03)
    eng.step()                                   # admission pass sheds
    assert late.state == "TIMEOUT" and late.done
    assert "deadline" in late.error and shed == [late]
    assert eng.stats["timeout"] == 1
    eng.run_until_idle()
    assert head.state == "FINISHED"              # deadline was queue-wait only
    assert eng.scheduler.timed_out == 1


def test_serving_eos_and_temperature_lanes(tiny):
    cfg, params = tiny
    eng = ServingEngine(cfg, params, serving=SERVE_CFG)
    greedy = _oracle_tokens(cfg, params, [5, 6, 7, 8], 6)
    # eos cut: force eos at the first greedy token -> finishes after 1
    r_eos = eng.submit([5, 6, 7, 8], 6, eos_token_id=greedy[0])
    # a temperature lane rides the same compiled step
    r_temp = eng.submit([9, 10, 11], 6, temperature=0.8)
    eng.run_until_idle()
    assert r_eos.output_tokens == [greedy[0]]
    assert len(r_temp.output_tokens) == 6
    with pytest.raises(NotImplementedError):
        eng.submit([1, 2], 4, top_k=5)


def test_init_inference_serve_entry(tiny):
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer
    cfg, params = tiny
    module = Transformer(cfg)
    eng = deepspeed_tpu.init_inference(
        module, {"dtype": "float32",
                 "serving": {"block_size": 16, "pool_blocks": 32,
                             "max_batch": 2, "max_blocks_per_seq": 8}},
        model_parameters=params)
    srv = eng.serve()
    out = srv.generate_batch([[3, 1, 4, 1, 5]], max_new_tokens=4)
    assert out[0] == _oracle_tokens(cfg, params, [3, 1, 4, 1, 5], 4)


def test_inference_bench_poisson_line(capsys):
    """The Poisson load leg drives the serving loop and prints the
    machine-readable p50/p99 line (acceptance criterion)."""
    import json
    from deepspeed_tpu.benchmarks.inference_bench import run_poisson
    row = run_poisson(
        "gpt2-tiny", rate=200.0, num_requests=5, prompt_len=24,
        new_tokens=4,
        serving={"block_size": 16, "pool_blocks": 32, "max_batch": 4,
                 "max_blocks_per_seq": 8},
        model_kwargs=dict(hidden_size=32, num_layers=2, num_heads=2,
                          vocab_size=64, attention_impl="reference"))
    line = [ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("inference_bench poisson: ")]
    assert line, "machine-readable poisson line missing"
    parsed = json.loads(line[0].split("inference_bench poisson: ", 1)[1])
    for key in ("p50_s", "p99_s", "tokens_per_s_per_chip", "rate"):
        assert key in parsed and parsed[key] == row[key]
    assert 0 < row["p50_s"] <= row["p99_s"]


@pytest.mark.slow
def test_serving_arch_matrix_token_exact():
    """Heavier matrix: ALiBi+softcap (Gemma/BLOOM-class), sliding window,
    GQA+rotary+RMSNorm — each serves token-exact vs sequential
    generate()."""
    archs = [
        dict(pos_embed="alibi", attn_softcap=20.0, final_logit_softcap=15.0,
             norm="layernorm"),
        dict(layer_windows=(32, 32), pos_embed="rotary"),
        dict(pos_embed="rotary", norm="rmsnorm", gated_mlp=True,
             activation="silu", num_kv_heads=2, tie_embeddings=False),
    ]
    rng = np.random.default_rng(13)
    for kw in archs:
        model, cfg = build_model("gpt2-tiny", hidden_size=32, num_layers=2,
                                 num_heads=4, vocab_size=64, max_seq_len=128,
                                 attention_impl="reference",
                                 dtype=jnp.float32, **kw)
        ids = np.zeros((1, 8), np.int32)
        params = model.init(jax.random.PRNGKey(1),
                            {"input_ids": ids})["params"]
        eng = ServingEngine(cfg, params,
                            serving={"block_size": 16, "pool_blocks": 32,
                                     "max_batch": 3, "max_blocks_per_seq": 8})
        prompts = [list(rng.integers(1, 64, size=n)) for n in (6, 13, 21)]
        reqs = [eng.submit(p, 5) for p in prompts]
        eng.run_until_idle()
        for p, r in zip(prompts, reqs):
            assert r.output_tokens == _oracle_tokens(cfg, params, p, 5), \
                f"arch {kw} diverged"


# ---------------------------------------------------------------------------
# round 12: chunked prefill, per-lane top-k/top-p, int8 paged KV pool
# ---------------------------------------------------------------------------

# tier-2 (round-17 budget sweep, ~10s): the cheaper tier-1 cousins are
# test_disagg.test_chunked_prefill_fairness_no_stall_beyond_one_chunk and
# test_disagg.test_disagg_fleet_requeue_carries_chunk_progress (same
# chunk machinery under fault); scripts/tier2.sh runs this compile-bound pin
@pytest.mark.slow
def test_chunked_prefill_token_exact_and_compile_bound(tiny):
    """A non-block-aligned chunk size is token-exact vs whole prefill,
    and the chunk machinery adds at most ONE extra prefill bucket (all
    full chunks share the chunk's block-rounded width; the final partial
    chunk lands in an existing bucket here)."""
    cfg, params = tiny
    rng = np.random.default_rng(23)
    prompts = [list(rng.integers(1, 64, size=n)) for n in (35, 50, 7)]
    whole = ServingEngine(cfg, params, serving=SERVE_CFG)
    outs_whole = whole.generate_batch(prompts, max_new_tokens=5)
    chunked = ServingEngine(cfg, params,
                            serving=dict(SERVE_CFG,
                                         prefill_chunk_tokens=10))
    outs_chunked = chunked.generate_batch(prompts, max_new_tokens=5)
    assert outs_chunked == outs_whole
    for p, o in zip(prompts, outs_whole):
        assert o == _oracle_tokens(cfg, params, p, 5)
    cache_size = getattr(chunked._prefill_fn, "_cache_size", None)
    if cache_size is not None:
        # chunks of 10 bucket to 16: every call (full chunks AND the
        # <=10-token finals) is the same [1, 16] program. The bound is
        # <= 2, not == 1: the very first prefill call can specialize
        # separately (fresh jnp.zeros pools vs donated committed pools —
        # e.g. when an earlier test left a global mesh set), which is a
        # one-time sharding entry, not a per-bucket retrace. Whole
        # prefill pays one bucket PER suffix width (48, 64, 16 here), so
        # chunking must strictly reduce specializations.
        assert cache_size() <= 2
        whole_size = getattr(whole._prefill_fn, "_cache_size")()
        assert cache_size() < whole_size


def test_lane_topk_topp_parity_with_generate_sample():
    """The vectorized per-lane filter + categorical at one key is
    token-identical to models.generation._sample at the same key, per
    lane, across greedy/top-k/top-p/combined lanes (the satellite's
    parity contract)."""
    from deepspeed_tpu.models.generation import _sample
    from deepspeed_tpu.serving.engine import lane_topk_topp
    rng = np.random.default_rng(0)
    lanes = [(0.7, 5, None), (1.0, None, 0.9), (0.5, 8, 0.5),
             (1.3, None, None), (0.9, 1, None), (0.8, 3, 0.95)]
    logits = jnp.asarray(rng.normal(size=(len(lanes), 64)),
                         jnp.float32)
    temps = jnp.asarray([t for t, _, _ in lanes], jnp.float32)
    tks = jnp.asarray([k or 0 for _, k, _ in lanes], jnp.int32)
    tps = jnp.asarray([p if p is not None else 1.0 for _, _, p in lanes],
                      jnp.float32)
    key = jax.random.PRNGKey(42)
    filtered = lane_topk_topp(logits / temps[:, None], tks, tps)
    for b, (t, k, p) in enumerate(lanes):
        ref = int(np.asarray(_sample(logits[b:b + 1], key, t, k, p))[0])
        got = int(np.asarray(jax.random.categorical(
            key, filtered[b:b + 1], axis=-1))[0])
        assert got == ref, f"lane {b} ({t}, {k}, {p}) diverged"


def test_sampling_filters_guard_and_greedy_invariance(tiny):
    """top_k/top_p raise without serving.sampling_filters (off by
    default: the nucleus filter puts a sort in the decode step); with the
    flag on, greedy lanes stay oracle-exact next to filtered lanes and
    the decode step still compiles once."""
    cfg, params = tiny
    eng_off = ServingEngine(cfg, params, serving=SERVE_CFG)
    with pytest.raises(NotImplementedError):
        eng_off.submit([1, 2, 3], 4, top_k=5)
    eng = ServingEngine(cfg, params,
                        serving=dict(SERVE_CFG, sampling_filters=True))
    p = [5, 9, 2, 33, 7]
    r_greedy = eng.submit(p, 5)
    r_filt = eng.submit(p, 5, temperature=0.8, top_k=4, top_p=0.9)
    eng.run_until_idle()
    assert r_greedy.output_tokens == _oracle_tokens(cfg, params, p, 5)
    assert len(r_filt.output_tokens) == 5
    cache_size = getattr(eng._decode_fn, "_cache_size", None)
    if cache_size is not None:
        assert cache_size() == 1


def test_int8_kv_pool_parity_jnp_and_kernel(tiny):
    """The quantized pool tier (serving.kv_cache_dtype='int8'):
    quantize-on-write, dequantize IN-kernel (round 17 — the round-12
    construction guard is gone). Greedy outputs match the f32 oracle
    within the int8 error bound (token-equal on this fixture — f32
    compute, real logit gaps) on BOTH decode paths: the jnp
    gather-then-dequant reference AND the Pallas kernel's int8 tier
    (interpret=True forces it on CPU), which must also agree with each
    other token-for-token."""
    cfg, params = tiny
    rng = np.random.default_rng(29)
    prompts = [list(rng.integers(1, 64, size=n)) for n in (5, 21)]
    eng = ServingEngine(cfg, params,
                        serving=dict(SERVE_CFG, kv_cache_dtype="int8"))
    assert eng.pools["k"].dtype == jnp.int8
    assert eng.pools["k_scale"].dtype == jnp.float32
    outs = eng.generate_batch(prompts, max_new_tokens=6)
    for p, o in zip(prompts, outs):
        assert o == _oracle_tokens(cfg, params, p, 6), \
            "int8 pool beyond the quantization error bound"
    # the Pallas int8 tier: same pools, dequant in-kernel
    eng_k = ServingEngine(cfg, params,
                          serving=dict(SERVE_CFG, kv_cache_dtype="int8"),
                          interpret=True)
    outs_k = eng_k.generate_batch(prompts, max_new_tokens=6)
    assert outs_k == outs, "in-kernel dequant diverged from the jnp path"


def test_int8_weight_only_decode_parity(tiny):
    """serving.weight_dtype='int8' (round 17): dense kernels pack ONCE to
    blockwise int8 + per-256-element f32 scales and every decode matmul
    rides the quant path. Greedy outputs are token-equal with the
    unquantized oracle on this fixture (f32 compute, real logit gaps
    exceed the <=absmax/127 weight error), the packed leaves are
    genuinely int8, and the kernel (interpret) and jnp reference paths
    agree token-for-token."""
    cfg, params = tiny
    rng = np.random.default_rng(31)
    prompts = [list(rng.integers(1, 64, size=n)) for n in (4, 18)]
    eng = ServingEngine(cfg, params,
                        serving=dict(SERVE_CFG, weight_dtype="int8"))
    blk = eng.params["blocks"]
    assert blk["attn_qkv"]["kernel"].dtype == jnp.int8
    assert blk["attn_qkv"]["kernel_qscale"].dtype == jnp.float32
    outs = eng.generate_batch(prompts, max_new_tokens=6)
    for p, o in zip(prompts, outs):
        assert o == _oracle_tokens(cfg, params, p, 6), \
            "int8 weight-only decode beyond the quantization error bound"
    eng_k = ServingEngine(cfg, params,
                          serving=dict(SERVE_CFG, weight_dtype="int8",
                                       kv_cache_dtype="int8"),
                          interpret=True)
    outs_k = eng_k.generate_batch(prompts, max_new_tokens=6)
    assert outs_k == outs, "quantized kernels diverged from the jnp path"
    with pytest.raises(ValueError):
        ServingEngine(cfg, params,
                      serving=dict(SERVE_CFG, weight_dtype="int4"))
