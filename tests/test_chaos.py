"""Crash-safety suite: prove the stack is restartable from ANY crash point.

The fault-injection matrix arms each named failpoint in the save path
(`deepspeed_tpu/testing/chaos.py`) against a REAL engine save and then
demonstrates that a fresh load resumes from the newest intact tag with
step/optimizer/lr-scheduler state intact — plus subprocess tests that
actually kill the process mid-write (os._exit, no cleanup) and drive the
SIGTERM preemption handler end-to-end.

Budget note: engines are shared (module fixtures, one trainer+resumer
pair for the whole matrix) — engine init + first-step compile is the
dominant cost and tier-1 runs under a hard wall clock.

Run standalone via ``scripts/chaos.sh``.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import textwrap
import time

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.checkpoint import AsyncCheckpointEngine
from deepspeed_tpu.elasticity import PREEMPTION_EXIT_CODE
from deepspeed_tpu.runtime import checkpointing as ck
from deepspeed_tpu.runtime.engine import NonFiniteError
from deepspeed_tpu.testing import chaos

from util import SimpleModel, random_batch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = {"train_batch_size": 8,
       "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
       "scheduler": {"type": "WarmupLR",
                     "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-3,
                                "warmup_num_steps": 10}}}


def _engine(extra=None):
    cfg = {**CFG, **(extra or {})}
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                               example_batch=random_batch(8))
    return engine


def _step(engine):
    return int(jax.device_get(engine.state.step))


@pytest.fixture(scope="module")
def shared():
    """One (trainer, resumer) engine pair + a prebuilt two-tag checkpoint
    template for the whole module — engine init dominates wall clock."""
    trainer = _engine()
    resumer = _engine()
    template = os.path.join(ck_tmp := os.environ.get("TMPDIR", "/tmp"),
                            f"dstpu_chaos_template_{os.getpid()}")
    shutil.rmtree(template, ignore_errors=True)
    builder = _engine()
    builder.train_batch(random_batch(8, seed=0))
    builder.save_checkpoint(template)               # global_step1
    builder.train_batch(random_batch(8, seed=1))
    builder.save_checkpoint(template)               # global_step2
    yield {"trainer": trainer, "resumer": resumer, "template": template}
    shutil.rmtree(template, ignore_errors=True)


def _clone_template(shared, tmp_path):
    d = str(tmp_path / "ck")
    shutil.copytree(shared["template"], d)
    return d


# ---------------------------------------------------------------- failpoints

def test_chaos_spec_parsing_and_reset():
    fps = chaos.parse_spec("a:raise;b:kill:skip=3;c:raise:times=2:skip=1")
    assert set(fps) == {"a", "b", "c"}
    assert fps["b"].mode == "kill" and fps["b"].skip == 3
    assert fps["c"].times == 2 and fps["c"].skip == 1
    with pytest.raises(ValueError):
        chaos.parse_spec("nocolon")
    with pytest.raises(ValueError):
        chaos.parse_spec("a:explode")
    chaos.arm("x", "raise", skip=1)
    chaos.failpoint("x")                     # skipped hit
    with pytest.raises(chaos.ChaosError):
        chaos.failpoint("x")
    chaos.failpoint("x")                     # times=1 exhausted: passes
    assert chaos.fired("x") == ["x"]
    chaos.reset_for_tests()
    chaos.failpoint("x")                     # disarmed: no-op
    assert chaos.fired() == []


def test_chaos_run_supervision_modes():
    """Round-4 spec surface: kill exit-code override, sleep delay, hang,
    sigterm (the firing paths for hang/sigterm are exercised by the
    subprocess tests in test_supervisor.py — in-process they would wedge
    or kill the suite)."""
    fps = chaos.parse_spec("a:kill:code=114;b:sleep:ms=50;c:hang;d:sigterm")
    assert fps["a"].mode == "kill" and fps["a"].code == 114
    assert fps["b"].mode == "sleep" and fps["b"].ms == 50
    assert fps["c"].mode == "hang" and fps["d"].mode == "sigterm"
    assert chaos.parse_spec("x:kill")["x"].code == chaos.KILL_EXIT_CODE
    with pytest.raises(ValueError):
        chaos.parse_spec("a:kill:bogus=1")
    # sleep mode: fires, delays, then CONTINUES (no exception)
    chaos.arm("s", "sleep", ms=40)
    t0 = time.monotonic()
    chaos.failpoint("s")
    assert time.monotonic() - t0 >= 0.03
    assert chaos.fired("s") == ["s"]


def test_chaos_intermittent_slowness_jitter_semantics():
    """Round-15 spec surface (the straggler failpoints): ``times=0`` =
    unlimited fires; ``every=N`` fires the first post-skip traversal and
    every Nth after it; ``p=P`` fires P% of eligible traversals on the
    deterministic accumulator pattern — degraded, not dead, and exactly
    reproducible."""
    fps = chaos.parse_spec("a:sleep:ms=5:every=3:times=0;b:sleep:p=40")
    assert fps["a"].every == 3 and fps["a"].times == 0
    assert fps["b"].p == 40
    with pytest.raises(ValueError):
        chaos.parse_spec("a:sleep:p=150")          # not a percentage
    with pytest.raises(ValueError):
        chaos.parse_spec("a:sleep:every=x")        # options stay ints

    # every=3, times=0: hits 1, 4, 7 fire over 7 traversals — forever
    chaos.arm("e", "sleep", ms=0, every=3, times=0)
    for _ in range(7):
        chaos.failpoint("e")
    assert len(chaos.fired("e")) == 3

    # p=50: evenly spaced half of the traversals (2, 4, 6, 8, 10)
    chaos.reset_for_tests()
    chaos.arm("p", "sleep", ms=0, p=50, times=0)
    for _ in range(10):
        chaos.failpoint("p")
    assert len(chaos.fired("p")) == 5

    # skip shifts the eligible window; the pattern stays deterministic
    chaos.reset_for_tests()
    chaos.arm("sk", "sleep", ms=0, every=2, skip=1, times=0)
    for _ in range(5):
        chaos.failpoint("sk")                      # eligible hits 2, 4
    assert len(chaos.fired("sk")) == 2

    # a positive times= still caps the budget under jitter
    chaos.reset_for_tests()
    chaos.arm("t", "sleep", ms=0, every=2, times=1)
    for _ in range(6):
        chaos.failpoint("t")
    assert len(chaos.fired("t")) == 1

    # flag mode rides the same accounting (query-style slowness knobs)
    chaos.reset_for_tests()
    chaos.arm("f", "flag", factor=7, every=2, times=0)
    got = [chaos.flag("f") for _ in range(4)]
    assert got == [7, None, 7, None]


# ------------------------------------------------- crash-at-every-stage matrix

#: every named failpoint a save traverses, in execution order
SAVE_FAILPOINTS = ["ckpt.write", "ckpt.meta", "ckpt.digest", "ckpt.marker",
                   "ckpt.rename", "ckpt.latest"]


def test_crash_at_every_failpoint_then_resume(shared, tmp_path):
    """For each failpoint: kill a real save there, then prove a fresh load
    resumes from the newest intact tag with step/optimizer/lr-scheduler
    state intact, and that `latest` never references a tag missing its
    completion marker. One trainer + one resumer engine for all stages."""
    d = str(tmp_path / "ck")
    e, r = shared["trainer"], shared["resumer"]
    e.train_batch(random_batch(8, seed=0))
    e.save_checkpoint(d)
    done = _step(e)                                     # newest intact step
    for fp in SAVE_FAILPOINTS:
        e.train_batch(random_batch(8, seed=done))
        n0 = len(chaos.fired(fp))
        chaos.arm(fp, "raise", times=100)
        with pytest.raises(IOError):
            e.save_checkpoint(d)
        chaos.disarm()
        assert len(chaos.fired(fp)) > n0, fp

        # invariant: whatever `latest` references is marker-complete
        latest = ck.get_latest_tag(d)
        assert latest is not None, fp
        assert os.path.exists(os.path.join(d, latest, ck.CKPT_META_FILE)), fp

        _, client = r.load_checkpoint(d)
        # the completion marker is written BEFORE rename/latest: a crash
        # at those two stages leaves the new tag fully durable (resolve
        # finishes the interrupted publish / repairs the pointer), while
        # earlier stages roll back to the previous tag
        expected = done + 1 if fp in ("ckpt.rename", "ckpt.latest") else done
        assert _step(r) == expected, fp
        assert client["global_steps"] == expected, fp
        assert r.lr_scheduler.state_dict()["last_step"] == expected, fp

        # a clean save of the same tag succeeds (no poisoning; for the
        # ckpt.latest case this also exercises the atomic tag OVERWRITE)
        e.save_checkpoint(d)
        done += 1
        assert ck.get_latest_tag(d) == f"global_step{done}", fp
        assert ck.verify_tag(os.path.join(d, f"global_step{done}")) is None, fp

    # optimizer/params state intact end-to-end: resume the final tag and
    # step both engines on one fresh batch — losses must match exactly
    r.load_checkpoint(d)
    b = random_batch(8, seed=77)
    assert float(e.train_batch(b)["loss"]) == float(r.train_batch(b)["loss"])


def test_quarantined_staging_left_for_forensics(shared, tmp_path):
    d = str(tmp_path / "ck")
    e = shared["trainer"]
    e.train_batch(random_batch(8, seed=0))
    chaos.arm("ckpt.write", "raise", times=100)
    with pytest.raises(IOError):
        e.save_checkpoint(d)
    chaos.disarm()
    assert any(n.endswith(ck.QUARANTINE_SUFFIX) for n in os.listdir(d))
    # quarantined debris is not a tag: listing and resolution ignore it
    assert ck.list_tags(d) == []
    with pytest.raises(FileNotFoundError):
        ck.resolve_load_tag(d)


# ------------------------------------------------------ corruption + rollback

def test_truncated_tag_rolls_back(shared, tmp_path):
    d = _clone_template(shared, tmp_path)
    npz = os.path.join(d, "global_step2", "model_states.npz")
    with open(npz, "r+b") as f:
        f.truncate(os.path.getsize(npz) // 2)
    r = shared["resumer"]
    r.load_checkpoint(d)
    assert _step(r) == 1
    # latest was repaired to the tag actually resumed from
    assert ck.get_latest_tag(d) == "global_step1"
    # the corrupt tag stays on disk for forensics
    assert os.path.isdir(os.path.join(d, "global_step2"))


def test_bitflip_detected_by_digest(shared, tmp_path):
    """Same size, flipped bytes — only the sha256 can catch this."""
    d = _clone_template(shared, tmp_path)
    npz = os.path.join(d, "global_step2", "model_states.npz")
    size = os.path.getsize(npz)
    with open(npz, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xff" * 16)
    assert "digest mismatch" in ck.verify_tag(os.path.join(d, "global_step2"))
    r = shared["resumer"]
    r.load_checkpoint(d)
    assert _step(r) == 1


def test_explicit_corrupt_tag_raises(shared, tmp_path):
    """tag= names user intent — substituting another checkpoint would be
    wrong, so an explicit corrupt tag raises instead of rolling back."""
    d = _clone_template(shared, tmp_path)
    npz = os.path.join(d, "global_step2", "model_states.npz")
    with open(npz, "r+b") as f:
        f.truncate(10)
    with pytest.raises(ck.CheckpointIntegrityError, match="global_step2"):
        shared["resumer"].load_checkpoint(d, tag="global_step2")


def test_markerless_tag_without_data_skipped(shared, tmp_path):
    """A tag dir with meta.json but no marker AND no data is debris, not a
    legacy checkpoint — rollback must skip it."""
    d = _clone_template(shared, tmp_path)
    bogus = os.path.join(d, "global_step9")
    os.makedirs(bogus)
    with open(os.path.join(bogus, "meta.json"), "w") as f:
        json.dump({"step": 9}, f)
    ck.write_latest(d, "global_step9")
    r = shared["resumer"]
    r.load_checkpoint(d)
    assert _step(r) == 2
    assert ck.get_latest_tag(d) == "global_step2"


def test_legacy_markerless_tag_still_loads(shared, tmp_path):
    """Pre-marker checkpoints (data + meta.json, no ckpt_meta.json) keep
    loading — crash partials can't masquerade as them because partials
    only ever live in .tmp/.failed dirs."""
    d = _clone_template(shared, tmp_path)
    os.remove(os.path.join(d, "global_step2", ck.CKPT_META_FILE))
    assert ck.verify_tag(os.path.join(d, "global_step2")) is None
    r = shared["resumer"]
    r.load_checkpoint(d)
    assert _step(r) == 2


# ------------------------------------------------------------- retention GC

def _fake_tag(d, step):
    p = os.path.join(d, f"global_step{step}")
    os.makedirs(p)
    with open(os.path.join(p, "meta.json"), "w") as f:
        json.dump({"step": step}, f)


def test_retention_keep_last_and_every(tmp_path):
    d = str(tmp_path / "ck")
    os.makedirs(d)
    for s in range(1, 8):
        _fake_tag(d, s)
    ck.write_latest(d, "global_step7")
    removed = ck.prune_checkpoints(d, keep_last=2, keep_every=3)
    # keep: newest 2 {6,7} + every 3rd {3,6} + latest {7}
    assert sorted(ck.list_tags(d)) == ["global_step3", "global_step6",
                                       "global_step7"]
    assert sorted(removed) == ["global_step1", "global_step2",
                               "global_step4", "global_step5"]
    assert ck.prune_checkpoints(d, keep_last=0) == []      # retention off


def test_engine_retention_wired_through_config(shared, tmp_path):
    """checkpoint.keep_last flows from the ds_config through every save."""
    d = str(tmp_path / "ck")
    e = shared["trainer"]
    e.config.checkpoint.keep_last = 2
    try:
        base = _step(e)
        for i in range(3):
            e.train_batch(random_batch(8, seed=i))
            e.save_checkpoint(d)
        assert sorted(ck.list_tags(d)) == [f"global_step{base + 2}",
                                           f"global_step{base + 3}"]
    finally:
        e.config.checkpoint.keep_last = None


# ------------------------------------------------- async writer: retry/failure

def test_async_retry_recovers_from_transient_io(tmp_path):
    eng = AsyncCheckpointEngine(max_retries=3, retry_backoff=0.01)
    path = str(tmp_path / "x.npz")
    chaos.arm("ckpt.write", "raise", times=2)      # fails twice, then clean
    eng.create("t1")
    eng.save({"a": np.zeros(4, np.float32)}, path)
    res = eng.commit("t1")
    assert res and res.ok
    assert len(chaos.fired("ckpt.write")) == 2     # both retries exercised
    assert np.array_equal(ck.read_flat_npz(path)["a"], np.zeros(4))
    eng.close()


def test_async_retries_are_bounded_and_commit_names_the_path(tmp_path):
    eng = AsyncCheckpointEngine(max_retries=2, retry_backoff=0.01)
    path = str(tmp_path / "y.npz")
    chaos.arm("ckpt.write", "raise", times=100)
    eng.create("t1")
    eng.save({"a": np.zeros(4, np.float32)}, path)
    res = eng.commit("t1")
    chaos.disarm()
    assert not res
    assert res.failed_paths() == [path]
    assert "ChaosError" in res.failures[0][1]
    assert len(chaos.fired("ckpt.write")) == 3     # 1 try + 2 retries
    eng.close()


def test_async_failure_does_not_poison_next_tag(tmp_path):
    """A failed tag is quarantined; the NEXT create() starts a clean
    generation whose writes run even though the previous ones failed."""
    eng = AsyncCheckpointEngine(max_retries=0, retry_backoff=0.01)
    stage = str(tmp_path / "tag1.tmp")
    os.makedirs(stage)
    chaos.arm("ckpt.write", "raise", times=1)
    eng.create("tag1", stage_dir=stage)
    eng.save({"a": np.zeros(4, np.float32)}, os.path.join(stage, "m.npz"))
    eng.run(lambda: open(str(tmp_path / "latest1"), "w").write("tag1"),
            label="latest1")
    res = eng.commit("tag1")
    assert not res
    # the ordered-behind job was skipped, not run against corrupt data
    assert not os.path.exists(str(tmp_path / "latest1"))
    # and the staging dir got quarantined
    assert os.path.isdir(str(tmp_path / "tag1") + ck.QUARANTINE_SUFFIX)
    # next generation is clean
    eng.create("tag2")
    path2 = str(tmp_path / "z.npz")
    eng.save({"b": np.ones(4, np.float32)}, path2)
    assert eng.commit("tag2")
    assert os.path.exists(path2)
    eng.close()


def test_async_close_explicit_and_idempotent(tmp_path):
    eng = AsyncCheckpointEngine()
    path = str(tmp_path / "c.npz")
    eng.save({"a": np.zeros(2, np.float32)}, path)
    res = eng.close()                               # drains pending writes
    assert res.ok and os.path.exists(path)
    assert eng.close().ok                           # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        eng.run(lambda: None)


def test_engine_async_save_failure_then_clean_save(tmp_path):
    """End-to-end: an async save whose writes fail must leave `latest`
    alone and not block the following save."""
    d = str(tmp_path / "ck")
    e = _engine({"checkpoint": {"async_save": True,
                                "write_retries": 0}})
    e.train_batch(random_batch(8, seed=0))
    e.save_checkpoint(d)
    assert e.wait_for_checkpoints()
    assert ck.get_latest_tag(d) == "global_step1"

    e.train_batch(random_batch(8, seed=1))
    chaos.arm("ckpt.write", "raise", times=100)
    e.save_checkpoint(d)
    res = e.wait_for_checkpoints()
    chaos.disarm()
    assert not res and res.failed_paths()
    assert ck.get_latest_tag(d) == "global_step1"

    e.train_batch(random_batch(8, seed=2))
    e.save_checkpoint(d)
    assert e.wait_for_checkpoints()
    assert ck.get_latest_tag(d) == "global_step3"
    assert e.close()


# ------------------------------------------ emergency-save / async overlap

def _install_handler_scoped(e, d, rcs):
    """install_preemption_handler swaps the PROCESS signal handlers; an
    in-process test must restore them or later tests inherit the hook."""
    import contextlib

    @contextlib.contextmanager
    def scoped():
        prev_term = signal.getsignal(signal.SIGTERM)
        prev_int = signal.getsignal(signal.SIGINT)
        try:
            yield e.install_preemption_handler(d, grace_secs=60,
                                               exit_fn=rcs.append)
        finally:
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)
    return scoped()


def test_emergency_save_skips_tag_already_drained(tmp_path):
    """ROADMAP gap (round-4): SIGTERM lands while the async writer still
    has THIS step's save in flight. The grace-window drain publishes it;
    the emergency save must NOT rewrite the same tag — the rewrite burns
    grace seconds re-serializing the model, and dying mid-rewrite leaves
    staging debris shadowing the drained publish."""
    d = str(tmp_path / "ck")
    e = _engine({"checkpoint": {"async_save": True}})
    e.train_batch(random_batch(8, seed=0))
    chaos.arm("ckpt.write", "sleep", ms=250, times=2)
    e.save_checkpoint(d)                      # async: writes in flight
    rcs = []
    with _install_handler_scoped(e, d, rcs) as handler:
        handler()                             # the preemption "signal"
    assert rcs == [PREEMPTION_EXIT_CODE]
    # exactly the async save's two data writes hit the writer: the
    # emergency path drained and SKIPPED, it did not write again
    assert chaos._armed["ckpt.write"].hits == 2
    assert ck.get_latest_tag(d) == "global_step1"
    assert ck.verify_tag(os.path.join(d, "global_step1")) is None
    assert [n for n in os.listdir(d)
            if n.endswith((".tmp", ck.QUARANTINE_SUFFIX))] == []
    assert e.close()


def test_emergency_save_writes_fresh_tag_when_steps_advanced(tmp_path):
    """The skip is exact: once training advanced past the in-flight tag,
    the emergency save must still write the NEW step."""
    d = str(tmp_path / "ck")
    e = _engine({"checkpoint": {"async_save": True}})
    e.train_batch(random_batch(8, seed=0))
    e.save_checkpoint(d)                      # global_step1 (async)
    e.train_batch(random_batch(8, seed=1))    # now at step 2, unsaved
    rcs = []
    with _install_handler_scoped(e, d, rcs) as handler:
        handler()
    assert rcs == [PREEMPTION_EXIT_CODE]
    assert ck.get_latest_tag(d) == "global_step2"
    assert ck.verify_tag(os.path.join(d, "global_step2")) is None
    r = _engine()
    _, client = r.load_checkpoint(d)
    assert client.get("preempted") is True
    assert client["global_steps"] == 2
    assert e.close()


# ----------------------------------------------------- subprocess crash tests

def _run_child(code, tmp_path, env_extra=None, timeout=300):
    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join([REPO, os.path.join(REPO, "tests")]),
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    env.pop("DSTPU_CHAOS", None)
    env.update(env_extra or {})
    script = tmp_path / "child.py"
    script.write_text(textwrap.dedent(code))
    return subprocess.Popen([sys.executable, str(script)], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True), timeout


CHILD_KILL = """
import os
import jax
jax.config.update("jax_platforms", "cpu")
import deepspeed_tpu as ds
from util import SimpleModel, random_batch

d = os.environ["CKDIR"]
cfg = {"train_batch_size": 8,
       "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
e, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                      example_batch=random_batch(8))
for i in range(2):
    e.train_batch(random_batch(8, seed=i))
e.save_checkpoint(d)                      # intact global_step2 (2 write hits)
e.train_batch(random_batch(8, seed=2))
e.save_checkpoint(d)                      # DSTPU_CHAOS kills this one
raise SystemExit(99)                      # must never get here
"""


def test_kill_mid_write_subprocess_resume(shared, tmp_path):
    """A real process death (os._exit, no flushes) in the middle of a data
    write: the parent then resumes from the intact tag."""
    d = str(tmp_path / "ck")
    # ckpt.write fires once per npz file; save 1 hits it twice (model +
    # optim), so skip=2 targets save 2's model write — mid-zip, after the
    # first array
    proc, timeout = _run_child(
        CHILD_KILL, tmp_path,
        env_extra={"CKDIR": d, "DSTPU_CHAOS": "ckpt.write:kill:skip=2"})
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == chaos.KILL_EXIT_CODE, (proc.returncode, err[-1500:])

    # crash debris: a staging dir, never a published tag
    assert os.path.isdir(os.path.join(d, "global_step3.tmp"))
    assert ck.list_tags(d) == ["global_step2"]
    assert ck.get_latest_tag(d) == "global_step2"

    r = shared["resumer"]
    r.load_checkpoint(d)
    assert _step(r) == 2
    # the stale staging dir does not block a new save of the same tag
    r.train_batch(random_batch(8, seed=2))
    r.save_checkpoint(d)
    assert ck.get_latest_tag(d) == "global_step3"
    assert ck.verify_tag(os.path.join(d, "global_step3")) is None


CHILD_PREEMPT = """
import os, time
import jax
jax.config.update("jax_platforms", "cpu")
import deepspeed_tpu as ds
from util import SimpleModel, random_batch

d = os.environ["CKDIR"]
cfg = {"train_batch_size": 8,
       "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
e, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                      example_batch=random_batch(8))
for i in range(2):
    e.train_batch(random_batch(8, seed=i))
e.install_preemption_handler(d, grace_secs=60)
open(os.environ["READY"], "w").write("ready")
for i in range(2, 10000):
    e.train_batch(random_batch(8, seed=i))
    time.sleep(0.01)
"""


def test_sigterm_emergency_save_roundtrip(shared, tmp_path):
    """SIGTERM mid-training: the handler checkpoints synchronously within
    the grace window and exits with the preemption rc; a fresh load
    resumes from the emergency tag."""
    d = str(tmp_path / "ck")
    ready = str(tmp_path / "ready")
    proc, timeout = _run_child(CHILD_PREEMPT, tmp_path,
                               env_extra={"CKDIR": d, "READY": ready})
    deadline = time.time() + timeout
    try:
        while not os.path.exists(ready):
            assert proc.poll() is None, proc.communicate()[1][-1500:]
            assert time.time() < deadline, "child never became ready"
            time.sleep(0.1)
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == PREEMPTION_EXIT_CODE, (proc.returncode,
                                                     err[-1500:])
    latest = ck.get_latest_tag(d)
    assert latest is not None
    assert ck.verify_tag(os.path.join(d, latest)) is None
    r = shared["resumer"]
    _, client = r.load_checkpoint(d)
    assert client.get("preempted") is True
    # the signal may land between the compiled step and the host-side
    # global_steps increment, so the two counters can skew by one — the
    # snapshot is still self-consistent and resumable
    assert _step(r) >= 2
    assert abs(_step(r) - client["global_steps"]) <= 1
    # resumed state trains on
    assert np.isfinite(float(r.train_batch(random_batch(8, seed=5))["loss"]))


# ------------------------------------------------------------ non-finite guard

def _nan_batch(seed=0):
    b = random_batch(8, seed=seed)
    b["x"] = b["x"].copy()
    b["x"][0, 0] = np.nan
    return b


def test_nonfinite_step_skipped_counted_and_checkpointed(shared, tmp_path):
    """bf16-style runs (no loss scaler): a nan batch must not touch params
    — the in-jit skip counts it, and the streak survives a checkpoint."""
    d = str(tmp_path / "ck")
    e = shared["trainer"]
    e.train_batch(random_batch(8, seed=0))
    skipped0 = e.skipped_steps
    before = {k: np.asarray(v).copy() for k, v in e.module_state_dict().items()}
    e.train_batch(_nan_batch())
    after = e.module_state_dict()
    for k in before:
        np.testing.assert_array_equal(before[k], np.asarray(after[k]), k)
    assert e.skipped_steps == skipped0 + 1
    assert int(jax.device_get(e.state.nonfinite_streak)) == 1
    e.save_checkpoint(d)
    r = shared["resumer"]
    r.load_checkpoint(d)
    assert int(jax.device_get(r.state.nonfinite_streak)) == 1
    assert r.skipped_steps == skipped0 + 1
    # a finite step resets the streak
    e.train_batch(random_batch(8, seed=1))
    assert int(jax.device_get(e.state.nonfinite_streak)) == 0
    assert e.skipped_steps == skipped0 + 1


def test_nonfinite_guard_aborts_after_n_consecutive():
    e = _engine({"nonfinite_guard": {"abort_after": 2}, "steps_per_print": 1})
    e.train_batch(random_batch(8, seed=0))
    e.train_batch(_nan_batch(1))
    with pytest.raises(NonFiniteError, match="2 consecutive"):
        e.train_batch(_nan_batch(2))
