"""Engine end-to-end tests across ZeRO stages / precisions.

Mirrors the reference's tests/unit/runtime/zero/test_zero.py (training
correctness per stage vs baseline) and half_precision tests, on an 8-device
virtual mesh.
"""

import numpy as np
import pytest

import deepspeed_tpu
from tests.util import SimpleModel, random_batch, batch_stream, require_devices


def make_engine(stage=0, precision="bf16", extra=None, tp=1):
    cfg = {
        "train_batch_size": 32,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        # threshold 0: at toy param sizes the reference-parity default (1e5)
        # would keep every param persistent and stage 3 would shard nothing
        "zero_optimization": {"stage": stage,
                              "stage3_param_persistence_threshold": 0},
        "gradient_clipping": 1.0,
    }
    if precision == "bf16":
        cfg["bf16"] = {"enabled": True}
    elif precision == "fp16":
        cfg["fp16"] = {"enabled": True}
    if tp > 1:
        cfg["tensor_parallel"] = {"tp_size": tp}
    if extra:
        cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(), config=cfg,
        example_batch=random_batch(4))
    return engine


def train_n(engine, n=15):
    losses = []
    stream = batch_stream(engine.config.train_batch_size)
    for _ in range(n):
        m = engine.train_batch(next(stream))
        losses.append(float(m["loss"]))
    return losses


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stage_trains(stage):
    engine = make_engine(stage=stage)
    losses = train_n(engine, n=40)
    assert losses[-1] < losses[0] * 0.8, f"stage {stage}: loss not decreasing: {losses}"


@pytest.mark.parametrize("stage", [0, 3])
def test_fp32_trains(stage):
    engine = make_engine(stage=stage, precision="fp32")
    losses = train_n(engine, n=40)
    assert losses[-1] < losses[0] * 0.8


def test_fp16_loss_scaling_trains():
    engine = make_engine(stage=2, precision="fp16")
    losses = train_n(engine, n=40)
    assert losses[-1] < losses[0] * 0.8
    assert engine.get_loss_scale() > 0


def test_stages_agree():
    """All ZeRO stages are pure resharding — same math, near-identical losses."""
    ref = train_n(make_engine(stage=0, precision="fp32"), n=5)
    for stage in (1, 2, 3):
        got = train_n(make_engine(stage=stage, precision="fp32"), n=5)
        np.testing.assert_allclose(got, ref, rtol=2e-4)


def test_zero3_with_tp_composes():
    require_devices(2)
    engine = make_engine(stage=3, tp=2)
    losses = train_n(engine, n=30)
    assert losses[-1] < losses[0] * 0.85


def test_forward_backward_step_api():
    """Micro-batch API parity: forward/backward/step ≡ train_batch."""
    engine = make_engine(stage=1, precision="fp32")
    micro = engine.config.train_micro_batch_size_per_gpu * engine.dp_world_size
    stream = batch_stream(micro)
    for step in range(4):
        for _ in range(engine.config.gradient_accumulation_steps):
            loss = engine.forward(next(stream))
            engine.backward(loss)
        assert engine.is_gradient_accumulation_boundary()
        metrics = engine.step()
        assert metrics is not None
    assert engine.global_steps == 4


def test_micro_api_flops_within_fused_budget():
    """The micro-batch API must not pay a recompute premium: forward() in
    training mode runs the fused value-and-grad (grads cached for
    backward()), so gas x micro-grad + apply costs within ~1.1x of the
    one-program train_batch step (round-3 Weak #4: the old deferred-grad
    design re-ran the forward inside backward, ~1.5x). Eval-mode forward
    stays a strictly cheaper forward-only program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.profiling.flops_profiler import compiled_cost

    # big enough that model FLOPs dominate the per-program fixed overhead
    # (clip/scale/counter scalar math); gas=1 so the fused program's scan
    # body (which XLA cost analysis counts once, not x trip-count) covers
    # exactly one microbatch — an apples-to-apples per-micro comparison
    cfg = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "bf16": {"enabled": True},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden=512), config=cfg,
        example_batch=random_batch(16))
    micro = engine.config.train_micro_batch_size_per_gpu * engine.dp_world_size
    batch = engine.shard_batch(random_batch(micro))
    rng = jax.random.PRNGKey(0)
    params = engine.state.params

    c_micro = compiled_cost(engine._micro_grad, params, engine.state.scale,
                            batch, rng, engine.state.step)
    grads = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params)
    c_apply = compiled_cost(
        lambda s, g, n, lr: engine._apply_update(s, g, n, lr),
        engine.state, grads, jnp.asarray(1.0, jnp.float32),
        engine._current_lr())

    micro_sharding = NamedSharding(engine.mesh, P(None, "data"))
    micros = jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x)[None], micro_sharding), batch)
    c_fused = compiled_cost(lambda s, m, r, lr: engine._train_step(s, m, r, lr),
                            engine.state, micros, rng, engine._current_lr())

    micro_total = c_micro["flops"] + c_apply["flops"]
    assert micro_total <= 1.15 * c_fused["flops"], (
        micro_total, c_fused["flops"])

    # eval-mode forward compiles no backward: strictly cheaper than the
    # fused value-and-grad
    c_fwd = compiled_cost(engine._fwd_loss, params, batch, rng,
                          engine.state.step)
    assert c_fwd["flops"] < 0.7 * c_micro["flops"], (c_fwd, c_micro)

    # mode switch round-trips; backward after an eval-mode forward is a
    # loud error (no gradient residuals exist — differentiating a
    # different, train-mode computation would be silently wrong numerics)
    engine.eval()
    loss = engine.forward(random_batch(micro))
    assert np.isfinite(float(loss))
    with pytest.raises(RuntimeError, match="eval-mode"):
        engine.backward(loss)
    engine.train()
    loss = engine.forward(random_batch(micro))
    engine.backward(loss)
    engine.step()


def test_train_step_no_implicit_host_transfers():
    """The compiled hot loop must do ZERO implicit host<->device transfers:
    jax.transfer_guard("disallow") raises on any implicit pull (a stray
    .item()/float() sneaking into the step would fail here long before it
    shows up as a BENCH delta). Inputs are explicitly placed outside the
    guard; the guarded region is exactly one compiled train step."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.parallel.mesh import BATCH_AXES

    engine = make_engine(stage=2)
    gas = engine.config.gradient_accumulation_steps
    micro_sharding = NamedSharding(engine.mesh, P(None, BATCH_AXES))
    micros = jax.tree.map(
        lambda x: jax.device_put(
            jnp.asarray(x).reshape((gas, x.shape[0] // gas) + x.shape[1:]),
            micro_sharding),
        random_batch(32))
    # every input explicitly placed: uncommitted scalars would be
    # implicitly replicated across the mesh inside the call
    rep = NamedSharding(engine.mesh, P())
    lr = jax.device_put(engine._current_lr(), rep)
    # warmup: compile outside the guard
    engine.state, _ = engine._train_step(
        engine.state, micros, jax.device_put(engine.next_rng(), rep), lr)
    rng = jax.device_put(engine.next_rng(), rep)
    with jax.transfer_guard("disallow"):
        engine.state, metrics = engine._train_step(engine.state, micros,
                                                   rng, lr)
    assert np.isfinite(float(metrics["loss"]))


def test_train_step_compiles_once_across_steps():
    """Retrace regression gate: 3 train_batch calls must hit ONE compiled
    program. A silent retrace (unstable closure, fresh jit wrapper, python
    value drifting into the trace) multiplies step wall time by compile
    time — this fails CI instead of surfacing as a BENCH delta."""
    engine = make_engine(stage=1)
    cache_size = getattr(engine._train_step, "_cache_size", None)
    if cache_size is None:
        pytest.skip("jax build has no PjitFunction._cache_size")
    stream = batch_stream(engine.config.train_batch_size)
    for _ in range(3):
        engine.train_batch(next(stream))
    assert cache_size() == 1, (
        f"train step traced {cache_size()}x across 3 identical steps")


def test_overflow_skips_step():
    """Inf grads must skip the update and shrink the loss scale.

    Overflow is forced through a near-f32-max loss scale (2^127) rather than
    huge inputs: TPUs compile with --xla_allow_excess_precision, which elides
    the intermediate fp16 rounding that would saturate big inputs, so only
    the scaled-loss route overflows on every platform."""
    engine = make_engine(stage=1, precision="fp16",
                         extra={"fp16": {"enabled": True,
                                         "initial_scale_power": 127,
                                         "hysteresis": 1}})
    params_before = engine.module_state_dict()
    batch = random_batch(32)
    batch["x"][:] = 1e3   # big activations so scaled grads blow past f32 max
    scale_before = engine.get_loss_scale()
    engine.train_batch(batch)
    params_after = engine.module_state_dict()
    assert int(engine.state.skipped_steps) == 1
    assert engine.get_loss_scale() < scale_before
    for k in params_before:
        np.testing.assert_array_equal(params_before[k], params_after[k])


def test_lr_schedule_wiring():
    engine = make_engine(stage=0, extra={
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 10, "warmup_max_lr": 1e-2,
                                 "warmup_type": "linear"}}})
    m1 = engine.train_batch(random_batch(32))
    lr_early = float(m1["lr"])
    for _ in range(12):
        m = engine.train_batch(random_batch(32))
    assert float(m["lr"]) > lr_early
    assert engine.get_lr()[0] == pytest.approx(1e-2, rel=1e-3)


def test_checkpoint_roundtrip(tmp_path):
    """Train → save → load into fresh engine → states identical; training continues.

    Mirrors the reference's checkpoint_correctness_verification
    (tests/unit/checkpoint/common.py:134)."""
    engine = make_engine(stage=2)
    train_n(engine, n=3)
    engine.save_checkpoint(str(tmp_path), tag="tag1")
    sd1 = engine.module_state_dict()
    step1 = int(engine.state.step)

    engine2 = make_engine(stage=2)
    engine2.load_checkpoint(str(tmp_path), tag="tag1")
    sd2 = engine2.module_state_dict()
    assert int(engine2.state.step) == step1
    for k in sd1:
        np.testing.assert_array_equal(sd1[k], sd2[k])

    # optimizer state must roundtrip bit-for-bit too
    import jax
    m1 = jax.tree.leaves(engine.state.opt_state)
    m2 = jax.tree.leaves(engine2.state.opt_state)
    for a, b in zip(m1, m2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # loading at a different ZeRO stage works (universal by construction)
    engine3 = make_engine(stage=3)
    engine3.load_checkpoint(str(tmp_path), tag="tag1")
    sd3 = engine3.module_state_dict()
    for k in sd1:
        np.testing.assert_array_equal(sd1[k], sd3[k])
    losses = train_n(engine3, n=3)
    assert np.isfinite(losses).all()


def test_latest_tag(tmp_path):
    engine = make_engine(stage=1)
    train_n(engine, n=2)
    engine.save_checkpoint(str(tmp_path))
    engine2 = make_engine(stage=1)
    engine2.load_checkpoint(str(tmp_path))  # resolves via `latest` file
    assert int(engine2.state.step) == int(engine.state.step)


def test_save_16bit_model(tmp_path):
    engine = make_engine(stage=3)
    engine.save_16bit_model(str(tmp_path))
    import os
    assert os.path.exists(os.path.join(str(tmp_path), "pytorch_model.npz"))


def test_zero_quantized_weights_qwz():
    require_devices(2)
    """ZeRO++ qwZ: stage-3 training with int8 quantized param gathers tracks
    the exact-gather run closely, and the compiled step's all-gathers move
    int8 (audited from HLO)."""
    import re

    def cfg(qw):
        return {
            "train_batch_size": 16,
            "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
            "zero_optimization": {"stage": 3, "zero_quantized_weights": qw,
                                  "stage3_param_persistence_threshold": 0},
            "seed": 3,
        }

    import jax
    import jax.numpy as jnp
    ds = deepspeed_tpu
    e_q, *_ = ds.initialize(model=SimpleModel(), example_batch=random_batch(16),
                            config=cfg(True))
    e_x, *_ = ds.initialize(model=SimpleModel(), example_batch=random_batch(16),
                            config=cfg(False))
    assert e_q._qw_gathers is not None
    assert any(f is not None for f in jax.tree.leaves(
        e_q._qw_gathers, is_leaf=lambda x: x is None or callable(x)))
    lq = lx = None
    for i in range(8):
        b = random_batch(16, seed=i)
        lq = float(e_q.train_batch(b)["loss"])
        lx = float(e_x.train_batch(b)["loss"])
    # int8 weight error perturbs but must not derail training
    assert abs(lq - lx) < 0.1 * abs(lx) + 0.05, (lq, lx)

    # HLO audit: the quantized step all-gathers s8 where the exact one
    # all-gathers f32/bf16
    micros = jax.tree.map(lambda x: jnp.asarray(x)[None], random_batch(16))
    def hlo(e):
        lowered = jax.jit(e._train_step).lower(
            e.state, micros, jax.random.PRNGKey(0),
            jnp.asarray(5e-3, jnp.float32))
        return lowered.compile().as_text()
    assert re.search(r"s8[^\n]*all-gather", hlo(e_q))
    assert not re.search(r"s8[^\n]*all-gather", hlo(e_x))


def test_zero_quantized_weights_composes_with_tp():
    require_devices(2)
    """qwZ must trace and train when TP axes share the param specs (the
    shard_map marks the TP axes manual and leaves them shard-local)."""
    engine = make_engine(stage=3, tp=2,
                         extra={"zero_optimization": {
                             "stage": 3, "zero_quantized_weights": True,
                             "stage3_param_persistence_threshold": 0}})
    losses = train_n(engine, n=10)
    assert losses[-1] < losses[0]
    import jax as _jax
    assert any(f is not None for f in _jax.tree.leaves(
        engine._qw_gathers, is_leaf=lambda x: x is None or callable(x)))


def test_zero_quantized_weights_requires_stage3():
    with pytest.raises(ValueError, match="stage 3"):
        deepspeed_tpu.initialize(model=SimpleModel(), example_batch=random_batch(16),
                      config={"train_batch_size": 16,
                              "optimizer": {"type": "Adam",
                                            "params": {"lr": 1e-3}},
                              "zero_optimization": {
                                  "stage": 2,
                                  "zero_quantized_weights": True}})


def test_pure_bf16_mode_trains():
    """bf16.master_weights=false: params ARE the master, moments bf16 —
    6 bytes/param of state (the device-resident beyond-HBM route; see
    BF16Config). Trains, and every state leaf really is bf16."""
    import jax
    import jax.numpy as jnp
    engine = make_engine(stage=1, extra={
        "bf16": {"enabled": True, "master_weights": False},
        "data_types": {"grad_accum_dtype": "bf16"}})
    assert engine.keep_master is False
    assert engine.state.master == ()
    for leaf in jax.tree.leaves(engine.state.opt_state):
        assert leaf.dtype == jnp.bfloat16
    losses = train_n(engine, n=40)
    assert losses[-1] < losses[0] * 0.8
    for leaf in jax.tree.leaves(engine.state.params):
        assert leaf.dtype == jnp.bfloat16


def test_grad_accum_dtype_bf16_close_to_fp32():
    """bf16 grad accumulation tracks fp32 accumulation closely at small gas
    (reference: data_types.grad_accum_dtype)."""
    e32 = make_engine(stage=1)
    e16 = make_engine(stage=1,
                      extra={"data_types": {"grad_accum_dtype": "bf16"}})
    stream_a, stream_b = batch_stream(32), batch_stream(32)
    for i in range(5):
        l32 = float(e32.train_batch(next(stream_a))["loss"])
        l16 = float(e16.train_batch(next(stream_b))["loss"])
        assert abs(l32 - l16) < 0.02 + 0.02 * abs(l32), (i, l32, l16)
