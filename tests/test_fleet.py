"""Serving-fleet resilience: supervised multi-replica decode, request
requeue with exactly-once token emission, retry budgets, blacklist /
parole, deadlines, and the chaos failure matrix (serve.replica_kill /
serve.replica_hang / serve.requeue).

The oracle everywhere is sequential ``models.generation.generate()`` —
under greedy decode a killed-and-requeued request must produce final
token sequences IDENTICAL to an uninjected run, and the per-token
``on_token`` ledger must contain each token exactly once.

Determinism notes: requests are submitted BEFORE ``start()`` so the
chaos ``skip`` counter lands while the victim replica provably has
in-flight work; hang legs ``warmup()`` first and only then tighten
``heartbeat_timeout``, so an XLA compile can never read as silence.
"""

import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import build_model
from deepspeed_tpu.models.generation import generate
from deepspeed_tpu.runtime import heartbeat as hb
from deepspeed_tpu.serving.fleet import (BLACKLISTED, LIVE, FleetSupervisor,
                                         ServingFleet, _Replica)
from deepspeed_tpu.serving.scheduler import FAILED, FINISHED, TIMEOUT
from deepspeed_tpu.testing import chaos


@pytest.fixture(scope="module")
def tiny():
    # f32: the exactly-once contract is proven via greedy token-exactness
    # against sequential generate(); see test_serving.py's fixture note
    model, cfg = build_model(
        "gpt2-tiny", hidden_size=32, num_layers=2, num_heads=2,
        vocab_size=64, max_seq_len=256, attention_impl="reference",
        dtype=jnp.float32)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, params


def _oracle_tokens(cfg, params, prompt, n):
    out = generate(cfg, params, jnp.asarray([list(prompt)]), n)
    return [int(x) for x in np.asarray(out)[0][len(prompt):]]


def _serving(replicas, **fleet_kw):
    fleet = {"replicas": replicas, "poll_interval": 0.05,
             "heartbeat_interval": 0.02, "heartbeat_timeout": 60.0}
    fleet.update(fleet_kw)
    return {"block_size": 16, "pool_blocks": 64, "max_batch": 2,
            "max_blocks_per_seq": 8, "fleet": fleet}


def _wait_inflight(flt, idx, timeout=30.0):
    """Block until replica ``idx`` holds in-flight work — the straggler
    legs arm slowness only once the victim PROVABLY has lanes (the tiny
    model serves whole requests in milliseconds; armed too early, the
    pre-dispatch sleep lets the fast replica drain the queue and the
    victim never works at all)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if flt._replicas[idx].inflight:
            return
        time.sleep(0.001)
    raise AssertionError(f"replica {idx} never got in-flight work")


# ---------------------------------------------------------------------------
# tier-1: kill -> requeue (with a requeue crash folded in), exactly-once
# ---------------------------------------------------------------------------

# tier-2 (round-17 budget sweep, ~13s): the cheaper tier-1 cousins are
# test_fleet_retry_budget_exhaustion_fails_cleanly (death -> retry path),
# test_fleet_supervisor_verdict_units and
# test_init_inference_serve_returns_started_fleet; scripts/chaos.sh and
# scripts/tier2.sh run this leg and the 3-replica kill matrix
@pytest.mark.slow
def test_fleet_kill_requeues_exactly_once_token_exact(tiny):
    """serve.replica_kill mid-decode: the dead replica's in-flight
    requests requeue onto survivors and finish token-exact vs sequential
    generate(), with the on_token ledger emitting each token exactly
    once. A serve.requeue crash during the requeue orphans the request
    for the next supervisor poll instead of losing it."""
    cfg, params = tiny
    rng = np.random.default_rng(7)
    prompts = [list(rng.integers(1, 64, size=n))
               for n in (5, 11, 17, 9, 13, 7)]
    emitted = {}
    flt = ServingFleet(cfg, params, serving=_serving(2))
    reqs = [flt.submit(
        p, 12, on_token=lambda r, t: emitted.setdefault(r.rid, [])
        .append(t)) for p in prompts]
    # replica 1 dispatches up to 2 lanes on its first iterations and
    # each request needs >= 12 decode steps, so hit 6 is mid-decode
    chaos.arm("serve.replica_kill", "raise", match="1", skip=5)
    chaos.arm("serve.requeue", "raise", times=1)
    try:
        flt.start()
        assert flt.drain(timeout=180)
        assert chaos.fired("serve.replica_kill")
        assert flt.stats["deaths"] == 1 and flt.stats["restarts"] == 1
        assert flt.stats["requeues"] >= 1          # work actually moved
        death = flt.deaths[0]
        assert death["replica"] == 1 and death["reason"] == "crash"
        # attribution via heartbeat evidence: the replica's last word
        assert death["evidence"]["phase"] == hb.PHASE_SERVE
        for p, r in zip(prompts, reqs):
            oracle = _oracle_tokens(cfg, params, p, 12)
            assert r.state == FINISHED
            assert r.output_tokens == oracle, \
                f"request {r.rid} diverged after requeue"
            assert emitted[r.rid] == oracle, \
                f"request {r.rid} re-fired or dropped a token"
    finally:
        flt.close()


def test_fleet_retry_budget_exhaustion_fails_cleanly(tiny):
    """Past retry_budget requeues the request concludes FAILED (callback
    fires, error names the budget) instead of looping forever."""
    cfg, params = tiny
    rng = np.random.default_rng(3)
    seen = []
    flt = ServingFleet(cfg, params, serving=_serving(1, retry_budget=0))
    req = flt.submit(list(rng.integers(1, 64, size=10)), 10,
                     on_finish=lambda r: seen.append(r.state))
    chaos.arm("serve.replica_kill", "raise", match="0", skip=3)
    try:
        flt.start()
        assert req.wait(timeout=120)
        assert req.state == FAILED and "retry budget" in req.error
        assert seen == [FAILED]
        assert flt.stats["failed"] == 1 and flt.stats["requeues"] == 0
        # the fleet itself recovered: a fresh request serves
        ok = flt.submit(list(rng.integers(1, 64, size=8)), 4)
        assert ok.wait(timeout=120) and ok.state == FINISHED
    finally:
        flt.close()


def test_fleet_deadline_sheds_queued_request_with_timeout(tiny):
    """A queued request past its TTL is shed with TIMEOUT while admitted
    work runs to completion — graceful admission backpressure."""
    cfg, params = tiny
    rng = np.random.default_rng(5)
    serving = {"block_size": 16, "pool_blocks": 4, "max_batch": 1,
               "max_blocks_per_seq": 3, "prefix_cache": False,
               "fleet": {"replicas": 1, "poll_interval": 0.02,
                         "heartbeat_interval": 0.02}}
    flt = ServingFleet(cfg, params, serving=serving)
    # the head occupies the single lane; the follower cannot be
    # dispatched and expires while queued (the strict-FIFO edge). The
    # deadline-less tail BEHIND it must survive the shed (the queue is
    # rebuilt mid-traffic) and still be dispatched and served
    head = flt.submit(list(rng.integers(1, 64, size=30)), 16)
    late = flt.submit(list(rng.integers(1, 64, size=30)), 16,
                      deadline_s=0.05)
    tail = flt.submit(list(rng.integers(1, 64, size=20)), 4)
    try:
        flt.start()
        assert late.wait(timeout=120)
        assert late.state == TIMEOUT and "deadline" in late.error
        assert head.wait(timeout=120) and head.state == FINISHED
        assert tail.wait(timeout=120) and tail.state == FINISHED
        assert flt.stats["timeout"] == 1 and flt.stats["completed"] == 2
    finally:
        flt.close()


def test_fleet_submit_validation_is_synchronous(tiny):
    """Inadmissible requests fail at submit() — a request no replica
    could ever admit must not be discovered asynchronously."""
    cfg, params = tiny
    serving = {"block_size": 16, "pool_blocks": 3, "max_batch": 2,
               "max_blocks_per_seq": 8,
               "fleet": {"replicas": 2, "max_queue": 1}}
    flt = ServingFleet(cfg, params, serving=serving)   # NOT started
    with pytest.raises(ValueError, match="empty prompt"):
        flt.submit([], 4)
    with pytest.raises(ValueError, match="max_model_len"):
        flt.submit(list(range(1, 120)), 32)
    with pytest.raises(ValueError, match="pool has 2"):
        flt.submit(list(range(1, 40)), 16)
    flt.submit([1, 2, 3], 2)
    with pytest.raises(RuntimeError, match="queue full"):
        flt.submit([4, 5, 6], 2)


def test_fleet_supervisor_verdict_units():
    """Detection predicate, model-free: thread death is a crash; a stale
    non-terminal record (or never writing at all) is silence; a terminal
    record is a conclusion, not silence; fresh records are healthy."""
    sup = FleetSupervisor(SimpleNamespace(
        fcfg=SimpleNamespace(heartbeat_timeout=1.0)))
    now = time.monotonic()

    rep = _Replica(0)
    rep.thread = SimpleNamespace(is_alive=lambda: False)
    assert sup._verdict(rep, {"phase": "SERVE", "ts": time.time()},
                        now) == "crash"

    rep = _Replica(1)                       # thread None -> liveness skipped
    fresh = {"phase": "SERVE", "ts": time.time()}
    stale = {"phase": "SERVE", "ts": time.time() - 5.0}
    stalled = {"phase": "STALLED", "ts": time.time() - 5.0}
    assert sup._verdict(rep, fresh, now) is None
    assert sup._verdict(rep, stale, now) == "silence"
    assert sup._verdict(rep, stalled, now) is None    # conclusion
    rep.started_ts = now - 0.2
    assert sup._verdict(rep, None, now) is None       # launch grace
    rep.started_ts = now - 5.0
    assert sup._verdict(rep, None, now) == "silence"  # never wrote
    # timeout 0 disables silence (thread liveness still applies)
    sup0 = FleetSupervisor(SimpleNamespace(
        fcfg=SimpleNamespace(heartbeat_timeout=0.0)))
    assert sup0._verdict(rep, stale, now) is None


# tier-2 (round-17 budget sweep, ~10s): the cheaper tier-1 cousin is
# test_serving.test_inference_bench_poisson_line (same row plumbing,
# single engine); the slow-replica fleet row rides
# test_inference_bench_poisson_fleet_slow_replica_row in tier2
@pytest.mark.slow
def test_inference_bench_poisson_fleet_line(capsys):
    """--poisson --fleet N failure-injection leg prints the
    machine-readable degraded-throughput row (tokens/s before / during /
    after a replica loss) in the poisson:/comm_bench: convention."""
    import json
    from deepspeed_tpu.benchmarks.inference_bench import run_poisson_fleet
    row = run_poisson_fleet(
        "gpt2-tiny", rate=100.0, num_requests=10, prompt_len=24,
        new_tokens=5, replicas=2,
        serving={"block_size": 16, "pool_blocks": 32, "max_batch": 2,
                 "max_blocks_per_seq": 8,
                 "fleet": {"heartbeat_timeout": 60.0}},
        model_kwargs=dict(hidden_size=32, num_layers=2, num_heads=2,
                          vocab_size=64, attention_impl="reference"))
    line = [ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("inference_bench poisson_fleet: ")]
    assert line, "machine-readable poisson_fleet line missing"
    parsed = json.loads(line[0].split("inference_bench poisson_fleet: ",
                                      1)[1])
    for key in ("tps_before", "tps_during", "tps_after", "deaths",
                "requeues", "p50_s", "p99_s", "replicas"):
        assert key in parsed and parsed[key] == row[key]
    assert row["deaths"] == 1 and row["completed"] == 10
    assert row["failed"] == 0 and row["replicas"] == 2


@pytest.mark.slow
def test_fleet_straggler_drain_requeues_token_exact(tiny):
    """Acceptance (round 15): a serve.replica_slow-DEGRADED replica —
    alive, stepping, just slow — is detected by the FleetSupervisor's
    relative-slowness detector and DRAINED through the death path:
    admission stops, its lanes requeue exactly-once, the replacement
    restarts warmed, and greedy outputs stay token-identical to an
    uninjected twin. No dead/wrong check could have fired: the replica
    never crashes and never goes silent.

    slow-marked per the tier-1 budget guardrail (~8s of serving);
    cheaper tier-1 cousins: the detector/FP-guard + flag-consumption
    units in test_straggler.py, test_fleet_straggler_detection_off_by_
    default, and the chaos jitter semantics in test_chaos.py —
    scripts/chaos.sh and scripts/tier2.sh run this leg."""
    cfg, params = tiny
    rng = np.random.default_rng(23)
    prompts = [list(rng.integers(1, 64, size=n)) for n in (5, 11, 9, 13)]
    emitted = {}
    serving = _serving(2, straggler={"enabled": True, "warmup": 2,
                                     "strike_window": 2, "cooldown": 5})
    flt = ServingFleet(cfg, params, serving=serving)
    try:
        flt.start()
        flt.warmup()       # compile off-path: a compile is not a straggle
        reqs = [flt.submit(
            p, 48, on_token=lambda r, t: emitted.setdefault(r.rid, [])
            .append(t)) for p in prompts]
        _wait_inflight(flt, 1)
        chaos.arm("serve.replica_slow", "sleep", ms=150, times=0,
                  match="1")
        deadline = time.monotonic() + 60
        while flt.stats["deaths"] == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        chaos.disarm("serve.replica_slow")
        assert flt.drain(timeout=180)
        assert flt.stats["deaths"] == 1 and flt.stats["restarts"] == 1
        death = flt.deaths[0]
        assert death["replica"] == 1 and death["reason"] == "straggler"
        assert death["action"] == "restart"
        # the verdict's evidence carries the inflated gauge
        assert death["evidence"]["gauges"]["step_ms"] > 100.0
        # the healthy replica was never touched
        assert flt._replicas[0].generation == 0
        for p, r in zip(prompts, reqs):
            oracle = _oracle_tokens(cfg, params, p, 48)
            assert r.state == FINISHED and r.output_tokens == oracle
            assert emitted[r.rid] == oracle       # exactly-once emission
    finally:
        flt.close()


def test_fleet_straggler_detection_off_by_default(tiny):
    """Without fleet.straggler.enabled the supervisor builds no
    detector — slowness is never a death verdict (evidence-only is the
    package default posture)."""
    cfg, params = tiny
    flt = ServingFleet(cfg, params, serving=_serving(2))
    assert flt.supervisor._straggler is None


def test_init_inference_serve_returns_started_fleet(tiny):
    """init_inference(...).serve() with fleet.replicas > 1 returns a
    STARTED ServingFleet; generate_batch round-trips token-exact."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer
    cfg, params = tiny
    eng = deepspeed_tpu.init_inference(
        Transformer(cfg),
        {"dtype": "float32",
         "serving": {"block_size": 16, "pool_blocks": 32, "max_batch": 2,
                     "max_blocks_per_seq": 8,
                     "fleet": {"replicas": 2, "poll_interval": 0.05}}},
        model_parameters=params)
    srv = eng.serve()
    assert isinstance(srv, ServingFleet)
    try:
        out = srv.generate_batch([[3, 1, 4, 1, 5], [2, 7, 2]],
                                 max_new_tokens=4)
        assert out[0] == _oracle_tokens(cfg, params, [3, 1, 4, 1, 5], 4)
        assert out[1] == _oracle_tokens(cfg, params, [2, 7, 2], 4)
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# slow: the 3-replica acceptance matrix + hang/blacklist/parole + fleet oom
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_acceptance_3replica_kill_matrix(tiny):
    """Acceptance criterion: 3 replicas, serve.replica_kill mid-decode —
    every admitted request completes with final token sequences identical
    to an uninjected run, the loss is attributed via heartbeat evidence,
    and throughput recovers WITHOUT restarting surviving replicas."""
    cfg, params = tiny
    rng = np.random.default_rng(11)
    prompts = [list(rng.integers(1, 64, size=n))
               for n in (5, 11, 17, 23, 9, 13, 7, 21, 8)]
    emitted = {}
    flt = ServingFleet(cfg, params, serving=_serving(3))
    reqs = [flt.submit(
        p, 12, on_token=lambda r, t: emitted.setdefault(r.rid, [])
        .append(t)) for p in prompts]
    chaos.arm("serve.replica_kill", "raise", match="1", skip=6)
    try:
        flt.start()
        survivors = {rep.idx: rep.engine for rep in flt._replicas
                     if rep.idx != 1}
        assert flt.drain(timeout=240)
        # one death, attributed; requeued work completed elsewhere
        assert flt.stats["deaths"] == 1 and flt.stats["requeues"] >= 1
        death = flt.deaths[0]
        assert death["replica"] == 1 and death["reason"] == "crash"
        assert death["evidence"]["phase"] == hb.PHASE_SERVE
        assert death["action"] == "restart"
        # survivors were never torn down: same engine objects, same
        # generation — throughput recovered without touching them
        for idx, engine in survivors.items():
            assert flt._replicas[idx].engine is engine
            assert flt._replicas[idx].generation == 0
        assert flt.stats["completed"] == len(prompts)
        for p, r in zip(prompts, reqs):
            oracle = _oracle_tokens(cfg, params, p, 12)
            assert r.state == FINISHED and r.output_tokens == oracle
            assert emitted[r.rid] == oracle     # exactly-once emission
    finally:
        flt.close()
    # after close, every live replica concluded with an EXIT record —
    # `dstpu health` on the fleet dir reads conclusions, not silence
    records = hb.read_heartbeats(flt.heartbeat_dir)
    for rep in flt._replicas:
        if rep.state == LIVE:
            assert records[rep.idx]["phase"] == hb.PHASE_EXIT


@pytest.mark.slow
def test_fleet_hang_silence_detected_and_blacklisted(tiny):
    """serve.replica_hang: a wedged loop goes heartbeat-silent, the
    supervisor declares it via the rc-117 silence contract, requeues its
    work, and blacklist_after strikes quarantine it — the fleet keeps
    serving on the survivor at reduced capacity."""
    cfg, params = tiny
    rng = np.random.default_rng(13)
    prompts = [list(rng.integers(1, 64, size=n)) for n in (5, 9, 13, 7)]
    serving = _serving(2, blacklist_after=1, poll_interval=0.1)
    flt = ServingFleet(cfg, params, serving=serving)
    try:
        flt.start()
        flt.warmup()           # compile off-path: a compile is not a wedge
        flt.fcfg.heartbeat_timeout = 1.0    # now silence means silence
        reqs = [flt.submit(p, 10) for p in prompts]
        chaos.arm("serve.replica_hang", "hang", match="1", skip=3)
        assert flt.drain(timeout=240)
        assert flt.stats["deaths"] == 1
        death = flt.deaths[0]
        assert death["replica"] == 1 and death["reason"] == "silence"
        assert death["action"] == "blacklist"
        assert flt._replicas[1].state == BLACKLISTED
        assert flt._replicas[0].state == LIVE      # reduced, still serving
        for p, r in zip(prompts, reqs):
            assert r.state == FINISHED
            assert r.output_tokens == _oracle_tokens(cfg, params, p, 10)
        # the quarantined replica's STALLED verdict is health-visible
        assert hb.read_heartbeats(flt.heartbeat_dir)[1]["phase"] == \
            hb.PHASE_STALLED
    finally:
        flt.close()


@pytest.mark.slow
def test_fleet_parole_restores_min_replicas(tiny):
    """With live replicas below min_replicas, the least-struck
    blacklisted replica is paroled back instead of starving the fleet."""
    cfg, params = tiny
    rng = np.random.default_rng(17)
    serving = _serving(2, blacklist_after=1, min_replicas=2,
                       poll_interval=0.1)
    flt = ServingFleet(cfg, params, serving=serving)
    try:
        flt.start()
        flt.warmup()
        flt.fcfg.heartbeat_timeout = 1.0
        reqs = [flt.submit(list(rng.integers(1, 64, size=9)), 8)
                for _ in range(4)]
        chaos.arm("serve.replica_hang", "hang", match="1", skip=3)
        assert flt.drain(timeout=240)
        assert flt.stats["deaths"] == 1 and flt.stats["paroles"] == 1
        assert flt.deaths[0]["action"] == "blacklist"
        # paroled back: replica 1 is LIVE again on a fresh generation,
        # strikes standing (it can be re-blacklisted)
        rep1 = flt._replicas[1]
        assert rep1.state == LIVE and rep1.generation >= 1
        assert rep1.strikes == 1
        assert all(r.state == FINISHED for r in reqs)
    finally:
        flt.close()


@pytest.mark.slow
def test_fleet_straggler_blacklist_flag_health_visible(tiny):
    """Repeated drains blacklist the chronically-slow replica, and its
    final record — STALLED, STRAGGLER-flagged — stays health-visible
    (the restart path overwrites the rank file; the blacklist path is
    the durable verdict)."""
    cfg, params = tiny
    rng = np.random.default_rng(29)
    serving = _serving(2, blacklist_after=1,
                       straggler={"enabled": True, "warmup": 2,
                                  "strike_window": 2, "cooldown": 5})
    flt = ServingFleet(cfg, params, serving=serving)
    try:
        flt.start()
        flt.warmup()
        # submit BEFORE arming: the victim dispatches at full speed and
        # provably holds in-flight lanes when the slowness lands
        reqs = [flt.submit(list(rng.integers(1, 64, size=9)), 48)
                for _ in range(6)]
        _wait_inflight(flt, 1)
        chaos.arm("serve.replica_slow", "sleep", ms=150, times=0,
                  match="1")
        deadline = time.monotonic() + 60
        while flt.stats["deaths"] == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        chaos.disarm("serve.replica_slow")
        assert flt.drain(timeout=180)
        assert flt.stats["deaths"] == 1 and flt.stats["blacklisted"] == 1
        assert flt.deaths[0]["reason"] == "straggler"
        assert flt.deaths[0]["action"] == "blacklist"
        assert flt._replicas[1].state == BLACKLISTED
        assert flt._replicas[0].state == LIVE     # reduced, still serving
        for r in reqs:
            assert r.state == FINISHED
        rec = hb.read_heartbeats(flt.heartbeat_dir)[1]
        assert rec["phase"] == hb.PHASE_STALLED
        assert "STRAGGLER" in rec["flags"]
    finally:
        flt.close()


@pytest.mark.slow
def test_inference_bench_poisson_fleet_slow_replica_row(capsys):
    """--poisson --fleet N --slow-replica: the degraded-throughput row
    (tps before/during/after + drain/recovery stamps) in the SERVEBENCH
    newest-recorded-sweep convention."""
    import json
    from deepspeed_tpu.benchmarks.inference_bench import run_poisson_fleet
    # enough queued work that the victim provably holds lanes when the
    # slowness lands AND while detection converges (a too-small run
    # finishes before a 150ms-degraded replica ever shows in the gauges)
    row = run_poisson_fleet(
        "gpt2-tiny", rate=200.0, num_requests=48, prompt_len=24,
        new_tokens=6, replicas=2, slow_replica=True, slow_ms=150,
        serving={"block_size": 16, "pool_blocks": 32, "max_batch": 2,
                 "max_blocks_per_seq": 8,
                 "fleet": {"heartbeat_timeout": 60.0}},
        model_kwargs=dict(hidden_size=32, num_layers=2, num_heads=2,
                          vocab_size=64, attention_impl="reference"))
    line = [ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("inference_bench poisson_fleet_slow: ")]
    assert line, "machine-readable poisson_fleet_slow line missing"
    parsed = json.loads(
        line[0].split("inference_bench poisson_fleet_slow: ", 1)[1])
    for key in ("tps_before", "tps_during", "tps_after", "slow_at_s",
                "drained_at_s", "recovered_at_s", "deaths", "requeues"):
        assert key in parsed and parsed[key] == row[key]
    assert row["mode"] == "poisson_fleet_slow"
    assert row["deaths"] == 1 and row["completed"] == 48
    assert row["failed"] == 0 and row["kill_at_s"] is None
    assert row["drained_at_s"] >= row["slow_at_s"]


@pytest.mark.slow
def test_fleet_serve_oom_keeps_other_replicas_serving(tiny):
    """serve.oom under the fleet: an injected allocation failure defers
    one replica's admission (request stays queued, PR-8 contract) while
    the rest of the fleet keeps serving — no death, no requeue storm."""
    cfg, params = tiny
    rng = np.random.default_rng(19)
    prompts = [list(rng.integers(1, 64, size=n)) for n in (5, 9, 13, 7, 11)]
    flt = ServingFleet(cfg, params, serving=_serving(2))
    reqs = [flt.submit(p, 8) for p in prompts]
    chaos.arm("serve.oom", "raise", times=2)
    try:
        flt.start()
        assert flt.drain(timeout=240)
        assert chaos.fired("serve.oom")
        assert flt.stats["deaths"] == 0 and flt.stats["failed"] == 0
        for p, r in zip(prompts, reqs):
            assert r.state == FINISHED
            assert r.output_tokens == _oracle_tokens(cfg, params, p, 8)
    finally:
        flt.close()
