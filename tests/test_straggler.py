"""Straggler defense (round 15, runtime/straggler.py): relative-slowness
detection over step_ms heartbeat gauges, the flag -> blacklist -> rc-117
escalation ladder, and the false-positive guards the acceptance criteria
pin — a UNIFORMLY slow world and compile/restore phases must produce
ZERO verdicts, and detection is evidence-only unless
``straggler.abort_after`` is set.

The plain-python halves (StepClock, StragglerDetector, record gating,
supervisor/agent flag consumption) are tier-1 sub-second. The
engine-in-anger end-to-end leg — a ``run.slow``-injected rank
STRAGGLER-flagged, struck and blacklisted by DSElasticAgent, with the
degraded world resuming training and the flag visible in ``dstpu
health`` — builds real engines in child processes and is ``slow``-marked
(``scripts/chaos.sh`` runs it). The fleet-side drain legs live in
tests/test_fleet.py next to the kill/hang matrix they extend.
"""

import os
import subprocess
import sys
import textwrap
import time

import pytest

from deepspeed_tpu.config.config import StragglerConfig
from deepspeed_tpu.runtime import heartbeat as hb
from deepspeed_tpu.runtime.straggler import (ABORT, SLOW, STEP_MS_GAUGE,
                                             STRAGGLER_FLAG, StepClock,
                                             StragglerAbort,
                                             StragglerDetector,
                                             record_step_ms)
from deepspeed_tpu.runtime.watchdog import STALL_EXIT_CODE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(**kw):
    base = dict(enabled=True, warmup=2, strike_window=2, cooldown=5,
                zmax=6.0, rel_threshold=1.5, abort_after=0)
    base.update(kw)
    return StragglerConfig(**base)


def _rec(ms, phase="STEP", **extra):
    rec = {"phase": phase, "ts": time.time()}
    if ms is not None:
        rec["gauges"] = {STEP_MS_GAUGE: ms}
    rec.update(extra)
    return rec


# ------------------------------------------------------------------ StepClock

def test_step_clock_rolling_median_and_reset():
    now = [0.0]
    c = StepClock(window=4, clock=lambda: now[0])
    assert c.gauge() is None                      # predates the gauge
    assert c.mark() is None                       # first mark = baseline only
    for dt in (0.1, 0.1, 0.1, 5.0):               # one save-sized outlier
        now[0] += dt
        c.mark()
    # median over (100, 100, 100, 5000)ms windows of 4 -> robust to the
    # outlier (the rolling MEDIAN is the whole point of the gauge)
    assert c.gauge() == pytest.approx(100.0, abs=1.0)
    # reset drops the pending boundary: the next mark re-baselines and
    # the spanning gap is never recorded as a step
    c.reset()
    now[0] += 60.0
    n_before = len(c.buf)
    c.mark()
    assert len(c.buf) == n_before


def test_step_clock_push_ms():
    c = StepClock(window=3)
    assert c.push_ms(10) == 10.0
    c.push_ms(30)
    assert c.push_ms(20) == 20.0                  # median of 10/20/30


# ---------------------------------------------------------- record gating

def test_record_step_ms_phase_guards():
    """Compile/restore/save/init and terminal records never participate
    in a window — a rank mid-compile must not read as a straggler (the
    acceptance false-positive guard)."""
    assert record_step_ms(_rec(500.0)) == 500.0
    assert record_step_ms(_rec(500.0, phase="SERVE")) == 500.0
    for phase in ("COMPILE", "RESTORE", "SAVE", "INIT",
                  "STALLED", "PREEMPTED", "EXIT"):
        assert record_step_ms(_rec(500.0, phase=phase)) is None
    # records predating the gauge (no step_ms) are skipped, not zeroed
    assert record_step_ms(_rec(None)) is None
    assert record_step_ms({"phase": "STEP", "gauges": {"queue": 3}}) is None


# ------------------------------------------------------------------ detector

def test_detector_flags_one_slow_rank_after_warmup_and_strikes():
    det = StragglerDetector(_cfg())
    world = {0: _rec(100), 1: _rec(101), 2: _rec(99), 3: _rec(800)}
    assert det.observe(world) == {}               # warmup window 1
    assert det.slow_now == {3}                    # measured, not verdicted
    assert det.observe(world) == {}               # warmup window 2
    assert det.observe(world) == {3: SLOW}        # strikes crossed
    # debounced: the standing verdict is not re-issued every window
    assert det.observe(world) == {}


def test_detector_uniformly_slow_world_produces_zero_verdicts():
    """Everyone throttled alike: the world median scales with the world,
    so the relative criterion never fires — the acceptance guard."""
    det = StragglerDetector(_cfg())
    for _ in range(10):
        assert det.observe({r: _rec(100) for r in range(4)}) == {}
    for _ in range(10):                           # the whole rack slows 5x
        assert det.observe({r: _rec(500) for r in range(4)}) == {}
    assert det.slow_now == set()
    assert det.verdicts == {}


def test_detector_compile_phase_world_produces_zero_windows():
    det = StragglerDetector(_cfg())
    for _ in range(6):
        assert det.observe({0: _rec(100, phase="COMPILE"),
                            1: _rec(9000, phase="COMPILE")}) == {}
    assert det.windows == 0                       # nothing comparable seen


def test_detector_small_world_ratio_fallback():
    """Below 4 gauges a MAD is meaningless; the relative floor alone
    decides — a 2-replica fleet can still catch a 3x straggler."""
    det = StragglerDetector(_cfg())
    world = {0: _rec(10), 1: _rec(300)}
    out = [det.observe(world) for _ in range(4)]
    assert out[2] == {1: SLOW}
    # mild (sub-threshold) skew in a 2-rank world: never a verdict
    det2 = StragglerDetector(_cfg())
    for _ in range(6):
        assert det2.observe({0: _rec(100), 1: _rec(120)}) == {}


def test_detector_clean_window_retires_strikes_and_persistence():
    det = StragglerDetector(_cfg(abort_after=3))
    world_slow = {0: _rec(100), 1: _rec(100), 2: _rec(100), 3: _rec(900)}
    world_ok = {r: _rec(100) for r in range(4)}
    for _ in range(2):
        det.observe(world_slow)
    assert det.observe(world_slow) == {3: SLOW}
    det.observe(world_slow)                       # persist 1 of 3
    assert det.observe(world_ok) == {}            # recovered
    assert det.strikes[3] == 0 and 3 not in det.persist
    # a later relapse must re-earn strike_window (=2) windows: the first
    # slow window is a strike, not a verdict (after the cooldown lapsed)
    for _ in range(6):
        det.observe(world_ok)                     # cooldown lapses
    assert det.observe(world_slow) == {}          # strike 1 of 2
    assert det.observe(world_slow) == {3: SLOW}


def test_detector_evidence_only_by_default_and_abort_escalation():
    """abort_after=0 (default): SLOW is the ceiling — nothing ever asks
    for a teardown. abort_after=N: a rank still slow N windows past its
    verdict escalates to ABORT."""
    det0 = StragglerDetector(_cfg(abort_after=0))
    world = {0: _rec(100), 1: _rec(100), 2: _rec(100), 3: _rec(900)}
    seen = [det0.observe(world) for _ in range(20)]
    assert ABORT not in {v for out in seen for v in out.values()}

    det = StragglerDetector(_cfg(abort_after=2))
    out = [det.observe(world) for _ in range(6)]
    assert out[2] == {3: SLOW}
    assert out[4] == {3: ABORT}


def test_detector_single_gauge_is_not_a_window():
    det = StragglerDetector(_cfg())
    for _ in range(6):
        assert det.observe({0: _rec(900)}) == {}
    assert det.windows == 0


def test_straggler_abort_carries_stall_exit_code():
    assert StragglerAbort("x").exit_code == STALL_EXIT_CODE


# --------------------------------------------- blacklist-evidence consumption

def _flagged_channel(tmp_path, host="w1", rank=1):
    w = hb.HeartbeatWriter(str(tmp_path), rank, host=host,
                           refresh_interval=0)
    w.write(hb.PHASE_STEP, 40, force=True,
            extra={STEP_MS_GAUGE: 900.0})
    w.add_flag(STRAGGLER_FLAG)
    return w


def test_run_supervisor_failed_hosts_consumes_straggler_flag(tmp_path):
    """The flag names a HOST (the rc names nobody): it must feed the
    blacklist exactly like the SDC flag."""
    from deepspeed_tpu.launcher.supervisor import RankSpec, RunSupervisor
    _flagged_channel(tmp_path)
    sup = RunSupervisor([RankSpec("w0", ["true"]), RankSpec("w1", ["true"])],
                        heartbeat_dir=str(tmp_path))
    assert sup.failed_hosts() == ["w1"]


def test_backend_supervisor_failed_hosts_consumes_straggler_flag(tmp_path):
    from deepspeed_tpu.launcher.supervisor import BackendSupervisor
    _flagged_channel(tmp_path)
    sup = BackendSupervisor(["true"], heartbeat_dir=str(tmp_path),
                            rank_hosts=["w0", "w1"])
    assert sup.failed_hosts() == ["w1"]


def test_elastic_agent_failure_evidence_consumes_straggler_flag(tmp_path):
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    _flagged_channel(tmp_path)
    agent = DSElasticAgent(lambda m: None, str(tmp_path / "hostfile"),
                           heartbeat_dir=str(tmp_path))
    assert agent._failure_evidence(object(), ["w0", "w1"]) == ["w1"]


# --------------------------------------------------------------- end to end

_CHILD = textwrap.dedent("""
    import os, sys, time
    import numpy as np
    import flax.linen as nn
    import jax.numpy as jnp
    import deepspeed_tpu as ds

    class M(nn.Module):
        @nn.compact
        def __call__(self, batch, train=False):
            h = nn.Dense(16)(batch["x"])
            return jnp.mean((h.sum(-1) - batch["y"]) ** 2)

    def batch(i):
        r = np.random.RandomState(i)
        return {"x": r.randn(8, 4).astype(np.float32),
                "y": r.randn(8).astype(np.float32)}

    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "straggler": {"enabled": True, "check_interval": 0.2,
                         "window": 4, "warmup": 2, "strike_window": 2,
                         "cooldown": 3, "abort_after": 2}}
    eng, *_ = ds.initialize(model=M(), config=cfg, example_batch=batch(0))
    if eng.heartbeat is not None:
        eng.heartbeat.min_interval = 0.02
    marker = os.environ.get("DSTPU_TEST_MARKER", "")
    steps = int(os.environ.get("DSTPU_TEST_STEPS", "2000"))
    try:
        for i in range(steps):
            eng.train_batch(batch(i))
            if marker and i == 0:
                open(marker, "w").write("trained")
            time.sleep(0.02)       # a fast-but-real step cadence
    except Exception as e:         # StragglerAbort carries exit_code=117
        code = getattr(e, "exit_code", None)
        sys.exit(code if isinstance(code, int) else 1)
    sys.exit(0)
""")


@pytest.mark.slow
def test_run_slow_rank_flagged_struck_blacklisted_and_world_resumes(
        tmp_path):
    """Acceptance, end to end: a ``run.slow``-injected rank's step time
    sits MADs above the world median -> it STRAGGLER-flags itself on the
    shared heartbeat channel, aborts rc 117 after
    ``straggler.abort_after`` persistent windows, RunSupervisor tears the
    world down, DSElasticAgent counts the stall, strikes and blacklists
    the host, and the DEGRADED world resumes training — with the flag
    still visible in ``dstpu health`` afterwards."""
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    from deepspeed_tpu.launcher.runner import health_main
    from deepspeed_tpu.launcher.supervisor import RankSpec, RunSupervisor
    hb_dir = str(tmp_path / "hb")
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("w0 slots=1\nw1 slots=1\n")
    marker = str(tmp_path / "progress")
    worlds = []

    def _env(rank, host, **extra):
        env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
               "DSTPU_HEARTBEAT_DIR": hb_dir,
               "DSTPU_HEARTBEAT_RANK": str(rank),
               "DSTPU_HEARTBEAT_HOST": host}
        env.update(extra)
        return env

    def launch(members):
        worlds.append(list(members))
        cmd = [sys.executable, str(script)]
        if len(worlds) == 1:
            specs = [
                RankSpec("w0", cmd,
                         env=_env(0, "w0", DSTPU_TEST_MARKER=marker)),
                # w1 is degraded, not dead: every step sleeps 300ms on
                # top of the real step — the shape no dead/wrong check
                # can see
                RankSpec("w1", cmd,
                         env=_env(1, "w1",
                                  DSTPU_CHAOS="run.slow:sleep:ms=300"
                                              ":times=0")),
            ]
            # supervisor #1 carries the channel (flag evidence for
            # failed_hosts); grace is small — the survivor has no
            # preemption handler and dies on SIGTERM
            return RunSupervisor(specs, grace_secs=2.0,
                                 heartbeat_dir=hb_dir).start()
        # the degraded relaunch: w0 alone proves training RESUMES (a
        # real 3-step engine run over the prior run's marker). No
        # heartbeat env: run-1's channel evidence must survive for the
        # post-run health assertions.
        code = (f"import os, sys\n"
                f"assert os.path.exists({marker!r}), 'no prior progress'\n")
        specs = [RankSpec("w0", [sys.executable, "-c", code +
                                 "sys.exit(0)\n"]),
                 RankSpec("w0", cmd, env={
                     "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                     "DSTPU_TEST_STEPS": "3"})]
        return RunSupervisor(specs, grace_secs=2.0).start()

    # agent WITHOUT heartbeat_dir: the supervisor's failed_hosts() is
    # the evidence feed, and the agent must not clear the channel
    # between launches (the test reads it afterwards)
    agent = DSElasticAgent(launch, str(hostfile), max_restarts=3,
                           check_interval=0.1, blacklist_after=1)
    rc = agent.run()
    assert rc == 0
    assert worlds == [["w0", "w1"], ["w0"]]
    assert agent.stalls == 1                      # rc 117, counted
    assert agent.blacklisted == {"w1"}
    assert agent.strikes["w1"] >= 1
    # the slow rank's final word on the channel: STALLED, STRAGGLER-flagged
    recs = hb.read_heartbeats(hb_dir)
    assert recs[1]["phase"] == hb.PHASE_STALLED
    assert STRAGGLER_FLAG in recs[1].get("flags", ())
    assert recs[1]["host"] == "w1"
    # the healthy rank never flagged itself (no false positive)
    assert not recs[0].get("flags")
    # operator view: rc 1, the flag and the RATE gauge visible
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        health_rc = health_main([hb_dir])
    out = buf.getvalue()
    assert health_rc == 1
    assert "STRAGGLER" in out and "straggler (slow host)" in out
    assert "RATE" in out.splitlines()[0]
