"""Sparsity layouts + block-sparse attention oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import mha_reference
from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig, VariableSparsityConfig,
    build_sparsity_config, layout_to_dense_mask, sparse_attention)


def test_fixed_layout_structure():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              num_global_blocks=1)
    lo = cfg.make_layout(128)          # 8 blocks
    assert lo.shape == (2, 8, 8)
    # block attends its own window
    assert lo[0, 3, 2] and lo[0, 3, 3]
    # later blocks attend last block of earlier windows (global)
    assert lo[0, 5, 1]                 # window0 = blocks {0,1}; global = 1
    assert not lo[0, 0, 5]             # no forward attention outside window


def test_longformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=16,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=(0,))
    lo = cfg.make_layout(128)
    assert lo[0, 4, 3] and lo[0, 4, 4] and lo[0, 4, 5]   # window
    assert not lo[0, 4, 6]
    assert lo[0, 0].all() and lo[0, :, 0].all()          # global block 0


def test_bigbird_layout_density():
    cfg = BigBirdSparsityConfig(num_heads=1, block=16, num_random_blocks=2,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    lo = cfg.make_layout(256)          # 16 blocks
    density = lo.mean()
    assert 0.1 < density < 0.7         # sparse but non-trivial


def test_sliding_window_causal():
    cfg = LocalSlidingWindowSparsityConfig(num_heads=1, block=16,
                                           num_sliding_window_blocks=2)
    lo = cfg.make_layout(96)
    assert not np.triu(lo[0], 1).any()           # strictly causal blocks
    assert lo[0, 4, 3] and lo[0, 4, 4] and not lo[0, 4, 2]


def test_variable_and_registry():
    cfg = build_sparsity_config("variable", num_heads=2, block=16,
                                local_window_blocks=(2, 4),
                                global_block_indices=(0,))
    lo = cfg.make_layout(128)
    assert lo.shape == (2, 8, 8)
    with pytest.raises(ValueError):
        build_sparsity_config("nope", num_heads=1)


def test_sparse_attention_matches_masked_reference():
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 2, 64, 16)), jnp.float32)
               for _ in range(3))
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2)
    out = sparse_attention(q, k, v, cfg)
    mask = layout_to_dense_mask(cfg.make_layout(64), 16)[None]
    ref = mha_reference(q, k, v, causal=False, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)
    # dense config reproduces full attention
    dense = build_sparsity_config("dense", num_heads=2, block=16)
    out_d = sparse_attention(q, k, v, dense)
    full = mha_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(full), rtol=1e-6)
