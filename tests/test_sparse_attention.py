"""Sparsity layouts + block-sparse attention oracle."""

import jax.numpy as jnp
import numpy as np
import pytest

@pytest.fixture(autouse=True)
def _precise_matmuls():
    """Kernel-parity tolerances assume fp32 math; on real TPUs jnp matmuls
    default to bf16 internally, so pin the precision for these tests."""
    with jax.default_matmul_precision("highest"):
        yield


from deepspeed_tpu.ops.attention import mha_reference
from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig, VariableSparsityConfig,
    build_sparsity_config, layout_to_dense_mask, sparse_attention)


def test_fixed_layout_structure():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              num_global_blocks=1)
    lo = cfg.make_layout(128)          # 8 blocks
    assert lo.shape == (2, 8, 8)
    # block attends its own window
    assert lo[0, 3, 2] and lo[0, 3, 3]
    # later blocks attend last block of earlier windows (global)
    assert lo[0, 5, 1]                 # window0 = blocks {0,1}; global = 1
    assert not lo[0, 0, 5]             # no forward attention outside window


def test_longformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=16,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=(0,))
    lo = cfg.make_layout(128)
    assert lo[0, 4, 3] and lo[0, 4, 4] and lo[0, 4, 5]   # window
    assert not lo[0, 4, 6]
    assert lo[0, 0].all() and lo[0, :, 0].all()          # global block 0


def test_bigbird_layout_density():
    cfg = BigBirdSparsityConfig(num_heads=1, block=16, num_random_blocks=2,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    lo = cfg.make_layout(256)          # 16 blocks
    density = lo.mean()
    assert 0.1 < density < 0.7         # sparse but non-trivial


def test_sliding_window_causal():
    cfg = LocalSlidingWindowSparsityConfig(num_heads=1, block=16,
                                           num_sliding_window_blocks=2)
    lo = cfg.make_layout(96)
    assert not np.triu(lo[0], 1).any()           # strictly causal blocks
    assert lo[0, 4, 3] and lo[0, 4, 4] and not lo[0, 4, 2]


def test_variable_and_registry():
    cfg = build_sparsity_config("variable", num_heads=2, block=16,
                                local_window_blocks=(2, 4),
                                global_block_indices=(0,))
    lo = cfg.make_layout(128)
    assert lo.shape == (2, 8, 8)
    with pytest.raises(ValueError):
        build_sparsity_config("nope", num_heads=1)


def test_sparse_attention_matches_masked_reference():
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 2, 64, 16)), jnp.float32)
               for _ in range(3))
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2)
    out = sparse_attention(q, k, v, cfg)
    mask = layout_to_dense_mask(cfg.make_layout(64), 16)[None]
    ref = mha_reference(q, k, v, causal=False, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-7)
    # dense config reproduces full attention
    dense = build_sparsity_config("dense", num_heads=2, block=16)
    out_d = sparse_attention(q, k, v, dense)
    full = mha_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(full), rtol=1e-6, atol=1e-7)


# -- Pallas layout-skip kernel parity (interpret mode on CPU) -----------------

import jax
from deepspeed_tpu.ops.pallas.block_sparse_attention import (
    block_sparse_flash_attention)
from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                BSLongformerSparsityConfig,
                                                LocalSlidingWindowSparsityConfig)


def _qkv(S=256, H=2, D=32, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.standard_normal((1, H, S, D)) * 0.5,
                             jnp.float32) for _ in range(3))


def _kernel_vs_oracle(cfg, causal, S=256, block_q=128, block_k=128):
    q, k, v = _qkv(S=S, H=cfg.num_heads)
    layout = cfg.make_layout(S)
    out = block_sparse_flash_attention(q, k, v, layout, cfg.block,
                                       causal=causal, block_q=block_q,
                                       block_k=block_k, interpret=True)
    mask = layout_to_dense_mask(layout, cfg.block)[None]
    ref = mha_reference(q, k, v, causal=causal, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_bs_kernel_fixed_parity():
    _kernel_vs_oracle(FixedSparsityConfig(num_heads=2, block=16,
                                          num_local_blocks=4), causal=False)


def test_bs_kernel_fixed_causal_parity():
    _kernel_vs_oracle(
        FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=4,
                            attention="unidirectional"), causal=True)


def test_bs_kernel_bigbird_parity():
    _kernel_vs_oracle(BigBirdSparsityConfig(num_heads=2, block=16,
                                            num_random_blocks=2), causal=False)


def test_bs_kernel_longformer_parity():
    _kernel_vs_oracle(BSLongformerSparsityConfig(num_heads=2, block=16,
                                                 num_sliding_window_blocks=3),
                      causal=False)


def test_bs_kernel_sliding_causal_parity():
    _kernel_vs_oracle(
        LocalSlidingWindowSparsityConfig(num_heads=2, block=16,
                                         num_sliding_window_blocks=4),
        causal=True)


def test_bs_kernel_grads_match_oracle():
    """Backward parity: d(sum(out*w))/d{q,k,v} vs the mask oracle."""
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=4,
                              attention="unidirectional")
    S = 256
    q, k, v = _qkv(S=S, H=2)
    layout = cfg.make_layout(S)
    mask = layout_to_dense_mask(layout, cfg.block)[None]
    w = jnp.asarray(np.random.default_rng(1).standard_normal(
        (1, 2, S, 32)), jnp.float32)

    def f_kernel(q, k, v):
        out = block_sparse_flash_attention(q, k, v, layout, cfg.block,
                                           causal=True, block_q=128,
                                           block_k=128, interpret=True)
        return jnp.sum(out * w)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True, mask=mask) * w)

    gk = jax.grad(f_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4)


def test_bs_kernel_rejects_untileable():
    q, k, v = _qkv(S=256, H=2, D=12)   # D % 8 != 0
    cfg = FixedSparsityConfig(num_heads=2, block=16)
    with pytest.raises(ValueError, match="tile"):
        block_sparse_flash_attention(q, k, v, cfg.make_layout(256), 16,
                                     interpret=True)


def test_sparse_attention_routes_to_kernel():
    """use_kernel=True + interpret exercises the kernel path end-to-end from
    the public entry; numerics must equal the oracle path."""
    q, k, v = _qkv(S=256, H=2)
    cfg = BSLongformerSparsityConfig(num_heads=2, block=16)
    out_k = sparse_attention(q, k, v, cfg, use_kernel=True, interpret=True)
    out_m = sparse_attention(q, k, v, cfg, use_kernel=False)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_m),
                               rtol=2e-5, atol=2e-5)


def test_sliding_window_attention_exact_parity():
    """The sliding-window kernel path (block skip + exact in-block window)
    must match the dense (q_pos - k_pos < W) causal mask bit-for-bit in fp32,
    including windows that don't align to any block size."""
    from deepspeed_tpu.ops.attention import (mha_reference,
                                             sliding_window_attention)
    rng = np.random.default_rng(5)
    B, H, S, D = 2, 2, 256, 32
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
               for _ in range(3))
    qp = np.arange(S)[:, None]
    kp = np.arange(S)[None, :]
    for W in (1, 37, 64, 100, 256):
        out = sliding_window_attention(q, k, v, W, interpret=True)
        mask = jnp.asarray((qp - kp < W))[None, None]
        ref = mha_reference(q, k, v, causal=True, mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6, err_msg=f"W={W}")


def test_sliding_window_attention_grads():
    from deepspeed_tpu.ops.attention import (mha_reference,
                                             sliding_window_attention)
    rng = np.random.default_rng(6)
    B, H, S, D, W = 1, 2, 128, 16, 48
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
               for _ in range(3))
    qp = np.arange(S)[:, None]
    kp = np.arange(S)[None, :]
    mask = jnp.asarray((qp - kp < W))[None, None]

    gk = jax.grad(lambda *a: jnp.sum(
        sliding_window_attention(*a, W, interpret=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(
        mha_reference(*a, causal=True, mask=mask) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        # 5e-4: fp32 accumulation-order differences on real TPUs
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)
