"""Disaggregated serving (round 12): chunked prefill + prefill/decode
split over the paged-KV block handoff (serving/disagg.py).

The acceptance contract: greedy outputs are TOKEN-IDENTICAL across all
three serving modes — whole prefill, chunked prefill, disaggregated
prefill->decode handoff — against the sequential ``generate()`` oracle,
the decode ROLE compiles its decode step exactly once, and a replica
kill at any of ``serve.chunk`` / ``serve.handoff`` /
``serve.handoff_drop`` ends with every request COMPLETED (token-exact)
or FAILED-within-retry-budget while the shared pool's free+refcounted
accounting balances after recovery.
"""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import build_model
from deepspeed_tpu.models.generation import generate
from deepspeed_tpu.runtime import heartbeat as hb
from deepspeed_tpu.serving.disagg import (BlockHandoff, DisaggEngine,
                                          HandoffFull, HandoffItem)
from deepspeed_tpu.serving.engine import ServingEngine
from deepspeed_tpu.serving.fleet import ServingFleet
from deepspeed_tpu.serving.kv_cache import BlockPool
from deepspeed_tpu.serving.scheduler import FINISHED, Request, TIMEOUT
from deepspeed_tpu.testing import chaos


@pytest.fixture(scope="module")
def tiny():
    # f32: greedy token-exactness across differently-fused programs (see
    # test_serving.py's fixture note)
    model, cfg = build_model(
        "gpt2-tiny", hidden_size=32, num_layers=2, num_heads=2,
        vocab_size=64, max_seq_len=256, attention_impl="reference",
        dtype=jnp.float32)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, params


def _oracle_tokens(cfg, params, prompt, n):
    out = generate(cfg, params, jnp.asarray([list(prompt)]), n)
    return [int(x) for x in np.asarray(out)[0][len(prompt):]]


SERVE_CFG = {"block_size": 16, "pool_blocks": 64, "max_batch": 4,
             "max_blocks_per_seq": 8}


def _fleet_serving(prefill=1, decode=1, chunk=10, **fleet_kw):
    fleet = {"prefill_replicas": prefill, "decode_replicas": decode,
             "poll_interval": 0.05, "heartbeat_interval": 0.02,
             "heartbeat_timeout": 60.0}
    fleet.update(fleet_kw)
    return dict(SERVE_CFG, max_batch=2, prefill_chunk_tokens=chunk,
                fleet=fleet)


# ---------------------------------------------------------------------------
# the acceptance-criteria three-mode matrix
# ---------------------------------------------------------------------------

# tier-2 (round-17 budget sweep, ~14s): the cheaper tier-1 cousins are
# test_chunked_prefill_fairness_no_stall_beyond_one_chunk,
# test_disagg_handoff_chaos_refcount_exact and
# test_init_inference_serve_disagg_entry; scripts/chaos.sh and
# scripts/tier2.sh run this acceptance matrix
@pytest.mark.slow
def test_three_modes_staggered_token_exact(tiny):
    """Whole prefill, chunked prefill (non-block-aligned chunk) and the
    disaggregated pair produce IDENTICAL greedy outputs for a staggered
    multi-request load — and the disagg decode role compiles exactly one
    decode step while its prefill role never traces one."""
    cfg, params = tiny
    rng = np.random.default_rng(7)
    # 4 distinct lengths over 6 requests: mixed-length coverage while
    # the sequential-generate oracle compiles only 4 programs
    lens = [5, 11, 21, 33, 11, 5]
    prompts = [list(rng.integers(1, 64, size=n)) for n in lens]
    new = 6
    oracles = [_oracle_tokens(cfg, params, p, new) for p in prompts]

    def drive(eng):
        reqs = [eng.submit(p, new) for p in prompts[:3]]
        eng.step(); eng.step()
        reqs += [eng.submit(p, new) for p in prompts[3:]]
        for _ in range(2000):
            if eng.idle:
                break
            eng.step()
        return [r.output_tokens for r in reqs]

    whole = drive(ServingEngine(cfg, params, serving=SERVE_CFG))
    chunked = drive(ServingEngine(
        cfg, params, serving=dict(SERVE_CFG, prefill_chunk_tokens=10)))
    dis = DisaggEngine(cfg, params,
                       serving=dict(SERVE_CFG, prefill_chunk_tokens=10))
    disagg = drive(dis)
    for p, o, w, c, d in zip(prompts, oracles, whole, chunked, disagg):
        assert w == o, f"whole diverged for {p}"
        assert c == o, f"chunked diverged for {p}"
        assert d == o, f"disagg diverged for {p}"
    # fixed-shape discipline across the split: decode role compiles its
    # decode step ONCE and never traces a prefill; prefill role never
    # traces a decode
    cache_size = getattr(dis.decode._decode_fn, "_cache_size", None)
    if cache_size is not None:
        assert cache_size() == 1
        assert dis.decode._prefill_fn._cache_size() == 0
        assert dis.prefill._decode_fn._cache_size() == 0
    dis.close()


@pytest.mark.slow
def test_three_modes_arch_matrix_token_exact():
    """The acceptance arch matrix: ALiBi+softcap and GQA+rotary+RMSNorm
    serve token-identical across whole / chunked / disagg modes."""
    archs = [
        dict(pos_embed="alibi", attn_softcap=20.0, final_logit_softcap=15.0,
             norm="layernorm"),
        dict(pos_embed="rotary", norm="rmsnorm", gated_mlp=True,
             activation="silu", num_kv_heads=2, tie_embeddings=False),
    ]
    rng = np.random.default_rng(13)
    for kw in archs:
        model, cfg = build_model("gpt2-tiny", hidden_size=32, num_layers=2,
                                 num_heads=4, vocab_size=64, max_seq_len=128,
                                 attention_impl="reference",
                                 dtype=jnp.float32, **kw)
        ids = np.zeros((1, 8), np.int32)
        params = model.init(jax.random.PRNGKey(1),
                            {"input_ids": ids})["params"]
        prompts = [list(rng.integers(1, 64, size=n)) for n in (6, 21, 33)]
        oracles = [_oracle_tokens(cfg, params, p, 5) for p in prompts]
        scfg = {"block_size": 16, "pool_blocks": 32, "max_batch": 3,
                "max_blocks_per_seq": 8}
        for mode, eng in (
                ("whole", ServingEngine(cfg, params, serving=scfg)),
                ("chunked", ServingEngine(
                    cfg, params,
                    serving=dict(scfg, prefill_chunk_tokens=10))),
                ("disagg", DisaggEngine(
                    cfg, params,
                    serving=dict(scfg, prefill_chunk_tokens=10)))):
            outs = eng.generate_batch(prompts, max_new_tokens=5)
            for p, o, got in zip(prompts, oracles, outs):
                assert got == o, f"arch {kw} mode {mode} diverged"


# ---------------------------------------------------------------------------
# handoff queue units (host-side, no model)
# ---------------------------------------------------------------------------

def test_handoff_bounded_and_deadline_aware():
    pool = BlockPool(num_blocks=16, block_size=4)
    ho = BlockHandoff(pool, capacity=1)

    def item(req):
        blocks = pool.alloc(1)
        return HandoffItem(req=req, blocks=blocks,
                           table=np.asarray(blocks, np.int32), ctx=4,
                           last_tok=1)

    a = item(Request(prompt=[1], max_new_tokens=4))
    b = item(Request(prompt=[2], max_new_tokens=4))
    ho.push(a)
    with pytest.raises(HandoffFull):
        ho.push(b)                      # bounded: backpressure, no drop
    assert ho.pending == 1
    got = ho.pop()
    assert got is a and ho.pop() is None
    pool.release(got.blocks)
    # deadline-aware: an expired item is shed with TIMEOUT + release
    done = []
    expired_req = Request(prompt=[3], max_new_tokens=4,
                          deadline_ts=time.monotonic() - 1.0,
                          on_finish=lambda r: done.append(r.state))
    c = item(expired_req)
    ho.push(c)
    shed = ho.shed_expired()
    assert [it.req.rid for it in shed] == [expired_req.rid]
    assert expired_req.state == TIMEOUT and done == [TIMEOUT]
    pool.release(b.blocks)
    assert pool.used_count == 0         # every path returned its blocks


def test_handoff_push_failpoint_leaves_blocks_with_caller():
    """serve.handoff fires BEFORE the enqueue: the item is never
    half-queued, the blocks stay with the (dying) pusher — and a retry
    succeeds (the standalone prefill role's backpressure path)."""
    pool = BlockPool(num_blocks=8, block_size=4)
    ho = BlockHandoff(pool, capacity=4)
    blocks = pool.alloc(1)
    it = HandoffItem(req=Request(prompt=[1], max_new_tokens=2),
                     blocks=blocks, table=np.asarray(blocks, np.int32),
                     ctx=4, last_tok=0)
    chaos.arm("serve.handoff", "raise", times=1)
    try:
        with pytest.raises(chaos.ChaosError):
            ho.push(it)
        assert ho.pending == 0 and pool.refcount(blocks[0]) == 1
        ho.push(it)                     # retry lands
        assert ho.pending == 1
    finally:
        chaos.disarm()


# ---------------------------------------------------------------------------
# chunked-prefill + handoff refcount exactness under chaos (standalone)
# ---------------------------------------------------------------------------

def test_disagg_handoff_chaos_refcount_exact(tiny):
    """A serve.handoff crash mid-run: the pushed-but-failed item is
    retried, every request finishes token-exact, and afterwards the
    shared pool shows NO leak and NO double-free (release raises on
    double-free, so a clean used_count==0 after cache clear proves
    both)."""
    cfg, params = tiny
    rng = np.random.default_rng(11)
    prompts = [list(rng.integers(1, 64, size=n)) for n in (5, 21, 33)]
    eng = DisaggEngine(cfg, params,
                       serving=dict(SERVE_CFG, prefill_chunk_tokens=10))
    reqs = [eng.submit(p, 6) for p in prompts]
    chaos.arm("serve.handoff", "raise", times=1)
    try:
        raised = False
        for _ in range(2000):
            if eng.idle:
                break
            try:
                eng.step()
            except chaos.ChaosError:
                raised = True
        assert raised and chaos.fired("serve.handoff")
        for p, r in zip(prompts, reqs):
            assert r.state == FINISHED
            assert r.output_tokens == _oracle_tokens(cfg, params, p, 6)
        eng.shared.prefix_cache.clear()
        assert eng.pool.used_count == 0
    finally:
        chaos.disarm()


# ---------------------------------------------------------------------------
# chunked-prefill admission fairness
# ---------------------------------------------------------------------------

def test_chunked_prefill_fairness_no_stall_beyond_one_chunk(tiny):
    """A long prompt admitted mid-decode must not stall running lanes:
    with chunked prefill every loop iteration still runs the decode
    step, so the running request gains EXACTLY one token per iteration
    (max inter-token gap = 1 iteration) while the long prefill spans
    multiple iterations."""
    cfg, params = tiny
    eng = ServingEngine(cfg, params,
                        serving=dict(SERVE_CFG, prefill_chunk_tokens=16))
    rng = np.random.default_rng(5)
    short_prompt = list(rng.integers(1, 64, size=5))
    runner = eng.submit(short_prompt, 24)
    eng.step()                           # admitted + prefill started
    eng.step()                           # single chunk done + 1st decode
    assert runner.state == "RUNNING"
    long_prompt = list(rng.integers(1, 64, size=80))   # 5 chunks of 16
    eng.submit(long_prompt, 4)
    prefill_iters = 0
    while len(runner.output_tokens) < 24:
        before = len(runner.output_tokens)
        eng.step()
        if eng._prefilling is not None:
            prefill_iters += 1
        assert len(runner.output_tokens) == before + 1, \
            "running lane stalled behind the long prefill"
    assert prefill_iters >= 2, "long prompt should span several chunks"
    assert runner.output_tokens == _oracle_tokens(cfg, params,
                                                  short_prompt, 24)


# ---------------------------------------------------------------------------
# disagg fleet: kill matrix (tier-1 keeps one failpoint; slow runs all)
# ---------------------------------------------------------------------------

def _drive_fleet_kill(tiny, failpoint, skip=1):
    cfg, params = tiny
    rng = np.random.default_rng(7)
    prompts = [list(rng.integers(1, 64, size=n)) for n in (5, 21, 33, 11)]
    emitted = {}
    flt = ServingFleet(cfg, params,
                       serving=_fleet_serving(retry_budget=3))
    reqs = [flt.submit(
        p, 8, on_token=lambda r, t: emitted.setdefault(r.rid, [])
        .append(t)) for p in prompts]
    chaos.arm(failpoint, "raise", times=1, skip=skip)
    try:
        flt.start()
        assert flt.drain(timeout=180), f"{failpoint}: drain failed"
        assert chaos.fired(failpoint)
        assert flt.stats["deaths"] == 1
        for p, r in zip(prompts, reqs):
            oracle = _oracle_tokens(cfg, params, p, 8)
            assert r.state == FINISHED, (failpoint, r.state, r.error)
            assert r.output_tokens == oracle, \
                f"{failpoint}: request {r.rid} diverged after recovery"
            assert emitted[r.rid] == oracle, \
                f"{failpoint}: token re-fired or dropped"
        flt.close()
        flt._drain_quarantine()
        # accounting balance: after release of the prefix cache's own
        # refs, free + refcounted must cover the whole pool (no leak; a
        # double-free would have raised inside release)
        flt._shared.prefix_cache.clear()
        assert flt._shared.pool.used_count == 0, \
            f"{failpoint}: leaked {flt._shared.pool.used_count} blocks"
        return flt
    finally:
        chaos.disarm()


@pytest.mark.slow
def test_disagg_fleet_kill_at_handoff_exactly_once(tiny):
    """Prefill replica killed AT the handoff push: blocks released via
    quarantine, half-done request requeued exactly-once, outputs
    token-exact, pool accounting balanced. (slow: the tier-1 cousins are
    the single-request serve.chunk fleet kill below and the standalone
    serve.handoff chaos leg; scripts/chaos.sh and tier2 run this and the
    full matrix.)"""
    flt = _drive_fleet_kill(tiny, "serve.handoff")
    death = flt.deaths[0]
    assert death["replica"] == 0 and death["reason"] == "crash"
    assert flt.stats["restarts"] == 1


@pytest.mark.slow
def test_disagg_fleet_crash_matrix_all_failpoints(tiny):
    """The full crash-at-every-failpoint matrix: serve.chunk (prefill
    mid-chunk), serve.handoff (push), serve.handoff_drop (pop->install
    window on the decode side)."""
    for fp in ("serve.chunk", "serve.handoff", "serve.handoff_drop"):
        _drive_fleet_kill(tiny, fp)


def test_disagg_fleet_requeue_carries_chunk_progress(tiny):
    """A prefill replica killed mid-chunk requeues its half-prefilled
    request with the chunk progress carried (observability contract) —
    and the retry still completes token-exact."""
    cfg, params = tiny
    rng = np.random.default_rng(3)
    long_prompt = list(rng.integers(1, 64, size=40))   # 4 chunks of 10
    flt = ServingFleet(cfg, params, serving=_fleet_serving(retry_budget=2))
    req = flt.submit(long_prompt, 6)
    # skip=2: the kill lands on a LATER chunk of the same prefill, so
    # progress is provably > 0 when the replica dies
    chaos.arm("serve.chunk", "raise", times=1, skip=2)
    try:
        flt.start()
        assert req.wait(timeout=120)
        assert req.state == FINISHED
        assert req.output_tokens == _oracle_tokens(cfg, params,
                                                   long_prompt, 6)
        assert req.retries == 1
        assert req.prefill_progress > 0, \
            "chunk progress of the dead leg should be carried"
        flt.close()
    finally:
        chaos.disarm()


# ---------------------------------------------------------------------------
# roles visible in dstpu health; init_inference entry
# ---------------------------------------------------------------------------

def test_health_shows_prefill_decode_roles(tmp_path, capsys):
    """PREFILL/DECODE role gauges ride the heartbeat records into
    ``dstpu health`` (round-12 acceptance: roles visible)."""
    from deepspeed_tpu.launcher.runner import health_main
    w0 = hb.HeartbeatWriter(str(tmp_path), rank=0, host="replica-0")
    w0.write(hb.PHASE_SERVE, 3, force=True,
             extra={"queue": 1, "active": 1, "lanes": 2,
                    "role": "PREFILL", "handoff": 0})
    w1 = hb.HeartbeatWriter(str(tmp_path), rank=1, host="replica-1")
    w1.write(hb.PHASE_SERVE, 9, force=True,
             extra={"queue": 0, "active": 2, "lanes": 2,
                    "role": "DECODE", "handoff": 0})
    rc = health_main([str(tmp_path)])
    out = capsys.readouterr().out
    assert "role=PREFILL" in out and "role=DECODE" in out
    assert rc == 0


@pytest.mark.slow
def test_disagg_fleet_stamps_role_gauges(tiny, tmp_path):
    """End-to-end: a running disagg fleet's heartbeat records carry the
    role gauge per replica. (slow: the tier-1 cousin is the record-level
    health rendering test above.)"""
    cfg, params = tiny
    flt = ServingFleet(cfg, params, serving=_fleet_serving(),
                       heartbeat_dir=str(tmp_path))
    try:
        flt.start()
        r = flt.submit([1, 2, 3, 4, 5], 4)
        assert r.wait(timeout=60) and r.state == FINISHED
        records = hb.read_heartbeats(str(tmp_path))
        roles = {rank: (rec.get("gauges") or {}).get("role")
                 for rank, rec in records.items()}
        assert roles.get(0) == "PREFILL" and roles.get(1) == "DECODE"
    finally:
        flt.close()


def test_init_inference_serve_disagg_entry(tiny):
    """serve() with fleet.prefill_replicas/decode_replicas returns a
    started disagg fleet even at replicas=1; output token-exact."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer
    cfg, params = tiny
    eng = deepspeed_tpu.init_inference(
        Transformer(cfg),
        {"dtype": "float32",
         "serving": dict(SERVE_CFG, max_batch=2, prefill_chunk_tokens=10,
                         fleet={"prefill_replicas": 1,
                                "decode_replicas": 1,
                                "poll_interval": 0.05})},
        model_parameters=params)
    srv = eng.serve()
    assert isinstance(srv, ServingFleet) and srv.disagg
    try:
        out = srv.generate_batch([[3, 1, 4, 1, 5], [2, 7, 2]],
                                 max_new_tokens=4)
        assert out[0] == _oracle_tokens(cfg, params, [3, 1, 4, 1, 5], 4)
        assert out[1] == _oracle_tokens(cfg, params, [2, 7, 2], 4)
    finally:
        srv.close()


def test_fleet_rejects_one_sided_disagg(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError):
        ServingFleet(cfg, params,
                     serving=dict(SERVE_CFG,
                                  fleet={"prefill_replicas": 1,
                                         "decode_replicas": 0}))
    # the serve() entry must also reject it — falling through to plain
    # single-engine serving would silently drop the operator's intent
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer
    eng = deepspeed_tpu.init_inference(
        Transformer(cfg),
        {"dtype": "float32",
         "serving": dict(SERVE_CFG, fleet={"prefill_replicas": 1})},
        model_parameters=params)
    with pytest.raises(ValueError):
        eng.serve()


# ---------------------------------------------------------------------------
# serving-bench record / newest-recorded-sweep regression units
# ---------------------------------------------------------------------------

def test_serve_bench_record_discovery_regression(tmp_path):
    from deepspeed_tpu.benchmarks.inference_bench import (
        check_serve_regression, latest_serve_bench, record_serve_bench)
    rows = [{"mode": "poisson", "preset": "gpt2-125m", "rate": 4.0,
             "prompt": 64, "new_tokens": 24, "chunk": 0,
             "p50_s": 0.5, "p99_s": 0.9, "tokens_per_s": 120.0}]
    path = tmp_path / "SERVEBENCH_r01.json"
    record_serve_bench(rows, str(path))
    name, base = latest_serve_bench(str(tmp_path), jax.device_count())
    assert name == "SERVEBENCH_r01.json" and len(base) == 1
    # p50 blow-up and tokens/s collapse both flag; a mild change doesn't
    assert check_serve_regression([dict(rows[0], p50_s=2.0)], base)
    assert check_serve_regression([dict(rows[0], tokens_per_s=10.0)], base)
    assert not check_serve_regression([dict(rows[0], p50_s=0.6)], base)
    # a different-rate row is a different cell: not compared
    assert not check_serve_regression([dict(rows[0], rate=8.0,
                                            p50_s=5.0)], base)
    # sweeps from another device count are skipped
    other = tmp_path / "SERVEBENCH_r02.json"
    other.write_text(json.dumps({"n": 4096, "rows": rows}))
    import os
    os.utime(other, (time.time() + 60, time.time() + 60))
    name2, _ = latest_serve_bench(str(tmp_path), jax.device_count())
    assert name2 == "SERVEBENCH_r01.json"
