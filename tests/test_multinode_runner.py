"""multinode_runner command construction + BackendSupervisor (round 6).

Previously untested surface: the exact argv each scheduler backend gets
(pdsh -S/-w host list, slurm node-name handling, mvapich env injection),
plus the round-6 supervision deltas — kill paths, per-rank output
routing, and the BackendSupervisor's heartbeat-driven teardown and rc
reconstruction over a fake scheduler process.
"""

import io
import os
import shlex
import sys
import time
import types

import pytest

from deepspeed_tpu.elasticity.elastic_agent import PREEMPTION_EXIT_CODE
from deepspeed_tpu.launcher.multinode_runner import (MVAPICHRunner,
                                                     OpenMPIRunner,
                                                     PDSHRunner, SlurmRunner,
                                                     build_runner)
from deepspeed_tpu.launcher.supervisor import BackendSupervisor
from deepspeed_tpu.runtime import heartbeat as hb
from deepspeed_tpu.runtime.watchdog import STALL_EXIT_CODE

PY = sys.executable


def _args(**kw):
    ns = types.SimpleNamespace(user_script="train.py", user_args=["--x", "1"],
                               hostfile="/job/hostfile", include="")
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


# --------------------------------------------------- command construction

def test_pdsh_cmd_fanout_flags_and_host_list():
    r = PDSHRunner(_args())
    r.add_export("XLA_FLAGS", "--flag=1")
    cmd = r.get_cmd({"DSTPU_COORDINATOR": "w1"},
                    {"w1": [0], "w2": [0], "w3": [0]})
    assert cmd[0] == "pdsh"
    assert "-S" in cmd                     # propagate the LARGEST rank rc
    assert cmd[cmd.index("-w") + 1] == "w1,w2,w3"
    joined = " ".join(cmd)
    assert f"export XLA_FLAGS={shlex.quote('--flag=1')}" in joined
    assert "cd " in joined                 # remote shells land in the cwd
    assert "--node_rank=-1" in joined      # rank autodetected per host
    assert cmd[-3:] == ["train.py", "--x", "1"]


def test_slurm_cmd_strips_slot_parts_from_nodelist():
    """The include syntax's ':slot' parts are not valid slurm node names
    — the nodelist must carry BARE hostnames (what the filtered pool's
    keys already are)."""
    from deepspeed_tpu.launcher.runner import parse_inclusion_exclusion
    pool = {"w1": 4, "w2": 4, "w3": 4}
    active = parse_inclusion_exclusion(pool, include_str="w1:0,2@w3")
    cmd = SlurmRunner(_args()).get_cmd({"E": "v"}, active)
    nodelist = cmd[cmd.index("--nodelist") + 1]
    assert nodelist == "w1,w3"
    assert ":" not in nodelist
    assert "--ntasks-per-node=1" in cmd
    assert "--label" in cmd                # per-rank output attribution
    assert any(c.startswith("--export=ALL,") and "E=v" in c for c in cmd)


def test_openmpi_cmd_one_rank_per_node_and_env_x_flags():
    cmd = OpenMPIRunner(_args(hostfile="/tmp/hf")).get_cmd(
        {"E": "v"}, {"a": [0], "b": [0]})
    assert cmd[:3] == ["mpirun", "-n", "2"]
    assert cmd[cmd.index("--hostfile") + 1] == "/tmp/hf"
    assert cmd[cmd.index("--map-by") + 1] == "ppr:1:node"
    assert "-x" in cmd and "E=v" in cmd


def test_mvapich_env_detection_and_injection(monkeypatch):
    """mpirun_rsh takes bare K=V argv (no -x): the MV2 defaults are
    injected when absent, never clobbering explicit settings."""
    r = MVAPICHRunner(_args(hostfile="/tmp/hf"))
    cmd = r.get_cmd({"E": "v"}, {"a": [0], "b": [0]})
    assert cmd[:3] == ["mpirun_rsh", "-np", "2"]
    assert cmd[cmd.index("-hostfile") + 1] == "/tmp/hf"
    assert "MV2_SMP_USE_CMA=0" in cmd and "MV2_DEBUG_SHOW_BACKTRACE=1" in cmd
    assert "E=v" in cmd
    # explicit env beats the injected default
    cmd = r.get_cmd({"MV2_SMP_USE_CMA": "1"}, {"a": [0]})
    assert "MV2_SMP_USE_CMA=1" in cmd and "MV2_SMP_USE_CMA=0" not in cmd
    # backend detection probes for mpirun_rsh, not mpirun
    probed = []
    monkeypatch.setattr("shutil.which",
                        lambda name: probed.append(name) or None)
    assert not r.backend_exists()
    assert probed == ["mpirun_rsh"]


def test_build_runner_rejects_unknown_backend():
    with pytest.raises(ValueError, match="unknown launcher"):
        build_runner("nope", _args())


# ------------------------------------------------------------- kill paths

def test_pdsh_kill_cmd_targets_active_hosts():
    cmd = PDSHRunner(_args()).get_kill_cmd({}, {"w1": [0], "w2": [0]})
    assert cmd[0] == "pdsh"
    assert cmd[cmd.index("-w") + 1] == "w1,w2"
    assert any("pkill" in c and "deepspeed_tpu.launcher.launch" in c
               for c in cmd)


def test_slurm_kill_cmd_is_scancel_of_the_allocation(monkeypatch):
    r = SlurmRunner(_args())
    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    assert r.get_kill_cmd({}, {"a": [0]}) is None      # no allocation
    assert r.get_kill_cmd({"SLURM_JOB_ID": "1234"}, {"a": [0]}) == \
        ["scancel", "--signal=TERM", "1234"]
    monkeypatch.setenv("SLURM_JOB_ID", "77")
    assert r.get_kill_cmd({}, {"a": [0]}) == ["scancel", "--signal=TERM",
                                              "77"]


def test_openmpi_has_no_separate_kill_path():
    # mpirun forwards SIGTERM to its children itself
    assert OpenMPIRunner(_args()).get_kill_cmd({}, {"a": [0]}) is None


# ---------------------------------------------------------- output routing

def test_route_line_per_backend():
    assert PDSHRunner(_args()).route_line("w2: hello\n") == ("w2", "hello\n")
    assert PDSHRunner(_args()).route_line("no prefix here\n") is None
    assert SlurmRunner(_args()).route_line("3: payload\n") == \
        ("rank3", "payload\n")
    assert SlurmRunner(_args()).route_line("w2: named\n") is None
    assert OpenMPIRunner(_args()).route_line("anything\n") is None


# ------------------------------------------------------- BackendSupervisor

def test_backend_supervisor_clean_run_routes_logs(tmp_path):
    """A pdsh-style merged stream demultiplexes into per-host files and
    still mirrors to the live stream."""
    buf = io.StringIO()
    script = ("import sys\n"
              "print('w1: alpha'); print('w2: beta'); print('scheduler note')\n")
    sup = BackendSupervisor([PY, "-c", script],
                            log_dir=str(tmp_path / "logs"), stream=buf,
                            route_line=PDSHRunner(_args()).route_line,
                            backend="pdsh", heartbeat_poll=0.05)
    assert sup.run() == 0
    assert (tmp_path / "logs" / "w1.log").read_text() == "alpha\n"
    assert (tmp_path / "logs" / "w2.log").read_text() == "beta\n"
    assert "scheduler note" in (tmp_path / "logs" / "pdsh.log").read_text()
    assert "w1: alpha" in buf.getvalue()


@pytest.mark.slow
def test_backend_supervisor_heartbeat_silence_triggers_backend_kill_path(
        tmp_path):
    """Acceptance: a heartbeat-silent simulated backend rank triggers
    teardown THROUGH the backend's own kill command, and the run reports
    the stall rc."""
    hb_dir = tmp_path / "hb"
    t = [1000.0]
    w = hb.HeartbeatWriter(str(hb_dir), 0, host="w1", refresh_interval=0,
                           clock=lambda: t[0])
    marker = tmp_path / "killed"
    t0 = time.monotonic()
    sup = BackendSupervisor(
        [PY, "-c", "import time; time.sleep(120)"],
        # sh, not a fresh python: interpreter startup on a loaded CI host
        # can exceed the kill-cmd timeout (max(grace_secs, 1.0))
        kill_cmd=["/bin/sh", "-c", f"printf scancel > {marker}"],
        heartbeat_dir=str(hb_dir), heartbeat_timeout=0.3,
        heartbeat_poll=0.05, grace_secs=2.0, stream=io.StringIO()).start()
    # the rank attests once AFTER the run starts (start() scopes the
    # channel to this run), then goes silent forever
    w.write(hb.PHASE_STEP, 12, force=True)
    rc = sup.wait(timeout=60)
    assert rc == STALL_EXIT_CODE
    assert time.monotonic() - t0 < 30
    assert marker.read_text() == "scancel"        # backend kill path ran
    assert sup.failed_hosts() == ["w1"]


@pytest.mark.slow
def test_backend_supervisor_reconstructs_preemption_rc(tmp_path):
    """srun flattens rc 114 into its own step rc; the workers' PREEMPTED
    terminal records restore the contract."""
    hb_dir = tmp_path / "hb"
    w = hb.HeartbeatWriter(str(hb_dir), 0, host="w1", refresh_interval=0)
    sup = BackendSupervisor(
        [PY, "-c", "import time; time.sleep(0.4); raise SystemExit(1)"],
        heartbeat_dir=str(hb_dir),
        heartbeat_poll=0.05, stream=io.StringIO()).start()
    w.write(hb.PHASE_PREEMPTED, 30, force=True)   # this run's final word
    assert sup.wait(timeout=60) == PREEMPTION_EXIT_CODE


@pytest.mark.slow
def test_backend_supervisor_stalled_evidence_beats_scheduler_rc(tmp_path):
    hb_dir = tmp_path / "hb"
    w = hb.HeartbeatWriter(str(hb_dir), 0, host="w1", refresh_interval=0)
    sup = BackendSupervisor(
        [PY, "-c", "import time; time.sleep(0.4); raise SystemExit(9)"],
        heartbeat_dir=str(hb_dir),
        heartbeat_poll=0.05, stream=io.StringIO()).start()
    w.write(hb.PHASE_STALLED, 8, force=True)      # this run's final word
    assert sup.wait(timeout=60) == STALL_EXIT_CODE
    assert sup.failed_hosts() == ["w1"]


@pytest.mark.slow
def test_backend_supervisor_sdc_flag_names_host_scheduler_rc_cannot(
        tmp_path):
    """The scheduler flattens every rank's rc 118 into one step rc; the
    flagged heartbeat record is the only per-host SDC attribution."""
    hb_dir = tmp_path / "hb"
    sup = BackendSupervisor(
        [PY, "-c", "import time; time.sleep(0.8); raise SystemExit(118)"],
        heartbeat_dir=str(hb_dir), heartbeat_poll=0.05,
        stream=io.StringIO()).start()
    w = hb.HeartbeatWriter(str(hb_dir), 1, host="w2", refresh_interval=0)
    w.write(hb.PHASE_STEP, 50, force=True)
    w.add_flag("SDC")
    assert sup.wait(timeout=60) == 118
    assert sup.failed_hosts() == ["w2"]


def test_backend_supervisor_clean_exit_wins_over_old_noise(tmp_path):
    """The channel is run-scoped: a reused dir holding a PREVIOUS run's
    STALLED verdict and a stale mid-step record must not reconstruct a
    clean run's rc as 117 (the agent would restart a succeeding world
    until max_restarts) nor trip the silence monitor at t=0."""
    hb_dir = tmp_path / "hb"
    prev = hb.HeartbeatWriter(str(hb_dir), 1, host="w2", refresh_interval=0,
                              clock=lambda: 1000.0)
    prev.write(hb.PHASE_STALLED, 40, force=True)  # last run's verdict
    stale = hb.HeartbeatWriter(str(hb_dir), 0, host="w1", refresh_interval=0,
                               clock=lambda: 1000.0)
    stale.write(hb.PHASE_STEP, 12, force=True)    # ancient mid-step record
    sup = BackendSupervisor([PY, "-c", "pass"],
                            heartbeat_dir=str(hb_dir),
                            heartbeat_timeout=120.0, heartbeat_poll=0.05,
                            stream=io.StringIO())
    assert sup.run() == 0
    assert sup.failed_hosts() == []


@pytest.mark.slow
def test_backend_supervisor_detects_rank_that_never_writes(tmp_path):
    """A host dead BEFORE launch.py ever runs produces no record at all;
    expected_ranks (from rank_hosts) makes that silence detectable and
    attributable in hostfile vocabulary."""
    hb_dir = tmp_path / "hb"
    live = hb.HeartbeatWriter(str(hb_dir), 0, host="w1",
                              refresh_interval=0.05)
    t0 = time.monotonic()
    sup = BackendSupervisor([PY, "-c", "import time; time.sleep(120)"],
                            heartbeat_dir=str(hb_dir), heartbeat_timeout=0.4,
                            heartbeat_poll=0.05, grace_secs=0.5,
                            rank_hosts=["w1", "w2"],
                            stream=io.StringIO()).start()
    live.write(hb.PHASE_STEP, 5, force=True)      # rank 0 attests; rank 1 never
    rc = sup.wait(timeout=60)
    live.close()
    assert rc == STALL_EXIT_CODE
    assert time.monotonic() - t0 < 30
    assert sup.failed_hosts() == ["w2"]


def test_backend_supervisor_popen_facade(tmp_path):
    import subprocess
    sup = BackendSupervisor([PY, "-c", "import time; time.sleep(120)"],
                            grace_secs=0.5, heartbeat_poll=0.05,
                            stream=io.StringIO()).start()
    assert sup.poll() is None
    with pytest.raises(subprocess.TimeoutExpired):
        sup.wait(timeout=0.2)
    sup.terminate()
    rc = sup.wait(timeout=30)
    assert rc != 0
    assert sup.poll() == rc == sup.returncode


# ------------------------------------------------- runner-side integration

def test_build_backend_supervisor_wires_runner_surfaces(tmp_path,
                                                        monkeypatch):
    from collections import OrderedDict

    from deepspeed_tpu.launcher.runner import build_backend_supervisor
    monkeypatch.setattr("shutil.which", lambda name: "/usr/bin/" + name)
    args = _args(launcher="pdsh", master_addr="", master_port=29500,
                 grace_secs=7.0, log_dir="", heartbeat_dir=str(tmp_path),
                 heartbeat_timeout=45.0)
    active = OrderedDict([("w1", [0]), ("w2", [0])])
    sup = build_backend_supervisor(active, args, {"DSTPU_X": "1"})
    assert sup.cmd[0] == "pdsh"
    assert "DSTPU_X=1" in " ".join(sup.cmd)
    assert sup.kill_cmd[0] == "pdsh"
    assert sup.grace_secs == 7.0
    assert sup.heartbeat_monitor is not None
    assert sup.heartbeat_monitor.timeout == 45.0
    assert sup.backend == "pdsh"
    assert not sup._started                       # not launched yet


def test_dstpu_health_subcommand(tmp_path, capsys):
    from deepspeed_tpu.launcher.runner import health_main
    w0 = hb.HeartbeatWriter(str(tmp_path), 0, host="w0", refresh_interval=0)
    w0.write(hb.PHASE_STEP, 120, force=True)
    w1 = hb.HeartbeatWriter(str(tmp_path), 1, host="w1", refresh_interval=0)
    w1.write(hb.PHASE_STALLED, 88, force=True)
    rc = health_main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1                                # a wedged rank is news
    assert "w0" in out and "STEP" in out and "120" in out
    assert "w1" in out and "STALLED" in out and "wedged" in out
    # empty channel: nothing provably alive
    assert health_main([str(tmp_path / "empty")]) == 1


def test_dstpu_health_flags_column_and_rc(tmp_path, capsys):
    """Round-7 satellite: integrity flags (SDC from the cross-replica
    audit) surface in a FLAGS column and flip the exit code — a host
    whose numbers can't be trusted is operator news even while its
    process is alive and stepping."""
    from deepspeed_tpu.launcher.runner import health_main
    w0 = hb.HeartbeatWriter(str(tmp_path), 0, host="w0", refresh_interval=0)
    w0.write(hb.PHASE_STEP, 200, force=True)
    w1 = hb.HeartbeatWriter(str(tmp_path), 1, host="w1", refresh_interval=0)
    w1.write(hb.PHASE_STEP, 200, force=True)
    w1.add_flag("SDC")
    w1.stamp_terminal(hb.PHASE_EXIT)
    rc = health_main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "FLAGS" in out
    lines = {ln.split()[0]: ln for ln in out.splitlines() if ln.strip()}
    assert "SDC" in lines["1"] and "rc 118" in lines["1"]
    # a flagged EXIT is a concluded integrity abort, never a "clean exit"
    assert "clean exit" not in lines["1"]
    assert "SDC" not in lines["0"]
    w0.write(hb.PHASE_STEP, 201, force=True)      # unflagged world: rc 0
    import shutil
    clean = tmp_path / "clean"
    clean.mkdir()
    shutil.copy(w0.path, clean / "rank0.hb")
    assert health_main([str(clean)]) == 0
    capsys.readouterr()


def test_dstpu_health_rate_column(tmp_path, capsys):
    """Round-15 satellite: the rolling step_ms gauge renders as a RATE
    column ('-' for records predating the gauge), promoted OUT of the
    GAUGES column; rc semantics unchanged — a slow rank is the straggler
    DETECTOR's verdict to make, but a STRAGGLER flag (its verdict) is
    operator news and flips the rc like any flag."""
    from deepspeed_tpu.launcher.runner import health_main
    w0 = hb.HeartbeatWriter(str(tmp_path), 0, host="w0", refresh_interval=0)
    w0.write(hb.PHASE_STEP, 50, force=True, extra={"step_ms": 800.0})
    w1 = hb.HeartbeatWriter(str(tmp_path), 1, host="w1", refresh_interval=0)
    w1.write(hb.PHASE_STEP, 50, force=True)        # predates the gauge
    rc = health_main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0                                 # slow is not wedged
    header = out.splitlines()[0].split()
    assert header[:7] == ["RANK", "STAGE", "HOST", "PHASE", "STEP", "RATE",
                          "AGE"]
    rows = {ln.split()[0]: ln.split() for ln in out.splitlines()[1:]
            if ln.strip()}
    assert rows["0"][5] == "800ms"
    assert rows["1"][5] == "-"
    assert "step_ms=" not in out                   # promoted, not duplicated
    # the STRAGGLER flag (the detector's verdict) is news: rc 1, named
    w0.add_flag("STRAGGLER")
    rc = health_main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "STRAGGLER" in out and "straggler (slow host)" in out
    assert "rc 118" not in out                     # not an integrity abort


def test_dstpu_health_stage_column(tmp_path, capsys):
    """Round-13 satellite: MPMD stage workers stamp a pipeline-stage
    gauge; `dstpu health` promotes it to a STAGE column (the round-12
    role=PREFILL/DECODE pattern) so "which stage is that rank" is one
    glance. Non-pipeline ranks show '-', and the gauge is promoted OUT
    of the GAUGES column (no duplicate)."""
    from deepspeed_tpu.launcher.runner import health_main
    w0 = hb.HeartbeatWriter(str(tmp_path), 0, host="w0", refresh_interval=0)
    w0.write(hb.PHASE_STEP, 7, force=True, extra={"stage": 0})
    w1 = hb.HeartbeatWriter(str(tmp_path), 1, host="w1", refresh_interval=0)
    w1.write(hb.PHASE_STEP, 7, force=True, extra={"stage": 1, "q": 3})
    w2 = hb.HeartbeatWriter(str(tmp_path), 2, host="w2", refresh_interval=0)
    w2.write(hb.PHASE_STEP, 7, force=True)
    rc = health_main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    header = out.splitlines()[0].split()
    assert header[:3] == ["RANK", "STAGE", "HOST"]
    rows = {ln.split()[0]: ln.split() for ln in out.splitlines()[1:]
            if ln.strip()}
    assert rows["0"][1] == "0" and rows["1"][1] == "1"
    assert rows["2"][1] == "-"
    assert "stage=" not in out and "q=3" in out
