"""Comm-plan subsystem tests: plan round-trip + selection determinism,
the resolution ladder, the blockwise-int8 collectives (value + wire-byte
audits in test_onebit.py's HLO-parsing style), engine integration for the
ZeRO-2 int8 grad sync (multi-step parity vs the exact twin, accuracy
guard), the MoE int8 dispatch, the comm_bench record format, and the
``dstpu comm-plan`` CLI.
"""

import json
import os
import pathlib
import random
import socket
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu import comm_plan as cp
from deepspeed_tpu.comm_plan.runtime import (AccuracyGuard, PlanContext,
                                             resolve_algo)
from deepspeed_tpu.runtime.onebit import hlo_collective_bytes

from util import SimpleModel, random_batch, require_devices

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)


# ---------------------------------------------------------------- plan format

def _rows(shuffle_seed=None):
    rows = [
        {"op": "reduce_scatter", "algo": "exact", "axis": "all",
         "size_mb": 8.0, "size_bytes": 8 * 2 ** 20, "latency_us": 900.0},
        {"op": "reduce_scatter", "algo": "int8", "axis": "all",
         "size_mb": 8.0, "size_bytes": 8 * 2 ** 20, "latency_us": 400.0},
        {"op": "all_to_all", "algo": "exact", "axis": "all",
         "size_mb": 8.0, "size_bytes": 8 * 2 ** 20, "latency_us": 500.0},
        {"op": "all_to_all", "algo": "int8", "axis": "all",
         "size_mb": 8.0, "size_bytes": 8 * 2 ** 20, "latency_us": 700.0},
        {"op": "all_reduce", "algo": "exact", "axis": "all",
         "size_mb": 1.0, "size_bytes": 2 ** 20, "latency_us": 120.0},
        # a tie: exact must win (ALGOS-order tie-break, safer first)
        {"op": "all_reduce", "algo": "int8", "axis": "all",
         "size_mb": 1.0, "size_bytes": 2 ** 20, "latency_us": 120.0},
    ]
    if shuffle_seed is not None:
        random.Random(shuffle_seed).shuffle(rows)
    return rows


def test_plan_json_round_trip(tmp_path):
    plan = cp.select_plan(_rows(), meta={"n_devices": 8})
    path = str(tmp_path / "plan.json")
    plan.save(path)
    loaded = cp.CommPlan.load(path)
    assert loaded.to_json() == plan.to_json()
    assert loaded.meta == {"n_devices": 8}
    # entries survive with their provenance
    e = loaded.entry_for("reduce_scatter", "all", 8 * 2 ** 20)
    assert e.algo == "int8" and e.source == "sweep" and e.est_us == 400.0


def test_selector_deterministic_under_record_order():
    base = cp.select_plan(_rows()).to_json()
    for seed in range(5):
        assert cp.select_plan(_rows(shuffle_seed=seed)).to_json() == base


def test_selector_picks_fastest_and_breaks_ties_safely():
    plan = cp.select_plan(_rows())
    assert plan.choose("reduce_scatter", "all", 8 * 2 ** 20) == "int8"
    assert plan.choose("all_to_all", "all", 8 * 2 ** 20) == "exact"
    # tied latency: exact (lower ALGOS index) wins
    assert plan.choose("all_reduce", "all", 2 ** 20) == "exact"


def test_plan_rejects_unknown_algo_and_newer_version():
    bad = {"version": 1, "entries": [
        {"kind": "all_reduce", "axis": "all", "bucket": 20,
         "algo": "fp4"}]}
    with pytest.raises(ValueError, match="unknown algo"):
        cp.CommPlan.from_json(json.dumps(bad))
    with pytest.raises(ValueError, match="newer"):
        cp.CommPlan.from_json(json.dumps({"version": 99, "entries": []}))


def test_axis_wildcard_and_unknown_bucket():
    plan = cp.select_plan(_rows())
    # the "all" sweep row answers a query on a named axis
    assert plan.choose("reduce_scatter", "data", 8 * 2 ** 20) == "int8"
    # a bucket no sweep covered -> None (callers fall to heuristic)
    assert plan.choose("reduce_scatter", "data", 512 * 2 ** 20) is None


# ----------------------------------------------------------- resolution ladder

def test_resolve_unknown_bucket_falls_back_to_heuristic():
    ctx = PlanContext(plan=cp.select_plan(_rows()))
    # 512 MB: no plan entry -> heuristic -> int8 (over threshold)
    assert resolve_algo(ctx, "grad_reduce_scatter", "data",
                        512 * 2 ** 20, axis_size=8) == "int8"
    # 64 KB: no plan entry -> heuristic -> exact (latency floor)
    assert resolve_algo(ctx, "grad_reduce_scatter", "data",
                        64 * 2 ** 10, axis_size=8) == "exact"
    # single-member axis: always exact
    assert resolve_algo(ctx, "grad_reduce_scatter", "data",
                        512 * 2 ** 20, axis_size=1) == "exact"


def test_resolve_override_wins_and_validates():
    ctx = PlanContext(plan=cp.select_plan(_rows()),
                      overrides={"grad_reduce_scatter": "exact"})
    # the plan says int8 at 8MB; the site override forces exact
    assert resolve_algo(ctx, "grad_reduce_scatter", "data",
                        8 * 2 ** 20, axis_size=8) == "exact"
    # wire-kind override reaches the site too
    ctx2 = PlanContext(overrides={"all_to_all": "int8"})
    assert resolve_algo(ctx2, "moe_all_to_all", "expert",
                        1024, axis_size=2) == "int8"
    # unexecutable forced algo raises (never silently degrades)
    ctx3 = PlanContext(overrides={"grad_reduce_scatter": "onebit"})
    with pytest.raises(ValueError, match="not executable"):
        resolve_algo(ctx3, "grad_reduce_scatter", "data", 1024,
                     axis_size=8)


def test_plan_entry_with_site_unsupported_algo_falls_through():
    plan = cp.CommPlan()
    plan.add(cp.PlanEntry("reduce_scatter", "all",
                          cp.bucket_of(8 * 2 ** 20), "hierarchical"))
    ctx = PlanContext(plan=plan)
    # the entry names an algo the grad-sync seam can't execute: the
    # heuristic answers instead (int8 at 8MB)
    assert resolve_algo(ctx, "grad_reduce_scatter", "data",
                        8 * 2 ** 20, axis_size=8) == "int8"


def test_accuracy_guard_latches_on_small_norms():
    g = AccuracyGuard(0.5)
    assert not g.use_exact          # no observation yet: plan's choice
    g.observe(2.0)
    assert not g.use_exact
    g.observe(0.1)
    assert g.use_exact
    g.observe(float("nan"))         # overflow step: ignored
    assert g.use_exact
    g.observe(3.0)
    assert not g.use_exact


# ------------------------------------------------------ quantized collectives

@pytest.fixture()
def mesh8():
    require_devices(8)
    return Mesh(np.asarray(jax.devices()[:8]), ("data",))


def test_quantized_reduce_scatter_value(mesh8):
    from deepspeed_tpu.runtime.comm.quantized import quantized_reduce_scatter
    rng = np.random.default_rng(0)
    vals = rng.standard_normal((8, 5000)).astype(np.float32)
    x = jax.device_put(jnp.asarray(vals), NamedSharding(mesh8, P("data")))
    out = np.asarray(quantized_reduce_scatter(x, mesh=mesh8, axis="data",
                                              mean=True))
    want = vals.mean(axis=0)
    got = out.reshape(-1)[:5000]
    # blockwise scales: the error bound is per-BLOCK absmax / 127, far
    # tighter than a per-tensor scale on heavy-tailed data
    per_elem = np.abs(vals).max() / 127.0
    assert np.abs(got - want).max() <= per_elem * 1.01


def test_grad_sync_matches_mean_and_propagates_nonfinite(mesh8):
    from deepspeed_tpu.runtime.comm.quantized import grad_sync
    rng = np.random.default_rng(1)
    vals = rng.standard_normal((8, 4097)).astype(np.float32)  # odd size
    x = jax.device_put(jnp.asarray(vals), NamedSharding(mesh8, P("data")))
    want = vals.mean(axis=0)
    out_e = np.asarray(grad_sync(x, mesh=mesh8, axis="data", algo="exact"))
    np.testing.assert_allclose(out_e, want, rtol=0, atol=1e-6)
    out_q = np.asarray(grad_sync(x, mesh=mesh8, axis="data", algo="int8"))
    assert out_q.shape == want.shape
    assert np.abs(out_q - want).max() <= np.abs(vals).max() / 127 * 2
    # an inf on ONE rank must poison the synced result (overflow
    # detection downstream relies on propagation)
    bad = vals.copy()
    bad[3, 17] = np.inf
    xb = jax.device_put(jnp.asarray(bad), NamedSharding(mesh8, P("data")))
    out_b = np.asarray(grad_sync(xb, mesh=mesh8, axis="data", algo="int8"))
    assert not np.isfinite(out_b).all()


def test_quantized_all_to_all_matches_exact(mesh8):
    from deepspeed_tpu.runtime.comm.quantized import quantized_all_to_all
    from deepspeed_tpu.utils.jax_compat import shard_map
    rng = np.random.default_rng(2)
    vals = rng.standard_normal((64, 48)).astype(np.float32)
    x = jax.device_put(jnp.asarray(vals), NamedSharding(mesh8, P("data")))
    got = np.asarray(quantized_all_to_all(x, mesh=mesh8, axis="data"))
    exact = shard_map(
        lambda xl: jax.lax.all_to_all(xl, "data", split_axis=0,
                                      concat_axis=0, tiled=True),
        mesh=mesh8, in_specs=P("data"), out_specs=P("data"),
        axis_names={"data"}, check_vma=False)
    want = np.asarray(jax.jit(exact)(x))
    assert np.abs(got - want).max() <= np.abs(vals).max() / 127 * 1.01


def test_queue_exchange_roundtrip_and_expert_alignment():
    require_devices(8)
    from deepspeed_tpu.runtime.comm.quantized import make_queue_exchange
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(1, 2, 2, 2, 1),
                ("pipe", "data", "expert", "seq", "model"))
    G, E, Cg, H = 8, 4, 3, 16
    rng = np.random.default_rng(3)
    sh = NamedSharding(mesh, P(("data", "expert", "seq"), None, None, None))
    x = jax.device_put(jnp.asarray(
        rng.standard_normal((G, E, Cg, H)).astype(np.float32)), sh)
    for algo, tol in (("exact", 1e-6), ("int8", None)):
        disp, comb = make_queue_exchange(mesh, algo=algo)
        rt = np.asarray(jax.jit(lambda v: comb(disp(v)))(x))
        bound = tol if tol is not None else \
            2 * np.abs(np.asarray(x)).max() / 127
        assert np.abs(rt - np.asarray(x)).max() <= bound, algo
    # expert alignment: rows tagged with their expert index land intact
    tag = np.zeros((G, E, Cg, H), np.float32)
    for e in range(E):
        tag[:, e] = e
    disp, _ = make_queue_exchange(mesh, algo="exact")
    full = np.asarray(jax.jit(disp)(jax.device_put(jnp.asarray(tag), sh)))
    assert full.shape == (E, G * Cg, H)
    for e in range(E):
        assert (full[e] == e).all()


# ------------------------------------------------------------ wire-byte audit

def test_wire_bytes_grad_sync_int8_vs_exact(mesh8):
    """Acceptance: the int8 grad sync moves <= ~28% of the exact path's
    collective bytes — audited from optimized HLO over IDENTICAL op
    structures (f32 vs int8 payload + the f32 per-block scales)."""
    from deepspeed_tpu.runtime.comm.quantized import grad_sync
    x = jax.device_put(jnp.ones((8, 65536), jnp.float32),
                       NamedSharding(mesh8, P("data")))

    def audit(algo):
        fn = jax.jit(lambda v: grad_sync(v, mesh=mesh8, axis="data",
                                         algo=algo))
        txt = fn.lower(x).compile().as_text()
        return txt, hlo_collective_bytes(txt)

    txt_e, bytes_e = audit("exact")
    txt_q, bytes_q = audit("int8")
    assert bytes_e > 0 and bytes_q > 0
    assert "s8" in txt_q and "s8" not in txt_e
    assert bytes_q <= 0.28 * bytes_e, (bytes_q, bytes_e,
                                       bytes_q / bytes_e)


def test_wire_bytes_all_to_all_int8_vs_exact(mesh8):
    from deepspeed_tpu.runtime.comm.quantized import quantized_all_to_all
    from deepspeed_tpu.utils.jax_compat import shard_map
    x = jax.device_put(jnp.ones((64, 4096), jnp.float32),
                       NamedSharding(mesh8, P("data")))
    exact = jax.jit(shard_map(
        lambda xl: jax.lax.all_to_all(xl, "data", split_axis=0,
                                      concat_axis=0, tiled=True),
        mesh=mesh8, in_specs=P("data"), out_specs=P("data"),
        axis_names={"data"}, check_vma=False))
    quant = jax.jit(lambda v: quantized_all_to_all(v, mesh=mesh8,
                                                   axis="data"))
    bytes_e = hlo_collective_bytes(exact.lower(x).compile().as_text())
    txt_q = quant.lower(x).compile().as_text()
    bytes_q = hlo_collective_bytes(txt_q)
    assert "s8" in txt_q
    assert bytes_q <= 0.28 * bytes_e, (bytes_q, bytes_e,
                                       bytes_q / bytes_e)


# --------------------------------------------------------- engine integration

def _engine(cfg_extra=None, seed=7):
    cfg = {"train_batch_size": 16,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 2}, "seed": seed}
    cfg.update(cfg_extra or {})
    engine, *_ = ds.initialize(model=SimpleModel(),
                               example_batch=random_batch(16), config=cfg)
    return engine


def test_engine_int8_grad_sync_training_parity():
    """Acceptance: multi-step training parity — the quantized-sync twin's
    loss curve tracks the exact engine within tolerance, and the audit
    tag proves the int8 program actually ran every step."""
    require_devices(8)
    e0 = _engine()
    e1 = _engine({"comm_plan": {"enabled": True,
                                "overrides": {"grad_reduce_scatter":
                                              "int8"}}})
    assert e1.comm_plan_ctx.resolved["grad_reduce_scatter"] == "int8"
    l0, l1 = [], []
    for i in range(12):
        b = random_batch(16, seed=i)
        l0.append(float(e0.train_batch(b)["loss"]))
        m = e1.train_batch(b)
        l1.append(float(m["loss"]))
        assert m["grad_sync_algo"] == "int8"
    assert np.isfinite(l1).all()
    assert l1[-1] < l1[0]                     # it trains
    assert max(abs(a - b) for a, b in zip(l0, l1)) < 0.05, (l0, l1)


def test_engine_accuracy_guard_forces_exact():
    """Acceptance: the guard forces the exact program once the observed
    grad norm is below the threshold — with a huge threshold, step 1 runs
    the plan's int8 choice (nothing observed yet) and every later step
    runs exact."""
    require_devices(8)
    e = _engine({"comm_plan": {"enabled": True,
                               "guard_min_grad_norm": 1e9,
                               "overrides": {"grad_reduce_scatter":
                                             "int8"}}})
    algos = [e.train_batch(random_batch(16, seed=i))["grad_sync_algo"]
             for i in range(3)]
    assert algos == ["int8", "exact", "exact"], algos
    # and with a tiny threshold the guard never trips
    e2 = _engine({"comm_plan": {"enabled": True,
                                "guard_min_grad_norm": 1e-9,
                                "overrides": {"grad_reduce_scatter":
                                              "int8"}}})
    algos2 = [e2.train_batch(random_batch(16, seed=i))["grad_sync_algo"]
              for i in range(3)]
    assert algos2 == ["int8", "int8", "int8"], algos2


def test_engine_forced_sync_outside_envelope_degrades():
    """Round-14 contract change: a forced non-exact grad sync OUTSIDE
    the envelope degrades to exact with a warning instead of raising
    (TP now sits inside the envelope on native-shard_map hosts; the
    full degrade matrix is pinned in test_comm_overlap.py)."""
    require_devices(8)
    e = _engine({"zero_optimization": {"stage": 3},
                 "comm_plan": {"enabled": True,
                               "overrides": {"grad_reduce_scatter":
                                             "int8"}}})
    assert e.comm_plan_ctx.resolved["grad_reduce_scatter"] == "exact"
    assert np.isfinite(float(e.train_batch(random_batch(16))["loss"]))


# tier-2 (round-17 budget sweep, ~10s): the cheaper tier-1 cousins are
# test_engine_forced_sync_outside_envelope_degrades (same degrade path,
# forced) and test_resolve_unknown_bucket_falls_back_to_heuristic;
# scripts/tier2.sh runs this unforced-selection leg
@pytest.mark.slow
def test_engine_unforced_selection_degrades_to_exact_outside_envelope():
    """A plan-driven (not forced) int8 verdict on an incompatible mesh
    logs and runs exact — selection must never brick a launch."""
    require_devices(8)
    plan = cp.CommPlan()
    # a wildcard entry that covers EVERY grad-sync bucket this model hits
    for bucket in range(10, 34):
        plan.add(cp.PlanEntry("reduce_scatter", "all", bucket, "int8"))
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        f.write(plan.to_json())
        path = f.name
    try:
        from deepspeed_tpu.models import build_model, causal_lm_loss
        model, mcfg = build_model("gpt2-tiny", hidden_size=64,
                                  num_layers=1, num_heads=4,
                                  vocab_size=128, max_seq_len=32,
                                  attention_impl="reference")
        cfg = {"train_batch_size": 4,
               "train_micro_batch_size_per_gpu": 1,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 2},
               "tensor_parallel": {"tp_size": 2},
               "comm_plan": {"enabled": True, "plan_path": path}}
        batch = {"input_ids": np.random.default_rng(0).integers(
            0, 128, size=(4, 16))}
        eng, *_ = ds.initialize(model=model, config=cfg,
                                loss_fn=causal_lm_loss,
                                example_batch=batch,
                                sharding_rules=mcfg.tp_rules())
        assert eng.comm_plan_ctx.resolved["grad_reduce_scatter"] == "exact"
        assert np.isfinite(float(eng.train_batch(batch)["loss"]))
    finally:
        os.unlink(path)


@pytest.mark.slow
def test_engine_moe_int8_dispatch_training_parity():
    """The MoE expert all-to-all routed through the explicit int8
    exchange: loss curve tracks the exact (implicit-SPMD) twin. Tier-1
    covers the same composition through the dryrun's moe_q leg; this is
    the closer-tolerance twin comparison."""
    require_devices(8)
    from deepspeed_tpu.models import build_model, make_moe_loss

    def mk(extra):
        model, mcfg = build_model(
            "gpt2-tiny", hidden_size=64, num_layers=2, num_heads=4,
            vocab_size=256, max_seq_len=64, moe_experts=4,
            moe_capacity_factor=2.0, attention_impl="reference")
        cfg = {"train_batch_size": 16,
               "train_micro_batch_size_per_gpu": 2,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
               "bf16": {"enabled": True},
               "zero_optimization": {"stage": 2},
               "moe": {"enabled": True, "ep_size": 2}, "seed": 3, **extra}
        batch = {"input_ids": np.random.default_rng(3).integers(
            0, 256, size=(16, 32))}
        e, *_ = ds.initialize(model=model, config=cfg,
                              loss_fn=make_moe_loss(mcfg.moe_aux_weight),
                              example_batch=batch,
                              sharding_rules=mcfg.tp_rules())
        return e, batch

    e0, batch = mk({})
    e1, _ = mk({"comm_plan": {"enabled": True,
                              "overrides": {"moe_all_to_all": "int8"}}})
    l0 = [float(e0.train_batch(batch)["loss"]) for _ in range(8)]
    l1 = [float(e1.train_batch(batch)["loss"]) for _ in range(8)]
    assert e1.comm_plan_ctx.resolved["moe_all_to_all"] == "int8"
    assert np.isfinite(l1).all()
    assert l1[-1] < l1[0]
    assert max(abs(a - b) for a, b in zip(l0, l1)) < 0.05, (l0, l1)


# ------------------------------------------------- comm_bench record format

def test_parse_bench_lines_and_selector_ingest():
    out = "\n".join([
        "irrelevant noise",
        'comm_bench: {"op": "reduce_scatter", "algo": "exact", '
        '"axis": "all", "size_mb": 8.0, "size_bytes": 8388608, '
        '"latency_us": 900.0}',
        "comm_bench: {broken json",
        'comm_bench: {"op": "reduce_scatter", "algo": "int8", '
        '"axis": "all", "size_mb": 8.0, "size_bytes": 8388608, '
        '"latency_us": 300.0}',
    ])
    rows = cp.parse_bench_lines(out)
    assert len(rows) == 2
    plan = cp.select_plan(rows)
    assert plan.choose("reduce_scatter", "all", 8 * 2 ** 20) == "int8"


def test_sweep_regression_compare():
    from deepspeed_tpu.benchmarks.communication import (
        check_sweep_regression)
    base = [{"op": "all_to_all", "algo": "int8", "axis": "all",
             "size_mb": 8.0, "latency_us": 100.0}]
    ok = [{"op": "all_to_all", "algo": "int8", "axis": "all",
           "size_mb": 8.0, "latency_us": 150.0}]
    slow = [{"op": "all_to_all", "algo": "int8", "axis": "all",
             "size_mb": 8.0, "latency_us": 250.0}]
    other = [{"op": "all_to_all", "algo": "exact", "axis": "all",
              "size_mb": 8.0, "latency_us": 250.0}]
    assert check_sweep_regression(ok, base) == []
    probs = check_sweep_regression(slow, base)
    assert len(probs) == 1 and "2.5x" in probs[0]
    # a row with no matching recorded cell is not a regression
    assert check_sweep_regression(other, base) == []


def test_latest_comm_sweep_discovery(tmp_path):
    from deepspeed_tpu.benchmarks.communication import latest_comm_sweep
    a = tmp_path / "comm_sweep_old.json"
    a.write_text(json.dumps({"n": 8, "rows": [{"op": "x",
                                               "latency_us": 1.0}]}))
    os.utime(a, (1, 1))
    b = tmp_path / "COMMBENCH_r02.json"
    b.write_text(json.dumps({"n": 8, "rows": [{"op": "y",
                                               "latency_us": 2.0}]}))
    name, rows = latest_comm_sweep(str(tmp_path), 8)
    assert name == "COMMBENCH_r02.json" and rows[0]["op"] == "y"
    # device-count mismatch: skipped
    name, rows = latest_comm_sweep(str(tmp_path), 2)
    assert name is None and rows == []


# ----------------------------------------------------------------------- CLI

def test_comm_plan_cli_show(tmp_path, capsys):
    from deepspeed_tpu.comm_plan.cli import main as cli_main
    plan = cp.select_plan(_rows())
    path = str(tmp_path / "plan.json")
    plan.save(path)
    rc = cli_main(["show", path, "--query",
                   f"reduce_scatter:data:{8 * 2 ** 20}"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "reduce_scatter" in out and "int8" in out
    assert "-> int8 (plan entry)" in out


def test_comm_plan_cli_sweep_records_and_selects(tmp_path, capsys):
    """End-to-end on the virtual mesh: one op, exact+int8, selection via
    the autotuning grid, plan written + parseable, comm_bench lines in
    the selector-ingestible format."""
    require_devices(8)
    from deepspeed_tpu.comm_plan.cli import main as cli_main
    out_path = str(tmp_path / "plan.json")
    rec_path = str(tmp_path / "sweep.json")
    rc = cli_main(["sweep", "--ops", "reduce_scatter", "--algos",
                   "exact,int8", "--sizes-mb", "0.25", "--iters", "2",
                   "--out", out_path, "--record", rec_path])
    out = capsys.readouterr().out
    assert rc == 0
    rows = cp.parse_bench_lines(out)
    assert {(r["op"], r["algo"]) for r in rows} == {
        ("reduce_scatter", "exact"), ("reduce_scatter", "int8")}
    plan = cp.CommPlan.load(out_path)
    assert plan.entries and plan.meta["n_devices"] == len(jax.devices())
    rec = json.loads(open(rec_path).read())
    assert rec["n"] == len(jax.devices()) and len(rec["rows"]) == 2


# ------------------------------------------------------------- 2-proc gloo

WORKER_INT8_ZERO2 = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])

import numpy as np
import deepspeed_tpu as ds

ds.init_distributed()
rank = ds.comm.get_rank()
assert ds.comm.get_world_size() == 2

sys.path.insert(0, os.path.join(os.environ["DSTPU_TEST_REPO"], "tests"))
from util import SimpleModel, random_batch

config = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    "zero_optimization": {"stage": 2},
    "comm_plan": {"enabled": True,
                  "overrides": {"grad_reduce_scatter": "int8"}},
    "seed": 11,
}
engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                           example_batch=random_batch(8))
assert engine.comm_plan_ctx.resolved["grad_reduce_scatter"] == "int8"
losses = []
for i in range(8):
    m = engine.train_batch(random_batch(8, seed=i))
    assert m["grad_sync_algo"] == "int8"
    losses.append(float(m["loss"]))
assert np.isfinite(losses).all(), losses
assert losses[-1] < losses[0], losses
print(f"RANK{rank} OK last={losses[-1]:.6f}", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_zero2_int8_grad_sync(tmp_path):
    """Acceptance satellite: a REAL 2-process gloo world runs ZeRO-2
    training with the int8 grad reduce-scatter — the cross-PROCESS wire
    really carries the quantized exchange, and both ranks see identical
    losses (the sync synced)."""
    worker = tmp_path / "worker_int8.py"
    worker.write_text(WORKER_INT8_ZERO2)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ,
                   DSTPU_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   DSTPU_NUM_PROCESSES="2",
                   DSTPU_PROCESS_ID=str(pid),
                   DSTPU_TEST_REPO=REPO_ROOT)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-3000:]}"
        assert f"RANK{pid} OK" in out, out[-2000:]
    l0 = outs[0].split("last=")[1].split()[0]
    l1 = outs[1].split("last=")[1].split()[0]
    assert l0 == l1, (l0, l1)
