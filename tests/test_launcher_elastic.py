"""Launcher end-to-end + elastic agent + multinode runner command building.

Round-1 Weak #10 (launcher never tested end-to-end) and missing #8 (elastic
agent). Mirrors the reference's tests/unit/test_ds_arguments + elasticity
coverage.
"""

import os
import subprocess
import sys
import threading
import time
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- multinode runner command construction ------------------------------------

def _args(**kw):
    ns = types.SimpleNamespace(user_script="train.py", user_args=["--x", "1"],
                               hostfile="/job/hostfile", include="")
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def test_pdsh_runner_cmd():
    from deepspeed_tpu.launcher.multinode_runner import PDSHRunner
    r = PDSHRunner(_args())
    r.add_export("XLA_FLAGS", "--foo")
    cmd = r.get_cmd({"A": "b"}, {"h1": [0], "h2": [0]})
    assert cmd[0] == "pdsh"
    assert "h1,h2" in cmd
    joined = " ".join(cmd)
    assert "export A=b" in joined and "export XLA_FLAGS" in joined
    assert cmd[-1] == "1" and cmd[-2] == "--x" and cmd[-3] == "train.py"


def test_openmpi_and_slurm_runner_cmds():
    from deepspeed_tpu.launcher.multinode_runner import (OpenMPIRunner,
                                                         SlurmRunner,
                                                         build_runner)
    cmd = OpenMPIRunner(_args()).get_cmd({"E": "v"}, {"a": [0], "b": [0]})
    assert cmd[:3] == ["mpirun", "-n", "2"]
    assert "-x" in cmd and "E=v" in cmd
    cmd = SlurmRunner(_args()).get_cmd({"E": "v"}, {"a": [0]})
    assert cmd[:2] == ["srun", "-n"]
    assert any(c.startswith("--export=ALL,") for c in cmd)
    with pytest.raises(ValueError, match="unknown launcher"):
        build_runner("nope", _args())


# -- launcher end-to-end on localhost -----------------------------------------

@pytest.mark.slow
def test_launcher_end_to_end_localhost(tmp_path):
    """dstpu with a localhost hostfile + --launcher local actually runs the
    user script through the per-host bootstrap (launch.py)."""
    script = tmp_path / "probe.py"
    marker = tmp_path / "ran.txt"
    script.write_text(
        "import sys\n"
        f"open({str(marker)!r}, 'w').write(' '.join(sys.argv[1:]))\n")
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "dstpu"),
         "--hostfile", str(hostfile), "--launcher", "local",
         str(script), "--hello", "world"],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-1500:]
    assert marker.exists()
    assert marker.read_text() == "--hello world"


def test_elastic_active_world_honors_include_exclude(tmp_path):
    """--exclude must hold across elastic relaunches: a flaky host kept
    out of the pod must not re-enter the world on the next restart."""
    from deepspeed_tpu.launcher.runner import elastic_active_world
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("w0 slots=2\nw1 slots=2\nw2 slots=1\n")
    args = types.SimpleNamespace(hostfile=str(hostfile), include="",
                                 exclude="w1", num_nodes=-1)
    active = elastic_active_world(args, ["w0", "w1", "w2"])
    assert list(active) == ["w0", "w2"]
    assert active["w0"] == [0, 1]
    # include filter narrows slots too
    args = types.SimpleNamespace(hostfile=str(hostfile), include="w0:1@w2",
                                 exclude="", num_nodes=-1)
    active = elastic_active_world(args, ["w0", "w1", "w2"])
    assert active == {"w0": [1], "w2": [0]}
    # no hostfile: localhost fallback world
    args = types.SimpleNamespace(hostfile=str(tmp_path / "missing"),
                                 include="", exclude="", num_nodes=-1)
    assert elastic_active_world(args, ["localhost"]) == {"localhost": [0]}


# -- elastic agent ------------------------------------------------------------

def test_elastic_agent_restarts_on_crash(tmp_path):
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n")
    attempts = tmp_path / "attempts"

    def launch(members):
        # crash on the first attempt, succeed on the second
        code = (f"import os\np={str(attempts)!r}\n"
                "n = int(open(p).read()) if os.path.exists(p) else 0\n"
                "open(p, 'w').write(str(n + 1))\n"
                "raise SystemExit(1 if n == 0 else 0)\n")
        return subprocess.Popen([sys.executable, "-c", code])

    agent = DSElasticAgent(launch, str(hostfile), max_restarts=3,
                           check_interval=0.05)
    assert agent.run() == 0
    assert agent.restarts == 1
    assert attempts.read_text() == "2"


@pytest.mark.slow
def test_elastic_agent_preemption_rc_not_counted(tmp_path):
    """A worker exiting with PREEMPTION_EXIT_CODE (what the engine's
    SIGTERM handler does after its emergency save) is a resume: relaunch
    without touching the max_restarts budget."""
    from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent,
                                                        PREEMPTION_EXIT_CODE)
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n")
    attempts = tmp_path / "attempts"

    def launch(members):
        code = (f"import os\np={str(attempts)!r}\n"
                "n = int(open(p).read()) if os.path.exists(p) else 0\n"
                "open(p, 'w').write(str(n + 1))\n"
                f"raise SystemExit({PREEMPTION_EXIT_CODE} if n < 2 else 0)\n")
        return subprocess.Popen([sys.executable, "-c", code])

    # max_restarts=0: ANY crash would end the run — only the preemption
    # rc's exemption lets this reach the clean exit
    agent = DSElasticAgent(launch, str(hostfile), max_restarts=0,
                           check_interval=0.05)
    assert agent.run() == 0
    assert agent.preemptions == 2
    assert agent.restarts == 0
    assert attempts.read_text() == "3"


@pytest.mark.slow
def test_elastic_agent_tolerates_transient_hostfile_states(tmp_path):
    """An atomic rewrite of the hostfile mid-poll (empty read, brief
    unlink, identical rewrite) must NOT look like a membership change."""
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    hostfile = tmp_path / "hostfile"
    content = "worker-0 slots=1\n"
    hostfile.write_text(content)
    launches = []

    def launch(members):
        launches.append(list(members))
        return subprocess.Popen([sys.executable, "-c",
                                 "import time; time.sleep(1.2)"])

    def churn():
        # several rewrite cycles while the agent polls at 20ms
        for _ in range(4):
            time.sleep(0.15)
            hostfile.write_text("")            # truncate+write in flight
            time.sleep(0.05)
            os.unlink(hostfile)                # rename-style blip
            time.sleep(0.05)
            hostfile.write_text(content)       # same membership lands

    t = threading.Thread(target=churn)
    t.start()
    agent = DSElasticAgent(launch, str(hostfile), check_interval=0.02)
    rc = agent.run()
    t.join()
    assert rc == 0
    assert agent.membership_changes == 0
    assert len(launches) == 1


# -- degraded-world elastic resume (round 6) ----------------------------------

class _FakeRun:
    """Popen-facade stub with supervisor-style failure attribution."""

    def __init__(self, rc, failed=()):
        self._rc = rc
        self._failed = list(failed)

    def poll(self):
        return self._rc

    def wait(self, timeout=None):
        return self._rc

    def terminate(self):
        pass

    kill = terminate

    def failed_hosts(self):
        return list(self._failed)


def test_agent_blacklists_failing_host_and_reforms_smaller_world(tmp_path):
    """Acceptance: a host implicated in repeated counted failures is
    quarantined; the agent relaunches a SMALLER world from the survivors
    and publishes it to the active hostfile."""
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("w0 slots=2\nw1 slots=1\n")
    active = tmp_path / "hostfile.active"
    worlds = []

    def launch(members):
        worlds.append(list(members))
        if "w1" in members:
            return _FakeRun(9, failed=["w1"])     # w1 crashes the world
        return _FakeRun(0)

    agent = DSElasticAgent(launch, str(hostfile), max_restarts=5,
                           check_interval=0.02, blacklist_after=2,
                           active_hostfile=str(active))
    assert agent.run() == 0
    # two strikes to quarantine, then the degraded world succeeds
    assert worlds == [["w0", "w1"], ["w0", "w1"], ["w0"]]
    assert agent.blacklisted == {"w1"}
    assert agent.strikes["w1"] == 2
    assert agent.restarts == 2
    assert active.read_text() == "w0 slots=2\n"   # operator-visible world


def test_agent_blacklist_respects_min_nodes_by_parole(tmp_path):
    """Quarantine must not starve the pod below --min-nodes: with every
    survivor needed, the offender is paroled back instead of the agent
    waiting forever on an impossible world."""
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("w0 slots=1\nw1 slots=1\n")
    worlds = []

    def launch(members):
        worlds.append(list(members))
        if len(worlds) < 3:
            return _FakeRun(9, failed=["w1"])
        return _FakeRun(0)

    agent = DSElasticAgent(launch, str(hostfile), max_restarts=5,
                           min_nodes=2, check_interval=0.02,
                           blacklist_after=1)
    assert agent.run() == 0
    # w1 is struck and quarantined, but min_nodes=2 paroles it right back
    assert all(w == ["w0", "w1"] for w in worlds)
    assert agent.blacklisted == set()


def test_failure_evidence_indexes_launched_world_not_members(tmp_path):
    """launch_fn may narrow the agent's confirmed membership further
    (--include/--exclude/--num_nodes): rank->host recovery for a record
    with an out-of-vocabulary self-reported host must index the world
    ranks were ACTUALLY assigned over (proc.rank_hosts), or the strike
    lands on an innocent filtered-out neighbor."""
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    from deepspeed_tpu.runtime import heartbeat as hb
    hb_dir = tmp_path / "hb"
    # rank 0 of the LAUNCHED world (w2 only — w1 was filtered out) died
    # stalled, self-reporting a name the hostfile never uses
    w = hb.HeartbeatWriter(str(hb_dir), 0, host="w2.internal.example",
                           refresh_interval=0)
    w.write(hb.PHASE_STALLED, 7, force=True)
    agent = DSElasticAgent(lambda m: None, str(tmp_path / "hostfile"),
                           heartbeat_dir=str(hb_dir))

    class Proc:
        rank_hosts = ["w2"]              # the narrowed launched world

    assert agent._failure_evidence(Proc(), ["w1", "w2"]) == ["w2"]
    # without rank_hosts the fallback degrades to the members list
    assert agent._failure_evidence(object(), ["w2"]) == ["w2"]


def test_run_elastic_forwards_heartbeat_knobs(tmp_path, monkeypatch):
    """--heartbeat-timeout must reach the agent: its lag-based silence
    evidence is gated on it, and the 0.0 default silently disables the
    documented path."""
    from deepspeed_tpu.elasticity import elastic_agent as ea
    from deepspeed_tpu.launcher import runner
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n")
    seen = {}

    class FakeAgent:
        def __init__(self, launch_fn, hostfile, **kw):
            seen.update(kw)

        def run(self):
            return 0

    monkeypatch.setattr(ea, "DSElasticAgent", FakeAgent)
    args = types.SimpleNamespace(
        hostfile=str(hostfile), max_restarts=3, min_nodes=1,
        check_interval=0.1, grace_secs=1.0,
        heartbeat_dir=str(tmp_path / "hb"), heartbeat_timeout=7.5)
    assert runner.run_elastic(args) == 0
    assert seen["heartbeat_dir"] == str(tmp_path / "hb")
    assert seen["heartbeat_timeout"] == 7.5


@pytest.mark.slow
def test_agent_blacklists_blackholed_host_via_real_supervisor(tmp_path):
    """End to end through RunSupervisor + keyed chaos: a blackholed host
    fails every dispatch, is quarantined after one strike, and the
    degraded relaunch picks up the prior run's on-disk progress."""
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    from deepspeed_tpu.launcher.supervisor import RankSpec, RunSupervisor
    from deepspeed_tpu.testing import chaos
    chaos.arm("host.blackhole", "raise", times=100, match="w1")
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("w0 slots=1\nw1 slots=1\n")
    progress = tmp_path / "progress"
    worlds = []

    def launch(members):
        worlds.append(list(members))
        if len(worlds) == 1:
            # w0 records progress, then idles in killable slices; w1's
            # blackholed dispatch keeps retrying for ~2s — long past the
            # write — before its exhaustion fails the world and the
            # teardown reaps w0
            code = (f"import time\n"
                    f"open({str(progress)!r}, 'w').write('ckpt')\n"
                    "for _ in range(600):\n"
                    "    time.sleep(0.05)\n")
            specs = [RankSpec("w0", [sys.executable, "-c", code]),
                     RankSpec("w1", ["true"], remote=True)]
            return RunSupervisor(specs, grace_secs=0.5, connect_retries=6,
                                 connect_backoff=0.15,
                                 connect_backoff_max=0.15).start()
        # the degraded relaunch: w0 proves it sees the prior run's marker
        # (rc 3, not a hang, if the first run was torn down before writing)
        code = (f"import os, sys\n"
                f"sys.exit(0 if os.path.exists({str(progress)!r}) else 3)\n")
        specs = [RankSpec("w0", [sys.executable, "-c", code])]
        return RunSupervisor(specs, grace_secs=0.5, connect_retries=0,
                             connect_backoff=0.01).start()

    agent = DSElasticAgent(launch, str(hostfile), max_restarts=3,
                           check_interval=0.05, blacklist_after=1)
    try:
        assert agent.run() == 0
    finally:
        chaos.disarm()
    assert worlds == [["w0", "w1"], ["w0"]]
    assert agent.blacklisted == {"w1"}
    assert agent.restarts == 1
    assert progress.read_text() == "ckpt"         # resumed, not restarted


@pytest.mark.slow
def test_elastic_agent_restarts_on_membership_change(tmp_path):
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("worker-0 slots=1\n")
    seen_worlds = []

    def launch(members):
        seen_worlds.append(list(members))
        if len(seen_worlds) == 1:
            return subprocess.Popen([sys.executable, "-c",
                                     "import time; time.sleep(60)"])
        return subprocess.Popen([sys.executable, "-c", "raise SystemExit(0)"])

    def scale_up():
        time.sleep(0.4)
        hostfile.write_text("worker-0 slots=1\nworker-1 slots=1\n")

    t = threading.Thread(target=scale_up)
    t.start()
    agent = DSElasticAgent(launch, str(hostfile), check_interval=0.05)
    rc = agent.run()
    t.join()
    assert rc == 0
    assert agent.membership_changes == 1
    assert seen_worlds[0] == ["worker-0"]
    assert seen_worlds[1] == ["worker-0", "worker-1"]
