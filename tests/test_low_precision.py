"""Round-17 quantized-compute tier: the single-sourced quant format's
error bounds, the weight-only int8 matmul's analytic logit bound, the
sentinel-gated low-precision training experiment, and the decode
hot-path audit proving no bulk dequant survives outside the kernels.

Layers covered:

* ``quant_format`` — property tests pinning the documented error model
  (COMM.md: per-element roundtrip error <= block_absmax / 127) for the
  blockwise wire/weight format AND the per-row KV format, plus the
  straight-through ``fake_quant_act`` (int8 + fp8-e4m3 emulation).
* ``ops/pallas/quant_matmul`` — interpret-mode kernel vs jnp reference
  parity, and both vs the exact f32 matmul within the analytic bound
  ``sum_b ||x_block||_1 * block_absmax_b / 127`` per output element.
* per-architecture weight-only logit bounds (gpt2-ish learned+gelu,
  llama-ish rmsnorm+gated+rotary+GQA) through ``paged_forward``.
* ``wire_low_precision`` gates (the experiment REQUIRES the integrity
  sentinel) and the engine loss-parity twin; the chaos sentinel.spike
  leg on a low-precision engine is ``slow`` (scripts/chaos.sh).
* the acceptance audit: the traced decode step contains NO int8 ->
  float convert of pool-slice / packed-kernel size outside pallas_call
  — the round-12 full-pool dequant copy is structurally gone.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.models import build_model, fused_loss_passthrough
from deepspeed_tpu.models.generation import ensure_scan_layout
from deepspeed_tpu.models.transformer import causal_lm_loss
from deepspeed_tpu.ops.pallas.quant_matmul import (pack_decode_weights,
                                                   pack_kernel, quant_matmul,
                                                   quant_matmul_reference)
from deepspeed_tpu.quant_format import (QUANT_BLOCK, block_dequant,
                                        block_quant, fake_quant_act,
                                        kv_quantize)
from deepspeed_tpu.runtime.engine import wire_low_precision
from deepspeed_tpu.serving.kv_cache import init_pool
from deepspeed_tpu.serving.model_runner import paged_forward
from deepspeed_tpu.testing import chaos
from tests.util import SimpleModel


# ------------------------------------------------------ quant_format bounds

@pytest.mark.parametrize("shape,block", [
    ((3, 256), 256),          # exact block multiple
    ((2, 300), 256),          # ragged tail -> one padded block
    ((4, 7, 96), 32),         # small blocks, leading dims
    ((1, 1), 256),            # single element
])
def test_block_quant_error_bound_property(shape, block):
    """THE documented error model (COMM.md / quant_format docstring):
    per-element roundtrip error <= block_absmax / 127."""
    rng = np.random.default_rng(hash(shape) % 2**32)
    x = (rng.standard_normal(shape) * 10 ** rng.uniform(-2, 2, shape)
         ).astype(np.float32)
    q, s, pad = block_quant(jnp.asarray(x), 8, block)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    deq = np.asarray(block_dequant(q, s, pad))[..., :shape[-1]]
    L = shape[-1]
    nb = -(-L // block)
    xp = np.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, nb * block - L)])
    absmax = np.abs(xp.reshape(shape[:-1] + (nb, block))).max(-1)
    bound = np.repeat(absmax / 127.0, block, axis=-1)[..., :L]
    np.testing.assert_array_less(np.abs(deq - x), bound + 1e-7)


def test_block_quant_zero_blocks_exact_and_int4_bound():
    x = jnp.zeros((2, 512), jnp.float32)
    q, s, pad = block_quant(x)
    assert pad == 0
    np.testing.assert_array_equal(np.asarray(s), 1.0)   # zero block scale 1
    np.testing.assert_array_equal(np.asarray(block_dequant(q, s, pad)), 0.0)
    # 4-bit widens the step to absmax / 7 — the bits knob scales the bound
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 256)),
                    jnp.float32)
    q4, s4, _ = block_quant(x, bits=4)
    assert int(np.abs(np.asarray(q4)).max()) <= 7
    err = np.abs(np.asarray(block_dequant(q4, s4, 0)) - np.asarray(x))
    absmax = np.abs(np.asarray(x)).reshape(2, 1, 256).max(-1)
    assert (err <= np.repeat(absmax / 7.0, 256, -1) + 1e-7).all()


def test_kv_quantize_error_bound_per_row():
    """Per-row format: one scale per (layer, head, slot) vector; error
    <= row_absmax / 127; zero rows roundtrip exactly."""
    rng = np.random.default_rng(1)
    t = rng.standard_normal((3, 4, 5, 64)).astype(np.float32)
    t[0, 1, 2] = 0.0                                    # a zero row
    q, s = kv_quantize(jnp.asarray(t))
    assert q.dtype == jnp.int8 and s.shape == t.shape[:-1] + (1,)
    deq = np.asarray(q, np.float32) * np.asarray(s)
    bound = np.abs(t).max(-1, keepdims=True) / 127.0
    assert (np.abs(deq - t) <= bound + 1e-7).all()
    np.testing.assert_array_equal(deq[0, 1, 2], 0.0)


def test_fake_quant_act_bounds_and_ste_gradient():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((4, 300)) * 3, jnp.float32)
    absmax = np.abs(np.asarray(x)).max()                # one padded block
    y8 = fake_quant_act(x, "int8")
    assert float(jnp.abs(y8 - x).max()) <= absmax / 127.0 + 1e-7
    yf = fake_quant_act(x, "fp8")
    # e4m3 normals carry a 3-bit mantissa: relative error <= 2^-4, plus a
    # subnormal floor from the absmax -> 448 block scale
    err = np.abs(np.asarray(yf) - np.asarray(x))
    assert (err <= np.abs(np.asarray(x)) * 0.0625 + absmax / 448.0).all()
    # straight-through: the gradient ignores the quantizer entirely
    for fmt in ("int8", "fp8"):
        g = jax.grad(lambda v: jnp.sum(fake_quant_act(v, fmt)))(x)
        np.testing.assert_array_equal(np.asarray(g), 1.0)
    with pytest.raises(ValueError, match="int8|fp8"):
        fake_quant_act(x, "int4")


# ------------------------------------------------------------- quant_matmul

@pytest.mark.parametrize("M,K,N", [(3, 300, 256), (9, 512, 128),
                                   (2, 32, 128)])
def test_quant_matmul_kernel_reference_parity_and_analytic_bound(M, K, N):
    """The interpret-mode Pallas kernel computes the reference's per-block
    identity; both sit within the analytic bound vs the exact product:
    |err[m, n]| <= sum_b ||x[m, block_b]||_1 * block_absmax_b[n] / 127."""
    rng = np.random.default_rng(M * 1000 + K)
    x = jnp.asarray(rng.standard_normal((M, K)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((K, N)) * 0.3, jnp.float32)
    q, s = pack_kernel(w)
    Kp = q.shape[0]
    nkb = s.shape[0]
    yk = np.asarray(quant_matmul(x, q, s, interpret=True))
    yr = np.asarray(quant_matmul_reference(x, q, s))
    np.testing.assert_allclose(yk, yr, atol=1e-4)
    y_true = np.asarray(x, np.float64) @ np.asarray(w, np.float64)
    # per-element weight error bound summed through the contraction
    wp = np.zeros((Kp, N), np.float32)
    wp[:K] = np.asarray(w)
    absmax = np.abs(wp.reshape(nkb, Kp // nkb, N)).max(1)      # [nkb, N]
    xp = np.zeros((M, Kp), np.float32)
    xp[:, :K] = np.abs(np.asarray(x))
    xnorm = xp.reshape(M, nkb, Kp // nkb).sum(-1)              # [M, nkb]
    bound = xnorm @ (absmax / 127.0)
    assert (np.abs(yr - y_true) <= bound + 1e-4).all()
    assert (np.abs(yk - y_true) <= bound + 1e-3).all()


def test_pack_decode_weights_selective_and_idempotent():
    rng = np.random.default_rng(3)
    mk = lambda *sh: jnp.asarray(rng.standard_normal(sh), jnp.float32)
    params = {
        "blocks": {
            "attn_qkv": {"kernel": mk(2, 64, 192), "bias": mk(2, 192)},
            "mlp_fc": {"kernel": mk(2, 64, 256)},
            "ln1": {"scale": mk(2, 64)},                # no kernel: untouched
            "moe": {"gate": {"kernel": mk(2, 64, 4)}},  # nested: untouched
        },
        "lm_head": {"kernel": mk(64, 100)},
        "wte": {"embedding": mk(100, 64)},
    }
    out = pack_decode_weights(params)
    for name in ("attn_qkv", "mlp_fc"):
        sub = out["blocks"][name]
        assert sub["kernel"].dtype == jnp.int8
        assert sub["kernel_qscale"].dtype == jnp.float32
        # stacked [L, K, N] leaves pack per-layer: leading dim preserved
        assert sub["kernel"].shape[0] == 2
    assert out["blocks"]["attn_qkv"]["bias"] is params["blocks"]["attn_qkv"]["bias"]
    assert out["blocks"]["ln1"] is params["blocks"]["ln1"]
    assert out["blocks"]["moe"]["gate"]["kernel"].dtype == jnp.float32
    assert out["lm_head"]["kernel"].dtype == jnp.int8
    assert out["wte"] is params["wte"]
    again = pack_decode_weights(out)                    # already packed: noop
    assert again["blocks"]["attn_qkv"]["kernel"] is \
        out["blocks"]["attn_qkv"]["kernel"]


# ------------------------------------- per-architecture weight-only bounds

_ARCHS = {
    "gpt2ish": dict(preset="gpt2-tiny", hidden_size=32, num_layers=2,
                    num_heads=2, vocab_size=64),
    "llamaish": dict(preset="llama-1.1b", hidden_size=32, num_layers=2,
                     num_heads=4, num_kv_heads=2, mlp_dim_override=64,
                     vocab_size=64),
}


# tier-2 (round-17 budget sweep, ~12s): the cheaper tier-1 cousins are
# test_quant_matmul_kernel_reference_parity_and_analytic_bound (per-matmul
# bound) and test_serving.test_int8_weight_only_decode_parity (end-to-end
# token-exactness); scripts/tier2.sh runs this per-arch magnitude pin
@pytest.mark.slow
@pytest.mark.parametrize("arch", sorted(_ARCHS))
def test_weight_only_int8_logit_bound_per_arch(arch):
    """Blockwise-int8 weights perturb prefill logits by a small bounded
    amount per architecture — and leave the greedy argmax intact on the
    tested prompt (the serving tier's token-exactness contract rides
    tests/test_serving.py's engine legs; this pins the magnitude)."""
    kw = dict(_ARCHS[arch])
    model, cfg = build_model(kw.pop("preset"), max_seq_len=64,
                             attention_impl="reference",
                             dtype=jnp.float32, **kw)
    ids = np.asarray([[5, 9, 2, 7, 11, 3, 1, 8]], np.int32)
    params = ensure_scan_layout(
        model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"],
        cfg.num_layers)
    packed = pack_decode_weights(params)
    bs, nbk = 16, 4
    pools = init_pool(cfg, 8, bs)
    bt = np.zeros((1, nbk), np.int32)
    bt[0] = [1, 2, 3, 4]
    run = lambda p, pl_: paged_forward(
        cfg, p, jnp.asarray(ids), pl_, jnp.asarray(bt),
        jnp.zeros((1,), jnp.int32), jnp.full((1,), ids.shape[1], jnp.int32),
        bs)[0]
    base = np.asarray(run(params, pools))
    quant = np.asarray(run(packed, init_pool(cfg, 8, bs)))
    err = np.abs(quant - base).max()
    assert err < 0.15, f"{arch}: weight-only logit err {err}"
    assert np.array_equal(base[0, -1].argmax(), quant[0, -1].argmax())


# ------------------------------------------- the experiment's sentinel gate

def _lp_model(**kw):
    return build_model("gpt2-tiny", hidden_size=32, num_layers=2,
                       num_heads=2, vocab_size=64, max_seq_len=64,
                       attention_impl="reference", **kw)


def test_wire_low_precision_gates():
    """The low-precision step is a GATED experiment: both routes (config
    section and model knob) demand the integrity sentinel; unsupported
    schedules / bit widths / model families raise instead of silently
    training full precision."""
    act = {"shared_parameters": {"enabled": True},
           "different_groups": {"g": {"params": {"bits": 8}}}}
    ok = DeepSpeedConfig(
        compression_training={"activation_quantization": act},
        integrity={"enabled": True})
    model, _ = _lp_model()
    wired = wire_low_precision(model, ok)
    assert wired.cfg.activation_quant == "int8"
    # section enabled but sentinel off
    with pytest.raises(ValueError, match="integrity"):
        wire_low_precision(model, DeepSpeedConfig(
            compression_training={"activation_quantization": act}))
    # model knob without sentinel
    knob, _ = _lp_model(activation_quant="int8")
    with pytest.raises(ValueError, match="integrity"):
        wire_low_precision(knob, DeepSpeedConfig())
    # the knob + sentinel passes through untouched
    assert wire_low_precision(
        knob, DeepSpeedConfig(integrity={"enabled": True})
    ).cfg.activation_quant == "int8"
    # schedule offsets can't reach inside the model
    with pytest.raises(NotImplementedError, match="schedule_offset"):
        wire_low_precision(model, DeepSpeedConfig(
            compression_training={"activation_quantization": {
                "shared_parameters": {"enabled": True,
                                      "schedule_offset": 100}}},
            integrity={"enabled": True}))
    # only 8-bit activations
    with pytest.raises(ValueError, match="bits=4"):
        wire_low_precision(model, DeepSpeedConfig(
            compression_training={"activation_quantization": {
                "shared_parameters": {"enabled": True},
                "different_groups": {"g": {"params": {"bits": 4}}}}},
            integrity={"enabled": True}))
    # not a transformer: nothing to wire the knob into
    with pytest.raises(ValueError, match="TransformerConfig|transformer"):
        wire_low_precision(SimpleModel(), ok)
    # the knob itself validates its values at config construction
    with pytest.raises(ValueError, match="activation_quant"):
        _lp_model(activation_quant="int4")


# -------------------------------------------------- engine loss parity twin

def _lp_engine(activation_quant=None, integrity=True, batch=None):
    model, _ = _lp_model(fused_loss=True, loss_chunk=32,
                         activation_quant=activation_quant)
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "steps_per_print": 1000,
    }
    if integrity:
        cfg["integrity"] = {"enabled": True, "warmup_steps": 6,
                            "window": 16, "zmax": 6.0, "cooldown_steps": 0}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=cfg, loss_fn=fused_loss_passthrough,
        example_batch=batch)
    return engine


def _lm_batches(n, b=8, s=32, vocab=64, distinct=6):
    rng = np.random.default_rng(4)
    pool = [{"input_ids": rng.integers(0, vocab, size=(b, s))}
            for _ in range(distinct)]
    return [pool[i % distinct] for i in range(n)]


# tier-2 (round-17 budget sweep, 25s): the cheaper tier-1 cousins are
# test_wire_low_precision_gates (wiring + integrity refusal) and
# test_fake_quant_act_bounds_and_ste_gradient (quantizer math + STE);
# scripts/chaos.sh and scripts/tier2.sh run this 3-engine parity leg
@pytest.mark.slow
def test_low_precision_training_loss_parity():
    """The experiment's headline: int8/fp8 fake-quant training tracks the
    full-precision twin's loss trajectory on identical data; running the
    knob WITHOUT the sentinel is refused at engine construction."""
    batches = _lm_batches(9)
    with pytest.raises(ValueError, match="integrity"):
        _lp_engine("int8", integrity=False, batch=batches[0])
    losses = {}
    for fmt in (None, "int8", "fp8"):
        eng = _lp_engine(fmt, batch=batches[0])
        losses[fmt] = [float(jax.device_get(eng.train_batch(b)["loss"]))
                       for b in batches]
    assert losses[None][-1] < losses[None][0]           # it trains
    for fmt in ("int8", "fp8"):
        assert losses[fmt][-1] == pytest.approx(losses[None][-1], rel=0.05), \
            (fmt, losses[fmt][-1], losses[None][-1])


@pytest.mark.slow
def test_chaos_spike_on_low_precision_engine_skips_and_recovers():
    """scripts/chaos.sh low-precision leg: the guardrail the experiment is
    gated on actually fires under it. A chaos-poisoned step (sentinel.spike
    scales the batch's float features x1e4 -> loss and grads x1e4) is
    skipped in-jit by the quantized engine's sentinel, and the run trains
    through to loss parity with an uninjected low-precision twin."""
    steps = 24
    b = 8
    rng = np.random.default_rng(5)
    pool = [{"input_ids": rng.integers(0, 64, size=(b, 16)),
             "chaos_gain": np.ones((b,), np.float32)} for _ in range(6)]
    batches = [pool[i % 6] for i in range(steps)]
    # the float feature the engine-side spike can scale: a loss gain of 1
    gain_loss = lambda out, bt: causal_lm_loss(out, bt) * \
        jnp.mean(bt["chaos_gain"])

    def engine():
        model, _ = _lp_model(activation_quant="int8")
        return deepspeed_tpu.initialize(
            model=model, config={
                "train_batch_size": b,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "gradient_clipping": 1.0,
                "bf16": {"enabled": True},
                "steps_per_print": 1000,
                "integrity": {"enabled": True, "warmup_steps": 6,
                              "window": 16, "zmax": 6.0,
                              "cooldown_steps": 0},
            }, loss_fn=gain_loss, example_batch=batches[0])[0]

    clean = engine()
    clean_losses = [float(jax.device_get(clean.train_batch(bt)["loss"]))
                    for bt in batches]

    chaos.arm("sentinel.spike", "flag", skip=10, times=1, factor=10000)
    eng = engine()
    skipped_at, losses = [], []
    for i, bt in enumerate(batches):
        m = eng.train_batch(bt)
        losses.append(float(jax.device_get(m["loss"])))
        if "anomaly_skip" in m and bool(np.asarray(
                jax.device_get(m["anomaly_skip"]))):
            skipped_at.append(i + 1)
    assert skipped_at == [11], skipped_at
    assert int(jax.device_get(eng.state.skipped_steps)) == 1
    assert eng.sentinel.rollbacks_done == 0             # rung 1 was enough
    assert losses[-1] == pytest.approx(clean_losses[-1], rel=0.25)


# ------------------------------------------------- decode hot-path audit

def _collect_bulk_int8_converts(jaxpr, threshold, found, pallas=None):
    """Walk a jaxpr (recursing into sub-jaxprs in eqn params) collecting
    int8 -> float convert_element_type eqns with >= threshold elements,
    SKIPPING pallas_call bodies (in-kernel dequant is the design)."""
    pallas = pallas if pallas is not None else [0]
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            pallas[0] += 1
            continue
        if eqn.primitive.name == "convert_element_type":
            src = eqn.invars[0].aval
            dst = eqn.outvars[0].aval
            if (getattr(src, "dtype", None) == jnp.int8
                    and jnp.issubdtype(dst.dtype, jnp.floating)
                    and dst.size >= threshold):
                found.append((src.shape, dst.dtype, dst.size))
        for p in eqn.params.values():
            for sub in (p if isinstance(p, (list, tuple)) else (p,)):
                inner = getattr(sub, "jaxpr", None)
                if isinstance(sub, jax.extend.core.Jaxpr):
                    _collect_bulk_int8_converts(sub, threshold, found, pallas)
                elif inner is not None and hasattr(inner, "eqns"):
                    _collect_bulk_int8_converts(inner, threshold, found,
                                                pallas)
    return pallas[0]


def test_decode_hot_path_has_no_bulk_dequant_outside_kernels():
    """Acceptance audit: trace one int8-KV + int8-weight decode step (the
    Pallas tier, interpret mode) and prove NO int8 -> float conversion of
    pool-slice or packed-kernel size happens outside a pallas_call — the
    round-12 O(pool) dequant copy and the _kernel_of full-weight
    materialization are structurally absent from the hot path."""
    model, cfg = build_model("gpt2-tiny", max_seq_len=256,
                             attention_impl="reference", dtype=jnp.float32)
    ids = np.zeros((2, 1), np.int32)
    params = ensure_scan_layout(
        model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"],
        cfg.num_layers)
    packed = pack_decode_weights(params)
    bs, nbk, nblocks = 16, 4, 8
    pools = init_pool(cfg, nblocks, bs, dtype=jnp.int8)
    bt = np.asarray([[1, 2, 0, 0], [3, 4, 5, 0]], np.int32)
    ctx = np.asarray([5, 21], np.int32)

    def step(pools):
        return paged_forward(cfg, packed, jnp.asarray(ids), pools,
                             jnp.asarray(bt), jnp.asarray(ctx - 1),
                             jnp.asarray(ctx), bs, interpret=True)

    jaxpr = jax.make_jaxpr(step)(pools)
    # the smallest guarded object: one layer's pool slice (nh * slots * hd
    # = 4 * 128 * 32 = 16k elems); packed kernels are >= 32k. Anything
    # int8->float at >= 1/4 of that size outside a kernel is a bulk copy.
    threshold = cfg.num_heads * nblocks * bs * cfg.head_dim // 4
    found = []
    n_pallas = _collect_bulk_int8_converts(jaxpr.jaxpr, threshold, found)
    assert n_pallas >= 2, "expected paged-attention AND quant-matmul " \
        f"pallas_calls on the traced decode step, saw {n_pallas}"
    assert not found, (
        "bulk int8->float dequant outside Pallas kernels on the decode "
        f"hot path: {found}")
