"""Inventory gap batch: data analyzer, memory utils, zero_to_fp32 CLI,
ds_ssh/MVAPICH, op registry, offload remat policy.
"""

import os
import subprocess
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from util import SimpleModel, random_batch


def test_data_analyzer_shard_and_merge(tmp_path):
    from deepspeed_tpu.runtime.data_pipeline.data_analyzer import DataAnalyzer
    data = [{"input_ids": np.zeros(n, np.int32)} for n in
            [5, 50, 10, 40, 20, 30]]
    path = str(tmp_path / "metrics")
    for w in range(2):
        DataAnalyzer(data, metric="seqlen", num_workers=2, worker_id=w,
                     save_path=path).run()
    DataAnalyzer.merge(path, num_workers=2)
    out = DataAnalyzer.load(path)
    np.testing.assert_array_equal(out["values"], [5, 50, 10, 40, 20, 30])
    np.testing.assert_array_equal(out["sorted_indices"], [0, 2, 4, 5, 3, 1])
    # feeds the curriculum sampler
    from deepspeed_tpu.runtime.data_pipeline import DeepSpeedDataSampler
    sampler = DeepSpeedDataSampler(
        out["values"], batch_size=2,
        curriculum_config={"min_difficulty": 10, "max_difficulty": 50,
                           "schedule_type": "fixed_linear",
                           "schedule_config": {"total_curriculum_step": 10,
                                               "difficulty_step": 10}})
    first = next(iter(sampler))
    assert all(out["values"][i] <= 10 for i in first)


def test_vocab_rarity_metric():
    from deepspeed_tpu.runtime.data_pipeline.data_analyzer import \
        vocab_rarity_metric
    freq = np.array([0.9, 0.1])
    m = vocab_rarity_metric(freq)
    common = m({"input_ids": np.zeros(4, np.int32)})
    rare = m({"input_ids": np.ones(4, np.int32)})
    assert rare > common


def test_see_memory_usage():
    from deepspeed_tpu.utils.memory import see_memory_usage
    assert see_memory_usage("tag") is None          # default no-op
    out = see_memory_usage("tag", force=True)
    assert out is not None and out["host_rss_GB"] > 0


def test_zero_to_fp32_cli(tmp_path):
    import deepspeed_tpu as ds
    cfg = {"train_batch_size": 8, "bf16": {"enabled": True},
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 2}}
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                               example_batch=random_batch(8))
    engine.train_batch(random_batch(8))
    engine.save_checkpoint(str(tmp_path / "ck"))
    out = str(tmp_path / "weights.npz")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "zero_to_fp32"),
         str(tmp_path / "ck"), out],
        env=dict(os.environ, PYTHONPATH=REPO), capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stderr[-1000:]
    with np.load(out) as d:
        assert all(d[k].dtype == np.float32 for k in d.files)
        assert len(d.files) >= 6


def test_ds_ssh_localhost(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("localhost slots=1\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "ds_ssh"),
         "-H", str(hostfile), "--", "echo", "hello-ds-ssh"],
        env=dict(os.environ, PYTHONPATH=REPO), capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0
    assert "hello-ds-ssh" in proc.stdout


def test_mvapich_runner_cmd():
    from deepspeed_tpu.launcher.multinode_runner import MVAPICHRunner
    ns = types.SimpleNamespace(user_script="t.py", user_args=[],
                               hostfile="/job/hostfile", include="")
    cmd = MVAPICHRunner(ns).get_cmd({"DSTPU_COORDINATOR": "h0"},
                                    {"a": [0], "b": [0]})
    assert cmd[:3] == ["mpirun_rsh", "-np", "2"]
    assert "--node_rank=-1" in cmd


def test_op_registry_selection_and_report():
    from deepspeed_tpu.ops.registry import compatibility_report, get_op
    rep = compatibility_report()
    assert "attention" in rep and "cpu_adam" in rep
    # on CPU the xla fallback must be chosen for attention
    fn = get_op("attention")
    from deepspeed_tpu.ops.attention import mha_reference
    assert fn is mha_reference or jax.default_backend() == "tpu"
    with pytest.raises(KeyError):
        get_op("nonexistent")
    # named-impl selection
    assert get_op("cpu_adam", "numpy") is not None


def test_offload_remat_policy_available():
    """remat_policy='offload' (cpu activation checkpointing) builds + runs."""
    from deepspeed_tpu.models import build_model, causal_lm_loss
    model, cfg = build_model("gpt2-tiny", remat=True, remat_policy="offload",
                             max_seq_len=64, attention_impl="reference",
                             dtype=jnp.float32)
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 16)))

    def loss(p):
        return causal_lm_loss(model.apply({"params": p},
                                          {"input_ids": ids}), ids)

    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    l, g = jax.jit(jax.value_and_grad(loss))(params)
    assert np.isfinite(float(l))
    assert all(np.all(np.isfinite(np.asarray(x))) for x in jax.tree.leaves(g))
