"""ZeRO sharding-policy unit tests: dim choice, persistence threshold, and
the no-involuntary-rematerialization property of the compiled MoE step.

Mirrors the reference's partitioning unit coverage (tests/unit/runtime/zero)
at the spec level — on TPU the partition IS the spec."""

import os
import subprocess
import sys

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.zero.stages import ZeroShardingPolicy, insert_zero_axes
from deepspeed_tpu.parallel.mesh import MeshManager


def test_insert_zero_axes_prefers_largest_free_dim():
    spec = insert_zero_axes((256, 64), None, ("data",), 4)
    assert spec == P("data", None)


def test_insert_zero_axes_avoid_last_skips_feature_dim():
    # only the last dim is free+divisible: compute params stay whole...
    spec = insert_zero_axes((250, 64), P("model", None), ("data",), 4,
                            avoid_last=True)
    assert spec == P("model", None)
    # ...but master/grad shards (no avoid_last) still take it
    spec = insert_zero_axes((250, 64), P("model", None), ("data",), 4)
    assert spec == P("model", "data")
    # 1-D params are exempt from avoid_last
    spec = insert_zero_axes((64,), None, ("data",), 4, avoid_last=True)
    assert spec == P("data")


def _policy(stage, threshold=0):
    mm = MeshManager()          # trivial 1-device mesh: sizes all 1
    pol = ZeroShardingPolicy(stage, mm, param_persistence_threshold=threshold)
    # fake a 4-way zero world so specs are non-trivial
    pol._zero_size = 4
    return pol


def test_persistence_threshold_keeps_small_params_whole():
    pol = _policy(3, threshold=1000)
    assert pol.param_spec((16, 32)) == P()          # 512 < 1000: persistent
    assert pol.param_spec((64, 256)) == P(("data", "expert", "seq"), None)  # 16384 >= 1000
    # master/grad shards ignore the threshold (memory lives there)
    assert pol.master_spec((16, 32)) == P(None, ("data", "expert", "seq"))


def test_grad_floor_keeps_tiny_grads_whole():
    pol = _policy(2)
    assert pol.grad_spec((64,)) == P()              # 64 < floor
    assert pol.grad_spec((256, 64)) == P(("data", "expert", "seq"), None)


MOE_NO_REMAT_SCRIPT = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, os.getcwd())   # repo root (the test sets cwd; PYTHONPATH
                                  # would break the axon plugin registration)
import numpy as np
import deepspeed_tpu as ds
from deepspeed_tpu.models import make_moe_loss, build_model

mmodel, mcfg = build_model("gpt2-tiny", hidden_size=64, num_layers=2,
    num_heads=4, vocab_size=256, max_seq_len=64, moe_experts=4,
    moe_capacity_factor=2.0, attention_impl="reference")
mconfig = {"train_batch_size": 16, "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    "bf16": {"enabled": True}, "zero_optimization": {"stage": 2},
    "moe": {"enabled": True, "ep_size": 2}}
mbatch = {"input_ids": np.random.default_rng(3).integers(0, 256, size=(16, 32))}
meng, *_ = ds.initialize(model=mmodel, config=mconfig,
                         loss_fn=make_moe_loss(mcfg.moe_aux_weight),
                         example_batch=mbatch, sharding_rules=mcfg.tp_rules())
print("loss", float(meng.train_batch(mbatch)["loss"]))
"""


@pytest.mark.slow
def test_moe_step_has_no_involuntary_rematerialization(tmp_path):
    """The grouped GShard dispatch layout keeps every tensor's sharding
    transition expressible as a collective — the SPMD partitioner must not
    fall back to replicate-and-reshard anywhere in the compiled MoE train
    step (round-2 VERDICT: 'a wall of XLA involuntary full rematerialization
    warnings on blocks/moe/reshape')."""
    import pathlib
    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
    script = tmp_path / "moe_no_remat.py"
    script.write_text(MOE_NO_REMAT_SCRIPT)
    proc = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        timeout=900, cwd=repo_root)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "loss" in proc.stdout
    assert "Involuntary full rematerialization" not in proc.stderr, \
        [l for l in proc.stderr.splitlines() if "rematerialization" in l][:4]


def test_compose_tp_dim_specs():
    """ZeRO axes compose onto an already-TP-sharded dim when divisible
    (round-3 Weak #2: a fresh H-dim sharding on kernel grads couples the
    backward scan carry into an H layout -> involuntary remat); embedding
    grads stay TP-only when vocab is genuinely TP-sharded."""
    from deepspeed_tpu.parallel.mesh import MeshManager
    from deepspeed_tpu.runtime.zero.stages import ZeroShardingPolicy

    mm = MeshManager(tp_size=2, sp_size=2)     # data=2, seq=2, model=2
    pol = ZeroShardingPolicy(3, mm)
    # stacked qkv kernel [L, H, 3H], TP on the last dim: ZeRO axes compose
    # onto it (192 % (2 tp * 4 zero) == 0) instead of opening the H dim
    spec = pol.grad_spec((2, 64, 192), P(None, None, "model"))
    assert spec == P(None, None, ("model", "data", "expert", "seq")), spec
    # compute params compose the same way
    spec = pol.param_spec((2, 64, 192), P(None, None, "model"))
    assert spec == P(None, None, ("model", "data", "expert", "seq")), spec
    # row-parallel attn_proj [L, H, H]: TP dim 1 absorbs the zero axes
    spec = pol.grad_spec((2, 64, 64), P(None, "model", None))
    assert spec == P(None, ("model", "data", "expert", "seq"), None), spec
    # vocab-parallel embedding: grads stay TP-only (scatter-dim widening and
    # fresh-H sharding both break partitioning; master keeps the ZeRO win)
    spec = pol.grad_spec((256, 64), P("model", None), path="wte/embedding")
    assert spec == P("model", None), spec
    assert pol.master_spec((256, 64), P("model", None),
                           path="wte/embedding") != P("model", None)
    # no TP spec (tp=1 world): unchanged fresh-dim behavior
    mm1 = MeshManager()
    pol1 = ZeroShardingPolicy(2, mm1)
    assert pol1.grad_spec((256, 64)) == P(("data", "expert", "seq"), None)


def test_dryrun_legs_have_no_involuntary_rematerialization():
    """ALL multichip dryrun legs (ZeRO3+TP+SP, PP+TP+DP, 1F1B+DP, MoE+EP)
    must compile without a single SPMD replicate-and-reshard fallback —
    round-3 left two on the ZeRO3+TP+SP backward scan carry."""
    import pathlib
    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    # lite shapes are the dryrun default; the 6.7b-shape ladder variant is
    # opt-in (DSTPU_DRYRUN_FULL=1) and costs ~12 min the suite should not
    # pay per run — make sure it stays off even if the caller exported it
    env.pop("DSTPU_DRYRUN_FULL", None)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        capture_output=True, text=True, timeout=1800, cwd=repo_root, env=env)
    assert proc.returncode == 0, proc.stderr[-3000:]
    # round 10 added the moe_q leg (int8 expert a2a through the comm-plan
    # explicit exchange) — its transitions must be remat-free too
    assert proc.stdout.count("ok") >= 6, proc.stdout
    assert "Involuntary full rematerialization" not in proc.stderr, \
        [l for l in proc.stderr.splitlines() if "rematerialization" in l][:4]
