"""ZeRO sharding-policy unit tests: dim choice, persistence threshold, and
the no-involuntary-rematerialization property of the compiled MoE step.

Mirrors the reference's partitioning unit coverage (tests/unit/runtime/zero)
at the spec level — on TPU the partition IS the spec."""

import subprocess
import sys

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.zero.stages import ZeroShardingPolicy, insert_zero_axes
from deepspeed_tpu.parallel.mesh import MeshManager


def test_insert_zero_axes_prefers_largest_free_dim():
    spec = insert_zero_axes((256, 64), None, ("data",), 4)
    assert spec == P("data", None)


def test_insert_zero_axes_avoid_last_skips_feature_dim():
    # only the last dim is free+divisible: compute params stay whole...
    spec = insert_zero_axes((250, 64), P("model", None), ("data",), 4,
                            avoid_last=True)
    assert spec == P("model", None)
    # ...but master/grad shards (no avoid_last) still take it
    spec = insert_zero_axes((250, 64), P("model", None), ("data",), 4)
    assert spec == P("model", "data")
    # 1-D params are exempt from avoid_last
    spec = insert_zero_axes((64,), None, ("data",), 4, avoid_last=True)
    assert spec == P("data")


def _policy(stage, threshold=0):
    mm = MeshManager()          # trivial 1-device mesh: sizes all 1
    pol = ZeroShardingPolicy(stage, mm, param_persistence_threshold=threshold)
    # fake a 4-way zero world so specs are non-trivial
    pol._zero_size = 4
    return pol


def test_persistence_threshold_keeps_small_params_whole():
    pol = _policy(3, threshold=1000)
    assert pol.param_spec((16, 32)) == P()          # 512 < 1000: persistent
    assert pol.param_spec((64, 256)) == P(("data", "expert", "seq"), None)  # 16384 >= 1000
    # master/grad shards ignore the threshold (memory lives there)
    assert pol.master_spec((16, 32)) == P(None, ("data", "expert", "seq"))


def test_grad_floor_keeps_tiny_grads_whole():
    pol = _policy(2)
    assert pol.grad_spec((64,)) == P()              # 64 < floor
    assert pol.grad_spec((256, 64)) == P(("data", "expert", "seq"), None)


MOE_NO_REMAT_SCRIPT = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, os.getcwd())   # repo root (the test sets cwd; PYTHONPATH
                                  # would break the axon plugin registration)
import numpy as np
import deepspeed_tpu as ds
from deepspeed_tpu.models import make_moe_loss, build_model

mmodel, mcfg = build_model("gpt2-tiny", hidden_size=64, num_layers=2,
    num_heads=4, vocab_size=256, max_seq_len=64, moe_experts=4,
    moe_capacity_factor=2.0, attention_impl="reference")
mconfig = {"train_batch_size": 16, "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    "bf16": {"enabled": True}, "zero_optimization": {"stage": 2},
    "moe": {"enabled": True, "ep_size": 2}}
mbatch = {"input_ids": np.random.default_rng(3).integers(0, 256, size=(16, 32))}
meng, *_ = ds.initialize(model=mmodel, config=mconfig,
                         loss_fn=make_moe_loss(mcfg.moe_aux_weight),
                         example_batch=mbatch, sharding_rules=mcfg.tp_rules())
print("loss", float(meng.train_batch(mbatch)["loss"]))
"""


def test_moe_step_has_no_involuntary_rematerialization(tmp_path):
    """The grouped GShard dispatch layout keeps every tensor's sharding
    transition expressible as a collective — the SPMD partitioner must not
    fall back to replicate-and-reshard anywhere in the compiled MoE train
    step (round-2 VERDICT: 'a wall of XLA involuntary full rematerialization
    warnings on blocks/moe/reshape')."""
    import pathlib
    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
    script = tmp_path / "moe_no_remat.py"
    script.write_text(MOE_NO_REMAT_SCRIPT)
    proc = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        timeout=900, cwd=repo_root)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "loss" in proc.stdout
    assert "Involuntary full rematerialization" not in proc.stderr, \
        [l for l in proc.stderr.splitlines() if "rematerialization" in l][:4]
