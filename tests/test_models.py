"""Flagship transformer: shapes, loss descent through the engine, TP rules."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import (Transformer, build_model, causal_lm_loss,
                                  get_config)


def tiny_batch(rng, cfg, batch=8, seq=32):
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq))
    return {"input_ids": ids}


def test_forward_shapes():
    model, cfg = build_model("gpt2-tiny", attention_impl="reference")
    batch = tiny_batch(np.random.default_rng(0), cfg)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    logits = model.apply({"params": params}, batch)
    assert logits.shape == (8, 32, cfg.vocab_size)
    assert logits.dtype == jnp.float32


# tier-2 (round-19 budget sweep, ~8s): the scanned path gates tier-1
# end-to-end (test_engine_trains_transformer[0],
# test_fused_loss_encoder_no_shift); this pin of the unrolled-loop
# twin runs in scripts/tier2.sh
@pytest.mark.slow
def test_scan_and_loop_agree():
    """nn.scan over layers must match the unrolled loop numerically."""
    kw = dict(hidden_size=64, num_layers=3, num_heads=4, vocab_size=128,
              max_seq_len=64, dtype=jnp.float32, attention_impl="reference")
    m_scan, cfg = build_model("gpt2-tiny", scan_layers=True, **kw)
    m_loop, _ = build_model("gpt2-tiny", scan_layers=False, **kw)
    batch = tiny_batch(np.random.default_rng(1), cfg, batch=2, seq=16)
    p_scan = m_scan.init(jax.random.PRNGKey(7), batch)["params"]
    # map scanned params [L, ...] -> per-layer dicts for the loop model
    p_loop = {k: v for k, v in p_scan.items() if k != "blocks"}
    for i in range(cfg.num_layers):
        p_loop[f"blocks_{i}"] = jax.tree.map(lambda x: x[i], p_scan["blocks"])
    out_scan = m_scan.apply({"params": p_scan}, batch)
    out_loop = m_loop.apply({"params": p_loop}, batch)
    np.testing.assert_allclose(out_scan, out_loop, rtol=2e-5, atol=2e-5)


# stages 2/3 are tier-2 (round 8 budget): test_zero_stage_trains[2]/[3]
# keep per-stage engine training gating tier-1 at a third the cost
@pytest.mark.parametrize(
    "stage", [0, pytest.param(2, marks=pytest.mark.slow),
              pytest.param(3, marks=pytest.mark.slow)])
def test_engine_trains_transformer(stage):
    model, cfg = build_model("gpt2-tiny", hidden_size=64, num_layers=2,
                             num_heads=4, vocab_size=256, max_seq_len=64,
                             attention_impl="reference")
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": stage},
    }
    rng = np.random.default_rng(2)
    batch = tiny_batch(rng, cfg, batch=16, seq=32)
    engine, *_ = ds.initialize(model=model, config=config,
                               loss_fn=causal_lm_loss, example_batch=batch)
    losses = [float(engine.train_batch(tiny_batch(rng, cfg, 16, 32))["loss"])
              for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_tp_rules_cover_params():
    model, cfg = build_model("gpt2-tiny", attention_impl="reference")
    rules = cfg.tp_rules()
    batch = tiny_batch(np.random.default_rng(0), cfg, batch=2, seq=16)
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    from deepspeed_tpu.utils.partitioning import build_tp_specs
    specs = build_tp_specs(params, rules)
    flat = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: x is None or hasattr(x, "index"))
    matched = [s for s in flat if s is not None]
    # qkv, qkv bias, proj, fc, fc bias, fc_proj, wte at minimum
    assert len(matched) >= 6


@pytest.mark.slow
def test_tp_sharded_engine_matches_unsharded():
    """2-way TP x 2-way DP on the 8-dev CPU mesh == single-device numerics."""
    kw = dict(hidden_size=64, num_layers=2, num_heads=4, vocab_size=256,
              max_seq_len=64, dtype=jnp.float32, attention_impl="reference")
    model, cfg = build_model("gpt2-tiny", **kw)
    rng = np.random.default_rng(3)
    batch = tiny_batch(rng, cfg, batch=16, seq=32)
    base = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
    }
    cfg_tp = dict(base, tensor_parallel={"tp_size": 2},
                  zero_optimization={"stage": 1})
    eng_plain, *_ = ds.initialize(model=model, config=base,
                                  loss_fn=causal_lm_loss, example_batch=batch,
                                  rng=jax.random.PRNGKey(11))
    eng_tp, *_ = ds.initialize(model=model, config=cfg_tp,
                               loss_fn=causal_lm_loss, example_batch=batch,
                               rng=jax.random.PRNGKey(11),
                               sharding_rules=cfg.tp_rules())
    m1 = eng_plain.train_batch(batch)
    m2 = eng_tp.train_batch(batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_fused_loss_matches_unfused():
    """fused_loss=True returns the same scalar + grads as logits->causal_lm_loss,
    including ignore_index=-100 masking, at a chunk size that forces padding."""
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, size=(2, 64))
    kw = dict(vocab_size=256, max_seq_len=64, dtype=jnp.float32,
              attention_impl="reference")
    m1, _ = build_model("gpt2-tiny", **kw)
    m2, _ = build_model("gpt2-tiny", fused_loss=True, loss_chunk=24, **kw)
    batch = {"input_ids": jnp.asarray(ids)}
    params = m1.init(jax.random.PRNGKey(0), batch)["params"]

    l1 = causal_lm_loss(m1.apply({"params": params}, batch), batch)
    l2 = m2.apply({"params": params}, batch)
    assert abs(float(l1 - l2)) < 1e-5

    g1 = jax.grad(lambda p: causal_lm_loss(m1.apply({"params": p}, batch),
                                           batch))(params)
    g2 = jax.grad(lambda p: m2.apply({"params": p}, batch))(params)
    errs = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))), g1, g2)
    assert max(jax.tree.leaves(errs)) < 1e-4

    labels = ids.copy()
    labels[:, 10:20] = -100
    b2 = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}
    l1m = causal_lm_loss(m1.apply({"params": params}, b2), b2)
    l2m = m2.apply({"params": params}, b2)
    assert abs(float(l1m - l2m)) < 1e-5


@pytest.mark.slow
def test_remat_policies_agree():
    """dots/full remat and no remat give identical losses AND gradients
    (remat only changes what is saved for backward, so grads are where a
    broken checkpoint policy would show up)."""
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 256, size=(2, 32))
    batch = {"input_ids": jnp.asarray(ids)}
    results = []
    for remat, policy in [(False, "dots"), (True, "dots"), (True, "full"),
                          (True, "attn")]:
        m, _ = build_model("gpt2-tiny", vocab_size=256, max_seq_len=32,
                           dtype=jnp.float32, attention_impl="reference",
                           remat=remat, remat_policy=policy)
        params = m.init(jax.random.PRNGKey(0), batch)["params"]
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(m.apply({"params": p}, batch), batch)
        )(params)
        results.append((float(loss), grads))
    base_loss, base_grads = results[0]
    for loss, grads in results[1:]:
        assert loss == pytest.approx(base_loss, abs=1e-6)
        errs = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                            base_grads, grads)
        assert max(jax.tree.leaves(errs)) < 1e-5


def test_fused_loss_encoder_no_shift():
    """causal=False (BERT-style) fused loss predicts in place: matches plain
    per-token cross_entropy on the logits with no shift."""
    from deepspeed_tpu.models.transformer import cross_entropy
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 256, size=(2, 48))
    kw = dict(vocab_size=256, max_seq_len=64, causal=False,
              dtype=jnp.float32, attention_impl="reference")
    m1, _ = build_model("gpt2-tiny", **kw)
    m2, _ = build_model("gpt2-tiny", fused_loss=True, loss_chunk=20, **kw)
    batch = {"input_ids": jnp.asarray(ids)}
    params = m1.init(jax.random.PRNGKey(0), batch)["params"]

    logits = m1.apply({"params": params}, batch)
    l1 = cross_entropy(logits, jnp.asarray(ids))
    l2 = m2.apply({"params": params}, batch)
    assert abs(float(l1 - l2)) < 1e-5

    labels = ids.copy()
    labels[:, :8] = -100            # masked-LM-style ignore positions
    b2 = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}
    l1m = cross_entropy(m1.apply({"params": params}, b2), jnp.asarray(labels))
    l2m = m2.apply({"params": params}, b2)
    assert abs(float(l1m - l2m)) < 1e-5


def test_adhoc_jit_off_mesh_runs_unconstrained():
    """With a multi-device session mesh installed, a plain-jit model call on
    data committed to ONE device must run unconstrained (the activation
    constraints would otherwise pin it to the full mesh and fail dispatch
    with an incompatible-devices error)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    model, cfg = build_model("gpt2-tiny", hidden_size=64, num_layers=2,
                             num_heads=4, vocab_size=256, max_seq_len=64,
                             attention_impl="reference")
    engine, *_ = ds.initialize(
        model=model,
        config={"train_batch_size": 16,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1}},
        loss_fn=causal_lm_loss,
        example_batch={"input_ids": np.zeros((16, 32), np.int64)})
    # divisible batch, params + inputs committed to a non-default device
    params = jax.device_put(jax.device_get(engine.state.params),
                            jax.devices()[1])
    x = jax.device_put(jnp.zeros((8, 32), jnp.int32), jax.devices()[1])
    out = jax.jit(lambda p, b: model.apply({"params": p}, b))(
        params, {"input_ids": x})
    assert out.shape == (8, 32, 256)
    assert {d.id for d in out.devices()} == {1}
    # the session engine still steps (its program keeps the mesh layout)
    m = engine.train_batch({"input_ids": np.random.default_rng(1).integers(
        0, 256, size=(16, 32))})
    assert np.isfinite(float(m["loss"]))


# tier-2 (round 8 budget): test_fused_loss_encoder_no_shift keeps the
# fused-CE path gating tier-1; the untied-head variant rides tier2
@pytest.mark.slow
def test_fused_loss_untied_head_matches_dense_path():
    """fused_loss now supports untied lm_head models (Llama family): the
    param tree is IDENTICAL to the non-fused nn.Dense path (shared
    checkpoints/HF imports) and the loss matches token-level CE."""
    from deepspeed_tpu.models import fused_loss_passthrough
    kw = dict(hidden_size=64, num_layers=2, num_heads=4, vocab_size=128,
              max_seq_len=64, tie_embeddings=False, dtype=jnp.float32,
              attention_impl="reference")
    m1, _ = build_model("gpt2-tiny", fused_loss=False, **kw)
    m2, _ = build_model("gpt2-tiny", fused_loss=True, loss_chunk=16, **kw)
    ids = np.random.default_rng(0).integers(0, 128, (2, 32))
    batch = {"input_ids": jnp.asarray(ids)}
    p = m1.init(jax.random.PRNGKey(0), batch)["params"]
    p2 = m2.init(jax.random.PRNGKey(0), batch)["params"]
    assert jax.tree.structure(p) == jax.tree.structure(p2)
    l1 = float(causal_lm_loss(m1.apply({"params": p}, batch), batch))
    l2 = float(m2.apply({"params": p}, batch))
    assert abs(l1 - l2) < 1e-4, (l1, l2)
    # biased untied head has no fused path — must refuse, not drop the bias
    m3, _ = build_model("gpt2-tiny", fused_loss=True, lm_head_bias=True, **kw)
    with pytest.raises(ValueError, match="BIASED"):
        m3.init(jax.random.PRNGKey(0), batch)


# tier-2 (round-19 budget sweep, ~9s): the cheaper tier-1 cousins are
# test_engine_trains_transformer[0] (same training loop, gpt2 preset),
# test_hf_llama_parity (llama block math) and
# test_fused_loss_encoder_no_shift (fused CE); scripts/tier2.sh runs this
@pytest.mark.slow
def test_llama_preset_trains():
    """The llama-1.1b preset's block recipe (tiny-shaped here) trains
    through the engine with the fused untied-head CE."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import fused_loss_passthrough
    model, cfg = build_model("llama-1.1b", hidden_size=64, num_layers=2,
                             num_heads=4, num_kv_heads=2, vocab_size=256,
                             max_seq_len=64, mlp_dim_override=96,
                             fused_loss=True, loss_chunk=16,
                             attention_impl="reference")
    rng = np.random.default_rng(1)
    mk = lambda: {"input_ids": rng.integers(0, 256, size=(16, 32))}
    engine, *_ = ds.initialize(
        model=model,
        config={"train_batch_size": 16,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2}},
        loss_fn=fused_loss_passthrough, example_batch=mk())
    losses = [float(engine.train_batch(mk())["loss"]) for _ in range(20)]
    # bf16 on random tokens descends noisily: compare window means
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.05, losses
