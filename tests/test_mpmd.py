"""MPMD pipeline placement (round 13): schedule/placement split, explicit
transfer channel, per-stage programs, one-stage elastic restart.

Parity strategy on this host matters: the SPMD pipeline executors need
``jax.shard_map`` (absent on the 0.4.x jaxlib — the documented
pre-existing failure class), so the always-on oracle is plain autodiff
of the SAME parameters through the non-pipelined model, and the
MPMD-vs-SPMD engine legs guard on shard_map availability. The MPMD path
itself never touches shard_map — it is the pipeline placement that DOES
run on 0.4.x hosts.
"""

import json
import os
import re
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from util import require_devices

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model, causal_lm_loss
from deepspeed_tpu.models.pipeline import build_pipelined_model
from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass, ForwardPass, LoadMicroBatch, RecvActivation, RecvGrad,
    SendActivation, SendGrad, TrainSchedule, build_1f1b_tables,
    build_gpipe_tables, build_tables, stage_instruction_stream)
from deepspeed_tpu.runtime.pipe.mpmd import (LocalChannel, MPMDPipeline,
                                             MPMDStageSupervisor,
                                             StageWorkerSpec,
                                             mpmd_value_and_grad)
from deepspeed_tpu.testing import chaos

HAS_SHARD_MAP = hasattr(jax, "shard_map")


# -- schedule layer: tables + instruction streams -----------------------------

def test_gpipe_tables_valid():
    """Full fill/drain then the backward wave: every micro forwards and
    backwards exactly once per stage, forwards strictly ordered down the
    pipe, backwards strictly ordered up it, and the in-flight bound is
    the GPipe regime (n_micro), not 1F1B's min(pp, m)."""
    for m, pp in [(4, 2), (8, 4), (3, 4), (6, 3)]:
        t = build_gpipe_tables(m, pp)
        fwd, bwd = t["fwd"], t["bwd"]
        for s in range(pp):
            assert sorted(x for x in fwd[:, s] if x >= 0) == list(range(m))
            assert sorted(x for x in bwd[:, s] if x >= 0) == list(range(m))
            inflight = np.cumsum(fwd[:, s] >= 0) - np.cumsum(bwd[:, s] >= 0)
            assert inflight.max() == m          # GPipe memory regime
        for s in range(1, pp):
            for f in range(m):
                assert int(np.where(fwd[:, s] == f)[0][0]) > \
                    int(np.where(fwd[:, s - 1] == f)[0][0])
                assert int(np.where(bwd[:, s - 1] == f)[0][0]) > \
                    int(np.where(bwd[:, s] == f)[0][0])


def test_build_tables_dispatch():
    t1 = build_tables("1f1b", 4, 2)
    t2 = build_tables("gpipe", 4, 2)
    assert t1["ticks"] <= t2["ticks"]       # 1f1b interleaves, gpipe waits
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        build_tables("zigzag", 4, 2)


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
def test_instruction_stream_matches_schedule_vocabulary(schedule):
    """The per-stage instruction stream rendered from the clock tables
    carries the SAME instruction counts as the reference-API generator
    schedule — the schedule/placement split's contract: one schedule,
    two executions."""
    m, pp = 6, 3
    tables = build_tables(schedule, m, pp)
    for sid in range(pp):
        stream = stage_instruction_stream(tables, sid)
        flat = [c for tick in stream for c in tick]
        assert sum(isinstance(c, ForwardPass) for c in flat) == m
        assert sum(isinstance(c, BackwardPass) for c in flat) == m
        if sid == 0:
            assert sum(isinstance(c, LoadMicroBatch) for c in flat) == m
            assert not any(isinstance(c, (RecvActivation, SendGrad))
                           for c in flat)
        else:
            assert sum(isinstance(c, RecvActivation) for c in flat) == m
            assert sum(isinstance(c, SendGrad) for c in flat) == m
        if sid < pp - 1:
            assert sum(isinstance(c, SendActivation) for c in flat) == m
            assert sum(isinstance(c, RecvGrad) for c in flat) == m
        else:
            assert not any(isinstance(c, (SendActivation, RecvGrad))
                           for c in flat)
        # legacy generator agreement (1f1b == the reference TrainSchedule)
        if schedule == "1f1b":
            ref = [c for step in TrainSchedule(m, pp, sid) for c in step]
            for cls in (ForwardPass, BackwardPass, RecvActivation,
                        SendActivation, RecvGrad, SendGrad, LoadMicroBatch):
                assert sum(isinstance(c, cls) for c in flat) == \
                    sum(isinstance(c, cls) for c in ref), (sid, cls)


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
def test_instruction_stream_send_recv_pairing(schedule):
    """Every send at tick t has its matching recv at tick t+1 on the
    neighbor — the one-tick transfer alignment both placements rely on."""
    m, pp = 5, 3
    tables = build_tables(schedule, m, pp)
    streams = [stage_instruction_stream(tables, s) for s in range(pp)]
    T = len(streams[0])
    for t in range(T):
        for s in range(pp):
            for c in streams[s][t]:
                if isinstance(c, SendActivation):
                    assert t + 1 < T
                    assert any(isinstance(r, RecvActivation)
                               and r.buffer_id == c.buffer_id
                               for r in streams[s + 1][t + 1])
                if isinstance(c, SendGrad):
                    assert any(isinstance(r, RecvGrad)
                               and r.buffer_id == c.buffer_id
                               for r in streams[s - 1][t + 1])


def test_spmd_executor_imports_tables_from_schedule_layer():
    """The placement split: one_f_one_b consumes the SAME table builder
    the schedule layer owns (a re-export, not a copy)."""
    from deepspeed_tpu.runtime.pipe import one_f_one_b, schedule
    assert one_f_one_b.build_1f1b_tables is schedule.build_1f1b_tables


# -- transfer channel ---------------------------------------------------------

def test_local_channel_fifo_and_schedule_violation():
    ch = LocalChannel()
    ch.send("act", 0, 1, 0, "a0")
    ch.send("act", 0, 1, 1, "a1")
    assert ch.recv("act", 1, 0) == "a0"
    with pytest.raises(RuntimeError, match="schedule violation"):
        ch.recv("act", 1, 2)                  # expected micro 2, queued 1
    ch.clear()
    from deepspeed_tpu.runtime.pipe.mpmd.channel import ChannelTimeout
    with pytest.raises(ChannelTimeout):
        ch.recv("act", 1, 0)


def test_local_channel_xfer_failpoint():
    ch = LocalChannel()
    chaos.arm("pipe.xfer", "raise", match="act:0->1")
    with pytest.raises(IOError):
        ch.send("act", 0, 1, 0, "x")
    # keyed: the grad edge is untouched
    ch.send("grad", 1, 0, 0, "g")
    assert chaos.fired("pipe.xfer") == ["pipe.xfer"]


# -- MPMD executor: parity oracles --------------------------------------------

def _toy_problem(pp=4, n_micro=6, mb=2, H=8):
    rng = np.random.RandomState(0)
    sp = {"w": jnp.asarray(rng.randn(pp, H, H) * 0.3, jnp.float32),
          "b": jnp.asarray(rng.randn(pp, H) * 0.1, jnp.float32)}
    head = {"v": jnp.asarray(rng.randn(H) * 0.5, jnp.float32)}
    micros = jnp.asarray(rng.randn(n_micro, mb, H), jnp.float32)
    labels = jnp.asarray(rng.randn(n_micro, mb), jnp.float32)

    def stage_fn(p, x, extra, stage):
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_fn(h, y, lab, ctx):
        return jnp.mean((y @ h["v"] - lab) ** 2)

    return sp, head, micros, labels, stage_fn, loss_fn


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
def test_mpmd_executor_matches_autodiff(schedule):
    require_devices(4)
    """Loss + every grad (stage, head, dmicros) == plain autodiff of the
    stacked stages — the executor's correctness oracle, shard_map-free."""
    pp, n_micro = 4, 6
    sp, head, micros, labels, stage_fn, loss_fn = _toy_problem(pp, n_micro)

    def ref_loss(sp, hp, mi):
        def one(m, lab):
            x = m
            for s in range(pp):
                x = stage_fn(jax.tree.map(lambda a: a[s], sp), x, {}, s)
            return loss_fn(hp, x, lab, ())
        return jnp.mean(jax.vmap(one)(mi, labels))

    ref_l, (rgs, rgh, rgm) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2))(sp, head, micros)
    loss, _aux, gs, gh, gm = mpmd_value_and_grad(
        stage_fn, loss_fn, sp, head, micros, labels,
        pp=pp, devices=jax.devices()[:pp], schedule=schedule)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-6)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(gs[k]), np.asarray(rgs[k]),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gh["v"]), np.asarray(rgh["v"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gm), np.asarray(rgm), rtol=1e-5,
                               atol=1e-6)


@pytest.mark.skipif(not HAS_SHARD_MAP,
                    reason="SPMD 1F1B executor needs jax.shard_map "
                           "(pre-existing 0.4.x gap; the MPMD side of this "
                           "parity is still covered vs autodiff)")
def test_mpmd_executor_matches_spmd_executor():
    require_devices(4)
    """Both placements of the SAME schedule tables produce the same loss
    and grads — the schedule/placement split's acceptance oracle."""
    from jax.sharding import Mesh
    from deepspeed_tpu.runtime.pipe.one_f_one_b import \
        pipeline_1f1b_value_and_grad
    pp, n_micro = 4, 6
    sp, head, micros, labels, stage_fn, loss_fn = _toy_problem(pp, n_micro)
    mesh = Mesh(np.asarray(jax.devices()[:pp]).reshape(pp), ("pipe",))
    l_s, _a, gs_s, gh_s, gm_s = jax.jit(
        lambda a, b, c, d: pipeline_1f1b_value_and_grad(
            stage_fn, lambda h, y, lab: loss_fn(h, y, lab, ()),
            a, b, c, d, mesh=mesh, pp=pp))(sp, head, micros, labels)
    l_m, _a2, gs_m, gh_m, gm_m = mpmd_value_and_grad(
        stage_fn, loss_fn, sp, head, micros, labels,
        pp=pp, devices=jax.devices()[:pp], schedule="1f1b")
    np.testing.assert_allclose(float(l_m), float(l_s), rtol=1e-6)
    for a, b in zip(jax.tree.leaves((gs_m, gh_m, gm_m)),
                    jax.tree.leaves((gs_s, gh_s, gm_s))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


def test_mpmd_executor_xfer_failpoint_surfaces():
    require_devices(4)
    """An armed pipe.xfer fault in the in-process channel surfaces to the
    caller as the IOError it is — no silent wrong answer."""
    pp, n_micro = 2, 4
    sp, head, micros, labels, stage_fn, loss_fn = _toy_problem(pp, n_micro)
    chaos.arm("pipe.xfer", "raise")
    with pytest.raises(IOError):
        mpmd_value_and_grad(stage_fn, loss_fn,
                            jax.tree.map(lambda x: x[:pp], sp), head,
                            micros, labels, pp=pp,
                            devices=jax.devices()[:pp])


# -- model + engine integration -----------------------------------------------

def _tiny_kw(**over):
    kw = dict(hidden_size=64, num_layers=4, num_heads=4, vocab_size=256,
              max_seq_len=64, dtype=jnp.float32, attention_impl="reference")
    kw.update(over)
    return kw


def _mk_batch(rng, vocab, b, s):
    return {"input_ids": rng.integers(0, vocab, size=(b, s))}


def _mpmd_engine(piped, schedule="1f1b", loss_fn=causal_lm_loss,
                 extra_cfg=None, batch=None):
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
        "zero_optimization": {"stage": 0},
        "pipeline": {"stages": piped.pp, "schedule": schedule,
                     "placement": "mpmd"},
        "seed": 11,
    }
    if extra_cfg:
        config.update(extra_cfg)
    if batch is None:
        batch = _mk_batch(np.random.default_rng(2), 256, 16, 32)
    engine, *_ = ds.initialize(model=piped, config=config, loss_fn=loss_fn,
                               example_batch=batch,
                               rng=jax.random.PRNGKey(7))
    return engine


# tier-2 (round-19 budget sweep, ~11s): the cheaper tier-1 cousins are
# test_mpmd_executor_matches_autodiff (stage-graph value+grad parity,
# both schedules) and test_mpmd_engine_loss_parity_vs_spmd_pipeline_engine
# (model-level loss parity through the engine); scripts/tier2.sh runs this
@pytest.mark.slow
def test_mpmd_model_matches_plain_autodiff():
    require_devices(2)
    """pp=2 transformer through the MPMD placement: loss and every grad
    match plain autodiff of the same params through the non-pipelined
    model (identical param structure by construction)."""
    kw = _tiny_kw()
    plain, _ = build_model("gpt2-tiny", scan_layers=True, **kw)
    piped, cfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=4, **kw)
    engine = _mpmd_engine(piped)
    batch = {k: jnp.asarray(v) for k, v in _mk_batch(
        np.random.default_rng(5), 256, 16, 32).items()}
    params = engine.state.params
    l1, g1 = piped.mpmd_value_and_grad(params, batch, mesh=engine.mesh)
    l2, g2 = jax.jit(jax.value_and_grad(lambda p: causal_lm_loss(
        plain.apply({"params": p}, batch), batch)))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-4)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g1)[0],
            jax.tree_util.tree_flatten_with_path(g2)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4, err_msg=str(pa))


@pytest.mark.slow
def test_mpmd_engine_trains_and_8step_losses_match_plain_engine():
    require_devices(2)
    """Engine-level acceptance on shard_map-less hosts: 8 training steps
    under placement='mpmd' descend and track a NON-pipelined engine fed
    identical batches (same init, same gas) step for step.

    slow (round-14 budget sweep, 25s): the cheaper tier-1 cousins are
    test_mpmd_engine_loss_parity_vs_spmd_pipeline_engine (single-step
    loss parity) and test_two_process_mpmd_two_stage_run (engine e2e)."""
    kw = _tiny_kw()
    piped, cfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=4, **kw)
    engine = _mpmd_engine(piped)
    plain, _ = build_model("gpt2-tiny", scan_layers=True, **kw)
    pconfig = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
        "zero_optimization": {"stage": 0},
        "seed": 11,
    }
    peng, *_ = ds.initialize(model=plain, config=pconfig,
                             loss_fn=causal_lm_loss,
                             example_batch=_mk_batch(
                                 np.random.default_rng(2), 256, 16, 32),
                             rng=jax.random.PRNGKey(7))
    mp_losses, pl_losses = [], []
    for i in range(8):
        b = _mk_batch(np.random.default_rng(60 + i), 256, 16, 32)
        mp_losses.append(float(engine.train_batch(b)["loss"]))
        pl_losses.append(float(peng.train_batch(b)["loss"]))
    assert mp_losses[-1] < mp_losses[0], mp_losses
    for i, (a, b) in enumerate(zip(mp_losses, pl_losses)):
        assert abs(a - b) < 2e-3, (i, a, b, mp_losses, pl_losses)


@pytest.mark.skipif(not HAS_SHARD_MAP,
                    reason="SPMD pipeline engine needs jax.shard_map "
                           "(pre-existing 0.4.x gap)")
def test_mpmd_engine_loss_parity_vs_spmd_pipeline_engine():
    require_devices(2)
    """The acceptance leg verbatim: MPMD vs SPMD pipeline engines on the
    SAME 1f1b schedule, identical batches, >= 8 steps — per-step losses
    agree."""
    kw = _tiny_kw()

    def make(placement):
        piped, _ = build_pipelined_model("gpt2-tiny", pp=2, n_micro=4, **kw)
        config = {
            "train_batch_size": 16,
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
            "zero_optimization": {"stage": 0},
            "pipeline": {"stages": 2, "schedule": "1f1b",
                         "placement": placement},
            "seed": 11,
        }
        engine, *_ = ds.initialize(
            model=piped, config=config, loss_fn=causal_lm_loss,
            example_batch=_mk_batch(np.random.default_rng(2), 256, 16, 32),
            rng=jax.random.PRNGKey(7))
        return engine

    e_s, e_m = make("spmd"), make("mpmd")
    for i in range(8):
        b = _mk_batch(np.random.default_rng(70 + i), 256, 16, 32)
        ls = float(e_s.train_batch(b)["loss"])
        lm = float(e_m.train_batch(b)["loss"])
        assert abs(ls - lm) < 2e-4, (i, ls, lm)


@pytest.mark.slow
def test_mpmd_model_remat_matches_plain_autodiff():
    require_devices(2)
    """remat=True models run the MPMD placement unchanged (the fused
    per-stage backward IS the recompute regime) — values still match
    plain autodiff.

    slow (round-14 budget sweep, 13s): the cheaper tier-1 cousin is
    test_mpmd_executor_matches_autodiff (same parity regime, remat
    off, stage-graph level)."""
    kw = _tiny_kw(remat=True)
    plain, _ = build_model("gpt2-tiny", scan_layers=True, **kw)
    piped, cfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=4, **kw)
    engine = _mpmd_engine(piped)
    batch = {k: jnp.asarray(v) for k, v in _mk_batch(
        np.random.default_rng(5), 256, 16, 32).items()}
    params = engine.state.params
    l1, g1 = piped.mpmd_value_and_grad(params, batch, mesh=engine.mesh)
    l2, g2 = jax.jit(jax.value_and_grad(lambda p: causal_lm_loss(
        plain.apply({"params": p}, batch), batch)))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-4)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g1)[0],
            jax.tree_util.tree_flatten_with_path(g2)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4, err_msg=str(pa))


@pytest.mark.slow
def test_mpmd_model_moe_aux_matches_plain_autodiff():
    # tier-2 (budget guardrail, ~22s): the dense-model parity twin
    # (test_mpmd_model_matches_plain_autodiff) and the executor aux
    # machinery stay tier-1; scripts/tier2.sh runs this variant
    require_devices(2)
    """with_aux through the MPMD placement: the MoE load-balance scalar
    rides the per-stage programs via its constant cotangent — loss AND
    grads match autodiff of the plain model under make_moe_loss. The
    oracle averages PER-MICRO losses (the pipeline's semantics — the
    load-balance term is nonlinear in batch composition, so a full-batch
    aux would legitimately differ)."""
    from deepspeed_tpu.models import make_moe_loss
    kw = _tiny_kw(moe_experts=2, moe_capacity_factor=2.0)
    plain, _ = build_model("gpt2-tiny", scan_layers=True, **kw)
    piped, cfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=4, **kw)
    moe_loss = make_moe_loss(cfg.moe_aux_weight)
    engine = _mpmd_engine(piped, loss_fn=moe_loss)
    batch = {k: jnp.asarray(v) for k, v in _mk_batch(
        np.random.default_rng(5), 256, 16, 32).items()}
    params = engine.state.params
    l1, g1 = piped.mpmd_value_and_grad(params, batch, mesh=engine.mesh)

    def ref(p):
        losses = []
        for m in range(4):
            mb = {k: v.reshape((4, 4) + v.shape[1:])[m]
                  for k, v in batch.items()}
            losses.append(moe_loss(plain.apply({"params": p}, mb), mb))
        return sum(losses) / 4

    l2, g2 = jax.jit(jax.value_and_grad(ref))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-4)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g1)[0],
            jax.tree_util.tree_flatten_with_path(g2)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-3,
                                   atol=3e-4, err_msg=str(pa))


@pytest.mark.slow
def test_mpmd_fp16_loss_scaling_through_engine():
    # tier-2 (budget guardrail, ~14s): the f32 engine path
    # (test_mpmd_engine_trains_and_8step_losses_match_plain_engine)
    # keeps gating tier-1
    require_devices(2)
    """fp16 + MPMD: the dynamic scale seeds every per-stage backward as a
    traced argument (no per-step recompile), grads unscale in the shared
    finalize tail, training stays finite."""
    piped, cfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=4,
                                       **_tiny_kw(dtype=jnp.float16))
    engine = _mpmd_engine(
        piped, extra_cfg={"fp16": {"enabled": True,
                                   "initial_scale_power": 8,
                                   "hysteresis": 1}})
    losses = []
    for i in range(4):
        b = _mk_batch(np.random.default_rng(20 + i), 256, 16, 32)
        losses.append(float(engine.train_batch(b)["loss"]))
    assert np.all(np.isfinite(losses)), losses


def test_mpmd_store_backward_refused():
    require_devices(2)
    piped, cfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=4,
                                       backward="store", **_tiny_kw())
    engine = _mpmd_engine(piped)
    with pytest.raises(ValueError, match="recompute"):
        engine.train_batch(_mk_batch(np.random.default_rng(1), 256, 16, 32))


def test_unknown_placement_rejected():
    require_devices(2)
    piped, cfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=4,
                                       **_tiny_kw())
    with pytest.raises(ValueError, match="placement"):
        _mpmd_engine(piped, extra_cfg={
            "pipeline": {"stages": 2, "placement": "hybrid"}})


# -- cross-process: driver + stage workers ------------------------------------

def _collect_losses(log_path):
    losses = {}
    with open(log_path) as f:
        for m in re.finditer(r'mpmd_step: ({.*})', f.read()):
            d = json.loads(m.group(1))
            losses[d["step"]] = d["loss"]
    return losses


def _run_driver(workdir, steps=6, specs=None, **kw):
    sup = MPMDStageSupervisor(2, workdir=os.path.join(workdir, "wd"),
                              steps=steps, n_micro=4, schedule="1f1b",
                              log_dir=os.path.join(workdir, "logs"),
                              specs=specs, **kw)
    rc = sup.run()
    losses = _collect_losses(os.path.join(workdir, "logs", "stage1.log"))
    return rc, losses, sup


def test_two_process_mpmd_two_stage_run(tmp_path):
    """The cross-process reference path: two stage WORKER processes over
    the socket channel, per-stage checkpoints, rc 0, one loss per step.
    (This is the pipeline-over-processes coverage that still runs on the
    0.4.x host where the SPMD 2-proc TP+PP leg cannot — see
    test_multiprocess.py's xfail.)"""
    rc, losses, sup = _run_driver(str(tmp_path), steps=4)
    assert rc == 0 and sup.restarts == [0, 0]
    assert set(losses) == set(range(4))
    # per-stage durable tags exist for every step (save_interval=1)
    for s in (0, 1):
        tags = os.listdir(os.path.join(str(tmp_path), "wd", f"stage{s}"))
        assert "global_step4" in tags


@pytest.mark.slow
def test_stage_kill_recovers_one_stage_with_loss_parity(tmp_path):
    """Acceptance: pipe.stage_kill takes out stage 1 at step 3; the
    driver restarts ONLY that stage (stage 0's process survives), the
    run completes rc 0, and the loss trajectory is IDENTICAL to an
    uninjected twin — no microbatch applied twice, none lost."""
    rc0, clean, sup0 = _run_driver(str(tmp_path / "clean"), steps=8)
    assert rc0 == 0
    specs = [StageWorkerSpec(),
             StageWorkerSpec(env_first={
                 "DSTPU_CHAOS": "pipe.stage_kill:kill:skip=3"})]
    rc1, injected, sup = _run_driver(str(tmp_path / "chaos"), steps=8,
                                     specs=specs)
    assert rc1 == 0
    assert sup.restarts == [0, 1], sup.restarts      # ONLY stage 1
    assert set(injected) == set(range(8))
    for k in clean:
        assert abs(clean[k] - injected[k]) < 1e-9, (k, clean, injected)


@pytest.mark.slow
def test_xfer_fault_recovers_with_loss_parity(tmp_path):
    """A transfer fault (pipe.xfer raise on stage 0's send) is a counted
    crash: one-stage restart, full-run loss parity with the clean twin."""
    rc0, clean, _ = _run_driver(str(tmp_path / "clean"), steps=8)
    specs = [StageWorkerSpec(env_first={
                 "DSTPU_CHAOS": "pipe.xfer:raise:skip=5"}),
             StageWorkerSpec()]
    rc1, injected, sup = _run_driver(str(tmp_path / "chaos"), steps=8,
                                     specs=specs)
    assert rc0 == 0 and rc1 == 0
    assert sup.restarts == [1, 0]
    for k in clean:
        assert abs(clean[k] - injected[k]) < 1e-9, (k, clean, injected)


@pytest.mark.slow
def test_stage_hang_watchdog_117_then_recovery(tmp_path):
    """A WEDGED stage (pipe.stage_kill:hang) is caught by the in-worker
    StallWatchdog (rc 117, STALLED heartbeat), counted, restarted — the
    run still completes with clean-twin loss parity. The rc 117 leg of
    the contract, end to end."""
    rc0, clean, _ = _run_driver(str(tmp_path / "clean"), steps=8)
    hbdir = str(tmp_path / "hb")
    specs = [StageWorkerSpec(),
             StageWorkerSpec(env_first={
                 "DSTPU_CHAOS": "pipe.stage_kill:hang:skip=3"})]
    rc1, injected, sup = _run_driver(
        str(tmp_path / "chaos"), steps=8, specs=specs,
        heartbeat_dir=hbdir, worker_args=["--stall-timeout", "3"])
    assert rc0 == 0 and rc1 == 0
    assert sup.restarts == [0, 1]
    for k in clean:
        assert abs(clean[k] - injected[k]) < 1e-9, (k, clean, injected)
    # the heartbeat channel carries STAGE-tagged records (dstpu health's
    # STAGE column reads exactly this gauge)
    from deepspeed_tpu.runtime import heartbeat as hb
    recs = hb.read_heartbeats(hbdir)
    assert recs and all(r.get("gauges", {}).get("stage") == r["rank"]
                        for r in recs.values())


@pytest.mark.slow
def test_restart_budget_exhausted_propagates_rc(tmp_path):
    """max_restarts=0: the first counted death tears the world down and
    the chaos kill's exit code survives aggregation (the rc contract is
    preserved upward, like RunSupervisor's)."""
    specs = [StageWorkerSpec(),
             StageWorkerSpec(env={  # re-arms every restart: always fatal
                 "DSTPU_CHAOS": "pipe.stage_kill:kill:skip=1"})]
    rc, _losses, sup = _run_driver(str(tmp_path), steps=6, specs=specs,
                                   max_restarts=0)
    assert rc == chaos.KILL_EXIT_CODE


def test_stageconn_send_raises_when_write_lock_starved():
    """Regression (TPU017 sweep): a peer wedged mid-read used to keep
    the per-connection write lock — and every later sender (welcome,
    broadcast) — stuck forever. A starved writer now fails like a dead
    peer, which every caller already handles."""
    import socket
    import time
    from deepspeed_tpu.runtime.pipe.mpmd.driver import _StageConn

    a, b = socket.socketpair()
    try:
        conn = _StageConn(a, 0)
        conn.wlock.acquire()            # the wedged sender
        try:
            t0 = time.monotonic()
            with pytest.raises(OSError, match="starved"):
                conn.send({"cmd": "ping"}, lock_timeout=0.05)
            assert time.monotonic() - t0 < 2
        finally:
            conn.wlock.release()
        conn.send({"cmd": "ping"}, lock_timeout=0.05)   # lock free: sends
    finally:
        a.close()
        b.close()


def test_socket_channel_send_raises_when_write_lock_starved():
    """Same contract on the worker side: the frame lock — now owned by
    the fabric endpoint the channel rides (round 18) — is bounded, and
    starvation surfaces as the OSError a dead driver socket raises."""
    import socket
    import threading as _th
    from collections import deque

    from deepspeed_tpu.runtime.fabric import SocketEndpoint
    from deepspeed_tpu.runtime.pipe.mpmd.channel import SocketChannel

    a, b = socket.socketpair()
    try:
        ep = SocketEndpoint.__new__(SocketEndpoint)
        ep.ident = "stage-0"
        ep._sock = a
        ep._wlock = _th.Lock()
        ep._redial = None
        ep._closed = False
        ep.generation = 0
        ch = SocketChannel.__new__(SocketChannel)
        ch.stage = 0
        ch._ep = ep
        ch._data = {}
        ch._control = deque()
        ep._wlock.acquire()
        try:
            with pytest.raises(OSError, match="starved"):
                ch.send_control({"cmd": "parked"}, lock_timeout=0.05)
            with pytest.raises(OSError, match="starved"):
                ch.send("act", 0, 1, 0, np.zeros(2, np.float32),
                        lock_timeout=0.05)
        finally:
            ep._wlock.release()
        ch.send_control({"cmd": "parked"}, lock_timeout=0.05)
    finally:
        a.close()
        b.close()
