"""Data efficiency tests: curriculum schedules, difficulty sampler, mmap
indexed dataset, seqlen curriculum through the engine, random-LTD.

Mirrors the reference's tests/unit/test_curriculum_learning.py + indexed
dataset round trips.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler,
                                                 DeepSpeedDataSampler,
                                                 MMapIndexedDataset,
                                                 MMapIndexedDatasetBuilder,
                                                 apply_seqlen_curriculum)

from util import SimpleModel, random_batch


def test_fixed_linear_schedule():
    s = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 64,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100,
                            "difficulty_step": 8}})
    assert s.get_difficulty(0) == 8
    assert s.get_difficulty(50) == 32    # 8 + 56*0.5 = 36 -> floor to 32
    assert s.get_difficulty(100) == 64
    assert s.get_difficulty(1000) == 64


def test_fixed_root_and_discrete():
    s = CurriculumScheduler({
        "min_difficulty": 2, "max_difficulty": 100,
        "schedule_type": "fixed_root",
        "schedule_config": {"total_curriculum_step": 100, "root_degree": 2,
                            "difficulty_step": 2}})
    # sqrt ramp: faster early
    assert s.get_difficulty(25) >= 2 + (100 - 2) * 0.45
    d = CurriculumScheduler({
        "min_difficulty": 1, "max_difficulty": 3,
        "schedule_type": "fixed_discrete",
        "schedule_config": {"difficulty": [1, 2, 3],
                            "max_step": [10, 20, 30]}})
    assert d.get_difficulty(5) == 1
    assert d.get_difficulty(15) == 2
    assert d.get_difficulty(999) == 3


def test_update_difficulty_monotone():
    s = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 32,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 10,
                            "difficulty_step": 8}})
    seen = [s.update_difficulty(i) for i in range(15)]
    assert seen[0] == 8 and seen[-1] == 32
    assert all(a <= b for a, b in zip(seen, seen[1:]))


def test_data_sampler_difficulty_gate():
    diffs = np.arange(100)                  # example i has difficulty i
    sampler = DeepSpeedDataSampler(
        diffs, batch_size=8,
        curriculum_config={"min_difficulty": 10, "max_difficulty": 100,
                           "schedule_type": "fixed_linear",
                           "schedule_config": {"total_curriculum_step": 50,
                                               "difficulty_step": 10}})
    it = iter(sampler)
    first = next(it)
    assert first.max() <= 10                # only easy examples early
    sampler.set_step(100)
    late = next(it)
    assert late.max() > 50                  # pool fully open


def test_indexed_dataset_roundtrip(tmp_path):
    prefix = str(tmp_path / "tokens")
    builder = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    docs = [np.arange(n, dtype=np.int32) * 2 for n in (5, 17, 3, 128)]
    for d in docs:
        builder.add_item(d)
    builder.finalize()
    dset = MMapIndexedDataset(prefix)
    assert len(dset) == 4
    assert list(dset.sizes) == [5, 17, 3, 128]
    for i, d in enumerate(docs):
        np.testing.assert_array_equal(dset[i], d)
    np.testing.assert_array_equal(dset.get(3, offset=10, length=5),
                                  docs[3][10:15])


def test_apply_seqlen_curriculum_truncates():
    batch = {"input_ids": np.zeros((4, 64), np.int32),
             "labels": np.zeros((4, 64), np.int32),
             "scalar": np.zeros((4,), np.float32)}
    out = apply_seqlen_curriculum(batch, 16)
    assert out["input_ids"].shape == (4, 16)
    assert out["scalar"].shape == (4,)


@pytest.mark.slow
def test_engine_seqlen_curriculum_ramps(tmp_path):
    """Training with a seqlen curriculum: the compiled step consumes ramping
    sequence lengths and the loss improves (reference 'Done' criterion)."""
    from deepspeed_tpu.models import build_model, causal_lm_loss
    model, cfg = build_model("gpt2-tiny", max_seq_len=64,
                             attention_impl="reference")
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "curriculum_learning": {
            "enabled": True, "curriculum_type": "seqlen",
            "min_difficulty": 8, "max_difficulty": 32,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 6,
                                "difficulty_step": 8}},
    }
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 32))
    engine, *_ = ds.initialize(model=model, config=config,
                               loss_fn=causal_lm_loss,
                               example_batch={"input_ids": ids})
    assert engine.curriculum is not None
    losses, seqlens = [], []
    for i in range(14):
        b = {"input_ids": np.random.default_rng(i % 4).integers(
            0, cfg.vocab_size, (8, 32))}
        losses.append(float(engine.train_batch(b)["loss"]))
        seqlens.append(engine.curriculum.scheduler.current_difficulty)
    assert seqlens[0] == 8 and seqlens[-1] == 32
    # loss is only comparable at EQUAL difficulty: compare the first full-
    # seqlen step against the tail of training at the same seqlen
    full = [l for l, s in zip(losses, seqlens) if s == 32]
    assert np.mean(full[-3:]) < full[0]


@pytest.mark.slow
def test_random_ltd_model_trains():
    """Middle layers process a random token subset; grads stay finite and
    training proceeds (reference: data_routing/random_ltd)."""
    from deepspeed_tpu.models import build_model, causal_lm_loss
    model, cfg = build_model("gpt2-tiny", num_layers=4, scan_layers=False,
                             ltd_tokens=16, ltd_start=1, ltd_end=3,
                             max_seq_len=64, attention_impl="reference")
    ids = np.random.default_rng(1).integers(0, cfg.vocab_size, (4, 32))
    batch = {"input_ids": jnp.asarray(ids)}
    params = model.init({"params": jax.random.PRNGKey(0),
                         "gating": jax.random.PRNGKey(1)},
                        batch, train=True)["params"]

    def loss_fn(p, rng):
        logits = model.apply({"params": p}, batch, train=True,
                             rngs={"gating": rng})
        return causal_lm_loss(logits, batch)

    l0, g = jax.value_and_grad(loss_fn)(params, jax.random.PRNGKey(2))
    assert np.isfinite(float(l0))
    gnorm = sum(float(jnp.sum(jnp.abs(x))) for x in jax.tree.leaves(g))
    assert np.isfinite(gnorm) and gnorm > 0
    # eval path ignores LTD (full sequence, no sampling rng needed)
    logits_eval = model.apply({"params": params}, batch)
    assert logits_eval.shape == (4, 32, cfg.vocab_size)


def test_native_batch_assembler(tmp_path):
    """C++ gather/prefetch matches the numpy fallback bit-for-bit, including
    truncation, padding, and repeated double-buffered prefetch."""
    from deepspeed_tpu.runtime.data_pipeline.native_loader import (
        NativeBatchAssembler)
    prefix = str(tmp_path / "tok")
    builder = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    rng = np.random.default_rng(0)
    docs = [rng.integers(0, 50000, size=n).astype(np.int32)
            for n in (3, 40, 16, 64, 1, 31)]
    for d in docs:
        builder.add_item(d)
    builder.finalize()
    dset = MMapIndexedDataset(prefix)

    nat = NativeBatchAssembler(dset, seq_len=16, pad_token=-1)
    assert nat.has_native, ("C++ data_loader failed to build — the native "
                            "path would be silently untested")
    ref = NativeBatchAssembler(dset, seq_len=16, pad_token=-1,
                               use_native=False)
    ids = [0, 3, 5, 1, 4]
    np.testing.assert_array_equal(nat.gather(ids), ref.gather(ids))
    # explicit shape/pad/truncate checks against the docs themselves
    out = nat.gather([0, 3])
    assert out.shape == (2, 16)
    np.testing.assert_array_equal(out[0, :3], docs[0])
    assert (out[0, 3:] == -1).all()                  # padded
    np.testing.assert_array_equal(out[1], docs[3][:16])   # truncated

    # double-buffered prefetch: several rounds, results identical to gather
    batches = [[1, 2], [5, 0, 3], [4]]
    nat.prefetch(batches[0])
    got = []
    for nxt in batches[1:]:
        got.append(nat.wait())
        nat.prefetch(nxt)
    got.append(nat.wait())
    for ids_b, arr in zip(batches, got):
        np.testing.assert_array_equal(arr, ref.gather(ids_b))
    # one-outstanding-prefetch contract
    nat.prefetch([0])
    with pytest.raises(RuntimeError, match="in flight"):
        nat.prefetch([1])
    nat.wait()
    nat.close()
