"""Quantizer ops, compressed collectives, 1-bit Adam."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.quantizer import (dequantize_asymmetric,
                                         dequantize_symmetric, fake_quantize,
                                         onebit_compress, onebit_decompress,
                                         quantize_asymmetric,
                                         quantize_symmetric)
from deepspeed_tpu.runtime.comm import (compressed_allreduce,
                                        quantized_allreduce)


def test_symmetric_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 256)), jnp.float32)
    q, s = quantize_symmetric(x, bits=8, groups=4)
    assert q.dtype == jnp.int8
    y = dequantize_symmetric(q, s, groups=4)
    # max error is half a quantization step per group
    step = np.asarray(s)[:, None]
    err = np.abs(np.asarray(x) - np.asarray(y)).reshape(4, -1)
    assert (err <= step / 2 + 1e-6).all()


def test_asymmetric_roundtrip():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.uniform(3.0, 9.0, (2, 128)), jnp.float32)
    q, s, zp = quantize_asymmetric(x, bits=8, groups=2)
    y = dequantize_asymmetric(q, s, zp, groups=2)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=0.05)


def test_fake_quantize_straight_through_grad():
    x = jnp.linspace(-1, 1, 64)
    g = jax.grad(lambda x: jnp.sum(fake_quantize(x, bits=4, groups=1)))(x)
    np.testing.assert_allclose(np.asarray(g), 1.0)


def test_stochastic_rounding_unbiased():
    x = jnp.full((1, 1024), 0.3, jnp.float32)
    qs = []
    for i in range(64):
        q, s = quantize_symmetric(x, bits=4, groups=1, stochastic=True,
                                  rng=jax.random.PRNGKey(i))
        qs.append(np.asarray(dequantize_symmetric(q, s, 1)).mean())
    # stochastic rounding is unbiased in expectation
    assert abs(np.mean(qs) - 0.3) < 0.02


def test_onebit_compress():
    x = jnp.asarray([1.0, -2.0, 3.0, -4.0])
    signs, scale = onebit_compress(x)
    assert float(scale) == pytest.approx(2.5)
    y = onebit_decompress(signs, scale)
    np.testing.assert_allclose(np.asarray(y), [2.5, -2.5, 2.5, -2.5])


# -- compressed collectives ---------------------------------------------------

@pytest.fixture(scope="module")
def data_mesh():
    from deepspeed_tpu.parallel.mesh import MeshManager
    return MeshManager()   # data axis = 8


def test_quantized_allreduce_close_to_mean(data_mesh):
    n = 8
    rng = np.random.default_rng(2)
    xs = jnp.asarray(rng.standard_normal((n, 512)), jnp.float32)
    mesh = data_mesh.mesh
    x_sh = jax.device_put(xs, NamedSharding(mesh, P("data")))
    err = jax.device_put(jnp.zeros((n, 512)), NamedSharding(mesh, P("data")))
    out, new_err = quantized_allreduce(x_sh, err, mesh=mesh, axis="data")
    exact = np.mean(np.asarray(xs), axis=0)
    np.testing.assert_allclose(np.asarray(out), exact, atol=0.05)


# tier-2 (round-19 budget sweep, ~6s): the cheaper tier-1 cousins are
# test_quantized_allreduce_close_to_mean (single-shot EF bound) and
# the sign/scale roundtrip units above; scripts/tier2.sh runs this
# multi-iteration convergence leg
@pytest.mark.slow
def test_compressed_allreduce_error_feedback_converges(data_mesh):
    """Repeated 1-bit allreduce of the same vector: error feedback makes the
    RUNNING AVERAGE of outputs converge to the true mean (EF property)."""
    n = 8
    rng = np.random.default_rng(3)
    xs = jnp.asarray(rng.standard_normal((n, 256)), jnp.float32)
    mesh = data_mesh.mesh
    sh = NamedSharding(mesh, P("data"))
    x_sh = jax.device_put(xs, sh)
    w_err = jax.device_put(jnp.zeros((n, 256)), sh)
    s_err = jax.device_put(jnp.zeros((n, 256 // n)), sh)
    exact = np.mean(np.asarray(xs), axis=0)
    outs = []
    for _ in range(24):
        out, w_err, s_err = compressed_allreduce(x_sh, w_err, s_err,
                                                 mesh=mesh, axis="data")
        outs.append(np.asarray(out))
    early = np.linalg.norm(np.mean(outs[:4], axis=0) - exact)
    late = np.linalg.norm(np.mean(outs, axis=0) - exact)
    assert late < early, (early, late)


# -- 1-bit adam ---------------------------------------------------------------

def test_onebit_adam_converges_quadratic():
    """Long warmup (v well-estimated before freeze, the algorithm's intended
    regime — reference docs recommend freeze at ~15-25% of total steps)."""
    from deepspeed_tpu.ops.optimizers import build_optimizer
    opt = build_optimizer("OneBitAdam", {"lr": 0.02, "freeze_step": 80})
    target = jnp.asarray(np.random.default_rng(4).standard_normal(16))
    params = {"w": jnp.zeros(16)}
    state = opt.init(params)
    loss_fn = lambda p: jnp.sum((p["w"] - target) ** 2)

    @jax.jit
    def step(params, state, t):
        g = jax.grad(loss_fn)(params)
        return opt.update(g, state, params, t)

    loss0 = float(loss_fn(params))
    for t in range(400):
        params, state = step(params, state, jnp.asarray(t))
    assert float(loss_fn(params)) < 0.01 * loss0
    # compression stage actually engaged (error feedback non-zero)
    assert float(jnp.max(jnp.abs(state["comp_err"]["w"]))) > 0.0
