"""MoE: gating math, expert-parallel layer, transformer integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from util import require_devices
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model, make_moe_loss
from deepspeed_tpu.moe import (MoE, compute_capacity, expert_parallel_apply,
                               top1_gating, top2_gating)


# -- gating -------------------------------------------------------------------

def test_top1_gating_capacity_and_dispatch():
    rng = np.random.default_rng(0)
    T, E = 64, 4
    logits = jnp.asarray(rng.standard_normal((T, E)), jnp.float32)
    C = compute_capacity(T, E, 1.0, 1)
    aux, combine, dispatch, counts = top1_gating(logits, capacity=C)
    # every slot used at most once; no expert over capacity
    assert dispatch.shape == (T, E, C)
    assert float(jnp.max(jnp.sum(dispatch, axis=(0,)))) <= 1.0 + 1e-6
    assert float(jnp.max(counts)) <= C
    # kept tokens carry their full gate weight; combine is 0 for dropped
    per_token = jnp.sum(combine, axis=(1, 2))
    assert float(jnp.max(per_token)) <= 1.0 + 1e-5
    assert float(aux) > 0.0


def test_top2_gating_two_experts_per_token():
    rng = np.random.default_rng(1)
    T, E = 32, 4
    logits = jnp.asarray(rng.standard_normal((T, E)), jnp.float32)
    C = compute_capacity(T, E, 2.0, 2)
    aux, combine, dispatch, counts = top2_gating(logits, capacity=C)
    sent = jnp.sum(dispatch, axis=(1, 2))      # experts per token
    assert float(jnp.max(sent)) <= 2.0
    # with generous capacity almost all tokens keep 2 experts
    assert float(jnp.mean(sent)) > 1.5
    # combine weights renormalized to ~1 for fully-kept tokens
    w = jnp.sum(combine, axis=(1, 2))
    kept2 = sent == 2
    np.testing.assert_allclose(np.asarray(w[kept2]), 1.0, atol=1e-5)


def test_gating_gradients_flow():
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)

    def loss(l):
        aux, combine, dispatch, _ = top1_gating(l, capacity=8)
        return jnp.sum(combine ** 2) + 0.01 * aux

    g = jax.grad(loss)(logits)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.max(jnp.abs(g))) > 0.0


# -- layer --------------------------------------------------------------------

def test_moe_layer_forward_and_params():
    m = MoE(hidden_size=32, num_experts=4, dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 8, 32)),
                    jnp.float32)
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    # expert stacks are [E, ...]
    assert params["experts"]["fc"]["kernel"].shape == (4, 32, 128)
    y, aux = m.apply({"params": params}, x)
    assert y.shape == x.shape
    assert np.isfinite(float(aux))


def test_expert_parallel_apply_matches_local():
    require_devices(2)
    """Explicit a2a path == plain vmap over experts (numerical oracle)."""
    from deepspeed_tpu.parallel.mesh import MeshManager
    mm = MeshManager(ep_size=4)   # expert axis = 4, data = 2
    E, ep, C, H = 8, 4, 4, 16
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.standard_normal((E, H, H)), jnp.float32)
    disp = jnp.asarray(rng.standard_normal((E, ep * C, H)), jnp.float32)

    apply_one = lambda wk, x: jnp.tanh(x @ wk)
    local = jax.vmap(apply_one)(w, disp)

    w_sh = jax.device_put(w, NamedSharding(mm.mesh, P("expert")))
    disp_sh = jax.device_put(disp, NamedSharding(mm.mesh, P(None, "expert")))
    out = expert_parallel_apply(apply_one, w_sh, disp_sh, mesh=mm.mesh, ep=ep)
    np.testing.assert_allclose(np.asarray(out), np.asarray(local),
                               rtol=1e-5, atol=1e-5)


# -- transformer integration --------------------------------------------------

# tier-2 (round-19 budget sweep, ~10s): the cheaper tier-1 cousins are
# the gating units above, test_moe_layer_forward_and_params (layer
# math) and test_moe_param_accounting; scripts/tier2.sh runs this
# multi-step training leg
@pytest.mark.slow
def test_moe_transformer_trains():
    require_devices(2)
    model, cfg = build_model("gpt2-tiny", hidden_size=64, num_layers=2,
                             num_heads=4, vocab_size=256, max_seq_len=64,
                             moe_experts=4, moe_capacity_factor=2.0,
                             attention_impl="reference")
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "moe": {"enabled": True, "ep_size": 4},
    }
    rng = np.random.default_rng(5)
    mk = lambda: {"input_ids": rng.integers(0, 256, size=(16, 32))}
    engine, *_ = ds.initialize(model=model, config=config,
                               loss_fn=make_moe_loss(cfg.moe_aux_weight),
                               example_batch=mk(),
                               sharding_rules=cfg.tp_rules())
    # expert stacks sharded over the expert axis
    qshape = engine.state.params["blocks"]["moe"]["experts"]["fc"]["kernel"]
    assert qshape.shape[1] == 4
    losses = [float(engine.train_batch(mk())["loss"]) for _ in range(8)]
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_moe_with_tp_composes():
    """MoE under tensor parallelism: in SPMD, TP-replicated tokens gate
    identically on every model-rank (same logits, same rng), so there are no
    duplicate-token semantics to fix up — the role of the reference's
    moe/mappings.py:27-108 (gather/drop of TP-duplicated tokens) dissolves
    into sharding propagation.  Proof: a tp=2 x ep=2 run tracks the tp=1 x
    ep=2 run loss for loss."""
    require_devices(4)

    def make(tp):
        model, cfg = build_model("gpt2-tiny", hidden_size=64, num_layers=2,
                                 num_heads=4, vocab_size=256, max_seq_len=64,
                                 moe_experts=4, moe_capacity_factor=2.0,
                                 attention_impl="reference", dtype=jnp.float32)
        config = {
            # same global batch + gas for both runs: tp=1 has dp=8 (micro 2),
            # tp=2 has dp=4 (micro 4)
            "train_batch_size": 16,
            "train_micro_batch_size_per_gpu": 4 if tp > 1 else 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
            "zero_optimization": {"stage": 1},
            "moe": {"enabled": True, "ep_size": 2},
            "seed": 5,
        }
        if tp > 1:
            config["tensor_parallel"] = {"tp_size": tp}
        engine, *_ = ds.initialize(model=model, config=config,
                                   loss_fn=make_moe_loss(cfg.moe_aux_weight),
                                   example_batch={"input_ids": np.zeros((16, 32), np.int64)},
                                   sharding_rules=cfg.tp_rules())
        return engine

    e1, e2 = make(1), make(2)
    rng = np.random.default_rng(7)
    for i in range(6):
        b = {"input_ids": rng.integers(0, 256, size=(16, 32))}
        l1 = float(e1.train_batch(b)["loss"])
        l2 = float(e2.train_batch(b)["loss"])
        assert abs(l1 - l2) < 5e-3 + 0.01 * abs(l1), (i, l1, l2)


def test_moe_param_accounting():
    """num_params counts every expert; num_active_params counts the moe_k a
    token routes through (the N that belongs in 6N FLOPs accounting)."""
    from deepspeed_tpu.models.transformer import get_config

    dense = get_config("gpt2-tiny")
    assert dense.num_active_params() == dense.num_params()

    moe = get_config("gpt2-tiny", moe_experts=4, moe_k=1)
    h, L = moe.hidden_size, moe.num_layers
    # total grows by (E-1) expert MLPs + router per layer
    assert (moe.num_params() - dense.num_params()
            == L * (3 * 2 * moe.mlp_dim * h + h * 4))
    # active grows only by the router term
    assert (moe.num_active_params() - dense.num_params() == L * h * 4)

    moe2 = get_config("gpt2-tiny", moe_experts=4, moe_k=2)
    assert (moe2.num_active_params() - moe.num_active_params()
            == L * 2 * moe.mlp_dim * h)

    # the flax param tree must agree with the analytic total
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import build_model
    model, cfg = build_model("gpt2-tiny", moe_experts=4)
    batch = {"input_ids": jnp.zeros((1, 8), jnp.int32)}
    params = jax.eval_shape(lambda r: model.init(r, batch)["params"],
                            jax.random.PRNGKey(0))
    n_tree = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    # analytic model skips biases/layernorm scales (~0.1%); stay within 1%
    assert abs(n_tree - cfg.num_params()) / n_tree < 0.01, \
        (n_tree, cfg.num_params())


# tier-2 (round 8 budget): test_moe_transformer_trains (top-1, ungated)
# keeps MoE training gating tier-1; SwiGLU-expert decode parity stays in
# test_hf_policies.test_moe_decode_parity
@pytest.mark.slow
def test_gated_moe_transformer_trains():
    """SwiGLU experts (Mixtral family, round 5): gated_mlp + moe_experts
    trains under expert parallelism — round 4 refused the combination.
    Expert stacks carry the 3 gated kernels, sharded over the expert axis."""
    require_devices(2)
    model, cfg = build_model("gpt2-tiny", hidden_size=64, num_layers=2,
                             num_heads=4, vocab_size=256, max_seq_len=64,
                             moe_experts=4, moe_k=2, moe_capacity_factor=2.0,
                             gated_mlp=True, activation="silu",
                             norm="rmsnorm", use_bias=False,
                             attention_impl="reference")
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "moe": {"enabled": True, "ep_size": 2},
    }
    rng = np.random.default_rng(6)
    mk = lambda: {"input_ids": rng.integers(0, 256, size=(16, 32))}
    engine, *_ = ds.initialize(model=model, config=config,
                               loss_fn=make_moe_loss(cfg.moe_aux_weight),
                               example_batch=mk(),
                               sharding_rules=cfg.tp_rules())
    experts = engine.state.params["blocks"]["moe"]["experts"]
    for k in ("gate", "fc", "proj"):
        assert k in experts, sorted(experts)
        # every gated kernel must be SHARDED over the expert axis — a
        # missing tp_rules entry leaves the stack replicated, silently
        # defeating the expert-parallel memory model (round-5 review catch)
        spec = experts[k]["kernel"].sharding.spec
        assert "expert" in str(spec), (k, spec)
    assert experts["gate"]["kernel"].shape[1] == 4     # [L, E, H, I]
    losses = [float(engine.train_batch(mk())["loss"]) for _ in range(8)]
    assert losses[-1] < losses[0], losses
