"""Checkpoint hardening tests: bf16 preservation, streaming writer, async
engine ordering, cross-topology round trip, and the inspector.

The cross-topology test makes round-1's "universal by construction" claim
real: save under dp=8, load under tp=2 x sp=2 x dp=2 and continue training
with identical losses (reference needs deepspeed/checkpoint/ reshape tools
for this).
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.checkpoint import (AsyncCheckpointEngine,
                                      DeepSpeedCheckpoint,
                                      NpzCheckpointEngine, inspect_checkpoint)
from deepspeed_tpu.runtime.checkpointing import (read_flat_npz, save_tree,
                                                 load_tree, write_flat_npz)

from util import SimpleModel, random_batch, require_devices


def test_bf16_preserved_bit_exact(tmp_path):
    """bf16 leaves round-trip as bf16 — no f32 upcast (round-1 Weak #6:
    checkpoint size doubled)."""
    import ml_dtypes
    tree = {"w": jnp.asarray(np.random.RandomState(0).randn(64, 8),
                             jnp.bfloat16),
            "b": jnp.arange(8, dtype=jnp.float32)}
    path = str(tmp_path / "t.npz")
    save_tree(tree, path)
    flat = read_flat_npz(path)
    assert flat["w"].dtype == np.dtype(ml_dtypes.bfloat16)
    assert (flat["w"].view(np.uint16) ==
            np.asarray(tree["w"]).view(np.uint16)).all()
    back = load_tree(path, tree)
    assert back["w"].dtype == jnp.bfloat16
    # on-disk footprint ~2 bytes/elem for the bf16 leaf, not 4
    assert os.path.getsize(path) < 64 * 8 * 3 + 8 * 4 + 2048


def test_streaming_writer_lazy_thunks(tmp_path):
    """The writer must call each thunk exactly once, sequentially (one leaf
    on host at a time — the no-whole-model-gather property)."""
    calls = []

    def thunk(name, arr):
        def f():
            calls.append(name)
            return arr
        return f

    flat = {f"k{i}": thunk(f"k{i}", np.full((4,), i, np.float32))
            for i in range(5)}
    path = str(tmp_path / "s.npz")
    write_flat_npz(flat, path)
    assert calls == [f"k{i}" for i in range(5)]
    out = read_flat_npz(path)
    assert np.array_equal(out["k3"], np.full((4,), 3, np.float32))


def test_async_engine_orders_latest_after_data(tmp_path):
    """latest must only appear after the (slow) data writes complete."""
    eng = AsyncCheckpointEngine()
    path = str(tmp_path / "big.npz")

    def slow_dict():
        time.sleep(0.3)
        return np.zeros(10, np.float32)

    eng.save({"a": slow_dict}, path)          # thunk runs on the worker
    marker = str(tmp_path / "latest")
    eng.run(lambda: open(marker, "w").write("tag"))
    assert not os.path.exists(marker) or os.path.exists(path)
    assert eng.commit("tag")
    assert os.path.exists(path) and os.path.exists(marker)


# tier-2 (round 8 budget): the sync roundtrip keeps save/restore gating
# tier-1; async-writer internals are also pinned by the chaos matrix
@pytest.mark.slow
def test_engine_async_checkpoint_roundtrip(tmp_path):
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "checkpoint": {"async_save": True}}
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                               example_batch=random_batch(8))
    for i in range(3):
        engine.train_batch(random_batch(8, seed=i))
    engine.save_checkpoint(str(tmp_path / "ck"))
    assert isinstance(engine.checkpoint_engine, AsyncCheckpointEngine)
    assert engine.wait_for_checkpoints()
    engine2, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                                example_batch=random_batch(8))
    engine2.load_checkpoint(str(tmp_path / "ck"))
    b = random_batch(8, seed=77)
    l1 = float(engine.train_batch(b)["loss"])
    l2 = float(engine2.train_batch(b)["loss"])
    assert abs(l1 - l2) < 1e-5


def _gpt_engine(mesh_sizes, tmp=None):
    from deepspeed_tpu.models import build_model, causal_lm_loss
    model, cfg = build_model("gpt2-tiny", max_seq_len=64,
                             attention_impl="reference")
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "seed": 5,
        **mesh_sizes,
    }
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 32))
    engine, *_ = ds.initialize(
        model=model, config=config, loss_fn=causal_lm_loss,
        example_batch={"input_ids": ids}, sharding_rules=cfg.tp_rules())
    return engine


def _lm_batch(i):
    return {"input_ids": np.random.default_rng(100 + i).integers(
        0, 1024, (8, 32))}


@pytest.mark.slow
def test_cross_topology_roundtrip(tmp_path):
    require_devices(8)
    """Save under pure dp=8, restore under tp=2 x sp=2 x dp=2: the loaded
    model must produce the same losses stepping forward."""
    e_dp = _gpt_engine({})                              # data=8
    for i in range(3):
        e_dp.train_batch(_lm_batch(i))
    e_dp.save_checkpoint(str(tmp_path / "ck"))
    ref = [float(e_dp.train_batch(_lm_batch(10 + i))["loss"])
           for i in range(2)]

    e_3d = _gpt_engine({"tensor_parallel": {"tp_size": 2},
                        "sequence_parallel": {"sp_size": 2}})
    e_3d.load_checkpoint(str(tmp_path / "ck"))
    got = [float(e_3d.train_batch(_lm_batch(10 + i))["loss"])
           for i in range(2)]
    np.testing.assert_allclose(ref, got, rtol=2e-2)


# tier-2 (round-17 budget sweep, ~10s): the cheaper tier-1 cousins are
# test_bf16_preserved_bit_exact and
# test_sharded_write_and_assemble_roundtrip (same on-disk layout the
# inspector reads); scripts/tier2.sh runs the inspector end-to-end
@pytest.mark.slow
def test_checkpoint_inspector(tmp_path):
    engine = _gpt_engine({})
    engine.train_batch(_lm_batch(0))
    engine.save_checkpoint(str(tmp_path / "ck"))
    ck = DeepSpeedCheckpoint(str(tmp_path / "ck"))
    assert ck.global_step == 1
    names = ck.parameter_names()
    assert any("attn_qkv" in n for n in names)
    assert ck.num_parameters() > 0
    summary = inspect_checkpoint(str(tmp_path / "ck"))
    assert summary["num_tensors"] == len(names)
    assert "bfloat16" in summary["dtypes"]


def test_sharded_write_and_assemble_roundtrip(tmp_path):
    """write_shard_npz stores only this process's replica-0 pieces;
    load_sharded_tree reassembles leaf-by-leaf — bit-exact round trip for
    sharded AND replicated leaves (round-2 Weak #5: sharded saves)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.runtime.checkpointing import (load_sharded_tree,
                                                     write_shard_npz)
    from deepspeed_tpu.parallel.mesh import MeshManager

    mm = MeshManager()
    mesh = mm.mesh
    rng = np.random.default_rng(0)
    w = rng.standard_normal((16, 8)).astype(np.float32)
    b = rng.standard_normal((8,)).astype(np.float32)
    bf = jnp.asarray(rng.standard_normal((8, 4)), jnp.bfloat16)
    tree = {
        "w": jax.device_put(jnp.asarray(w),
                            NamedSharding(mesh, P(("data", "expert", "seq"), None))),
        "b": jax.device_put(jnp.asarray(b), NamedSharding(mesh, P())),
        "h": jax.device_put(bf, NamedSharding(mesh, P())),
    }
    write_shard_npz(tree, str(tmp_path / "model_states-shard0.npz"))
    like = {"w": jnp.zeros_like(w), "b": jnp.zeros_like(b),
            "h": jnp.zeros(bf.shape, jnp.bfloat16)}
    out = load_sharded_tree(str(tmp_path), "model_states", like)
    np.testing.assert_array_equal(np.asarray(out["w"]), w)
    np.testing.assert_array_equal(np.asarray(out["b"]), b)
    assert out["h"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["h"]).view(np.uint16), np.asarray(bf).view(np.uint16))


def test_sharded_write_replicated_dedup(tmp_path):
    """A fully-replicated leaf produces exactly ONE stored piece (replica-0),
    not one per device."""
    import jax, json, zipfile
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.runtime.checkpointing import write_shard_npz
    from deepspeed_tpu.parallel.mesh import MeshManager

    mesh = MeshManager().mesh
    x = jax.device_put(jnp.ones((4, 4)), NamedSharding(mesh, P()))
    path = str(tmp_path / "g-shard0.npz")
    write_shard_npz({"x": x}, path)
    names = zipfile.ZipFile(path).namelist()
    assert sum(1 for n in names if n.startswith("x::")) == 1, names


def test_load_module_state_dict_roundtrip():
    """module_state_dict -> load_module_state_dict restores weights only
    (reference: engine.load_module_state_dict, engine.py:2582): params
    transfer across engines, optimizer state/counters stay put, and strict
    mode rejects mismatched key sets."""
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    e1, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                           example_batch=random_batch(8))
    for i in range(3):
        e1.train_batch(random_batch(8, seed=i))
    sd = e1.module_state_dict()

    e2, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                           example_batch=random_batch(8))
    step_before = int(jax.device_get(e2.state.step))
    e2.load_module_state_dict(sd)
    assert int(jax.device_get(e2.state.step)) == step_before  # weights only
    for k, v in e2.module_state_dict().items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(sd[k]), k)
    # the loaded engine continues training (placements/dtypes intact)
    assert np.isfinite(float(e2.train_batch(random_batch(8, seed=9))["loss"]))

    with pytest.raises(KeyError, match="strict"):
        e2.load_module_state_dict({"nope": np.zeros(2, np.float32)})
    e2.load_module_state_dict({}, strict=False)       # no-op, keeps values


def test_load_module_state_dict_refreshes_master():
    """bf16-with-fp32-master mode: the fused step recomputes params FROM the
    master, so a weights-only load must refresh the master too — with lr=0
    a post-load step must return the loaded weights, not the stale ones."""
    cfg = {"train_batch_size": 8, "bf16": {"enabled": True},
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    e1, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                           example_batch=random_batch(8))
    for i in range(3):
        e1.train_batch(random_batch(8, seed=i))
    sd = e1.module_state_dict()

    cfg0 = {**cfg, "optimizer": {"type": "Adam", "params": {"lr": 0.0}}}
    e2, *_ = ds.initialize(model=SimpleModel(), config=cfg0,
                           example_batch=random_batch(8))
    e2.load_module_state_dict(sd)
    e2.train_batch(random_batch(8, seed=9))      # lr=0: a no-op update
    for k, v in e2.module_state_dict().items():
        np.testing.assert_allclose(np.asarray(v), np.asarray(sd[k]),
                                   rtol=0, atol=0, err_msg=k)
