"""Aux subsystems: elasticity math, flops profiler, launcher parsing, ds_report."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.elasticity import (ElasticityError, compute_elastic_config,
                                      get_valid_gpus)
from deepspeed_tpu.launcher.runner import (decode_world_info,
                                           encode_world_info, fetch_hostfile,
                                           parse_hostfile,
                                           parse_inclusion_exclusion)
from deepspeed_tpu.profiling import (FlopsProfiler, compiled_cost,
                                     get_model_profile, params_count)


# -- elasticity ---------------------------------------------------------------

def test_valid_gpus():
    # batch 24, micro 2 or 3: gpus g valid iff (24/2) % g == 0 or (24/3) % g == 0
    assert get_valid_gpus(24, [2, 3], 1, 12) == [1, 2, 3, 4, 6, 8, 12]


def test_compute_elastic_config():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 100,
                          "micro_batch_sizes": [2, 4], "min_gpus": 1,
                          "max_gpus": 16, "version": 0.1}}
    batch, valid, micro = compute_elastic_config(cfg, world_size=8)
    assert batch <= 100
    assert 8 in valid
    assert micro in (2, 4)
    assert batch % (micro * 8) == 0


def test_elastic_config_rejects_bad_world():
    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 8,
                          "micro_batch_sizes": [4], "min_gpus": 1,
                          "max_gpus": 2}}
    with pytest.raises(ElasticityError):
        compute_elastic_config(cfg, world_size=7)


def test_elastic_disabled_raises():
    with pytest.raises(ElasticityError):
        compute_elastic_config({"elasticity": {"enabled": False}})


# -- launcher -----------------------------------------------------------------

def test_parse_hostfile():
    hf = ["# comment", "worker-1 slots=4", "", "worker-2 slots=8 # inline"]
    pool = parse_hostfile(hf)
    assert pool == {"worker-1": 4, "worker-2": 8}
    with pytest.raises(ValueError):
        parse_hostfile(["worker-1 gpus=4"])
    with pytest.raises(ValueError):
        parse_hostfile(["w slots=2", "w slots=2"])


def test_include_exclude_filters():
    pool = {"a": 4, "b": 4, "c": 2}
    inc = parse_inclusion_exclusion(pool, include_str="a:0,2@c")
    assert inc == {"a": [0, 2], "c": [0, 1]}
    exc = parse_inclusion_exclusion(pool, exclude_str="b@c:0")
    assert exc == {"a": [0, 1, 2, 3], "c": [1]}
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(pool, include_str="a", exclude_str="b")
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(pool, include_str="zzz")


def test_world_info_roundtrip():
    active = {"h1": [0, 1], "h2": [0]}
    assert decode_world_info(encode_world_info(active)) == active


def test_collect_env_exports_forwards_dstpu_prefix(monkeypatch):
    """Round-4: DSTPU_* (chaos specs, coordinator overrides, init
    timeouts) must reach remote hosts — they previously never did."""
    from deepspeed_tpu.launcher.runner import collect_env_exports
    monkeypatch.setenv("DSTPU_CHAOS", "run.kill:kill")
    monkeypatch.setenv("DSTPU_INIT_TIMEOUT", "60")
    monkeypatch.setenv("JAX_TRACEBACK_FILTERING", "off")
    monkeypatch.setenv("DSTPU_UNRELATED_HOME", "keepme")
    monkeypatch.setenv("NOT_FORWARDED", "x")
    exports = collect_env_exports()
    assert exports["DSTPU_CHAOS"] == "run.kill:kill"
    assert exports["DSTPU_INIT_TIMEOUT"] == "60"
    assert exports["DSTPU_UNRELATED_HOME"] == "keepme"
    assert exports["JAX_TRACEBACK_FILTERING"] == "off"
    assert "NOT_FORWARDED" not in exports


def test_build_ssh_cmd_connect_timeout_and_sentinel():
    """The supervisor's connect-phase contract lives in the ssh argv:
    ConnectTimeout bounds dead-host dispatch, and the sentinel line marks
    the retryable/not-retryable boundary."""
    from deepspeed_tpu.launcher.runner import build_ssh_cmd
    from deepspeed_tpu.launcher.supervisor import STARTED_SENTINEL
    cmd = build_ssh_cmd("w1", ["python", "t.py"], {"A": "b"},
                        connect_timeout=7)
    assert "ConnectTimeout=7" in cmd
    remote = cmd[-1]
    assert f"echo {STARTED_SENTINEL}; exec" in remote
    assert remote.index("export A=b") < remote.index(STARTED_SENTINEL)


# -- flops profiler -----------------------------------------------------------

def test_compiled_cost_counts_matmul_flops():
    a = jnp.ones((128, 256), jnp.float32)
    b = jnp.ones((256, 64), jnp.float32)
    cost = compiled_cost(lambda a, b: a @ b, a, b)
    # 2*M*N*K flops
    expected = 2 * 128 * 256 * 64
    assert cost["flops"] == pytest.approx(expected, rel=0.1)


# tier-2 (round-19 budget sweep, ~6s): the cheaper tier-1 cousins are
# test_compiled_cost_counts_matmul_flops (cost engine) and
# test_module_flops_breakdown_tree (breakdown walk);
# scripts/tier2.sh runs this full model-profile leg
@pytest.mark.slow
def test_profiler_and_breakdown():
    from deepspeed_tpu.models import build_model
    model, cfg = build_model("gpt2-tiny", hidden_size=32, num_layers=2,
                             num_heads=2, vocab_size=64, max_seq_len=32,
                             dtype=jnp.float32, attention_impl="reference")
    batch = {"input_ids": np.zeros((2, 16), np.int32)}
    flops, macs, n_params = get_model_profile(model, batch)
    assert flops > 0 and macs == flops / 2
    assert n_params == params_count(
        model.init(jax.random.PRNGKey(0), batch)["params"])

    prof = FlopsProfiler()
    stats = prof.profile(lambda x: jnp.sum(x @ x), jnp.ones((64, 64)))
    assert stats["tflops_achieved"] >= 0
    text = prof.print_model_profile(
        model.init(jax.random.PRNGKey(0), batch)["params"])
    assert "params total" in text


# -- ds_report ----------------------------------------------------------------

def test_env_report_runs():
    from deepspeed_tpu.env_report import report_text
    text = report_text()
    assert "deepspeed_tpu report" in text
    assert "jax" in text
    assert "[OKAY]" in text


def test_module_flops_breakdown_tree():
    """Per-module FLOPS attribution from the jaxpr name stack (reference:
    print_model_profile's per-module MAC tree) — exact matmul counts, scan
    bodies multiplied by layer count."""
    import jax.numpy as jnp
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.profiling.flops_profiler import module_flops_breakdown

    model, cfg = build_model("gpt2-tiny", dtype=jnp.float32,
                             attention_impl="reference")
    B, S, H, L = 2, 64, cfg.hidden_size, cfg.num_layers
    ids = jnp.zeros((B, S), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    tree = module_flops_breakdown(
        lambda p: model.apply({"params": p}, {"input_ids": ids}), params,
        depth=3)
    by_leaf = {k.split("/")[-1]: v for k, v in tree.items()}
    # exact analytic counts: 2*tokens*in*out, x L for scanned blocks
    tokens = B * S
    assert by_leaf["mlp_fc"] == 2.0 * tokens * H * cfg.mlp_dim * L
    assert by_leaf["attn_qkv"] == 2.0 * tokens * H * 3 * H * L
    assert by_leaf["wte.attend"] == 2.0 * tokens * H * cfg.vocab_size
    # attention score einsum: 2*B*nh*S*S*hd per layer
    assert by_leaf["bhqd,bhkd->bhqk"] == \
        2.0 * B * cfg.num_heads * S * S * cfg.head_dim * L
