"""Pipeline parallelism: schedule math, partitioning, SPMD parity + training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from util import require_devices

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model, causal_lm_loss
from deepspeed_tpu.models.pipeline import build_pipelined_model
from deepspeed_tpu.runtime.pipe import (
    DataParallelSchedule, InferenceSchedule, LayerSpec, PipelineModule,
    TrainSchedule, bubble_fraction, partition_balanced, partition_uniform)
from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass, ForwardPass, LoadMicroBatch, OptimizerStep, RecvActivation,
    SendActivation)


# -- schedules ----------------------------------------------------------------

def test_train_schedule_completeness():
    """Every stage forwards and backwards every microbatch exactly once."""
    m, s = 6, 3
    for sid in range(s):
        sched = TrainSchedule(micro_batches=m, stages=s, stage_id=sid)
        cmds = [c for step in sched for c in step]
        assert sum(isinstance(c, ForwardPass) for c in cmds) == m
        assert sum(isinstance(c, BackwardPass) for c in cmds) == m
        assert sum(isinstance(c, OptimizerStep) for c in cmds) == 1
        if sid == 0:
            assert sum(isinstance(c, LoadMicroBatch) for c in cmds) == m
            assert not any(isinstance(c, RecvActivation) for c in cmds)
        else:
            assert sum(isinstance(c, RecvActivation) for c in cmds) == m
        if sid < s - 1:
            assert sum(isinstance(c, SendActivation) for c in cmds) == m


def test_train_schedule_1f1b_order():
    """After warmup, forwards and backwards alternate (1F1B steady state)."""
    sched = TrainSchedule(micro_batches=8, stages=4, stage_id=0)
    steps = list(sched.steps())
    fwd_bwd = [("F" if any(isinstance(c, ForwardPass) for c in st) else "") +
               ("B" if any(isinstance(c, BackwardPass) for c in st) else "")
               for st in steps if st]
    joined = "".join(fwd_bwd)
    assert "FB" * 4 in joined  # steady-state interleave
    assert sched.num_pipe_buffers() == 4


def test_inference_schedule():
    sched = InferenceSchedule(micro_batches=4, stages=2, stage_id=1)
    cmds = [c for step in sched for c in step]
    assert sum(isinstance(c, ForwardPass) for c in cmds) == 4
    assert not any(isinstance(c, BackwardPass) for c in cmds)


def test_bubble_fraction():
    assert bubble_fraction(8, 1) == 0
    assert abs(bubble_fraction(8, 4) - 3 / 11) < 1e-9


# -- partitioning -------------------------------------------------------------

def test_partition_uniform():
    assert partition_uniform(10, 4) == [0, 3, 6, 8, 10]
    assert partition_uniform(8, 2) == [0, 4, 8]


def test_partition_balanced():
    # heavy layer should sit alone
    parts = partition_balanced([1, 1, 1, 10, 1, 1], 3)
    sums = [sum([1, 1, 1, 10, 1, 1][parts[i]:parts[i + 1]]) for i in range(3)]
    assert max(sums) == 10
    # uniform weights behave like uniform partitioning
    parts = partition_balanced([1] * 8, 4)
    assert parts == [0, 2, 4, 6, 8]


def test_pipeline_module_partition():
    class Emb: pass
    class Blk: pass
    class Head: pass
    layers = [LayerSpec(Emb)] + [LayerSpec(Blk) for _ in range(8)] + [LayerSpec(Head)]
    pm = PipelineModule(layers, num_stages=2, partition_method="type:Blk")
    counts = [len(pm.stage_layers(s)) for s in range(2)]
    assert sum(counts) == 10
    blk_per_stage = [sum(1 for l in pm.stage_layers(s) if l.typename is Blk)
                     for s in range(2)]
    assert blk_per_stage == [4, 4]
    start, end = pm.homogeneous_span()
    assert (start, end) == (1, 9)


# -- SPMD execution -----------------------------------------------------------

def _mk_batch(rng, vocab, b, s):
    return {"input_ids": rng.integers(0, vocab, size=(b, s))}


def test_pipelined_matches_sequential():
    require_devices(2)
    """pp=2 pipelined forward == plain scan-layers forward, same params."""
    kw = dict(hidden_size=64, num_layers=4, num_heads=4, vocab_size=256,
              max_seq_len=64, dtype=jnp.float32, attention_impl="reference")
    plain, cfg = build_model("gpt2-tiny", **kw)
    rng = np.random.default_rng(0)
    batch = _mk_batch(rng, cfg.vocab_size, 16, 32)

    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "pipeline": {"stages": 2},
        "tensor_parallel": {"tp_size": 2},
    }
    piped, _ = build_pipelined_model(cfg, pp=2, n_micro=4)
    engine, *_ = ds.initialize(model=piped, config=config,
                               loss_fn=causal_lm_loss, example_batch=batch,
                               rng=jax.random.PRNGKey(5),
                               sharding_rules=piped.tp_rules())
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
    assert isinstance(engine, PipelineEngine)

    params = jax.device_get(engine.state.params)
    logits_pipe = engine.eval_batch(batch)
    logits_plain = plain.apply({"params": params}, batch)
    np.testing.assert_allclose(np.asarray(logits_pipe),
                               np.asarray(logits_plain), rtol=2e-4, atol=2e-4)


def test_pipelined_training_descends():
    require_devices(2)
    kw = dict(hidden_size=64, num_layers=4, num_heads=4, vocab_size=256,
              max_seq_len=64, attention_impl="reference")
    piped, cfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=4, **kw)
    config = {
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "pipeline": {"stages": 2},
    }
    rng = np.random.default_rng(1)
    mk = lambda: _mk_batch(rng, cfg.vocab_size, 32, 32)
    engine, *_ = ds.initialize(model=piped, config=config,
                               loss_fn=causal_lm_loss, example_batch=mk(),
                               sharding_rules=piped.tp_rules())
    losses = [float(engine.train_batch(mk())["loss"]) for _ in range(8)]
    assert losses[-1] < losses[0], losses
    with pytest.raises(RuntimeError):
        engine.forward(mk())


# -- 1F1B executor (runtime/pipe/one_f_one_b) ---------------------------------

from deepspeed_tpu.runtime.pipe.one_f_one_b import (
    build_1f1b_tables, pipeline_1f1b_value_and_grad)


def test_1f1b_tables_valid():
    """Every micro forwards and backwards exactly once per stage, sends
    always land one tick before their consumption, and in-flight forwards
    never exceed the ring capacity."""
    for m, pp in [(4, 2), (8, 4), (3, 4), (6, 3)]:
        t = build_1f1b_tables(m, pp)
        fwd, bwd = t["fwd"], t["bwd"]
        for s in range(pp):
            assert sorted(x for x in fwd[:, s] if x >= 0) == list(range(m))
            assert sorted(x for x in bwd[:, s] if x >= 0) == list(range(m))
            # in-flight bound (the 1F1B memory claim): #fwd - #bwd <= min(pp,m)
            inflight = np.cumsum(fwd[:, s] >= 0) - np.cumsum(bwd[:, s] >= 0)
            assert inflight.max() <= min(pp, m)
        # fwd of micro f on stage s strictly after on stage s-1
        for s in range(1, pp):
            for f in range(m):
                t_prev = int(np.where(fwd[:, s - 1] == f)[0][0])
                t_here = int(np.where(fwd[:, s] == f)[0][0])
                assert t_here > t_prev


def test_1f1b_grads_match_sequential():
    require_devices(2)
    """Hand-scheduled 1F1B loss + grads == plain autodiff of the stacked
    stages (the executor's correctness oracle)."""
    from jax.sharding import Mesh
    pp, n_micro, mb, H = 4, 6, 2, 8
    rng = np.random.RandomState(0)
    sp = {"w": jnp.asarray(rng.randn(pp, H, H) * 0.3, jnp.float32),
          "b": jnp.asarray(rng.randn(pp, H) * 0.1, jnp.float32)}
    head = {"v": jnp.asarray(rng.randn(H) * 0.5, jnp.float32)}
    micros = jnp.asarray(rng.randn(n_micro, mb, H), jnp.float32)
    labels = jnp.asarray(rng.randn(n_micro, mb), jnp.float32)

    def stage_fn(p, x, extra, stage):
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_fn(h, y, lab):
        return jnp.mean((y @ h["v"] - lab) ** 2)

    def ref_loss(sp, hp, mi):
        def one(m, lab):
            x = m
            for s in range(pp):
                x = stage_fn(jax.tree.map(lambda a: a[s], sp), x, {}, s)
            return loss_fn(hp, x, lab)
        return jnp.mean(jax.vmap(one)(mi, labels))

    ref_l, (rgs, rgh, rgm) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2))(sp, head, micros)
    mesh = Mesh(np.asarray(jax.devices()[:pp]).reshape(pp), ("pipe",))
    loss, _aux, gs, gh, gm = jax.jit(
        lambda a, b, c, d: pipeline_1f1b_value_and_grad(
            stage_fn, loss_fn, a, b, c, d, mesh=mesh, pp=pp))(
        sp, head, micros, labels)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(gs[k]), np.asarray(rgs[k]),
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gh["v"]), np.asarray(rgh["v"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gm), np.asarray(rgm), rtol=1e-4,
                               atol=1e-5)


def test_pipeline_engine_1f1b_matches_gpipe():
    require_devices(2)
    """Same model trained one step under schedule=gpipe vs schedule=1f1b:
    losses and updated params agree (bf16 boundary, no f32 crossing)."""
    kw = dict(hidden_size=64, num_layers=4, num_heads=4, vocab_size=256,
              max_seq_len=64, dtype=jnp.float32, attention_impl="reference")

    def make(schedule):
        piped, cfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=4, **kw)
        config = {
            "train_batch_size": 32,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
            "zero_optimization": {"stage": 0},
            "pipeline": {"stages": 2, "schedule": schedule},
            "seed": 11,
        }
        rng = np.random.default_rng(2)
        batch = _mk_batch(rng, cfg.vocab_size, 32, 32)
        engine, *_ = ds.initialize(model=piped, config=config,
                                   loss_fn=causal_lm_loss,
                                   example_batch=batch,
                                   rng=jax.random.PRNGKey(7))
        return engine, cfg

    e_g, cfg = make("gpipe")
    e_f, _ = make("1f1b")
    # strongest check: 1F1B grads == autodiff grads at the shared init
    # (post-Adam params drift by design — Adam sign-amplifies fp roundoff)
    batch = _mk_batch(np.random.default_rng(49), cfg.vocab_size, 32, 32)
    batch_j = {k: jnp.asarray(v) for k, v in batch.items()}
    params = e_f.state.params
    mesh = e_f.mesh
    with mesh:
        _, g1 = jax.jit(lambda p, b: e_f.module.train_value_and_grad(
            p, b, mesh=mesh))(params, batch_j)
        _, g2 = jax.jit(jax.value_and_grad(lambda p: causal_lm_loss(
            e_f.module.apply({"params": p}, batch_j, mesh=mesh),
            batch_j)))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-6)
    for i in range(3):
        b = _mk_batch(np.random.default_rng(50 + i), cfg.vocab_size, 32, 32)
        lg = float(e_g.train_batch(b)["loss"])
        lf = float(e_f.train_batch(b)["loss"])
        assert abs(lg - lf) < 2e-3, (i, lg, lf)


def test_moe_pipeline_composition():
    require_devices(2)
    """MoE + PP (round-1 gap: raised NotImplementedError): the aux loss
    rides the pipe and the composition trains."""
    from deepspeed_tpu.models.transformer import make_moe_loss
    piped, cfg = build_pipelined_model(
        "gpt2-tiny", pp=2, n_micro=2, hidden_size=64, num_layers=4,
        num_heads=4, vocab_size=256, max_seq_len=64, moe_experts=4,
        dtype=jnp.float32, attention_impl="reference")
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
        "zero_optimization": {"stage": 0},
        "pipeline": {"stages": 2},
        "seed": 3,
    }
    rng = np.random.default_rng(4)
    mk = lambda: _mk_batch(rng, cfg.vocab_size, 16, 32)
    engine, *_ = ds.initialize(model=piped, config=config,
                               loss_fn=make_moe_loss(), example_batch=mk())
    losses = [float(engine.train_batch(mk())["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    # aux channel really contributes: eval returns (logits, aux)
    logits, aux = engine.eval_batch(mk())
    assert float(aux) > 0.0


# -- 1F1B generality (round-3 Missing #3) -------------------------------------


def _tiny_piped(pp=2, n_micro=4, **overrides):
    kw = dict(hidden_size=64, num_layers=4, num_heads=4, vocab_size=256,
              max_seq_len=64, dtype=jnp.float32, attention_impl="reference")
    kw.update(overrides)
    return build_pipelined_model("gpt2-tiny", pp=pp, n_micro=n_micro, **kw)


def _init_engine(piped, cfg, loss_fn=causal_lm_loss, schedule="1f1b",
                 batch=None, extra_cfg=None):
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
        "zero_optimization": {"stage": 0},
        "pipeline": {"stages": piped.pp, "schedule": schedule},
        "seed": 11,
    }
    if extra_cfg:
        config.update(extra_cfg)
    if batch is None:
        batch = _mk_batch(np.random.default_rng(2), cfg.vocab_size, 16, 32)
    engine, *_ = ds.initialize(model=piped, config=config, loss_fn=loss_fn,
                               example_batch=batch,
                               rng=jax.random.PRNGKey(7))
    return engine


def _masked_batch(rng, vocab, b, s):
    ids = rng.integers(0, vocab, size=(b, s))
    mask = np.ones((b, s), np.int32)
    for i in range(b):
        pad = int(rng.integers(0, s // 3))
        if pad:
            mask[i, -pad:] = 0
    labels = np.where(mask > 0, ids, -100)
    return {"input_ids": ids, "attention_mask": mask, "labels": labels}


def test_1f1b_masked_matches_autodiff():
    require_devices(2)
    """1F1B grads on a PADDED (attention_mask) batch == autodiff through the
    gpipe apply — the mask rides the pipe as a per-micro side input."""
    piped, cfg = _tiny_piped()
    engine = _init_engine(
        piped, cfg,
        batch=_masked_batch(np.random.default_rng(3), 256, 16, 32))
    batch = {k: jnp.asarray(v) for k, v in _masked_batch(
        np.random.default_rng(5), 256, 16, 32).items()}
    params = engine.state.params
    mesh = engine.mesh
    with mesh:
        l1, g1 = jax.jit(lambda p, b: piped.train_value_and_grad(
            p, b, mesh=mesh))(params, batch)
        l2, g2 = jax.jit(jax.value_and_grad(lambda p: causal_lm_loss(
            piped.apply({"params": p}, batch, train=False, mesh=mesh),
            batch)))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-4)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g1)[0],
            jax.tree_util.tree_flatten_with_path(g2)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4, err_msg=str(pa))


def test_1f1b_dropout_matches_gpipe_bitwise_rng():
    require_devices(2)
    """dropout>0: both schedules fold rngs per (micro, stage, layer)
    identically, so 1F1B grads == autodiff-through-gpipe grads with the
    same base rng — dropout parity, not just convergence."""
    piped, cfg = _tiny_piped(dropout=0.1)
    engine = _init_engine(piped, cfg)
    batch = {k: jnp.asarray(v) for k, v in _mk_batch(
        np.random.default_rng(5), 256, 16, 32).items()}
    params = engine.state.params
    mesh = engine.mesh
    base = jax.random.PRNGKey(123)
    with mesh:
        l1, g1 = jax.jit(lambda p, b: piped.train_value_and_grad(
            p, b, mesh=mesh, rng=base, train=True))(params, batch)
        l2, g2 = jax.jit(jax.value_and_grad(lambda p: causal_lm_loss(
            piped.apply({"params": p}, batch, train=True,
                        rngs={"dropout": base}, mesh=mesh),
            batch)))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-4)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g1)[0],
            jax.tree_util.tree_flatten_with_path(g2)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4, err_msg=str(pa))


def test_1f1b_moe_matches_autodiff():
    require_devices(2)
    """MoE through 1F1B: the aux loss flows through the manual backward via
    its constant cotangent — loss AND grads match autodiff of the gpipe
    path under make_moe_loss."""
    from deepspeed_tpu.models import make_moe_loss
    piped, cfg = _tiny_piped(moe_experts=2, moe_capacity_factor=2.0)
    moe_loss = make_moe_loss(cfg.moe_aux_weight)
    engine = _init_engine(piped, cfg, loss_fn=moe_loss)
    batch = {k: jnp.asarray(v) for k, v in _mk_batch(
        np.random.default_rng(5), 256, 16, 32).items()}
    params = engine.state.params
    mesh = engine.mesh
    with mesh:
        l1, g1 = jax.jit(lambda p, b: piped.train_value_and_grad(
            p, b, mesh=mesh))(params, batch)
        l2, g2 = jax.jit(jax.value_and_grad(lambda p: moe_loss(
            piped.apply({"params": p}, batch, train=False, mesh=mesh),
            batch)))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-4)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g1)[0],
            jax.tree_util.tree_flatten_with_path(g2)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-3,
                                   atol=3e-4, err_msg=str(pa))


def test_1f1b_store_outputs_matches_recompute():
    require_devices(2)
    """backward='store' (vjp residual rings, no recompute) produces the same
    grads as the default recompute mode."""
    piped_r, cfg = _tiny_piped(backward="recompute")
    piped_s, _ = _tiny_piped(backward="store")
    engine = _init_engine(piped_r, cfg)
    batch = {k: jnp.asarray(v) for k, v in _mk_batch(
        np.random.default_rng(5), 256, 16, 32).items()}
    params = engine.state.params
    mesh = engine.mesh
    with mesh:
        l1, g1 = jax.jit(lambda p, b: piped_r.train_value_and_grad(
            p, b, mesh=mesh))(params, batch)
        l2, g2 = jax.jit(lambda p, b: piped_s.train_value_and_grad(
            p, b, mesh=mesh))(params, batch)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g1)[0],
            jax.tree_util.tree_flatten_with_path(g2)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-6, err_msg=str(pa))


def test_1f1b_custom_loss_fn():
    require_devices(2)
    """A user loss_fn runs per-micro at the last stage; for a per-token-mean
    objective the micro average equals the full-batch value, so grads match
    full-batch autodiff."""
    def smoothed_ce(logits, batch):
        tgt = batch["input_ids"][:, 1:]
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
        nll = -jnp.take_along_axis(lp, tgt[..., None], axis=-1)[..., 0]
        smooth = -jnp.mean(lp, axis=-1)
        return jnp.mean(0.9 * nll + 0.1 * smooth)

    piped, cfg = _tiny_piped()
    engine = _init_engine(piped, cfg, loss_fn=smoothed_ce)
    batch = {k: jnp.asarray(v) for k, v in _mk_batch(
        np.random.default_rng(5), 256, 16, 32).items()}
    params = engine.state.params
    mesh = engine.mesh
    with mesh:
        l1, g1 = jax.jit(lambda p, b: piped.train_value_and_grad(
            p, b, mesh=mesh, loss_fn=smoothed_ce))(params, batch)
        l2, g2 = jax.jit(jax.value_and_grad(lambda p: smoothed_ce(
            piped.apply({"params": p}, batch, train=False, mesh=mesh),
            batch)))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-4)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g1)[0],
            jax.tree_util.tree_flatten_with_path(g2)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4, err_msg=str(pa))
    # and end-to-end through the engine
    m = engine.train_batch(
        _mk_batch(np.random.default_rng(6), cfg.vocab_size, 16, 32))
    assert np.isfinite(float(m["loss"]))


def test_1f1b_fp16_loss_scaling():
    require_devices(2)
    """fp16 + 1F1B: the scale seeds the manual backward, grads unscale in
    the engine tail; training proceeds and a forced overflow skips the
    step and halves the scale."""
    piped, cfg = _tiny_piped(dtype=jnp.float16)
    engine = _init_engine(
        piped, cfg,
        extra_cfg={"fp16": {"enabled": True, "initial_scale_power": 8,
                            "hysteresis": 1}})
    losses = []
    for i in range(4):
        b = _mk_batch(np.random.default_rng(20 + i), cfg.vocab_size, 16, 32)
        m = engine.train_batch(b)
        losses.append(float(m["loss"]))
    assert np.all(np.isfinite(losses)), losses


def test_1f1b_moe_through_engine():
    require_devices(2)
    """The ENGINE wiring for MoE + schedule='1f1b': make_moe_loss is
    recognized (aux handled by the executor, not the per-micro custom-loss
    path) and training descends."""
    from deepspeed_tpu.models import make_moe_loss
    piped, cfg = _tiny_piped(moe_experts=2, moe_capacity_factor=2.0)
    engine = _init_engine(piped, cfg,
                          loss_fn=make_moe_loss(cfg.moe_aux_weight))
    losses = [float(engine.train_batch(_mk_batch(
        np.random.default_rng(30 + i), cfg.vocab_size, 16, 32))["loss"])
        for i in range(6)]
    assert np.all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_ladder_zero1_pp_moe_ep_composition():
    require_devices(8)
    """The top of the BASELINE ladder's composition (config 5: ZeRO +
    pipeline + MoE alltoall) in ONE program: mesh(pp=2, data=2, expert=2)
    with ZeRO-1 master sharding under the pipe, expert params sharded over
    the expert axis, and the MoE aux riding the pipe. Round-3 Missing #1:
    pipeline and expert axes had never been composed."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.models.transformer import make_moe_loss
    piped, cfg = build_pipelined_model(
        "gpt2-tiny", pp=2, n_micro=2, hidden_size=64, num_layers=4,
        num_heads=4, vocab_size=256, max_seq_len=64, moe_experts=2,
        moe_capacity_factor=2.0, dtype=jnp.float32,
        attention_impl="reference")
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-3}},
        "zero_optimization": {"stage": 1},
        "pipeline": {"stages": 2},
        "moe": {"enabled": True, "ep_size": 2},
        "seed": 3,
    }
    rng = np.random.default_rng(4)
    mk = lambda: _mk_batch(rng, cfg.vocab_size, 16, 32)
    engine, *_ = ds.initialize(model=piped, config=config,
                               loss_fn=make_moe_loss(), example_batch=mk(),
                               sharding_rules=piped.tp_rules())
    assert engine.mesh_mgr.shape["pipe"] == 2
    assert engine.mesh_mgr.shape["expert"] == 2
    assert engine.mesh_mgr.shape["data"] == 2

    # expert kernels carry BOTH the pipe and expert axes in their sharding
    flat = jax.tree_util.tree_flatten_with_path(engine.state.params)[0]
    expert_kernels = [(path, leaf) for path, leaf in flat
                      if "experts" in str(path) and "kernel" in str(path)]
    assert expert_kernels
    for path, leaf in expert_kernels:
        spec = leaf.sharding.spec
        assert spec[0] == "pipe", (path, spec)
        assert "expert" in spec, (path, spec)

    # ZeRO-1: master/opt-state sharded over the zero axes under the pipe
    opt_leaves = jax.tree.leaves(engine.state.opt_state)
    assert any(
        any(ax in ("data", "expert", "seq")
            for entry in (l.sharding.spec or ())
            for ax in ((entry,) if isinstance(entry, str)
                       else tuple(entry or ())))
        for l in opt_leaves if hasattr(l, "sharding")), \
        "no opt-state leaf carries a ZeRO axis"

    losses = [float(engine.train_batch(mk())["loss"]) for _ in range(6)]
    assert np.all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_1f1b_moe_requires_marked_loss():
    """A raw custom loss on MoE+1F1B is rejected loudly: gpipe hands it the
    model's (logits, aux) tuple but the 1F1B executor computes aux itself
    and passes bare logits — silent misreads must be impossible."""
    require_devices(2)
    from deepspeed_tpu.models.transformer import make_moe_loss
    piped, cfg = _tiny_piped(moe_experts=4)

    def raw_loss(out, b):          # written against the gpipe contract
        logits, aux = out
        return causal_lm_loss(logits, b) + 0.01 * aux

    with pytest.raises(ValueError, match="make_moe_loss"):
        _init_engine(piped, cfg, loss_fn=raw_loss)

    # the supported spelling: make_moe_loss-wrapped custom base loss runs
    # and trains (base receives bare logits on BOTH schedules)
    def base(logits, b):
        return causal_lm_loss(logits, b)

    piped2, cfg2 = _tiny_piped(moe_experts=4)
    engine = _init_engine(piped2, cfg2,
                          loss_fn=make_moe_loss(0.01, base_loss=base))
    rng = np.random.default_rng(5)
    losses = [float(engine.train_batch(
        _mk_batch(rng, cfg2.vocab_size, 16, 32))["loss"]) for _ in range(4)]
    assert losses[-1] < losses[0], losses


def test_pipelined_llama_family_gpipe_and_1f1b():
    require_devices(2)
    """Modern-decoder (Llama/Gemma-class) models under BOTH pipeline
    schedules: rotary positions (no wpe), RMSNorm final norm, untied
    lm_head, embed_scale, GQA. Round 5: the pipelined embed/head plumbing
    previously hardcoded learned positions and a tied head. gpipe logits
    must match the dense Transformer; 1F1B must descend; windowed models
    are refused loudly."""
    kw = dict(hidden_size=64, num_layers=4, num_heads=4, num_kv_heads=2,
              vocab_size=256, max_seq_len=64, norm="rmsnorm",
              gated_mlp=True, activation="silu", use_bias=False,
              pos_embed="rotary", rotary_interleaved=False,
              tie_embeddings=False, embed_scale=8.0,
              dtype=jnp.float32, attention_impl="reference")
    plain, cfg = build_model("gpt2-tiny", **kw)
    rng = np.random.default_rng(7)
    batch = _mk_batch(rng, cfg.vocab_size, 32, 32)   # dp=4 x micro 2 x gas 4
    config = {
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "pipeline": {"stages": 2},
    }
    piped, _ = build_pipelined_model(cfg, pp=2, n_micro=4)
    engine, *_ = ds.initialize(model=piped, config=config,
                               loss_fn=causal_lm_loss, example_batch=batch,
                               rng=jax.random.PRNGKey(9),
                               sharding_rules=piped.tp_rules())
    params = jax.device_get(engine.state.params)
    assert "wpe" not in params and "lm_head" in params
    logits_pipe = engine.eval_batch(batch)
    logits_plain = plain.apply({"params": params}, batch)
    np.testing.assert_allclose(np.asarray(logits_pipe),
                               np.asarray(logits_plain),
                               rtol=2e-4, atol=2e-4)
    # 1F1B: same model through the hand-scheduled executor, loss descends
    # and the untied-head/embedding grads flow (step must change both)
    f_cfg = dict(config)
    f_cfg["pipeline"] = {"stages": 2, "schedule": "1f1b"}
    feng, *_ = ds.initialize(model=build_pipelined_model(
                                 cfg, pp=2, n_micro=4)[0],
                             config=f_cfg, loss_fn=causal_lm_loss,
                             example_batch=batch,
                             rng=jax.random.PRNGKey(9))
    head0 = np.asarray(feng.state.params["lm_head"]["kernel"])
    wte0 = np.asarray(feng.state.params["wte"]["embedding"])
    losses = [float(feng.train_batch(
        _mk_batch(rng, cfg.vocab_size, 32, 32))["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    assert not np.allclose(
        head0, np.asarray(feng.state.params["lm_head"]["kernel"]))
    assert not np.allclose(
        wte0, np.asarray(feng.state.params["wte"]["embedding"]))

    with pytest.raises(NotImplementedError, match="sliding"):
        build_pipelined_model(
            "gpt2-tiny", pp=2, n_micro=2, hidden_size=64, num_layers=2,
            num_heads=4, vocab_size=256, max_seq_len=64,
            layer_windows=(8, 8))


# -- pipe_bench placement rows (round 13) -------------------------------------

def test_pipe_bench_discovery_and_regression(tmp_path):
    """The pipe_bench rows ride the shared newest-recorded-sweep
    convention: device-count-filtered discovery, per-cell >2x wall
    regression detection, null SPMD cells (shard_map-less hosts)
    compared only when both sweeps carry one."""
    import json
    from deepspeed_tpu.benchmarks.pipeline_bench import (
        check_pipe_regression, latest_pipe_bench)

    row = {"pp": 2, "n_micro": 4, "hidden": 64, "layers": 4, "seq": 64,
           "mb": 2, "spmd_step_s": None, "mpmd_step_s": 0.2,
           "bubble_theory": 0.2, "bubble_1f1b_measured": 0.43}
    (tmp_path / "PIPEBENCH_r01.json").write_text(
        json.dumps({"n": 8, "rows": [row]}))
    # other-device-count sweeps are skipped
    (tmp_path / "PIPEBENCH_r02.json").write_text(
        json.dumps({"n": 2, "rows": [dict(row, mpmd_step_s=9.9)]}))
    name, rows = latest_pipe_bench(str(tmp_path), n_devices=8)
    assert name == "PIPEBENCH_r01.json" and rows == [row]

    ok = dict(row, mpmd_step_s=0.3)
    assert check_pipe_regression([ok], rows) == []
    bad = dict(row, mpmd_step_s=0.5)
    msgs = check_pipe_regression([bad], rows)
    assert len(msgs) == 1 and "mpmd_step_s" in msgs[0]
    # a null spmd cell on either side never trips the gate
    both_null = dict(row, spmd_step_s=None)
    assert check_pipe_regression([both_null], [row]) == []
    # unknown cells (new config) are not regressions
    assert check_pipe_regression([dict(row, pp=4)], rows) == []


def test_repo_has_recorded_pipe_sweep():
    """PIPEBENCH_r01 anchors the convention (CPU host; the SPMD cell is
    null there — the 0.4.x shard_map gap — and fills in on real-chip
    runs)."""
    import os
    from deepspeed_tpu.benchmarks.pipeline_bench import latest_pipe_bench
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    name, rows = latest_pipe_bench(repo)
    assert name and rows
    assert all("mpmd_step_s" in r for r in rows)
