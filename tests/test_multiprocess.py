"""Real multi-PROCESS coverage: 2 OS processes, jax.distributed rendezvous.

Mirrors the reference's DistributedTest pattern (tests/unit/common.py:110 —
fork N ranks with a TCP store rendezvous, train, checkpoint).  Everything
else in this suite simulates multi-chip with 8 virtual devices in ONE
process; this test exercises the rank-bootstrap path those tests skip:
``deepspeed_tpu.init_distributed`` -> ``jax.distributed.initialize`` with
the DSTPU_* env contract the launcher sets (launcher/runner.py), a global
mesh spanning two processes, cross-process collectives in the train step,
and a rank-0 checkpoint write.
"""

import pathlib
import socket
import subprocess
import sys

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)

WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])

import numpy as np
import deepspeed_tpu as ds

ds.init_distributed()          # DSTPU_COORDINATOR_ADDRESS / _NUM_PROCESSES / _PROCESS_ID
rank = ds.comm.get_rank()
world = ds.comm.get_world_size()
assert world == 2, world
assert len(jax.devices()) == 2          # one local device per process, global view

sys.path.insert(0, os.path.join(os.environ["DSTPU_TEST_REPO"], "tests"))
from util import SimpleModel, random_batch

config = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    "zero_optimization": {"stage": 1},
    "seed": 11,
}
engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                           example_batch=random_batch(8))
assert engine.dp_world_size == 2
# correctness here is the rank bootstrap + cross-process collectives +
# sharded checkpointing, not convergence (batch 8 is noisy): finite losses,
# and both ranks must report IDENTICAL values (the psum really synced)
losses = [float(engine.train_batch(random_batch(8, seed=i))["loss"])
          for i in range(12)]
assert np.isfinite(losses).all(), losses

ckdir = os.environ["DSTPU_TEST_CKPT"]
engine.save_checkpoint(ckdir, tag="mp")
print(f"RANK{rank} OK last={losses[-1]:.4f}", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_train_and_checkpoint(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    port = _free_port()
    ck = tmp_path / "ck"
    procs = []
    for pid in range(2):
        env = dict(**__import__("os").environ,
                   DSTPU_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   DSTPU_NUM_PROCESSES="2",
                   DSTPU_PROCESS_ID=str(pid),
                   DSTPU_TEST_REPO=REPO_ROOT,
                   DSTPU_TEST_CKPT=str(ck))
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-3000:]}"
        assert f"RANK{pid} OK" in out, out[-2000:]
    # both ranks computed the same loss (the collectives really synced)
    l0 = outs[0].split("last=")[1].split()[0]
    l1 = outs[1].split("last=")[1].split()[0]
    assert l0 == l1, (l0, l1)
    assert (ck / "mp").is_dir()

    # the 2-process job wrote SHARDED files (per-host pieces, no gather);
    # restore them here in the single-process 8-device suite — a
    # cross-process-count universal restore
    shard_files = list((ck / "mp").glob("model_states-shard*.npz"))
    assert len(shard_files) == 2, shard_files

    import deepspeed_tpu as ds
    from util import SimpleModel, random_batch
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "seed": 11,
    }
    engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                               example_batch=random_batch(8))
    engine.load_checkpoint(str(ck), tag="mp")
    assert int(engine.state.step) == 12
    m = engine.train_batch(random_batch(8, seed=100))
    assert float(m["loss"]) == float(m["loss"])   # finite, trains on
