"""Real multi-PROCESS coverage: 2 OS processes, jax.distributed rendezvous.

Mirrors the reference's DistributedTest pattern (tests/unit/common.py:110 —
fork N ranks with a TCP store rendezvous, train, checkpoint).  Everything
else in this suite simulates multi-chip with 8 virtual devices in ONE
process; this test exercises the rank-bootstrap path those tests skip:
``deepspeed_tpu.init_distributed`` -> ``jax.distributed.initialize`` with
the DSTPU_* env contract the launcher sets (launcher/runner.py), a global
mesh spanning two processes, cross-process collectives in the train step,
and a rank-0 checkpoint write.
"""

import pathlib
import socket
import subprocess
import sys

import pytest

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)

WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])

import numpy as np
import deepspeed_tpu as ds

ds.init_distributed()          # DSTPU_COORDINATOR_ADDRESS / _NUM_PROCESSES / _PROCESS_ID
rank = ds.comm.get_rank()
world = ds.comm.get_world_size()
assert world == 2, world
assert len(jax.devices()) == 2          # one local device per process, global view

sys.path.insert(0, os.path.join(os.environ["DSTPU_TEST_REPO"], "tests"))
from util import SimpleModel, random_batch

config = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    "zero_optimization": {"stage": 1},
    "seed": 11,
}
engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                           example_batch=random_batch(8))
assert engine.dp_world_size == 2
# correctness here is the rank bootstrap + cross-process collectives +
# sharded checkpointing, not convergence (batch 8 is noisy): finite losses,
# and both ranks must report IDENTICAL values (the psum really synced)
losses = [float(engine.train_batch(random_batch(8, seed=i))["loss"])
          for i in range(12)]
assert np.isfinite(losses).all(), losses

ckdir = os.environ["DSTPU_TEST_CKPT"]
engine.save_checkpoint(ckdir, tag="mp")
print(f"RANK{rank} OK last={losses[-1]:.4f}", flush=True)
"""


WORKER_TP_PP = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])

import numpy as np
import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model, causal_lm_loss
from deepspeed_tpu.models.pipeline import build_pipelined_model

ds.init_distributed()
rank = ds.comm.get_rank()
assert ds.comm.get_world_size() == 2
assert len(jax.devices()) == 4              # 2 virtual devices per process
assert len(jax.local_devices()) == 2

# leg 1: ZeRO-1 + TP=2 — the model axis spans the PROCESS boundary, so
# every qkv/mlp matmul's psum rides the gloo transport (the launcher
# contract has only ever carried dp=2 before this test)
model, cfg = build_model("gpt2-tiny", hidden_size=64, num_layers=2,
                         num_heads=4, vocab_size=256, max_seq_len=64,
                         attention_impl="reference")
config = {
    "train_batch_size": 4,
    "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    "zero_optimization": {"stage": 1},
    "tensor_parallel": {"tp_size": 2},
    "seed": 17,
}
batch = {"input_ids": np.random.default_rng(3).integers(0, 256, (4, 32))}
eng, *_ = ds.initialize(model=model, config=config,
                        loss_fn=causal_lm_loss, example_batch=batch,
                        sharding_rules=cfg.tp_rules())
tl = [float(eng.train_batch(batch)["loss"]) for _ in range(3)]
assert np.isfinite(tl).all(), tl

# leg 2: PP=2 (GPipe SPMD) x DP=2 — the ppermute stage boundary crosses
# processes
piped, pcfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=2,
                                    hidden_size=64, num_layers=2,
                                    num_heads=4, vocab_size=256,
                                    max_seq_len=64,
                                    attention_impl="reference")
pconfig = {
    "train_batch_size": 8,
    "train_micro_batch_size_per_gpu": 2,
    "gradient_accumulation_steps": 2,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    "zero_optimization": {"stage": 1},
    "pipeline": {"stages": 2},
    "seed": 17,
}
pbatch = {"input_ids": np.random.default_rng(4).integers(0, 256, (8, 32))}
peng, *_ = ds.initialize(model=piped, config=pconfig,
                         loss_fn=causal_lm_loss, example_batch=pbatch,
                         sharding_rules=piped.tp_rules())
pl = [float(peng.train_batch(pbatch)["loss"]) for _ in range(3)]
assert np.isfinite(pl).all(), pl

print(f"RANK{rank} OK tp={tl[-1]:.4f} pp={pl[-1]:.4f}", flush=True)
"""


WORKER_RANK_FAILPOINT = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])

import deepspeed_tpu as ds

ds.init_distributed()
rank = ds.comm.get_rank()
sys.path.insert(0, os.path.join(os.environ["DSTPU_TEST_REPO"], "tests"))
from util import SimpleModel, random_batch
from deepspeed_tpu.runtime import checkpointing as ck

config = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    "zero_optimization": {"stage": 1},
    "seed": 11,
}
engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                           example_batch=random_batch(8))
ckdir = os.environ["DSTPU_TEST_CKPT"]
engine.train_batch(random_batch(8, seed=0))
engine.save_checkpoint(ckdir)             # clean sharded save: both ranks ok
# non-zero ranks return from the save's allgather BEFORE rank 0 publishes
# `latest` — order the read behind the publish
ds.comm.barrier("after-save-1")
assert ck.get_latest_tag(ckdir) == "global_step1", ck.get_latest_tag(ckdir)

engine.train_batch(random_batch(8, seed=1))
# DSTPU_CHAOS (rank 1 only, skip=2) fails rank 1's shard writes HERE: the
# failure folds into the ok flag, every rank reaches the allgather, and
# `latest` must not advance onto the half-written tag
engine.save_checkpoint(ckdir)
ds.comm.barrier("after-save-2")
assert ck.get_latest_tag(ckdir) == "global_step1", ck.get_latest_tag(ckdir)

# no rank hung in the barrier AND the collectives still work after the
# failed save — the surviving-rank path is genuinely alive
loss = float(engine.train_batch(random_batch(8, seed=2))["loss"])
assert loss == loss, loss
print(f"RANK{rank} SURVIVED ok", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# tier-2 (round 8 budget): the 2-proc gloo category runs in tier2/chaos.sh;
# in-process multi-device engine training keeps gating tier-1
@pytest.mark.slow
def test_two_process_train_and_checkpoint(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    port = _free_port()
    ck = tmp_path / "ck"
    procs = []
    for pid in range(2):
        env = dict(**__import__("os").environ,
                   DSTPU_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   DSTPU_NUM_PROCESSES="2",
                   DSTPU_PROCESS_ID=str(pid),
                   DSTPU_TEST_REPO=REPO_ROOT,
                   DSTPU_TEST_CKPT=str(ck))
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-3000:]}"
        assert f"RANK{pid} OK" in out, out[-2000:]
    # both ranks computed the same loss (the collectives really synced)
    l0 = outs[0].split("last=")[1].split()[0]
    l1 = outs[1].split("last=")[1].split()[0]
    assert l0 == l1, (l0, l1)
    assert (ck / "mp").is_dir()

    # the 2-process job wrote SHARDED files (per-host pieces, no gather);
    # restore them here in the single-process 8-device suite — a
    # cross-process-count universal restore
    shard_files = list((ck / "mp").glob("model_states-shard*.npz"))
    assert len(shard_files) == 2, shard_files

    import deepspeed_tpu as ds
    from util import SimpleModel, random_batch
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "seed": 11,
    }
    engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                               example_batch=random_batch(8))
    engine.load_checkpoint(str(ck), tag="mp")
    assert int(engine.state.step) == 12
    m = engine.train_batch(random_batch(8, seed=100))
    assert float(m["loss"]) == float(m["loss"])   # finite, trains on

@pytest.mark.slow
def test_two_process_sharded_save_with_per_rank_failpoint(tmp_path):
    """ROADMAP gap (round-4): the REAL multi-host save path under a
    per-rank fault. Rank 1's shard writes fail mid-sharded-save (via
    DSTPU_CHAOS threaded into just that worker's env — the launcher now
    forwards DSTPU_* for exactly this); the PR-3 ok-flag/allgather path
    must keep every rank out of a hung barrier, leave `latest` on the
    previous tag, and quarantine the shared staging dir."""
    import os
    worker = tmp_path / "worker_failpoint.py"
    worker.write_text(WORKER_RANK_FAILPOINT)
    port = _free_port()
    ckdir = tmp_path / "ck"
    procs = []
    for pid in range(2):
        env = dict(os.environ,
                   DSTPU_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   DSTPU_NUM_PROCESSES="2",
                   DSTPU_PROCESS_ID=str(pid),
                   DSTPU_TEST_REPO=REPO_ROOT,
                   DSTPU_TEST_CKPT=str(ckdir))
        env.pop("JAX_PLATFORMS", None)
        env.pop("DSTPU_CHAOS", None)
        if pid == 1:
            # skip the 2 clean first-save shard files, then fail every
            # write of the second save — rank 0 stays fault-free
            env["DSTPU_CHAOS"] = "ckpt.write:raise:skip=2:times=100"
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-3000:]}"
        assert f"RANK{pid} SURVIVED ok" in out, out[-2000:]

    from deepspeed_tpu.runtime import checkpointing as ck
    assert ck.get_latest_tag(str(ckdir)) == "global_step1"
    assert ck.list_tags(str(ckdir)) == ["global_step1"]
    # the half-written tag was quarantined for forensics, not published
    assert any(n.startswith("global_step2") and
               n.endswith(ck.QUARANTINE_SUFFIX)
               for n in os.listdir(ckdir)), os.listdir(ckdir)


@pytest.mark.slow
@pytest.mark.xfail(
    not hasattr(__import__("jax"), "shard_map"),
    reason="pre-existing since PR 6, triaged round 13: the PP leg's SPMD "
           "pipeline (runtime/pipe/spmd.pipeline_apply) calls jax.shard_map, "
           "absent on 0.4.x jaxlib — the worker dies with AttributeError "
           "after the TP leg passes. Deliberately NOT routed through "
           "utils.jax_compat.shard_map: the 0.4.x legacy-shard_map adapter "
           "ABORTS inside XLA on SPMD-pipeline compiles (documented in "
           "jax_compat.py / PR 3). Cross-process pipeline coverage on this "
           "host lives in test_mpmd.py::test_two_process_mpmd_two_stage_run "
           "(the MPMD placement needs no shard_map); this leg un-xfails on "
           "jax>=0.5 hosts.",
    strict=False)
def test_two_process_tp_and_pp(tmp_path):
    """TP=2 and PP=2 over two REAL OS processes x 4 global devices (2 local
    each): the reference runs its whole feature matrix under
    launcher-spawned per-device processes (launcher/launch.py:129); before
    this test the jax.distributed path had only ever carried dp=2."""
    worker = tmp_path / "worker_tp_pp.py"
    worker.write_text(WORKER_TP_PP)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(**__import__("os").environ,
                   DSTPU_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   DSTPU_NUM_PROCESSES="2",
                   DSTPU_PROCESS_ID=str(pid),
                   DSTPU_TEST_REPO=REPO_ROOT)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=900)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-3000:]}"
        assert f"RANK{pid} OK" in out, out[-2000:]
    # both ranks must agree on both legs' losses (the collectives synced);
    # parse the tokens rather than the raw tail (stderr is merged, so
    # teardown log lines may follow the OK print)
    def tokens(out):
        return (out.split("tp=")[1].split()[0], out.split("pp=")[1].split()[0])
    assert tokens(outs[0]) == tokens(outs[1]), (outs[0][-200:], outs[1][-200:])


WORKER_SDC = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])

import deepspeed_tpu as ds

ds.init_distributed()
rank = ds.comm.get_rank()
assert ds.comm.get_world_size() == 2
assert len(jax.devices()) == 4          # dp=4: a real majority vote

sys.path.insert(0, os.path.join(os.environ["DSTPU_TEST_REPO"], "tests"))
from util import SimpleModel, random_batch
from deepspeed_tpu.runtime.sentinel import TrainingIntegrityError

config = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    "zero_optimization": {"stage": 0},      # replicated state: auditable
    "seed": 11,
    "steps_per_print": 1000,
    "integrity": {"audit_interval": 3},
}
engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                           example_batch=random_batch(8))
try:
    for i in range(6):
        engine.train_batch(random_batch(8, seed=i))
    print(f"RANK{rank} NO-DETECT", flush=True)
    sys.exit(1)
except TrainingIntegrityError as e:
    # mirror launch.py's rc mapping: the integrity contract is the rc
    print(f"RANK{rank} DETECTED {e}", flush=True)
    sys.exit(e.exit_code)
"""


@pytest.mark.slow
def test_two_process_sdc_bitflip_detected_and_attributed(tmp_path):
    """Acceptance (round 7): a silent bit-flip on ONE replica of a 2-proc
    x 2-device world is caught by the cross-replica audit within
    audit_interval steps, EVERY rank aborts with rc 118, and only the
    implicated rank's heartbeat record carries the SDC flag — in the
    operator's hostfile vocabulary, so the elastic agent can quarantine
    the right host."""
    worker = tmp_path / "worker_sdc.py"
    worker.write_text(WORKER_SDC)
    port = _free_port()
    hbdir = tmp_path / "hb"
    procs = []
    for pid in range(2):
        env = dict(**__import__("os").environ,
                   DSTPU_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   DSTPU_NUM_PROCESSES="2",
                   DSTPU_PROCESS_ID=str(pid),
                   DSTPU_TEST_REPO=REPO_ROOT,
                   DSTPU_HEARTBEAT_DIR=str(hbdir),
                   DSTPU_HEARTBEAT_HOST=f"w{pid}",
                   # keyed chaos: the flip lands on process 1 only
                   DSTPU_CHAOS="sentinel.sdc:flag:match=1")
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 118, \
            f"rank {pid} rc={p.returncode}:\n{out[-3000:]}"
        assert f"RANK{pid} DETECTED" in out, out[-2000:]
    from deepspeed_tpu.runtime import heartbeat as hb
    flagged = hb.flagged_ranks(str(hbdir))
    assert list(flagged) == [1], flagged       # only the implicated rank
    assert flagged[1]["host"] == "w1"
    assert "SDC" in flagged[1]["flags"]
