"""Transfer-fabric suite (round 18): one channel layer, one failure model.

Proves the `runtime/fabric/` contract every transport now rides — the
MPMD star, the disagg handoff, the process fleet:

- wire format: length-prefixed JSON+bytes frames with a CRC32 trailer;
  a flipped bit ANYWHERE raises ``FrameCorrupt`` (an ``OSError``) at
  receipt, including one injected on-wire by the ``net.corrupt``
  failpoint AFTER the trailer was computed;
- generation fencing: data frames from a stale epoch are dropped at
  receipt; control frames bypass the fence; a mid-stream welcome bumps
  the receiver's generation;
- bounded jittered reconnect: ``net.connect`` fires per dial attempt;
  a mid-stream ``OSError`` (``net.partition``, peer reset) runs the
  redial ladder and resumes with a FRESH generation from the hub's
  welcome; exhausted attempts raise ``ChannelClosed``;
- per-recv deadlines raise ``ChannelTimeout``; ``recv(timeout=0)`` is a
  genuine poll — a frame already on the wire IS delivered (regression:
  the serve loop drains commands between engine steps this way);
- bounded write locks starve into ``WriteLockStarved`` instead of
  wedging the caller on a peer stuck mid-read.

Everything here is pure-socket/pure-thread — no JAX, no engines — so
the whole file runs in a few seconds of tier-1 wall clock.
"""

import socket
import threading
import time

import pytest

from deepspeed_tpu.runtime.fabric import (ChannelClosed, ChannelTimeout,
                                          FrameCorrupt, HubConn,
                                          LocalEndpoint, RedialPolicy,
                                          SocketEndpoint, WriteLockStarved,
                                          pack_frame, read_frame,
                                          write_frame)
from deepspeed_tpu.testing import chaos


# --------------------------------------------------------------------------
# frame codec


def _pipe():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_frame_roundtrip():
    a, b = _pipe()
    try:
        write_frame(a, {"cmd": "x", "seq": 7}, b"\x00\x01payload\xff")
        meta, payload = read_frame(b)
        assert meta == {"cmd": "x", "seq": 7}
        assert payload == b"\x00\x01payload\xff"
        write_frame(a, {"empty": True})          # zero-length payload leg
        meta, payload = read_frame(b)
        assert meta == {"empty": True} and payload == b""
    finally:
        a.close(); b.close()


def test_frame_bitflip_is_peer_fatal():
    raw = bytearray(pack_frame({"seq": 1}, b"abcdef"))
    raw[-6] ^= 0x10                              # one bit, inside the payload
    a, b = _pipe()
    try:
        a.sendall(bytes(raw))
        with pytest.raises(FrameCorrupt) as ei:
            read_frame(b)
        assert isinstance(ei.value, OSError)     # callers treat it as a dead peer
    finally:
        a.close(); b.close()


@pytest.mark.parametrize("payload", [b"block-bytes", b""])
def test_net_corrupt_flips_on_wire(payload):
    """net.corrupt injects AFTER the CRC is computed — proven caught at
    the receiving end, not silently absorbed by a recomputed trailer."""
    chaos.arm("net.corrupt", mode="flag")
    a, b = _pipe()
    try:
        write_frame(a, {"seq": 1}, payload, key="spoke-0")
        with pytest.raises(FrameCorrupt):
            read_frame(b)
    finally:
        a.close(); b.close()


def test_net_corrupt_respects_match_key():
    chaos.arm("net.corrupt", mode="flag", match="spoke-1")
    a, b = _pipe()
    try:
        write_frame(a, {"seq": 1}, b"x", key="spoke-0")   # other spoke: clean
        assert read_frame(b)[1] == b"x"
    finally:
        a.close(); b.close()


# --------------------------------------------------------------------------
# local backend


def test_local_fifo_and_nonblocking_poll():
    ep = LocalEndpoint("loop")
    for i in range(3):
        ep.send({"seq": i}, i)
    assert [ep.recv(timeout=0.0)[0]["seq"] for _ in range(3)] == [0, 1, 2]
    with pytest.raises(ChannelTimeout):
        ep.recv(timeout=0.0)                     # empty queue, surfaced now
    ep.close()
    with pytest.raises(ChannelClosed):
        ep.recv(timeout=0.0)


def test_local_fence_drops_stale_data_keeps_control():
    ep = LocalEndpoint("loop", fence=True)
    ep.send({"seq": "stale"})                    # stamped gen=0
    ep.send({"cmd": "park"})                     # control: bypasses the fence
    ep.generation = 1                            # epoch bump (resync)
    ep.send({"seq": "fresh"})                    # stamped gen=1
    metas = [ep.recv(timeout=0.0)[0] for _ in range(2)]
    assert [m.get("cmd", m.get("seq")) for m in metas] == ["park", "fresh"]
    with pytest.raises(ChannelTimeout):
        ep.recv(timeout=0.0)                     # the stale frame is GONE


def test_local_chaos_surface():
    ep = LocalEndpoint("loop")
    chaos.arm("net.send")
    with pytest.raises(chaos.ChaosError):
        ep.send({"seq": 0})
    chaos.disarm()
    ep.send({"seq": 0})
    chaos.arm("net.recv")
    with pytest.raises(chaos.ChaosError):
        ep.recv(timeout=0.0)


# --------------------------------------------------------------------------
# socket backend — a minimal hub (per-ident epochs, recorded frames)


class MiniHub:
    def __init__(self):
        self.srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(8)
        self.addr = self.srv.getsockname()
        self.epochs = {}
        self.conns = {}
        self.frames = []
        self.hellos = []
        self._mu = threading.Lock()
        self._stop = threading.Event()
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stop.is_set():
            try:
                sock, _ = self.srv.accept()
            except OSError:
                return
            if self._stop.is_set():              # raced close(): a blocked
                sock.close()                     # accept holds the fd alive
                continue
            threading.Thread(target=self._serve, args=(sock,),
                             daemon=True).start()

    def _serve(self, sock):
        try:
            hello, _ = read_frame(sock)
        except OSError:
            sock.close()
            return
        ident = hello.get("ident", "?")
        with self._mu:
            self.hellos.append(hello)
            self.epochs[ident] = self.epochs.get(ident, 0) + 1
            conn = HubConn(sock, ident, gen=self.epochs[ident])
            self.conns[ident] = conn
        conn.welcome()
        while True:
            try:
                meta, payload = read_frame(sock)
            except OSError:
                break
            with self._mu:
                self.frames.append((ident, meta, payload))
        conn.close()

    @staticmethod
    def _sever(conn):
        # shutdown first: close() alone leaves a reader blocked in recv
        # holding the fd, and no FIN/RST ever reaches the spoke
        try:
            conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        conn.close()

    def conn(self, ident, timeout=5.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mu:
                if ident in self.conns:
                    return self.conns[ident]
            time.sleep(0.01)
        raise AssertionError(f"no hub connection for {ident}")

    def drop(self, ident):
        self._sever(self.conn(ident))

    def wait_frames(self, n, timeout=5.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mu:
                if len(self.frames) >= n:
                    return list(self.frames)
            time.sleep(0.01)
        with self._mu:
            return list(self.frames)

    def close(self):
        self._stop.set()
        try:
            self.srv.shutdown(socket.SHUT_RDWR)  # wake a blocked accept
        except OSError:
            pass
        try:
            self.srv.close()
        except OSError:
            pass
        with self._mu:
            conns = list(self.conns.values())
        for c in conns:
            self._sever(c)


@pytest.fixture()
def hub():
    h = MiniHub()
    yield h
    h.close()


def _spoke(hub, ident="spoke-0", **kw):
    kw.setdefault("redial", RedialPolicy(attempts=3, base=0.01, cap=0.05,
                                         dial_timeout=2.0))
    return SocketEndpoint(tuple(hub.addr), ident, connect_timeout=5.0, **kw)


def test_dial_retries_through_net_connect(hub):
    chaos.arm("net.connect", times=2)            # first two dials refused
    ep = _spoke(hub)
    try:
        assert ep.generation == 1                # handed out by the welcome
        assert len(chaos.fired("net.connect")) == 2
        ep.send({"seq": 0}, b"ok")
        ident, meta, payload = hub.wait_frames(1)[0]
        assert (ident, payload) == ("spoke-0", b"ok")
        assert meta["gen"] == 1                  # frames stamped with the epoch
    finally:
        ep.close()


def test_recv_deadline_raises_channel_timeout(hub):
    ep = _spoke(hub)
    try:
        t0 = time.monotonic()
        with pytest.raises(ChannelTimeout):
            ep.recv(timeout=0.2)
        assert time.monotonic() - t0 < 2.0
    finally:
        ep.close()


def test_recv_zero_timeout_delivers_inflight_frame(hub):
    """Regression: timeout=0 is a POLL, not a no-op — a frame already on
    the wire must come out (the serve loop drains commands this way)."""
    ep = _spoke(hub)
    try:
        hub.conn("spoke-0").send({"cmd": "serve", "rid": 7})
        deadline = time.monotonic() + 2.0
        while True:
            try:
                meta, _ = ep.recv(timeout=0.0)   # never a positive timeout
                break
            except ChannelTimeout:
                assert time.monotonic() < deadline, \
                    "in-flight frame never delivered via timeout=0 poll"
                time.sleep(0.01)
        assert meta["rid"] == 7
    finally:
        ep.close()


def test_partition_redials_into_fresh_generation(hub):
    """net.partition mid-send runs the redial ladder; the re-sent frame
    carries the NEW generation (the maybe-delivered original is fenced)."""
    chaos.arm("net.partition", times=1)
    ep = _spoke(hub)
    try:
        assert ep.generation == 1
        ep.send({"seq": 0}, b"after-heal")
        assert ep.generation == 2                # fresh epoch from re-welcome
        frames = hub.wait_frames(1)
        assert frames[-1][1]["gen"] == 2
        assert len(hub.hellos) == 2              # one redial happened
    finally:
        ep.close()


def test_hub_restart_spoke_redials_new_generation(hub):
    """A dropped hub connection (restarted peer) is NOT death: the spoke
    re-dials into a fresh epoch and traffic resumes."""
    ep = _spoke(hub)
    try:
        ep.send({"seq": 0})
        hub.wait_frames(1)
        hub.drop("spoke-0")
        # TCP may buffer one send into the dead socket; keep sending until
        # a frame lands on the NEW epoch
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            ep.send({"seq": 1})
            if any(m["gen"] == 2 for _, m, _ in hub.wait_frames(2, 0.2)):
                break
            time.sleep(0.05)
        assert ep.generation == 2
        assert any(m["gen"] == 2 for _, m, _ in hub.wait_frames(2))
    finally:
        ep.close()


def test_stale_generation_frame_dropped_at_receipt(hub):
    ep = _spoke(hub)
    try:
        conn = hub.conn("spoke-0")
        conn.send({"seq": "stale", "gen": 0})    # from a dead epoch
        conn.send({"seq": "fresh", "gen": 1})
        meta, _ = ep.recv(timeout=2.0)
        assert meta["seq"] == "fresh"            # the stale frame never surfaced
    finally:
        ep.close()


def test_midstream_welcome_bumps_generation(hub):
    ep = _spoke(hub)
    try:
        conn = hub.conn("spoke-0")
        conn.send({"cmd": "welcome", "gen": 5})  # hub-side epoch bump
        conn.send({"seq": 1, "gen": 5})
        meta, _ = ep.recv(timeout=2.0)
        assert meta["seq"] == 1 and ep.generation == 5
    finally:
        ep.close()


def test_write_lock_starved_is_oserror_not_wedge(hub):
    ep = _spoke(hub)
    try:
        assert ep._wlock.acquire(timeout=1.0)
        try:
            t0 = time.monotonic()
            with pytest.raises(WriteLockStarved) as ei:
                ep.send({"seq": 0}, lock_timeout=0.1)
            assert isinstance(ei.value, OSError)
            assert time.monotonic() - t0 < 2.0
            assert ep.generation == 1            # starvation never redials
        finally:
            ep._wlock.release()
    finally:
        ep.close()


def test_redial_exhaustion_raises_channel_closed(hub):
    ep = _spoke(hub, redial=RedialPolicy(attempts=1, base=0.01,
                                         dial_timeout=0.3))
    hub.close()                                  # the hub is GONE, not restarting
    with pytest.raises(ChannelClosed):
        for _ in range(20):
            ep.send({"seq": 0}, b"x" * 4096)
            time.sleep(0.02)
    ep.close()
