"""graftlint unit tests: one true-positive and one true-negative fixture
per rule (TPU001–TPU008, TPU010), plus suppression, baseline and self-lint
tests.

Fixtures are source snippets linted in-memory through a temp file — the
linter is AST-only, so none of this imports JAX or touches devices.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from deepspeed_tpu.analysis import Baseline, RULES, Severity, lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_snippet(tmp_path, source, select=None):
    f = tmp_path / "snippet.py"
    f.write_text(textwrap.dedent(source))
    return lint_paths([str(f)], select=select, root=str(tmp_path))


def codes(findings, gating_only=True):
    return [f.rule for f in findings if not gating_only or f.gating]


# --------------------------------------------------------------------- TPU001

def test_tpu001_positive_traced_item(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def train_step(state, batch):
            loss = jnp.mean(batch)
            print(loss.item())
            return state
    """)
    assert "TPU001" in codes(findings)
    (f,) = [f for f in findings if f.rule == "TPU001"]
    assert f.severity == Severity.ERROR
    assert f.symbol == "train_step"


def test_tpu001_positive_hot_path_float(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp

        class Engine:
            def train_batch(self, batch):
                metrics = self._step(batch)
                return float(metrics["loss"])
    """)
    (f,) = [f for f in findings if f.rule == "TPU001"]
    assert f.severity == Severity.WARNING


def test_tpu001_negative(tmp_path):
    # device_get is the sanctioned explicit transfer on the host step
    # path; float() of an already-pulled dict and of python config values
    # is free
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def train_step(state, batch):
            return state + jnp.mean(batch)

        class Engine:
            def train_batch(self, batch):
                metrics = self._step(batch)
                host = jax.device_get(metrics)
                gas = self.config.gas
                return float(host["loss"]), float(gas)
    """)
    assert "TPU001" not in codes(findings, gating_only=False)


# --------------------------------------------------------------------- TPU002

def test_tpu002_positive_jit_in_loop(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def sweep(model, batches):
            for b in batches:
                out = jax.jit(lambda x: model(x))(b)
    """)
    hits = [f for f in findings if f.rule == "TPU002"]
    assert hits and hits[0].severity == Severity.ERROR


def test_tpu002_positive_bound_method(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def init_state(opt, params):
            return jax.jit(opt.init)(params)
    """)
    hits = [f for f in findings if f.rule == "TPU002"]
    assert hits and hits[0].severity == Severity.WARNING


def test_tpu002_negative(tmp_path):
    # jit over a stable module-level fn does not retrace (cache keyed by
    # function identity), and a hoisted jitted callable is the idiom
    findings = lint_snippet(tmp_path, """
        import jax

        def _step(state, batch):
            return state

        train_step = jax.jit(_step, donate_argnums=(0,))

        def run(state, batches):
            for b in batches:
                state = train_step(state, b)
            return jax.jit(_step)(state, batches[0])
    """)
    assert "TPU002" not in codes(findings)


# --------------------------------------------------------------------- TPU003

def test_tpu003_positive(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        class Engine:
            def _make_step(self):
                @jax.jit
                def step(state, batch):
                    self.calls = self.calls + 1
                    return state
                return step
    """)
    hits = [f for f in findings if f.rule == "TPU003"]
    assert hits and "self.calls" in hits[0].message


def test_tpu003_negative(tmp_path):
    # locals and returned state are pure; building the step fn OUTSIDE the
    # traced region may mutate self freely
    findings = lint_snippet(tmp_path, """
        import jax

        class Engine:
            def _make_step(self):
                self.built = True

                @jax.jit
                def step(state, batch):
                    acc = state + 1
                    return acc
                return step
    """)
    assert "TPU003" not in codes(findings)


# --------------------------------------------------------------------- TPU004

def test_tpu004_positive_f64(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return x.astype(jnp.float64)
    """)
    hits = [f for f in findings if f.rule == "TPU004"]
    assert hits and hits[0].severity == Severity.ERROR


def test_tpu004_positive_loss_downcast(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(logits, batch):
            loss = jnp.mean(logits)
            return loss.astype(jnp.bfloat16)
    """)
    hits = [f for f in findings if f.rule == "TPU004"]
    assert hits and hits[0].severity == Severity.WARNING


def test_tpu004_negative(tmp_path):
    # f32 islands for loss/grad-norm math are the convention, and casting
    # activations (not losses) to the compute dtype is fine
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x, loss_scale):
            h = x.astype(jnp.bfloat16)
            loss = jnp.mean(h).astype(jnp.float32)
            return loss * loss_scale
    """)
    assert "TPU004" not in codes(findings)


# --------------------------------------------------------------------- TPU005

def test_tpu005_positive(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def make_step():
            def train_step(state, batch):
                return state
            return jax.jit(train_step)
    """)
    hits = [f for f in findings if f.rule == "TPU005"]
    assert hits and "donate" in hits[0].message


def test_tpu005_negative(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def make_step():
            def train_step(state, batch):
                return state
            return jax.jit(train_step, donate_argnums=(0,))

        def make_eval():
            def eval_step(params, batch):
                return batch
            return jax.jit(eval_step)
    """)
    assert "TPU005" not in codes(findings)


# --------------------------------------------------------------------- TPU006

def test_tpu006_positive(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(grads):
            overflow = jnp.any(jnp.isnan(grads))
            if overflow:
                return grads * 0
            return grads
    """)
    hits = [f for f in findings if f.rule == "TPU006"]
    assert hits and "overflow" in hits[0].message


def test_tpu006_negative(tmp_path):
    # static python config branches and `is None` guards are fine under
    # trace; jnp.where is the in-graph select
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(grads, clip=0.0, mask=None):
            if clip > 0:
                grads = grads * clip
            if mask is not None:
                grads = jnp.where(mask, grads, 0.0)
            nan = jnp.any(jnp.isnan(grads))
            return jnp.where(nan, jnp.zeros_like(grads), grads)
    """)
    assert "TPU006" not in codes(findings)


# --------------------------------------------------------------------- TPU007

def test_tpu007_positive_double_use(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def sample(rng, shape):
            a = jax.random.normal(rng, shape)
            b = jax.random.uniform(rng, shape)
            return a + b
    """)
    hits = [f for f in findings if f.rule == "TPU007"]
    assert hits and "rng" in hits[0].message


def test_tpu007_positive_loop_invariant(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def sample(rng, n):
            outs = []
            for i in range(n):
                outs.append(jax.random.normal(rng, (4,)))
            return outs
    """)
    hits = [f for f in findings if f.rule == "TPU007"]
    assert hits and "loop" in hits[0].message


def test_tpu007_negative(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def sample(rng, n):
            outs = []
            for i in range(n):
                rng, sub = jax.random.split(rng)
                outs.append(jax.random.normal(sub, (4,)))
            r1, r2 = jax.random.split(rng)
            return jax.random.normal(r1), jax.random.uniform(r2)
    """)
    assert "TPU007" not in codes(findings)


# --------------------------------------------------------------------- TPU008

def test_tpu008_positive_trailing_none(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def constrain(x):
            return lax.with_sharding_constraint(x, P("data", None))
    """)
    hits = [f for f in findings if f.rule == "TPU008"]
    assert hits and "trailing None" in hits[0].message
    assert hits[0].severity == Severity.WARNING


def test_tpu008_positive_single_name_tuple(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(mesh, x):
            return jax.device_put(x, NamedSharding(mesh, P(("model",))))
    """)
    hits = [f for f in findings if f.rule == "TPU008"]
    assert hits and "single-name tuple" in hits[0].message


def test_tpu008_negative_canonical_specs(tmp_path):
    # canonical forms — bare names, interior None, multi-axis tuples — and
    # specs built elsewhere (a variable the checker can't see into) pass
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def constrain(mesh, x, spec):
            a = lax.with_sharding_constraint(x, P("data"))
            b = lax.with_sharding_constraint(x, P(None, "model"))
            c = lax.with_sharding_constraint(x, P(("data", "expert")))
            d = lax.with_sharding_constraint(x, spec)
            e = jax.device_put(x, NamedSharding(mesh, P()))
            return a, b, c, d, e
    """)
    assert "TPU008" not in codes(findings, gating_only=False)


def test_tpu008_ignores_specs_outside_constraint_sites(tmp_path):
    # a non-canonical P literal that never reaches a constraint site is
    # someone's intermediate value — not this rule's business
    findings = lint_snippet(tmp_path, """
        from jax.sharding import PartitionSpec as P

        def build():
            return P("data", None)
    """)
    assert "TPU008" not in codes(findings, gating_only=False)


def test_tpu008_constant_resolution_same_module(tmp_path):
    """Round-10 depth: a module-level ``SPEC = P(...)`` read at a
    constraint site is checked like the inline literal — ONE finding,
    anchored at the definition (the fix location), however many sites
    read it. Canonical constants stay silent."""
    findings = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import PartitionSpec as P

        DRIFTY = P("data", None)
        CANON = P("data")

        def use(x):
            a = lax.with_sharding_constraint(x, DRIFTY)
            b = lax.with_sharding_constraint(x, DRIFTY)
            c = lax.with_sharding_constraint(x, CANON)
            return a, b, c
    """)
    hits = [f for f in findings if f.rule == "TPU008"]
    assert len(hits) == 1, hits
    assert "trailing None" in hits[0].message and "DRIFTY" in hits[0].message
    assert hits[0].line == 5          # the assignment, not the use sites


def test_tpu008_constant_resolution_cross_module(tmp_path):
    """The constant lives in another module of the lint run: resolution
    follows the import map (the TPU012 machinery); the finding anchors at
    the USE site and names the definition."""
    import textwrap
    from deepspeed_tpu.analysis import lint_paths
    (tmp_path / "specs.py").write_text(textwrap.dedent("""
        from jax.sharding import PartitionSpec as P
        QUEUE_SPEC = P(("expert",))
    """))
    (tmp_path / "user.py").write_text(textwrap.dedent("""
        from jax import lax
        from specs import QUEUE_SPEC

        def use(x):
            return lax.with_sharding_constraint(x, QUEUE_SPEC)
    """))
    findings = lint_paths([str(tmp_path / "specs.py"),
                           str(tmp_path / "user.py")], root=str(tmp_path))
    hits = [f for f in findings if f.rule == "TPU008"]
    assert len(hits) == 1, hits
    assert hits[0].path == "user.py"
    assert "specs.py:3" in hits[0].message
    assert "single-name tuple" in hits[0].message


def test_tpu008_constant_negative_shadowed_and_poisoned(tmp_path):
    """A locally-bound name shadows the module constant (the value is the
    caller's contract), and a REASSIGNED constant is poisoned — both stay
    silent rather than guess."""
    findings = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import PartitionSpec as P

        SPEC = P("data", None)
        FLIPPY = P("data", None)
        FLIPPY = P("model")

        def shadowed(x, SPEC):
            return lax.with_sharding_constraint(x, SPEC)

        def poisoned(x):
            return lax.with_sharding_constraint(x, FLIPPY)
    """)
    assert "TPU008" not in codes(findings, gating_only=False)


def test_tpu008_constant_fix_rewrites_definition(tmp_path):
    """--fix canonicalizes the CONSTANT's P(...) literal (same-module
    findings anchor there), idempotently."""
    from deepspeed_tpu.analysis.fixes import fix_paths
    src = textwrap.dedent("""
        from jax import lax
        from jax.sharding import PartitionSpec as P

        SPEC = P("data", None)

        def use(x):
            return lax.with_sharding_constraint(x, SPEC)
    """)
    f = tmp_path / "mod.py"
    f.write_text(src)
    n, changed = fix_paths([str(f)], root=str(tmp_path))
    assert n == 1 and changed == [str(f)]
    assert 'SPEC = P("data")' in f.read_text()
    n2, _ = fix_paths([str(f)], root=str(tmp_path))
    assert n2 == 0                      # idempotent


# --------------------------------------------------------------------- TPU009

def test_tpu009_positive_bf16_carry_widened(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp
        from jax import lax

        def run(xs):
            def body(c, x):
                c = (c + x).astype(jnp.float32)
                return c, x
            init = jnp.zeros((8,), jnp.bfloat16)
            return lax.scan(body, init, xs)
    """)
    hits = [f for f in findings if f.rule == "TPU009"]
    assert hits and "carry" in hits[0].message
    assert hits[0].severity == Severity.WARNING


def test_tpu009_positive_inline_init_f32_wrap(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        from jax import lax

        def run(xs):
            def body(c, x):
                return jnp.float32(c + x), x
            return lax.scan(body, jnp.zeros((8,), jnp.bfloat16), xs)
    """)
    assert [f.rule for f in findings if f.rule == "TPU009"]


def test_tpu009_negative_carry_cast_back(tmp_path):
    # the CORRECT idiom: accumulate in an f32 island, carry bf16
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        from jax import lax

        def run(xs):
            def body(c, x):
                acc = c.astype(jnp.float32) + x
                return acc.astype(jnp.bfloat16), x
            init = jnp.zeros((8,), jnp.bfloat16)
            return lax.scan(body, init, xs)
    """)
    assert "TPU009" not in codes(findings, gating_only=False)


def test_tpu009_negative_f32_scan_untouched(tmp_path):
    # an intentionally-f32 scan (init shows no 16-bit evidence) never fires
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        from jax import lax

        def run(xs):
            def body(c, x):
                return c.astype(jnp.float32) + x, x
            init = jnp.zeros((8,), jnp.float32)
            return lax.scan(body, init, xs)
    """)
    assert "TPU009" not in codes(findings, gating_only=False)


# --------------------------------------------- suppressions / baseline / CLI

def test_inline_suppression_same_line(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(state, x):
            return float(x * state)  # graftlint: disable=TPU001
    """)
    # the finding is still produced (and counted) but marked + non-gating
    hits = [f for f in findings if f.rule == "TPU001"]
    assert not hits or all(f.suppressed and not f.gating for f in hits)


def test_inline_suppression_preceding_line(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def init_state(opt, params):
            # graftlint: disable=TPU002 (init-time: one trace)
            return jax.jit(opt.init)(params)
    """)
    hits = [f for f in findings if f.rule == "TPU002"]
    assert hits and all(f.suppressed for f in hits)


def test_file_wide_suppression(tmp_path):
    findings = lint_snippet(tmp_path, """
        # graftlint: disable-file=TPU002
        import jax

        def a(opt, p):
            return jax.jit(opt.init)(p)

        def b(opt, p):
            return jax.jit(opt.update)(p)
    """)
    hits = [f for f in findings if f.rule == "TPU002"]
    assert len(hits) == 2 and all(f.suppressed for f in hits)


def test_baseline_roundtrip(tmp_path):
    src = """
        import jax

        def init_state(opt, params):
            return jax.jit(opt.init)(params)
    """
    findings = lint_snippet(tmp_path, src)
    gating = [f for f in findings if f.gating]
    assert gating
    bl_path = str(tmp_path / ".graftlint.json")
    Baseline.write(bl_path, gating)

    # same findings re-linted against the baseline stop gating
    findings2 = lint_snippet(tmp_path, src)
    bl = Baseline.load(bl_path)
    bl.apply(findings2)
    assert all(f.baselined and not f.gating for f in findings2
               if f.rule == "TPU002")
    assert not bl.stale_entries()

    # baseline matching survives pure line-number churn
    findings3 = lint_snippet(tmp_path, "\n\n\n" + textwrap.dedent(src))
    bl = Baseline.load(bl_path)
    bl.apply(findings3)
    assert all(f.baselined for f in findings3 if f.rule == "TPU002")

    # fixing the code strands the entry -> reported stale
    clean = lint_snippet(tmp_path, """
        import jax

        def nothing():
            return 1
    """)
    bl = Baseline.load(bl_path)
    bl.apply(clean)
    assert len(bl.stale_entries()) == 1


def test_baseline_entries_carry_justification():
    """Every checked-in baseline entry must say WHY it is accepted."""
    path = os.path.join(REPO, ".graftlint.json")
    with open(path) as f:
        data = json.load(f)
    for e in data["findings"]:
        assert e.get("justification"), e
        assert "TODO" not in e["justification"], e


def test_rule_registry_complete():
    assert {"TPU001", "TPU002", "TPU003", "TPU004", "TPU005", "TPU006",
            "TPU007", "TPU008", "TPU009", "TPU010", "TPU011", "TPU012",
            "TPU013"} <= set(RULES)
    for code, rule in RULES.items():
        assert rule.summary and rule.name, code


# --------------------------------------------------------------------- TPU010

def test_tpu010_positive_unscoped_pallas_call(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import pallas as pl

        def launch(x, kernel, spec):
            return pl.pallas_call(kernel, out_shape=spec)(x)
    """)
    (f,) = [f for f in findings if f.rule == "TPU010"]
    assert f.severity == Severity.WARNING
    assert f.symbol == "launch"
    assert "named_scope" in f.message


def test_tpu010_positive_scope_not_lexical(tmp_path):
    """A named_scope in the CALLER does not cover the launching function."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import pallas as pl

        def _launch(x, kernel, spec):
            return pl.pallas_call(kernel, out_shape=spec)(x)

        def entry(x, kernel, spec):
            with jax.named_scope("my_kernel"):
                return _launch(x, kernel, spec)
    """)
    assert "TPU010" in codes(findings)


def test_tpu010_negative_with_scope(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import pallas as pl

        def launch(x, kernel, spec):
            with jax.named_scope("my_kernel"):
                return pl.pallas_call(kernel, out_shape=spec)(x)

        @jax.named_scope("decorated_kernel")
        def launch2(x, kernel, spec):
            return pl.pallas_call(kernel, out_shape=spec)(x)
    """)
    assert "TPU010" not in codes(findings)


# ------------------------------------------- TPU011 (divergent collective)

def test_tpu011_positive_direct_rank_guarded_barrier(tmp_path):
    """The pre-PR-3 sharded-save hang shape: a host collective only rank 0
    dispatches."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def publish(tag):
            if jax.process_index() == 0:
                multihost_utils.sync_global_devices("publish-" + tag)
    """)
    (f,) = [f for f in findings if f.rule == "TPU011"]
    assert f.severity == Severity.ERROR
    assert "rank guard" in f.message
    assert f.symbol == "publish"


def test_tpu011_positive_transitive_one_level(tmp_path):
    """Acceptance: the guard sits one call away from the collective."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def _publish():
            multihost_utils.sync_global_devices("publish")

        def save(tag):
            if jax.process_index() == 0:
                _publish()
    """)
    (f,) = [f for f in findings if f.rule == "TPU011"]
    assert "_publish" in f.message and "sync_global_devices" in f.message
    assert f.symbol == "save"


def test_tpu011_positive_cross_module(tmp_path):
    """The call graph resolves the guarded call into ANOTHER module of
    the same lint run."""
    (tmp_path / "helpers.py").write_text(textwrap.dedent("""
        from jax.experimental import multihost_utils

        def publish():
            multihost_utils.sync_global_devices("publish")
    """))
    (tmp_path / "saver.py").write_text(textwrap.dedent("""
        import jax
        from helpers import publish

        def save(tag):
            if jax.process_index() == 0:
                publish()
    """))
    findings = lint_paths([str(tmp_path)], root=str(tmp_path))
    hits = [f for f in findings if f.rule == "TPU011"]
    assert any(f.path == "saver.py" and "publish" in f.message
               for f in hits)


def test_tpu011_positive_rank_guarded_early_exit(tmp_path):
    """`if rank != 0: return` ahead of a barrier: the exiting ranks never
    reach the rendezvous."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def save(rank, tag):
            if rank != 0:
                return
            write_marker(tag)
            multihost_utils.sync_global_devices("publish")
    """)
    (f,) = [f for f in findings if f.rule == "TPU011"]
    assert "early exit" in f.message


def test_tpu011_positive_lax_collective_in_guard(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax

        def reduce(x, rank):
            if rank == 0:
                return lax.psum(x, "data")
            return x
    """)
    assert "TPU011" in codes(findings)


def test_tpu011_positive_boolean_local_rank_guard(tmp_path):
    """Round-6 depth: the guard hides behind a boolean local
    (``is_master = rank == 0``) — the spelling the name-match missed."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def publish(tag, rank):
            is_master = rank == 0
            if is_master:
                multihost_utils.sync_global_devices("publish-" + tag)
    """)
    (f,) = [f for f in findings if f.rule == "TPU011"]
    assert f.symbol == "publish"


def test_tpu011_positive_boolean_local_from_probe_call(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def save():
            lead = jax.process_index() == 0
            if lead:
                multihost_utils.sync_global_devices("save")
    """)
    assert "TPU011" in codes(findings)


def test_tpu011_positive_boolean_local_chain_and_early_exit(tmp_path):
    """Alias chains resolve to a fixpoint, and a boolean-local guard on
    an early return ahead of a collective is the hang shape too."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def save(rank):
            is_master = rank == 0
            should_write = is_master
            if not should_write:
                return None
            multihost_utils.sync_global_devices("save")
    """)
    (f,) = [f for f in findings if f.rule == "TPU011"]
    assert f.symbol == "save"


def test_tpu011_negative_boolean_local_from_world_size(tmp_path):
    """World-size booleans evaluate identically on every rank — the
    sanctioned ``comm.barrier`` idiom must survive the new depth."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def barrier(name):
            is_dist = jax.process_count() > 1
            if is_dist:
                multihost_utils.sync_global_devices(name)
    """)
    assert "TPU011" not in codes(findings, gating_only=False)


def test_tpu011_negative_rank_derived_value_is_not_a_guard(tmp_path):
    """A rank-derived VALUE (an f-string, arithmetic) is not a
    rank-divergent predicate — taint without boolean-ness must not flag."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def save(rank):
            prefix = f"rank-{rank}"
            if prefix:
                multihost_utils.sync_global_devices("all-ranks-save")
    """)
    assert "TPU011" not in codes(findings, gating_only=False)


def test_tpu011_negative_guard_without_collective(tmp_path):
    """The SANCTIONED shape (checkpointing.py): rank-0-only host work,
    then an UNGUARDED barrier every rank reaches."""
    findings = lint_snippet(tmp_path, """
        import os
        import jax
        from jax.experimental import multihost_utils

        def save(tag, stage_dir):
            if jax.process_index() == 0 and os.path.isdir(stage_dir):
                os.rmdir(stage_dir)
            multihost_utils.sync_global_devices("stage-" + tag)
    """)
    assert "TPU011" not in codes(findings, gating_only=False)


def test_tpu011_negative_world_size_guard(tmp_path):
    """comm.barrier's own idiom: process_count() evaluates the SAME on
    every rank — not a divergence guard."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def barrier(name):
            if jax.process_count() > 1:
                multihost_utils.sync_global_devices(name)
    """)
    assert "TPU011" not in codes(findings, gating_only=False)


def test_tpu011_negative_guarded_logging_only(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def report(msg):
            if jax.process_index() == 0:
                print(msg)
    """)
    assert "TPU011" not in codes(findings, gating_only=False)


def test_tpu011_guarded_collective_does_not_propagate(tmp_path):
    """A collective ALREADY rank-guarded inside a callee is conditional
    there — calling that callee under another guard must not re-flag the
    call site (one finding, at the inner guard)."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def inner():
            if jax.process_index() == 0:
                multihost_utils.sync_global_devices("x")

        def outer(rank):
            if rank == 0:
                inner()
    """)
    hits = [f for f in findings if f.rule == "TPU011"]
    assert len(hits) == 1 and hits[0].symbol == "inner"


def test_tpu011_mutual_recursion_is_order_independent(tmp_path):
    """Reachability through a call cycle must not depend on which guarded
    call the linter analyzes first (incomplete cycle-truncated results
    must never be memoized)."""
    body = """
        import jax
        from jax.experimental import multihost_utils

        def a(n):
            multihost_utils.sync_global_devices("x")
            if n:
                b(n - 1)

        def b(n):
            if n:
                a(n - 1)

        {caller1}

        {caller2}
    """
    call_a = ("def use_a(rank, n):\n"
              "            if rank == 0:\n"
              "                a(n)")
    call_b = ("def use_b(rank, n):\n"
              "            if rank == 0:\n"
              "                b(n)")
    for first, second in ((call_a, call_b), (call_b, call_a)):
        findings = lint_snippet(
            tmp_path, body.format(caller1=first, caller2=second))
        guarded = {f.symbol for f in findings if f.rule == "TPU011"}
        assert {"use_a", "use_b"} <= guarded, guarded


# --------------------------------------------- TPU012 (mesh-axis validity)

def test_tpu012_positive_lexical_context(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax

        def run(xs, mesh):
            def inner(x):
                return lax.psum(x, "model")
            return jax.shard_map(inner, mesh=mesh, in_specs=None,
                                 out_specs=None, axis_names=("data",))(xs)
    """)
    (f,) = [f for f in findings if f.rule == "TPU012"]
    assert f.severity == Severity.ERROR
    assert "'model'" in f.message and "'data'" in f.message


def test_tpu012_positive_interprocedural(tmp_path):
    """The collective sits in a helper CALLED from the shard_map body —
    context resolves through the call graph."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax

        def _reduce(x):
            return lax.psum(x, "expert")

        def body(x):
            return _reduce(x)

        def run(xs, mesh):
            return jax.shard_map(body, mesh=mesh, in_specs=None,
                                 out_specs=None, axis_names=("data",))(xs)
    """)
    (f,) = [f for f in findings if f.rule == "TPU012"]
    assert f.symbol == "_reduce"


def test_tpu012_positive_unknown_axis_typo(tmp_path):
    """No context reaches the function: the axis is checked against the
    project-wide universe (typo class)."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax
        from jax.sharding import Mesh

        MESH_AXES = ("data", "model")

        def helper(x):
            return lax.psum(x, "modle")
    """)
    (f,) = [f for f in findings if f.rule == "TPU012"]
    assert "modle" in f.message and "typo" in f.message


def test_tpu012_negative_declared_axis(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax

        def run(xs, mesh):
            def inner(x):
                return lax.psum(x, ("data", "model"))
            return jax.shard_map(inner, mesh=mesh, in_specs=None,
                                 out_specs=None,
                                 axis_names=("data", "model"))(xs)
    """)
    assert "TPU012" not in codes(findings, gating_only=False)


def test_tpu012_negative_variable_axis_and_unknown_context(tmp_path):
    """A variable axis is the caller's contract; an axis_names built from
    a variable makes the context unknowable — both stay silent."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax

        def facade(x, axis="data"):
            return lax.psum(x, axis)

        def run(xs, mesh, ax):
            def inner(x):
                return lax.psum(x, "anything_goes")
            return jax.shard_map(inner, mesh=mesh, in_specs=None,
                                 out_specs=None, axis_names={ax})(xs)
    """)
    assert "TPU012" not in codes(findings, gating_only=False)


def test_tpu012_negative_subset_lint_without_declarations(tmp_path):
    """A subset lint (lint.sh --changed, one helper file) that declares
    NO axes must not call a valid axis a typo — the declarations live in
    the unchanged mesh module outside the run."""
    findings = lint_snippet(tmp_path, """
        from jax import lax

        def helper(x):
            return lax.psum(x, "model")
    """)
    assert "TPU012" not in codes(findings, gating_only=False)


def test_tpu012_negative_pmap_axis(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def probe(v):
            return jax.pmap(lambda x: jax.lax.psum(x, "i"),
                            axis_name="i")(v)
    """)
    assert "TPU012" not in codes(findings, gating_only=False)


def test_tpu012_positive_module_constant_axis(tmp_path):
    """Round-8 depth: an axis passed AS a module-level constant resolves
    like the literal — a constant naming an undeclared axis is flagged."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax

        WRONG_AXIS = "modle"

        def run(xs, mesh):
            def inner(x):
                return lax.psum(x, WRONG_AXIS)
            return jax.shard_map(inner, mesh=mesh, in_specs=None,
                                 out_specs=None, axis_names=("model",))(xs)
    """)
    (f,) = [f for f in findings if f.rule == "TPU012"]
    assert "modle" in f.message and "'model'" in f.message


def test_tpu012_negative_module_constant_axis_and_context(tmp_path):
    """Constants on BOTH sides: axis_names declared from a constant tuple
    and the collective passing a member constant — no finding; a tuple
    mixing constants and literals resolves too."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax

        DATA_AXIS = "data"
        MODEL_AXIS = "model"
        MESH_AXES = (DATA_AXIS, MODEL_AXIS)

        def run(xs, mesh):
            def inner(x):
                y = lax.psum(x, MODEL_AXIS)
                return lax.pmean(y, (DATA_AXIS, "model"))
            return jax.shard_map(inner, mesh=mesh, in_specs=None,
                                 out_specs=None, axis_names=MESH_AXES)(xs)
    """)
    assert "TPU012" not in codes(findings, gating_only=False)


def test_tpu012_constant_axis_cross_module(tmp_path):
    """The constant lives in ANOTHER module of the lint run (the
    parallel/mesh.py idiom): resolution follows the import map. The typo'd
    import is flagged against the project universe; the valid one is not."""
    from deepspeed_tpu.analysis import lint_paths
    import textwrap
    (tmp_path / "meshdef.py").write_text(textwrap.dedent("""
        MODEL_AXIS = "model"
        BAD_AXIS = "modle"
        MESH_AXES = ("data", "model")
    """))
    (tmp_path / "user.py").write_text(textwrap.dedent("""
        from jax import lax
        from meshdef import BAD_AXIS, MODEL_AXIS

        def good(x):
            return lax.psum(x, MODEL_AXIS)

        def bad(x):
            return lax.psum(x, BAD_AXIS)
    """))
    findings = lint_paths([str(tmp_path / "meshdef.py"),
                           str(tmp_path / "user.py")], root=str(tmp_path))
    tpu12 = [f for f in findings if f.rule == "TPU012"]
    assert len(tpu12) == 1 and tpu12[0].symbol == "bad"


def test_tpu012_negative_locally_shadowed_constant(tmp_path):
    """A function-local binding (param or assignment) shadowing a
    module-level constant reads the LOCAL value — a variable axis, the
    caller's contract; the module constant must not be resolved."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax

        AXIS = "not_an_axis"

        def facade(x, AXIS):
            return lax.psum(x, AXIS)

        def run(xs, mesh):
            def inner(x):
                AXIS = pick_axis()
                return lax.pmean(x, AXIS)
            return jax.shard_map(inner, mesh=mesh, in_specs=None,
                                 out_specs=None, axis_names=("model",))(xs)
    """)
    assert "TPU012" not in codes(findings, gating_only=False)


def test_tpu012_negative_locally_shadowed_imported_constant(tmp_path):
    """Shadowing must also beat the IMPORT MAP: a parameter named like an
    imported constant is a variable axis, not the other module's value."""
    from deepspeed_tpu.analysis import lint_paths
    import textwrap
    (tmp_path / "meshdef2.py").write_text(textwrap.dedent("""
        MODEL_AXIS = "not_declared_anywhere"
        MESH_AXES = ("data", "model")
    """))
    (tmp_path / "user2.py").write_text(textwrap.dedent("""
        from jax import lax
        from meshdef2 import MODEL_AXIS

        def facade(x, MODEL_AXIS):
            return lax.psum(x, MODEL_AXIS)
    """))
    findings = lint_paths([str(tmp_path / "meshdef2.py"),
                           str(tmp_path / "user2.py")], root=str(tmp_path))
    assert "TPU012" not in codes(findings, gating_only=False)


def test_tpu012_negative_conflicting_constant(tmp_path):
    """A name assigned two different literals is poisoned — never guess
    which assignment is live at the call site."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax

        AXIS = "modle"
        AXIS = "other_modle"

        def run(xs, mesh):
            def inner(x):
                return lax.psum(x, AXIS)
            return jax.shard_map(inner, mesh=mesh, in_specs=None,
                                 out_specs=None, axis_names=("model",))(xs)
    """)
    assert "TPU012" not in codes(findings, gating_only=False)


# --------------------------------------- TPU013 (collective-order divergence)

def test_tpu013_positive_raise_between_collectives(tmp_path):
    """The pre-PR-3 bug: a rank-local failure raising between the staging
    barrier and the allgather leaves every other rank hung."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def save(tag, ok):
            multihost_utils.sync_global_devices("stage-" + tag)
            if not ok:
                raise RuntimeError("local write failed")
            multihost_utils.sync_global_devices("done-" + tag)
    """)
    (f,) = [f for f in findings if f.rule == "TPU013"]
    assert f.severity == Severity.WARNING
    assert "raise" in f.message and "ok-flag" in f.message


def test_tpu013_positive_conditional_return_between(tmp_path):
    findings = lint_snippet(tmp_path, """
        from jax import lax

        def step(x, skip):
            y = lax.psum(x, "data")
            if skip:
                return y
            return y + lax.pmean(x, "data")
    """)
    assert "TPU013" in codes(findings)


def test_tpu013_positive_continue_before_loop_collective(tmp_path):
    findings = lint_snippet(tmp_path, """
        from jax import lax

        def sweep(chunks):
            out = []
            for c in chunks:
                if c is None:
                    continue
                out.append(lax.psum(c, "data"))
            return out
    """)
    (f,) = [f for f in findings if f.rule == "TPU013"]
    assert "continue" in f.message


def test_tpu013_positive_data_dependent_while(tmp_path):
    findings = lint_snippet(tmp_path, """
        from jax import lax

        def iterate(x):
            converged = check(x)
            while not converged:
                x = lax.pmean(x, "data")
                converged = check(x)
            return x
    """)
    (f,) = [f for f in findings if f.rule == "TPU013"]
    assert "while" in f.message


def test_tpu013_positive_transitive_event(tmp_path):
    """The second collective hides behind a same-module call — the pair
    still resolves through the graph."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def _finish(tag):
            multihost_utils.sync_global_devices("done-" + tag)

        def save(tag, ok):
            multihost_utils.sync_global_devices("stage-" + tag)
            if not ok:
                raise RuntimeError("local write failed")
            _finish(tag)
    """)
    hits = [f for f in findings if f.rule == "TPU013"]
    assert hits and "_finish" in hits[0].message


def test_tpu013_negative_okflag_idiom(tmp_path):
    """The PR-3 fix shape: catch the local failure, fold it into a value
    every rank contributes, raise only AFTER the final collective."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils

        def save(tag, write):
            multihost_utils.sync_global_devices("stage-" + tag)
            ok = True
            try:
                write(tag)
            except OSError:
                ok = False
            oks = multihost_utils.process_allgather(ok)
            if not all(oks):
                raise RuntimeError("some rank failed")
    """)
    assert "TPU013" not in codes(findings, gating_only=False)


def test_tpu013_negative_dispatch_returns(tmp_path):
    """comm.all_reduce's shape: each conditional return IS a collective —
    dispatch, not desequencing."""
    findings = lint_snippet(tmp_path, """
        from jax import lax

        def all_reduce(x, op):
            if op == "sum":
                return lax.psum(x, "data")
            if op == "max":
                return lax.pmax(x, "data")
            return lax.pmean(x, "data")
    """)
    assert "TPU013" not in codes(findings, gating_only=False)


def test_tpu013_negative_static_loops(tmp_path):
    findings = lint_snippet(tmp_path, """
        from jax import lax

        def pipeline(x, n_stages):
            for _ in range(n_stages):
                x = lax.ppermute(x, "pipe", [(0, 1)])
            while True:
                break
            return x
    """)
    assert "TPU013" not in codes(findings, gating_only=False)


def test_tpu011_suppression_and_baseline_interplay(tmp_path):
    """New rules ride the existing machinery: inline suppression
    de-gates, baseline round-trips."""
    src = """
        import jax
        from jax.experimental import multihost_utils

        def intentional():
            if jax.process_index() == 0:
                # graftlint: disable=TPU011 (single-host probe by design)
                multihost_utils.sync_global_devices("x")

        def buggy():
            if jax.process_index() == 0:
                multihost_utils.sync_global_devices("y")
    """
    findings = lint_snippet(tmp_path, src)
    hits = [f for f in findings if f.rule == "TPU011"]
    assert len(hits) == 2
    sup = [f for f in hits if f.suppressed]
    assert len(sup) == 1 and sup[0].symbol == "intentional"
    gating = [f for f in hits if f.gating]
    assert len(gating) == 1 and gating[0].symbol == "buggy"
    # baseline the remaining one: stops gating, goes stale once fixed
    bl_path = str(tmp_path / ".graftlint.json")
    Baseline.write(bl_path, gating)
    findings2 = lint_snippet(tmp_path, src)
    bl = Baseline.load(bl_path)
    bl.apply(findings2)
    assert all(not f.gating for f in findings2 if f.rule == "TPU011")


# ----------------------------------------------------------- --fix autofixes

FIXABLE_SRC = """\
import jax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental import pallas as pl


def constrain(mesh, x):
    a = lax.with_sharding_constraint(x, P("data", None))
    b = jax.device_put(x, NamedSharding(mesh, P(("model",))))
    return a, b


def launch(x, kernel, spec):
    return pl.pallas_call(kernel, out_shape=spec)(x)
"""


def _run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis"] + args,
        capture_output=True, text=True, cwd=cwd)


def test_fix_rewrites_specs_and_wraps_pallas(tmp_path):
    f = tmp_path / "fixme.py"
    f.write_text(FIXABLE_SRC)
    proc = _run_cli([str(f), "--no-baseline", "--fix"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    fixed = f.read_text()
    assert 'P("data")' in fixed and 'P("data", None)' not in fixed
    assert 'P("model")' in fixed and '("model",)' not in fixed
    assert 'with jax.named_scope("launch"):' in fixed
    # fixed file re-lints clean
    findings = lint_paths([str(f)], root=str(tmp_path))
    assert not [x for x in findings if x.gating]


def test_fix_is_idempotent(tmp_path):
    f = tmp_path / "fixme.py"
    f.write_text(FIXABLE_SRC)
    assert _run_cli([str(f), "--no-baseline", "--fix"]).returncode == 0
    once = f.read_text()
    proc = _run_cli([str(f), "--no-baseline", "--fix"])
    assert proc.returncode == 0
    assert f.read_text() == once                 # second pass: no-op
    assert "applied 0 fix(es)" in proc.stderr


def test_fix_adds_missing_jax_import(tmp_path):
    f = tmp_path / "kern.py"
    f.write_text(textwrap.dedent("""\
        from jax.experimental import pallas as pl

        def launch(x, kernel, spec):
            return pl.pallas_call(kernel, out_shape=spec)(x)
    """))
    assert _run_cli([str(f), "--no-baseline", "--fix"]).returncode == 0
    fixed = f.read_text()
    assert "import jax\n" in fixed
    assert 'with jax.named_scope("launch"):' in fixed
    findings = lint_paths([str(f)], root=str(tmp_path))
    assert not [x for x in findings if x.gating]


def test_fix_respects_inline_suppression(tmp_path):
    f = tmp_path / "keep.py"
    src = textwrap.dedent("""\
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def constrain(x):
            # graftlint: disable=TPU008 (kept verbatim for a repro)
            return lax.with_sharding_constraint(x, P("data", None))
    """)
    f.write_text(src)
    assert _run_cli([str(f), "--no-baseline", "--fix"]).returncode == 0
    assert f.read_text() == src                  # suppressed: untouched


def test_fix_tpu009_casts_carry_back_preserving_f32_island(tmp_path):
    """Round-7 satellite: the TPU009 autofix appends ``.astype(<init
    dtype>)`` to the widened carry expression — the f32 math INSIDE stays
    (accumulate in an f32 island), the carry dtype goes back to the
    init's own 16-bit token."""
    f = tmp_path / "scan9.py"
    f.write_text(textwrap.dedent("""\
        import jax
        import jax.numpy as jnp
        from jax import lax


        def run(xs):
            def body(c, x):
                c = (c + x).astype(jnp.float32)
                return c, x
            init = jnp.zeros((8,), jnp.bfloat16)
            return lax.scan(body, init, xs)
    """))
    proc = _run_cli([str(f), "--no-baseline", "--fix"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    fixed = f.read_text()
    assert "(c + x).astype(jnp.float32).astype(jnp.bfloat16)" in fixed
    findings = lint_paths([str(f)], root=str(tmp_path))
    assert not [x for x in findings if x.gating]
    # idempotent: a second pass edits nothing
    assert _run_cli([str(f), "--no-baseline", "--fix"]).returncode == 0
    assert f.read_text() == fixed


def test_fix_tpu009_inline_init_and_fp16_token(tmp_path):
    """The cast-back uses the init's OWN dtype token (fp16 init -> fp16
    cast), including when the init is inline in the scan call."""
    f = tmp_path / "scan9b.py"
    f.write_text(textwrap.dedent("""\
        import jax.numpy as jnp
        from jax import lax


        def run(xs):
            def inner(c, x):
                return jnp.float32(c + x), x
            return lax.scan(inner, jnp.zeros((8,), jnp.float16), xs)
    """))
    assert _run_cli([str(f), "--no-baseline", "--fix"]).returncode == 0
    assert "jnp.float32(c + x).astype(jnp.float16)" in f.read_text()
    findings = lint_paths([str(f)], root=str(tmp_path))
    assert not [x for x in findings if x.gating]


def test_fix_tpu009_respects_inline_suppression(tmp_path):
    f = tmp_path / "keep9.py"
    src = textwrap.dedent("""\
        import jax.numpy as jnp
        from jax import lax


        def run(xs):
            def body(c, x):
                # graftlint: disable=TPU009 (intentional f32 upgrade)
                return jnp.float32(c + x), x
            return lax.scan(body, jnp.zeros((8,), jnp.bfloat16), xs)
    """)
    f.write_text(src)
    assert _run_cli([str(f), "--no-baseline", "--fix"]).returncode == 0
    assert f.read_text() == src                  # suppressed: untouched


# ------------------------------------------------------------------- SARIF

def test_sarif_format_and_file_output(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\ndef g(opt, p):\n"
                   "    return jax.jit(opt.init)(p)\n")
    out = tmp_path / "report.sarif"
    proc = _run_cli([str(bad), "--format", "sarif", "--no-baseline",
                     "--sarif", str(out)])
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"TPU001", "TPU011", "TPU012", "TPU013"} <= rule_ids
    (res,) = run["results"]
    assert res["ruleId"] == "TPU002" and res["level"] == "warning"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 4
    assert res["partialFingerprints"]["graftlint/v1"]
    # --sarif wrote the identical document to the file
    assert json.loads(out.read_text())["runs"][0]["results"]


def test_sarif_marks_suppressed_findings(tmp_path):
    f = tmp_path / "sup.py"
    f.write_text("import jax\n\ndef g(opt, p):\n"
                 "    return jax.jit(opt.init)(p)"
                 "  # graftlint: disable=TPU002 (init-time)\n")
    proc = _run_cli([str(f), "--format", "sarif", "--no-baseline"])
    assert proc.returncode == 0
    (res,) = json.loads(proc.stdout)["runs"][0]["results"]
    assert res["suppressions"][0]["kind"] == "inSource"


# tier-2 (round-19 budget sweep, ~5s): the cheaper tier-1 cousins are
# test_package_is_lint_clean_against_baseline (same full-package walk,
# gating verdict) and the per-rule SARIF shape units above;
# scripts/tier2.sh runs this SARIF-emission twin
@pytest.mark.slow
def test_package_sarif_run_is_finding_free(tmp_path):
    """Tier-1 gate (CI shape): the full-package SARIF run carries no
    result without a suppression — every finding is either fixed,
    inline-justified, or (currently: never) baselined."""
    out = tmp_path / "pkg.sarif"
    proc = _run_cli(["deepspeed_tpu", "--format", "json",
                     "--sarif", str(out)])
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    doc = json.loads(out.read_text())
    unsuppressed = [
        r for r in doc["runs"][0]["results"]
        if not r.get("suppressions") and r["level"] in ("error", "warning")]
    assert unsuppressed == [], unsuppressed


def test_facade_catalog_covers_comm_module():
    """Every comm/comm.py wrapper that dispatches a collective must be in
    FACADE_COLLECTIVES — otherwise callers through the facade silently
    lose the TPU011–TPU013 guarantees on subset lints."""
    import ast as _ast
    from deepspeed_tpu.analysis import collectives as C
    from deepspeed_tpu.analysis.core import ModuleInfo

    path = os.path.join(REPO, "deepspeed_tpu", "comm", "comm.py")
    with open(path) as f:
        src = f.read()
    module = ModuleInfo(path, src, "deepspeed_tpu/comm/comm.py")
    for node in module.tree.body:
        if not isinstance(node, _ast.FunctionDef):
            continue
        dispatches = any(
            module.scope.imports.qualify(c.func) in C.LAX_COLLECTIVES
            or module.scope.imports.qualify(c.func) in C.HOST_COLLECTIVES
            for c in _ast.walk(node) if isinstance(c, _ast.Call))
        if dispatches:
            assert f"deepspeed_tpu.comm.comm.{node.name}" \
                in C.FACADE_COLLECTIVES, (
                    f"comm.{node.name} dispatches a collective but is not "
                    "in analysis/collectives.py FACADE_COLLECTIVES")


def test_baseline_ledger_is_empty():
    """ROADMAP open item closed: the accepted-debt ledger is at zero —
    every accepted finding is a justified INLINE suppression."""
    with open(os.path.join(REPO, ".graftlint.json")) as f:
        data = json.load(f)
    assert data["findings"] == []


def test_cli_json_format(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text("import jax\n\ndef g(opt, p):\n"
                 "    return jax.jit(opt.init)(p)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis", str(f),
         "--format", "json", "--no-baseline"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert data["summary"]["gating"] == 1
    assert data["findings"][0]["rule"] == "TPU002"


def test_cli_select_ignore(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text("import jax\n\ndef g(opt, p):\n"
                 "    return jax.jit(opt.init)(p)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis", str(f),
         "--ignore", "TPU002", "--no-baseline"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_package_is_lint_clean_against_baseline():
    """Tier-1 gate: graftlint over deepspeed_tpu/ must exit 0 with the
    checked-in baseline — a new host sync/retrace/dtype leak fails CI
    here instead of surfacing as a BENCH regression."""
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis", "deepspeed_tpu",
         "--format", "json"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    data = json.loads(proc.stdout)
    assert data["summary"]["gating"] == 0


# --------------------------------------------------------------------- TPU014

def test_tpu014_positive_device_put_in_traced_code(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(state, batch, target_sharding):
            x = jax.device_put(batch, target_sharding)
            return state + x
    """)
    (f,) = [f for f in findings if f.rule == "TPU014"]
    assert f.severity == Severity.ERROR
    assert "transfer channel" in f.message


def test_tpu014_positive_transitively_traced(tmp_path):
    """device_put in a helper only ever called from traced code."""
    findings = lint_snippet(tmp_path, """
        import jax

        def _bounce(x, sh):
            return jax.device_put(x, sh)

        @jax.jit
        def step(state, x, sh):
            return state + _bounce(x, sh)
    """)
    assert "TPU014" in codes(findings)


def test_tpu014_positive_host_roundtrip_on_step_path(tmp_path):
    """device_put of a host pull on the hot step path: a full
    device->host->device round-trip per step (WARNING tier)."""
    findings = lint_snippet(tmp_path, """
        import jax
        import numpy as np

        class Engine:
            def train_batch(self, batch):
                acts = self.collect()
                moved = jax.device_put(np.asarray(acts), self.sharding)
                return self.step_fn(moved)
    """)
    (f,) = [f for f in findings if f.rule == "TPU014"]
    assert f.severity == Severity.WARNING
    assert "round-trip" in f.message


def test_tpu014_negative_host_side_placement(tmp_path):
    """Init/restore/channel placement outside traced or hot code is the
    sanctioned idiom."""
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        def init_state(params, sharding):
            return jax.tree.map(
                lambda p: jax.device_put(jnp.zeros_like(p), sharding),
                params)

        def channel_send(payload, placement):
            return jax.device_put(payload, placement)
    """)
    assert "TPU014" not in codes(findings)


def test_tpu014_negative_plain_device_put_on_step_path(tmp_path):
    """A bare device_put of an already-on-host buffer on the step path
    (offload staging) is not the round-trip shape and stays clean."""
    findings = lint_snippet(tmp_path, """
        import jax

        class Tier:
            def step(self, j):
                return jax.device_put(self._staging[j], self.shardings[j])
    """)
    assert "TPU014" not in codes(findings)


# --------------------------------------------------------------------- TPU015

def lint_named(tmp_path, name, source):
    """TPU015 fires by MODULE, so the fixture file needs the real name."""
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return lint_paths([str(f)], select={"TPU015"}, root=str(tmp_path))


_BLOCKING_SRC = """
    import threading

    class FleetSupervisor:
        def poll(self):
            self._lock.acquire()
            item = self.queue.get()
            self._done_evt.wait()
            self._thread.join()
            return item
"""


def test_tpu015_positive_unbounded_blocking_in_supervision_module(tmp_path):
    """All four shapes of the bug class the PR-6 review passes fixed by
    hand: lock.acquire() / queue.get() / Event.wait() / thread.join()
    without a timeout, in a supervision module."""
    findings = lint_named(tmp_path, "fleet.py", _BLOCKING_SRC)
    assert codes(findings) == ["TPU015"] * 4
    assert all(f.severity == Severity.WARNING for f in findings)
    msgs = " ".join(f.message for f in findings)
    assert "acquire" in msgs and "get" in msgs


def test_tpu015_negative_same_code_outside_supervision_modules(tmp_path):
    """Ordinary code is allowed to wait — the rule is scoped to the
    modules whose JOB is converting hangs into exits."""
    findings = lint_named(tmp_path, "engine.py", _BLOCKING_SRC)
    assert "TPU015" not in codes(findings)


def test_tpu015_negative_bounded_and_nonblocking_calls(tmp_path):
    findings = lint_named(tmp_path, "supervisor.py", """
        import threading

        class RunSupervisor:
            def monitor(self, proc, reader):
                self._lock.acquire(timeout=5.0)
                self._lock.acquire(False)          # non-blocking probe
                self.queue.get(timeout=0.5)
                self._done_evt.wait(0.05)
                reader.join(timeout=5)
                rc = proc.wait()                   # Popen: the monitor's job
                desc = ", ".join(str(r) for r in self.ranks)
                phase = rec.get("phase")           # dict get, not queue
                return rc, desc, phase
    """)
    assert "TPU015" not in codes(findings)


def test_tpu015_positive_watchdog_and_elastic_agent_scoped(tmp_path):
    """The module set covers every supervision component, not just the
    launcher supervisor."""
    for name in ("watchdog.py", "elastic_agent.py", "straggler.py"):
        findings = lint_named(tmp_path, name, """
            def run(self):
                self._stop_event.wait()
        """)
        assert "TPU015" in codes(findings), name


def test_tpu015_positive_explicit_blocking_positionals(tmp_path):
    """The positional escape hatch is closed: acquire(True) / get(1) are
    just an explicit "block forever" (the timeout slot is SECOND), and
    wait(None) is the spelled-out unbounded wait — all the same bug as
    the bare calls, review-pass finding round 15."""
    findings = lint_named(tmp_path, "supervisor.py", """
        def monitor(self):
            self._lock.acquire(True)
            self.queue.get(1)
            self._done_evt.wait(None)
    """)
    assert codes(findings) == ["TPU015"] * 3


def test_tpu015_negative_positional_timeouts(tmp_path):
    """acquire/get with BOTH positionals carry a timeout; wait's first
    positional IS the timeout."""
    findings = lint_named(tmp_path, "supervisor.py", """
        def monitor(self):
            self._lock.acquire(True, 5.0)
            self.queue.get(True, 0.5)
            self._done_evt.wait(0.05)
    """)
    assert "TPU015" not in codes(findings)


def test_tpu015_suppression_respected(tmp_path):
    findings = lint_named(tmp_path, "fleet.py", """
        def drain(self):
            self._lock.acquire()   # graftlint: disable=TPU015
    """)
    assert all(f.suppressed for f in findings if f.rule == "TPU015")


# ---------------------------------------- TPU016 (lock-order inversion)

def test_tpu016_positive_direct_inversion(tmp_path):
    """The canonical deadlock: two functions nest the same two locks in
    opposite orders."""
    findings = lint_snippet(tmp_path, """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def fwd():
            with _a:
                with _b:
                    pass

        def rev():
            with _b:
                with _a:
                    pass
    """, select={"TPU016"})
    (f,) = [f for f in findings if f.rule == "TPU016"]
    assert f.severity == Severity.ERROR
    assert "_a" in f.message and "_b" in f.message
    assert "deadlock" in f.message


def test_tpu016_positive_transitive_cross_module(tmp_path):
    """The two nesting orders only meet through call edges across
    modules — the shape no per-function scan can see."""
    (tmp_path / "shared.py").write_text(textwrap.dedent("""
        import threading
        A = threading.Lock()
        B = threading.Lock()
    """))
    (tmp_path / "worker.py").write_text(textwrap.dedent("""
        from shared import A, B

        def take_b():
            with B:
                pass

        def fwd():
            with A:
                take_b()
    """))
    (tmp_path / "drain.py").write_text(textwrap.dedent("""
        from shared import A, B

        def take_a():
            with A:
                pass

        def rev():
            with B:
                take_a()
    """))
    findings = lint_paths([str(tmp_path)], select={"TPU016"},
                          root=str(tmp_path))
    hits = [f for f in findings if f.rule == "TPU016"]
    assert len(hits) == 1
    assert "shared.A" in hits[0].message and "shared.B" in hits[0].message


def test_tpu016_negative_bounded_acquire_is_not_an_edge(tmp_path):
    """acquire(timeout=) fails gracefully instead of deadlocking — the
    codebase's own cycle-breaking idiom must stay clean."""
    findings = lint_snippet(tmp_path, """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def fwd():
            with _a:
                with _b:
                    pass

        def rev():
            with _b:
                if _a.acquire(timeout=0.2):
                    _a.release()
    """, select={"TPU016"})
    assert "TPU016" not in codes(findings)


def test_tpu016_negative_consistent_order(tmp_path):
    findings = lint_snippet(tmp_path, """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def one():
            with _a:
                with _b:
                    pass

        def two():
            with _a:
                with _b:
                    pass
    """, select={"TPU016"})
    assert "TPU016" not in codes(findings)


# ---------------------------------------- TPU017 (blocking under a lock)

def test_tpu017_positive_device_sync_under_lock(tmp_path):
    findings = lint_snippet(tmp_path, """
        import threading
        import jax

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def snap(self, x):
                with self._lock:
                    return jax.device_get(x)
    """, select={"TPU017"})
    (f,) = [f for f in findings if f.rule == "TPU017"]
    assert "_lock" in f.message and "device_get" in f.message


def test_tpu017_positive_transitive_through_helper(tmp_path):
    """The blocking site is one call away — the PR-11 fleet shape
    (lock held across an opaque step)."""
    findings = lint_snippet(tmp_path, """
        import threading
        import time

        _lock = threading.Lock()

        def _flush():
            time.sleep(5.0)

        def push(item):
            with _lock:
                _flush()
    """, select={"TPU017"})
    (f,) = [f for f in findings if f.rule == "TPU017"]
    assert "_flush" in f.message and "time.sleep" in f.message


def test_tpu017_negative_blocking_outside_the_lock(tmp_path):
    findings = lint_snippet(tmp_path, """
        import threading
        import time

        _lock = threading.Lock()

        def push(item):
            with _lock:
                staged = item
            time.sleep(0.1)
            return staged
    """, select={"TPU017"})
    assert "TPU017" not in codes(findings)


def test_tpu017_negative_bounded_entry_region_is_exempt(tmp_path):
    """A region entered through acquire(timeout=) is survivable by
    design: waiters fail over instead of wedging."""
    findings = lint_snippet(tmp_path, """
        import threading
        import time

        _lock = threading.Lock()

        def probe():
            if _lock.acquire(timeout=1.0):
                try:
                    time.sleep(0.5)
                finally:
                    _lock.release()
    """, select={"TPU017"})
    assert "TPU017" not in codes(findings)


def test_tpu017_negative_condition_wait_releases_the_lock(tmp_path):
    """cv.wait() on the held condition RELEASES it while waiting — not
    blocking under the lock."""
    findings = lint_snippet(tmp_path, """
        import threading

        class Batcher:
            def __init__(self):
                self._cv = threading.Condition()

            def take(self):
                with self._cv:
                    self._cv.wait()
    """, select={"TPU017"})
    assert "TPU017" not in codes(findings)


# ------------------------------------- TPU018 (unsynchronized shared state)

_RACY_SRC = """
    import threading

    class Fleet:
        def __init__(self):
            self._lock = threading.Lock()
            self.state = 0
            threading.Thread(target=self._poll).start()
            threading.Thread(target=self._drain).start()

        def _poll(self):
            self.state = 1

        def _drain(self):
            return self.state
"""


def test_tpu018_positive_two_entries_no_lock(tmp_path):
    findings = lint_snippet(tmp_path, _RACY_SRC, select={"TPU018"})
    (f,) = [f for f in findings if f.rule == "TPU018"]
    assert "state" in f.message
    assert "_poll" in f.message and "_drain" in f.message
    assert "locks held: none" in f.message


def test_tpu018_negative_common_lock_serializes(tmp_path):
    findings = lint_snippet(tmp_path, """
        import threading

        class Fleet:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = 0
                threading.Thread(target=self._poll).start()
                threading.Thread(target=self._drain).start()

            def _poll(self):
                with self._lock:
                    self.state = 1

            def _drain(self):
                with self._lock:
                    return self.state
    """, select={"TPU018"})
    assert "TPU018" not in codes(findings)


def test_tpu018_negative_single_entry_never_conflicts(tmp_path):
    """One thread entry = one extra thread per instance: an attr only
    that thread touches cannot race."""
    findings = lint_snippet(tmp_path, """
        import threading

        class Fleet:
            def __init__(self):
                self.state = 0
                threading.Thread(target=self._poll).start()

            def _poll(self):
                self.state = self.state + 1
    """, select={"TPU018"})
    assert "TPU018" not in codes(findings)


def test_tpu018_positive_unique_attr_receiver_resolution(tmp_path):
    """The write goes through a local alias (``rep = self.rep``), not
    ``self`` — resolved because the attr is unique to one class."""
    findings = lint_snippet(tmp_path, """
        import threading

        class Replica:
            def __init__(self):
                self.weight = 0

        class Pool:
            def __init__(self, rep):
                self.rep = rep
                threading.Thread(target=self.bump).start()
                threading.Thread(target=self.read).start()

            def bump(self):
                rep = self.rep
                rep.weight = 1

            def read(self):
                rep = self.rep
                return rep.weight
    """, select={"TPU018"})
    (f,) = [f for f in findings if f.rule == "TPU018"]
    assert "weight" in f.message


def test_tpu018_suppression_respected(tmp_path):
    f = tmp_path / "snippet.py"
    src = textwrap.dedent(_RACY_SRC).replace(
        "self.state = 1",
        "self.state = 1  # graftlint: disable=TPU018")
    f.write_text(src)
    findings = lint_paths([str(f)], select={"TPU018"}, root=str(tmp_path))
    hits = [f for f in findings if f.rule == "TPU018"]
    assert hits and all(f.suppressed for f in hits)


# ---------------------------------------- TPU019 (exit-path blocking)

def test_tpu019_positive_with_lock_under_signal_handler(tmp_path):
    findings = lint_snippet(tmp_path, """
        import signal
        import threading

        _lock = threading.Lock()

        def _cleanup():
            with _lock:
                pass

        def _handler(signum, frame):
            _cleanup()

        def install():
            signal.signal(signal.SIGTERM, _handler)
    """, select={"TPU019"})
    (f,) = [f for f in findings if f.rule == "TPU019"]
    assert "with-statement" in f.message
    assert "_handler (signal handler)" in f.message


def test_tpu019_positive_stamp_terminal_is_a_named_root(tmp_path):
    """Any ``stamp_terminal`` is the last-words path by contract — no
    registration site needed to make it an exit root."""
    findings = lint_snippet(tmp_path, """
        import threading

        class Writer:
            def __init__(self):
                self._lock = threading.Lock()

            def stamp_terminal(self, phase):
                self._lock.acquire()
                self._last = phase
                self._lock.release()
    """, select={"TPU019"})
    (f,) = [f for f in findings if f.rule == "TPU019"]
    assert "terminal stamp path" in f.message


def test_tpu019_positive_bounded_api_called_without_lock_timeout(tmp_path):
    findings = lint_snippet(tmp_path, """
        import signal

        def write(phase, lock_timeout=None):
            return phase

        def _handler(signum, frame):
            write("EXIT")

        def install():
            signal.signal(signal.SIGTERM, _handler)
    """, select={"TPU019"})
    (f,) = [f for f in findings if f.rule == "TPU019"]
    assert "without lock_timeout=" in f.message
    assert "autofixable" in f.message


def test_tpu019_negative_bounded_acquire_on_exit_path(tmp_path):
    findings = lint_snippet(tmp_path, """
        import signal
        import threading

        _lock = threading.Lock()

        def _cleanup():
            if _lock.acquire(timeout=2.0):
                _lock.release()

        def _handler(signum, frame):
            _cleanup()

        def install():
            signal.signal(signal.SIGTERM, _handler)
    """, select={"TPU019"})
    assert "TPU019" not in codes(findings)


def test_tpu019_negative_same_code_off_the_exit_path(tmp_path):
    findings = lint_snippet(tmp_path, """
        import threading

        _lock = threading.Lock()

        def steady_state():
            with _lock:
                pass
    """, select={"TPU019"})
    assert "TPU019" not in codes(findings)


def test_tpu019_fix_threads_lock_timeout_and_is_idempotent(tmp_path):
    f = tmp_path / "exiting.py"
    f.write_text(textwrap.dedent("""\
        import signal


        def write(phase, lock_timeout=None):
            return phase


        def _handler(signum, frame):
            write("EXIT")


        def install():
            signal.signal(signal.SIGTERM, _handler)
    """))
    proc = _run_cli([str(f), "--no-baseline", "--fix",
                     "--select", "TPU019"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    fixed = f.read_text()
    assert 'write("EXIT", lock_timeout=5.0)' in fixed
    proc = _run_cli([str(f), "--no-baseline", "--fix",
                     "--select", "TPU019"])
    assert proc.returncode == 0
    assert f.read_text() == fixed                # second pass: no-op
    assert "applied 0 fix(es)" in proc.stderr


# ---------------------------------------- TPU020 (failpoint catalog sync)

def _chaos_pkg(tmp_path, catalog, doc_names):
    pkg = tmp_path / "pkg"
    (pkg / "testing").mkdir(parents=True)
    entries = "".join(f'    "{n}": "somewhere",\n' for n in catalog)
    (pkg / "testing" / "chaos.py").write_text(
        "FAILPOINTS = {\n" + entries + "}\n\n\n"
        "def failpoint(name, key=None):\n    pass\n")
    docs = tmp_path / "docs"
    docs.mkdir()
    rows = "".join(f"| `{n}` | x |\n" for n in doc_names)
    (docs / "RESILIENCE.md").write_text("| name | fires |\n|--|--|\n" + rows)
    return pkg


def test_tpu020_positive_uncataloged_failpoint(tmp_path):
    pkg = _chaos_pkg(tmp_path, ["run.kill"], ["run.kill"])
    (pkg / "engine.py").write_text(textwrap.dedent("""
        from testing import chaos

        def step():
            chaos.failpoint("run.kill")
            chaos.failpoint("run.unknown")
    """))
    findings = lint_paths([str(pkg)], select={"TPU020"}, root=str(tmp_path))
    (f,) = [f for f in findings if f.rule == "TPU020"]
    assert "run.unknown" in f.message and "FAILPOINTS" in f.message


def test_tpu020_positive_cataloged_but_undocumented(tmp_path):
    pkg = _chaos_pkg(tmp_path, ["run.kill", "run.hidden"], ["run.kill"])
    (pkg / "engine.py").write_text(textwrap.dedent("""
        from testing import chaos

        def step():
            chaos.failpoint("run.hidden")
    """))
    findings = lint_paths([str(pkg)], select={"TPU020"}, root=str(tmp_path))
    (f,) = [f for f in findings if f.rule == "TPU020"]
    assert "run.hidden" in f.message and "RESILIENCE.md" in f.message


def test_tpu020_negative_cataloged_and_documented(tmp_path):
    pkg = _chaos_pkg(tmp_path, ["run.kill"], ["run.kill"])
    (pkg / "engine.py").write_text(textwrap.dedent("""
        from testing import chaos

        def step():
            chaos.failpoint("run.kill")
    """))
    findings = lint_paths([str(pkg)], select={"TPU020"}, root=str(tmp_path))
    assert "TPU020" not in codes(findings)


def test_failpoint_catalog_matches_docs_table():
    """Repo-state mirror of test_facade_catalog_covers_comm_module:
    every cataloged failpoint is documented in RESILIENCE.md's table."""
    import ast as _ast
    path = os.path.join(REPO, "deepspeed_tpu", "testing", "chaos.py")
    with open(path) as f:
        tree = _ast.parse(f.read())
    cataloged = set()
    for node in tree.body:
        target = getattr(getattr(node, "targets", [None])[0], "id", None) \
            or getattr(getattr(node, "target", None), "id", None)
        if target == "FAILPOINTS":
            cataloged = {k.value for k in node.value.keys}
    assert cataloged, "FAILPOINTS catalog missing from testing/chaos.py"
    import re as _re
    with open(os.path.join(REPO, "docs", "RESILIENCE.md")) as f:
        documented = set(_re.findall(r"`([a-z][a-z0-9_]*\.[a-z0-9_.]+)`",
                                     f.read()))
    missing = cataloged - documented
    assert not missing, f"cataloged but undocumented: {sorted(missing)}"


# ---------------------------------------- TPU021 (exit-code literals)

def test_tpu021_positive_reserved_literals(tmp_path):
    findings = lint_snippet(tmp_path, """
        import sys

        def bail(rc):
            if rc == 114:
                return "preempted"
            sys.exit(117)
    """, select={"TPU021"})
    hits = [f for f in findings if f.rule == "TPU021"]
    assert len(hits) == 2
    msgs = " ".join(f.message for f in hits)
    assert "PREEMPTION_EXIT_CODE" in msgs and "STALL_EXIT_CODE" in msgs


def test_tpu021_positive_13_only_in_exit_context(tmp_path):
    findings = lint_snippet(tmp_path, """
        import os

        def boom():
            os._exit(13)

        def harmless():
            return list(range(13))
    """, select={"TPU021"})
    hits = [f for f in findings if f.rule == "TPU021"]
    assert len(hits) == 1
    assert "KILL_EXIT_CODE" in hits[0].message


def test_tpu021_negative_signal_rc_and_plain_numbers(tmp_path):
    findings = lint_snippet(tmp_path, """
        def classify(rc):
            if rc == -15:
                return "sigterm"
            pad = 13
            return pad
    """, select={"TPU021"})
    assert "TPU021" not in codes(findings)


def test_tpu021_fix_swaps_literal_and_imports_constant(tmp_path):
    f = tmp_path / "bail.py"
    f.write_text(textwrap.dedent("""\
        import sys


        def bail():
            sys.exit(117)
    """))
    proc = _run_cli([str(f), "--no-baseline", "--fix",
                     "--select", "TPU021"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    fixed = f.read_text()
    assert "sys.exit(STALL_EXIT_CODE)" in fixed
    assert "from deepspeed_tpu.exit_codes import STALL_EXIT_CODE" in fixed
    proc = _run_cli([str(f), "--no-baseline", "--fix",
                     "--select", "TPU021"])
    assert proc.returncode == 0
    assert f.read_text() == fixed


# ----------------------------------- concurrency-suite tier-1 gates

def test_concurrency_rules_registered():
    assert {"TPU016", "TPU017", "TPU018", "TPU019", "TPU020",
            "TPU021"} <= set(RULES)


def test_package_sweep_is_clean_with_concurrency_rules():
    """Tier-1 gate: the full package lints clean with TPU016–TPU021
    enabled and NO baseline — real findings were fixed, deliberate
    designs carry inline justifications. This also pins the PR's
    runtime fixes: reverting the supervisor's locked heartbeat
    snapshot (TPU018), the MPMD bounded sends (TPU017) or the
    watchdog's bounded once-guard (TPU019) re-fails it."""
    findings = lint_paths(
        [os.path.join(REPO, "deepspeed_tpu")],
        select={"TPU016", "TPU017", "TPU018", "TPU019", "TPU020",
                "TPU021"},
        root=REPO)
    gating = [(f.path, f.line, f.rule, f.message)
              for f in findings if f.gating]
    assert gating == []


def test_analyzer_runtime_budget():
    """Tier-1 gate: the WHOLE analyzer (parse + index + every rule)
    stays under the 10s CI budget on the full package."""
    import time as _time
    timings = {}
    t0 = _time.monotonic()
    lint_paths([os.path.join(REPO, "deepspeed_tpu")], root=REPO,
               timings=timings)
    total = _time.monotonic() - t0
    assert total < 10.0, f"analyzer took {total:.1f}s (budget 10s)"
    assert "<parse+index>" in timings
    assert any(k.startswith("TPU") for k in timings)


def test_cli_timing_flag_prints_per_rule_breakdown(tmp_path):
    f = tmp_path / "ok.py"
    f.write_text("x = 1\n")
    proc = _run_cli([str(f), "--no-baseline", "--timing"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftlint: timing (" in proc.stderr
    assert " ms" in proc.stderr


def test_tpu017_baseline_interplay(tmp_path):
    """A baselined concurrency finding stops gating but stays visible —
    and the ledger entry goes stale when the code is fixed."""
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent("""
        import threading
        import time

        _lock = threading.Lock()

        def push(item):
            with _lock:
                time.sleep(5.0)
    """))
    findings = lint_paths([str(f)], select={"TPU017"}, root=str(tmp_path))
    (hit,) = [x for x in findings if x.rule == "TPU017"]
    assert hit.gating
    bl_path = tmp_path / ".graftlint.json"
    Baseline.write(str(bl_path), [hit])
    findings = lint_paths([str(f)], select={"TPU017"}, root=str(tmp_path))
    bl = Baseline.load(str(bl_path))
    bl.apply(findings)
    (hit,) = [x for x in findings if x.rule == "TPU017"]
    assert hit.baselined and not hit.gating


# --------------------------------- resource-lifecycle rules (TPU022–TPU025)

def test_resource_rules_registered():
    assert {"TPU022", "TPU023", "TPU024", "TPU025"} <= set(RULES)


def test_tpu022_positive_raise_before_release(tmp_path):
    findings = lint_snippet(tmp_path, """
        import socket

        def dial(addr):
            s = socket.create_connection(addr)
            if addr is None:
                raise ValueError("no addr")
            s.close()
    """, select={"TPU022"})
    (hit,) = [f for f in findings if f.rule == "TPU022"]
    assert hit.gating and "socket" in hit.message


def test_tpu022_positive_failpoint_path(tmp_path):
    # a keyed chaos failpoint IS a raise-capable site: the matrix can
    # fire it with the handle live
    findings = lint_snippet(tmp_path, """
        import socket
        from deepspeed_tpu.testing import chaos

        def send(addr):
            s = socket.create_connection(addr)
            chaos.failpoint("net.send")
            s.close()
    """, select={"TPU022"})
    (hit,) = [f for f in findings if f.rule == "TPU022"]
    assert "failpoint" in hit.message


def test_tpu022_positive_discarded_handle(tmp_path):
    findings = lint_snippet(tmp_path, """
        def slurp(p):
            return open(p).read()
    """, select={"TPU022"})
    (hit,) = [f for f in findings if f.rule == "TPU022"]
    assert "discarded" in hit.message


def test_tpu022_negative_handler_release(tmp_path):
    findings = lint_snippet(tmp_path, """
        import socket

        def dial(addr, hello):
            s = socket.create_connection(addr)
            try:
                s.sendall(hello)
            except OSError:
                s.close()
                raise
            return s
    """, select={"TPU022"})
    assert "TPU022" not in codes(findings, gating_only=False)


def test_tpu022_negative_release_via_callee(tmp_path):
    # interprocedural discharge: the callee provably closes its param
    findings = lint_snippet(tmp_path, """
        import socket

        def shutdown(conn):
            conn.close()

        def run(addr):
            s = socket.create_connection(addr)
            shutdown(s)
            raise RuntimeError("post-release failures are fine")
    """, select={"TPU022"})
    assert "TPU022" not in codes(findings, gating_only=False)


def test_tpu022_positive_non_discharging_callee(tmp_path):
    # the callee only LOOKS at the handle — obligation stays here
    findings = lint_snippet(tmp_path, """
        import socket

        def remember(conn):
            _dead = conn is None

        def run(addr):
            s = socket.create_connection(addr)
            remember(s)
            raise RuntimeError("boom")
    """, select={"TPU022"})
    assert [f for f in findings if f.rule == "TPU022"]


def test_tpu022_negative_ownership_transfers(tmp_path):
    # stored on self / returned / handed to an unresolvable supervisor:
    # all three end this function's obligation
    findings = lint_snippet(tmp_path, """
        import socket

        class Client:
            def connect(self, addr):
                s = socket.create_connection(addr)
                self._sock = s
                self.hello()

        def make(addr):
            s = socket.create_connection(addr)
            return s

        def spawn(registry, addr):
            s = socket.create_connection(addr)
            registry.register(s)
            raise RuntimeError("registry owns it now")
    """, select={"TPU022"})
    assert "TPU022" not in codes(findings, gating_only=False)


def test_tpu022_negative_with_statement(tmp_path):
    findings = lint_snippet(tmp_path, """
        def read(p):
            with open(p) as f:
                return f.read()
    """, select={"TPU022"})
    assert "TPU022" not in codes(findings, gating_only=False)


def test_tpu022_negative_constituent_release(tmp_path):
    # wrapper construction: closing the wrapped socket discharges the
    # wrapper (the procfleet _serve_conn shape)
    findings = lint_snippet(tmp_path, """
        import socket

        class HubConn:
            def __init__(self, sock):
                self._sock = sock

        def serve(listener):
            sock, _ = listener.accept()
            try:
                conn = HubConn(sock)
                handshake(conn)
            except (OSError, ValueError):
                sock.close()
                return
    """, select={"TPU022"})
    assert "TPU022" not in codes(findings, gating_only=False)


def test_tpu022_positive_staging_dir_unprotected(tmp_path):
    findings = lint_snippet(tmp_path, """
        import os
        from deepspeed_tpu.testing import chaos

        def save(ckpt_dir, tag):
            stage_dir = os.path.join(ckpt_dir, tag + ".tmp")
            os.makedirs(stage_dir, exist_ok=True)
            chaos.failpoint("ckpt.save")
            os.replace(stage_dir, os.path.join(ckpt_dir, tag))
    """, select={"TPU022"})
    (hit,) = [f for f in findings if f.rule == "TPU022"]
    assert "staging" in hit.message


def test_tpu022_negative_staging_quarantined(tmp_path):
    findings = lint_snippet(tmp_path, """
        import os
        from deepspeed_tpu.testing import chaos

        def quarantine_staging(stage_dir, reason=""):
            pass

        def save(ckpt_dir, tag):
            stage_dir = os.path.join(ckpt_dir, tag + ".tmp")
            os.makedirs(stage_dir, exist_ok=True)
            try:
                chaos.failpoint("ckpt.save")
                os.replace(stage_dir, os.path.join(ckpt_dir, tag))
            except BaseException:
                quarantine_staging(stage_dir, reason="torn save")
                raise
    """, select={"TPU022"})
    assert "TPU022" not in codes(findings, gating_only=False)


def test_tpu023_positive_started_never_joined(tmp_path):
    findings = lint_snippet(tmp_path, """
        import threading

        def run(work):
            t = threading.Thread(target=work)
            t.start()
            return 1
    """, select={"TPU023"})
    (hit,) = [f for f in findings if f.rule == "TPU023"]
    assert "join" in hit.message


def test_tpu023_negative_joined_daemon_or_registered(tmp_path):
    findings = lint_snippet(tmp_path, """
        import threading

        def run_joined(work):
            t = threading.Thread(target=work)
            t.start()
            t.join()

        def run_daemon(work):
            t = threading.Thread(target=work, daemon=True)
            t.start()

        class Owner:
            def start(self, work):
                t = threading.Thread(target=work)
                t.start()
                self._t = t

            def stop(self):
                self._t.join()
    """, select={"TPU023"})
    assert "TPU023" not in codes(findings, gating_only=False)


def test_tpu023_positive_registered_attr_never_joined(tmp_path):
    findings = lint_snippet(tmp_path, """
        import threading

        class Owner:
            def start(self, work):
                t = threading.Thread(target=work)
                t.start()
                self._t = t
    """, select={"TPU023"})
    (hit,) = [f for f in findings if f.rule == "TPU023"]
    assert "_t" in hit.message


def test_tpu024_positive_double_close(tmp_path):
    findings = lint_snippet(tmp_path, """
        def teardown(sock):
            sock.close()
            sock.close()
    """, select={"TPU024"})
    (hit,) = [f for f in findings if f.rule == "TPU024"]
    assert hit.severity == Severity.ERROR
    assert hit.related and hit.related[0][1] == 3


def test_tpu024_negative_rebound_between(tmp_path):
    findings = lint_snippet(tmp_path, """
        def redial(sock, addr, connect):
            sock.close()
            sock = connect(addr)
            sock.close()
    """, select={"TPU024"})
    assert "TPU024" not in codes(findings, gating_only=False)


def test_tpu024_negative_cross_branch(tmp_path):
    # guarded / cross-branch releases are path-dependent: out of scope
    findings = lint_snippet(tmp_path, """
        def teardown(sock, hard):
            if hard:
                sock.close()
            else:
                sock.close()
    """, select={"TPU024"})
    assert "TPU024" not in codes(findings, gating_only=False)


def test_tpu025_positive_send_after_close(tmp_path):
    findings = lint_snippet(tmp_path, """
        def bye(sock, frame):
            sock.close()
            sock.send(frame)
    """, select={"TPU025"})
    (hit,) = [f for f in findings if f.rule == "TPU025"]
    assert "send" in hit.message and hit.related


def test_tpu025_negative_reap_vocabulary_and_rebind(tmp_path):
    findings = lint_snippet(tmp_path, """
        def reap(sock, connect, addr):
            sock.close()
            _fd = sock.fileno()
            sock = connect(addr)
            sock.send(b"hello")
    """, select={"TPU025"})
    assert "TPU025" not in codes(findings, gating_only=False)


def test_tpu022_suppression_and_baseline_interplay(tmp_path):
    src = textwrap.dedent("""
        import socket

        def dial(addr):
            s = socket.create_connection(addr)  # graftlint: disable=TPU022 (caller adopts via gc)
            if addr is None:
                raise ValueError
    """)
    f = tmp_path / "mod.py"
    f.write_text(src)
    findings = lint_paths([str(f)], select={"TPU022"}, root=str(tmp_path))
    (hit,) = [x for x in findings if x.rule == "TPU022"]
    assert hit.suppressed and not hit.gating
    # without the suppression the finding gates, and a baseline entry
    # un-gates it without hiding it
    f.write_text(src.replace("  # graftlint: disable=TPU022 "
                             "(caller adopts via gc)", ""))
    findings = lint_paths([str(f)], select={"TPU022"}, root=str(tmp_path))
    (hit,) = [x for x in findings if x.rule == "TPU022"]
    assert hit.gating
    bl_path = tmp_path / ".graftlint.json"
    Baseline.write(str(bl_path), [hit])
    findings = lint_paths([str(f)], select={"TPU022"}, root=str(tmp_path))
    bl = Baseline.load(str(bl_path))
    bl.apply(findings)
    (hit,) = [x for x in findings if x.rule == "TPU022"]
    assert hit.baselined and not hit.gating


def test_package_sweep_is_clean_with_resource_rules():
    """Tier-1 gate: the full package lints clean with TPU022–TPU025
    enabled and NO baseline. This pins the PR's runtime fixes: reverting
    the fabric handshake cleanup (sockets._dial), the stage worker's
    staging quarantine, or the replica worker's terminal-stamp/endpoint
    try/finally re-fails it."""
    findings = lint_paths(
        [os.path.join(REPO, "deepspeed_tpu")],
        select={"TPU022", "TPU023", "TPU024", "TPU025"},
        root=REPO)
    gating = [(f.path, f.line, f.rule, f.message)
              for f in findings if f.gating]
    assert gating == []


# ------------------------------------- scope-aware local-def resolution

def test_scoped_resolution_finds_widening_body_among_twins(tmp_path):
    # two nested defs named `body`: the scan must bind to ITS scope's
    # def, not whichever the module walk met last
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        from jax import lax

        def widening(xs):
            def body(c, x):
                c = (c + x).astype(jnp.float32)
                return c, x
            init = jnp.zeros((8,), jnp.bfloat16)
            return lax.scan(body, init, xs)

        def unrelated():
            def body(c, x):
                return c, x
            return body
    """)
    assert [f for f in findings if f.rule == "TPU009"]


def test_scoped_resolution_no_fp_from_foreign_twin(tmp_path):
    # the clean scan must NOT inherit the widening from a same-named
    # def in another scope (the old defs[-1] collapse)
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        from jax import lax

        def other():
            def body(c, x):
                c = (c + x).astype(jnp.float32)
                return c, x
            return body

        def clean(xs):
            def body(c, x):
                acc = c.astype(jnp.float32) + x
                return acc.astype(jnp.bfloat16), x
            init = jnp.zeros((8,), jnp.bfloat16)
            return lax.scan(body, init, xs)
    """)
    assert "TPU009" not in codes(findings, gating_only=False)


def test_scoped_resolution_rebinding_prefers_nearest_prior(tmp_path):
    # module-level rebinding: the reference binds to the def live at the
    # reference line, not the file's last one
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        from jax import lax

        def body(c, x):
            c = (c + x).astype(jnp.float32)
            return c, x

        def run(xs):
            init = jnp.zeros((8,), jnp.bfloat16)
            return lax.scan(body, init, xs)

        def body(c, x):  # noqa: F811 — rebinding fixture
            return c, x
    """)
    # `run` references the FIRST body (live at its line): widening found
    assert [f for f in findings if f.rule == "TPU009"]


# ------------------------------------------------ SARIF relatedLocations

def test_sarif_related_locations_shape(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        def teardown(sock):
            sock.close()
            sock.close()
    """))
    proc = _run_cli([str(bad), "--format", "sarif", "--no-baseline",
                     "--select", "TPU024"])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    (res,) = json.loads(proc.stdout)["runs"][0]["results"]
    (rel,) = res["relatedLocations"]
    assert rel["physicalLocation"]["artifactLocation"]["uri"].endswith(
        "bad.py")
    assert rel["physicalLocation"]["region"]["startLine"] == 3
    assert "first release" in rel["message"]["text"]


def test_finding_to_dict_carries_related(tmp_path):
    findings = lint_snippet(tmp_path, """
        def teardown(sock):
            sock.close()
            sock.close()
    """, select={"TPU024"})
    (hit,) = [f for f in findings if f.rule == "TPU024"]
    d = hit.to_dict()
    assert d["related"][0]["line"] == 3 and d["related"][0]["path"]


# ---------------------------------------------------- CLI rule selection

def test_cli_rules_and_exclude_rules_aliases(tmp_path):
    f = tmp_path / "two.py"
    f.write_text(textwrap.dedent("""
        import threading

        def run(work, sock):
            t = threading.Thread(target=work)
            t.start()
            sock.close()
            sock.close()
    """))
    proc = _run_cli([str(f), "--no-baseline", "--format", "json",
                     "--rules", "TPU023,TPU024"])
    got = {x["rule"] for x in json.loads(proc.stdout)["findings"]}
    assert got == {"TPU023", "TPU024"}
    proc = _run_cli([str(f), "--no-baseline", "--format", "json",
                     "--rules", "TPU023,TPU024",
                     "--exclude-rules", "TPU024"])
    got = {x["rule"] for x in json.loads(proc.stdout)["findings"]}
    assert got == {"TPU023"}
    # unknown codes are a usage error, not a silent no-op
    proc = _run_cli([str(f), "--rules", "TPU999"])
    assert proc.returncode == 2
