"""graftlint unit tests: one true-positive and one true-negative fixture
per rule (TPU001–TPU008, TPU010), plus suppression, baseline and self-lint
tests.

Fixtures are source snippets linted in-memory through a temp file — the
linter is AST-only, so none of this imports JAX or touches devices.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from deepspeed_tpu.analysis import Baseline, RULES, Severity, lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_snippet(tmp_path, source, select=None):
    f = tmp_path / "snippet.py"
    f.write_text(textwrap.dedent(source))
    return lint_paths([str(f)], select=select, root=str(tmp_path))


def codes(findings, gating_only=True):
    return [f.rule for f in findings if not gating_only or f.gating]


# --------------------------------------------------------------------- TPU001

def test_tpu001_positive_traced_item(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def train_step(state, batch):
            loss = jnp.mean(batch)
            print(loss.item())
            return state
    """)
    assert "TPU001" in codes(findings)
    (f,) = [f for f in findings if f.rule == "TPU001"]
    assert f.severity == Severity.ERROR
    assert f.symbol == "train_step"


def test_tpu001_positive_hot_path_float(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp

        class Engine:
            def train_batch(self, batch):
                metrics = self._step(batch)
                return float(metrics["loss"])
    """)
    (f,) = [f for f in findings if f.rule == "TPU001"]
    assert f.severity == Severity.WARNING


def test_tpu001_negative(tmp_path):
    # device_get is the sanctioned explicit transfer on the host step
    # path; float() of an already-pulled dict and of python config values
    # is free
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def train_step(state, batch):
            return state + jnp.mean(batch)

        class Engine:
            def train_batch(self, batch):
                metrics = self._step(batch)
                host = jax.device_get(metrics)
                gas = self.config.gas
                return float(host["loss"]), float(gas)
    """)
    assert "TPU001" not in codes(findings, gating_only=False)


# --------------------------------------------------------------------- TPU002

def test_tpu002_positive_jit_in_loop(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def sweep(model, batches):
            for b in batches:
                out = jax.jit(lambda x: model(x))(b)
    """)
    hits = [f for f in findings if f.rule == "TPU002"]
    assert hits and hits[0].severity == Severity.ERROR


def test_tpu002_positive_bound_method(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def init_state(opt, params):
            return jax.jit(opt.init)(params)
    """)
    hits = [f for f in findings if f.rule == "TPU002"]
    assert hits and hits[0].severity == Severity.WARNING


def test_tpu002_negative(tmp_path):
    # jit over a stable module-level fn does not retrace (cache keyed by
    # function identity), and a hoisted jitted callable is the idiom
    findings = lint_snippet(tmp_path, """
        import jax

        def _step(state, batch):
            return state

        train_step = jax.jit(_step, donate_argnums=(0,))

        def run(state, batches):
            for b in batches:
                state = train_step(state, b)
            return jax.jit(_step)(state, batches[0])
    """)
    assert "TPU002" not in codes(findings)


# --------------------------------------------------------------------- TPU003

def test_tpu003_positive(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        class Engine:
            def _make_step(self):
                @jax.jit
                def step(state, batch):
                    self.calls = self.calls + 1
                    return state
                return step
    """)
    hits = [f for f in findings if f.rule == "TPU003"]
    assert hits and "self.calls" in hits[0].message


def test_tpu003_negative(tmp_path):
    # locals and returned state are pure; building the step fn OUTSIDE the
    # traced region may mutate self freely
    findings = lint_snippet(tmp_path, """
        import jax

        class Engine:
            def _make_step(self):
                self.built = True

                @jax.jit
                def step(state, batch):
                    acc = state + 1
                    return acc
                return step
    """)
    assert "TPU003" not in codes(findings)


# --------------------------------------------------------------------- TPU004

def test_tpu004_positive_f64(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return x.astype(jnp.float64)
    """)
    hits = [f for f in findings if f.rule == "TPU004"]
    assert hits and hits[0].severity == Severity.ERROR


def test_tpu004_positive_loss_downcast(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(logits, batch):
            loss = jnp.mean(logits)
            return loss.astype(jnp.bfloat16)
    """)
    hits = [f for f in findings if f.rule == "TPU004"]
    assert hits and hits[0].severity == Severity.WARNING


def test_tpu004_negative(tmp_path):
    # f32 islands for loss/grad-norm math are the convention, and casting
    # activations (not losses) to the compute dtype is fine
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x, loss_scale):
            h = x.astype(jnp.bfloat16)
            loss = jnp.mean(h).astype(jnp.float32)
            return loss * loss_scale
    """)
    assert "TPU004" not in codes(findings)


# --------------------------------------------------------------------- TPU005

def test_tpu005_positive(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def make_step():
            def train_step(state, batch):
                return state
            return jax.jit(train_step)
    """)
    hits = [f for f in findings if f.rule == "TPU005"]
    assert hits and "donate" in hits[0].message


def test_tpu005_negative(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def make_step():
            def train_step(state, batch):
                return state
            return jax.jit(train_step, donate_argnums=(0,))

        def make_eval():
            def eval_step(params, batch):
                return batch
            return jax.jit(eval_step)
    """)
    assert "TPU005" not in codes(findings)


# --------------------------------------------------------------------- TPU006

def test_tpu006_positive(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(grads):
            overflow = jnp.any(jnp.isnan(grads))
            if overflow:
                return grads * 0
            return grads
    """)
    hits = [f for f in findings if f.rule == "TPU006"]
    assert hits and "overflow" in hits[0].message


def test_tpu006_negative(tmp_path):
    # static python config branches and `is None` guards are fine under
    # trace; jnp.where is the in-graph select
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(grads, clip=0.0, mask=None):
            if clip > 0:
                grads = grads * clip
            if mask is not None:
                grads = jnp.where(mask, grads, 0.0)
            nan = jnp.any(jnp.isnan(grads))
            return jnp.where(nan, jnp.zeros_like(grads), grads)
    """)
    assert "TPU006" not in codes(findings)


# --------------------------------------------------------------------- TPU007

def test_tpu007_positive_double_use(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def sample(rng, shape):
            a = jax.random.normal(rng, shape)
            b = jax.random.uniform(rng, shape)
            return a + b
    """)
    hits = [f for f in findings if f.rule == "TPU007"]
    assert hits and "rng" in hits[0].message


def test_tpu007_positive_loop_invariant(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def sample(rng, n):
            outs = []
            for i in range(n):
                outs.append(jax.random.normal(rng, (4,)))
            return outs
    """)
    hits = [f for f in findings if f.rule == "TPU007"]
    assert hits and "loop" in hits[0].message


def test_tpu007_negative(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def sample(rng, n):
            outs = []
            for i in range(n):
                rng, sub = jax.random.split(rng)
                outs.append(jax.random.normal(sub, (4,)))
            r1, r2 = jax.random.split(rng)
            return jax.random.normal(r1), jax.random.uniform(r2)
    """)
    assert "TPU007" not in codes(findings)


# --------------------------------------------------------------------- TPU008

def test_tpu008_positive_trailing_none(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        def constrain(x):
            return lax.with_sharding_constraint(x, P("data", None))
    """)
    hits = [f for f in findings if f.rule == "TPU008"]
    assert hits and "trailing None" in hits[0].message
    assert hits[0].severity == Severity.WARNING


def test_tpu008_positive_single_name_tuple(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(mesh, x):
            return jax.device_put(x, NamedSharding(mesh, P(("model",))))
    """)
    hits = [f for f in findings if f.rule == "TPU008"]
    assert hits and "single-name tuple" in hits[0].message


def test_tpu008_negative_canonical_specs(tmp_path):
    # canonical forms — bare names, interior None, multi-axis tuples — and
    # specs built elsewhere (a variable the checker can't see into) pass
    findings = lint_snippet(tmp_path, """
        import jax
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def constrain(mesh, x, spec):
            a = lax.with_sharding_constraint(x, P("data"))
            b = lax.with_sharding_constraint(x, P(None, "model"))
            c = lax.with_sharding_constraint(x, P(("data", "expert")))
            d = lax.with_sharding_constraint(x, spec)
            e = jax.device_put(x, NamedSharding(mesh, P()))
            return a, b, c, d, e
    """)
    assert "TPU008" not in codes(findings, gating_only=False)


def test_tpu008_ignores_specs_outside_constraint_sites(tmp_path):
    # a non-canonical P literal that never reaches a constraint site is
    # someone's intermediate value — not this rule's business
    findings = lint_snippet(tmp_path, """
        from jax.sharding import PartitionSpec as P

        def build():
            return P("data", None)
    """)
    assert "TPU008" not in codes(findings, gating_only=False)


# --------------------------------------------------------------------- TPU009

def test_tpu009_positive_bf16_carry_widened(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp
        from jax import lax

        def run(xs):
            def body(c, x):
                c = (c + x).astype(jnp.float32)
                return c, x
            init = jnp.zeros((8,), jnp.bfloat16)
            return lax.scan(body, init, xs)
    """)
    hits = [f for f in findings if f.rule == "TPU009"]
    assert hits and "carry" in hits[0].message
    assert hits[0].severity == Severity.WARNING


def test_tpu009_positive_inline_init_f32_wrap(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        from jax import lax

        def run(xs):
            def body(c, x):
                return jnp.float32(c + x), x
            return lax.scan(body, jnp.zeros((8,), jnp.bfloat16), xs)
    """)
    assert [f.rule for f in findings if f.rule == "TPU009"]


def test_tpu009_negative_carry_cast_back(tmp_path):
    # the CORRECT idiom: accumulate in an f32 island, carry bf16
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        from jax import lax

        def run(xs):
            def body(c, x):
                acc = c.astype(jnp.float32) + x
                return acc.astype(jnp.bfloat16), x
            init = jnp.zeros((8,), jnp.bfloat16)
            return lax.scan(body, init, xs)
    """)
    assert "TPU009" not in codes(findings, gating_only=False)


def test_tpu009_negative_f32_scan_untouched(tmp_path):
    # an intentionally-f32 scan (init shows no 16-bit evidence) never fires
    findings = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        from jax import lax

        def run(xs):
            def body(c, x):
                return c.astype(jnp.float32) + x, x
            init = jnp.zeros((8,), jnp.float32)
            return lax.scan(body, init, xs)
    """)
    assert "TPU009" not in codes(findings, gating_only=False)


# --------------------------------------------- suppressions / baseline / CLI

def test_inline_suppression_same_line(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(state, x):
            return float(x * state)  # graftlint: disable=TPU001
    """)
    # the finding is still produced (and counted) but marked + non-gating
    hits = [f for f in findings if f.rule == "TPU001"]
    assert not hits or all(f.suppressed and not f.gating for f in hits)


def test_inline_suppression_preceding_line(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def init_state(opt, params):
            # graftlint: disable=TPU002 (init-time: one trace)
            return jax.jit(opt.init)(params)
    """)
    hits = [f for f in findings if f.rule == "TPU002"]
    assert hits and all(f.suppressed for f in hits)


def test_file_wide_suppression(tmp_path):
    findings = lint_snippet(tmp_path, """
        # graftlint: disable-file=TPU002
        import jax

        def a(opt, p):
            return jax.jit(opt.init)(p)

        def b(opt, p):
            return jax.jit(opt.update)(p)
    """)
    hits = [f for f in findings if f.rule == "TPU002"]
    assert len(hits) == 2 and all(f.suppressed for f in hits)


def test_baseline_roundtrip(tmp_path):
    src = """
        import jax

        def init_state(opt, params):
            return jax.jit(opt.init)(params)
    """
    findings = lint_snippet(tmp_path, src)
    gating = [f for f in findings if f.gating]
    assert gating
    bl_path = str(tmp_path / ".graftlint.json")
    Baseline.write(bl_path, gating)

    # same findings re-linted against the baseline stop gating
    findings2 = lint_snippet(tmp_path, src)
    bl = Baseline.load(bl_path)
    bl.apply(findings2)
    assert all(f.baselined and not f.gating for f in findings2
               if f.rule == "TPU002")
    assert not bl.stale_entries()

    # baseline matching survives pure line-number churn
    findings3 = lint_snippet(tmp_path, "\n\n\n" + textwrap.dedent(src))
    bl = Baseline.load(bl_path)
    bl.apply(findings3)
    assert all(f.baselined for f in findings3 if f.rule == "TPU002")

    # fixing the code strands the entry -> reported stale
    clean = lint_snippet(tmp_path, """
        import jax

        def nothing():
            return 1
    """)
    bl = Baseline.load(bl_path)
    bl.apply(clean)
    assert len(bl.stale_entries()) == 1


def test_baseline_entries_carry_justification():
    """Every checked-in baseline entry must say WHY it is accepted."""
    path = os.path.join(REPO, ".graftlint.json")
    with open(path) as f:
        data = json.load(f)
    for e in data["findings"]:
        assert e.get("justification"), e
        assert "TODO" not in e["justification"], e


def test_rule_registry_complete():
    assert {"TPU001", "TPU002", "TPU003", "TPU004", "TPU005", "TPU006",
            "TPU007", "TPU008", "TPU009", "TPU010"} <= set(RULES)
    for code, rule in RULES.items():
        assert rule.summary and rule.name, code


# --------------------------------------------------------------------- TPU010

def test_tpu010_positive_unscoped_pallas_call(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import pallas as pl

        def launch(x, kernel, spec):
            return pl.pallas_call(kernel, out_shape=spec)(x)
    """)
    (f,) = [f for f in findings if f.rule == "TPU010"]
    assert f.severity == Severity.WARNING
    assert f.symbol == "launch"
    assert "named_scope" in f.message


def test_tpu010_positive_scope_not_lexical(tmp_path):
    """A named_scope in the CALLER does not cover the launching function."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import pallas as pl

        def _launch(x, kernel, spec):
            return pl.pallas_call(kernel, out_shape=spec)(x)

        def entry(x, kernel, spec):
            with jax.named_scope("my_kernel"):
                return _launch(x, kernel, spec)
    """)
    assert "TPU010" in codes(findings)


def test_tpu010_negative_with_scope(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import pallas as pl

        def launch(x, kernel, spec):
            with jax.named_scope("my_kernel"):
                return pl.pallas_call(kernel, out_shape=spec)(x)

        @jax.named_scope("decorated_kernel")
        def launch2(x, kernel, spec):
            return pl.pallas_call(kernel, out_shape=spec)(x)
    """)
    assert "TPU010" not in codes(findings)


def test_cli_json_format(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text("import jax\n\ndef g(opt, p):\n"
                 "    return jax.jit(opt.init)(p)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis", str(f),
         "--format", "json", "--no-baseline"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert data["summary"]["gating"] == 1
    assert data["findings"][0]["rule"] == "TPU002"


def test_cli_select_ignore(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text("import jax\n\ndef g(opt, p):\n"
                 "    return jax.jit(opt.init)(p)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis", str(f),
         "--ignore", "TPU002", "--no-baseline"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_package_is_lint_clean_against_baseline():
    """Tier-1 gate: graftlint over deepspeed_tpu/ must exit 0 with the
    checked-in baseline — a new host sync/retrace/dtype leak fails CI
    here instead of surfacing as a BENCH regression."""
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis", "deepspeed_tpu",
         "--format", "json"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    data = json.loads(proc.stdout)
    assert data["summary"]["gating"] == 0
