"""Traffic-shaped serving (round 19): autoscaling fleet, priority lanes
with preemption, and overload-graceful admission.

Three layers, mirroring the code split:

* **Policy units** (serving/autoscale.py) — the AutoscalePolicy is pure
  and clock-injectable, so the false-flap guards are fake-clock unit
  tests: a single burst under cooldown causes at most ONE scale event,
  a warming replica's silence never triggers a scale-down, steady state
  produces zero events.
* **Queue/ladder units** (serving/scheduler.py) — TieredQueue ordering
  (highest tier first, FIFO within, aging floor, all-standard == exact
  FIFO) and the admit_or_shed overload ladder (batch highwater
  rejection, hard-full tier shedding, machine-readable
  AdmissionRejected — never a hang, never a silent drop).
* **Fleet end-to-end** (serving/fleet.py, thread placement in tier-1;
  the process placement rides tier-2) — scale-up under a burst and
  drain-down in the idle trough with greedy outputs token-exact vs
  sequential generate(), deadline-pressured preemption through the
  exactly-once requeue, and the crash matrix: serve.scale_up /
  serve.preempt failpoints, scale-down-during-kill, and
  preempt-during-replica-death never double-emit or lose a request.

Determinism notes follow tests/test_fleet.py: requests are submitted
BEFORE ``start()`` where dispatch timing matters, and the preemption
legs use ``max_batch=1`` so "no free lane" is a constructed fact, not a
race.
"""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.config.config import AutoscaleConfig
from deepspeed_tpu.models import build_model
from deepspeed_tpu.models.generation import generate
from deepspeed_tpu.runtime import heartbeat as hb
from deepspeed_tpu.serving.autoscale import (AUTOSCALER_RANK, SCALE_DOWN,
                                             SCALE_UP, AutoscalePolicy,
                                             Observation)
from deepspeed_tpu.serving.fleet import RETIRED, ServingFleet
from deepspeed_tpu.serving.scheduler import (BATCH, FINISHED, LATENCY, SHED,
                                             STANDARD, AdmissionRejected,
                                             Request, TieredQueue,
                                             admit_or_shed)
from deepspeed_tpu.testing import chaos


# ---------------------------------------------------------------------------
# policy units (fake clock — no fleet, no threads, no sleeps)
# ---------------------------------------------------------------------------

def _policy(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("up_queue_per_replica", 4)
    kw.setdefault("up_after", 2)
    kw.setdefault("down_idle_s", 10.0)
    kw.setdefault("cooldown_s", 15.0)
    return AutoscalePolicy(AutoscaleConfig(**kw))


def _obs(queue=0, live=1, warming=0, draining=0, active=0, pressured=0):
    return Observation(queue_depth=queue, pressured=pressured, live=live,
                       warming=warming, draining=draining,
                       active_lanes=active, total_lanes=live * 8)


def test_policy_single_burst_under_cooldown_at_most_one_event():
    """False-flap guard: a sustained burst produces exactly ONE scale-up
    until the cooldown expires, regardless of how many polls see it."""
    pol = _policy(cooldown_s=15.0, up_after=2)
    hot = _obs(queue=50, live=1)
    events = [pol.observe(hot, now=float(t)) for t in range(10)]
    assert events.count(SCALE_UP) == 1
    assert set(events) <= {SCALE_UP, None}
    # cooldown expiry: the STILL-hot fleet may scale again, exactly once
    events2 = [pol.observe(hot, now=20.0 + t) for t in range(10)]
    assert events2.count(SCALE_UP) == 1


def test_policy_warming_replica_silence_never_scales_down():
    """False-flap guard: while any replica warms (compiling off-path,
    gauges idle — compile is not idleness), NO verdict fires in either
    direction, and the idle/hot streaks reset so the warming window
    can't be double-counted once it lands."""
    pol = _policy(down_idle_s=1.0, cooldown_s=0.0)
    for t in range(100):                # 100s of "idle" while warming
        assert pol.observe(_obs(queue=0, live=1, warming=1,
                                active=0), now=float(t)) is None
    # warming also blocks scale-up (capacity already in flight)
    pol2 = _policy(cooldown_s=0.0)
    for t in range(10):
        assert pol2.observe(_obs(queue=99, live=1, warming=1),
                            now=float(t)) is None
    # once warmed, the idle trough must be UNBROKEN from here
    assert pol.observe(_obs(queue=0, live=2, active=0), now=100.0) is None
    assert pol.observe(_obs(queue=0, live=2, active=0),
                       now=101.5) == SCALE_DOWN


def test_policy_steady_state_zero_events():
    """Moderately loaded (below the trigger) and never idle: no events,
    ever — the autoscaler must not fidget under normal traffic."""
    pol = _policy(up_queue_per_replica=4, down_idle_s=5.0, cooldown_s=0.0)
    for t in range(200):
        obs = _obs(queue=3, live=2, active=4)  # 3 < 4*2, lanes busy
        assert pol.observe(obs, now=float(t) * 0.5) is None


def test_policy_hysteresis_and_bounds():
    pol = _policy(up_after=3, cooldown_s=0.0, max_replicas=2)
    hot = _obs(queue=50, live=1)
    assert pol.observe(hot, now=0.0) is None     # streak 1
    assert pol.observe(_obs(queue=0, live=1, active=1),
                       now=1.0) is None          # streak broken
    assert pol.observe(hot, now=2.0) is None
    assert pol.observe(hot, now=3.0) is None
    assert pol.observe(hot, now=4.0) == SCALE_UP
    # at max_replicas the verdict is withheld entirely
    assert pol.observe(_obs(queue=50, live=2), now=5.0) is None
    # at min_replicas the trough is ignored
    pol2 = _policy(min_replicas=1, down_idle_s=0.5, cooldown_s=0.0)
    for t in range(20):
        assert pol2.observe(_obs(queue=0, live=1, active=0),
                            now=float(t)) is None


def test_policy_deadline_pressure_triggers_without_queue_depth():
    pol = _policy(up_after=1, cooldown_s=0.0, up_queue_per_replica=100)
    assert pol.observe(_obs(queue=1, live=1, pressured=1),
                       now=0.0) == SCALE_UP


# ---------------------------------------------------------------------------
# tiered queue + overload ladder units
# ---------------------------------------------------------------------------

def _req(priority=STANDARD, arrival=None, deadline=None):
    r = Request(prompt=[1, 2], max_new_tokens=4, priority=priority)
    if arrival is not None:
        r.arrival_ts = arrival
    if deadline is not None:
        r.deadline_ts = deadline
    return r


def test_tiered_queue_orders_by_tier_then_fifo():
    tq = TieredQueue(aging_s=0)
    b = _req(BATCH, arrival=0.0)
    s1 = _req(STANDARD, arrival=1.0)
    s2 = _req(STANDARD, arrival=2.0)
    l1 = _req(LATENCY, arrival=3.0)
    for r in (b, s1, s2, l1):
        tq.append(r)
    assert [tq.popnext(now=4.0) for _ in range(4)] == [l1, s1, s2, b]


def test_tiered_queue_all_standard_is_exact_fifo():
    """The degeneration pin: single-tier traffic is the old deque — the
    strict-FIFO contract every round-8/11 test relies on."""
    tq = TieredQueue(aging_s=30.0)
    reqs = [_req(STANDARD, arrival=float(i)) for i in range(8)]
    for r in reqs:
        tq.append(r)
    assert list(tq) == reqs
    assert [tq.popnext(now=100.0) for _ in range(8)] == reqs


def test_tiered_queue_aging_floor_unstarves_batch():
    """A batch head older than aging_s competes at rank 0 — deferred,
    never starved."""
    tq = TieredQueue(aging_s=5.0)
    old_batch = _req(BATCH, arrival=0.0)
    young_lat = _req(LATENCY, arrival=8.0)
    tq.append(old_batch)
    tq.append(young_lat)
    # not yet aged: latency first
    assert tq.peeknext(now=4.0) is young_lat
    # aged past the floor: the batch head arrived first and now ties at
    # rank 0, so arrival order breaks the tie
    assert tq.peeknext(now=6.0) is old_batch


def test_tiered_queue_requeue_front_stays_in_own_tier():
    tq = TieredQueue(aging_s=0)
    s = _req(STANDARD, arrival=1.0)
    b1 = _req(BATCH, arrival=2.0)
    b2 = _req(BATCH, arrival=3.0)
    tq.append(s)
    tq.append(b2)
    tq.appendleft(b1)            # requeued batch: ahead of b2, behind s
    assert [tq.popnext(now=4.0) for _ in range(3)] == [s, b1, b2]


def test_admission_ladder_batch_highwater_and_hard_full():
    tq = TieredQueue(aging_s=0)
    for i in range(3):
        tq.append(_req(STANDARD, arrival=float(i)))
    # past the highwater fraction, NEW batch work is rejected
    # machine-readably while standard/latency still land
    with pytest.raises(AdmissionRejected) as ei:
        admit_or_shed(tq, _req(BATCH), max_queue=4, batch_highwater=0.5)
    assert ei.value.info["reason"] == "batch_highwater"
    assert "queue full" in str(ei.value)
    assert admit_or_shed(tq, _req(STANDARD, arrival=9.0),
                         max_queue=4, batch_highwater=0.5) is None
    # hard full + no lower tier to shed -> rejected, structured verdict
    with pytest.raises(AdmissionRejected) as ei:
        admit_or_shed(tq, _req(STANDARD), max_queue=4)
    info = ei.value.info
    assert info["error"] == "admission_rejected"
    assert info["reason"] == "queue_full" and info["max_queue"] == 4
    json.loads(str(ei.value).split(": ", 1)[1])   # message embeds JSON
    # hard full + a latency arrival: the YOUNGEST lowest-tier queued
    # request is shed to make room
    tq2 = TieredQueue(aging_s=0)
    b_old = _req(BATCH, arrival=0.0)
    b_young = _req(BATCH, arrival=5.0)
    for r in (b_old, _req(STANDARD, arrival=1.0), b_young,
              _req(STANDARD, arrival=2.0)):
        tq2.append(r)
    victim = admit_or_shed(tq2, _req(LATENCY), max_queue=4)
    assert victim is b_young
    assert len(tq2) == 4


# ---------------------------------------------------------------------------
# fleet end-to-end (thread placement; tiny model, token-exact oracles)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    model, cfg = build_model(
        "gpt2-tiny", hidden_size=32, num_layers=2, num_heads=2,
        vocab_size=64, max_seq_len=256, attention_impl="reference",
        dtype=jnp.float32)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, params


def _oracle_tokens(cfg, params, prompt, n):
    out = generate(cfg, params, jnp.asarray([list(prompt)]), n)
    return [int(x) for x in np.asarray(out)[0][len(prompt):]]


def _serving(replicas=1, autoscale=None, max_batch=2, **fleet_kw):
    fleet = {"replicas": replicas, "poll_interval": 0.05,
             "heartbeat_interval": 0.02, "heartbeat_timeout": 60.0}
    if autoscale:
        fleet["autoscale"] = autoscale
    fleet.update(fleet_kw)
    return {"block_size": 16, "pool_blocks": 64, "max_batch": max_batch,
            "max_blocks_per_seq": 8, "fleet": fleet}


_SNAPPY_AS = {"enabled": True, "min_replicas": 1, "max_replicas": 2,
              "up_queue_per_replica": 1, "up_after": 2,
              "down_idle_s": 0.3, "cooldown_s": 0.2}


def test_fleet_autoscale_up_then_drain_down_token_exact(tiny):
    """The tentpole loop, end to end: a queue burst scales the fleet up
    (warmed — the new replica never serves cold), outputs stay
    token-exact vs sequential generate(), the idle trough drains the
    scaled-up replica back down through the straggler-drain path (EXIT
    terminal stamp, not STALLED), and every verdict lands in the
    capacity ledger and the autoscaler's heartbeat rank."""
    cfg, params = tiny
    rng = np.random.default_rng(3)
    # uniform length: one prefill + one oracle compile (tier-1 budget)
    prompts = [list(rng.integers(1, 64, size=8)) for _ in range(6)]
    emitted = {}
    flt = ServingFleet(cfg, params, serving=_serving(
        replicas=1, autoscale=_SNAPPY_AS))
    reqs = [flt.submit(
        p, 10, on_token=lambda r, t: emitted.setdefault(r.rid, [])
        .append(t)) for p in prompts]
    try:
        flt.start()
        assert flt.drain(timeout=180)
        # drain() can return while the warm spawn is still compiling on
        # the supervisor thread; the event lands when the spawn finishes
        deadline = time.monotonic() + 60.0
        while flt.stats["scale_ups"] < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert flt.stats["scale_ups"] >= 1
        ups = [e for e in flt.scale_events if e.action == SCALE_UP]
        assert ups and ups[0].replica == 1 and "queue" in ups[0].reason
        for p, r in zip(prompts, reqs):
            oracle = _oracle_tokens(cfg, params, p, 10)
            assert r.state == FINISHED and r.output_tokens == oracle
            assert emitted[r.rid] == oracle
        # idle trough: the scaled-up replica drains back down
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if flt.stats["scale_downs"] >= 1 \
                    and len(flt.live_replicas()) == 1:
                break
            time.sleep(0.02)
        assert flt.stats["scale_downs"] >= 1, flt.scale_events
        downs = [e for e in flt.scale_events if e.action == SCALE_DOWN]
        assert downs and downs[0].drained_ts is not None
        assert downs[0].error is None            # clean drain, not death
        assert flt._replicas[downs[0].replica].state == RETIRED
        assert flt.stats["deaths"] == 0 and flt.stats["restarts"] == 0
        # evidence: the retired replica concluded with EXIT (not
        # STALLED/silent) and the autoscaler rank carries the ledger
        recs = hb.read_heartbeats(flt.heartbeat_dir)
        assert recs[downs[0].replica]["phase"] == hb.PHASE_EXIT
        asr = recs[AUTOSCALER_RANK]
        assert asr["gauges"]["role"] == "AUTOSCALER"
        assert asr["gauges"]["events"] == len(flt.scale_events)
    finally:
        flt.close()


def test_fleet_autoscale_scale_up_crash_rolls_back(tiny):
    """serve.scale_up crash matrix: a failed warmed spawn rolls the slot
    back (no phantom replica), records an ``up_failed`` event, and the
    fleet keeps serving every request to conclusion."""
    cfg, params = tiny
    rng = np.random.default_rng(5)
    prompts = [list(rng.integers(1, 64, size=7)) for _ in range(5)]
    flt = ServingFleet(cfg, params, serving=_serving(
        replicas=1, autoscale=_SNAPPY_AS))
    chaos.arm("serve.scale_up", "raise", times=1)
    reqs = [flt.submit(p, 10) for p in prompts]
    try:
        flt.start()
        assert flt.drain(timeout=180)
        assert chaos.fired("serve.scale_up")
        fails = [e for e in flt.scale_events if e.action == "up_failed"]
        assert fails and fails[0].error
        with flt._lock:
            assert all(r.idx == i for i, r in enumerate(flt._replicas))
        for p, r in zip(prompts, reqs):
            assert r.state == FINISHED
            assert r.output_tokens == _oracle_tokens(cfg, params, p, 10)
    finally:
        chaos.disarm()
        flt.close()


# tier-2 (round-19 budget, ~10s): the cheaper tier-1 cousins are
# test_fleet_autoscale_scale_up_crash_rolls_back (spawn-side crash)
# and test_fleet.test_fleet_kill_requeues_exactly_once_token_exact
# (the same requeue ledger, undrained); scripts/chaos.sh runs this leg
@pytest.mark.slow
def test_fleet_scale_down_during_kill_requeues_exactly_once(tiny):
    """Crash matrix: a DRAINING replica that dies mid-drain ends the
    drain by death — its lanes requeue through the exactly-once
    token-exact path, the death records action 'retired' (the
    autoscaler wanted the capacity gone: no strike, no replacement),
    and nothing double-emits."""
    cfg, params = tiny
    rng = np.random.default_rng(11)
    prompts = [list(rng.integers(1, 64, size=n))
               for n in (6, 10, 8, 12, 7, 9)]
    emitted = {}
    flt = ServingFleet(cfg, params, serving=_serving(replicas=2))
    reqs = [flt.submit(
        p, 16, on_token=lambda r, t: emitted.setdefault(r.rid, [])
        .append(t)) for p in prompts]
    try:
        flt.start()
        deadline = time.monotonic() + 30.0
        while not flt._replicas[1].inflight:
            assert time.monotonic() < deadline, "replica 1 never dispatched"
            time.sleep(0.001)
        flt._replicas[1].draining = True         # scale-down in flight
        chaos.arm("serve.replica_kill", "raise", match="1", skip=2)
        assert flt.drain(timeout=180)
        assert chaos.fired("serve.replica_kill")
        assert flt.stats["deaths"] == 1
        assert flt.deaths[0]["action"] == "retired"
        assert flt.stats["restarts"] == 0        # capacity stays gone
        assert flt.live_replicas() == [0]
        for p, r in zip(prompts, reqs):
            oracle = _oracle_tokens(cfg, params, p, 16)
            assert r.state == FINISHED and r.output_tokens == oracle
            assert emitted[r.rid] == oracle, \
                f"request {r.rid} re-fired or dropped a token"
    finally:
        chaos.disarm()
        flt.close()


def test_fleet_preemption_token_exact_no_retry_charge(tiny):
    """Deadline-pressured latency preempts the youngest RUNNING batch
    lane: the victim's emitted prefix is synced before eviction and it
    resumes token-exact (vs an uninjected sequential oracle) with NO
    retry-budget charge; the latency request takes the freed lane."""
    cfg, params = tiny
    rng = np.random.default_rng(17)
    bprompt = list(rng.integers(1, 64, size=9))
    lprompt = list(rng.integers(1, 64, size=6))
    emitted = {}
    flt = ServingFleet(cfg, params, serving=_serving(
        replicas=1, max_batch=1, preempt_pressure_s=30.0))
    batch_req = flt.submit(
        bprompt, 24, priority=BATCH,
        on_token=lambda r, t: emitted.setdefault(r.rid, []).append(t))
    try:
        flt.start()
        deadline = time.monotonic() + 30.0
        while not flt._replicas[0].inflight:
            assert time.monotonic() < deadline, "batch never dispatched"
            time.sleep(0.001)
        lat_req = flt.submit(lprompt, 8, priority=LATENCY, deadline_s=20.0)
        assert flt.drain(timeout=180)
        assert flt.stats["preempted"] == 1
        assert batch_req.preemptions == 1
        assert batch_req.retries == 0            # eviction is not failure
        for req, prompt, n in ((batch_req, bprompt, 24),
                               (lat_req, lprompt, 8)):
            oracle = _oracle_tokens(cfg, params, prompt, n)
            assert req.state == FINISHED and req.output_tokens == oracle
        assert emitted[batch_req.rid] == _oracle_tokens(
            cfg, params, bprompt, 24), "victim re-fired or lost a token"
    finally:
        flt.close()


# tier-2 (round-19 budget, ~9s): the cheaper tier-1 cousins are
# test_fleet_preemption_token_exact_no_retry_charge (clean preempt
# ledger) and the serve.preempt orphan economy asserted there; the
# death half rides test_fleet's kill legs; scripts/chaos.sh runs this
@pytest.mark.slow
def test_fleet_preempt_crash_then_replica_death_exactly_once(tiny):
    """Crash matrix: serve.preempt fires between eviction and requeue —
    the victim parks on the orphan list — and then the victim's OLD
    replica dies before the orphan retry lands. Nothing is lost and
    nothing double-emits: the orphan retry requeues the victim
    token-exactly (one retry charged, the documented orphan economy)
    and the death path requeues only what the dead replica still
    held."""
    cfg, params = tiny
    rng = np.random.default_rng(23)
    bprompts = [list(rng.integers(1, 64, size=n)) for n in (8, 10)]
    lprompt = list(rng.integers(1, 64, size=5))
    emitted = {}
    flt = ServingFleet(cfg, params, serving=_serving(
        replicas=2, max_batch=1, preempt_pressure_s=30.0))
    breqs = [flt.submit(
        p, 20, priority=BATCH,
        on_token=lambda r, t: emitted.setdefault(r.rid, [])
        .append(t)) for p in bprompts]
    chaos.arm("serve.preempt", "raise", times=1)
    try:
        flt.start()
        deadline = time.monotonic() + 30.0
        while not (flt._replicas[0].inflight and flt._replicas[1].inflight):
            assert time.monotonic() < deadline, "lanes never filled"
            time.sleep(0.001)
        lat_req = flt.submit(lprompt, 8, priority=LATENCY, deadline_s=20.0)
        deadline = time.monotonic() + 30.0
        while not chaos.fired("serve.preempt"):
            assert time.monotonic() < deadline, "preemption never fired"
            time.sleep(0.001)
        # the victim (replica 0's batch lane — _maybe_preempt walks the
        # replicas in order) is orphan-parked; now its old replica dies
        # before/while the orphan retry lands
        victim = next(r for r in breqs if r.preemptions >= 1)
        chaos.arm("serve.replica_kill", "raise", match="0", times=1)
        assert flt.drain(timeout=180)
        assert flt.stats["preempted"] == 1
        assert victim.preemptions == 1
        assert victim.retries >= 1               # the orphan retry charges
        for req, prompt, n in ((breqs[0], bprompts[0], 20),
                               (breqs[1], bprompts[1], 20),
                               (lat_req, lprompt, 8)):
            oracle = _oracle_tokens(cfg, params, prompt, n)
            assert req.state == FINISHED and req.output_tokens == oracle
        for req, prompt in zip(breqs, bprompts):
            assert emitted[req.rid] == _oracle_tokens(
                cfg, params, prompt, 20), \
                f"request {req.rid} re-fired or dropped a token"
    finally:
        chaos.disarm()
        flt.close()


def test_fleet_overload_ladder_sheds_and_rejects_machine_readably(tiny):
    """Admission under overload, fleet-level: expired work sheds with
    TIMEOUT (existing), a hard-full queue rejects same-tier arrivals
    with the machine-readable AdmissionRejected, and a latency arrival
    at a hard-full queue sheds the youngest batch victim (concluded
    SHED, callback fired, structured error) — never a hang, never a
    silent drop."""
    cfg, params = tiny
    rng = np.random.default_rng(29)
    flt = ServingFleet(cfg, params, serving=_serving(
        replicas=1, max_queue=3, batch_highwater=0.99))
    shed = []
    p = list(rng.integers(1, 64, size=5))
    flt.submit(p, 4, priority=STANDARD)
    flt.submit(p, 4, priority=STANDARD)
    victim = flt.submit(p, 4, priority=BATCH,
                        on_finish=lambda r: shed.append(r))
    # hard full, batch arrival, nothing below batch: structured reject
    with pytest.raises(AdmissionRejected) as ei:
        flt.submit(p, 4, priority=BATCH)
    assert ei.value.info["reason"] == "queue_full"
    assert "queue full" in str(ei.value)
    # hard full, latency arrival: the batch victim is shed to make room
    kept = flt.submit(p, 4, priority=LATENCY)
    assert victim.state == SHED and shed == [victim]
    assert json.loads(victim.error)["reason"] == "displaced_by_tier"
    assert flt.stats["shed"] == 1
    assert kept.rid in flt._outstanding
    flt.close()


def test_fleet_submit_rejects_unknown_tier(tiny):
    cfg, params = tiny
    flt = ServingFleet(cfg, params, serving=_serving(replicas=1))
    with pytest.raises(ValueError, match="priority tier"):
        flt.submit([1, 2, 3], 4, priority="urgent")
    flt.close()


def test_autoscale_refuses_disagg(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="disagg"):
        ServingFleet(cfg, params, serving=_serving(
            replicas=1, autoscale=_SNAPPY_AS, prefill_replicas=1,
            decode_replicas=1))


def test_serve_entry_forces_fleet_for_floor1_autoscale(tiny):
    """replicas=1 + autoscale.enabled through init_inference().serve()
    must return a STARTED fleet — the single-engine path has no
    supervisor to grow capacity (the verify drive caught serve()
    falling through to a bare ServingEngine)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import Transformer
    cfg, params = tiny
    srv = deepspeed_tpu.init_inference(
        Transformer(cfg),
        {"dtype": "float32",
         "serving": {"block_size": 16, "pool_blocks": 32, "max_batch": 2,
                     "max_blocks_per_seq": 8,
                     "fleet": {"replicas": 1, "poll_interval": 0.05,
                               "heartbeat_interval": 0.02,
                               "autoscale": dict(_SNAPPY_AS)}}},
        model_parameters=params).serve()
    try:
        assert isinstance(srv, ServingFleet)
        assert srv.autoscale is not None and srv.autoscale.max_replicas == 2
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# tier-2: process placement + the bench trace row (slow — OS processes /
# full bench plumbing; the tier-1 cousins are the thread-placement legs
# above plus the policy/ladder units)
# ---------------------------------------------------------------------------

# tier-2 (round-19 budget): the cheaper tier-1 cousins are
# test_fleet_autoscale_up_then_drain_down_token_exact (same loop, thread
# placement) and the policy units; scripts/chaos.sh runs this leg
@pytest.mark.slow
def test_procfleet_autoscale_up_then_drain_down_token_exact(tiny, tmp_path):
    """The tentpole loop on the PROCESS placement: burst -> warmed
    worker-process spawn -> token-exact outputs -> idle trough ->
    drain, RETIRE, and a clean rc-0 worker exit (no death verdict)."""
    from deepspeed_tpu.serving.procfleet import ProcessFleet
    cfg, params = tiny
    rng = np.random.default_rng(31)
    prompts = [list(rng.integers(1, 64, size=n))
               for n in (5, 9, 7, 11, 6, 8)]
    scfg = _serving(replicas=1, autoscale=dict(_SNAPPY_AS, down_idle_s=0.5),
                    placement="process")
    flt = ProcessFleet(cfg, params, serving=scfg, log_dir=str(tmp_path))
    reqs = [flt.submit(p, 10) for p in prompts]
    try:
        flt.start()
        assert flt.drain(timeout=300)
        deadline = time.monotonic() + 60.0
        while flt.stats["scale_ups"] < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert flt.stats["scale_ups"] >= 1, flt.scale_events
        for p, r in zip(prompts, reqs):
            oracle = _oracle_tokens(cfg, params, p, 10)
            assert r.state == FINISHED and r.output_tokens == oracle
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if flt.stats["scale_downs"] >= 1 \
                    and len(flt.live_replicas()) == 1:
                break
            time.sleep(0.05)
        assert flt.stats["scale_downs"] >= 1, flt.scale_events
        downs = [e for e in flt.scale_events if e.action == SCALE_DOWN]
        assert downs[0].drained_ts is not None and downs[0].error is None
        assert flt.stats["deaths"] == 0          # drain, not death
        rep = flt._replicas[downs[0].replica]
        assert rep.state == RETIRED
        deadline = time.monotonic() + 30.0
        while rep.proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        assert rep.proc.poll() == 0              # clean stop, not a kill
    finally:
        flt.close()


# tier-2 (round-19 budget): the cheaper tier-1 cousin is
# test_fleet_preemption_token_exact_no_retry_charge (same contract,
# thread placement); scripts/chaos.sh runs this leg
@pytest.mark.slow
def test_procfleet_preempt_cancel_token_exact(tiny, tmp_path):
    """Preemption across the process boundary: the hub cancels the
    victim's lane in its worker, requeues it hub-side from the
    cumulative ledger, and both requests finish token-exact with no
    retry charge on the victim."""
    from deepspeed_tpu.serving.procfleet import ProcessFleet
    cfg, params = tiny
    rng = np.random.default_rng(37)
    bprompt = list(rng.integers(1, 64, size=9))
    lprompt = list(rng.integers(1, 64, size=6))
    scfg = _serving(replicas=1, max_batch=1, preempt_pressure_s=60.0,
                    placement="process")
    flt = ProcessFleet(cfg, params, serving=scfg, log_dir=str(tmp_path))
    try:
        flt.start()
        flt.warmup(timeout=240)
        batch_req = flt.submit(bprompt, 48, priority=BATCH)
        deadline = time.monotonic() + 60.0
        while not flt._replicas[0].inflight:
            assert time.monotonic() < deadline, "batch never dispatched"
            time.sleep(0.005)
        lat_req = flt.submit(lprompt, 8, priority=LATENCY, deadline_s=50.0)
        assert flt.drain(timeout=300)
        assert flt.stats["preempted"] == 1
        assert batch_req.preemptions == 1 and batch_req.retries == 0
        for req, prompt, n in ((batch_req, bprompt, 48),
                               (lat_req, lprompt, 8)):
            oracle = _oracle_tokens(cfg, params, prompt, n)
            assert req.state == FINISHED and req.output_tokens == oracle
    finally:
        flt.close()


# tier-2 (round-19 budget): the cheaper tier-1 cousins are the thread
# autoscale leg above and test_serving.test_inference_bench_poisson_line
# (row plumbing); scripts/chaos.sh runs this leg
@pytest.mark.slow
def test_inference_bench_trace_autoscale_row(capsys):
    """--poisson --trace prints the machine-readable poisson_autoscale
    row: scale events, per-tier p99, and a clean drain back to the
    floor."""
    from deepspeed_tpu.benchmarks.inference_bench import (
        parse_trace, run_poisson_autoscale)
    trace = parse_trace("2@1.5,8@2,2@1.5")
    row = run_poisson_autoscale(
        "gpt2-tiny", trace, prompt_len=8, new_tokens=8,
        serving={"block_size": 16, "pool_blocks": 64, "max_batch": 2,
                 "max_blocks_per_seq": 8},
        max_replicas=2,
        model_kwargs={"hidden_size": 32, "num_layers": 2, "num_heads": 2,
                      "vocab_size": 64, "attention_impl": "reference",
                      "dtype": jnp.float32})
    line = next(ln for ln in capsys.readouterr().out.splitlines()
                if ln.startswith("inference_bench poisson_autoscale: "))
    parsed = json.loads(line.split(": ", 1)[1])
    assert parsed == row
    assert row["mode"] == "poisson_autoscale"
    assert row["burst_rate"] == 8.0 and row["rate"] == 2.0
    assert row["completed"] == row["requests"] > 0
    assert row["failed"] == 0 and row["timeout"] == 0
    assert row["clean_drain"] is True
    assert set(row["p99_by_tier"]) <= {"latency", "standard", "batch"}
