"""ProcessTopology / PipelineParallelGrid tests.

Mirrors reference tests/unit/runtime/pipe/test_topology.py (pure python, no devices).
"""

import pytest

from deepspeed_tpu.parallel.topology import (
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    PipelineParallelGrid,
    ProcessTopology,
)


def test_rank_coord_roundtrip():
    topo = ProcessTopology(axes=["pipe", "data", "model"], dims=[2, 2, 2])
    assert topo.world_size() == 8
    for rank in range(8):
        c = topo.get_coord(rank)
        assert topo.get_rank(pipe=c.pipe, data=c.data, model=c.model) == rank


def test_row_major_layout():
    # last axis varies fastest (reference topology.py layout)
    topo = ProcessTopology(axes=["pipe", "data"], dims=[2, 4])
    assert topo.get_rank(pipe=0, data=0) == 0
    assert topo.get_rank(pipe=0, data=3) == 3
    assert topo.get_rank(pipe=1, data=0) == 4


def test_axis_comm_lists():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=4)
    dp_lists = topo.get_axis_comm_lists("data")
    assert [0, 1, 2, 3] in dp_lists and [4, 5, 6, 7] in dp_lists
    pp_lists = topo.get_axis_comm_lists("pipe")
    assert [0, 4] in pp_lists


def test_filter_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.filter_match(pipe=0) == [0, 1, 2, 3]


def test_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert "pipe" in topo.get_rank_repr(0) and "model" in topo.get_rank_repr(0)
    assert "data" not in topo.get_rank_repr(0)  # data axis omitted in ckpt names


def test_grid_stage_mapping():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    grid = PipelineParallelGrid(topo, global_rank=0)
    assert grid.get_pipe_parallel_world_size() == 2
    assert grid.get_data_parallel_world_size() == 2
    assert grid.get_model_parallel_world_size() == 2
    assert grid.is_first_stage()
    nxt = grid.stage_to_global(1)
    c = topo.get_coord(nxt)
    assert c.pipe == 1 and c.data == 0 and c.model == 0


def test_duplicate_axes_raise():
    with pytest.raises(ValueError):
        ProcessTopology(axes=["a", "a"], dims=[2, 2])
