"""Shared test fixtures: tiny models + random data.

Mirrors the reference's tests/unit/simple_model.py model zoo.
"""

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn


class SimpleModel(nn.Module):
    """Classification MLP whose loss is directly returned (DeepSpeed contract)."""
    hidden: int = 32
    nclass: int = 8
    nlayers: int = 2

    @nn.compact
    def __call__(self, batch, train=False):
        x, y = batch["x"], batch["y"]
        h = x
        for _ in range(self.nlayers):
            h = nn.relu(nn.Dense(self.hidden)(h))
        logits = nn.Dense(self.nclass)(h)
        logp = nn.log_softmax(logits)
        return -jnp.mean(jnp.sum(jax.nn.one_hot(y, self.nclass) * logp, axis=-1))


def random_batch(batch_size: int, dim: int = 16, nclass: int = 8, seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.randn(batch_size, dim).astype(np.float32)
    y = (x[:, :nclass].argmax(-1)).astype(np.int32)  # learnable labels
    return {"x": x, "y": y}


def batch_stream(batch_size: int, dim: int = 16, nclass: int = 8, seed: int = 0):
    i = seed
    while True:
        yield random_batch(batch_size, dim, nclass, seed=i)
        i += 1


def tree_allclose(a, b, rtol=1e-5, atol=1e-6):
    ok = jax.tree.map(
        lambda x, y: np.allclose(np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)
    return all(jax.tree.leaves(ok))


def require_devices(n: int):
    """Skip when the active platform exposes fewer than n devices (the
    reference's requires_cuda_env pattern, tests/unit/common.py:78 — here
    the axis is device count: DSTPU_TEST_PLATFORM=tpu on a single chip
    cannot host the virtual multi-chip meshes the CPU suite uses)."""
    import jax
    import pytest
    if len(jax.devices()) < n:
        pytest.skip(f"needs >= {n} devices; platform has {len(jax.devices())}")
