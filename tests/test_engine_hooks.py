"""Small engine hooks: eigenvalue, progressive layer drop, MoQ, sparse
embedding grads, TiledLinear.

Mirrors the reference's tests for runtime/eigenvalue.py,
progressive_layer_drop.py, quantize.py, sparse_tensor.py, zero/tiling.py.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from util import SimpleModel, random_batch


@pytest.fixture(scope="module")
def data_mesh():
    from deepspeed_tpu.parallel.mesh import MeshManager
    return MeshManager()   # data axis = 8


def test_eigenvalue_quadratic_exact():
    """For loss = 0.5 x^T A x the max |eigenvalue| is known exactly."""
    from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
    rng = np.random.RandomState(0)
    Q, _ = np.linalg.qr(rng.randn(8, 8))
    eigs = np.array([5.0, 3.0, 1.0, 0.5, 0.2, 0.1, 0.05, 0.01])
    A = jnp.asarray(Q @ np.diag(eigs) @ Q.T, jnp.float32)

    def loss(params):
        x = params["x"]
        return 0.5 * x @ A @ x

    ev = Eigenvalue(max_iter=200, tol=1e-5)
    got = ev.compute_eigenvalue(loss, {"x": jnp.ones(8)})
    assert abs(got - 5.0) < 0.05, got


def test_engine_compute_eigenvalue():
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "eigenvalue": {"enabled": True, "max_iter": 30, "tol": 1e-2}}
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                               example_batch=random_batch(8))
    eig = engine.compute_eigenvalue(random_batch(8))
    assert np.isfinite(eig) and eig >= 0


def test_pld_schedule_math():
    from deepspeed_tpu.runtime.progressive_layer_drop import \
        ProgressiveLayerDrop
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta() == 1.0
    t100 = pld.update_state(100)
    assert abs(t100 - (0.5 * math.exp(-1.0) + 0.5)) < 1e-9
    assert abs(pld.update_state(10 ** 6) - 0.5) < 1e-6
    assert pld.get_state()["progressive_layer_drop"]


@pytest.mark.slow
def test_pld_model_trains_and_drops():
    """PLD engine run: theta ramps down, layers drop stochastically in
    training, eval is deterministic full-depth."""
    from deepspeed_tpu.models import build_model, causal_lm_loss
    model, cfg = build_model("gpt2-tiny", num_layers=4, pld=True,
                             max_seq_len=64, attention_impl="reference",
                             dtype=jnp.float32)
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "progressive_layer_drop": {"enabled": True, "theta": 0.3,
                                   "gamma": 0.01},
    }
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 32))
    engine, *_ = ds.initialize(model=model, config=config,
                               loss_fn=causal_lm_loss,
                               example_batch={"input_ids": ids})
    assert engine.progressive_layer_drop is not None
    for i in range(4):
        m = engine.train_batch({"input_ids": np.random.default_rng(i).integers(
            0, cfg.vocab_size, (8, 32))})
        assert np.isfinite(float(m["loss"]))
    assert engine.progressive_layer_drop.get_theta() < 1.0
    # eval: no pld rng -> deterministic full depth
    l1 = engine.eval_batch({"input_ids": ids})
    l2 = engine.eval_batch({"input_ids": ids})
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


def test_moq_spec_and_engine():
    from deepspeed_tpu.runtime.quantize import build_moq_spec
    qt = {"enabled": True,
          "quantize_bits": {"start_bits": 16, "target_bits": 8},
          "quantize_schedule": {"quantize_period": 50, "schedule_offset": 2},
          "quantize_groups": 1}
    spec = build_moq_spec(qt)
    assert spec.groups[0].start_bits == 16
    assert build_moq_spec({"enabled": False}) is None
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 5e-3}},
           "quantize_training": qt}
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                               example_batch=random_batch(8))
    assert engine.compression_spec is not None
    assert any(g.name == "moq" for g in engine.compression_spec.groups)
    losses = [float(engine.train_batch(random_batch(8, seed=i))["loss"])
              for i in range(12)]
    assert losses[-1] < losses[0]


def test_sparse_embedding_grads(data_mesh):
    """Sparse (ids, rows) exchange == dense grad psum, with far fewer wire
    bytes (reference: engine sparse_allreduce_bucket)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.utils.sparse_grads import (SparseTensor,
                                                  embedding_grad_sparse,
                                                  sparse_allreduce)
    mesh = data_mesh.mesh
    n, V, H, T = 8, 100, 16, 12
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (n, T)))
    rows = jnp.asarray(rng.standard_normal((n, T, H)), jnp.float32)

    def per_rank(ids, rows):
        st = embedding_grad_sparse(ids[0], rows[0], V)
        return sparse_allreduce(st, "data")[None]

    out = jax.jit(jax.shard_map(
        per_rank, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=P("data"), check_vma=False))(ids, rows)
    dense = np.zeros((V, H), np.float32)
    for r in range(n):
        for t in range(T):
            dense[int(ids[r, t])] += np.asarray(rows[r, t])
    np.testing.assert_allclose(np.asarray(out)[0], dense, rtol=1e-5,
                               atol=1e-5)
    st = SparseTensor.from_dense(jnp.asarray(dense), ids[0])
    assert st.sparse_size() < V * H          # the wire-byte point


def test_tiled_linear_matches_dense():
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear
    x = jnp.asarray(np.random.RandomState(0).randn(4, 32), jnp.float32)
    tl = TiledLinear(features=24, in_splits=4, out_splits=3)
    params = tl.init(jax.random.PRNGKey(0), x)["params"]
    y = tl.apply({"params": params}, x)
    # assemble the equivalent dense kernel from the tiles
    K = np.zeros((32, 24), np.float32)
    for i in range(4):
        for j in range(3):
            K[i * 8:(i + 1) * 8, j * 8:(j + 1) * 8] = \
                np.asarray(params[f"kernel_{i}_{j}"])
    ref = x @ K + np.asarray(params["bias"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5,
                               atol=1e-6)
    with pytest.raises(ValueError, match="divisible"):
        TiledLinear(features=24, in_splits=5).init(jax.random.PRNGKey(0), x)


def test_moq_eigenvalue_rescale():
    """Curvature-paced MoQ: the schedule period stretches by the measured
    eigenvalue ratio (capped)."""
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "eigenvalue": {"enabled": True, "max_iter": 10, "tol": 1e-1},
           "quantize_training": {
               "enabled": True,
               "quantize_bits": {"start_bits": 16, "target_bits": 8},
               "quantize_schedule": {"quantize_period": 40,
                                     "schedule_offset": 0}}}
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                               example_batch=random_batch(8))
    spec1 = engine.moq_rescale(random_batch(8))       # baseline measurement
    p1 = [g.quantization_period for g in spec1.groups]
    spec2 = engine.moq_rescale(random_batch(8, seed=5))
    p2 = [g.quantization_period for g in spec2.groups]
    assert all(b >= a for a, b in zip(p1, p2))        # never shrinks
    engine.train_batch(random_batch(8))               # still trains


@pytest.mark.slow
def test_profile_trace(tmp_path):
    """engine.profile_trace captures an xplane trace (SURVEY §5 tracing)."""
    import glob
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg,
                               example_batch=random_batch(8))
    out = engine.profile_trace(str(tmp_path / "trace"),
                               [random_batch(8, seed=i) for i in range(3)])
    assert glob.glob(out + "/**/*.xplane.pb", recursive=True)
