"""Training-integrity sentinel (round 7): rolling robust detector,
remediation ladder (in-jit skip -> verified rollback + data fast-forward
-> rc-118 abort), folded non-finite guard, cross-replica SDC audit, and
the audited-clean resume marker.

The plain-python halves (RollingRobust, observe() ladder, checksum vote,
markers, config shim, dataloader fast-forward) are tier-1 sub-second.
The engine-in-anger chaos matrices (spike->skip parity, spike-storm->
rollback, post-rollback abort, SDC bit-flip) build real engines and are
``slow``-marked — ``scripts/chaos.sh`` runs them; the compile-count and
single-device-get gates stay tier-1 because they pin the acceptance
criterion that the sentinel adds ZERO extra device syncs.
"""

import math
import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.config.config import IntegrityConfig
from deepspeed_tpu.runtime import sentinel as sl
from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              RepeatingLoader)
from deepspeed_tpu.runtime.sentinel import (NonFiniteError, RollingRobust,
                                            TrainingIntegrityError,
                                            TrainingSentinel,
                                            compare_replica_checksums)
from deepspeed_tpu.testing import chaos
from tests.util import SimpleModel, batch_stream, random_batch


# ------------------------------------------------------------ RollingRobust

def test_rolling_robust_median_mad():
    r = RollingRobust(window=8)
    for v in (1.0, 2.0, 3.0, 4.0, 5.0):
        r.push(v)
    med, sigma = r.stats()
    assert med == 3.0
    assert sigma == pytest.approx(1.4826, rel=1e-6)    # MAD = 1.0


def test_rolling_robust_needs_four_samples():
    r = RollingRobust(window=8)
    for v in (1.0, 2.0, 3.0):
        r.push(v)
    assert r.stats() is None and r.zscore(10.0) is None \
        and r.threshold(3.0) is None


def test_rolling_robust_outlier_cannot_drag_baseline():
    # the median/MAD baseline must survive the very anomaly it detects —
    # a mean/std would be dragged by the 1e6 sample, a median is not
    r = RollingRobust(window=16)
    for v in (1.0, 1.1, 0.9, 1.0, 1.05, 0.95):
        r.push(v)
    z_before = r.zscore(1e6)
    r.push(1e6)
    med, _ = r.stats()
    assert med < 1.2
    assert r.zscore(1e6) > 0.5 * z_before


def test_rolling_robust_flat_warmup_sigma_floor():
    # a perfectly flat window (MAD 0) must not turn the first jitter into
    # an anomaly: sigma is floored at 1e-3 x max(|median|, 1)
    r = RollingRobust(window=8)
    for _ in range(6):
        r.push(10.0)
    med, sigma = r.stats()
    assert med == 10.0 and sigma == pytest.approx(0.01)
    assert r.zscore(10.001) < 1.0


def test_rolling_robust_window_bound():
    r = RollingRobust(window=4)
    for v in range(100):
        r.push(float(v))
    assert len(r) == 4
    assert r.stats()[0] == pytest.approx(97.5)


# ------------------------------------------------------- observe() ladder

def _sentinel(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("metrics", ["loss", "grad_norm"])
    kw.setdefault("window", 16)
    kw.setdefault("zmax", 5.0)
    kw.setdefault("warmup_steps", 4)
    kw.setdefault("cooldown_steps", 0)
    kw.setdefault("rollback_after", 2)
    kw.setdefault("strike_window", 10)
    kw.setdefault("abort_after_rollbacks", 1)
    return TrainingSentinel(IntegrityConfig(**kw))


def _feed_clean(s, n, start=0):
    rng = np.random.default_rng(0)
    for i in range(n):
        v = 1.0 + 0.01 * float(rng.standard_normal())
        assert s.observe(start + i, {"loss": v, "grad_norm": v}) == sl.OK
    return start + n


def test_observe_warmup_no_verdict():
    s = _sentinel(warmup_steps=10)
    # wild values during warmup: samples accumulate, nothing strikes
    for i, v in enumerate((1.0, 50.0, 2.0, 80.0, 1.5)):
        assert s.observe(i, {"loss": v, "grad_norm": v}) == sl.OK
    assert not s.strikes


def test_observe_spike_strikes_then_rolls_back_then_aborts():
    s = _sentinel()
    step = _feed_clean(s, 6)
    spike = {"loss": 100.0, "grad_norm": 100.0}
    assert s.observe(step, spike) == sl.STRIKE
    assert s.observe(step + 1, spike) == sl.ROLLBACK       # rollback_after=2
    s.note_rollback(restored_step=step - 2)
    assert s.rollbacks_done == 1
    # the anomaly reproduces post-rollback: ladder rung 3
    t = step + 2
    assert s.observe(t, spike) == sl.STRIKE
    with pytest.raises(TrainingIntegrityError) as ei:
        s.observe(t + 1, spike)
    assert ei.value.exit_code == sl.INTEGRITY_EXIT_CODE == 118


def test_observe_cooldown_counts_one_event_once():
    s = _sentinel(cooldown_steps=5, rollback_after=3)
    step = _feed_clean(s, 6)
    spike = {"loss": 100.0, "grad_norm": 100.0}
    assert s.observe(step, spike) == sl.STRIKE
    assert s.observe(step + 1, spike) == sl.COOLDOWN       # same event
    assert s.observe(step + 2, spike) == sl.COOLDOWN
    assert len(s.strikes) == 1


def test_observe_strikes_age_out_of_window():
    s = _sentinel(rollback_after=2, strike_window=5)
    step = _feed_clean(s, 6)
    spike = {"loss": 100.0, "grad_norm": 100.0}
    assert s.observe(step, spike) == sl.STRIKE
    step = _feed_clean(s, 8, start=step + 1)               # > strike_window
    assert s.observe(step, spike) == sl.STRIKE             # not ROLLBACK


def test_observe_clean_stretch_retires_rollback_arm():
    s = _sentinel(strike_window=5)
    step = _feed_clean(s, 6)
    s.note_rollback(restored_step=step)
    assert s.rollbacks_done == 1
    _feed_clean(s, 8, start=step + 1)                      # > strike_window
    assert s.rollbacks_done == 0                           # rollback worked


def test_observe_anomalous_sample_never_pollutes_baseline():
    s = _sentinel(cooldown_steps=0, rollback_after=99)
    step = _feed_clean(s, 8)
    med_before = s.stats["loss"].stats()[0]
    for i in range(4):
        assert s.observe(step + i,
                         {"loss": 100.0, "grad_norm": 100.0}) == sl.STRIKE
    assert s.stats["loss"].stats()[0] == pytest.approx(med_before)


def test_observe_in_jit_skip_strikes_without_baseline_damage():
    s = _sentinel(rollback_after=99)
    step = _feed_clean(s, 6)
    accepted = s.accepted
    v = s.observe(step, {"loss": 1.0, "grad_norm": 50.0, "anomaly_skip": 1})
    assert v == sl.STRIKE
    assert "batch skipped" in s.last_anomaly
    assert s.accepted == accepted                  # skipped step: no sample


def test_nonfinite_fold_raises_even_with_detector_off():
    # the PR-3 nonfinite_guard semantics live in the SAME observe() path
    s = TrainingSentinel(IntegrityConfig(enabled=False,
                                         nonfinite_abort_after=3))
    assert s.observe(5, {"nonfinite_streak": 2}) == sl.OK
    with pytest.raises(NonFiniteError) as ei:
        s.observe(6, {"nonfinite_streak": 3})
    assert isinstance(ei.value, TrainingIntegrityError)
    assert ei.value.exit_code == 118


def test_disabled_sentinel_is_inert():
    s = TrainingSentinel(IntegrityConfig(enabled=False))
    assert not s.wants_every_step
    assert s.spike_limit() is None
    assert s.observe(1, {"loss": float("inf")}) == sl.OK


def test_spike_limit_inf_during_warmup_then_finite():
    s = _sentinel(warmup_steps=4, zmax=5.0)
    assert s.spike_limit() == math.inf             # arg shape never changes
    _feed_clean(s, 6)
    thr = s.spike_limit()
    assert math.isfinite(thr) and thr > 1.0
    s2 = _sentinel(skip=False)
    assert s2.spike_limit() is None                # rung 1 off: no jit arm


def test_spike_limit_arms_even_without_grad_norm_in_metrics():
    # dropping grad_norm from cfg.metrics must not silently kill the skip
    # rung: its stats are tracked whenever skip is on
    s = _sentinel(metrics=["loss"])
    _feed_clean(s, 6)
    assert math.isfinite(s.spike_limit())


# --------------------------------------------------------- checksum vote

def test_checksum_vote_unanimous_and_minority():
    assert compare_replica_checksums([("a", 1), ("b", 1), ("c", 1)]) == []
    assert compare_replica_checksums(
        [("a", 1), ("b", 1), ("c", 2)]) == ["c"]
    assert compare_replica_checksums(
        [("a", 7), ("b", 3), ("c", 7), ("d", 7)]) == ["b"]


def test_checksum_vote_tie_implicates_everyone():
    # 1-vs-1: the mismatch is certain, the culprit is not
    assert set(compare_replica_checksums([("a", 1), ("b", 2)])) == {"a", "b"}
    assert set(compare_replica_checksums(
        [("a", 1), ("b", 1), ("c", 2), ("d", 2)])) == {"a", "b", "c", "d"}


def test_checksum_vote_degenerate_inputs():
    assert compare_replica_checksums([]) == []
    assert compare_replica_checksums([("a", 1)]) == []


def test_audited_clean_marker_roundtrip(tmp_path):
    assert sl.read_last_audited_clean(str(tmp_path)) is None
    sl.write_last_audited_clean(str(tmp_path), "global_step40")
    assert sl.read_last_audited_clean(str(tmp_path)) == "global_step40"
    sl.write_last_audited_clean(str(tmp_path), "global_step50")
    assert sl.read_last_audited_clean(str(tmp_path)) == "global_step50"
    assert os.listdir(str(tmp_path)) == [sl.LAST_AUDITED_CLEAN_FILE]
    # failures are swallowed: the marker is an optimization, never a gate
    sl.write_last_audited_clean(str(tmp_path / "no" / "such"), "t")


# ----------------------------------------------------------- config shim

def test_nonfinite_guard_alias_folds_into_integrity():
    cfg = DeepSpeedConfig(nonfinite_guard={"abort_after": 7})
    assert cfg.integrity.nonfinite_abort_after == 7


def test_explicit_integrity_wins_over_alias():
    cfg = DeepSpeedConfig(nonfinite_guard={"abort_after": 7},
                          integrity={"nonfinite_abort_after": 3})
    assert cfg.integrity.nonfinite_abort_after == 3


# ------------------------------------------------- dataloader fast-forward

def _loader(n=64, batch=8, **kw):
    data = [np.asarray([i], np.float32) for i in range(n)]
    return DeepSpeedDataLoader(data, batch_size=batch, **kw)


def test_dataloader_fast_forward_matches_uninterrupted_stream():
    a, b = _loader(), _loader()                    # 8 batches/epoch
    stream = RepeatingLoader(a)
    for _ in range(11):                            # 1 epoch + 3 batches
        next(stream)
    b.fast_forward(11)
    np.testing.assert_array_equal(next(iter(b)), next(stream))
    assert b.epoch == a.epoch


def test_dataloader_fast_forward_one_partial_epoch_then_full():
    dl = _loader(n=32, batch=8)                    # 4 batches/epoch
    dl.fast_forward(6)
    assert dl.epoch == 1
    first_epoch = list(dl)
    assert len(first_epoch) == 2                   # resumes mid-epoch
    assert len(list(dl)) == 4                      # then full epochs again


def test_dataloader_forwards_epoch_to_sampler():
    # the torch set_epoch idiom: an epoch-aware sampler re-derives its
    # order per epoch, which keeps fast_forward honest for it too
    class Sampler:
        def __init__(self):
            self.epochs = []

        def set_epoch(self, e):
            self.epochs.append(e)

        def __iter__(self):
            return iter(range(16))

    smp = Sampler()
    dl = DeepSpeedDataLoader([np.asarray([i]) for i in range(16)],
                             batch_size=4, data_sampler=smp)
    dl.fast_forward(6)                             # epoch 1, batch 2
    assert len(list(dl)) == 2
    assert smp.epochs == [1]


def test_repeating_loader_fast_forward_delegates_and_drains():
    inner = _loader(n=16, batch=4)
    rep = RepeatingLoader(inner)
    rep.fast_forward(5)                            # delegates O(1)
    assert inner.epoch == 1
    ref = RepeatingLoader(_loader(n=16, batch=4))
    for _ in range(5):
        next(ref)
    np.testing.assert_array_equal(next(rep), next(ref))
    # a bare iterable has no fast_forward: RepeatingLoader drains
    rep2 = RepeatingLoader([np.asarray([i]) for i in range(6)])
    rep2.fast_forward(2)
    np.testing.assert_array_equal(next(rep2), np.asarray([2]))


# ------------------------------------------------------ engine integration

def _engine(extra_integrity=None, stage=1, **cfg_extra):
    integ = {"enabled": True, "warmup_steps": 6, "window": 16,
             "zmax": 6.0, "cooldown_steps": 0}
    integ.update(extra_integrity or {})
    cfg = {
        "train_batch_size": 32,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
        "bf16": {"enabled": True},
        "steps_per_print": 1000,
        "integrity": integ,
    }
    cfg.update(cfg_extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(), config=cfg, example_batch=random_batch(4))
    return engine


def test_sentinel_stats_add_zero_extra_device_syncs(monkeypatch):
    """Acceptance gate: with the detector ON (every-step host feed),
    _after_step still performs exactly ONE batched device_get per step and
    the train step still compiles once. The sentinel's statistics ride the
    existing pull; the spike-limit feed is a device scalar argument."""
    import jax
    engine = _engine()
    cache_size = getattr(engine._train_step, "_cache_size", None)
    stream = batch_stream(engine.config.train_batch_size)
    engine.train_batch(next(stream))               # compile outside count
    real = jax.device_get
    calls = []
    monkeypatch.setattr(jax, "device_get",
                        lambda x: calls.append(1) or real(x))
    for _ in range(3):
        engine.train_batch(next(stream))
    assert len(calls) == 3, (
        f"{len(calls)} device_get calls across 3 steps — the sentinel "
        "must ride the ONE batched _after_step pull")
    if cache_size is not None:
        assert cache_size() == 1, (
            f"train step traced {cache_size()}x with integrity enabled")


@pytest.mark.slow
def test_chaos_spike_skipped_in_jit_reaches_loss_parity():
    """Ladder rung 1 end-to-end: a chaos-poisoned batch (x1e4 features)
    is skipped IN-JIT by the sentinel's grad-norm ceiling — state
    untouched, streak counted — and the run trains through to loss parity
    with an uninjected twin."""
    import jax
    clean = _engine()
    stream = batch_stream(clean.config.train_batch_size)
    clean_losses = [float(jax.device_get(
        clean.train_batch(next(stream))["loss"])) for _ in range(30)]

    chaos.arm("sentinel.spike", "flag", skip=14, times=1, factor=10000)
    eng = _engine()
    stream = batch_stream(eng.config.train_batch_size)
    skipped_at = []
    losses = []
    for i in range(30):
        m = eng.train_batch(next(stream))
        losses.append(float(jax.device_get(m["loss"])))
        if "anomaly_skip" in m and bool(np.asarray(
                jax.device_get(m["anomaly_skip"]))):
            skipped_at.append(i + 1)
    assert skipped_at == [15], skipped_at
    assert int(jax.device_get(eng.state.skipped_steps)) == 1
    assert eng.sentinel.rollbacks_done == 0        # rung 1 was enough
    # loss parity with the uninjected twin: the poisoned batch cost one
    # skipped update and zero state damage
    assert losses[-1] < losses[0] * 0.8
    assert losses[-1] == pytest.approx(clean_losses[-1], rel=0.25)


@pytest.mark.slow
def test_chaos_spike_storm_rolls_back_and_fast_forwards(tmp_path):
    """Ladder rung 2 end-to-end: with the skip rung off, a 3-batch spike
    storm damages state, strikes out the window, and the engine restores
    the last intact tag via the verified loader — while data_position is
    NOT rewound, so the poisoned span is never replayed."""
    import jax
    eng = _engine({"skip": False, "rollback_after": 3, "strike_window": 20,
                   "abort_after_rollbacks": 1})
    stream = batch_stream(eng.config.train_batch_size)
    for _ in range(10):
        eng.train_batch(next(stream))
    eng.save_checkpoint(str(tmp_path), tag="clean10")
    chaos.arm("sentinel.spike", "flag", skip=0, times=3, factor=10000)
    for _ in range(3):
        eng.train_batch(next(stream))
    assert eng.sentinel.rollbacks_done == 1
    assert eng.global_steps == 10                  # restored tag
    assert eng.data_position == 13                 # pipeline NOT rewound
    # clean data resumes training from the restored state
    losses = [float(jax.device_get(eng.train_batch(next(stream))["loss"]))
              for _ in range(10)]
    assert np.isfinite(losses).all()
    assert eng.sentinel.last_verdict in (sl.OK, sl.STRIKE)

    # the restored engine can reposition a fresh loader past the span
    dl = _loader(n=1024, batch=32)
    n = eng.fast_forward_dataloader(dl)
    assert n == eng.data_position
    assert dl._start_batch == eng.data_position % len(dl)


@pytest.mark.slow
def test_chaos_spike_reproduced_post_rollback_aborts_rc118(tmp_path):
    """Ladder rung 3 end-to-end: a spike that reproduces after a rollback
    is not the data — abort with the rc-118 integrity contract."""
    eng = _engine({"skip": False, "rollback_after": 2, "strike_window": 20,
                   "abort_after_rollbacks": 1})
    stream = batch_stream(eng.config.train_batch_size)
    for _ in range(10):
        eng.train_batch(next(stream))
    eng.save_checkpoint(str(tmp_path), tag="clean10")
    chaos.arm("sentinel.spike", "flag", skip=0, times=8, factor=10000)
    with pytest.raises(TrainingIntegrityError) as ei:
        for _ in range(10):
            eng.train_batch(next(stream))
    assert ei.value.exit_code == 118
    assert eng.sentinel.rollbacks_done == 1


@pytest.mark.slow
def test_rollback_without_checkpoint_aborts_loudly():
    eng = _engine({"skip": False, "rollback_after": 2, "strike_window": 20})
    stream = batch_stream(eng.config.train_batch_size)
    for _ in range(8):
        eng.train_batch(next(stream))
    chaos.arm("sentinel.spike", "flag", skip=0, times=4, factor=10000)
    with pytest.raises(TrainingIntegrityError, match="no checkpoint"):
        for _ in range(4):
            eng.train_batch(next(stream))


@pytest.mark.slow
def test_chaos_sdc_bitflip_detected_flagged_and_aborted(tmp_path,
                                                        monkeypatch):
    """Cross-replica SDC audit end-to-end (single process, 8 devices): a
    chaos bit-flip on ONE device's replicated params loses the checksum
    majority vote within audit_interval steps; the rank stamps an SDC
    heartbeat flag (blacklist evidence) and aborts rc 118."""
    import jax
    from deepspeed_tpu.runtime import heartbeat as hb
    hbdir = tmp_path / "hb"
    monkeypatch.setenv(hb.HEARTBEAT_DIR_ENV, str(hbdir))
    eng = _engine({"enabled": False, "audit_interval": 5})
    stream = batch_stream(eng.config.train_batch_size)
    for _ in range(4):
        eng.train_batch(next(stream))
    eng.save_checkpoint(str(tmp_path / "ck"), tag="t4")
    eng.train_batch(next(stream))                  # step 5: clean audit
    assert sl.read_last_audited_clean(str(tmp_path / "ck")) == "t4"

    chaos.arm("sentinel.sdc", "flag", match="0")   # this process's key
    with pytest.raises(TrainingIntegrityError, match="SDC") as ei:
        for _ in range(5):
            eng.train_batch(next(stream))          # step 10: dirty audit
    assert ei.value.exit_code == 118
    flags = hb.flagged_ranks(str(hbdir))
    assert 0 in flags and sl.SDC_FLAG in flags[0]["flags"]

    # a fresh engine's tag=None resume prefers the audited-clean tag even
    # though later tags exist (they may carry the corruption)
    eng2 = _engine({"enabled": False, "audit_interval": 5})
    eng2.save_checkpoint(str(tmp_path / "ck"), tag="t9-post-audit")
    eng2.load_checkpoint(str(tmp_path / "ck"))
    assert eng2.global_steps == 4                  # t4, not t9-post-audit


@pytest.mark.slow
def test_audit_explicit_tag_not_overridden(tmp_path):
    eng = _engine({"enabled": False, "audit_interval": 5})
    stream = batch_stream(eng.config.train_batch_size)
    for _ in range(2):
        eng.train_batch(next(stream))
    eng.save_checkpoint(str(tmp_path), tag="t2")
    sl.write_last_audited_clean(str(tmp_path), "t-other")
    eng.load_checkpoint(str(tmp_path), tag="t2")   # user intent wins
    assert eng.global_steps == 2


@pytest.mark.slow
def test_data_position_checkpointed_and_restored(tmp_path):
    eng = _engine()
    stream = batch_stream(eng.config.train_batch_size)
    for _ in range(7):
        eng.train_batch(next(stream))
    assert eng.data_position == 7
    eng.save_checkpoint(str(tmp_path))
    eng2 = _engine()
    eng2.load_checkpoint(str(tmp_path))
    assert eng2.data_position == 7
