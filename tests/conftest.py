"""Test harness: 8 virtual CPU devices simulate a multi-chip TPU mesh.

Mirrors the reference's DistributedTest pattern (tests/unit/common.py) of
simulating multi-node on localhost — here via XLA's host-platform device-count
flag instead of forked NCCL processes. Set DSTPU_TEST_PLATFORM=tpu to run the
suite against real chips.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

if os.environ.get("DSTPU_TEST_PLATFORM", "cpu") == "cpu":
    # sitecustomize pins JAX_PLATFORMS=axon before pytest starts; config.update
    # is the only override that still works after jax has been imported.
    jax.config.update("jax_platforms", "cpu")

import pytest

# the chaos env knob must never leak into the suite from the outer
# environment — a stray DSTPU_CHAOS would fail arbitrary checkpoint tests
os.environ.pop("DSTPU_CHAOS", None)


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(autouse=True)
def _chaos_isolation():
    """Deterministic fault injection: every test starts and ends with no
    armed failpoints, and DSTPU_CHAOS set by a test (for its subprocesses)
    is scrubbed afterwards."""
    from deepspeed_tpu.testing import chaos
    chaos.reset_for_tests()
    yield
    chaos.reset_for_tests()
    os.environ.pop("DSTPU_CHAOS", None)
