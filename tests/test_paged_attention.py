"""Pallas paged-attention kernel: parity vs a dense numpy oracle.

The kernel gathers K/V blocks through a per-sequence block table inside
the pipeline (serving decode path); the oracle materializes each
sequence's logical K/V by following the table on the host and runs dense
masked attention. Interpret mode on CPU — the same kernel runs compiled
on TPU. Covers the acceptance regimes: padding (ragged context lengths,
dead table entries), ALiBi, softcap, sliding window, stacked layer pools.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas.paged_attention import (
    paged_attention, paged_attention_reference)
from deepspeed_tpu.quant_format import kv_quantize


def _oracle(q, k_pool, v_pool, bt, lens, *, window=0, slopes=None,
            softcap=0.0):
    """Dense numpy oracle: gather via table, mask, f32 softmax."""
    B, nh, T, hd = q.shape
    bs = k_pool.shape[2]
    nbk = bt.shape[1]
    out = np.zeros((B, nh, T, hd), np.float32)
    for b in range(B):
        k = np.concatenate([k_pool[:, bt[b, j]] for j in range(nbk)],
                           axis=1)                     # [nh, nbk*bs, hd]
        v = np.concatenate([v_pool[:, bt[b, j]] for j in range(nbk)], axis=1)
        q_abs = np.arange(lens[b] - T, lens[b])        # [T]
        k_pos = np.arange(nbk * bs)
        s = np.einsum("htd,hkd->htk", q[b].astype(np.float32),
                      k.astype(np.float32)) / np.sqrt(hd)
        if softcap:
            s = np.tanh(s / softcap) * softcap
        if slopes is not None:
            s = s + slopes[:, None, None] * (
                k_pos[None, None, :] - q_abs[None, :, None])
        mask = k_pos[None, :] <= q_abs[:, None]
        if window > 0:
            mask &= q_abs[:, None] - k_pos[None, :] < window
        s = np.where(mask[None], s, -1e30)
        p = np.asarray(jax.nn.softmax(jnp.asarray(s), axis=-1))
        out[b] = np.einsum("htk,hkd->htd", p, v.astype(np.float32))
    return out


def _data(B=3, nh=4, hd=64, bs=16, num_blocks=32, nbk=8, seed=0):
    """Random pool + a random (valid, non-overlapping) block assignment."""
    rng = np.random.default_rng(seed)
    k_pool = rng.standard_normal((nh, num_blocks, bs, hd)).astype(np.float32)
    v_pool = rng.standard_normal((nh, num_blocks, bs, hd)).astype(np.float32)
    # distinct physical blocks per (b, j); block 0 reserved as null
    perm = rng.permutation(num_blocks - 1)[:B * nbk] + 1
    bt = perm.reshape(B, nbk).astype(np.int32)
    lens = rng.integers(1, nbk * bs + 1, size=B).astype(np.int32)
    q = rng.standard_normal((B, nh, 1, hd)).astype(np.float32)
    return q, k_pool, v_pool, bt, lens


@pytest.mark.parametrize("seed", [0, 1])
def test_paged_parity_ragged_lengths(seed):
    q, kp, vp, bt, lens = _data(seed=seed)
    out = paged_attention(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                          jnp.asarray(bt), jnp.asarray(lens), interpret=True)
    np.testing.assert_allclose(np.asarray(out), _oracle(q, kp, vp, bt, lens),
                               rtol=2e-5, atol=2e-5)


def test_paged_parity_alibi():
    q, kp, vp, bt, lens = _data()
    slopes = np.asarray([2.0 ** -(i + 1) for i in range(4)], np.float32)
    out = paged_attention(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                          jnp.asarray(bt), jnp.asarray(lens),
                          alibi_slopes=jnp.asarray(slopes), interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), _oracle(q, kp, vp, bt, lens, slopes=slopes),
        rtol=2e-5, atol=2e-5)


def test_paged_parity_softcap():
    q, kp, vp, bt, lens = _data(seed=2)
    out = paged_attention(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                          jnp.asarray(bt), jnp.asarray(lens), softcap=30.0,
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), _oracle(q, kp, vp, bt, lens, softcap=30.0),
        rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [16, 50])
def test_paged_parity_window(window):
    q, kp, vp, bt, lens = _data(seed=3)
    out = paged_attention(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                          jnp.asarray(bt), jnp.asarray(lens),
                          window=jnp.asarray(window, jnp.int32),
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), _oracle(q, kp, vp, bt, lens, window=window),
        rtol=2e-5, atol=2e-5)


def test_paged_stacked_layer_pool():
    """layer_idx form: blocks picked straight out of the [L, ...] pool."""
    L = 3
    q, kp, vp, bt, lens = _data(B=2, nbk=4)
    kpl = np.stack([kp * (l + 1) for l in range(L)])
    vpl = np.stack([vp * 0.5 * (l + 1) for l in range(L)])
    for li in range(L):
        out = paged_attention(jnp.asarray(q), jnp.asarray(kpl),
                              jnp.asarray(vpl), jnp.asarray(bt),
                              jnp.asarray(lens),
                              layer_idx=jnp.asarray(li, jnp.int32),
                              interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), _oracle(q, kpl[li], vpl[li], bt, lens),
            rtol=2e-5, atol=2e-5)


def test_paged_reference_matches_kernel_and_serves_prefill():
    """The jnp reference (the CPU/serving fallback) agrees with the numpy
    oracle for T=1 AND for the prefill regime (T>1) the kernel refuses."""
    q, kp, vp, bt, lens = _data(seed=4)
    ref = paged_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt),
        jnp.asarray(lens))
    np.testing.assert_allclose(np.asarray(ref), _oracle(q, kp, vp, bt, lens),
                               rtol=2e-5, atol=2e-5)
    # prefill: 5 queries ending at lens[b]
    rng = np.random.default_rng(9)
    B, nh, _, hd = q.shape
    lens5 = np.maximum(lens, 5)
    q5 = rng.standard_normal((B, nh, 5, hd)).astype(np.float32)
    ref5 = paged_attention_reference(
        jnp.asarray(q5), jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt),
        jnp.asarray(lens5))
    np.testing.assert_allclose(
        np.asarray(ref5), _oracle(q5, kp, vp, bt, lens5), rtol=2e-5,
        atol=2e-5)
    with pytest.raises(ValueError, match="1 token"):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_attention as kern)
        kern(jnp.asarray(q5), jnp.asarray(kp), jnp.asarray(vp),
             jnp.asarray(bt), jnp.asarray(lens5), interpret=True)


def test_router_dispatch():
    """ops.attention.paged_attention: kernel for T=1 under interpret,
    reference for prefill — same numerics either way."""
    from deepspeed_tpu.ops.attention import paged_attention as router
    q, kp, vp, bt, lens = _data(seed=5)
    out = router(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                 jnp.asarray(bt), jnp.asarray(lens), interpret=True)
    np.testing.assert_allclose(np.asarray(out), _oracle(q, kp, vp, bt, lens),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# int8 KV tier (round 17): the kernel DMAs int8 blocks + per-row scales and
# dequantizes IN VMEM — parity vs the numpy oracle running on the
# dequantized pools must be as tight as the f32 tier's, in every routed
# regime, because the in-kernel dequant reconstructs the identical values.
# ---------------------------------------------------------------------------

def _int8_pools(kp, vp):
    """Quantize pools to the serving format: int8 values + one f32 scale
    per (head, block, slot) row; returns the exact dequantized floats the
    oracle attends over."""
    (kq, ks), (vq, vs) = kv_quantize(jnp.asarray(kp)), kv_quantize(
        jnp.asarray(vp))
    kd = np.asarray(kq, np.float32) * np.asarray(ks)
    vd = np.asarray(vq, np.float32) * np.asarray(vs)
    return kq, ks, vq, vs, kd, vd


@pytest.mark.parametrize("regime", ["plain", "alibi", "softcap", "window16",
                                    "window50"])
def test_paged_int8_parity_all_regimes(regime):
    q, kp, vp, bt, lens = _data(seed=6)
    kq, ks, vq, vs, kd, vd = _int8_pools(kp, vp)
    kw, okw = {}, {}
    if regime == "alibi":
        slopes = np.asarray([2.0 ** -(i + 1) for i in range(4)], np.float32)
        kw["alibi_slopes"] = jnp.asarray(slopes)
        okw["slopes"] = slopes
    elif regime == "softcap":
        kw["softcap"] = okw["softcap"] = 30.0
    elif regime.startswith("window"):
        w = int(regime[len("window"):])
        kw["window"] = jnp.asarray(w, jnp.int32)
        okw["window"] = w
    args = (jnp.asarray(q), kq, vq, jnp.asarray(bt), jnp.asarray(lens))
    out = paged_attention(*args, k_scale=ks, v_scale=vs, interpret=True,
                          **kw)
    ref = paged_attention_reference(*args, k_scale=ks, v_scale=vs, **kw)
    want = _oracle(q, kd, vd, bt, lens, **okw)
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ref), want, rtol=2e-5, atol=2e-5)


def test_paged_int8_stacked_layer_pool():
    """int8 + layer_idx: per-layer scale slices ride the SAME block-table
    index map as the values — each layer dequantizes with its own rows."""
    L = 3
    q, kp, vp, bt, lens = _data(B=2, nbk=4, seed=7)
    kpl = np.stack([kp * (l + 1) for l in range(L)])
    vpl = np.stack([vp * 0.5 * (l + 1) for l in range(L)])
    kq, ks, vq, vs, kd, vd = _int8_pools(kpl, vpl)
    for li in range(L):
        out = paged_attention(jnp.asarray(q), kq, vq, jnp.asarray(bt),
                              jnp.asarray(lens), k_scale=ks, v_scale=vs,
                              layer_idx=jnp.asarray(li, jnp.int32),
                              interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), _oracle(q, kd[li], vd[li], bt, lens),
            rtol=2e-5, atol=2e-5)


def test_paged_int8_guards():
    """int8 pools without scales (and scales without int8 pools) raise —
    a silent garbage read is the failure mode these guard against."""
    q, kp, vp, bt, lens = _data(B=1, nbk=2, seed=8)
    kq, ks, vq, vs, _, _ = _int8_pools(kp, vp)
    with pytest.raises(ValueError, match="scale"):
        paged_attention(jnp.asarray(q), kq, vq, jnp.asarray(bt),
                        jnp.asarray(lens), interpret=True)
    with pytest.raises(ValueError, match="int8"):
        paged_attention(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                        jnp.asarray(bt), jnp.asarray(lens), k_scale=ks,
                        v_scale=vs, interpret=True)


# tier-2 (round-17 budget sweep, ~9s): the cheaper tier-1 cousins are
# test_paged_int8_parity_all_regimes (kernel+reference vs dequant oracle)
# and test_serving.test_int8_kv_pool_parity_jnp_and_kernel (engine-level
# token parity); scripts/tier2.sh runs this full-plumbing GQA+rotary leg
@pytest.mark.slow
def test_paged_int8_gqa_rotary_decode_kernel_vs_reference():
    """GQA + rotary through the full decode plumbing: a llama-ish
    paged_forward prefill writes the int8 pool (kv heads repeated to full
    heads upstream, rotary applied before the write), then ONE decode step
    runs twice — interpret=True (Pallas int8 kernel, in-VMEM dequant) and
    interpret=False (jnp reference, post-gather dequant). Same pool bytes,
    same logits, same greedy token."""
    from deepspeed_tpu.models import build_model
    from deepspeed_tpu.models.generation import ensure_scan_layout
    from deepspeed_tpu.serving.kv_cache import init_pool
    from deepspeed_tpu.serving.model_runner import paged_forward
    model, cfg = build_model(
        "llama-1.1b", hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, mlp_dim_override=64, vocab_size=64, max_seq_len=64,
        attention_impl="reference", dtype=jnp.float32)
    ids = np.asarray([[3, 1, 4, 1, 5, 9, 2], [6, 5, 3, 5, 8, 9, 7]],
                     np.int32)
    params = ensure_scan_layout(
        model.init(jax.random.PRNGKey(1), {"input_ids": ids})["params"],
        cfg.num_layers)
    bs, nbk = 16, 2
    bt = np.asarray([[1, 2], [3, 4]], np.int32)
    T = ids.shape[1]
    run = lambda interp: _gqa_decode(cfg, params, ids, bt, bs, nbk, interp)
    logits_k = run(True)
    logits_r = run(False)
    np.testing.assert_allclose(logits_k, logits_r, rtol=2e-5, atol=2e-5)
    assert np.array_equal(logits_k[:, -1].argmax(-1),
                          logits_r[:, -1].argmax(-1))


def _gqa_decode(cfg, params, ids, bt, bs, nbk, interpret):
    from deepspeed_tpu.serving.kv_cache import init_pool
    from deepspeed_tpu.serving.model_runner import paged_forward
    B, T = ids.shape
    pools = init_pool(cfg, 8, bs, dtype=jnp.int8)
    zeros = jnp.zeros((B,), jnp.int32)
    # prefill (reference attention path for T>1) populates the int8 pool
    _, pools = paged_forward(cfg, params, jnp.asarray(ids), pools,
                             jnp.asarray(bt), zeros,
                             jnp.full((B,), T, jnp.int32), bs,
                             interpret=interpret)
    nxt = jnp.asarray([[7], [2]], jnp.int32)
    logits, _ = paged_forward(cfg, params, nxt, pools, jnp.asarray(bt),
                              jnp.full((B,), T, jnp.int32),
                              jnp.full((B,), T + 1, jnp.int32), bs,
                              interpret=interpret)
    return np.asarray(logits)
