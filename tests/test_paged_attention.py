"""Pallas paged-attention kernel: parity vs a dense numpy oracle.

The kernel gathers K/V blocks through a per-sequence block table inside
the pipeline (serving decode path); the oracle materializes each
sequence's logical K/V by following the table on the host and runs dense
masked attention. Interpret mode on CPU — the same kernel runs compiled
on TPU. Covers the acceptance regimes: padding (ragged context lengths,
dead table entries), ALiBi, softcap, sliding window, stacked layer pools.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas.paged_attention import (
    paged_attention, paged_attention_reference)


def _oracle(q, k_pool, v_pool, bt, lens, *, window=0, slopes=None,
            softcap=0.0):
    """Dense numpy oracle: gather via table, mask, f32 softmax."""
    B, nh, T, hd = q.shape
    bs = k_pool.shape[2]
    nbk = bt.shape[1]
    out = np.zeros((B, nh, T, hd), np.float32)
    for b in range(B):
        k = np.concatenate([k_pool[:, bt[b, j]] for j in range(nbk)],
                           axis=1)                     # [nh, nbk*bs, hd]
        v = np.concatenate([v_pool[:, bt[b, j]] for j in range(nbk)], axis=1)
        q_abs = np.arange(lens[b] - T, lens[b])        # [T]
        k_pos = np.arange(nbk * bs)
        s = np.einsum("htd,hkd->htk", q[b].astype(np.float32),
                      k.astype(np.float32)) / np.sqrt(hd)
        if softcap:
            s = np.tanh(s / softcap) * softcap
        if slopes is not None:
            s = s + slopes[:, None, None] * (
                k_pos[None, None, :] - q_abs[None, :, None])
        mask = k_pos[None, :] <= q_abs[:, None]
        if window > 0:
            mask &= q_abs[:, None] - k_pos[None, :] < window
        s = np.where(mask[None], s, -1e30)
        p = np.asarray(jax.nn.softmax(jnp.asarray(s), axis=-1))
        out[b] = np.einsum("htk,hkd->htd", p, v.astype(np.float32))
    return out


def _data(B=3, nh=4, hd=64, bs=16, num_blocks=32, nbk=8, seed=0):
    """Random pool + a random (valid, non-overlapping) block assignment."""
    rng = np.random.default_rng(seed)
    k_pool = rng.standard_normal((nh, num_blocks, bs, hd)).astype(np.float32)
    v_pool = rng.standard_normal((nh, num_blocks, bs, hd)).astype(np.float32)
    # distinct physical blocks per (b, j); block 0 reserved as null
    perm = rng.permutation(num_blocks - 1)[:B * nbk] + 1
    bt = perm.reshape(B, nbk).astype(np.int32)
    lens = rng.integers(1, nbk * bs + 1, size=B).astype(np.int32)
    q = rng.standard_normal((B, nh, 1, hd)).astype(np.float32)
    return q, k_pool, v_pool, bt, lens


@pytest.mark.parametrize("seed", [0, 1])
def test_paged_parity_ragged_lengths(seed):
    q, kp, vp, bt, lens = _data(seed=seed)
    out = paged_attention(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                          jnp.asarray(bt), jnp.asarray(lens), interpret=True)
    np.testing.assert_allclose(np.asarray(out), _oracle(q, kp, vp, bt, lens),
                               rtol=2e-5, atol=2e-5)


def test_paged_parity_alibi():
    q, kp, vp, bt, lens = _data()
    slopes = np.asarray([2.0 ** -(i + 1) for i in range(4)], np.float32)
    out = paged_attention(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                          jnp.asarray(bt), jnp.asarray(lens),
                          alibi_slopes=jnp.asarray(slopes), interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), _oracle(q, kp, vp, bt, lens, slopes=slopes),
        rtol=2e-5, atol=2e-5)


def test_paged_parity_softcap():
    q, kp, vp, bt, lens = _data(seed=2)
    out = paged_attention(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                          jnp.asarray(bt), jnp.asarray(lens), softcap=30.0,
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), _oracle(q, kp, vp, bt, lens, softcap=30.0),
        rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [16, 50])
def test_paged_parity_window(window):
    q, kp, vp, bt, lens = _data(seed=3)
    out = paged_attention(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                          jnp.asarray(bt), jnp.asarray(lens),
                          window=jnp.asarray(window, jnp.int32),
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), _oracle(q, kp, vp, bt, lens, window=window),
        rtol=2e-5, atol=2e-5)


def test_paged_stacked_layer_pool():
    """layer_idx form: blocks picked straight out of the [L, ...] pool."""
    L = 3
    q, kp, vp, bt, lens = _data(B=2, nbk=4)
    kpl = np.stack([kp * (l + 1) for l in range(L)])
    vpl = np.stack([vp * 0.5 * (l + 1) for l in range(L)])
    for li in range(L):
        out = paged_attention(jnp.asarray(q), jnp.asarray(kpl),
                              jnp.asarray(vpl), jnp.asarray(bt),
                              jnp.asarray(lens),
                              layer_idx=jnp.asarray(li, jnp.int32),
                              interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), _oracle(q, kpl[li], vpl[li], bt, lens),
            rtol=2e-5, atol=2e-5)


def test_paged_reference_matches_kernel_and_serves_prefill():
    """The jnp reference (the CPU/serving fallback) agrees with the numpy
    oracle for T=1 AND for the prefill regime (T>1) the kernel refuses."""
    q, kp, vp, bt, lens = _data(seed=4)
    ref = paged_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt),
        jnp.asarray(lens))
    np.testing.assert_allclose(np.asarray(ref), _oracle(q, kp, vp, bt, lens),
                               rtol=2e-5, atol=2e-5)
    # prefill: 5 queries ending at lens[b]
    rng = np.random.default_rng(9)
    B, nh, _, hd = q.shape
    lens5 = np.maximum(lens, 5)
    q5 = rng.standard_normal((B, nh, 5, hd)).astype(np.float32)
    ref5 = paged_attention_reference(
        jnp.asarray(q5), jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(bt),
        jnp.asarray(lens5))
    np.testing.assert_allclose(
        np.asarray(ref5), _oracle(q5, kp, vp, bt, lens5), rtol=2e-5,
        atol=2e-5)
    with pytest.raises(ValueError, match="1 token"):
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_attention as kern)
        kern(jnp.asarray(q5), jnp.asarray(kp), jnp.asarray(vp),
             jnp.asarray(bt), jnp.asarray(lens5), interpret=True)


def test_router_dispatch():
    """ops.attention.paged_attention: kernel for T=1 under interpret,
    reference for prefill — same numerics either way."""
    from deepspeed_tpu.ops.attention import paged_attention as router
    q, kp, vp, bt, lens = _data(seed=5)
    out = router(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                 jnp.asarray(bt), jnp.asarray(lens), interpret=True)
    np.testing.assert_allclose(np.asarray(out), _oracle(q, kp, vp, bt, lens),
                               rtol=2e-5, atol=2e-5)
