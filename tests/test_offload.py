"""ZeRO-Offload tests: C++ CPU Adam numerics, AIO round-trips, NVMe swapping,
and end-to-end engine training with offload_optimizer cpu/nvme.

Mirrors the reference's tests/unit/test_zero.py cpu_offload parametrizations +
csrc/aio/py_test round-trip checks.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _precise_matmuls():
    """Parity tolerances assume fp32 math; on real TPUs jnp matmuls default
    to bf16 internally, so pin the precision for these tests."""
    import jax as _jax
    with _jax.default_matmul_precision("highest"):
        yield


import deepspeed_tpu as ds
from deepspeed_tpu.ops.cpu import AsyncIOHandle, DeepSpeedCPUAdam
from deepspeed_tpu.ops.optimizers import adamw as jax_adamw
from deepspeed_tpu.runtime.swap_tensor import (OptimizerStateSwapper,
                                               PartitionedParamSwapper)

from util import SimpleModel, random_batch


def test_cpu_adam_matches_jax_adamw():
    """The offloaded C++ kernel must be step-for-step identical (to fp32
    roundoff) with the in-jit AdamW the non-offload engine uses."""
    rng = np.random.RandomState(0)
    n = 4097  # odd size: exercises SIMD tail handling
    p_cpu = rng.randn(n).astype(np.float32)
    # explicit copy: on the CPU backend jnp.asarray can alias the numpy
    # buffer, which the C++ kernel then mutates in place
    p_jax = jnp.array(p_cpu, copy=True)
    cpu_opt = DeepSpeedCPUAdam(lr=1e-2, betas=(0.9, 0.95), eps=1e-8,
                               weight_decay=0.1, adamw_mode=True)
    st_cpu = cpu_opt.init_state(p_cpu)
    jx = jax_adamw(lr=1e-2, betas=(0.9, 0.95), eps=1e-8, weight_decay=0.1)
    st_jax = jx.init({"w": p_jax})
    params = {"w": p_jax}
    for step in range(5):
        g = rng.randn(n).astype(np.float32)
        cpu_opt.step(step + 1, p_cpu, g, st_cpu)
        params, st_jax = jx.update({"w": jnp.asarray(g)}, st_jax, params,
                                   jnp.asarray(step, jnp.int32))
    np.testing.assert_allclose(p_cpu, np.asarray(params["w"]), rtol=2e-5,
                               atol=2e-6)


def test_cpu_adam_grad_scale_fused():
    """grad_scale divides grads inside the kernel (loss-scale unscaling)."""
    rng = np.random.RandomState(1)
    p1 = rng.randn(512).astype(np.float32)
    p2 = p1.copy()
    g = rng.randn(512).astype(np.float32)
    opt = DeepSpeedCPUAdam(lr=1e-3)
    s1, s2 = opt.init_state(p1), opt.init_state(p2)
    opt.step(1, p1, g * 8.0, s1, grad_scale=8.0)
    opt.step(1, p2, g, s2, grad_scale=1.0)
    np.testing.assert_allclose(p1, p2, rtol=1e-6, atol=1e-7)


def test_cpu_adam_bf16_out_matches_xla_cast():
    rng = np.random.RandomState(2)
    p = rng.randn(300).astype(np.float32)
    g = rng.randn(300).astype(np.float32)
    opt = DeepSpeedCPUAdam(lr=1e-2)
    st = opt.init_state(p)
    import ml_dtypes
    bf = np.zeros(300, ml_dtypes.bfloat16)
    opt.step(1, p, g, st, bf16_out=bf)
    expected = jnp.asarray(p).astype(jnp.bfloat16)
    assert bf.view(np.uint16).tolist() == \
        np.asarray(expected).view(np.uint16).tolist()


def test_aio_sync_roundtrip(tmp_path):
    h = AsyncIOHandle(block_size=4096, thread_count=2)
    data = np.arange(10000, dtype=np.float32)
    path = str(tmp_path / "buf.swp")
    h.sync_pwrite(data, path)
    out = np.zeros_like(data)
    h.sync_pread(out, path)
    np.testing.assert_array_equal(data, out)


def test_aio_async_roundtrip(tmp_path):
    h = AsyncIOHandle(block_size=1024, thread_count=4)
    bufs = [np.random.RandomState(i).randn(3000).astype(np.float32)
            for i in range(6)]
    paths = [str(tmp_path / f"t{i}.swp") for i in range(6)]
    for b, p in zip(bufs, paths):
        h.async_pwrite(b, p)
    h.wait()
    outs = [np.zeros(3000, np.float32) for _ in range(6)]
    for o, p in zip(outs, paths):
        h.async_pread(o, p)
    h.wait()
    for b, o in zip(bufs, outs):
        np.testing.assert_array_equal(b, o)


def test_optimizer_state_swapper_pipeline(tmp_path):
    """The read->compute->write pipeline must deliver each leaf's own state
    and persist mutations (reference: partitioned_optimizer_swapper)."""
    shapes = [(64,), (32, 8), (100,)]
    sw = OptimizerStateSwapper(str(tmp_path), ["m", "v"], shapes,
                               buffer_count=4)
    # round 1: write leaf index j+1 into every element of slot m of leaf j
    def fill(j, views):
        views["m"][:] = j + 1.0
        views["v"][:] = (j + 1.0) * 10.0
    sw.pipeline(fill)
    # round 2: verify persisted values arrive back
    seen = {}
    def check(j, views):
        seen[j] = (views["m"].copy(), views["v"].copy())
    sw.pipeline(check)
    for j, shape in enumerate(shapes):
        n = int(np.prod(shape))
        np.testing.assert_array_equal(seen[j][0], np.full(n, j + 1.0, np.float32))
        np.testing.assert_array_equal(seen[j][1], np.full(n, (j + 1.0) * 10.0,
                                                          np.float32))


def test_partitioned_param_swapper_roundtrip(tmp_path):
    shapes = [(16, 16), (50,)]
    sw = PartitionedParamSwapper(str(tmp_path), shapes)
    leaves = [np.random.RandomState(i).randn(*s).astype(np.float32)
              for i, s in enumerate(shapes)]
    sw.swap_out(leaves)
    back = sw.swap_in()
    for a, b in zip(leaves, back):
        np.testing.assert_array_equal(a, b)


def _make_engine(offload_device=None, tmp_path=None, dtype_section=None,
                 seed=42):
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-2, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "seed": seed,
    }
    if dtype_section:
        config[dtype_section] = {"enabled": True}
    if offload_device:
        zo = {"stage": 1 if offload_device else 0,
              "offload_optimizer": {"device": offload_device}}
        if offload_device == "nvme":
            zo["offload_optimizer"]["nvme_path"] = str(tmp_path / "nvme")
        config["zero_optimization"] = zo
    model = SimpleModel()
    engine, *_ = ds.initialize(model=model, config=config,
                               example_batch=random_batch(8))
    return engine


def test_engine_offload_cpu_trains():
    engine = _make_engine("cpu")
    assert engine.offload is not None
    assert engine.state.opt_state == {}          # nothing on device
    assert engine.state.master == ()
    losses = [float(engine.train_batch(random_batch(8, seed=i))["loss"])
              for i in range(12)]
    assert losses[-1] < losses[0]


def test_engine_offload_matches_device_path():
    """Offloaded AdamW must track the on-device jitted AdamW step-for-step."""
    e_dev = _make_engine(None)
    e_off = _make_engine("cpu")
    for i in range(5):
        b = random_batch(8, seed=i)
        l_dev = float(e_dev.train_batch(b)["loss"])
        l_off = float(e_off.train_batch(b)["loss"])
        assert abs(l_dev - l_off) < 2e-4, (i, l_dev, l_off)
    p_dev = jax.tree.leaves(e_dev.state.params)
    p_off = jax.tree.leaves(e_off.state.params)
    for a, b in zip(p_dev, p_off):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5)


def test_engine_offload_bf16_trains():
    engine = _make_engine("cpu", dtype_section="bf16")
    losses = [float(engine.train_batch(random_batch(8, seed=i))["loss"])
              for i in range(12)]
    assert losses[-1] < losses[0]
    for leaf in jax.tree.leaves(engine.state.params):
        assert leaf.dtype == jnp.bfloat16


def test_engine_offload_nvme_trains(tmp_path):
    engine = _make_engine("nvme", tmp_path=tmp_path)
    losses = [float(engine.train_batch(random_batch(8, seed=i))["loss"])
              for i in range(8)]
    assert losses[-1] < losses[0]
    swap_root = tmp_path / "nvme" / "zero_offload_opt"
    assert (swap_root / "exp_avg").is_dir()
    assert any(f.suffix == ".swp" for f in (swap_root / "exp_avg").iterdir())


def test_engine_offload_checkpoint_roundtrip(tmp_path):
    engine = _make_engine("cpu")
    for i in range(3):
        engine.train_batch(random_batch(8, seed=i))
    engine.save_checkpoint(str(tmp_path / "ckpt"))
    ref_losses = [float(engine.train_batch(random_batch(8, seed=10 + i))["loss"])
                  for i in range(3)]
    engine2 = _make_engine("cpu")
    engine2.load_checkpoint(str(tmp_path / "ckpt"))
    got_losses = [float(engine2.train_batch(random_batch(8, seed=10 + i))["loss"])
                  for i in range(3)]
    np.testing.assert_allclose(ref_losses, got_losses, rtol=1e-5)


def test_gathered_parameters_writeback():
    """Task: zero.GatheredParameters write-back (round-1 Weak #8)."""
    from deepspeed_tpu.zero import GatheredParameters
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    g = GatheredParameters(params, modifier_rank=0)
    with g as host:
        host["w"][0, :] = 7.0
    assert g.updated is not None
    assert np.asarray(g.updated["w"])[0, 0] == 7.0
    assert np.asarray(g.updated["w"])[1, 0] == 1.0
    # original untouched (functional semantics)
    assert float(params["w"][0, 0]) == 1.0


def test_offload_param_transient_mode():
    """offload_param + offload_optimizer: device params are TRANSIENT — the
    engine state holds none between steps (HBM frees to host masters), and
    training/eval/checkpointing still work (reference: ZeRO-3 param offload,
    partition_parameters.py)."""
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "gradient_clipping": 1.0,
        "bf16": {"enabled": True},
        "zero_optimization": {
            "stage": 1,
            "offload_optimizer": {"device": "cpu"},
            "offload_param": {"device": "cpu"}},
        "seed": 42,
    }
    engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                               example_batch=random_batch(8))
    assert engine._transient_params
    assert engine.state.params == ()          # nothing resident
    losses = [float(engine.train_batch(random_batch(8, seed=i))["loss"])
              for i in range(20)]
    assert np.mean(losses[-6:]) < np.mean(losses[:3])   # bf16: noisy descent
    assert engine.state.params == ()          # still nothing resident
    out = engine.eval_batch(random_batch(8))  # transient materialization
    assert np.isfinite(float(out))
    # checkpoint round-trips from the host-resident weights (no empty files)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        engine.save_checkpoint(d + "/ck")
        engine.save_16bit_model(d + "/m")
        with np.load(d + "/m/pytorch_model.npz") as data:
            assert len(data.files) >= 6
        engine3, *_ = ds.initialize(model=SimpleModel(), config=config,
                                    example_batch=random_batch(8))
        engine3.load_checkpoint(d + "/ck")
        b = random_batch(8, seed=99)
        np.testing.assert_allclose(float(engine.eval_batch(b)),
                                   float(engine3.eval_batch(b)), rtol=1e-5)
    # matches the persistent-params offload run step for step
    cfg2 = dict(config)
    cfg2["zero_optimization"] = {"stage": 1,
                                 "offload_optimizer": {"device": "cpu"}}
    e2, *_ = ds.initialize(model=SimpleModel(), config=cfg2,
                           example_batch=random_batch(8))
    l2 = [float(e2.train_batch(random_batch(8, seed=i))["loss"])
          for i in range(20)]
    np.testing.assert_allclose(losses, l2, rtol=1e-5)


def test_offload_param_requires_offload_optimizer():
    config = {"train_batch_size": 8,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
              "zero_optimization": {"stage": 1,
                                    "offload_param": {"device": "cpu"}}}
    with pytest.raises(ValueError, match="offload_param"):
        ds.initialize(model=SimpleModel(), config=config,
                      example_batch=random_batch(8))


def test_on_device_init():
    """zero.OnDevice: dtype-cast init, meta (shape-only) init, cpu placement
    (reference: utils/init_on_device.py OnDevice)."""
    import flax.linen as nn
    from deepspeed_tpu import zero

    model = nn.Dense(8)
    x = jnp.ones((2, 4), jnp.float32)

    with zero.OnDevice(dtype=jnp.bfloat16, device="cpu") as od:
        params = od.init(model.init, jax.random.PRNGKey(0), x)
    assert params["params"]["kernel"].dtype == jnp.bfloat16
    assert "cpu" in str(jax.tree.leaves(params)[0].devices()).lower()

    with zero.OnDevice(device="meta") as od:
        shapes = od.init(model.init, jax.random.PRNGKey(0), x)
    leaf = shapes["params"]["kernel"]
    assert isinstance(leaf, jax.ShapeDtypeStruct) and leaf.shape == (4, 8)

    with zero.OnDevice(enabled=False) as od:
        real = od.init(model.init, jax.random.PRNGKey(0), x)
    assert real["params"]["kernel"].dtype == jnp.float32


def test_offload_param_nvme_tier(tmp_path):
    """ZeRO-Infinity param tier: offload_param device=nvme puts the fp32
    masters on disk (no host-RAM master list), streams them through the step
    pipeline, and matches the cpu-offload run step for step (reference:
    partitioned_param_swapper.py:35, wired at stage3.py:481)."""
    def make(param_device, subdir="pnvme"):
        config = {
            "train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "gradient_clipping": 1.0,
            "bf16": {"enabled": True},
            "zero_optimization": {
                "stage": 1,
                "offload_optimizer": {"device": "cpu"},
                "offload_param": {"device": param_device,
                                  "nvme_path": str(tmp_path / subdir)}},
            "seed": 42,
        }
        engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                                   example_batch=random_batch(8))
        return engine

    e_nvme = make("nvme")
    assert e_nvme._transient_params
    assert e_nvme.offload.master is None            # no RAM master list
    assert e_nvme.offload.param_pool is not None
    proot = tmp_path / "pnvme" / "zero_offload_params"
    assert any(f.suffix == ".swp" for f in proot.iterdir())

    e_cpu = make("cpu")
    l_nvme = [float(e_nvme.train_batch(random_batch(8, seed=i))["loss"])
              for i in range(10)]
    l_cpu = [float(e_cpu.train_batch(random_batch(8, seed=i))["loss"])
             for i in range(10)]
    np.testing.assert_allclose(l_nvme, l_cpu, rtol=1e-5)

    # eval materializes transiently from NVMe
    assert np.isfinite(float(e_nvme.eval_batch(random_batch(8))))

    # checkpoint round-trips through the NVMe masters — a distinct nvme_path
    # so e2 cannot accidentally read e_nvme's swap files
    e_nvme.save_checkpoint(str(tmp_path / "ck"))
    e2 = make("nvme", subdir="pnvme2")
    e2.load_checkpoint(str(tmp_path / "ck"))
    b = random_batch(8, seed=77)
    np.testing.assert_allclose(float(e_nvme.eval_batch(b)),
                               float(e2.eval_batch(b)), rtol=1e-5)


def test_offload_param_nvme_and_opt_nvme(tmp_path):
    """Params AND optimizer state both on NVMe — the full ZeRO-Infinity
    storage tier; host RAM holds only the streaming buffers."""
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {
            "stage": 1,
            "offload_optimizer": {"device": "nvme",
                                  "nvme_path": str(tmp_path / "nv")},
            "offload_param": {"device": "nvme"}},   # nvme_path falls back
        "seed": 42,
    }
    engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                               example_batch=random_batch(8))
    assert engine.offload.master is None and engine.offload.state is None
    losses = [float(engine.train_batch(random_batch(8, seed=i))["loss"])
              for i in range(20)]
    assert np.mean(losses[-6:]) < np.mean(losses[:3])
    assert (tmp_path / "nv" / "zero_offload_params").is_dir()
    assert (tmp_path / "nv" / "zero_offload_opt" / "exp_avg").is_dir()


def test_nvme_root_collision_namespacing(tmp_path):
    """Two live engines pointed at the same nvme_path must not clobber each
    other's swap files: the second instance claims a suffixed directory."""
    def make():
        config = {
            "train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {
                "stage": 1,
                "offload_optimizer": {"device": "cpu"},
                "offload_param": {"device": "nvme",
                                  "nvme_path": str(tmp_path / "shared")}},
            "seed": 42,
        }
        engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                                   example_batch=random_batch(8))
        return engine

    e1 = make()
    before = [np.array(m) for m in
              (e1.offload._master_host(j) for j in range(e1.offload.n_leaves))]
    e2 = make()                       # same nvme_path: must not overwrite e1
    assert e1.offload.param_pool.root != e2.offload.param_pool.root
    after = [e1.offload._master_host(j) for j in range(e1.offload.n_leaves)]
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a, b)


def test_swap_pipeline_overlap_ratio_synthetic_bandwidth():
    """Round-3 Weak #6: the 'transfers hidden behind compute' claim of the
    read-ahead/write-behind pipeline, made measurable. Pool stand-ins with a
    KNOWN synthetic transfer time drive pipeline_pools; with reads of j+1
    and write-backs of j overlapping compute of j, wall time approaches
    n * max(transfer, compute) instead of the serial
    n * (read + compute + write)."""
    import time
    from concurrent.futures import ThreadPoolExecutor

    from deepspeed_tpu.runtime.swap_tensor import pipeline_pools

    TRANSFER = 0.05             # synthetic one-way transfer time per leaf
    COMPUTE = 0.06
    N = 8

    class SyntheticPool:
        """read_async/write_async/wait contract of SwappedTensorPool with a
        sleep-backed 'device link' on a worker thread."""

        def __init__(self):
            self._pool = ThreadPoolExecutor(max_workers=2)
            self._pending = []
            self.n_transfers = 0

        def _xfer(self):
            time.sleep(TRANSFER)

        def read_async(self, j):
            self.n_transfers += 1
            self._pending.append(self._pool.submit(self._xfer))
            return np.zeros(4, np.float32)

        def write_async(self, j, data):
            self.n_transfers += 1
            self._pending.append(self._pool.submit(self._xfer))

        def wait(self):
            pending, self._pending = self._pending, []
            for f in pending:
                f.result()

    def compute(j, views):
        time.sleep(COMPUTE)

    serial = N * (2 * TRANSFER + COMPUTE)           # no overlap at all
    ideal = N * max(2 * TRANSFER, COMPUTE) + 2 * TRANSFER   # fill/drain
    # best of 3: the bounds measure the PIPELINE, not the host scheduler —
    # inside a full-suite run the accumulated daemon threads (watchdogs,
    # refreshers, executors) can delay sleep wakeups by hundreds of ms and
    # flake a single measurement; a no-overlap regression fails all three
    wall = float("inf")
    for _ in range(3):
        pool = SyntheticPool()
        t0 = time.perf_counter()
        pipeline_pools({"state": pool}, N, compute)
        wall = min(wall, time.perf_counter() - t0)
        assert pool.n_transfers == 2 * N            # every leaf read+written
        if wall < 1.5 * ideal:
            break
    overlap_ratio = serial / wall
    # the pipeline must recover a real fraction of the transfer time:
    # strictly faster than serial AND within 1.5x of the ideal bound
    # (expected wall ~0.58 s; serial 1.28 s; 1.5*ideal 1.35 s would catch
    # a no-overlap regression, 0.75*serial = 0.96 s catches it earlier)
    assert wall < 0.75 * serial, (wall, serial)
    assert wall < 1.5 * ideal, (wall, ideal)
    assert overlap_ratio > 1.3, overlap_ratio


def test_load_module_state_dict_transient_mode():
    """Weights-only load in offload_param transient mode: device params are
    (), the real weights live in the host master — the loader must reseed
    it (not reject the state_dict against an empty tree)."""
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {
            "stage": 1,
            "offload_optimizer": {"device": "cpu"},
            "offload_param": {"device": "cpu"}},
        "seed": 42,
    }
    e1, *_ = ds.initialize(model=SimpleModel(), config=config,
                           example_batch=random_batch(8))
    for i in range(3):
        e1.train_batch(random_batch(8, seed=i))
    sd = e1.module_state_dict()

    e2, *_ = ds.initialize(model=SimpleModel(), config=config,
                           example_batch=random_batch(8))
    e2.load_module_state_dict(sd)
    assert e2.state.params == ()              # still transient
    b = random_batch(8, seed=99)
    np.testing.assert_allclose(float(e1.eval_batch(b)),
                               float(e2.eval_batch(b)), rtol=1e-5)


def test_load_module_state_dict_preserves_master_precision():
    """Weights-only load with host-offloaded master: the fp32 master is
    reseeded from the FULL-PRECISION state_dict, not the engine's bf16
    device params (which would round every weight through bf16)."""
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1,
                              "offload_optimizer": {"device": "cpu"}},
        "seed": 1,
    }
    engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                               example_batch=random_batch(8))
    sd = engine.module_state_dict()
    # values with bits below bf16's 8-bit mantissa: bf16 would round them
    sd = {k: np.full_like(np.asarray(v, np.float32), 1.0 + 2.0 ** -12)
          for k, v in sd.items()}
    engine.load_module_state_dict(sd)
    master = engine.offload.state_dict()["master"]
    for leaf in jax.tree.leaves(master):
        np.testing.assert_array_equal(np.asarray(leaf).ravel()[0],
                                      np.float32(1.0 + 2.0 ** -12))
