"""Overlap-schedule comm-plan algorithms (round 14, docs/COMM.md):
chunked allgather→matmul for the ZeRO-3 param fetch and chunked grad
reduce-scatter for the ZeRO-2 sync, registered as the
``overlap``/``overlap_int8`` algorithm family.

Coverage: registration + plan round-trip, selector picks overlap from
recorded rows only (never the heuristic), executor values, HLO
chunk-structure audits for BOTH seams in the test_onebit wire-byte
style (>= chunks chunk-sized collectives, no full-tensor collective on
the overlapped path, no full-remat of the model body), chunk-count
compile invariance, exact-vs-overlap multi-step loss parity through the
shared ``_finalize_step`` tail, the widened-envelope degrade matrix,
per-axis sweeps, the ds_bench overlap rows (``overlap_ratio``), and a
2-proc gloo ZeRO-2 overlap e2e (tier-2).
"""

import json
import os
import pathlib
import re
import socket
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu import comm_plan as cp
from deepspeed_tpu.comm_plan.plan import (ALGOS, QUANTIZED_ALGOS,
                                          SITE_ALGOS, SITE_KIND)
from deepspeed_tpu.runtime.comm.overlap import (chunked_ag_matmul,
                                                chunked_matmul_rs,
                                                effective_chunks,
                                                make_overlap_gather,
                                                overlap_grad_sync)
from deepspeed_tpu.runtime.onebit import hlo_collective_bytes

from util import SimpleModel, random_batch, require_devices

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)


def _count_ops(hlo_text, name):
    """Collective ops by result shape (first tuple element for
    tuple-shaped results), async-pair aware ('-start' counted, '-done'
    skipped): [(dtype, dims tuple), ...]."""
    out = []
    op_pat = re.compile(r"\s" + name + r"(-start|-done)?\(")
    shape_pat = re.compile(r"=\s*\(?\s*([a-z0-9]+)\[([0-9,]*)\]")
    for line in hlo_text.splitlines():
        m = op_pat.search(line)
        if not m or m.group(1) == "-done":
            continue
        s = shape_pat.search(line)
        if s:
            dims = tuple(int(d) for d in s.group(2).split(",") if d)
            out.append((s.group(1), dims))
    return out


# ------------------------------------------------------------- registration

def test_overlap_algos_registered_and_plan_round_trip(tmp_path):
    for algo in ("overlap", "overlap_int8"):
        assert algo in ALGOS
    assert set(SITE_ALGOS["grad_reduce_scatter"]) >= {"exact", "int8",
                                                      "overlap",
                                                      "overlap_int8"}
    assert set(SITE_ALGOS["param_all_gather"]) >= {"exact", "overlap"}
    assert SITE_KIND["param_all_gather"] == "all_gather"
    # overlap moves exact values: the accuracy guard must not latch it
    assert "overlap" not in QUANTIZED_ALGOS
    assert "overlap_int8" in QUANTIZED_ALGOS
    plan = cp.CommPlan()
    plan.add(cp.PlanEntry("all_gather", "all", 20, "overlap"))
    plan.add(cp.PlanEntry("reduce_scatter", "data", 23, "overlap_int8"))
    path = str(tmp_path / "plan.json")
    plan.save(path)
    loaded = cp.CommPlan.load(path)
    assert loaded.choose("all_gather", "data", 2 ** 20) == "overlap"
    assert loaded.choose("reduce_scatter", "data",
                         8 * 2 ** 20) == "overlap_int8"


def _overlap_rows(kind, size_bytes, overlap_us=100.0, exact_us=300.0):
    return [
        {"op": kind, "algo": "exact", "axis": "all",
         "size_bytes": size_bytes, "latency_us": exact_us},
        {"op": kind, "algo": "overlap", "axis": "all",
         "size_bytes": size_bytes, "latency_us": overlap_us,
         "overlap_ratio": 0.6, "chunks": 4},
    ]


def test_selector_picks_overlap_where_its_latency_wins():
    rows = (_overlap_rows("reduce_scatter", 8 * 2 ** 20)
            + _overlap_rows("all_gather", 2 ** 20))
    plan = cp.select_plan(rows)
    assert plan.choose("reduce_scatter", "data", 8 * 2 ** 20) == "overlap"
    assert plan.choose("all_gather", "data", 2 ** 20) == "overlap"
    # and where it loses, exact stays
    plan2 = cp.select_plan(_overlap_rows("all_gather", 2 ** 20,
                                         overlap_us=500.0))
    assert plan2.choose("all_gather", "data", 2 ** 20) == "exact"
    # a tie breaks toward the SAFER algorithm: exact < overlap in ALGOS
    plan3 = cp.select_plan(_overlap_rows("all_gather", 2 ** 20,
                                         overlap_us=300.0))
    assert plan3.choose("all_gather", "data", 2 ** 20) == "exact"


def test_heuristic_never_returns_overlap():
    """Overlap is selected from recorded rows or forced — never
    hard-coded by the no-sweep fallback (acceptance: 'never
    hard-coded')."""
    for kind in ("all_gather", "reduce_scatter", "all_to_all",
                 "all_reduce"):
        for nbytes in (2 ** 12, 2 ** 23, 2 ** 30):
            assert cp.heuristic_algo(kind, nbytes, axis_size=8) in (
                "exact", "int8")


def test_effective_chunks_divisibility():
    assert effective_chunks(16, 4) == 4
    assert effective_chunks(6, 4) == 3      # largest divisor <= 4
    assert effective_chunks(7, 4) == 1
    assert effective_chunks(2, 8) == 2      # floored at the length


# ----------------------------------------------------------------- executors

@pytest.fixture()
def mesh8():
    require_devices(8)
    return Mesh(np.asarray(jax.devices()[:8]), ("data",))


def test_overlap_grad_sync_value(mesh8):
    rng = np.random.default_rng(0)
    vals = rng.standard_normal((8, 4097)).astype(np.float32)  # odd size
    x = jax.device_put(jnp.asarray(vals), NamedSharding(mesh8, P("data")))
    want = vals.mean(axis=0)
    out = np.asarray(overlap_grad_sync(x, mesh=mesh8, axis="data",
                                       chunks=4, algo="overlap"))
    np.testing.assert_allclose(out, want, rtol=0, atol=1e-6)
    out8 = np.asarray(overlap_grad_sync(x, mesh=mesh8, axis="data",
                                        chunks=4, algo="overlap_int8"))
    assert np.abs(out8 - want).max() <= np.abs(vals).max() / 127 * 2
    # nonfinite propagation (overflow detection relies on it)
    bad = vals.copy()
    bad[5, 99] = np.inf
    xb = jax.device_put(jnp.asarray(bad), NamedSharding(mesh8, P("data")))
    outb = np.asarray(overlap_grad_sync(xb, mesh=mesh8, axis="data",
                                        chunks=4, algo="overlap_int8"))
    assert not np.isfinite(outb).all()


def test_overlap_gather_fwd_bwd_parity(mesh8):
    rng = np.random.default_rng(1)
    w_np = rng.standard_normal((256, 64)).astype(np.float32)
    w = jax.device_put(jnp.asarray(w_np),
                       NamedSharding(mesh8, P("data", None)))
    x = jax.device_put(
        jnp.asarray(rng.standard_normal((4, 256)).astype(np.float32)),
        NamedSharding(mesh8, P()))
    ref = jax.jit(jax.value_and_grad(
        lambda w, x: jnp.sum((x @ w) ** 2)))
    v_ref, g_ref = ref(w, x)
    for algo in ("overlap", "overlap_int8"):
        g = make_overlap_gather(mesh8, ("data",), 0, chunks=4, algo=algo)
        got = np.asarray(jax.jit(g)(w))
        if algo == "overlap":
            np.testing.assert_allclose(got, w_np, rtol=0, atol=0)
        else:
            assert np.abs(got - w_np).max() <= \
                np.abs(w_np).max() / 127 * 1.01
        v, gr = jax.jit(jax.value_and_grad(
            lambda w, x: jnp.sum((x @ g(w)) ** 2)))(w, x)
        scale = np.abs(np.asarray(g_ref)).max()
        tol = 1e-5 if algo == "overlap" else 0.05
        assert abs(float(v - v_ref)) <= tol * abs(float(v_ref))
        assert np.abs(np.asarray(gr) - np.asarray(g_ref)).max() <= \
            tol * scale


# ------------------------------------------------------- HLO structure audit

def test_hlo_grad_sync_overlap_is_chunked_no_full_collective(mesh8):
    """The overlapped sync's wire is >= chunks chunk-sized hops and has
    NO whole-buffer collective; the int8 variant's payload is s8 with
    scales riding per chunk, at <= 28% of the chunked-exact bytes."""
    numel = 65536
    x = jax.device_put(jnp.ones((8, numel), jnp.float32),
                       NamedSharding(mesh8, P("data")))

    def hlo(algo, chunks):
        fn = jax.jit(lambda v: overlap_grad_sync(
            v, mesh=mesh8, axis="data", chunks=chunks, algo=algo))
        return fn.lower(x).compile().as_text()

    txt = hlo("overlap", 4)
    a2a = _count_ops(txt, "all-to-all")
    ag = _count_ops(txt, "all-gather")
    assert len(a2a) >= 4 and len(ag) >= 4, (len(a2a), len(ag))
    # full-buffer hop would move numel/8 columns at once
    full_cols = numel // 8
    assert all(full_cols not in dims for _, dims in a2a), a2a
    txt8 = hlo("overlap_int8", 4)
    assert "s8" in txt8 and "s8" not in txt
    bytes_exact = hlo_collective_bytes(txt)
    bytes_int8 = hlo_collective_bytes(txt8)
    assert bytes_int8 <= 0.28 * bytes_exact, (bytes_int8, bytes_exact)


HLO_Z3_AUDIT = textwrap.dedent(r"""
    import os, sys, json, re
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])
    sys.path.insert(0, os.path.join(os.environ["DSTPU_TEST_REPO"],
                                    "tests"))
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from util import SimpleModel, random_batch

    H = 128
    cfg = {"train_batch_size": 16,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 3,
                                 "stage3_param_persistence_threshold": 0},
           "comm_plan": {"enabled": True, "overlap_min_leaf_elems": 256,
                         "overlap_chunks": 4,
                         "overrides": {"param_all_gather": "overlap"}},
           "seed": 7}
    engine, *_ = ds.initialize(model=SimpleModel(hidden=H),
                               example_batch=random_batch(16), config=cfg)
    assert engine.comm_plan_ctx.resolved["param_all_gather"] == "overlap"
    micros = jax.tree.map(lambda x: jnp.asarray(x)[None],
                          random_batch(16))
    txt = jax.jit(engine._train_step).lower(
        engine.state, micros, jax.random.PRNGKey(0),
        jnp.asarray(5e-3, jnp.float32)).compile().as_text()
    op_pat = re.compile(
        r"\s(all-gather|reduce-scatter)(-start|-done)?"
        r"\(([a-z0-9]+)\[([0-9,]*)\]")
    shape_pat = re.compile(r"=\s*\(?\s*[a-z0-9]+\[([0-9,]*)\]")
    ags, rss = [], []
    for line in txt.splitlines():
        m = op_pat.search(line)
        if not m or m.group(2) == "-done":
            continue
        s = shape_pat.search(line)
        if not s:
            continue
        res = tuple(int(d) for d in s.group(1).split(",") if d)
        opnd = tuple(int(d) for d in m.group(4).split(",") if d)
        (ags if m.group(1) == "all-gather" else rss).append((opnd, res))
    # chunk-sized gathers of the HxH kernel: local [H/8, H] sliced into
    # 4 chunks -> gathered chunk [8, H/32, H]. A FULL-tensor param
    # gather would move the whole [H/8, H] shard to [H, H] in one op
    # (the cotangent replication at the transposed region boundary also
    # lands on [H, H] but from a [H, H/8] column operand — that one is
    # XLA's resharding of the grad, not a param fetch).
    chunk = (8, H // 32, H)
    out = {"chunk_ags": sum(1 for o, r in ags if r == chunk),
           "full_param_ags": sum(1 for o, r in ags
                                 if o == (H // 8, H) and r == (H, H)),
           "chunk_rss": sum(1 for o, r in rss if r == (1,) + chunk[1:]),
           "n_rss": len(rss)}
    print("AUDIT: " + json.dumps(out))
""")


def test_hlo_zero3_overlap_step_chunked_no_full_gather_no_remat(tmp_path):
    """Acceptance audit, subprocess so XLA's stderr is capturable: the
    overlapped ZeRO-3 step holds >= overlap_chunks chunk-sized
    allgathers of the HxH kernel and ZERO full-tensor gathers of it,
    the backward reduce-scatters in the same chunks, and the compile
    emits no involuntary full rematerialization of the model body."""
    require_devices(8)
    script = tmp_path / "z3_audit.py"
    script.write_text(HLO_Z3_AUDIT)
    env = dict(os.environ, DSTPU_TEST_REPO=REPO_ROOT,
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    audit = json.loads(proc.stdout.split("AUDIT: ")[1].splitlines()[0])
    assert audit["chunk_ags"] >= 4, audit
    assert audit["full_param_ags"] == 0, audit
    assert audit["chunk_rss"] >= 4, audit
    assert "Involuntary full rematerialization" not in proc.stderr, \
        [l for l in proc.stderr.splitlines()
         if "rematerialization" in l][:4]


# --------------------------------------------------------- engine integration

def _engine(cfg_extra=None, seed=7, hidden=32):
    cfg = {"train_batch_size": 16,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 2}, "seed": seed}
    cfg.update(cfg_extra or {})
    engine, *_ = ds.initialize(model=SimpleModel(hidden=hidden),
                               example_batch=random_batch(16), config=cfg)
    return engine


# tier-2 (round-17 budget sweep, ~10s): the cheaper tier-1 cousins are
# test_overlap_grad_sync_value (wire values) and
# test_hlo_grad_sync_overlap_is_chunked_no_full_collective (structure);
# scripts/tier2.sh runs this 12-step engine parity leg
@pytest.mark.slow
def test_engine_zero2_overlap_12step_loss_parity():
    """Acceptance: exact-vs-overlap 12-step loss parity through the
    shared _finalize_step tail. The overlap wire moves exact values, so
    the twin tracks the exact engine to float tolerance; overlap_int8
    tracks within the blockwise-int8 band."""
    require_devices(8)
    e0 = _engine()
    e1 = _engine({"comm_plan": {"enabled": True,
                                "overrides": {"grad_reduce_scatter":
                                              "overlap"}}})
    e2 = _engine({"comm_plan": {"enabled": True,
                                "overrides": {"grad_reduce_scatter":
                                              "overlap_int8"}}})
    assert e1.comm_plan_ctx.resolved["grad_reduce_scatter"] == "overlap"
    l0, l1, l2 = [], [], []
    for i in range(12):
        b = random_batch(16, seed=i)
        l0.append(float(e0.train_batch(b)["loss"]))
        m1 = e1.train_batch(b)
        assert m1["grad_sync_algo"] == "overlap"
        l1.append(float(m1["loss"]))
        m2 = e2.train_batch(b)
        assert m2["grad_sync_algo"] == "overlap_int8"
        l2.append(float(m2["loss"]))
    assert np.isfinite(l1).all() and np.isfinite(l2).all()
    assert l1[-1] < l1[0]                     # it trains
    assert max(abs(a - b) for a, b in zip(l0, l1)) < 1e-4, (l0, l1)
    assert max(abs(a - b) for a, b in zip(l0, l2)) < 0.05, (l0, l2)


def test_engine_zero3_overlap_param_gather_parity():
    """The chunked explicit stage-3 gather is numerically the implicit
    gather: twin loss curves match to float tolerance, and the audit
    tag proves every step ran the overlapped program."""
    require_devices(8)
    z3 = {"zero_optimization": {"stage": 3,
                                "stage3_param_persistence_threshold": 0}}
    e0 = _engine(dict(z3), hidden=128)
    e1 = _engine({**z3, "comm_plan": {"enabled": True,
                                      "overlap_min_leaf_elems": 256,
                                      "overrides": {"param_all_gather":
                                                    "overlap"}}},
                 hidden=128)
    assert e1.comm_plan_ctx.resolved["param_all_gather"] == "overlap"
    assert e1._overlap_gathers is not None
    l0, l1 = [], []
    for i in range(8):
        b = random_batch(16, seed=i)
        l0.append(float(e0.train_batch(b)["loss"]))
        m = e1.train_batch(b)
        assert m["param_gather_algo"] == "overlap"
        l1.append(float(m["loss"]))
    assert np.isfinite(l1).all()
    assert max(abs(a - b) for a, b in zip(l0, l1)) < 1e-4, (l0, l1)


def test_chunk_count_compile_invariance():
    """Changing overlap_chunks recompiles ONCE (it is a static trace
    constant), never per step: 3 steps at chunks=4 hit one compiled
    program, and the chunk count actually shapes the wire (different
    chunks -> different collective counts)."""
    require_devices(8)
    e = _engine({"comm_plan": {"enabled": True, "overlap_chunks": 4,
                               "overrides": {"grad_reduce_scatter":
                                             "overlap"}}})
    for i in range(3):
        assert e.train_batch(
            random_batch(16, seed=i))["grad_sync_algo"] == "overlap"
    cache_size = getattr(e._train_step_q, "_cache_size", None)
    if cache_size is not None:
        assert cache_size() == 1, (
            f"overlap step traced {cache_size()}x across 3 steps")
    # chunk count shapes the program: 2 vs 4 chunks -> 2x collectives
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    x = jax.device_put(jnp.ones((8, 4096), jnp.float32),
                       NamedSharding(mesh, P("data")))

    def n_a2a(chunks):
        fn = jax.jit(lambda v: overlap_grad_sync(
            v, mesh=mesh, axis="data", chunks=chunks, algo="overlap"))
        return len(_count_ops(fn.lower(x).compile().as_text(),
                              "all-to-all"))

    assert n_a2a(4) > n_a2a(2) >= 2


def test_engine_overlap_selected_from_recorded_plan(tmp_path):
    """Acceptance: overlap is selected PER CELL by the plan built from
    sweep rows — no override, no hard-coding. Rows make overlap win the
    grad-sync reduce-scatter buckets and the param-fetch all_gather
    buckets; both engines resolve and run it."""
    require_devices(8)
    rows = []
    for b in range(10, 27):
        rows += _overlap_rows("reduce_scatter", 2 ** b)
        rows += _overlap_rows("all_gather", 2 ** b)
    plan = cp.select_plan(rows)
    path = str(tmp_path / "plan.json")
    plan.save(path)
    e = _engine({"comm_plan": {"enabled": True, "plan_path": path}})
    assert e.comm_plan_ctx.resolved["grad_reduce_scatter"] == "overlap"
    assert e.train_batch(random_batch(16))["grad_sync_algo"] == "overlap"
    z3 = {"zero_optimization": {"stage": 3,
                                "stage3_param_persistence_threshold": 0},
          "comm_plan": {"enabled": True, "plan_path": path,
                        "overlap_min_leaf_elems": 256}}
    e3 = _engine(z3, hidden=128)
    assert e3.comm_plan_ctx.resolved["param_all_gather"] == "overlap"
    m = e3.train_batch(random_batch(16))
    assert m["param_gather_algo"] == "overlap"
    assert np.isfinite(float(m["loss"]))


# tier-2 (round-17 budget sweep, ~11s): the cheaper tier-1 cousins are
# test_comm_plan.test_engine_accuracy_guard_forces_exact (lossy latch)
# and test_engine_zero3_overlap_param_gather_parity (exact-wire overlap
# keeps running); scripts/tier2.sh runs this exemption matrix
@pytest.mark.slow
def test_accuracy_guard_exempts_exact_wire_overlap():
    """The guard forces exact only for LOSSY formats: overlap_int8
    latches to exact, plain overlap keeps running (it already moves
    exact values)."""
    require_devices(8)
    e = _engine({"comm_plan": {"enabled": True,
                               "guard_min_grad_norm": 1e9,
                               "overrides": {"grad_reduce_scatter":
                                             "overlap"}}})
    algos = [e.train_batch(random_batch(16, seed=i))["grad_sync_algo"]
             for i in range(3)]
    assert algos == ["overlap", "overlap", "overlap"], algos
    e2 = _engine({"comm_plan": {"enabled": True,
                                "guard_min_grad_norm": 1e9,
                                "overrides": {"grad_reduce_scatter":
                                              "overlap_int8"}}})
    algos2 = [e2.train_batch(random_batch(16, seed=i))["grad_sync_algo"]
              for i in range(3)]
    assert algos2 == ["overlap_int8", "exact", "exact"], algos2


# ------------------------------------------------------------- envelope pins

# tier-2 (round-17 budget sweep, ~12s): the cheaper tier-1 cousins are
# test_comm_plan.test_engine_forced_sync_outside_envelope_degrades (same
# degrade contract, one site) and test_effective_chunks_divisibility;
# scripts/tier2.sh runs the full forced/unforced matrix
@pytest.mark.slow
def test_envelope_degrade_matrix():
    """Round-14 contract: a forced non-exact grad sync OUTSIDE the
    envelope degrades to exact with a warning instead of raising, and
    this pins exactly which configs degrade on this host. TP now sits
    INSIDE the envelope where native jax.shard_map exists; on the 0.4.x
    line it degrades (the legacy adapter aborts inside XLA)."""
    require_devices(8)
    import logging
    from deepspeed_tpu.utils.logging import logger as ds_logger
    records = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r.getMessage())
    ds_logger.addHandler(handler)
    try:
        # stage 3 shards compute params: degrades everywhere
        e = _engine({"zero_optimization": {"stage": 3},
                     "comm_plan": {"enabled": True,
                                   "overrides": {"grad_reduce_scatter":
                                                 "int8"}}})
    finally:
        ds_logger.removeHandler(handler)
    assert e.comm_plan_ctx.resolved["grad_reduce_scatter"] == "exact"
    assert any("running exact" in m for m in records), records
    assert np.isfinite(float(e.train_batch(random_batch(16))["loss"]))
    # TP composition: envelope membership depends on native shard_map
    from deepspeed_tpu.models import build_model, causal_lm_loss
    model, mcfg = build_model("gpt2-tiny", hidden_size=64, num_layers=1,
                              num_heads=4, vocab_size=128, max_seq_len=32,
                              attention_impl="reference")
    cfg = {"train_batch_size": 4, "train_micro_batch_size_per_gpu": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 2},
           "tensor_parallel": {"tp_size": 2},
           "comm_plan": {"enabled": True,
                         "overrides": {"grad_reduce_scatter": "int8"}}}
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 128, size=(4, 16))}
    records.clear()
    ds_logger.addHandler(handler)
    try:
        eng, *_ = ds.initialize(model=model, config=cfg,
                                loss_fn=causal_lm_loss,
                                example_batch=batch,
                                sharding_rules=mcfg.tp_rules())
    finally:
        ds_logger.removeHandler(handler)
    if hasattr(jax, "shard_map"):
        # modern jaxlib: TP composes — the forced verdict holds
        assert eng.comm_plan_ctx.resolved["grad_reduce_scatter"] == "int8"
    else:
        assert eng.comm_plan_ctx.resolved["grad_reduce_scatter"] == "exact"
        assert any("native jax.shard_map" in m for m in records), records
        assert np.isfinite(float(eng.train_batch(batch)["loss"]))
    # an unexecutable forced algo NAME still raises (never silently runs
    # something else)
    with pytest.raises(ValueError, match="not executable"):
        _engine({"comm_plan": {"enabled": True,
                               "overrides": {"grad_reduce_scatter":
                                             "onebit"}}})


@pytest.mark.slow
def test_tp_composed_explicit_sync_parity():
    """The widened envelope actually syncing under TP (native
    jax.shard_map hosts only): int8 grad sync with tp_size=2 tracks the
    exact twin. Skipped on the 0.4.x line, where the envelope test above
    pins the degrade instead."""
    require_devices(8)
    if not hasattr(jax, "shard_map"):
        pytest.skip("TP-composed explicit sync needs native jax.shard_map")
    from deepspeed_tpu.models import build_model, causal_lm_loss

    def mk(extra):
        model, mcfg = build_model("gpt2-tiny", hidden_size=64,
                                  num_layers=1, num_heads=4,
                                  vocab_size=128, max_seq_len=32,
                                  attention_impl="reference")
        cfg = {"train_batch_size": 8,
               "train_micro_batch_size_per_gpu": 2,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 2},
               "tensor_parallel": {"tp_size": 2}, "seed": 5, **extra}
        batch = {"input_ids": np.random.default_rng(0).integers(
            0, 128, size=(8, 16))}
        e, *_ = ds.initialize(model=model, config=cfg,
                              loss_fn=causal_lm_loss,
                              example_batch=batch,
                              sharding_rules=mcfg.tp_rules())
        return e, batch

    e0, batch = mk({})
    e1, _ = mk({"comm_plan": {"enabled": True,
                              "overrides": {"grad_reduce_scatter":
                                            "int8"}}})
    assert e1.comm_plan_ctx.resolved["grad_reduce_scatter"] == "int8"
    l0 = [float(e0.train_batch(batch)["loss"]) for _ in range(6)]
    l1 = [float(e1.train_batch(batch)["loss"]) for _ in range(6)]
    assert np.isfinite(l1).all()
    assert max(abs(a - b) for a, b in zip(l0, l1)) < 0.05, (l0, l1)


# --------------------------------------------------- per-axis sweeps + bench

def test_per_axis_sweep_records_one_row_per_mesh_axis(tmp_path, capsys):
    """Satellite: on a >1-axis mesh the sweep records one row per axis
    (hierarchical ICI/DCN selection needs per-axis measurements); the
    selected plan carries per-axis entries the wildcard resolution
    prefers over 'all'."""
    require_devices(8)
    from deepspeed_tpu.comm_plan.cli import main as cli_main
    out_path = str(tmp_path / "plan.json")
    rc = cli_main(["sweep", "--ops", "reduce_scatter", "--algos",
                   "exact", "--sizes-mb", "0.25", "--iters", "2",
                   "--mesh", "data=2,model=4", "--out", out_path])
    out = capsys.readouterr().out
    assert rc == 0
    rows = cp.parse_bench_lines(out)
    assert {r["axis"] for r in rows} == {"data", "model"}
    assert {r["n"] for r in rows} == {2, 4}
    plan = cp.CommPlan.load(out_path)
    kinds = {(e.kind, e.axis) for e in plan.entries.values()}
    assert ("reduce_scatter", "data") in kinds
    assert ("reduce_scatter", "model") in kinds
    # per-axis entry answers the exact-axis query (no wildcard needed)
    nbytes = next(iter(plan.entries.values())).bucket
    e = plan.entry_for("reduce_scatter", "model", 2 ** nbytes)
    assert e is not None and e.axis == "model"


def test_comm_bench_overlap_rows_have_ratio(mesh8):
    """ds_bench's overlap cells: latency_us is the EXPOSED comm time,
    the wall/comm/compute split and overlap_ratio ride the row, and the
    selector ingests them unchanged."""
    from deepspeed_tpu.benchmarks.communication import run_op_sweep
    rows = run_op_sweep("all_gather", [0.25], jnp.float32, iters=2,
                        algo="overlap", mesh=mesh8, axis="data")
    rows += run_op_sweep("reduce_scatter", [0.25], jnp.float32, iters=2,
                         algo="overlap_int8", mesh=mesh8, axis="data")
    for r in rows:
        assert r["algo"] in ("overlap", "overlap_int8")
        assert r["latency_us"] > 0
        assert r["overlap_ratio"] > 0
        assert r["chunks"] >= 2
        assert r["wall_us"] >= r["latency_us"]
    plan = cp.select_plan(rows)
    assert plan.entries          # rows are selector-ingestible


def test_bench_pipeline_values(mesh8):
    """The benchmark payloads compute what they claim (a wrong payload
    would time garbage): chunked ag->matmul == x @ w; chunked
    matmul->rs chunks reconstruct the mean-reduced grads."""
    rng = np.random.default_rng(3)
    w = jax.device_put(
        jnp.asarray(rng.standard_normal((64, 32)).astype(np.float32)),
        NamedSharding(mesh8, P("data", None)))
    x = jax.device_put(
        jnp.asarray(rng.standard_normal((8, 64)).astype(np.float32)),
        NamedSharding(mesh8, P()))
    got = np.asarray(chunked_ag_matmul(x, w, mesh=mesh8, axis="data",
                                       chunks=4))
    np.testing.assert_allclose(got, np.asarray(x) @ np.asarray(w),
                               rtol=1e-5, atol=1e-4)
    u = jax.device_put(
        jnp.asarray(rng.standard_normal((8, 16)).astype(np.float32)),
        NamedSharding(mesh8, P("data")))
    v = jax.device_put(
        jnp.asarray(rng.standard_normal((16, 40)).astype(np.float32)),
        NamedSharding(mesh8, P()))
    got = np.asarray(chunked_matmul_rs(u, v, mesh=mesh8, axis="data",
                                       chunks=4))
    want = (np.asarray(u) @ np.asarray(v)).mean(axis=0)    # [40]
    # per-chunk scattered layout: chunk k's served piece (padded to
    # ceil(seg/n)) sits at column k*c per rank; reassemble and compare
    segs = [(0, 10), (10, 20), (20, 30), (30, 40)]
    c = got.shape[1] // 4
    for k, (lo, hi) in enumerate(segs):
        piece = np.concatenate([got[r, k * c:(k + 1) * c]
                                for r in range(8)])[:hi - lo]
        np.testing.assert_allclose(piece, want[lo:hi], rtol=1e-5,
                                   atol=1e-5)


# --------------------------------------------------------------- 2-proc gloo

WORKER_OVERLAP_ZERO2 = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
sys.path.insert(0, os.environ["DSTPU_TEST_REPO"])

import numpy as np
import deepspeed_tpu as ds

ds.init_distributed()
rank = ds.comm.get_rank()
assert ds.comm.get_world_size() == 2

sys.path.insert(0, os.path.join(os.environ["DSTPU_TEST_REPO"], "tests"))
from util import SimpleModel, random_batch

config = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    "zero_optimization": {"stage": 2},
    "comm_plan": {"enabled": True, "overlap_chunks": 4,
                  "overrides": {"grad_reduce_scatter": "overlap"}},
    "seed": 11,
}
engine, *_ = ds.initialize(model=SimpleModel(), config=config,
                           example_batch=random_batch(8))
assert engine.comm_plan_ctx.resolved["grad_reduce_scatter"] == "overlap"
losses = []
for i in range(8):
    m = engine.train_batch(random_batch(8, seed=i))
    assert m["grad_sync_algo"] == "overlap"
    losses.append(float(m["loss"]))
assert np.isfinite(losses).all(), losses
assert losses[-1] < losses[0], losses
print(f"RANK{rank} OK last={losses[-1]:.6f}", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_zero2_overlap_grad_sync(tmp_path):
    """Acceptance satellite (tier-2, scripts/tier2.sh): a REAL
    2-process gloo world runs ZeRO-2 with the chunked overlap sync —
    the cross-process wire carries the chunk hops, and both ranks see
    identical losses (the sync synced)."""
    worker = tmp_path / "worker_overlap.py"
    worker.write_text(WORKER_OVERLAP_ZERO2)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ,
                   DSTPU_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   DSTPU_NUM_PROCESSES="2",
                   DSTPU_PROCESS_ID=str(pid),
                   DSTPU_TEST_REPO=REPO_ROOT)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-3000:]}"
        assert f"RANK{pid} OK" in out, out[-2000:]
    assert outs[0].split("last=")[1].split()[0] == \
        outs[1].split("last=")[1].split()[0]
