"""Config parsing + batch triangulation tests.

Mirrors reference tests/unit/runtime/test_ds_config_model.py and config tests.
"""

import pytest

from deepspeed_tpu.config import DeepSpeedConfig, load_config


def test_defaults():
    cfg = load_config(None)
    assert cfg.zero_optimization.stage == 0
    assert not cfg.fp16.enabled
    assert not cfg.bf16.enabled
    assert cfg.gradient_clipping == 0.0


def test_ds_json_keys_parse():
    """A representative reference-style ds_config must parse unchanged."""
    cfg = load_config({
        "train_batch_size": 64,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 0.00015, "betas": [0.9, 0.999]}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 1000}},
        "fp16": {"enabled": True, "loss_scale": 0, "initial_scale_power": 16,
                 "loss_scale_window": 1000, "hysteresis": 2, "min_loss_scale": 1},
        "zero_optimization": {
            "stage": 2,
            "allgather_partitions": True,
            "reduce_scatter": True,
            "allgather_bucket_size": 50000000,
            "reduce_bucket_size": 50000000,
            "overlap_comm": True,
            "contiguous_gradients": True,
            "cpu_offload": True,
        },
        "gradient_clipping": 1.0,
        "wall_clock_breakdown": False,
        "steps_per_print": 10,
        "activation_checkpointing": {"partition_activations": True, "cpu_checkpointing": False},
        "flops_profiler": {"enabled": True, "profile_step": 1},
        "tensorboard": {"enabled": True, "output_path": "/tmp/tb"},
        "comms_logger": {"enabled": True},
        "aio": {"block_size": 1048576, "queue_depth": 8},
        "elasticity": {"enabled": False},
    })
    assert cfg.train_batch_size == 64
    assert cfg.optimizer.type == "Adam"
    assert cfg.optimizer.params["lr"] == 0.00015
    assert cfg.scheduler.type == "WarmupLR"
    assert cfg.fp16.enabled and cfg.fp16.loss_scale == 0
    assert cfg.zero_optimization.stage == 2
    # deprecated cpu_offload migrates to offload_optimizer (reference config_utils.py)
    assert cfg.zero_optimization.offload_optimizer.device == "cpu"
    assert cfg.gradient_clipping == 1.0
    assert cfg.activation_checkpointing.partition_activations


def test_bf16_alias():
    cfg = load_config({"train_batch_size": 8, "bfloat16": {"enabled": True}})
    assert cfg.bf16.enabled
    assert cfg.precision_dtype == "bfloat16"


def test_stage3_aliases():
    cfg = load_config({"zero_optimization": {
        "stage": 3,
        "stage3_prefetch_bucket_size": 1000,
        "stage3_param_persistence_threshold": 5,
        "stage3_gather_16bit_weights_on_model_save": True,
    }})
    z = cfg.zero_optimization
    assert z.prefetch_bucket_size == 1000
    assert z.param_persistence_threshold == 5
    assert z.gather_16bit_weights_on_model_save


@pytest.mark.parametrize("given,expected", [
    ({"train_batch_size": 32}, (32, 4, 1)),
    ({"train_batch_size": 32, "gradient_accumulation_steps": 2}, (32, 2, 2)),
    ({"train_micro_batch_size_per_gpu": 4, "gradient_accumulation_steps": 2}, (64, 4, 2)),
    ({"train_batch_size": 64, "train_micro_batch_size_per_gpu": 4}, (64, 4, 2)),
])
def test_batch_triangulation(given, expected):
    """reference: runtime/config.py _set_batch_related_parameters (dp=8)."""
    cfg = load_config(given)
    cfg.resolve_batch_sizes(dp_world_size=8)
    assert (cfg.train_batch_size, cfg.train_micro_batch_size_per_gpu,
            cfg.gradient_accumulation_steps) == expected


def test_batch_inconsistency_raises():
    cfg = load_config({"train_batch_size": 10, "train_micro_batch_size_per_gpu": 4,
                       "gradient_accumulation_steps": 4})
    with pytest.raises(ValueError):
        cfg.resolve_batch_sizes(dp_world_size=8)


def test_no_batch_raises():
    cfg = load_config({})
    with pytest.raises(ValueError):
        cfg.resolve_batch_sizes(dp_world_size=8)
