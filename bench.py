"""Benchmark: flagship GPT training-step throughput on the available chip(s).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Baseline: the reference's headline sustained training throughput of
50 TFLOPS/GPU (ZeRO-3 Offload on V100, docs/_posts/2021-03-08-zero3-offload.md:65;
see BASELINE.md). vs_baseline = our model TFLOPs/chip / 50.

Tuned config (measured on v5e, round 2): micro-batch 16 x gas 16 in one
compiled step, selective "dots" remat (save attention outputs, recompute the
rest), fused chunked CE loss in 256-token chunks (no [B,S,V] fp32 logits
materialization), Pallas flash attention with 1024x1024 blocks both passes
(at seq<=1024 the whole sequence sits in one tile; measured +30% THROUGHPUT
vs the round-1 256/512 blocks).
"""

import json
import time

import numpy as np

BASELINE_TFLOPS_PER_CHIP = 50.0


def main():
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, fused_loss_passthrough

    on_tpu = jax.default_backend() == "tpu"
    n_chips = len(jax.devices())

    if on_tpu:
        preset, micro, gas, seq, steps = "gpt2-350m", 16, 16, 1024, 4
    else:  # smoke path for CPU-only environments
        preset, micro, gas, seq, steps = "gpt2-tiny", 8, 1, 128, 3

    model, cfg = build_model(preset, max_seq_len=seq, remat=on_tpu,
                             remat_policy="dots", fused_loss=True,
                             loss_chunk=256)
    batch_size = micro * gas * max(n_chips, 1)
    config = {
        "train_batch_size": batch_size,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10,
    }
    rng = np.random.default_rng(0)

    def make_batch():
        return {"input_ids": rng.integers(
            0, cfg.vocab_size, size=(batch_size, seq))}

    engine, *_ = ds.initialize(model=model, config=config,
                               loss_fn=fused_loss_passthrough,
                               example_batch=make_batch())
    # two warmup steps (compile + steady state); float() forces real completion
    # (block_until_ready alone does not synchronize through remote relays)
    float(engine.train_batch(make_batch())["loss"])
    float(engine.train_batch(make_batch())["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(make_batch())
    loss = float(m["loss"])
    # the loss only depends on params through step N-1; read back a param
    # element so the final optimizer update is included in the timed region
    float(jax.tree.leaves(engine.state.params)[0].ravel()[0])
    dt = (time.perf_counter() - t0) / steps

    # 6 * N * T model flops per token-step (fwd 2NT + bwd 4NT)
    n_params = cfg.num_params()
    tokens = batch_size * seq
    flops = 6.0 * n_params * tokens
    tflops_per_chip = flops / dt / max(n_chips, 1) / 1e12

    print(json.dumps({
        "metric": "gpt2_train_tflops_per_chip",
        "value": round(tflops_per_chip, 3),
        "unit": "TFLOPs/chip",
        "vs_baseline": round(tflops_per_chip / BASELINE_TFLOPS_PER_CHIP, 4),
        "detail": {"preset": preset, "micro": micro, "gas": gas,
                   "batch": batch_size, "seq": seq,
                   "chips": n_chips, "step_time_s": round(dt, 4),
                   "loss": round(loss, 4), "backend": jax.default_backend()},
    }))


if __name__ == "__main__":
    main()
