"""Benchmark: flagship GPT training-step throughput on the available chip(s).

Prints TWO JSON lines (driver records the last):
  1. gpt2-350m ZeRO-1 sustained throughput (round-2 continuity metric)
  2. gpt2-1.3b ZeRO-3 device-resident throughput — the BASELINE.md
     north-star config, runnable on ONE v5e chip via pure-bf16 state
     (params-are-master + bf16 moments + bf16 grad accumulation; host
     offload is relay-bandwidth-starved here — see docs/BENCHMARKS.md
     roofline notes).

Baseline: the reference's headline sustained training throughput of
50 TFLOPS/GPU (ZeRO-3 Offload on V100, docs/_posts/2021-03-08-zero3-offload.md:65;
see BASELINE.md). vs_baseline = our model TFLOPs/chip / 50.

Tuned configs (measured on v5e, rounds 2-5 — sweeps in scripts/perf_sweep.py
and the round-5 gas-amortization sweep in docs/BENCHMARKS.md): every leg
carries a fixed ~0.33 s/step optimizer+sync overhead, so raising gradient
accumulation amortizes it — gas 16 -> 128 lifted the 1.3b north-star from
~104 to ~113 TF/chip (62.0% MFU incl. attention). seq-2048 additionally
switched to "full" remat, which frees enough HBM for micro 2 (the round-4
micro-1 shape was the real ceiling there: 84.5 -> ~93 TF).
"""

import json
import os

BASELINE_TFLOPS_PER_CHIP = 50.0


def _emit(r, metric):
    print(json.dumps({
        "metric": metric,
        "value": r["value"],
        "unit": "TFLOPs/chip",
        "vs_baseline": round(r["value"] / BASELINE_TFLOPS_PER_CHIP, 4),
        "detail": r["detail"],
    }), flush=True)


def paged_decode_microbench():
    """int8-vs-baseline paged-decode attention step (round 17): same block
    table, same query, pool stored int8 + per-row scales vs the model
    dtype. On TPU this times the in-kernel dequant tier (int8 crosses
    HBM); on CPU the jnp reference's post-gather dequant. Emits one JSON
    line; under ``DSTPU_SERVE_BENCH_GATE=1`` an int8 step slower than 2x
    the baseline is fatal (the SERVEBENCH gate convention)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.ops.attention import paged_attention
    from deepspeed_tpu.quant_format import kv_quantize

    on_tpu = jax.default_backend() == "tpu"
    base_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    B, nh, hd, bs = (8, 16, 64, 32) if on_tpu else (4, 8, 64, 32)
    num_blocks, nbk = (1024, 32) if on_tpu else (128, 8)
    rng = np.random.default_rng(0)
    kp = rng.standard_normal((nh, num_blocks, bs, hd)).astype(np.float32)
    vp = rng.standard_normal((nh, num_blocks, bs, hd)).astype(np.float32)
    perm = rng.permutation(num_blocks - 1)[:B * nbk] + 1
    bt = jnp.asarray(perm.reshape(B, nbk).astype(np.int32))
    lens = jnp.full((B,), nbk * bs, jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, nh, 1, hd)), base_dtype)
    kb, vb = jnp.asarray(kp, base_dtype), jnp.asarray(vp, base_dtype)
    (kq, ks), (vq, vs) = kv_quantize(jnp.asarray(kp)), kv_quantize(
        jnp.asarray(vp))

    f_base = jax.jit(lambda q, k, v: paged_attention(q, k, v, bt, lens))
    f_int8 = jax.jit(lambda q, k, ks, v, vs: paged_attention(
        q, k, v, bt, lens, k_scale=ks, v_scale=vs))

    def timed(fn, *a, iters=30):
        np.asarray(fn(*a).reshape(-1)[0])           # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*a)
        np.asarray(out.reshape(-1)[0])
        return (time.perf_counter() - t0) / iters

    t_base = timed(f_base, q, kb, vb)
    t_int8 = timed(f_int8, q, kq, ks, vq, vs)
    speedup = t_base / max(t_int8, 1e-9)
    print(json.dumps({
        "metric": "paged_decode_int8_vs_baseline_speedup",
        "value": round(speedup, 3),
        "unit": "x",
        "detail": {"baseline_dtype": str(jnp.dtype(base_dtype)),
                   "baseline_ms": round(t_base * 1e3, 3),
                   "int8_ms": round(t_int8 * 1e3, 3),
                   "batch": B, "heads": nh, "head_dim": hd,
                   "block_size": bs, "blocks_per_seq": nbk,
                   "pool_blocks": num_blocks,
                   "backend": jax.default_backend()},
    }), flush=True)
    if t_int8 > 2.0 * t_base:
        msg = (f"PAGED-DECODE REGRESSION: int8 step {t_int8 * 1e3:.3f}ms > "
               f"2x baseline {t_base * 1e3:.3f}ms")
        if os.environ.get("DSTPU_SERVE_BENCH_GATE") == "1":
            raise SystemExit(msg)
        print(msg, flush=True)
    return speedup


def main():
    import jax
    from deepspeed_tpu.benchmarks.training_bench import run_training_bench

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        import gc

        # tiny HBM footprint: the decode microbench runs before the
        # training legs claim the chip
        paged_decode_microbench()
        gc.collect()
        jax.clear_caches()
        # the 1.3b legs need nearly the whole chip: run them FIRST (clean
        # HBM), free everything, then run the 350m leg; emit the north-star
        # 1.3b seq-1024 line LAST so the driver records it.
        # Per-step timings are individually fenced (round-3 Weak #1); step
        # counts are sized so every leg runs 45-90 s of timed steps at the
        # round-5 gas settings. Config rationale: docs/BENCHMARKS.md
        # round-5 sweep (fixed ~0.33 s/step overhead amortized by gas;
        # "full" remat frees HBM for micro 2 at seq 2048).
        r13 = run_training_bench("gpt2-1.3b", seq=1024, micro=2, gas=128,
                                 steps=5, zero_stage=3, remat=True,
                                 remat_policy="dots", fused_loss=True,
                                 pure_bf16=True, grad_accum_dtype="bf16",
                                 verbose=False)
        gc.collect()
        jax.clear_caches()
        # seq 2048: "full" remat frees enough HBM for micro 2 (round 4's
        # micro-1 was the binding constraint: 84.5 TF); gas 32 amortizes
        # the fixed step overhead; 512-token CE chunks suit the longer seq
        r20 = run_training_bench("gpt2-1.3b", seq=2048, micro=2, gas=32,
                                 steps=6, zero_stage=3, remat=True,
                                 remat_policy="full", fused_loss=True,
                                 loss_chunk=512, pure_bf16=True,
                                 grad_accum_dtype="bf16", verbose=False)
        gc.collect()
        jax.clear_caches()
        # modern-decoder leg (round 4): TinyLlama-1.1B shapes — RMSNorm,
        # SwiGLU, GQA 32q/4kv, rotary, untied head (docs/BENCHMARKS.md)
        rll = run_training_bench("llama-1.1b", seq=1024, micro=2, gas=64,
                                 steps=6, zero_stage=3, remat=True,
                                 remat_policy="dots", fused_loss=True,
                                 pure_bf16=True, grad_accum_dtype="bf16",
                                 verbose=False)
        gc.collect()
        jax.clear_caches()
        # masked BERT-large @ seq 2048 (round 6): REAL ragged padding masks
        # riding the flash kernel in-kernel vs the O(S²)-materializing jnp
        # fallback — the verdict's "unrepresentative maskless leg" replaced.
        # The jnp leg needs micro 2 + full remat (its [B,H,S,S] logits are
        # the memory hog the kernel path exists to avoid).
        rbf = run_training_bench("bert-large", seq=2048, micro=8, gas=4,
                                 steps=4, zero_stage=1, remat=True,
                                 remat_policy="dots", masked=True,
                                 attention_impl="flash", verbose=False)
        gc.collect()
        jax.clear_caches()
        rbr = run_training_bench("bert-large", seq=2048, micro=2, gas=4,
                                 steps=3, zero_stage=1, remat=True,
                                 remat_policy="full", masked=True,
                                 attention_impl="reference", verbose=False)
        gc.collect()
        jax.clear_caches()
        _emit(rbf, "bert_large_masked_seq2048_flash_tflops_per_chip")
        print(json.dumps({
            "metric": "bert_large_masked_seq2048_flash_vs_jnp",
            "value": round(rbf["value"] / max(rbr["value"], 1e-9), 3),
            "unit": "x",
            "detail": {"flash_tflops": rbf["value"],
                       "jnp_tflops": rbr["value"],
                       "flash": rbf["detail"], "jnp": rbr["detail"]},
        }), flush=True)
        # micro 4 (the round-4 cold-start autotune's pick over the hand
        # micro 16) x gas 128 (round-5 amortization sweep)
        r = run_training_bench("gpt2-350m", seq=1024, micro=4, gas=128,
                               steps=6, zero_stage=1, remat=True,
                               remat_policy="dots", fused_loss=True,
                               verbose=False)
        _emit(r, "gpt2_train_tflops_per_chip")
        _emit(rll, "llama_1p1b_zero3_train_tflops_per_chip")
        _emit(r20, "gpt2_1p3b_seq2048_zero3_train_tflops_per_chip")
        _emit(r13, "gpt2_1p3b_zero3_train_tflops_per_chip")
    else:  # smoke path for CPU-only environments
        paged_decode_microbench()
        r = run_training_bench("gpt2-tiny", seq=128, micro=8, gas=1, steps=3,
                               zero_stage=1, fused_loss=True, verbose=False)
        _emit(r, "gpt2_train_tflops_per_chip")
        r = run_training_bench("gpt2-tiny", seq=128, micro=8, gas=1, steps=3,
                               zero_stage=3, pure_bf16=True,
                               grad_accum_dtype="bf16", fused_loss=True,
                               verbose=False)
        _emit(r, "gpt2_1p3b_zero3_train_tflops_per_chip")


if __name__ == "__main__":
    main()
