"""Benchmark: flagship GPT training-step throughput on the available chip(s).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Baseline: the reference's headline sustained training throughput of
50 TFLOPS/GPU (ZeRO-3 Offload on V100, docs/_posts/2021-03-08-zero3-offload.md:65;
see BASELINE.md). vs_baseline = our model TFLOPs/chip / 50.

Tuned config (measured on v5e, round 2 — sweep in scripts/perf_sweep.py):
micro-batch 16 x gas 16 in one compiled step, selective "dots" remat (save
matmul + flash-attention outputs, recompute elementwise), fused chunked CE
loss in 256-token chunks (no [B,S,V] fp32 logits materialization), Pallas
flash attention. micro>=32 or remat off exceed the chip's 15.75GB HBM at
compile. The measurement loop itself lives in
deepspeed_tpu/benchmarks/training_bench.py (shared with ds_bench --training).
"""

import json

BASELINE_TFLOPS_PER_CHIP = 50.0


def main():
    import jax
    from deepspeed_tpu.benchmarks.training_bench import run_training_bench

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        preset, micro, gas, seq, steps = "gpt2-350m", 16, 16, 1024, 4
    else:  # smoke path for CPU-only environments
        preset, micro, gas, seq, steps = "gpt2-tiny", 8, 1, 128, 3

    r = run_training_bench(preset, seq=seq, micro=micro, gas=gas, steps=steps,
                           zero_stage=1, remat=on_tpu, remat_policy="dots",
                           fused_loss=True, verbose=False)
    print(json.dumps({
        "metric": "gpt2_train_tflops_per_chip",
        "value": r["value"],
        "unit": "TFLOPs/chip",
        "vs_baseline": round(r["value"] / BASELINE_TFLOPS_PER_CHIP, 4),
        "detail": {**r["detail"], "preset": preset},
    }))


if __name__ == "__main__":
    main()
