"""Benchmark: flagship GPT training-step throughput on the available chip(s).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Baseline: the reference's headline sustained training throughput of
50 TFLOPS/GPU (ZeRO-3 Offload on V100, docs/_posts/2021-03-08-zero3-offload.md:65;
see BASELINE.md). vs_baseline = our model TFLOPs/chip / 50.
"""

import json
import time

import numpy as np

BASELINE_TFLOPS_PER_CHIP = 50.0


def main():
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, causal_lm_loss

    on_tpu = jax.default_backend() == "tpu"
    n_chips = len(jax.devices())

    if on_tpu:
        preset, batch_size, seq, steps = "gpt2-350m", 8, 1024, 10
    else:  # smoke path for CPU-only environments
        preset, batch_size, seq, steps = "gpt2-tiny", 8, 128, 3

    model, cfg = build_model(preset, max_seq_len=seq, remat=on_tpu)
    config = {
        "train_batch_size": batch_size * max(n_chips, 1),
        "train_micro_batch_size_per_gpu": batch_size,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
    }
    rng = np.random.default_rng(0)

    def make_batch():
        return {"input_ids": rng.integers(
            0, cfg.vocab_size, size=(batch_size * max(n_chips, 1), seq))}

    engine, *_ = ds.initialize(model=model, config=config,
                               loss_fn=causal_lm_loss,
                               example_batch=make_batch())
    engine.train_batch(make_batch())  # compile + warmup
    jax.block_until_ready(engine.state.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(make_batch())
    jax.block_until_ready(engine.state.params)
    dt = (time.perf_counter() - t0) / steps

    # 6 * N * T model flops per token-step (fwd 2NT + bwd 4NT)
    n_params = cfg.num_params()
    tokens = batch_size * max(n_chips, 1) * seq
    flops = 6.0 * n_params * tokens
    tflops_per_chip = flops / dt / max(n_chips, 1) / 1e12

    print(json.dumps({
        "metric": "gpt2_train_tflops_per_chip",
        "value": round(tflops_per_chip, 3),
        "unit": "TFLOPs/chip",
        "vs_baseline": round(tflops_per_chip / BASELINE_TFLOPS_PER_CHIP, 4),
        "detail": {"preset": preset, "batch": batch_size, "seq": seq,
                   "chips": n_chips, "step_time_s": round(dt, 4),
                   "loss": round(float(m["loss"]), 4), "backend": jax.default_backend()},
    }))


if __name__ == "__main__":
    main()
