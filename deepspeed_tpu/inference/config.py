"""Inference config. Capability parity with reference deepspeed/inference/config.py
(DeepSpeedInferenceConfig pydantic model, :124-240)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from pydantic import Field

from ..config.config import DeepSpeedConfigModel, ServingConfig, \
    WatchdogConfig


class InferenceTPConfig(DeepSpeedConfigModel):
    tp_size: int = 1
    enabled: bool = True


class QuantConfig(DeepSpeedConfigModel):
    enabled: bool = False
    bits: int = 8


class MoEInferenceConfig(DeepSpeedConfigModel):
    enabled: bool = False
    ep_size: int = 1
    moe_experts: list = Field(default_factory=lambda: [1])


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    dtype: str = "bfloat16"   # reference default fp16; bf16 is the TPU-native choice
    tensor_parallel: InferenceTPConfig = Field(default_factory=InferenceTPConfig,
                                               alias="tp")
    moe: MoEInferenceConfig = Field(default_factory=MoEInferenceConfig)
    quant: QuantConfig = Field(default_factory=QuantConfig)
    replace_with_kernel_inject: bool = False
    injection_policy: Optional[Dict[Any, Any]] = None
    max_out_tokens: int = 1024
    min_out_tokens: int = 1
    max_tokens: int = 1024
    checkpoint: Optional[str] = None
    enable_cuda_graph: bool = False   # accepted for parity; XLA always "graph-captures"
    replace_method: str = "auto"
    # round 8: continuous-batching serving loop (engine.serve(); shares the
    # section schema with the training config) + the PR-6 watchdog knobs
    # that bound it (serve_timeout)
    serving: ServingConfig = Field(default_factory=ServingConfig)
    watchdog: WatchdogConfig = Field(default_factory=WatchdogConfig)


def load_inference_config(config) -> DeepSpeedInferenceConfig:
    if config is None:
        return DeepSpeedInferenceConfig()
    if isinstance(config, DeepSpeedInferenceConfig):
        return config
    if isinstance(config, dict):
        return DeepSpeedInferenceConfig(**config)
    import json
    with open(config) as f:
        return DeepSpeedInferenceConfig(**json.load(f))
