"""InferenceEngine — config-driven inference wrapper.

Capability parity with the reference's ``deepspeed/inference/engine.py``
(InferenceEngine: TP group creation, dtype conversion, kernel injection,
cuda-graph capture, generate). TPU-native mapping:

  TP process group            -> "model" mesh axis + param sharding rules
  kernel injection            -> jit (XLA fuses what ds fuses by hand); Pallas
                                 decode attention plugs in via models/ layers
  CUDA-graph capture/replay   -> jit compilation cache (always on)
  KV-cache workspace          -> scan-carried cache pytree (models/generation)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import MeshManager
from ..utils.logging import log_dist
from ..utils.partitioning import build_tp_specs
from .config import DeepSpeedInferenceConfig, load_inference_config


class InferenceEngine:
    def __init__(self,
                 model=None,
                 config=None,
                 model_parameters=None,
                 apply_fn: Optional[Callable] = None,
                 sharding_rules: Optional[Dict[str, P]] = None,
                 example_batch=None,
                 mesh_manager: Optional[MeshManager] = None,
                 **kwargs):
        self.module = model
        self.config: DeepSpeedInferenceConfig = load_inference_config(config)
        tp = self.config.tensor_parallel.tp_size
        self.mesh_mgr = mesh_manager or MeshManager(tp_size=tp)
        self.mesh = self.mesh_mgr.mesh
        self.dtype = {"float16": jnp.float16, "fp16": jnp.float16,
                      "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
                      "float32": jnp.float32, "fp32": jnp.float32,
                      "int8": jnp.bfloat16}[str(self.config.dtype)]

        if model_parameters is None:
            if example_batch is None or model is None:
                raise ValueError("need model + model_parameters (or example_batch "
                                 "to init fresh weights)")
            model_parameters = model.init(jax.random.PRNGKey(0), example_batch)["params"]

        # dtype conversion + TP sharding of weights (reference: engine.py:450 dtype
        # convert + module_inject TP slicing — here one device_put with specs)
        tp_specs = build_tp_specs(model_parameters, sharding_rules)
        self._shardings = jax.tree.map(
            lambda spec: jax.sharding.NamedSharding(self.mesh, spec if spec is not None
                                                    else P()),
            tp_specs, is_leaf=lambda x: isinstance(x, P) or x is None)
        self.params = jax.tree.map(
            lambda p, s: jax.device_put(jnp.asarray(p, self.dtype), s),
            model_parameters, self._shardings)

        if apply_fn is not None:
            self._apply = apply_fn
        else:
            self._apply = lambda params, batch: model.apply({"params": params}, batch)
        self._fwd = jax.jit(self._apply)
        log_dist(f"InferenceEngine: dtype={self.config.dtype} tp={tp}", ranks=[0])

    def load_checkpoint(self, path: str):
        """Load a name-keyed npz (save_16bit_model / model_states.npz output)
        and reshard every tensor onto THIS engine's TP mesh — the role of the
        reference's TP-degree-resharding checkpoint loader
        (runtime/state_dict_factory.py:20,214 merge/split of mp_rank shards).
        Checkpoints are whole-tensor name-keyed, so any source topology loads
        onto any tp_size; the device_put splits along the rule-declared axes.
        """
        from ..runtime import checkpointing as ckpt_lib
        self.params = ckpt_lib.load_tree(path, self.params, self._shardings)
        log_dist(f"InferenceEngine: loaded + TP-resharded {path}", ranks=[0])
        return self

    def forward(self, batch):
        return self._fwd(self.params, batch)

    __call__ = forward

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k=None, rng=None,
                 **kwargs):
        """Autoregressive generation with KV cache (reference:
        engine.generate guard + fused decode kernels, engine.py:537)."""
        from ..models.transformer import Transformer
        if isinstance(self.module, Transformer):
            from ..models.generation import generate as _gen
            return _gen(self.module.cfg, self.params,
                        jnp.asarray(input_ids), max_new_tokens,
                        temperature, rng, top_k)
        if hasattr(self.module, "generate"):
            # forward the engine-level settings, but only those the module's
            # own generate signature accepts (or **kwargs swallows)
            import inspect
            named = {"max_new_tokens": max_new_tokens, "temperature": temperature,
                     "top_k": top_k, "rng": rng}
            try:
                sig = inspect.signature(self.module.generate)
                has_var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                                 for p in sig.parameters.values())
                if not has_var_kw:
                    named = {k: v for k, v in named.items()
                             if k in sig.parameters}
            except (TypeError, ValueError):
                pass
            return self.module.generate(self.params, input_ids,
                                        **named, **kwargs)
        raise NotImplementedError(
            "generate() requires a deepspeed_tpu.models.Transformer or a "
            "model exposing its own generate method")
