"""InferenceEngine — config-driven inference wrapper.

Capability parity with the reference's ``deepspeed/inference/engine.py``
(InferenceEngine: TP group creation, dtype conversion, kernel injection,
cuda-graph capture, generate). TPU-native mapping:

  TP process group            -> "model" mesh axis + param sharding rules
  kernel injection            -> jit (XLA fuses what ds fuses by hand); Pallas
                                 decode attention plugs in via models/ layers
  CUDA-graph capture/replay   -> jit compilation cache (always on)
  KV-cache workspace          -> scan-carried cache pytree (models/generation)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import MeshManager
from ..utils.logging import log_dist
from ..utils.partitioning import build_tp_specs
from .config import DeepSpeedInferenceConfig, load_inference_config


def quantize_weights_int8(params):
    """Weight-only int8: per-output-channel symmetric quantization of the
    matmul kernels (attention / MLP / experts / lm_head).  Embeddings,
    layernorms, biases and the MoE router stay high precision.  Each
    quantized leaf ``kernel`` gains a sibling ``kernel_scale`` such that
    ``kernel.astype(f32) * kernel_scale`` reconstructs the weight within
    scale/2 elementwise (the int8 error bound) — the capability slot of the
    reference's int8 inference kernels (csrc/transformer/inference
    ds_*_int8, pt_binding.cpp:1703-1779)."""

    def walk(node, path=()):
        if not isinstance(node, dict):
            return node
        out = {}
        for key, val in node.items():
            if (key == "kernel" and hasattr(val, "ndim") and val.ndim >= 2
                    and "gate" not in path):
                w = jnp.asarray(val, jnp.float32)
                scale = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0
                scale = jnp.where(scale == 0.0, 1.0, scale)
                out["kernel"] = jnp.clip(jnp.round(w / scale),
                                         -127, 127).astype(jnp.int8)
                out["kernel_scale"] = scale
            elif isinstance(val, dict):
                out[key] = walk(val, path + (key,))
            else:
                out[key] = val
        return out

    return walk(params)


class InferenceEngine:
    def __init__(self,
                 model=None,
                 config=None,
                 model_parameters=None,
                 apply_fn: Optional[Callable] = None,
                 sharding_rules: Optional[Dict[str, P]] = None,
                 example_batch=None,
                 mesh_manager: Optional[MeshManager] = None,
                 **kwargs):
        self.module = model
        self.config: DeepSpeedInferenceConfig = load_inference_config(config)
        tp = self.config.tensor_parallel.tp_size
        self.mesh_mgr = mesh_manager or MeshManager(tp_size=tp)
        self.mesh = self.mesh_mgr.mesh
        self.quantized = str(self.config.dtype) == "int8"
        # int8 = weight-only quantization; activations compute in bf16
        self.dtype = {"float16": jnp.float16, "fp16": jnp.float16,
                      "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
                      "float32": jnp.float32, "fp32": jnp.float32,
                      "int8": jnp.bfloat16}[str(self.config.dtype)]

        if model_parameters is None:
            if example_batch is None or model is None:
                raise ValueError("need model + model_parameters (or example_batch "
                                 "to init fresh weights)")
            model_parameters = model.init(jax.random.PRNGKey(0), example_batch)["params"]

        # dtype conversion + TP sharding of weights (reference: engine.py:450 dtype
        # convert + module_inject TP slicing — here one device_put with specs).
        # the quantized path builds its own shardings over the restacked
        # int8 tree inside _quantize_and_place.
        if self.quantized:
            from ..models.transformer import Transformer
            if not isinstance(model, Transformer) or apply_fn is not None:
                raise ValueError(
                    "dtype='int8' is weight-only quantization through the "
                    "deepspeed_tpu.models.Transformer decode path; an "
                    "arbitrary module/apply_fn computes through its own "
                    "flax Dense layers which the int8 kernels cannot "
                    "intercept — build the model via models.build_model, "
                    "or use dtype='bf16'")
            import numpy as _np
            self._raw_like = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(_np.shape(x), _np.float32),
                model_parameters)
            self._sharding_rules = sharding_rules
            self._quantize_and_place(model_parameters)
            cfg = model.cfg
            from ..models.generation import (forward_with_cache, init_cache,
                                             padded_cache_len)

            def int8_apply(params, batch):
                ids = batch["input_ids"] if isinstance(batch, dict) else batch
                B, T = ids.shape
                cache = init_cache(cfg, B, padded_cache_len(T))
                logits, _ = forward_with_cache(cfg, params, ids, cache)
                return logits

            self._apply = int8_apply
        else:
            tp_specs = build_tp_specs(model_parameters, sharding_rules)
            self._shardings = jax.tree.map(
                lambda spec: jax.sharding.NamedSharding(
                    self.mesh, spec if spec is not None else P()),
                tp_specs, is_leaf=lambda x: isinstance(x, P) or x is None)
            self.params = jax.tree.map(
                lambda p, s: jax.device_put(jnp.asarray(p, self.dtype), s),
                model_parameters, self._shardings)
            if apply_fn is not None:
                self._apply = apply_fn
            else:
                self._apply = lambda params, batch, *a, **kw: model.apply(
                    {"params": params}, batch, *a, **kw)
        self._fwd = jax.jit(self._apply)
        log_dist(f"InferenceEngine: dtype={self.config.dtype} tp={tp}"
                 + (" (int8 weight-only)" if self.quantized else ""), ranks=[0])

    def _quantize_and_place(self, model_parameters) -> None:
        """Quantize f32 host params into the int8 weight-only layout and
        place on the TP mesh: int8 kernels keep their TP spec, the tiny
        per-channel scales replicate.  The restack to scan layout happens
        FIRST so self._shardings always matches self.params structurally."""
        from ..models.generation import ensure_scan_layout
        stacked = ensure_scan_layout(model_parameters,
                                     self.module.cfg.num_layers)
        tp_specs = build_tp_specs(stacked, self._sharding_rules)
        base = jax.tree.map(
            lambda spec: jax.sharding.NamedSharding(
                self.mesh, spec if spec is not None else P()),
            tp_specs, is_leaf=lambda x: isinstance(x, P) or x is None)
        qparams = quantize_weights_int8(stacked)
        rep = jax.sharding.NamedSharding(self.mesh, P())

        def _shard_like(path, leaf):
            keys = tuple(getattr(k, "key", k) for k in path)
            node = base
            for key in keys:
                if not (isinstance(node, dict) and key in node):
                    return rep                       # kernel_scale etc.
                node = node[key]
            return node if isinstance(node, jax.sharding.Sharding) else rep

        flat, treedef = jax.tree_util.tree_flatten_with_path(qparams)
        self._shardings = jax.tree_util.tree_unflatten(
            treedef, [_shard_like(p, l) for p, l in flat])

        def _place(path, p, s):
            key = getattr(path[-1], "key", "")
            if hasattr(p, "dtype") and p.dtype == jnp.int8:
                arr = p
            elif key == "kernel_scale":
                arr = jnp.asarray(p, jnp.float32)     # dequant precision
            else:
                arr = jnp.asarray(p, self.dtype)
            return jax.device_put(arr, s)

        self.params = jax.tree_util.tree_map_with_path(
            _place, qparams, self._shardings)

    def load_checkpoint(self, path: str):
        """Load a name-keyed npz (save_16bit_model / model_states.npz output)
        and reshard every tensor onto THIS engine's TP mesh — the role of the
        reference's TP-degree-resharding checkpoint loader
        (runtime/state_dict_factory.py:20,214 merge/split of mp_rank shards).
        Checkpoints are whole-tensor name-keyed, so any source topology loads
        onto any tp_size; the device_put splits along the rule-declared axes.
        """
        from ..runtime import checkpointing as ckpt_lib
        if self.quantized:
            # checkpoints hold full-precision kernels: load to host f32,
            # then re-quantize into the int8 layout
            raw = ckpt_lib.load_tree(path, self._raw_like)
            self._quantize_and_place(raw)
        else:
            self.params = ckpt_lib.load_tree(path, self.params, self._shardings)
        log_dist(f"InferenceEngine: loaded + TP-resharded {path}", ranks=[0])
        return self

    def forward(self, batch, *args, **kwargs):
        # extra positional/keyword inputs pass through to the module (e.g.
        # a diffusion UNet's (latents, timesteps, context) signature)
        return self._fwd(self.params, batch, *args, **kwargs)

    __call__ = forward

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k=None, rng=None,
                 top_p=None, repetition_penalty=None, attention_mask=None,
                 kv_cache_dtype=None, **kwargs):
        """Autoregressive generation with KV cache (reference:
        engine.generate guard + fused decode kernels, engine.py:537).
        top_p / repetition_penalty / left-padded ragged batches
        (attention_mask) follow HF generate semantics."""
        from ..models.transformer import Transformer
        if isinstance(self.module, Transformer):
            # left-pad validation + all-ones-mask normalization live in
            # generate() itself (the shared entry point) — no duplicate here
            from ..models.generation import generate as _gen
            return _gen(self.module.cfg, self.params,
                        jnp.asarray(input_ids), max_new_tokens,
                        temperature, rng, top_k, top_p, repetition_penalty,
                        attention_mask, kv_cache_dtype)
        if hasattr(self.module, "generate"):
            # forward the engine-level settings, but only those the module's
            # own generate signature accepts (or **kwargs swallows)
            import inspect
            named = {"max_new_tokens": max_new_tokens, "temperature": temperature,
                     "top_k": top_k, "rng": rng, "top_p": top_p,
                     "repetition_penalty": repetition_penalty,
                     "attention_mask": attention_mask,
                     "kv_cache_dtype": kv_cache_dtype}
            try:
                sig = inspect.signature(self.module.generate)
                has_var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                                 for p in sig.parameters.values())
                if not has_var_kw:
                    named = {k: v for k, v in named.items()
                             if k in sig.parameters}
            except (TypeError, ValueError):
                pass
            return self.module.generate(self.params, input_ids,
                                        **named, **kwargs)
        raise NotImplementedError(
            "generate() requires a deepspeed_tpu.models.Transformer or a "
            "model exposing its own generate method")

    def serve(self, serving=None, heartbeat=None, interpret=False):
        """Continuous-batching serving loop over THIS engine's weights
        (round 8): a ``serving.ServingEngine`` with a paged KV block
        pool, FIFO admission control, prefix-cache reuse, and one
        fixed-shape compiled decode step — see docs/SERVING.md.

        ``serving`` overrides the config's ``serving`` section (dict or
        ServingConfig). When the config arms ``watchdog.serve_timeout``,
        the loop is supervised by the PR-6 stall watchdog (rc 117 on a
        wedged iteration). int8 weight-only engines serve unchanged (the
        dequant rides the paged forward's matmuls).

        With ``serving.fleet.replicas > 1`` (round 11) this returns a
        STARTED :class:`~deepspeed_tpu.serving.fleet.ServingFleet`
        instead: N replica loops behind one shared admission queue,
        supervised through the heartbeat channel (replica death ->
        requeue with exactly-once emission; docs/SERVING.md §Fleet). The
        fleet supervises its replicas itself — the in-process stall
        watchdog stays off (its rc-117 exit would take the whole fleet).
        Use it as a context manager, or call ``close()``, so the loop
        exit stamps EXIT terminal heartbeats."""
        from ..models.transformer import Transformer
        if not isinstance(self.module, Transformer):
            raise NotImplementedError(
                "serve() requires a deepspeed_tpu.models.Transformer "
                "(the paged runner mirrors its decode layer math)")
        from ..config.config import ServingConfig
        scfg = serving if serving is not None else self.config.serving
        if isinstance(scfg, dict):
            scfg = ServingConfig(**scfg)
        if (scfg.fleet.prefill_replicas > 0) != \
                (scfg.fleet.decode_replicas > 0):
            # one-sided disagg must fail HERE, not silently fall through
            # to single-engine serving (ServingFleet's own guard would
            # never run)
            raise ValueError(
                "serving.fleet: prefill_replicas and decode_replicas "
                "must both be > 0 for disaggregated serving (got "
                f"{scfg.fleet.prefill_replicas}/"
                f"{scfg.fleet.decode_replicas})")
        disagg = (scfg.fleet.prefill_replicas > 0
                  and scfg.fleet.decode_replicas > 0)
        # autoscale.enabled forces the fleet even at replicas=1 — a
        # floor-1 autoscaling fleet IS the replicas=1 case, and the
        # single-engine path has no supervisor to grow it
        if (scfg.fleet.replicas > 1 or disagg
                or scfg.fleet.autoscale.enabled
                or str(scfg.fleet.placement) == "process"):
            from ..serving.procfleet import make_fleet
            from ..utils.logging import logger
            hb_dir = scfg.fleet.heartbeat_dir
            if heartbeat is not None and hb_dir is None:
                # a caller-provided writer is rank-scoped; the fleet
                # writes PER-REPLICA records (and run-scopes its channel
                # with clear_channel, which would wipe a shared training
                # dir's rank files) — so adopt a `fleet/` subdir of the
                # writer's channel rather than silently dropping the
                # operator's monitoring location
                import os
                hb_dir = os.path.join(heartbeat.directory, "fleet")
                logger.warning(
                    "serve(): fleet mode replaces the provided heartbeat "
                    "writer with per-replica writers under %s — point "
                    "`dstpu health` there", hb_dir)
            if self.config.watchdog.serve_timeout > 0:
                logger.warning(
                    "serve(): watchdog.serve_timeout is not armed under "
                    "a fleet — its rc-117 exit would take every replica; "
                    "the FleetSupervisor (fleet.heartbeat_timeout) "
                    "supervises replicas instead")
            # placement-dispatching: "thread" builds the round-11
            # ServingFleet, "process" the round-18 ProcessFleet —
            # same serving surface either way
            fleet = make_fleet(self.module.cfg, self.params, serving=scfg,
                               heartbeat_dir=hb_dir, interpret=interpret)
            fleet.start()
            return fleet
        from ..serving.engine import ServingEngine
        eng = ServingEngine(self.module.cfg, self.params, serving=scfg,
                            heartbeat=heartbeat, interpret=interpret)
        if self.config.watchdog.serve_timeout > 0:
            eng.arm_watchdog(self.config.watchdog.serve_timeout)
        return eng
