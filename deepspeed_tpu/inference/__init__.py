"""deepspeed_tpu.inference — config-driven inference engine.

reference: deepspeed/inference/ (InferenceEngine + config), entered through
deepspeed.init_inference (deepspeed_tpu.init_inference here).
"""

from .config import DeepSpeedInferenceConfig, load_inference_config
from .engine import InferenceEngine

__all__ = ["InferenceEngine", "DeepSpeedInferenceConfig",
           "load_inference_config"]
