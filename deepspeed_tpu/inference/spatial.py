"""Spatial (image-model) inference support — attention over feature maps.

Capability slot of the reference's spatial inference path
(deepspeed/module_inject for diffusion UNets: replaces the spatial
transformer's attention with fused kernels and optimized layouts,
model_implementations/diffusers/*). TPU shape: the hot op — self-attention
over flattened H*W token grids — runs through ops.attention (Pallas flash on
TPU; H*W rarely divides the tile sizes, and the kernel's block snapping
keeps e.g. 64x64=4096-token maps on the fast path). The InferenceEngine
already hosts arbitrary flax modules, so "spatial inference" = these
building blocks + batch sharding, not module surgery.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import attention


def spatial_attention(x: jnp.ndarray,
                      num_heads: int,
                      *,
                      impl: str = "auto") -> jnp.ndarray:
    """Identity-projected self-attention over a feature map [B, H, W, C]
    (the geometry transform; real blocks use SpatialSelfAttention below)."""
    B, H, W, C = x.shape
    hd = C // num_heads
    t = x.reshape(B, H * W, num_heads, hd).transpose(0, 2, 1, 3)
    out = attention(t, t, t, causal=False, impl=impl)
    return out.transpose(0, 2, 1, 3).reshape(B, H, W, C)


class SpatialSelfAttention(nn.Module):
    """Diffusion-UNet-style attention block: GroupNorm -> qkv -> attention
    over the H*W token grid -> proj, residual (the structure the reference's
    diffusers injection replaces with its fused kernels)."""
    num_heads: int
    num_groups: int = 32
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        B, H, W, C = x.shape
        hd = C // self.num_heads
        h = nn.GroupNorm(num_groups=min(self.num_groups, C),
                         dtype=self.dtype, param_dtype=jnp.float32,
                         name="norm")(x)
        qkv = nn.Dense(3 * C, dtype=self.dtype, param_dtype=jnp.float32,
                       name="qkv")(h.reshape(B, H * W, C))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        heads = lambda t: t.reshape(B, H * W, self.num_heads, hd
                                    ).transpose(0, 2, 1, 3)
        out = attention(heads(q), heads(k), heads(v), causal=False,
                        impl=self.attention_impl)
        out = out.transpose(0, 2, 1, 3).reshape(B, H * W, C)
        out = nn.Dense(C, dtype=self.dtype, param_dtype=jnp.float32,
                       name="proj")(out)
        return x + out.reshape(B, H, W, C)


# -- diffusers-grade UNet assembly (round-3 Missing #4) -----------------------
#
# The reference injects fused kernels into diffusers' UNet2DConditionModel
# (module_inject/replace_module.py:205 generic_injection +
# model_implementations/diffusers/*). The TPU shape is a native flax UNet
# with the same computational structure (resnet blocks with timestep
# injection, spatial transformers with self+cross attention and geglu FF,
# down/mid/up with skip concats) plus a name-mapped loader for
# diffusers-format state dicts. The diffusers package itself is not in this
# image (and there is no network egress), so parity is established
# per-component against torch mirrors of the documented diffusers ops
# (tests/test_inference.py) rather than against a downloaded checkpoint —
# the loader speaks the diffusers key naming either way.


def _groups(channels: int, want: int = 32) -> int:
    """Largest group count <= want that divides the channel count (toy
    widths aren't the multiples of 32 diffusers assumes)."""
    g = max(min(want, channels), 1)
    while channels % g:
        g -= 1
    return g


def timestep_embedding(t: jnp.ndarray, dim: int,
                       max_period: float = 10000.0) -> jnp.ndarray:
    """Sinusoidal timestep embedding [B] -> [B, dim] (diffusers
    get_timestep_embedding, flip_sin_to_cos=True arrangement)."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(max_period) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


class TimestepMLP(nn.Module):
    """time_embedding: Linear -> SiLU -> Linear (diffusers TimestepEmbedding)."""
    dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, emb):
        h = nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="linear_1")(emb)
        h = nn.silu(h)
        return nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                        name="linear_2")(h)


class ResnetBlock(nn.Module):
    """GroupNorm -> SiLU -> Conv3x3, + time-emb projection, GroupNorm ->
    SiLU -> Conv3x3, residual (1x1 shortcut on channel change) — diffusers
    ResnetBlock2D."""
    out_channels: int
    num_groups: int = 32
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, temb):
        C = x.shape[-1]
        h = nn.GroupNorm(num_groups=_groups(C, self.num_groups), epsilon=1e-5,
                         param_dtype=jnp.float32, name="norm1")(x)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    param_dtype=jnp.float32, name="conv1")(h)
        t = nn.Dense(self.out_channels, dtype=self.dtype,
                     param_dtype=jnp.float32,
                     name="time_emb_proj")(nn.silu(temb))
        h = h + t[:, None, None, :]
        h = nn.GroupNorm(num_groups=_groups(self.out_channels, self.num_groups),
                         epsilon=1e-5, param_dtype=jnp.float32, name="norm2")(h)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    param_dtype=jnp.float32, name="conv2")(h)
        if C != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        param_dtype=jnp.float32, name="conv_shortcut")(x)
        return x + h


class CrossAttention(nn.Module):
    """Multi-head attention with an optional cross context (diffusers
    Attention: to_q/to_k/to_v unbiased, to_out biased)."""
    num_heads: int
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x, context=None):
        B, T, C = x.shape
        ctx = x if context is None else context
        hd = C // self.num_heads
        dense = lambda n, feats, bias: nn.Dense(
            feats, use_bias=bias, dtype=self.dtype, param_dtype=jnp.float32,
            name=n)
        q = dense("to_q", C, False)(x)
        k = dense("to_k", C, False)(ctx)
        v = dense("to_v", C, False)(ctx)
        heads = lambda t: t.reshape(B, t.shape[1], self.num_heads, hd
                                    ).transpose(0, 2, 1, 3)
        out = attention(heads(q), heads(k), heads(v), causal=False,
                        impl=self.attention_impl)
        out = out.transpose(0, 2, 1, 3).reshape(B, T, C)
        return dense("to_out", C, True)(out)


class GEGLU(nn.Module):
    """geglu feed-forward gate (diffusers GEGLU: one Dense to 2*inner)."""
    inner: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(2 * self.inner, dtype=self.dtype,
                     param_dtype=jnp.float32, name="proj")(x)
        a, g = jnp.split(h, 2, axis=-1)
        # exact (erf) gelu: torch/diffusers F.gelu default
        return a * nn.gelu(g, approximate=False)


class TransformerBlock(nn.Module):
    """LayerNorm -> self-attn -> LayerNorm -> cross-attn -> LayerNorm ->
    geglu FF, all residual (diffusers BasicTransformerBlock)."""
    num_heads: int
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x, context):
        # torch/diffusers LayerNorm eps
        ln = lambda n: nn.LayerNorm(epsilon=1e-5, param_dtype=jnp.float32,
                                    name=n)
        x = x + CrossAttention(self.num_heads, self.dtype,
                               self.attention_impl, name="attn1")(ln("norm1")(x))
        x = x + CrossAttention(self.num_heads, self.dtype,
                               self.attention_impl,
                               name="attn2")(ln("norm2")(x), context)
        h = GEGLU(4 * x.shape[-1], self.dtype, name="ff_geglu")(
            ln("norm3")(x))
        x = x + nn.Dense(x.shape[-1], dtype=self.dtype,
                         param_dtype=jnp.float32, name="ff_out")(h)
        return x


class SpatialTransformer(nn.Module):
    """GroupNorm -> 1x1 proj_in -> transformer blocks over the H*W grid ->
    1x1 proj_out, residual (diffusers Transformer2DModel)."""
    num_heads: int
    depth: int = 1
    num_groups: int = 32
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x, context):
        B, H, W, C = x.shape
        res = x
        h = nn.GroupNorm(num_groups=_groups(C, self.num_groups), epsilon=1e-5,
                         param_dtype=jnp.float32, name="norm")(x)
        h = nn.Conv(C, (1, 1), dtype=self.dtype, param_dtype=jnp.float32,
                    name="proj_in")(h)
        h = h.reshape(B, H * W, C)
        for i in range(self.depth):
            h = TransformerBlock(self.num_heads, self.dtype,
                                 self.attention_impl,
                                 name=f"blocks_{i}")(h, context)
        h = h.reshape(B, H, W, C)
        h = nn.Conv(C, (1, 1), dtype=self.dtype, param_dtype=jnp.float32,
                    name="proj_out")(h)
        return res + h


class UNet2DCondition(nn.Module):
    """Conditional diffusion UNet: conv_in -> down (resnets + transformers +
    downsample) -> mid -> up (skip-concat resnets + transformers +
    upsample) -> norm/silu/conv_out. Structure of diffusers
    UNet2DConditionModel at configurable width/depth."""
    block_channels: tuple = (32, 64)
    layers_per_block: int = 1
    num_heads: int = 4
    cross_attention: bool = True
    out_channels: int = 4
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x, timesteps, context=None):
        ch0 = self.block_channels[0]
        temb = timestep_embedding(timesteps, ch0)
        temb = TimestepMLP(4 * ch0, self.dtype, name="time_embedding")(temb)

        h = nn.Conv(ch0, (3, 3), padding=1, dtype=self.dtype,
                    param_dtype=jnp.float32, name="conv_in")(x)
        skips = [h]
        # down path
        for bi, ch in enumerate(self.block_channels):
            for li in range(self.layers_per_block):
                h = ResnetBlock(ch, dtype=self.dtype,
                                name=f"down_{bi}_res_{li}")(h, temb)
                if self.cross_attention:
                    h = SpatialTransformer(
                        self.num_heads, dtype=self.dtype,
                        attention_impl=self.attention_impl,
                        name=f"down_{bi}_attn_{li}")(h, context)
                skips.append(h)
            if bi < len(self.block_channels) - 1:
                h = nn.Conv(ch, (3, 3), strides=2, padding=1,
                            dtype=self.dtype, param_dtype=jnp.float32,
                            name=f"down_{bi}_downsample")(h)
                skips.append(h)
        # mid
        h = ResnetBlock(self.block_channels[-1], dtype=self.dtype,
                        name="mid_res_0")(h, temb)
        if self.cross_attention:
            h = SpatialTransformer(self.num_heads, dtype=self.dtype,
                                   attention_impl=self.attention_impl,
                                   name="mid_attn")(h, context)
        h = ResnetBlock(self.block_channels[-1], dtype=self.dtype,
                        name="mid_res_1")(h, temb)
        # up path (skip concats, reverse order)
        for bi, ch in reversed(list(enumerate(self.block_channels))):
            for li in range(self.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = ResnetBlock(ch, dtype=self.dtype,
                                name=f"up_{bi}_res_{li}")(h, temb)
                if self.cross_attention:
                    h = SpatialTransformer(
                        self.num_heads, dtype=self.dtype,
                        attention_impl=self.attention_impl,
                        name=f"up_{bi}_attn_{li}")(h, context)
            if bi > 0:
                B, H, W, C = h.shape
                h = jax.image.resize(h, (B, 2 * H, 2 * W, C), "nearest")
                h = nn.Conv(C, (3, 3), padding=1, dtype=self.dtype,
                            param_dtype=jnp.float32,
                            name=f"up_{bi}_upsample")(h)
        h = nn.GroupNorm(num_groups=_groups(h.shape[-1]), epsilon=1e-5,
                         param_dtype=jnp.float32, name="norm_out")(h)
        h = nn.silu(h)
        return nn.Conv(self.out_channels, (3, 3), padding=1,
                       dtype=self.dtype, param_dtype=jnp.float32,
                       name="conv_out")(h)


def load_torch_conv(w, b=None):
    """torch Conv2d weight [O, I, kh, kw] -> flax Conv kernel [kh, kw, I, O]."""
    import numpy as np
    out = {"kernel": jnp.asarray(np.transpose(np.asarray(w), (2, 3, 1, 0)))}
    if b is not None:
        out["bias"] = jnp.asarray(np.asarray(b))
    return out


def load_torch_linear(w, b=None):
    """torch Linear weight [O, I] -> flax Dense kernel [I, O]."""
    import numpy as np
    out = {"kernel": jnp.asarray(np.asarray(w).T)}
    if b is not None:
        out["bias"] = jnp.asarray(np.asarray(b))
    return out
