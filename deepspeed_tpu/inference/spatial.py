"""Spatial (image-model) inference support — attention over feature maps.

Capability slot of the reference's spatial inference path
(deepspeed/module_inject for diffusion UNets: replaces the spatial
transformer's attention with fused kernels and optimized layouts,
model_implementations/diffusers/*). TPU shape: the hot op — self-attention
over flattened H*W token grids — runs through ops.attention (Pallas flash on
TPU; H*W rarely divides the tile sizes, and the kernel's block snapping
keeps e.g. 64x64=4096-token maps on the fast path). The InferenceEngine
already hosts arbitrary flax modules, so "spatial inference" = these
building blocks + batch sharding, not module surgery.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import attention


def spatial_attention(x: jnp.ndarray,
                      num_heads: int,
                      *,
                      impl: str = "auto") -> jnp.ndarray:
    """Identity-projected self-attention over a feature map [B, H, W, C]
    (the geometry transform; real blocks use SpatialSelfAttention below)."""
    B, H, W, C = x.shape
    hd = C // num_heads
    t = x.reshape(B, H * W, num_heads, hd).transpose(0, 2, 1, 3)
    out = attention(t, t, t, causal=False, impl=impl)
    return out.transpose(0, 2, 1, 3).reshape(B, H, W, C)


class SpatialSelfAttention(nn.Module):
    """Diffusion-UNet-style attention block: GroupNorm -> qkv -> attention
    over the H*W token grid -> proj, residual (the structure the reference's
    diffusers injection replaces with its fused kernels)."""
    num_heads: int
    num_groups: int = 32
    dtype: Any = jnp.float32
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        B, H, W, C = x.shape
        hd = C // self.num_heads
        h = nn.GroupNorm(num_groups=min(self.num_groups, C),
                         dtype=self.dtype, param_dtype=jnp.float32,
                         name="norm")(x)
        qkv = nn.Dense(3 * C, dtype=self.dtype, param_dtype=jnp.float32,
                       name="qkv")(h.reshape(B, H * W, C))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        heads = lambda t: t.reshape(B, H * W, self.num_heads, hd
                                    ).transpose(0, 2, 1, 3)
        out = attention(heads(q), heads(k), heads(v), causal=False,
                        impl=self.attention_impl)
        out = out.transpose(0, 2, 1, 3).reshape(B, H * W, C)
        out = nn.Dense(C, dtype=self.dtype, param_dtype=jnp.float32,
                       name="proj")(out)
        return x + out.reshape(B, H, W, C)
