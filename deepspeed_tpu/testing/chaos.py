"""Fault-injection harness: named failpoints for crash-safety tests.

The checkpoint stack is only provably restartable if a test can crash a
real save at EVERY stage — mid-array-write, after the data but before the
completion marker, between the rename and the ``latest`` update — and then
demonstrate that a fresh engine resumes from the newest intact tag. This
module provides the crash trigger: production code declares *failpoints*
(named, stable identifiers; see docs/RESILIENCE.md for the catalog) and
tests arm them to raise an ``IOError`` or kill the process at exactly that
point.

Design constraints:

- **Zero cost disarmed.** ``failpoint(name)`` with nothing armed is one
  dict lookup that misses. No env reads, no locks on the fast path.
- **Deterministic.** A failpoint fires on an exact hit index (``skip``
  hits pass through first) for an exact number of times — no randomness,
  so the crash-at-every-stage matrix is a plain parametrize.
- **Cross-process.** Subprocess tests (kill-mid-write, SIGTERM) arm
  failpoints in the child via the ``DSTPU_CHAOS`` env var, parsed once at
  first failpoint evaluation in that process.

Spec syntax (env var or ``arm()``)::

    DSTPU_CHAOS="ckpt.write:raise"            # raise IOError on 1st hit
    DSTPU_CHAOS="ckpt.write:kill"             # os._exit(13) on 1st hit
    DSTPU_CHAOS="ckpt.write:raise:skip=1"     # pass 1 hit, fail the 2nd
    DSTPU_CHAOS="ckpt.write:raise:times=2"    # fail the first 2 hits
    DSTPU_CHAOS="a:raise;b:kill:skip=3"       # several failpoints
    DSTPU_CHAOS="run.preempt:kill:code=114"   # kill with a chosen exit code
    DSTPU_CHAOS="run.hang:hang"               # block forever (wedged rank)
    DSTPU_CHAOS="ckpt.write:sleep:ms=300"     # delay, then continue
    DSTPU_CHAOS="run.preempt:sigterm"         # SIGTERM self (preemption)
    DSTPU_CHAOS="host.blackhole:raise:match=w2"  # keyed: only host w2
    DSTPU_CHAOS="sentinel.spike:flag:factor=1000"  # query-style injection
    DSTPU_CHAOS="run.slow:sleep:ms=300:times=0"   # every hit, forever
    DSTPU_CHAOS="run.slow:sleep:ms=300:every=3:times=0"  # every 3rd hit
    DSTPU_CHAOS="run.slow:sleep:ms=300:p=40:times=0"     # ~40% of hits

Run-supervision modes (round-4): ``hang`` blocks the calling thread
forever — the userspace approximation of a wedged collective, what the
stall watchdog and the supervisor's teardown exist to catch. ``sleep``
delays ``ms`` milliseconds and then continues — for overlap tests that
need an IO operation to still be in flight when something else happens.
``sigterm`` sends SIGTERM to the calling process (the installed
preemption handler fires, exactly like a real TPU preemption notice).
``kill`` takes ``code=N`` to emulate any exit-code contract.

Intermittent-slowness semantics (round-15, the straggler defense —
*degraded, not dead*): ``times=0`` means UNLIMITED fires (the default
stays 1), and two deterministic jitter filters shape WHICH eligible
traversals fire: ``every=N`` fires the first post-``skip`` traversal
and every Nth after it (periodic throttling — a host that hiccups on a
cadence), while ``p=P`` (percent, 0-100) fires P% of eligible
traversals on an evenly-spaced accumulator pattern (acc += P, fire and
subtract at 100) — probabilistic-LOOKING degradation with zero
randomness, so the straggler matrices stay exactly reproducible. The
``run.slow`` failpoint at the train-batch boundary and the keyed
``serve.replica_slow`` in the fleet worker loop combine these with
``sleep`` to make one rank/replica slow-but-alive.

Serving failpoints (round-8, the continuous-batching loop): on the
serving hot path production code declares ``serve.enqueue``
(Scheduler.submit — an exploding enqueue must surface to the submitting
caller, never wedge the loop) and ``serve.oom`` (BlockPool.alloc — an
injected allocation failure must leave the request QUEUED and the loop
serving, indistinguishable from a genuinely full pool).

Fleet failpoints (round-11, serving/fleet.py): ``serve.replica_kill``
and ``serve.replica_hang`` fire at the top of each replica worker
iteration, KEYED by the replica index (``match=1`` takes out replica 1
only) — ``raise`` mode is replica death (in-flight requests must
requeue with exactly-once emission), ``hang`` is the silence case the
FleetSupervisor detects through the heartbeat channel. ``serve.requeue``
fires inside the requeue itself: a crash THERE must orphan-and-retry
the request, never lose it. In-process fleets use ``raise``/``hang``;
``kill`` mode would exit the whole process and belongs to
process-per-replica deployments.

Disaggregated-serving failpoints (round-12, serving/disagg.py):
``serve.chunk`` fires per chunked-prefill chunk (serving/engine.py —
a crash mid-prefill must release the partial allocation and requeue the
request exactly-once, chunk progress carried); ``serve.handoff`` fires
inside ``BlockHandoff.push`` BEFORE the item is queued (a crash leaves
the blocks with the dying prefill role — never a half-queued item);
``serve.handoff_drop`` fires between a decode-side pop and the lane
install (a crash there is a decode death holding a popped item — its
blocks ride the quarantine, the request requeues through the
token-exact prompt+emitted path). The crash-at-every-failpoint matrix
lives in tests/test_disagg.py.

Query mode (round-7, the training-integrity sentinel): ``flag`` never
raises or kills — production code ASKS :func:`flag` whether the site is
armed and fired, and perturbs its own data when it is (a grad spike
scales the batch, an SDC fault flips a bit in one replica's weights).
The ``factor=N`` option carries the perturbation magnitude.

reference counterpart: DeepSpeed's tests monkeypatch torch.save /
simulate SIGTERM by hand per test; a named-failpoint registry is the
jax_graft-native equivalent of kernel-style fault injection (fail_make_
request) — one mechanism, every crash site.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Dict, List, Optional

#: exit code used by ``kill`` mode — distinct from Python's 1 and from
#: PREEMPTION_EXIT_CODE so tests can tell "chaos killed it" apart from
#: ordinary failures. Re-exported from the single-source contract module.
from ..exit_codes import KILL_EXIT_CODE  # noqa: E402

_lock = threading.Lock()
_armed: Dict[str, "_FailPoint"] = {}
_env_loaded = False
_history: List[str] = []        # fired failpoint names, in order

#: Catalog of every failpoint/flag name instrumented in the package,
#: name -> where it fires. graftlint rule TPU020 checks that every
#: ``failpoint("...")`` / ``chaos.flag("...")`` call site in source uses a
#: name listed here AND documented in docs/RESILIENCE.md's failpoint
#: table, so the catalog, the docs, and the instrumentation can never
#: drift apart (the failpoint analogue of
#: ``test_facade_catalog_covers_comm_module``). Arming an uncataloged
#: name from a test still works — the catalog constrains *source*
#: instrumentation sites, not test scripts.
FAILPOINTS: Dict[str, str] = {
    "pipe.stage_kill": "MPMD stage worker, top of the stage step loop",
    "pipe.xfer": "MPMD channel, inter-stage frame read/write",
    "ckpt.write": "checkpoint shard write",
    "ckpt.digest": "checkpoint shard digest computation",
    "ckpt.marker": "checkpoint commit-marker write",
    "ckpt.rename": "checkpoint atomic rename into place",
    "ckpt.latest": "LATEST pointer update",
    "ckpt.meta": "checkpoint metadata write",
    "run.kill": "training step loop, hard kill",
    "run.preempt": "training step loop, simulated preemption",
    "run.hang": "training step loop, infinite hang",
    "run.slow": "training step loop, injected per-step delay",
    "run.compile_hang": "first-step compilation, infinite hang",
    "sentinel.spike": "flag: sentinel sees a fake loss spike",
    "sentinel.sdc": "flag: sentinel sees a fake checksum mismatch",
    "hb.write": "heartbeat file write",
    "host.blackhole": "launcher, host stops responding",
    "launch.ssh": "launcher, ssh/session establishment",
    "serve.chunk": "serving engine, per-chunk prefill",
    "serve.handoff": "disagg prefill->decode block handoff push",
    "serve.handoff_drop": "disagg handoff entry expiry/drop",
    "serve.enqueue": "serving scheduler/fleet request enqueue",
    "serve.replica_hang": "fleet replica worker, infinite hang",
    "serve.replica_kill": "fleet replica worker, hard kill",
    "serve.replica_slow": "fleet replica worker, injected delay",
    "serve.requeue": "fleet, in-flight requeue after replica death",
    "serve.scale_up": "fleet autoscaler, between slot append and warmed "
                      "spawn (keyed by new replica index)",
    "serve.preempt": "fleet preemption, between lane eviction and the "
                     "victim's requeue",
    "serve.oom": "KV block pool exhaustion",
    "net.connect": "fabric endpoint, per dial attempt (initial + redial)",
    "net.send": "fabric endpoint send, surfaced to the caller unretried",
    "net.recv": "fabric endpoint recv, frame delivery to the caller",
    "net.corrupt": "flag: fabric frame codec flips a payload bit on-wire",
    "net.partition": "fabric link I/O, mid-stream loss driving the "
                     "redial ladder",
    "net.slow": "fabric endpoint send, injected link latency",
}


class ChaosError(IOError):
    """The injected fault. Subclasses IOError so code under test exercises
    its real transient-IO handling (retry/backoff, quarantine) — a chaos
    fault must be indistinguishable from a disk hiccup."""

    def __init__(self, name: str):
        super().__init__(f"chaos failpoint '{name}' fired")
        self.failpoint = name


_MODES = ("raise", "kill", "hang", "sleep", "sigterm", "flag")


class _FailPoint:
    __slots__ = ("name", "mode", "skip", "times", "hits", "fired", "code",
                 "ms", "match", "factor", "every", "p", "acc")

    def __init__(self, name: str, mode: str, skip: int = 0, times: int = 1,
                 code: Optional[int] = None, ms: int = 0,
                 match: Optional[str] = None, factor: int = 1,
                 every: int = 0, p: int = 0):
        if mode not in _MODES:
            raise ValueError(f"chaos mode must be one of {_MODES}, "
                             f"got {mode!r}")
        if not 0 <= p <= 100:
            raise ValueError(f"chaos p= must be a percentage 0-100, got {p}")
        self.name = name
        self.mode = mode
        self.skip = skip
        self.times = times  # fire budget; 0 = unlimited (round 15)
        self.code = KILL_EXIT_CODE if code is None else code
        self.ms = ms        # sleep mode: delay in milliseconds
        self.match = match  # keyed failpoints: fire only when key == match
        self.factor = factor  # flag mode: perturbation magnitude
        self.every = every  # jitter: fire 1st eligible hit + every Nth after
        self.p = p          # jitter: fire P% of eligible hits (accumulator)
        self.acc = 0        # the p= accumulator — deterministic, no PRNG
        self.hits = 0       # total traversals of this failpoint
        self.fired = 0      # times it actually failed

    def advance(self) -> bool:
        """One traversal's fire decision (caller holds the module lock):
        skip first, then the fire budget (``times=0`` = unlimited), then
        the deterministic jitter filters — ``every=N`` passes the first
        post-skip traversal and every Nth after it; ``p=P`` passes P% of
        what remains via an evenly-spaced accumulator."""
        self.hits += 1
        if self.hits <= self.skip:
            return False
        if 0 < self.times <= self.fired:
            return False
        if self.every > 1 and (self.hits - self.skip - 1) % self.every != 0:
            return False
        if self.p > 0:
            self.acc += self.p
            if self.acc < 100:
                return False
            self.acc -= 100
        self.fired += 1
        _history.append(self.name)
        return True


def parse_spec(spec: str) -> Dict[str, _FailPoint]:
    """Parse a ``DSTPU_CHAOS`` spec string (see module docstring)."""
    out: Dict[str, _FailPoint] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(
                f"bad chaos spec {part!r}: expected name:mode[:k=v...]")
        name, mode = fields[0], fields[1]
        kwargs = {}
        for f in fields[2:]:
            k, _, v = f.partition("=")
            if k == "match":            # keyed failpoints take a STRING
                kwargs[k] = v           # (e.g. match=worker-2 on
                continue                # host.blackhole)
            if k not in ("skip", "times", "code", "ms", "factor", "every",
                         "p"):
                raise ValueError(f"bad chaos spec option {f!r} in {part!r}")
            kwargs[k] = int(v)
        out[name] = _FailPoint(name, mode, **kwargs)
    return out


def _load_env_once() -> None:
    global _env_loaded
    # registry lock: brackets dict ops only, never blocking work — a
    # signal handler passing through a failpoint cannot wedge on it
    with _lock:  # graftlint: disable=TPU019
        if _env_loaded:
            return
        _env_loaded = True
        spec = os.environ.get("DSTPU_CHAOS", "")
        if spec:
            _armed.update(parse_spec(spec))


def arm(name: str, mode: str = "raise", skip: int = 0, times: int = 1,
        code: Optional[int] = None, ms: int = 0,
        match: Optional[str] = None, factor: int = 1,
        every: int = 0, p: int = 0) -> None:
    """Programmatically arm a failpoint (in-process tests). ``match``
    restricts a KEYED failpoint to one key — e.g. ``host.blackhole``
    with ``match="worker-2"`` only fires for that host's dispatch.
    ``times=0`` = unlimited fires; ``every=``/``p=`` are the
    deterministic jitter filters (module docstring)."""
    with _lock:
        _armed[name] = _FailPoint(name, mode, skip=skip, times=times,
                                  code=code, ms=ms, match=match,
                                  factor=factor, every=every, p=p)


def disarm(name: Optional[str] = None) -> None:
    """Disarm one failpoint, or all of them (``name=None``). The fired
    history survives so a test can still assert WHAT fired; use
    :func:`reset_for_tests` for a full wipe."""
    global _env_loaded
    with _lock:
        if name is None:
            _armed.clear()
            _env_loaded = True      # env consumed; do not re-read
        else:
            _armed.pop(name, None)


def reset_for_tests() -> None:
    """Full reset incl. re-reading DSTPU_CHAOS on next use — conftest's
    per-test hook, so a test that sets the env var (subprocess specs) and
    one that arms programmatically can't interfere."""
    global _env_loaded
    with _lock:
        _armed.clear()
        _history.clear()
        _env_loaded = False


def fired(name: Optional[str] = None) -> List[str]:
    """Names of failpoints that actually fired (optionally filtered)."""
    with _lock:
        return [h for h in _history if name is None or h == name]


def armed() -> List[str]:
    _load_env_once()
    with _lock:
        return sorted(_armed)


def failpoint(name: str, key: Optional[str] = None) -> None:
    """Declare a failpoint. No-op unless a test armed ``name``.

    ``raise`` mode raises :class:`ChaosError` (an IOError). ``kill`` mode
    calls ``os._exit(code)`` (default ``KILL_EXIT_CODE``) — no atexit
    handlers, no flushes: the closest userspace approximation of the
    machine dying. ``hang`` blocks this thread forever (a wedged rank);
    ``sleep`` delays ``ms`` milliseconds then continues; ``sigterm``
    raises SIGTERM in this process (drives the preemption handler).

    ``key`` marks a KEYED site (the dispatching host, a rank id): a spec
    armed with ``match=K`` fires — and counts hits — only when
    ``key == K``, so one armed ``host.blackhole`` can take out a single
    host of a multi-host world.
    """
    if not _env_loaded:
        _load_env_once()
    if not _armed:
        return
    # registry lock: dict lookups and counter bumps only (the injected
    # hang/sleep happens AFTER release) — safe under a signal handler
    with _lock:  # graftlint: disable=TPU019
        fp = _armed.get(name)
        if fp is None:
            return
        if fp.match is not None and key != fp.match:
            return
        if not fp.advance():
            return
        mode, code, ms = fp.mode, fp.code, fp.ms
    if mode == "kill":
        os._exit(code)
    if mode == "hang":
        while True:             # cannot be woken — only killed from outside
            time.sleep(3600)
    if mode == "sleep":
        time.sleep(ms / 1000.0)
        return
    if mode == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if mode == "flag":
        return          # query sites use flag(); traversal alone is inert
    raise ChaosError(name)


def flag(name: str, key: Optional[str] = None) -> Optional[int]:
    """Query-style failpoint: the injection magnitude (``factor``) when an
    armed ``flag``-mode spec fires at this traversal, else ``None``.

    Unlike :func:`failpoint`, the site itself performs the perturbation —
    this only answers "should I, and how hard?". Hit/skip/times/match
    accounting is identical, so a spec like
    ``sentinel.spike:flag:skip=10:times=3:factor=1000`` scales exactly
    steps 11-13 and nothing else."""
    if not _env_loaded:
        _load_env_once()
    if not _armed:
        return None
    with _lock:
        fp = _armed.get(name)
        if fp is None or fp.mode != "flag":
            return None
        if fp.match is not None and key != fp.match:
            return None
        if not fp.advance():
            return None
        return fp.factor
