"""deepspeed_tpu.testing — fault-injection / chaos utilities.

Production code imports ``chaos.failpoint`` at checkpoint-critical sites;
with no failpoints armed every call is a dict lookup that misses — safe to
leave compiled into the hot save path.
"""

from . import chaos

__all__ = ["chaos"]
