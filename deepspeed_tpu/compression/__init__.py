"""deepspeed_tpu.compression — QAT, pruning, layer reduction.

reference: deepspeed/compression/ (compress.py + basic_layer.py + config.py).
"""

from .compress import (CompressionGroup, CompressionSpec, apply_compression,
                       apply_layer_reduction, export_int8, init_compression,
                       parse_compression_config)

__all__ = ["CompressionSpec", "CompressionGroup", "init_compression",
           "parse_compression_config", "apply_compression",
           "apply_layer_reduction", "export_int8"]
