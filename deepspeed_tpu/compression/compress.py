"""Compression training — QAT, magnitude pruning, layer reduction.

Capability parity with the reference's ``deepspeed/compression/``
(compress.py init_compression/redundancy_clean/student_initialization,
basic_layer.py:834-923 LinearLayer_Compress with quantize/prune forward
hooks, scheduler hook in runtime/engine.py:1395). TPU-native shape: torch
module surgery becomes a pure transform over the params pytree —
``apply_compression(params, spec, step)`` fake-quantizes / masks each
matched leaf inside the jitted train step, with the schedule gate
(step >= schedule_offset) as traced arithmetic. Straight-through gradients
come from the quantizer's custom VJP, and pruning masks are stop_gradient'd
so grads see d(w*mask)/dw = mask — the reference's autograd behavior.

Techniques (reference constants.py):
  weight_quantization    start_bits -> target_bits halving every
                         quantization_period steps, symmetric/asymmetric
  activation_quantization  consumed by the model via spec.activation_bits
  sparse_pruning         elementwise magnitude, keep dense_ratio
  row_pruning            L1 row norms, keep dense_ratio rows
  channel_pruning        L1 column norms
  head_pruning           L1 per attention-head blocks of the out-proj rows
  layer_reduction        student keeps teacher_layer of the scanned stack
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.partitioning import path_str

PyTree = Any


@dataclasses.dataclass(frozen=True)
class CompressionGroup:
    kind: str                       # weight_quantization | sparse_pruning | ...
    name: str
    modules: Tuple[str, ...]        # regexes on param paths
    schedule_offset: int = 0
    # quantization
    start_bits: int = 8
    target_bits: int = 8
    quantization_period: int = 0
    quantization_type: str = "symmetric"
    quantize_groups: int = 1
    # pruning
    dense_ratio: float = 1.0
    num_heads: int = 0              # head_pruning

    def matches(self, path: str) -> bool:
        return any(re.search(m, path) for m in self.modules)


@dataclasses.dataclass
class CompressionSpec:
    groups: List[CompressionGroup]
    activation_bits: int = 0        # 0 = off; consumed by the model family
    activation_offset: int = 0
    layer_reduction: Optional[Dict] = None

    @property
    def enabled(self) -> bool:
        return bool(self.groups) or self.activation_bits > 0 or \
            bool(self.layer_reduction)


def parse_compression_config(cfg: Dict) -> CompressionSpec:
    """ds_config['compression_training'] -> CompressionSpec
    (reference: compression/config.py get_compression_config)."""
    groups: List[CompressionGroup] = []

    def collect(kind: str, defaults_from_shared=()):
        section = cfg.get(kind) or {}
        shared = section.get("shared_parameters") or {}
        if not shared.get("enabled", False):
            return
        for name, g in (section.get("different_groups") or {}).items():
            params = g.get("params") or {}
            groups.append(CompressionGroup(
                kind=kind, name=name,
                modules=tuple(g.get("modules", [".*"])),
                schedule_offset=int(shared.get("schedule_offset", 0)),
                start_bits=int(params.get("start_bits", 8)),
                target_bits=int(params.get("target_bits",
                                           params.get("start_bits", 8))),
                quantization_period=int(params.get("quantization_period", 0)),
                quantization_type=str(
                    shared.get("quantization_type",
                               shared.get("quantizer_kernel", "symmetric"))
                    if isinstance(shared.get("quantization_type", "symmetric"),
                                  str) else "symmetric"),
                quantize_groups=int(params.get("quantize_groups", 1)),
                dense_ratio=float(params.get("dense_ratio", 1.0)),
                num_heads=int(params.get("num_heads", 0)),
            ))

    for kind in ("weight_quantization", "sparse_pruning", "row_pruning",
                 "channel_pruning", "head_pruning"):
        collect(kind)

    act = cfg.get("activation_quantization") or {}
    act_shared = act.get("shared_parameters") or {}
    act_bits = 0
    act_offset = 0
    if act_shared.get("enabled", False):
        act_offset = int(act_shared.get("schedule_offset", 0))
        bits_list = [int((g.get("params") or {}).get("bits", 8))
                     for g in (act.get("different_groups") or {}).values()]
        act_bits = min(bits_list) if bits_list else 8

    lr_cfg = cfg.get("layer_reduction") or {}
    layer_reduction = lr_cfg if lr_cfg.get("enabled", False) else None
    return CompressionSpec(groups=groups, activation_bits=act_bits,
                           activation_offset=act_offset,
                           layer_reduction=layer_reduction)


# ---------------------------------------------------------------------------
# the per-leaf transforms (all jit-safe; `step` is a traced scalar)
# ---------------------------------------------------------------------------

def _quantize_ste(w, bits, symmetric: bool, groups: int):
    """Fake-quant with straight-through grads and a TRACED bit width (the
    reference's bit schedule changes bits during training)."""

    @jax.custom_vjp
    def fq(w, bits):
        wf = w.astype(jnp.float32)
        shape = wf.shape
        g = wf.reshape(groups, -1) if wf.size % groups == 0 else wf.reshape(1, -1)
        qmax = 2.0 ** (bits - 1) - 1.0
        if symmetric:
            absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
            scale = jnp.where(absmax == 0, 1.0, absmax / qmax)
            q = jnp.clip(jnp.round(g / scale), -qmax, qmax)
            out = q * scale
        else:
            lo = jnp.min(g, axis=1, keepdims=True)
            hi = jnp.max(g, axis=1, keepdims=True)
            scale = jnp.where(hi == lo, 1.0, (hi - lo) / (2 * qmax))
            q = jnp.clip(jnp.round((g - lo) / scale), 0, 2 * qmax)
            out = q * scale + lo
        return out.reshape(shape).astype(w.dtype)

    fq.defvjp(lambda w, bits: (fq(w, bits), None),
              lambda _, g: (g, None))
    return fq(w, bits)


def _bits_at(group: CompressionGroup, step):
    """start_bits -> target_bits halving every quantization_period steps
    (reference: basic_layer QuantAct bit schedule)."""
    if group.quantization_period <= 0 or group.start_bits == group.target_bits:
        return jnp.asarray(float(group.target_bits))
    halvings = jnp.floor((step - group.schedule_offset)
                         / group.quantization_period)
    bits = group.start_bits / (2.0 ** jnp.maximum(halvings, 0.0))
    return jnp.maximum(bits, float(group.target_bits))


def _topk_mask(scores, keep_ratio: float):
    """Boolean mask keeping the top keep_ratio fraction by score."""
    n = scores.size
    k = max(int(round(n * keep_ratio)), 1)
    thresh = jnp.sort(scores.reshape(-1))[n - k]
    return scores >= thresh


def _transform_leaf(w, group: CompressionGroup, step):
    active = (step >= group.schedule_offset)
    if group.kind == "weight_quantization":
        bits = _bits_at(group, step)
        wq = _quantize_ste(w, bits, group.quantization_type != "asymmetric",
                           group.quantize_groups)
        return jnp.where(active, wq, w)
    if group.kind == "sparse_pruning":
        mask = jax.lax.stop_gradient(
            _topk_mask(jnp.abs(w.astype(jnp.float32)), group.dense_ratio))
        return jnp.where(active, w * mask, w)
    if group.kind == "row_pruning":
        scores = jnp.sum(jnp.abs(w.astype(jnp.float32)),
                         axis=tuple(range(1, w.ndim)))
        mask = _topk_mask(scores, group.dense_ratio)
        mask = jax.lax.stop_gradient(mask).reshape(
            (-1,) + (1,) * (w.ndim - 1))
        return jnp.where(active, w * mask, w)
    if group.kind == "channel_pruning":
        scores = jnp.sum(jnp.abs(w.astype(jnp.float32)),
                         axis=tuple(range(w.ndim - 1)))
        mask = jax.lax.stop_gradient(_topk_mask(scores, group.dense_ratio))
        return jnp.where(active, w * mask, w)
    if group.kind == "head_pruning":
        # rows of the attention out-proj grouped per head (reference:
        # basic_layer head_pruning on output_matrix rows)
        nh = group.num_heads
        rows = w.shape[0]
        if nh <= 0 or rows % nh:
            raise ValueError(f"head_pruning needs num_heads dividing "
                             f"rows {rows}, got {nh}")
        per = rows // nh
        scores = jnp.sum(jnp.abs(w.astype(jnp.float32)).reshape(
            nh, per, -1), axis=(1, 2))
        mask = jax.lax.stop_gradient(_topk_mask(scores, group.dense_ratio))
        mask = jnp.repeat(mask, per).reshape((rows,) + (1,) * (w.ndim - 1))
        return jnp.where(active, w * mask, w)
    raise ValueError(f"unknown compression kind {group.kind}")


def apply_compression(params: PyTree, spec: CompressionSpec, step) -> PyTree:
    """Transform every matched leaf. Runs inside jit; grads flow straight-
    through to the raw master weights (QAT semantics)."""
    if not spec.groups:
        return params
    step = jnp.asarray(step, jnp.float32)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree.structure(params)
    out = []
    for path, leaf in flat:
        p = path_str(path)
        newleaf = leaf
        if leaf.ndim >= 1 and ("kernel" in p or "embedding" in p):
            for g in spec.groups:
                if g.matches(p):
                    newleaf = _transform_leaf(newleaf, g, step)
        out.append(newleaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def init_compression(config: Dict) -> CompressionSpec:
    """Entry point matching the reference's compress.init_compression —
    returns the spec the engine threads into its train step."""
    section = config.get("compression_training", config)
    if hasattr(section, "model_dump"):
        section = section.model_dump()
    return parse_compression_config(section)


# ---------------------------------------------------------------------------
# layer reduction + export
# ---------------------------------------------------------------------------

def apply_layer_reduction(params: PyTree, keep_layers: List[int]) -> PyTree:
    """Student initialization from a teacher's scanned stack (reference:
    compress.student_initialization — teacher_layer selects which teacher
    blocks seed the student)."""
    idx = jnp.asarray(keep_layers, jnp.int32)

    def take(leaf):
        return jnp.take(leaf, idx, axis=0)

    out = dict(params)
    if "blocks" not in out:
        raise ValueError("layer_reduction expects scan-layers params "
                         "(a 'blocks' subtree stacked [L, ...])")
    out["blocks"] = jax.tree.map(take, out["blocks"])
    return out


def export_int8(params: PyTree, spec: CompressionSpec) -> Dict[str, Any]:
    """Post-training export: matched weights as (int8, scale) pairs, the
    rest as-is (reference: redundancy_clean / inference handoff)."""
    from ..ops.quantizer import quantize_symmetric
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for path, leaf in flat:
        p = path_str(path)
        matched = any(g.matches(p) and g.kind == "weight_quantization"
                      for g in spec.groups)
        if matched and leaf.ndim >= 1:
            q, scale = quantize_symmetric(leaf, bits=8, groups=1)
            out[p + ".int8"] = np.asarray(q)
            out[p + ".scale"] = np.asarray(scale)
        else:
            out[p] = np.asarray(leaf)
    return out
